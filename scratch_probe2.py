"""Probe: triangular_solve rate, panel qr/lu rates on TPU."""
import sys
import jax
import jax.numpy as jnp
import bench

n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
nb = 512


def probe_trsm(prec, c=None):
    c = c or n
    l = jnp.tril(jax.random.normal(jax.random.key(0), (c, c), jnp.float32)) \
        + 10.0 * jnp.eye(c, dtype=jnp.float32)
    b = jax.random.normal(jax.random.key(1), (n, c), jnp.float32)

    def step(x, cs):
        l, b = cs
        with jax.default_matmul_precision(prec):
            y = jax.lax.linalg.triangular_solve(
                jnp.conj(l), b + 1e-20 * x, left_side=False, lower=True,
                transpose_a=True)
        return y

    t = bench._per_iter_seconds(step, b, (l, b), k1=2, k2=6)
    return n * c * c / 1e9 / t, t


def probe_qr_panel(h):
    a = jax.random.normal(jax.random.key(0), (h, nb), jnp.float32)

    def step(x, cs):
        (a,) = cs
        with jax.default_matmul_precision("highest"):
            ht, taus = jnp.linalg.qr(a + 1e-20 * x, mode="raw")
        return a + 1e-30 * ht.T

    t = bench._per_iter_seconds(step, a, (a,), k1=2, k2=4)
    return t


def probe_lu_panel(h):
    a = jax.random.normal(jax.random.key(0), (h, nb), jnp.float32)

    def step(x, cs):
        (a,) = cs
        with jax.default_matmul_precision("highest"):
            lu, piv, perm = jax.lax.linalg.lu(a + 1e-20 * x)
        return a + 1e-30 * lu

    t = bench._per_iter_seconds(step, a, (a,), k1=2, k2=4)
    return t


which = sys.argv[2] if len(sys.argv) > 2 else "all"
if which in ("all", "trsm"):
    for prec in ("high", "highest"):
        g, t = probe_trsm(prec)
        print(f"trsm n={n} c={n} prec={prec}: {g:9.1f} GFLOP/s ({t*1e3:.2f} ms)")
    g, t = probe_trsm("highest", c=nb)
    print(f"trsm n={n} c={nb} (panel): {g:9.1f} GFLOP/s ({t*1e3:.3f} ms)")
if which in ("all", "panels"):
    for h in (4096, 16384):
        t = probe_qr_panel(h)
        print(f"qr raw panel ({h}x{nb}): {t*1e3:.2f} ms")
        t = probe_lu_panel(h)
        print(f"lu panel     ({h}x{nb}): {t*1e3:.2f} ms")
