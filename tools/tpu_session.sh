#!/bin/sh
# Pre-staged on-chip measurement session (VERDICT r4 next-round #1).
# Run the moment the TPU tunnel is healthy; every step has a hard
# timeout with SIGKILL follow-up (the tunnel hang ignores SIGTERM) so a
# mid-session drop cannot hang the shell, and each artifact is written
# via a temp file so a failed step never ships an empty/partial JSON.
#
#   sh tools/tpu_session.sh
#
# Artifacts (commit them):
#   PERF_r05_n16384.json  bench.py at BASELINE continuity size
#   PERF_r05_n8192.json   bench.py at the r2 series size
#   PERF_r05_profile.json phase decomposition of the iterative potrf
#   perf_traces/          jax.profiler trace of one potrf call
set -ex
cd "$(dirname "$0")/.."

# 1. probe (killable; bench.py re-probes too, belt and braces)
timeout -k 10 90 python /tmp/probe_tpu.py || timeout -k 10 90 python -c \
  "import jax; print(jax.devices())"

# 2. headline bench at n=16384 (BASELINE size) and 8192 (r2 continuity)
timeout -k 10 3600 python bench.py 16384 > PERF_r05_n16384.json.tmp \
  && mv PERF_r05_n16384.json.tmp PERF_r05_n16384.json
timeout -k 10 1800 python bench.py 8192 > PERF_r05_n8192.json.tmp \
  && mv PERF_r05_n8192.json.tmp PERF_r05_n8192.json

# 3. potrf phase decomposition + one profiler trace
timeout -k 10 1800 python tools/profile_potrf.py 8192 1024 \
  --trace perf_traces/potrf_n8192 > PERF_r05_profile.json.tmp \
  && mv PERF_r05_profile.json.tmp PERF_r05_profile.json
timeout -k 10 1800 python tools/profile_potrf.py 16384 1024 \
  > PERF_r05_profile_n16384.json.tmp \
  && mv PERF_r05_profile_n16384.json.tmp PERF_r05_profile_n16384.json

tail -n 1 PERF_r05_n16384.json PERF_r05_n8192.json PERF_r05_profile.json
