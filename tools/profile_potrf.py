#!/usr/bin/env python
"""Phase decomposition + profiler trace for the iterative potrf.

Pre-staged for the on-chip session (VERDICT r4 next-round #1): answers
"where does the potrf time go on a real chip" with measurements, not
arguments. Three chained-phase timings reconstruct the per-step budget
of _potrf_iter (slate_tpu/linalg/cholesky.py):

  tiles    — the nt sequential diagonal-tile Choleskys (latency floor)
  panels   — per-step batched-leaf inverse + panel gemm
  trailing — per-step triangle-aware herk recursion (the MXU flops)

and the full driver is timed with the same scan methodology as bench.py
(dispatch/sync overhead cancels between two scan lengths). If
t_total ≈ t_tiles + t_panels + t_trailing the phases serialize (single
chip: expected — there is a true data dependence); the printed
panel_fraction is the share a mesh's async scheduler could hide under
the trailing update (the Lookahead/P3 capability,
/root/reference/src/potrf.cc:84-195).

Round 7: the iterative loop is the LOOKAHEAD pipeline by default and
every level's ops carry jax.named_scope labels (potrf_l{k}_tile /
_panel / _trail_next / _l{k+1}_tile_lookahead / _trail_rest — see
linalg/cholesky.py::_potrf_iter), so a --trace artifact shows
per-level panel/trailing timestamps directly: overlap, where the
backend schedules it, appears as the l{k+1} tile-factor region
straddling the l{k} trail_rest gemms. --lookahead {0,1} selects the
schedule; the output also reports the lookahead A/B total
(panel-hidden vs exposed — the lookahead model's per-level floor is
max(panel, trailing) instead of their sum).

Optionally captures a jax.profiler trace of ONE full potrf call
(--trace DIR) for the committed artifact; on a ≥2-device backend the
trace is the direct overlap evidence (look for all-gather ops running
concurrently with the trailing-update fusions).

Usage: python tools/profile_potrf.py [n] [nb] [--trace DIR]
                                     [--lookahead {0,1}]
Writes one JSON line to stdout; commentary to stderr.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import apply_env_platforms  # noqa: E402

apply_env_platforms()  # honor JAX_PLATFORMS despite the axon sitecustomize

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# single source of truth for the timing protocol — the two committed
# evidence producers (bench.py, this) must share one methodology
from bench import _per_iter_seconds  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("n", type=int, nargs="?", default=8192)
    ap.add_argument("nb", type=int, nargs="?", default=1024)
    ap.add_argument("--trace", default=None, metavar="DIR")
    ap.add_argument("--lookahead", type=int, default=1, choices=(0, 1),
                    help="pipeline schedule for the traced/timed "
                         "driver (1 = lookahead pipeline, 0 = the "
                         "sequential round-6 schedule)")
    opts = ap.parse_args()
    n, nb, trace_dir = opts.n, opts.nb, opts.trace
    lookahead = opts.lookahead

    import slate_tpu as st
    from slate_tpu.core.types import Uplo
    from slate_tpu.linalg.cholesky import _potrf_iter, _tile_chol
    from slate_tpu.matgen import random_spd
    from slate_tpu.ops import blocked

    plat = jax.devices()[0].platform
    print(f"# platform={plat} n={n} nb={nb} nt={n // nb}", file=sys.stderr)

    a0 = jnp.tril(random_spd(n, dtype=jnp.float32, seed=3))
    a0 = a0 + n * jnp.eye(n, dtype=jnp.float32)  # keep iterates SPD
    nt = n // nb
    prec = "high"

    def full(a):
        out, _ = _potrf_iter(a, nb, prec, lookahead)
        return a + 1e-30 * out

    def full_other(a):
        out, _ = _potrf_iter(a, nb, prec, 1 - lookahead)
        return a + 1e-30 * out

    def tiles_only(a):
        out = a
        for k in range(nt):
            k0, k1 = k * nb, (k + 1) * nb
            lkk, _ = _tile_chol(out[k0:k1, k0:k1])
            out = jax.lax.dynamic_update_slice(out, lkk, (k0, k0))
        return a + 1e-30 * out

    def panels_only(a):
        out = a
        for k in range(nt - 1):
            k0, k1 = k * nb, (k + 1) * nb
            inv = blocked.trtri_lower_batched(out[k0:k1, k0:k1])
            pan = blocked.mm(out[k1:, k0:k1], jnp.conj(inv).T, prec)
            out = jax.lax.dynamic_update_slice(out, pan, (k1, k0))
        return a + 1e-30 * out

    def trailing_only(a):
        # round 6: the loop's trailing phase is the slab-wise in-place
        # update (herk_trailing_inplace) — the reconstruction must time
        # what the driver actually runs
        out = a
        for k in range(nt - 1):
            k0, k1 = k * nb, (k + 1) * nb
            out = blocked.herk_trailing_inplace(
                out, out[k1:, k0:k1], k1, nb, prec=prec)
        return a + 1e-30 * out

    res = {"platform": plat, "n": n, "nb": nb, "nt": nt,
           "lookahead": lookahead}
    for name, fn in (("total", full), ("tiles", tiles_only),
                     ("panels", panels_only), ("trailing", trailing_only)):
        t = _per_iter_seconds(lambda c, cs, f=fn: f(c), a0, (), k1=2, k2=6)
        res[f"t_{name}_ms"] = round(t * 1e3, 2)
        print(f"# {name:9s} {t * 1e3:8.2f} ms/iter", file=sys.stderr)
    # lookahead A/B: the other schedule's total (round 7). The
    # lookahead model's floor replaces tiles+panels+trailing SUM with
    # per-level max(panel chain, remainder trailing): hidden_floor
    # below is that model evaluated from the measured phase terms.
    t_other = _per_iter_seconds(lambda c, cs: full_other(c), a0, (),
                                k1=2, k2=6)
    res[f"t_total_lookahead{1 - lookahead}_ms"] = round(t_other * 1e3, 2)
    print(f"# total(lookahead={1 - lookahead}) {t_other * 1e3:8.2f} "
          "ms/iter", file=sys.stderr)
    phase_sum = res["t_tiles_ms"] + res["t_panels_ms"] + res["t_trailing_ms"]
    res["t_phase_sum_ms"] = round(phase_sum, 2)
    res["panel_fraction"] = round(
        (res["t_tiles_ms"] + res["t_panels_ms"]) / max(res["t_total_ms"], 1e-9), 3)
    res["serialization"] = round(res["t_total_ms"] / max(phase_sum, 1e-9), 3)
    # per-level lookahead floor: panel terms hide under the remainder
    # trailing (or vice versa) — the exposed schedule pays their sum
    res["t_lookahead_model_floor_ms"] = round(
        max(res["t_tiles_ms"] + res["t_panels_ms"], res["t_trailing_ms"]),
        2)
    from slate_tpu.obs import flops as model_flops
    gflops = model_flops.potrf(n) / 1e9 / max(res["t_total_ms"] / 1e3, 1e-9)
    res["potrf_gflops"] = round(gflops, 1)

    if trace_dir:
        # trace the JITTED program (eager dispatch would serialize ops
        # host-side and falsely show zero overlap)
        jit_potrf = jax.jit(lambda x: _potrf_iter(x, nb, prec, lookahead))
        jax.block_until_ready(jit_potrf(a0))  # warm the compile cache
        with jax.profiler.trace(trace_dir):
            out, info = jit_potrf(a0)
            jax.block_until_ready(out)
        res["trace_dir"] = trace_dir
        print(f"# trace written to {trace_dir}", file=sys.stderr)
        # MEASURED lookahead overlap (ISSUE 4): when the profiler run
        # left a chrome-format device trace, align the per-level
        # potrf_l{k}_* named scopes and report how much of each
        # level-(k+1) lookahead tile-factor ran under the level-k
        # trail_rest gemms — the number the PERF.md round-7 model
        # (per-level floor = max(panel, trailing)) only predicts.
        from slate_tpu.obs import merge as obs_merge
        paths = obs_merge.find_device_traces(trace_dir)
        if paths:
            events = obs_merge.load_trace(paths[0])
            ov = obs_merge.lookahead_overlap(events, driver="potrf")
            res["lookahead_overlap"] = {
                "panel_s": round(ov["panel_s"], 6),
                "hidden_s": round(ov["hidden_s"], 6),
                "overlap_fraction": round(ov["overlap_fraction"], 3),
                "levels": len(ov["levels"]),
                "source": paths[0],
            }
            if ov["levels"]:
                print(f"# measured lookahead overlap: "
                      f"{ov['overlap_fraction']:.1%} of lookahead-panel "
                      "time hidden under trailing gemms", file=sys.stderr)
            else:
                print("# no lookahead-scoped device events in the trace "
                      "(XLA:CPU strips named-scope metadata; on TPU the "
                      "scopes survive in event args) — overlap reported "
                      "as 0 levels", file=sys.stderr)
        else:
            res["lookahead_overlap"] = None
            print("# no chrome-format device trace found under "
                  f"{trace_dir} (xplane-only profiler output needs the "
                  "tensorboard converter) — overlap not measured",
                  file=sys.stderr)

    print(json.dumps(res))


if __name__ == "__main__":
    main()
