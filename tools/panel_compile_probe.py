#!/usr/bin/env python
"""Measure Mosaic compile wall-time of the panel-base kernels per height.

Round-5 finding: the in-VMEM LU/QR panel kernels are fast to EXECUTE
but were expensive to COMPILE while their column loops were Python-
unrolled (pre-fix, first-call latency at n=16384 exceeded 30 minutes
through the axon tunnel and the remote compile helper was OOM-killed
on the 8 MB MLIR). The loops are lax.fori_loop now; this probe times
compile+first-call per height so the eligibility gates carry measured
height bounds (scoped-vmem limits, see pallas_ops._PANEL_MAX_CELLS)
instead of guesses.

Usage: python tools/panel_compile_probe.py [qr|lu] [heights_csv]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from slate_tpu.ops import pallas_ops  # noqa: E402


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "qr"
    heights = ([int(x) for x in sys.argv[2].split(",")]
               if len(sys.argv) > 2 else [512, 1024, 2048, 4096])
    w = 32
    rng = np.random.default_rng(0)
    print(f"# {which}_panel_base compile probe on {jax.devices()[0]}")
    for h in heights:
        a = jnp.asarray(rng.standard_normal((h, w)).astype(np.float32))
        t0 = time.time()
        if which == "qr":
            out = pallas_ops.qr_panel_base(a)
        else:
            out = pallas_ops.lu_panel_base(a)
        jax.block_until_ready(out)
        t_compile = time.time() - t0
        t0 = time.time()
        if which == "qr":
            out = pallas_ops.qr_panel_base(a)
        else:
            out = pallas_ops.lu_panel_base(a)
        jax.block_until_ready(out)
        t_run = time.time() - t0
        print(f"H={h:6d}: compile+first {t_compile:8.2f} s, "
              f"cached call {t_run * 1e3:8.2f} ms", flush=True)


if __name__ == "__main__":
    main()
