#!/usr/bin/env python
"""Seeded chaos soak for the serving runtime (round 14).

Serves a mixed workload — dense chol + lu, a grouped small-problem
fleet, and mixed-precision refined operators — through the full
Session/Batcher/Executor stack while a deterministic
:class:`~slate_tpu.runtime.FaultInjector` fires every injectable fault
class at once (transient dispatch failures, slow-device latency,
compile stalls, HBM-budget exhaustion, singular low-precision
operands, refinement non-convergence, dropped fleet snapshots), then
EXIT-GATES on the invariants every robustness claim in CHANGES.md now
rests on:

* **zero wrong answers** — every completed future's solution meets the
  residual bound of its operator (a fault may fail a request, never
  corrupt one);
* **zero lost/hung futures** — every submitted future is resolved
  after the final flush (no request falls between the reflexes);
* **conservation** — ``requests_total = completed + failed + shed +
  admission_rejected + deadline_expired + cancelled`` on every phase's
  metrics (no path resolves a future without counting it);
* **SLO accounting consistent** — the request-source SLO event stream
  agrees event-for-event with the conservation counters (total =
  completed+failed+expired; bad = failed+expired);
* **fleet fold under snapshot loss** — the aggregator folds the
  surviving process snapshots bit-exactly when the injector drops one;
* **schedule reproducibility** — the soak runs twice under the same
  seed and the two fault schedules (site, kind, sequence) are
  IDENTICAL (``schedule_digest`` equality): deterministic wave-locked
  submission (full buckets only, expired requests in their own
  bucket) makes the opportunity sequence, hence the schedule, a pure
  function of the seed.

Breaker/degradation drills run as separate deterministic phases (rate
1.0, count-limited plans) so the circuit breaker, the
grouped→per_request and mixed→working_precision ladder rungs, and
admission control + load shedding are each exercised every run, not
probabilistically. Round 16 adds the numerics drill: a cond≈1e12
matgen operand under a bf16 refine policy must be flagged SUSPECT by
the resident-factor condest, demoted to working precision (counted),
and still serve a residual-correct answer.

Writes the committed ``CHAOS_r*.json`` artifact (validated by
``tools/bench_gate.py --check-schema``); ``--smoke`` is the
run_tests.py wiring (fewer waves, same invariants). All shapes stay
n ≤ 64 (CPU-smoke compile budget, ROADMAP housekeeping note).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np  # noqa: E402

from slate_tpu.compat.platform import apply_env_platforms  # noqa: E402

apply_env_platforms()

RESID_TOL = 1e-3  # f32 working precision, n<=64 (|Ax-b|_inf / n|x|_inf)


def soak_plan(seed):
    """Every injectable class at once. lo_factor_fail fires ``after=1``
    so the FIRST refined operator survives factoring (its solve then
    hits the injected non-convergence) and the SECOND takes the
    singular-lo-factor fallback — both refine reflexes exercised
    deterministically in one soak."""
    from slate_tpu.runtime import FaultPlan, FaultSpec
    return FaultPlan(seed=seed, specs=(
        FaultSpec("dispatch_error", rate=0.12),
        FaultSpec("slow_device", rate=0.15, latency_s=2e-3),
        FaultSpec("compile_stall", rate=0.5, latency_s=5e-3),
        FaultSpec("hbm_exhaustion", rate=0.2),
        FaultSpec("lo_factor_fail", rate=1.0, after=1, count=1),
        FaultSpec("refine_no_converge", rate=1.0, count=1),
        FaultSpec("snapshot_drop", rate=1.0, count=1),
    ))


def _operators(rng, n_dense=48, nb=16, n_small=16, n_small_handles=4):
    """The mixed workload's operators, all f32 (chaos runs without
    forced x64). Returns (specs, dense_mats) where specs is
    [(name, register-kwargs, dense matrix for residual checks)]."""
    import slate_tpu as st

    ops = []
    a = rng.standard_normal((n_dense, n_dense)).astype(np.float32)
    spd = (a @ a.T + n_dense * np.eye(n_dense)).astype(np.float32)
    ops.append(("chol", dict(
        A=st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower),
        op="chol"), spd))
    ge = (rng.standard_normal((n_dense, n_dense))
          + n_dense * np.eye(n_dense)).astype(np.float32)
    ops.append(("lu", dict(A=st.from_dense(ge, nb=nb), op="lu"), ge))
    for i in range(n_small_handles):
        s = (rng.standard_normal((n_small, n_small))
             + n_small * np.eye(n_small)).astype(np.float32)
        ops.append((f"small{i}", dict(A=s, op="lu_small"), s))
    for i in range(2):
        a2 = rng.standard_normal((n_dense, n_dense)).astype(np.float32)
        spd2 = (a2 @ a2.T + n_dense * np.eye(n_dense)).astype(np.float32)
        ops.append((f"refined{i}", dict(
            A=st.hermitian(np.tril(spd2), nb=nb, uplo=st.Uplo.Lower),
            op="chol", refine=True), spd2))
    return ops


def _conservation(metrics) -> dict:
    """The conservation invariant over one Metrics instance (round 18
    grows the partition: a tenant turned away at its own quota is a
    counted ``quota_rejected`` outcome, never a silent drop)."""
    g = metrics.get
    parts = {
        "requests_total": g("requests_total"),
        "completed": g("completed_requests"),
        "failed": g("failed_requests_total"),
        "shed": g("shed_requests_total"),
        "admission_rejected": g("admission_rejected_total"),
        "deadline_expired": g("deadline_expired_total"),
        "quota_rejected": g("quota_rejections_total"),
        "cancelled": g("cancelled_requests"),
    }
    accounted = sum(v for k, v in parts.items()
                    if k != "requests_total")
    parts["accounted"] = accounted
    parts["ok"] = parts["requests_total"] == accounted
    return parts


def _check_residual(dense, x, b) -> float:
    x = np.asarray(x, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    denom = dense.shape[0] * max(float(np.abs(x).max()), 1.0)
    return float(np.abs(dense.astype(np.float64) @ x - b).max()) / denom


def run_soak(seed, waves, max_batch=8):
    """The main soak phase: deterministic wave-locked serving under
    the full fault plan. Returns (report, injector, session)."""
    from slate_tpu.runtime import Executor, Session

    rng = np.random.default_rng(seed)
    sess = Session(hbm_budget=64 << 20)
    sess.enable_slo()
    inj = sess.enable_faults(soak_plan(seed))
    ops = _operators(rng)
    dense_by_handle = {}
    handles = {}
    for name, kw, dense in ops:
        h = sess.register(handle=name, **kw)
        handles[name] = h
        dense_by_handle[h] = dense
    t0 = time.perf_counter()
    wrong = 0
    lost = 0
    outcomes = {"completed": 0, "failed": 0, "expired": 0}
    with Executor(sess, max_batch=max_batch, max_wait=3600.0,
                  retries=2, backoff_base=1e-3, backoff_max=4e-3,
                  breaker_threshold=3, breaker_cooldown=30.0) as ex:
        for name in handles:
            ex.warmup([handles[name]])
        n_dense = dense_by_handle[handles["chol"]].shape[0]
        n_small = dense_by_handle[handles["small0"]].shape[0]
        small_names = [n for n in handles if n.startswith("small")]
        for wave in range(waves):
            futs = []  # (future, handle, b)
            # every live bucket gets EXACTLY max_batch requests per
            # wave (full buckets only -> deterministic composition);
            # the deadline-expired lane uses a different rhs width so
            # its bucket never blocks the flush
            for name in ("chol", "lu", "refined0", "refined1"):
                for _ in range(max_batch):
                    b = rng.standard_normal(n_dense).astype(np.float32)
                    futs.append((ex.submit(handles[name], b),
                                 handles[name], b))
            for j in range(max_batch):
                sm = small_names[j % len(small_names)]
                b = rng.standard_normal(n_small).astype(np.float32)
                futs.append((ex.submit(handles[sm], b), handles[sm], b))
            for _ in range(2):
                b = rng.standard_normal((n_dense, 2)).astype(np.float32)
                futs.append((ex.submit(handles["chol"], b,
                                       timeout_s=0.0),
                             handles["chol"], b))
            ex.flush()
            for f, h, b in futs:
                if not f.done():
                    lost += 1
                    continue
                if f.exception() is not None:
                    from slate_tpu.runtime import DeadlineExceeded
                    if isinstance(f.exception(), DeadlineExceeded):
                        outcomes["expired"] += 1
                    else:
                        outcomes["failed"] += 1
                    continue
                outcomes["completed"] += 1
                if _check_residual(dense_by_handle[h], f.result(),
                                   b) > RESID_TOL:
                    wrong += 1
    wall = time.perf_counter() - t0
    snap = sess.metrics.snapshot()
    cons = _conservation(sess.metrics)
    # SLO accounting consistency: the request-source error-rate stream
    # must agree event-for-event with the conservation counters
    slo_rows = sess.slo.evaluate()["objectives"]
    err_row = next(r for r in slo_rows if r["name"] == "request_errors")
    long_win = max(err_row["windows"], key=lambda w: w["window_s"])
    slo_total = long_win["total"]
    slo_bad = long_win["bad"]
    expect_total = (cons["completed"] + cons["failed"]
                    + cons["deadline_expired"])
    expect_bad = cons["failed"] + cons["deadline_expired"]
    slo_ok = (slo_total == expect_total and slo_bad == expect_bad)
    # fleet fold under snapshot loss: N pseudo-processes, the injector
    # drops one, the aggregator folds the survivors bit-exactly
    from slate_tpu.obs.aggregate import aggregate_processes
    snaps, dropped = [], 0
    for i in range(3):
        if inj.fire("snapshot"):
            dropped += 1
            sess.metrics.inc("faults_injected_total")
            sess.metrics.inc("fault:snapshot_drop")
            continue
        snaps.append(snap)
    fleet = aggregate_processes(snaps, hosts=[f"p{i}"
                                             for i in range(len(snaps))])
    fleet_ok = (len(snaps) == 3 - dropped and dropped == 1
                and fleet["metrics"]["counters"]["requests_total"]
                == len(snaps) * snap["counters"]["requests_total"])
    report = {
        "wall_s": wall,
        "waves": waves,
        "outcomes": outcomes,
        "wrong_answers": wrong,
        "lost_futures": lost,
        "conservation": cons,
        "slo": {"total": slo_total, "bad": slo_bad,
                "expected_total": expect_total,
                "expected_bad": expect_bad, "ok": slo_ok},
        "fleet_fold": {"snapshots": 3, "dropped": dropped,
                       "ok": fleet_ok},
        "counters": {k: snap["counters"].get(k, 0.0) for k in (
            "requests_total", "completed_requests",
            "failed_requests_total", "deadline_expired_total",
            "shed_requests_total", "admission_rejected_total",
            "cancelled_requests", "retries", "failed_batches",
            "faults_injected_total", "refine_fallbacks_total",
            "evictions", "budget_overflows",
            "breaker_trips_total", "degraded_dispatches_total")},
        "fault_counters": {k: v for k, v in snap["counters"].items()
                           if k.startswith("fault:")},
        "ok": (wrong == 0 and lost == 0 and cons["ok"] and slo_ok
               and fleet_ok and outcomes["expired"] > 0
               and outcomes["completed"] > 0),
    }
    return report, inj, sess


def run_breaker_drill(seed, max_batch=4):
    """Deterministic breaker + grouped→per_request ladder drill: every
    early dispatch fails (rate 1.0, count-limited), retries are off,
    so the breaker trips on the Nth consecutive bucket failure and the
    tripping bucket replays through the per-request degraded lane;
    once the fault budget is exhausted the lane completes the rest."""
    from slate_tpu.runtime import (Executor, FaultPlan, FaultSpec,
                                   Session)

    rng = np.random.default_rng(seed + 1)
    sess = Session()
    inj = sess.enable_faults(FaultPlan(seed=seed, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=6),)))
    n = 16
    mats = [(rng.standard_normal((n, n))
             + n * np.eye(n)).astype(np.float32) for _ in range(4)]
    hs = [sess.register(m, op="lu_small") for m in mats]
    wrong = lost = 0
    completed = 0
    with Executor(sess, max_batch=max_batch, max_wait=3600.0,
                  retries=0, breaker_threshold=2,
                  breaker_cooldown=30.0) as ex:
        futs = []
        for wave in range(5):
            for j in range(max_batch):
                b = rng.standard_normal(n).astype(np.float32)
                futs.append((ex.submit(hs[j % len(hs)], b),
                             mats[j % len(hs)], b))
            ex.flush()
        for f, m, b in futs:
            if not f.done():
                lost += 1
            elif f.exception() is None:
                completed += 1
                if _check_residual(m, f.result(), b) > RESID_TOL:
                    wrong += 1
    g = sess.metrics.get
    cons = _conservation(sess.metrics)
    return {
        "conservation": cons,
        "wrong_answers": wrong, "lost_futures": lost,
        "completed": completed,
        "breaker_trips": g("breaker_trips_total"),
        "degraded_dispatches": g("degraded_dispatches_total"),
        "breaker_short_circuits": g("breaker_short_circuits"),
        "ok": (wrong == 0 and lost == 0 and cons["ok"]
               and g("breaker_trips_total") >= 1
               and g("degraded_dispatches_total") >= 1
               and completed > 0),
    }, inj


def run_mixed_drill(seed):
    """mixed→working_precision ladder drill: a refined operator's
    bucket trips its breaker; the ladder demotes it (lo resident
    evicted, refine off — counted in refine_demotions_total) and
    replays per-request at working precision."""
    from slate_tpu.runtime import (Executor, FaultPlan, FaultSpec,
                                   Session)
    import slate_tpu as st

    rng = np.random.default_rng(seed + 2)
    sess = Session()
    inj = sess.enable_faults(FaultPlan(seed=seed, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=4),)))
    n, nb = 32, 16
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=True)
    sess.warmup(h)
    wrong = lost = completed = 0
    with Executor(sess, max_batch=4, max_wait=3600.0, retries=0,
                  breaker_threshold=2, breaker_cooldown=30.0) as ex:
        futs = []
        for wave in range(4):
            for _ in range(4):
                b = rng.standard_normal(n).astype(np.float32)
                futs.append((ex.submit(h, b), b))
            ex.flush()
        for f, b in futs:
            if not f.done():
                lost += 1
            elif f.exception() is None:
                completed += 1
                if _check_residual(spd, f.result(), b) > RESID_TOL:
                    wrong += 1
    g = sess.metrics.get
    cons = _conservation(sess.metrics)
    return {
        "conservation": cons,
        "wrong_answers": wrong, "lost_futures": lost,
        "completed": completed,
        "breaker_trips": g("breaker_trips_total"),
        "refine_demotions": g("refine_demotions_total"),
        "degraded_dispatches": g("degraded_dispatches_total"),
        "ok": (wrong == 0 and lost == 0 and cons["ok"]
               and g("refine_demotions_total") >= 1
               and completed > 0),
    }, inj


def run_shed_drill(seed):
    """Admission control + load shedding, deterministically (driving
    the Batcher directly, no worker races): a bounded queue turns
    excess submits away at the door; an age-triggered shed then drops
    the cheapest-to-recompute half of what's queued; the survivors are
    served and every future is accounted."""
    from slate_tpu.runtime import (Batcher, RequestShed, Session,
                                   ShedPolicy)
    import slate_tpu as st

    rng = np.random.default_rng(seed + 3)
    sess = Session()
    n, nb = 32, 16
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                   uplo=st.Uplo.Lower), op="chol")
    sess.warmup(h)
    bat = Batcher(sess, max_batch=64, max_wait=3600.0,
                  shed_policy=ShedPolicy(max_queue_depth=8,
                                         max_age_s=0.01,
                                         shed_fraction=0.5,
                                         min_queue_depth=2))
    futs = [bat.submit(h, rng.standard_normal(n).astype(np.float32))
            for _ in range(12)]
    time.sleep(0.05)  # age past max_age_s
    shed = bat.maybe_shed()
    bat.flush()
    lost = sum(1 for f in futs if not f.done())
    rejected = sum(1 for f in futs
                   if f.exception() is not None
                   and isinstance(f.exception(), RequestShed))
    completed = sum(1 for f in futs if f.exception() is None)
    cons = _conservation(sess.metrics)
    g = sess.metrics.get
    return {
        "conservation": cons,
        "lost_futures": lost,
        "admission_rejected": g("admission_rejected_total"),
        "shed": shed, "completed": completed,
        "ok": (lost == 0 and cons["ok"]
               and g("admission_rejected_total") == 4  # 12 vs depth 8
               and shed == 4                           # half of 8
               and completed == 4),
    }


def run_numerics_drill(seed):
    """Numerical-health reflex drill (round 16): a matgen operand with
    κ₂ ≈ 1e12 — four orders past f32's breakdown point, six past
    bf16's — registers under a bf16 refine policy with the numerics
    monitor on. The factor-time condest probe (driven through the
    RESIDENT bf16 factor) must flag the handle SUSPECT, the health
    reflex must demote it off the refine ladder (counted in BOTH
    ``refine_demotions_total`` and ``health_demotions_total``), the
    demoted solve must run at working precision and return a
    residual-correct answer (backward error is what a stable LU owes
    regardless of conditioning — forward error at κ=1e12 in f32 is
    physics, not a bug), and the suspect state must survive into the
    placement snapshot's round-16 health column. Deterministic: the
    operand is seeded matgen, the sampler is seeded, and the condest
    estimate is a pure function of the factor bits."""
    from slate_tpu.matgen import cond_targeted
    from slate_tpu.refine import RefinePolicy
    from slate_tpu.runtime import Session
    import slate_tpu as st

    rng = np.random.default_rng(seed + 4)
    n, nb = 32, 16
    a = np.asarray(cond_targeted(n, 1e12, dtype=np.float32,
                                 seed=seed + 4, spd=False))
    sess = Session()
    sess.enable_numerics(sample_fraction=1.0, sample_seed=seed)
    h = sess.register(st.from_dense(a, nb=nb), op="lu",
                      refine=RefinePolicy(factor_dtype="bfloat16"))
    wrong = completed = 0
    for _ in range(3):
        b = rng.standard_normal(n).astype(np.float32)
        x = sess.solve(h, b)
        completed += 1
        if _check_residual(a, x, b) > RESID_TOL:
            wrong += 1
    g = sess.metrics.get
    health = sess.numerics.health(h)
    rows = sess.placement_snapshot(host="drill")["rows"]
    placement_health = rows[0]["health"] if rows else None
    entry_refine_off = sess._ops[h].refine is None
    cons = _conservation(sess.metrics)
    return {
        "conservation": cons,
        "wrong_answers": wrong, "lost_futures": 0,
        "completed": completed,
        "health": health,
        "placement_health": placement_health,
        "condest": sess.numerics.snapshot()["handles"][repr(h)]["condest"],
        "refine_demotions": g("refine_demotions_total"),
        "health_demotions": g("health_demotions_total"),
        "residual_probes": g("residual_probes_total"),
        "ok": (wrong == 0 and completed == 3 and cons["ok"]
               and health == "suspect"
               and placement_health == "suspect"
               and entry_refine_off
               and g("refine_demotions_total") >= 1
               and g("health_demotions_total") >= 1
               and g("residual_probes_total") >= 1),
    }


def recovery_plan(seed):
    """The crash-chaos plan (round 17): the process crash fires at the
    SECOND wave boundary (after=1) so one clean wave runs first;
    ``restore_corrupt`` skips the two replication-transfer restores
    (after=2) and corrupts the FIRST failover restore; ``replica_stale``
    hits the first replica-served failover handle. All count-limited —
    every rung of the recovery ladder is exercised exactly once."""
    from slate_tpu.runtime import FaultPlan, FaultSpec
    return FaultPlan(seed=seed, specs=(
        FaultSpec("process_crash", rate=1.0, after=1, count=1),
        FaultSpec("restore_corrupt", rate=1.0, after=2, count=1),
        FaultSpec("replica_stale", rate=1.0, count=1),
    ))


def run_recovery_drill(seed, waves=3):
    """Crash-recovery drill (round 17, the fleet-reflex half of the
    robustness story): a 3-member Fleet serves a mixed workload
    (dense chol, grouped small lu, refined bf16) with heat-driven
    replication + checkpoints; a deterministic ``process_crash`` kills
    the member holding the hottest handles MID-WAVE (its queued
    requests orphan and re-route), and the failover ladder is walked
    with every rung observed: the first replica-served handle is
    injected STALE (counted refresh, refactor — never stale bits), the
    second serves from its replica with NO refactor, the first
    checkpoint restore is injected CORRUPT (checksum catches it,
    counted degrade to refactor), the second restores warm. A
    post-crash admission surge exercises the round-14 shed policy on
    the survivors. Exit gates: zero wrong answers, zero lost futures
    (every fleet future resolves — failed-over or counted-shed),
    survivor conservation, attribution-fold consistency across the
    crash, the partial-host placement fold (the dead member's
    checkpoint keeps it in the fold), and an exact post-crash refactor
    count (stale refresh + corrupt degrade = 2; the replica and the
    clean restore refactor nothing)."""
    import shutil
    import tempfile

    from slate_tpu.obs.aggregate import (merge_attribution_snapshots,
                                         merge_metrics_snapshots)
    from slate_tpu.refine import RefinePolicy
    from slate_tpu.runtime import (FaultInjector, Fleet, RequestShed,
                                   Session, ShedPolicy)
    import slate_tpu as st

    rng = np.random.default_rng(seed + 5)
    root = tempfile.mkdtemp(prefix="slate_chaos_ckpt_")
    inj = FaultInjector(recovery_plan(seed))
    sessions = {}
    for i in range(3):
        s = Session(hbm_budget=64 << 20,
                    checkpoint_dir=os.path.join(root, f"p{i}"))
        s.enable_attribution()
        s.faults = inj  # ONE shared schedule across the fleet
        sessions[f"p{i}"] = s
    fleet = Fleet(sessions, max_batch=4, max_wait=3600.0,
                  checkpoint_root=root, faults=inj,
                  shed_policy=ShedPolicy(max_queue_depth=16,
                                         min_queue_depth=2))
    n_dense, n_small, nb = 32, 16, 16
    dense = {}
    # the victim hosts the hottest dense pair (replication targets) AND
    # two small operators (the restore paths); survivors hold the rest
    for name, member in (("d0", "p0"), ("d1", "p0")):
        a = rng.standard_normal((n_dense, n_dense)).astype(np.float32)
        spd = (a @ a.T + n_dense * np.eye(n_dense)).astype(np.float32)
        fleet.register(st.hermitian(np.tril(spd), nb=nb,
                                    uplo=st.Uplo.Lower),
                       op="chol", handle=name, member=member)
        dense[name] = spd
    for name, member in (("s0", "p0"), ("s1", "p0"), ("s2", "p1"),
                         ("s3", "p2")):
        m = (rng.standard_normal((n_small, n_small))
             + n_small * np.eye(n_small)).astype(np.float32)
        fleet.register(m, op="lu_small", handle=name, member=member)
        dense[name] = m
    a2 = rng.standard_normal((n_dense, n_dense)).astype(np.float32)
    spd2 = (a2 @ a2.T + n_dense * np.eye(n_dense)).astype(np.float32)
    fleet.register(st.hermitian(np.tril(spd2), nb=nb,
                                uplo=st.Uplo.Lower),
                   op="chol", handle="r0", member="p1",
                   refine=RefinePolicy(factor_dtype="bfloat16"))
    dense["r0"] = spd2
    fleet.warmup()
    victim = "p0"

    futs = []  # (future, handle, b)

    def submit_all():
        for h in sorted(dense):
            nn = dense[h].shape[0]
            b = rng.standard_normal(nn).astype(np.float32)
            futs.append((fleet.submit(h, b), h, b))

    # wave 0: serve + drive d0/d1 hottest (3 extra accesses each), then
    # replicate the top-2 hottest and flush every member's checkpoint
    submit_all()
    inj.fire("fleet.process")  # wave-0 opportunity (after=1 skips it)
    fleet.flush()
    for _ in range(3):
        for h in ("d0", "d1"):
            b = rng.standard_normal(n_dense).astype(np.float32)
            futs.append((fleet.submit(h, b), h, b))
        fleet.flush()
    replicated = fleet.replicate_hot(2)
    fleet.checkpoint_all()
    t_crash = None
    pre_factors = 0.0
    for wave in range(1, waves):
        submit_all()
        if inj.fire("fleet.process"):  # fires at wave 1 exactly once
            pre_factors = sum(
                fleet.member(m).metrics.get("factors_total")
                for m in fleet.alive() if m != victim)
            t0 = time.perf_counter()
            fleet.kill(victim)
            t_crash = time.perf_counter() - t0
        fleet.flush()
    # post-crash admission surge: the round-14 shed policy protects the
    # survivors — excess requests are turned away COUNTED, never lost
    surge = [fleet.submit("s2", rng.standard_normal(n_small)
                          .astype(np.float32)) for _ in range(40)]
    fleet.flush()
    surge_rejected = sum(1 for f in surge if f.done()
                         and isinstance(f.exception(), RequestShed))
    surge_lost = sum(1 for f in surge if not f.done())
    post_factors = sum(fleet.member(m).metrics.get("factors_total")
                       for m in fleet.alive())

    wrong = lost = 0
    outcomes = {"completed": 0, "failed": 0}
    for f, h, b in futs:
        if not f.done():
            lost += 1
            continue
        if f.exception() is not None:
            outcomes["failed"] += 1
            continue
        outcomes["completed"] += 1
        if _check_residual(dense[h], f.result(), b) > RESID_TOL:
            wrong += 1
    survivors = fleet.alive()
    cons = {m: _conservation(fleet.member(m).metrics)
            for m in survivors}
    # attribution-fold consistency ACROSS the crash: the survivors'
    # per-tenant cells still sum bit-exactly to their folded globals
    # (the dead member lost both sides together — consistent)
    attr_fold = merge_attribution_snapshots(
        [fleet.member(m).attribution.snapshot() for m in survivors])
    metrics_fold = merge_metrics_snapshots(
        [fleet.member(m).metrics.snapshot() for m in survivors],
        hosts=survivors)
    from slate_tpu.obs.attribution import CLASSES
    attr_ok = all(
        attr_fold["totals"].get(cls, 0.0)
        == metrics_fold["counters"].get(counter, 0.0)
        for cls, counter in CLASSES.items())
    # the partial-host placement fold: the dead member's checkpoint
    # keeps its rows in the fleet placement input, marked partial
    pdoc = fleet.placement()
    partial_ok = (pdoc["partial_hosts"] == [victim]
                  and any(r["host"] == victim for r in pdoc["rows"]))
    g = fleet.metrics.get
    refactors_after_crash = post_factors - pre_factors
    report = {
        "waves": waves,
        "outcomes": outcomes,
        "wrong_answers": wrong,
        "lost_futures": lost + surge_lost,
        "replicated": [str(h) for h in replicated],
        "failover_ms": None if t_crash is None else t_crash * 1e3,
        "refactors_after_crash": refactors_after_crash,
        "surge": {"submitted": len(surge),
                  "admission_rejected": surge_rejected},
        "conservation": {
            "per_member": cons,
            "ok": all(c["ok"] for c in cons.values())},
        "attribution_fold_ok": attr_ok,
        "partial_placement_fold_ok": partial_ok,
        "fleet_counters": {k: v for k, v in
                           fleet.metrics.snapshot()["counters"].items()},
        "restore_corrupt_total": sum(
            fleet.member(m).metrics.get("restore_corrupt_total")
            for m in survivors),
        "ok": (wrong == 0 and lost == 0 and surge_lost == 0
               and outcomes["completed"] > 0
               and all(c["ok"] for c in cons.values())
               and attr_ok and partial_ok
               and g("fleet_failover_replica_served") >= 1
               and g("fleet_replica_stale_refreshes") >= 1
               and g("fleet_failover_restored") >= 1
               and g("fleet_failover_requests_total") >= 1
               and sum(fleet.member(m).metrics
                       .get("restore_corrupt_total")
                       for m in survivors) >= 1
               # replica-served and clean-restored handles refactor
               # NOTHING; only the stale refresh + the corrupt degrade
               # pay a refactor — bounded recovery, exactly 2
               and refactors_after_crash == 2
               and surge_rejected > 0),
    }
    shutil.rmtree(root, ignore_errors=True)
    return report, inj


def run_noisy_drill(seed, waves=3):
    """Noisy-neighbor isolation drill (round 18): one tenant submits
    10× its weight's share of the traffic, both arms under the SAME
    seed — quotas + weighted-fair dispatch ON (the round-18 isolation
    layer) vs OFF (FIFO, no quotas, the pre-round-18 serving).

    With isolation ON the victim tenant's p99 stays bounded (its
    buckets dispatch within the DRR starvation bound, not behind the
    aggressor's whole backlog), it completes EVERYTHING it submitted
    (its fair share — it runs under it), and the aggressor's excess is
    quota-rejected at the door, counted per tenant
    (``quota_rejections_total`` + the tenant-labeled
    ``quota_rejected`` outcome cells). With isolation OFF the same
    seed shows victim starvation: its requests wait behind the
    aggressor's entire arrival history, so its p99 is a multiple of
    the ON arm's. Both arms: zero wrong answers, zero lost futures,
    per-tenant outcome conservation (completed/failed/shed/expired/
    quota_rejected partitions each tenant's submissions), and the
    victim's solutions are BIT-IDENTICAL across arms — same programs,
    different dispatch order (the fairness bit-parity pin)."""
    from slate_tpu.runtime import (Batcher, FaultPlan, FaultSpec,
                                   QuotaExceeded, Session, TenantPolicy)
    import slate_tpu as st

    rng0 = np.random.default_rng(seed + 6)
    n, nb = 32, 16
    a = rng0.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    ge = (rng0.standard_normal((n, n))
          + n * np.eye(n)).astype(np.float32)
    max_batch = 4
    noisy_per_wave, victim_per_wave = 10 * max_batch, max_batch

    def run_arm(fair):
        rng = np.random.default_rng(seed + 7)
        policies = ({"noisy": TenantPolicy(weight=1.0,
                                           max_in_flight=3 * max_batch),
                     "victim": TenantPolicy(weight=4.0)}
                    if fair else None)
        sess = Session(tenant_policies=policies)
        sess.enable_attribution()
        # deterministic service time: every dispatch sleeps 20 ms —
        # long against this host's real dispatch cost, so completion
        # order IS the latency story (thread-free pump)
        inj = sess.enable_faults(FaultPlan(seed=seed, specs=(
            FaultSpec("slow_device", rate=1.0, latency_s=20e-3),)))
        hv = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                        uplo=st.Uplo.Lower),
                           op="chol", tenant="victim", handle="v")
        hn = sess.register(st.from_dense(ge, nb=nb), op="lu",
                           tenant="noisy", handle="nz")
        sess.warmup(hv)
        sess.warmup(hn)
        bat = Batcher(sess, max_batch=max_batch, max_wait=3600.0)
        lat = {"victim": [], "noisy": []}
        submitted = {"victim": 0, "noisy": 0}
        xs_victim = []
        wrong = lost = 0
        # wave 0 is the untimed warm wave: it pays the one-time bucket
        # compiles (both arms equally) so the recorded waves' latency
        # story is dispatch ORDER, not compilation
        for wave in range(waves + 1):
            recorded = wave > 0
            futs = []
            # the aggressor submits FIRST (its backlog is what FIFO
            # makes the victim wait behind)
            for _ in range(noisy_per_wave):
                b = rng.standard_normal(n).astype(np.float32)
                submitted["noisy"] += 1
                futs.append(("noisy", ge, bat.submit(hn, b), b))
            for _ in range(victim_per_wave):
                b = rng.standard_normal(n).astype(np.float32)
                submitted["victim"] += 1
                futs.append(("victim", spd, bat.submit(hv, b), b))
            t0 = time.perf_counter()
            # dispatch one bucket at a time, stamping completion time
            # (DRR order in the fair arm, FIFO dict order otherwise)
            done_at = {}
            for key, reqs in bat.pop_ready(force=True):
                bat.run(key, reqs)
                now = time.perf_counter() - t0
                for r in reqs:
                    done_at[id(r.future)] = now
            for tenant, dense, f, b in futs:
                if not f.done():
                    lost += 1
                    continue
                err = f.exception()
                if err is not None:
                    if not isinstance(err, QuotaExceeded):
                        lost += 1  # only quota rejections are expected
                    continue
                if recorded:
                    lat[tenant].append(done_at.get(id(f), 0.0))
                x = f.result()
                if tenant == "victim":
                    xs_victim.append(np.asarray(x))
                if _check_residual(dense, x, b) > RESID_TOL:
                    wrong += 1
        snap = sess.attribution.snapshot()["tenants"]
        per_tenant = {
            t: {cls: row["totals"].get(cls, 0.0)
                for cls in ("completed", "failed", "shed", "expired",
                            "quota_rejected")}
            for t, row in snap.items()}
        # per-tenant conservation: every submission lands in exactly
        # one outcome cell of ITS tenant
        tenant_cons_ok = all(
            sum(per_tenant.get(t, {}).values()) == submitted[t]
            for t in submitted)

        def p99(xs):
            return (sorted(xs)[max(int(0.99 * len(xs)) - 1, 0)]
                    if xs else 0.0)

        return {
            "fair": fair,
            "submitted": dict(submitted),
            "per_tenant": per_tenant,
            "victim_p99_s": p99(lat["victim"]),
            "noisy_p99_s": p99(lat["noisy"]),
            "victim_completed": len(xs_victim),
            "quota_rejected": sess.metrics.get("quota_rejections_total"),
            "conservation": _conservation(sess.metrics),
            "tenant_conservation_ok": tenant_cons_ok,
            "wrong_answers": wrong,
            "lost_futures": lost,
        }, xs_victim, inj

    fair, xs_fair, inj = run_arm(True)
    fifo, xs_fifo, _ = run_arm(False)
    # bit-parity: same programs, different dispatch order — the
    # victim's solutions are identical bits across arms
    parity = (len(xs_fair) == len(xs_fifo)
              and all((a == b).all()
                      for a, b in zip(xs_fair, xs_fifo)))
    report = {
        "arms": {"fair": fair, "fifo": fifo},
        "victim_p99_ratio_fifo_over_fair": (
            fifo["victim_p99_s"] / fair["victim_p99_s"]
            if fair["victim_p99_s"] > 0 else None),
        "dispatch_order_bit_parity": parity,
        "wrong_answers": fair["wrong_answers"] + fifo["wrong_answers"],
        "lost_futures": fair["lost_futures"] + fifo["lost_futures"],
        "conservation": {
            "ok": (fair["conservation"]["ok"]
                   and fifo["conservation"]["ok"]
                   and fair["tenant_conservation_ok"]
                   and fifo["tenant_conservation_ok"])},
        "ok": (fair["wrong_answers"] == 0 and fifo["wrong_answers"] == 0
               and fair["lost_futures"] == 0
               and fifo["lost_futures"] == 0
               and fair["conservation"]["ok"]
               and fifo["conservation"]["ok"]
               and fair["tenant_conservation_ok"]
               and fifo["tenant_conservation_ok"]
               # isolation ON: the victim completed its whole share
               # (within-20%-of-fair-share acceptance — it runs UNDER
               # its share, so the bound is everything it asked for)
               and fair["victim_completed"]
               >= 0.8 * fair["submitted"]["victim"]
               # the aggressor pays: quota rejections on, none off
               and fair["quota_rejected"] > 0
               and fifo["quota_rejected"] == 0
               # starvation shown OFF, bounded ON (same seed)
               and fair["victim_p99_s"] < fifo["victim_p99_s"] / 2
               and parity),
    }
    return report, inj


def run_migration_drill(seed):
    """Migration-on-eviction drill (round 18): an HBM-pressured fleet
    member migrates its COLDEST resident to the least-loaded member
    via the round-17 checkpoint-transfer path instead of evicting it
    into refactor-on-miss. Exit gates: the migrated resident arrives
    BYTE-IDENTICAL; a request queued on the source at migration time
    still resolves (zero lost futures); post-migration solves route to
    the target and pay 0 refactors, while the control (plain eviction
    of the same-shaped handle) pays exactly 1; a seeded
    ``migration_abort`` kills the first transfer attempt mid-flight —
    the source keeps serving untouched, the retry is counted, and the
    target never holds a half-resident."""
    import jax

    from slate_tpu.runtime import (FaultInjector, FaultPlan, FaultSpec,
                                   Fleet, Session)
    import slate_tpu as st

    rng = np.random.default_rng(seed + 8)
    n, nb = 32, 16
    sessions = {f"p{i}": Session(hbm_budget=64 << 20) for i in range(2)}
    for s in sessions.values():
        s.enable_attribution()
    inj = FaultInjector(FaultPlan(seed=seed, specs=(
        FaultSpec("migration_abort", rate=1.0, count=1),)))
    fleet = Fleet(sessions, max_batch=4, max_wait=3600.0, faults=inj)
    dense = {}
    for i in range(3):
        a = rng.standard_normal((n, n)).astype(np.float32)
        spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
        fleet.register(st.hermitian(np.tril(spd), nb=nb,
                                    uplo=st.Uplo.Lower),
                       op="chol", handle=f"d{i}", member="p0")
        dense[f"d{i}"] = spd
    fleet.warmup()
    futs = []
    for h in sorted(dense):
        b = rng.standard_normal(n).astype(np.float32)
        futs.append((fleet.submit(h, b), h, b))
    fleet.flush()
    # heat: d1/d2 hot, d0 cold -> d0 is the migration candidate
    for _ in range(3):
        for h in ("d1", "d2"):
            b = rng.standard_normal(n).astype(np.float32)
            futs.append((fleet.submit(h, b), h, b))
        fleet.flush()
    src = fleet.member("p0")
    pre_payload = jax.tree_util.tree_leaves(src._cache["d0"].payload)
    pre_factors = sum(fleet.member(m).metrics.get("factors_total")
                      for m in fleet.alive())
    # a request queued on the source AT migration time must resolve
    bq = rng.standard_normal(n).astype(np.float32)
    fq = fleet.submit("d0", bq)
    # the pressure reflex: source headroom at/below the floor ->
    # migrate its coldest (the first transfer attempt is
    # injected-aborted; the counted retry lands it)
    moved = fleet.migrate_pressured(
        headroom_floor=src.hbm_headroom(), k=1)
    migrated_ok = moved.get("p0") == ["d0"]
    queued_ok = fq.done() and fq.exception() is None
    post_payload = jax.tree_util.tree_leaves(
        fleet.member("p1")._cache["d0"].payload) \
        if "d0" in fleet.member("p1") else []
    byte_identical = (len(post_payload) == len(pre_payload)
                      and all((np.asarray(x) == np.asarray(y)).all()
                              for x, y in zip(pre_payload,
                                              post_payload)))
    # routed requests follow: next solve lands on p1, 0 refactors
    b2 = rng.standard_normal(n).astype(np.float32)
    f2 = fleet.submit("d0", b2)
    fleet.flush()
    x2 = f2.result()
    wrong = int(_check_residual(dense["d0"], x2, b2) > RESID_TOL)
    migrated_refactors = sum(
        fleet.member(m).metrics.get("factors_total")
        for m in fleet.alive()) - pre_factors
    # the control: plain eviction pays 1 refactor per handle on the
    # next touch (the failure mode migration exists to avoid)
    fleet.member("p0").evict("d1")
    f3 = fleet.submit("d1", b2)
    fleet.flush()
    wrong += int(_check_residual(dense["d1"], f3.result(), b2)
                 > RESID_TOL)
    evicted_refactors = sum(
        fleet.member(m).metrics.get("factors_total")
        for m in fleet.alive()) - pre_factors - migrated_refactors
    lost = sum(1 for f, _, _ in futs if not f.done())
    cons = {m: _conservation(fleet.member(m).metrics)
            for m in fleet.alive()}
    g = fleet.metrics.get
    report = {
        "migrated": {m: [str(h) for h in hs]
                     for m, hs in moved.items()},
        "byte_identical": byte_identical,
        "queued_request_followed": queued_ok,
        "refactors_migrated_handle": migrated_refactors,
        "refactors_evicted_handle": evicted_refactors,
        "migration_aborts": g("fleet_migration_aborts_total"),
        "migration_retries": g("fleet_migration_retries_total"),
        "migrations": g("fleet_migrations_total"),
        "wrong_answers": wrong,
        "lost_futures": lost,
        "conservation": {"per_member": cons,
                         "ok": all(c["ok"] for c in cons.values())},
        "ok": (migrated_ok and byte_identical and queued_ok
               and wrong == 0 and lost == 0
               and migrated_refactors == 0
               and evicted_refactors == 1
               and g("fleet_migration_aborts_total") == 1
               and g("fleet_migration_retries_total") == 1
               and all(c["ok"] for c in cons.values())),
    }
    return report, inj


def run_spectral_drill(seed):
    """Resident-spectral fleet drill (round 19): eigendecompositions
    are full fleet citizens. Two exit-gated halves:

    1. **Replica failover**: a 2-member fleet serves a resident eig
       operator on p0, heat-replicates it to p1 (the round-17
       checkpoint-transfer path moving the ``eig_factors`` node), then
       p0 dies with a request in flight. The replica must serve with
       ZERO refactors (the 9n³ two-stage decomposition is exactly what
       failover exists to not re-pay), the queued future must resolve,
       and the post-crash answers stay residual-correct.

    2. **Suspect reflex on a poisoned spectrum**: a single session
       with the numerics monitor at probe rate 1.0 serves an eig
       operator whose resident Λ is shifted by ‖A‖ after factoring —
       a genuinely wrong eigendecomposition the one-gemm residual
       probe (‖A·v_i − λ_i·v_i‖) must catch. The handle must demote to
       SUSPECT (counted transition), and the state must land in the
       placement snapshot's health column."""
    import jax

    from slate_tpu.runtime import Fleet, Session
    from slate_tpu.spectral import EigFactors
    import slate_tpu as st

    rng = np.random.default_rng(seed + 9)
    n, nb = 32, 16
    a = rng.standard_normal((n, n)).astype(np.float32)
    a = ((a + a.T) / 2 + n * np.eye(n)).astype(np.float32)

    # -- half 1: replicated eigendecomposition survives member death --
    import shutil
    import tempfile
    root = tempfile.mkdtemp(prefix="slate_spectral_drill_")
    sessions = {f"p{i}": Session(hbm_budget=64 << 20)
                for i in range(2)}
    for s in sessions.values():
        s.enable_attribution()  # handle heat rides the ledger
    fleet = Fleet(sessions, max_batch=4, max_wait=3600.0,
                  checkpoint_root=root)
    A = st.from_dense(a, nb=nb, kind=st.MatrixKind.Hermitian)
    fleet.register(A, op="eig", handle="s0", member="p0")
    # ballast on the survivor so the fleet keeps a non-spectral lane
    spd = (a @ a.T / n + n * np.eye(n)).astype(np.float32)
    fleet.register(st.hermitian(np.tril(spd), nb=nb,
                                uplo=st.Uplo.Lower),
                   op="chol", handle="c0", member="p1")
    fleet.warmup()
    futs = []
    for _ in range(4):  # heat: the eig resident is the hot handle
        b = rng.standard_normal(n).astype(np.float32)
        futs.append((fleet.submit("s0", b), "s0", b))
        fleet.flush()
    replicated = fleet.replicate_hot(1)
    pre_factors = sum(fleet.member(m).metrics.get("factors_total")
                      for m in fleet.alive() if m != "p0")
    bq = rng.standard_normal(n).astype(np.float32)
    fq = fleet.submit("s0", bq)  # in flight at the moment of death
    fleet.kill("p0")
    b2 = rng.standard_normal(n).astype(np.float32)
    f2 = fleet.submit("s0", b2)
    fleet.flush()  # drains the re-routed orphan too
    queued_ok = fq.done() and fq.exception() is None
    wrong = 0
    if queued_ok:
        wrong += int(_check_residual(a, fq.result(), bq) > RESID_TOL)
    wrong += int(_check_residual(a, f2.result(), b2) > RESID_TOL)
    refactors = sum(fleet.member(m).metrics.get("factors_total")
                    for m in fleet.alive()) - pre_factors
    lost = sum(1 for f, _, _ in futs if not f.done())
    replica_served = fleet.metrics.get("fleet_failover_replica_served")
    cons = {m: _conservation(fleet.member(m).metrics)
            for m in fleet.alive()}
    shutil.rmtree(root, ignore_errors=True)

    # -- half 2: poisoned spectrum -> suspect demotion ----------------
    sess = Session()
    sess.enable_numerics(sample_fraction=1.0, sample_seed=seed)
    h = sess.register(st.from_dense(a, nb=nb,
                                    kind=st.MatrixKind.Hermitian),
                      op="eig")
    sess.warmup(h, nrhs=1)
    res = sess._cache[h]
    anorm = float(np.abs(a).sum(axis=1).max())
    # shift Λ by ‖A‖: V is still orthonormal but A·v − λ·v is now
    # O(‖A‖) — a wrong decomposition only the residual probe can see
    res.payload = EigFactors(
        res.payload.v, res.payload.lam + 10.0 * anorm)
    sess.apply(h, rng.standard_normal(n).astype(np.float32))
    health = sess.numerics.health(h)
    rows = sess.placement_snapshot(host="drill")["rows"]
    placement_health = rows[0]["health"] if rows else None
    transitions = sess.metrics.get("health_transitions_total")
    cons_b = _conservation(sess.metrics)

    return {
        "replicated": [str(x) for x in replicated],
        "queued_request_served": queued_ok,
        "refactors_after_crash": refactors,
        "replica_served": replica_served,
        "wrong_answers": wrong,
        "lost_futures": lost,
        "suspect_health": health,
        "suspect_placement_health": placement_health,
        "health_transitions": transitions,
        "conservation": {"per_member": cons, "single": cons_b,
                         "ok": (all(c["ok"] for c in cons.values())
                                and cons_b["ok"])},
        "ok": (queued_ok and wrong == 0 and lost == 0
               and refactors == 0 and replica_served >= 1
               and health == "suspect"
               and placement_health == "suspect"
               and transitions >= 1
               and all(c["ok"] for c in cons.values())
               and cons_b["ok"]),
    }


def run_update_drill(seed):
    """Incremental-maintenance reflex drill (round 20): every degrade
    path of the update verb, deterministically.

    (a) a seeded ``update_abort`` kills the rank-k sweep MID-UPDATE on
        an SPD resident — the mutation must degrade to a COUNTED
        refactor of the committed post-mutation operand, the next
        solve must be residual-correct (the refactor is the authority,
        never a half-swept factor), and the NEXT update (fault budget
        spent) must run clean on the incremental path;
    (b) an indefinite downdate (A − W·Wᴴ loses positive definiteness)
        must be counted in ``update_downdate_failures_total``, and the
        subsequent solve must RAISE — the authoritative refactor
        reports the indefiniteness: detected, never served;
    (c) a fleet update under a seeded ``replica_stale`` must degrade
        its replica sync to a counted FULL re-transfer that
        re-establishes the delta base (the next update delta-syncs
        again), with zero lost futures and every member answering
        residual-correct on the POST-update operand."""
    from slate_tpu.core.exceptions import SlateError
    from slate_tpu.runtime import (FaultInjector, FaultPlan, FaultSpec,
                                   Fleet, Session)
    import slate_tpu as st

    rng = np.random.default_rng(seed + 10)
    n, nb = 32, 16
    wrong = 0

    # -- (a) injected mid-update abort -> counted refactor, right answer
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    sess = Session(faults=FaultInjector(FaultPlan(seed=seed, specs=(
        FaultSpec("update_abort", rate=1.0, count=1),))))
    h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                   uplo=st.Uplo.Lower),
                      op="chol", handle="u0")
    sess.factor(h)
    w = (0.1 * rng.standard_normal((n, 2))).astype(np.float32)
    out_abort = sess.update(h, w)
    mutated = spd.astype(np.float64) + (w.astype(np.float64)
                                        @ w.astype(np.float64).T)
    b = rng.standard_normal(n).astype(np.float32)
    wrong += int(_check_residual(mutated, sess.solve(h, b), b)
                 > RESID_TOL)
    ga = sess.metrics.get
    abort_ok = (bool(out_abort["refactored"])
                and out_abort.get("reason") == "abort"
                and ga("update_aborts_total") == 1
                and ga("update_refactors_total") == 1)
    # the fault budget is spent: the next mutation serves incrementally
    w2 = (0.1 * rng.standard_normal((n, 1))).astype(np.float32)
    out_clean = sess.update(h, w2)
    w264 = w2.astype(np.float64)
    mutated = mutated + w264 @ w264.T
    wrong += int(_check_residual(mutated, sess.solve(h, b), b)
                 > RESID_TOL)
    clean_ok = (bool(out_clean["applied"])
                and ga("update_refactors_total") == 1)
    cons_a = _conservation(sess.metrics)

    # -- (b) indefinite downdate: counted, detected, never served ------
    a2 = rng.standard_normal((n, n)).astype(np.float32)
    spd2 = (a2 @ a2.T + n * np.eye(n)).astype(np.float32)
    sess_b = Session()
    hb = sess_b.register(st.hermitian(np.tril(spd2), nb=nb,
                                      uplo=st.Uplo.Lower),
                         op="chol", handle="u1")
    sess_b.factor(hb)
    out_dd = sess_b.update(
        hb, (10.0 * rng.standard_normal((n, 2))).astype(np.float32),
        downdate=True)
    gb = sess_b.metrics.get
    downdate_counted = (bool(out_dd["refactored"])
                        and out_dd.get("reason") == "downdate_indefinite"
                        and gb("update_downdate_failures_total") == 1)
    refused = False
    try:
        sess_b.solve(hb, b)
    except SlateError:
        refused = True
    cons_b = _conservation(sess_b.metrics)

    # -- (c) stale replica base -> counted full re-transfer ------------
    inj = FaultInjector(FaultPlan(seed=seed, specs=(
        FaultSpec("replica_stale", rate=1.0, count=1),)))
    fleet = Fleet({"p0": Session(), "p1": Session()},
                  max_batch=4, max_wait=3600.0, faults=inj)
    a3 = rng.standard_normal((n, n)).astype(np.float32)
    spd3 = (a3 @ a3.T + n * np.eye(n)).astype(np.float32)
    fleet.register(st.hermitian(np.tril(spd3), nb=nb,
                                uplo=st.Uplo.Lower),
                   op="chol", handle="u2", member="p0")
    fleet.member("p0").factor("u2")
    fleet.replicate("u2")
    futs = []
    for _ in range(4):
        bq = rng.standard_normal(n).astype(np.float32)
        futs.append((fleet.submit("u2", bq), bq))
    fleet.flush()
    w3 = (0.1 * rng.standard_normal((n, 1))).astype(np.float32)
    fleet.update("u2", w3)  # the stale fault forces the full path
    gf = fleet.metrics.get
    stale_counted = (gf("fleet_delta_base_stale_total") == 1
                     and gf("fleet_full_replications_total") == 1)
    w364 = w3.astype(np.float64)
    mutated3 = spd3.astype(np.float64) + w364 @ w364.T
    for name in ("p0", "p1"):
        member = fleet.member(name)
        if "u2" in member:
            wrong += int(_check_residual(mutated3,
                                         member.solve("u2", b), b)
                         > RESID_TOL)
    # base re-established by the full transfer: delta path again
    w4 = (0.1 * rng.standard_normal((n, 1))).astype(np.float32)
    fleet.update("u2", w4)
    delta_resumed = gf("fleet_delta_replications_total") >= 1
    w464 = w4.astype(np.float64)
    mutated3 = mutated3 + w464 @ w464.T
    for _ in range(4):
        bq = rng.standard_normal(n).astype(np.float32)
        futs.append((fleet.submit("u2", bq), bq))
    fleet.flush()
    lost = sum(1 for f, _ in futs if not f.done())
    for f, bq in futs[4:]:
        if f.done() and f.exception() is None:
            wrong += int(_check_residual(mutated3, f.result(), bq)
                         > RESID_TOL)
    cons_c = {m: _conservation(fleet.member(m).metrics)
              for m in fleet.alive()}
    fleet.close()

    return {
        "abort": {"result": {k: out_abort.get(k) for k in
                             ("applied", "refactored", "reason")},
                  "counted": abort_ok,
                  "next_update_incremental": clean_ok},
        "downdate": {"result": {k: out_dd.get(k) for k in
                                ("applied", "refactored", "reason")},
                     "counted": downdate_counted,
                     "solve_refused": refused},
        "stale_replica": {"counted_full_retransfer": stale_counted,
                          "delta_path_resumed": delta_resumed,
                          "delta_sync_bytes":
                          gf("fleet_delta_sync_bytes"),
                          "full_sync_bytes":
                          gf("fleet_full_sync_bytes")},
        "wrong_answers": wrong,
        "lost_futures": lost,
        "conservation": {
            "session": cons_a, "downdate_session": cons_b,
            "per_member": cons_c,
            "ok": (cons_a["ok"] and cons_b["ok"]
                   and all(c["ok"] for c in cons_c.values()))},
        "ok": (abort_ok and clean_ok and downdate_counted and refused
               and stale_counted and delta_resumed
               and wrong == 0 and lost == 0
               and cons_a["ok"] and cons_b["ok"]
               and all(c["ok"] for c in cons_c.values())),
    }


def run_tuner_drill(seed):
    """Online shadow-tuner drill (round 21): the watchdog-triggered
    promotion loop end-to-end, deterministically, with the shadow seam
    under fault injection the whole way.

    (a) an injected regression — a synthetic baseline whose committed
        best the live serve.solves_per_sec can never reach (the drill
        gates its OWN platform via ``gated_platforms``, honestly:
        nothing pretends to be a TPU) — makes ``Watchdog.check()``
        flag the series, and the listener seam hands the anomaly row
        to the attached :class:`ShadowTuner`;
    (b) the FIRST shadow attempt runs into injected ``compile_stall``
        + ``dispatch_error`` at the ``tuner.compile`` site: a counted
        rejection, the breaker stays closed, and the live futures
        served through the Executor meanwhile are all answered
        residual-correct (a shadow fault can never fail a live
        future);
    (c) with the Executor queue non-empty, ``poll()`` defers — the
        idle-capacity gate is observable, not aspirational;
    (d) the retry shadow-compiles the next ladder rung clean, the A/B
        runs both arms for real (the agreement check is live), the
        *timing* is injected deterministically — a 2x candidate win
        promotes (counted, traced), a 5% win on a second handle is
        rejected (< the 10% bar) — and the post-promotion refactor is
        zero new compiles;
    (e) a watchdog re-flag of the promoted handle demotes it (counted)
        back to the pre-promotion config with zero new compiles (the
        previous program is still resident);
    (f) a separate session drives consecutive shadow failures into the
        breaker (counted open, poll short-circuits) while its own live
        solves keep answering."""
    import jax

    from slate_tpu.obs.watchdog import BASELINE_SCHEMA, Watchdog
    from slate_tpu.runtime import Executor, FaultPlan, FaultSpec, Session
    from slate_tpu.tuning import ShadowTuner
    import slate_tpu as st

    class _DrillTuner(ShadowTuner):
        """A/B arms execute for real (the agreement check upstream runs
        both programs on the device); only the *timing* is injected —
        live arm 1.0, candidate ``cand_scale`` — so the ≥10% promotion
        rule is exercised on both sides of the bar without trusting
        CPU-smoke jitter."""

        cand_scale = 0.5

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._mcalls = 0

        def _measure(self, exe, A):
            super()._measure(exe, A)  # real executions, discarded timing
            self._mcalls += 1
            return 1.0 if self._mcalls % 2 == 1 else float(self.cand_scale)

    rng = np.random.default_rng(seed + 11)
    platform = jax.default_backend()
    n, nb = 48, 16
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    ge = (rng.standard_normal((n, n))
          + n * np.eye(n)).astype(np.float32)

    sess = Session()
    h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                   uplo=st.Uplo.Lower),
                      op="chol", handle="t0")
    h_lu = sess.register(st.from_dense(ge, nb=nb), op="lu", handle="t1")
    sess.warmup(h)
    sess.warmup(h_lu)

    # (a) the injected regression: a baseline best no live window meets
    baseline = {"schema": BASELINE_SCHEMA, "series": [{
        "kind": "serve", "metric": "serve.solves_per_sec",
        "platform": platform, "n": n, "batch": None, "op": "chol",
        "dtype": None, "best": 1e12, "direction": "higher"}]}
    wd = Watchdog(baseline=baseline, metrics=sess.metrics,
                  gated_platforms=(platform,))
    wrong = lost = completed = 0
    events = []
    with Executor(sess, max_batch=4, max_wait=3600.0) as ex:
        tuner = _DrillTuner(sess, batcher=ex.batcher, probes=1).attach(wd)
        futs = []
        for _ in range(4):
            b = rng.standard_normal(n).astype(np.float32)
            futs.append((ex.submit(h, b), spd, b))
        ex.flush()
        wd.watch_session(sess, platform=platform, n=n, op="chol")
        wd.check()
        flagged = tuner.pending() == 1  # watchdog row -> listener -> flag
        events.append(("flagged", flagged))

        # (c) queued live work defers the tuner: the idle gate. The
        # probe request sits in a partial bucket (max_wait is the
        # wave lock) exactly while poll() looks, then the bucket is
        # completed and flushed — full-bucket discipline preserved
        b_gate = rng.standard_normal(n).astype(np.float32)
        futs.append((ex.submit(h, b_gate), spd, b_gate))
        deferred = tuner.poll().get("deferred", False)
        for _ in range(3):
            b = rng.standard_normal(n).astype(np.float32)
            futs.append((ex.submit(h, b), spd, b))
        ex.flush()

        # (b) first shadow attempt eats the injected faults, live path
        # untouched (both budgets are consumed AT the tuner.compile
        # site before any live opportunity sees them)
        sess.enable_faults(FaultPlan(seed=seed, specs=(
            FaultSpec("compile_stall", rate=1.0, latency_s=5e-3, count=1),
            FaultSpec("dispatch_error", rate=1.0, count=1),
        )))
        r1 = tuner.poll()
        g = sess.metrics.get
        shadow_rejected = (r1.get("compiled", 0) == 0
                          and g("tuner_rejections_total") == 1
                          and not tuner.breaker_open)
        for _ in range(4):
            b = rng.standard_normal(n).astype(np.float32)
            futs.append((ex.submit(h, b), spd, b))
        ex.flush()

        # (d) retry: clean shadow compile of the next rung, then the
        # deterministic-win A/B -> promotion; recovery refactor warm
        r2 = tuner.poll()
        compiles_before = len(sess.compile_log)
        r3 = tuner.poll()
        promoted = (r2.get("compiled", 0) == 1 and r3.get("promoted", 0) == 1
                    and g("tuner_shadow_compiles_total") == 1
                    and g("tuner_promotions_total") == 1
                    and len(sess.compile_log) == compiles_before)
        tuned_label = sess._ops[h].tuned
        for _ in range(4):
            b = rng.standard_normal(n).astype(np.float32)
            futs.append((ex.submit(h, b), spd, b))
        ex.flush()

        # the losing arm: 5% candidate win on the lu handle -> rejected
        tuner.cand_scale = 0.95
        tuner._mcalls = 0
        tuner.flag(h_lu)
        tuner.poll()  # arm
        r_lose = tuner.poll()  # A/B
        loss_rejected = (r_lose.get("rejected", 0) == 1
                         and g("tuner_promotions_total") == 1
                         and g("tuner_rejections_total") == 2)

        # (e) re-flag of the promoted handle -> counted demotion,
        # zero new compiles (previous program still resident)
        compiles_before = len(sess.compile_log)
        tuner.on_anomaly({"n": n, "op": "chol"})
        sess.factor(h)
        demoted = (g("tuner_demotions_total") == 1
                   and sess._ops[h].tuned is None
                   and len(sess.compile_log) == compiles_before)
        for _ in range(4):
            b = rng.standard_normal(n).astype(np.float32)
            futs.append((ex.submit(h, b), spd, b))
        ex.flush()
        for f, m, b in futs:
            if not f.done():
                lost += 1
            elif f.exception() is None:
                completed += 1
                if _check_residual(m, f.result(), b) > RESID_TOL:
                    wrong += 1
    cons = _conservation(sess.metrics)

    # (f) the breaker: consecutive shadow failures open it; the live
    # path keeps answering (the fault budget is exactly the two
    # shadow attempts)
    sess_b = Session()
    inj = sess_b.enable_faults(FaultPlan(seed=seed, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=2),)))
    a2 = rng.standard_normal((n, n)).astype(np.float32)
    spd2 = (a2 @ a2.T + n * np.eye(n)).astype(np.float32)
    hb = sess_b.register(st.hermitian(np.tril(spd2), nb=nb,
                                      uplo=st.Uplo.Lower),
                         op="chol", handle="t2")
    sess_b.warmup(hb)
    tuner_b = ShadowTuner(sess_b, breaker_limit=2)
    tuner_b.flag(hb)
    tuner_b.poll()
    tuner_b.poll()
    short = tuner_b.poll()
    gb = sess_b.metrics.get
    breaker_opened = (tuner_b.breaker_open
                      and gb("tuner_breaker_open_total") == 1
                      and short.get("breaker_open", False))
    bb = rng.standard_normal(n).astype(np.float32)
    wrong += int(_check_residual(spd2, sess_b.solve(hb, bb), bb)
                 > RESID_TOL)
    cons_b = _conservation(sess_b.metrics)

    return {
        "watchdog_flagged": flagged,
        "idle_gate_deferred": deferred,
        "shadow_fault_rejected": shadow_rejected,
        "promoted_on_win": promoted,
        "promoted_config": tuned_label,
        "loss_rejected": loss_rejected,
        "demoted_on_reflag": demoted,
        "breaker_opened": breaker_opened,
        "counters": {k: g(k) for k in (
            "tuner_shadow_compiles_total", "tuner_promotions_total",
            "tuner_rejections_total", "tuner_demotions_total")},
        "tuner_events": [e["event"] for e in tuner.events],
        "completed": completed,
        "wrong_answers": wrong,
        "lost_futures": lost,
        "conservation": {"session": cons, "breaker_session": cons_b,
                         "ok": cons["ok"] and cons_b["ok"]},
        "ok": (flagged and deferred and shadow_rejected and promoted
               and loss_rejected and demoted and breaker_opened
               and wrong == 0 and lost == 0 and completed > 0
               and cons["ok"] and cons_b["ok"]),
    }, inj


def run_recorder_drill(seed):
    """Flight-recorder / decision-journal drill (round 22): black-box
    incident capture under injected faults, deterministically.

    (a) a served pass with the recorder ON before the first register:
        injected ``dispatch_error`` trips the breaker (journaled
        ``breaker_open``), an explicit evict + ``clear_cache`` drive
        the eviction reflex — and every (kind, counter) pair in
        ``KIND_COUNTERS`` where either side moved holds with absolute
        equality (the journal IS the counter, one decision at a time);
    (b) incidents: 6 fault firings at one site + the breaker trip all
        land inside the dedup/rate-limit windows (the drill injects a
        deterministic 1ms-step clock, so this is seed-stable, not
        wall-clock luck) -> exactly ONE incident is captured, the rest
        are counted dedups/rate-limits; repeated ``/incidents``
        scrapes mint nothing new; jumping the clock past the dedup
        window lets the SAME (reason, key) capture again — the window
        is a window, not a latch;
    (c) every captured document validates as ``slate_tpu.incident.v1``
        (runtime validator), carries the journal slice + counts, and
        its crash-safe on-disk twin is byte-loadable and id-identical;
    (d) the journal digest is a pure function of the seed: a second
        same-seed pass reproduces it (``DIGEST_FIELDS`` exclude
        wall-clock and inputs)."""
    import tempfile

    from slate_tpu.obs import validate_incident
    from slate_tpu.obs.events import KIND_COUNTERS
    from slate_tpu.runtime import Executor, FaultPlan, FaultSpec, Session

    def one_pass(tag):
        rng = np.random.default_rng(seed + 12)
        t = {"now": 0.0}

        def clock():
            t["now"] += 1e-3
            return t["now"]

        sess = Session()
        idir = tempfile.mkdtemp(prefix=f"slate_tpu_chaos_inc_{tag}_")
        rec = sess.enable_recorder(incident_dir=idir, clock=clock)
        sess.enable_faults(FaultPlan(seed=seed, specs=(
            FaultSpec("dispatch_error", rate=1.0, count=6),)))
        n = 16
        mats = [(rng.standard_normal((n, n))
                 + n * np.eye(n)).astype(np.float32) for _ in range(4)]
        hs = [sess.register(m, op="lu_small") for m in mats]
        wrong = lost = completed = 0
        with Executor(sess, max_batch=4, max_wait=3600.0,
                      retries=0, breaker_threshold=2,
                      breaker_cooldown=3600.0) as ex:
            futs = []
            for wave in range(5):
                for j in range(4):
                    b = rng.standard_normal(n).astype(np.float32)
                    futs.append((ex.submit(hs[j], b), mats[j], b))
                ex.flush()
            for f, m, b in futs:
                if not f.done():
                    lost += 1
                elif f.exception() is None:
                    completed += 1
                    if _check_residual(m, f.result(), b) > RESID_TOL:
                        wrong += 1
        sess.evict(hs[0])
        sess.clear_cache()
        return sess, rec, idir, wrong, lost, completed

    sess, rec, idir, wrong, lost, completed = one_pass("a")
    g = sess.metrics.get

    # (a) journal/counter parity: absolute equality per kind
    parity = {}
    for kind, counter in sorted(KIND_COUNTERS.items()):
        j, c = rec.journal.count(kind), g(counter)
        if j or c:
            parity[kind] = {"journal": j, "counter": c, "ok": j == c}
    parity_ok = bool(parity) and all(v["ok"] for v in parity.values())
    kinds_fired = sorted(parity)

    # (b) exactly one capture; scrapes are reads, not triggers
    p1 = rec.incidents.payload()
    p2 = rec.incidents.payload()
    one_captured = (g("incidents_captured_total") == 1
                    and len(p1["incidents"]) == 1
                    and p1 == p2
                    and g("incidents_captured_total") == 1)
    deduped = g("incidents_deduped_total")
    rate_limited = g("incidents_rate_limited_total")
    # the dedup window expires: jump the injected clock past it and
    # the same (reason, key) captures a SECOND document
    rec.incidents._clock = (lambda t0=rec.incidents._clock:
                            t0() + 3600.0)
    redoc = rec.incident("fault", key="dispatch",
                         context={"drill": "window_expiry"})
    window_expires = (redoc is not None
                      and g("incidents_captured_total") == 2)

    # (c) schema + crash-safe disk twin
    docs = rec.incidents.incidents()
    schema_errs = [e for d in docs for e in validate_incident(d)]
    # the first capture fires at the FIRST injected fault — before any
    # decision exists, so its slice is honestly empty; the post-drill
    # capture must carry the breaker + eviction decisions and counts
    slice_ok = bool(docs and docs[-1]["journal"]["events"]
                    and docs[-1]["journal"]["counts"])
    disk = sorted(fn for fn in os.listdir(idir) if fn.endswith(".json"))
    disk_ids = set()
    for fn in disk:
        with open(os.path.join(idir, fn)) as f:
            disk_ids.add(json.load(f)["id"])
    disk_ok = (len(disk) == len(docs)
               and disk_ids == {d["id"] for d in docs})

    # (d) same seed, same journal digest
    digest = rec.journal.digest()
    sess_b, rec_b, _idir_b, wrong_b, lost_b, _comp_b = one_pass("b")
    digest_b = rec_b.journal.digest()
    wrong += wrong_b
    lost += lost_b
    cons = _conservation(sess.metrics)
    cons_b = _conservation(sess_b.metrics)

    return {
        "parity": parity,
        "kinds_fired": kinds_fired,
        "one_incident_despite_scrapes": one_captured,
        "incidents_deduped": deduped,
        "incidents_rate_limited": rate_limited,
        "dedup_window_expires": window_expires,
        "incident_schema_errors": schema_errs,
        "journal_slice_rides_along": slice_ok,
        "disk_twin_ok": disk_ok,
        "journal_digest": digest,
        "digest_reproducible": digest == digest_b,
        "completed": completed,
        "wrong_answers": wrong,
        "lost_futures": lost,
        "conservation": {"session": cons, "repeat_session": cons_b,
                         "ok": cons["ok"] and cons_b["ok"]},
        "ok": (parity_ok and one_captured and deduped >= 1
               and window_expires and not schema_errs and slice_ok
               and disk_ok and digest == digest_b
               and "breaker_open" in parity and "eviction" in parity
               and wrong == 0 and lost == 0 and completed > 0
               and cons["ok"] and cons_b["ok"]),
    }


def run_forecast_drill(seed):
    """Sensing-substrate drill (round 23): the lead-time invariant,
    deterministically.

    A scripted diurnal serving trace on an injected clock (20 s steps,
    240 s cycles, no sleeps): handle ``fc0`` gets a burst schedule that
    peaks mid-cycle, ``fc1`` a flat one-request trickle, ``fc2``/
    ``fc3`` stay cold. Every step pumps the time-series store, so the
    attribution ledger's decayed ``heat:*`` series carry the real
    periodic signal of the workload — nothing is synthesized.

    (a) after 4 cycles of history, queried in the trough, the
        forecaster's ``predicted_hot`` ranks ``fc0`` first with a
        seasonal method and the TRUE period — and its predicted peak
        timestamp lies AHEAD of the query (the forecast is a warning,
        not a report);
    (b) the 5th cycle is then actually served: the realized heat peak
        lands within 2 steps of the predicted timestamp, and the
        warning led it by >= 2 steps — the pre-warm window ROADMAP
        item 3 needs;
    (c) the telemetry trace is a pure function of the seed: a second
        same-seed pass reproduces the digest of the scripted-clock
        series (heat + counters) AND the full forecast document;
    (d) counter conservation holds through the store: every counter
        series' delta sum equals the live metric counter exactly at
        the final pump."""
    import hashlib

    from slate_tpu.runtime import Executor, Session
    from slate_tpu.runtime.metrics import Metrics

    period_s, step_s = 240.0, 20.0
    steps_per_cycle = int(period_s / step_s)  # 12
    history_cycles = 4
    # mid-cycle burst schedule for fc0 (requests per step)
    hot_schedule = [0, 0, 0, 1, 2, 3, 3, 2, 1, 0, 0, 0]
    assert len(hot_schedule) == steps_per_cycle

    def one_pass():
        rng = np.random.default_rng(seed + 23)
        t = {"now": 0.0}
        clock = lambda: t["now"]  # noqa: E731 — scripted, SET not stepped
        # ONE scripted clock everywhere a timestamp can enter the
        # telemetry: metrics gauge stamps, attribution heat decay and
        # wall labels, and the store itself — mixed wall/scripted
        # timelines would hand the forecaster garbage periods
        sess = Session(metrics=Metrics(clock=clock))
        sess.enable_attribution(halflife_s=60.0, clock=clock,
                                wall=clock)
        store = sess.enable_timeseries(interval_s=0.0, clock=clock)
        n = 16
        mats = [(rng.standard_normal((n, n))
                 + n * np.eye(n)).astype(np.float32) for _ in range(4)]
        hs = [sess.register(m, op="lu_small", handle=f"fc{j}")
              for j, m in enumerate(mats)]
        wrong = lost = completed = 0

        def serve_step(ex, i):
            nonlocal wrong, lost, completed
            t["now"] = step_s * (i + 1)
            futs = []
            counts = [hot_schedule[i % steps_per_cycle], 1, 0, 0]
            for j, c in enumerate(counts):
                for _ in range(c):
                    b = rng.standard_normal(n).astype(np.float32)
                    futs.append((ex.submit(hs[j], b), mats[j], b))
            ex.flush()
            for f, m, b in futs:
                if not f.done():
                    lost += 1
                elif f.exception() is None:
                    completed += 1
                    if _check_residual(m, f.result(), b) > RESID_TOL:
                        wrong += 1
            sess.pump_timeseries(force=True)

        # max_batch=1: the burst sizes are the SIGNAL here (1..3 per
        # step, never a full 4-batch) — partial buckets would
        # otherwise sit out max_wait; single-request buckets dispatch
        # on submit and flush() drains deterministically
        with Executor(sess, max_batch=1, max_wait=3600.0) as ex:
            for i in range(history_cycles * steps_per_cycle):
                serve_step(ex, i)
            # (a) the forecast, queried in the trough
            t_query = t["now"]
            hot = sess.forecaster.predicted_hot(k=4,
                                                horizon_s=period_s)
            fc_doc = sess.forecaster.payload(horizon_s=period_s, k=4,
                                             max_series=64,
                                             points_limit=16)
            # the clean per-step heat series carries the seasonal
            # claim — forecast it NOW, before the holdout cycle can
            # leak into its history
            fc_hot = sess.forecaster.forecast_series(
                f"heat:{repr(hs[0])}", horizon_s=period_s)
            # (b) actually serve the held-out 5th cycle
            for i in range(history_cycles * steps_per_cycle,
                           (history_cycles + 1) * steps_per_cycle):
                serve_step(ex, i)

        hot_key = repr(hs[0])
        actual = store.points(f"heat:{hot_key}", lo=t_query + 1e-9)
        actual_peak_ts = (max(actual, key=lambda p: p[1])[0]
                          if actual else None)
        # (c) digest over the heat series (scripted clock end to end)
        # — counter rings stay OUT: the seconds-class counters measure
        # real wall time and are honest but not replayable (their
        # conservation is checked exactly in (d) instead)
        digest_names = sorted(
            nm for nm in store.names()
            if nm.startswith(("heat:", "handle_heat:")))
        digest = hashlib.sha256(json.dumps(
            {nm: store.series_payload(nm) for nm in digest_names},
            sort_keys=True).encode()).hexdigest()
        fc_digest = hashlib.sha256(json.dumps(
            {"predicted_hot": fc_doc["predicted_hot"],
             "series": {nm: row for nm, row in
                        fc_doc["series"].items()
                        if nm.startswith(("heat:", "handle_heat:"))}},
            sort_keys=True).encode()).hexdigest()
        # (d) exact counter conservation through the store
        counters = sess.metrics.snapshot()["counters"]
        cons_store = all(total == counters.get(nm, 0.0)
                         for nm, total in
                         store.counter_totals().items())
        return {"sess": sess, "hot": hot, "fc_hot": fc_hot,
                "t_query": t_query,
                "actual_peak_ts": actual_peak_ts, "digest": digest,
                "fc_digest": fc_digest, "cons_store": cons_store,
                "hot_key": hot_key, "wrong": wrong, "lost": lost,
                "completed": completed}

    a = one_pass()
    b = one_pass()

    top = a["hot"][0] if a["hot"] else None
    fc0_rows = [r for r in a["hot"] if "fc0" in r["handle"]]
    fc1_rows = [r for r in a["hot"] if "fc1" in r["handle"]]
    ranked = (top is not None and "fc0" in top["handle"]
              and bool(fc0_rows)
              and (not fc1_rows
                   or max(r["predicted_peak"] for r in fc0_rows)
                   > max(r["predicted_peak"] for r in fc1_rows)))
    fc_hot = a["fc_hot"]
    seasonal = (fc_hot["method"] in ("holt_winters",
                                     "seasonal_naive")
                and fc_hot["period_s"] == period_s)
    pred_peak_ts = (max(fc_hot["points"], key=lambda p: p[1])[0]
                    if fc_hot["points"] else None)
    leads = (pred_peak_ts is not None
             and a["actual_peak_ts"] is not None
             and pred_peak_ts > a["t_query"]
             and a["actual_peak_ts"] - a["t_query"] >= 2 * step_s
             and abs(pred_peak_ts - a["actual_peak_ts"])
             <= 2 * step_s)
    reproducible = (a["digest"] == b["digest"]
                    and a["fc_digest"] == b["fc_digest"])
    wrong = a["wrong"] + b["wrong"]
    lost = a["lost"] + b["lost"]
    cons = _conservation(a["sess"].metrics)
    cons_b = _conservation(b["sess"].metrics)
    return {
        "period_s": period_s,
        "cycles_history": history_cycles,
        "predicted_hot_top": ({k: v for k, v in top.items()}
                              if top else None),
        "query_ts": a["t_query"],
        "predicted_peak_ts": pred_peak_ts,
        "actual_peak_ts": a["actual_peak_ts"],
        "ranked_hot_first": ranked,
        "seasonal_method": seasonal,
        "lead_time_ok": leads,
        "trace_digest": a["digest"],
        "forecast_digest": a["fc_digest"],
        "digest_reproducible": reproducible,
        "store_conservation_ok": a["cons_store"] and b["cons_store"],
        "completed": a["completed"] + b["completed"],
        "wrong_answers": wrong,
        "lost_futures": lost,
        "conservation": {"session": cons, "repeat_session": cons_b,
                         "ok": cons["ok"] and cons_b["ok"]},
        "ok": (ranked and seasonal and leads and reproducible
               and a["cons_store"] and b["cons_store"]
               and wrong == 0 and lost == 0
               and a["completed"] > 0
               and cons["ok"] and cons_b["ok"]),
    }


def run_all(seed, waves):
    """One full chaos pass; returns (phase reports, schedule record)."""
    soak, inj, _sess = run_soak(seed, waves)
    drill, inj_b = run_breaker_drill(seed)
    mixed, inj_m = run_mixed_drill(seed)
    shed = run_shed_drill(seed)
    numerics = run_numerics_drill(seed)
    recovery, inj_r = run_recovery_drill(seed)
    noisy, inj_n = run_noisy_drill(seed)
    migration, inj_g = run_migration_drill(seed)
    spectral = run_spectral_drill(seed)
    update = run_update_drill(seed)
    tuner, inj_t = run_tuner_drill(seed)
    recorder = run_recorder_drill(seed)
    forecast = run_forecast_drill(seed)
    schedule = {
        "digest": "+".join(i.schedule_digest()
                           for i in (inj, inj_b, inj_m, inj_r,
                                     inj_n, inj_g, inj_t)),
        "events": sum(len(i.schedule())
                      for i in (inj, inj_b, inj_m, inj_r,
                                inj_n, inj_g, inj_t)),
        "fired_counts": inj.fired_counts(),
        "opportunities": inj.opportunity_counts(),
    }
    return {"soak": soak, "breaker_drill": drill,
            "mixed_drill": mixed, "shed_drill": shed,
            "numerics_drill": numerics,
            "recovery_drill": recovery,
            "noisy_drill": noisy,
            "migration_drill": migration,
            "spectral_drill": spectral,
            "update_drill": update,
            "tuner_drill": tuner,
            "recorder_drill": recorder,
            "forecast_drill": forecast}, schedule


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--waves", type=int, default=8,
                   help="soak waves (each: 5 full buckets + an "
                        "expired lane)")
    p.add_argument("--smoke", action="store_true",
                   help="run_tests wiring: fewer waves, same "
                        "invariants and determinism gate")
    p.add_argument("--out", default=None,
                   help="artifact path (default CHAOS_r01.json; "
                        "--smoke defaults to a /tmp throwaway)")
    p.add_argument("--no-repeat", action="store_true",
                   help="skip the second same-seed pass (the "
                        "reproducibility gate) — debugging only; the "
                        "artifact records schedule_reproducible=null")
    args = p.parse_args(argv)
    waves = 3 if args.smoke else args.waves
    out = args.out or ("/tmp/CHAOS_smoke.json" if args.smoke
                       else "CHAOS_r01.json")
    import jax
    platform = jax.devices()[0].platform

    phases, schedule = run_all(args.seed, waves)
    if args.no_repeat:
        reproducible = None
    else:
        print("# chaos: second same-seed pass (reproducibility gate)",
              file=sys.stderr)
        phases2, schedule2 = run_all(args.seed, waves)
        reproducible = (schedule["digest"] == schedule2["digest"]
                        and phases2["soak"]["ok"])
    plan = soak_plan(args.seed)
    enabled = [s.kind for s in plan.specs if s.rate > 0]
    enabled += [s.kind for s in recovery_plan(args.seed).specs
                if s.rate > 0 and s.kind not in enabled]
    enabled.append("migration_abort")  # run_migration_drill's plan
    enabled.append("update_abort")  # run_update_drill's plan
    invariants = {
        "wrong_answers": sum(ph.get("wrong_answers", 0)
                             for ph in phases.values()),
        "lost_futures": sum(ph.get("lost_futures", 0)
                            for ph in phases.values()),
        "conservation_ok": all(ph["conservation"]["ok"]
                               for ph in phases.values()),
        "slo_consistent": phases["soak"]["slo"]["ok"],
        "fleet_fold_ok": phases["soak"]["fleet_fold"]["ok"],
        "schedule_reproducible": reproducible,
        # round 16: the cond~1e12 operand was flagged suspect, demoted
        # off the refine ladder (counted), and still answered correctly
        "numerics_suspect_demoted": phases["numerics_drill"]["ok"],
        # round 17: process killed mid-soak -> replicas served with no
        # refactor, corrupt checkpoint caught by checksum and degraded
        # to a counted refactor, stale replica refreshed, orphaned
        # requests failed over, attribution + partial-placement folds
        # consistent across the crash — and never a wrong answer
        "failover_recovered": phases["recovery_drill"]["ok"],
        # round 18: with quotas + weighted-fair dispatch ON the victim
        # tenant's p99 stays bounded and it completes its share while
        # the aggressor is quota-rejected; the SAME seed with them OFF
        # shows victim starvation — and the victim's answers are
        # bit-identical across arms (order changed, programs didn't)
        "noisy_neighbor_isolated": phases["noisy_drill"]["ok"],
        # round 18: an HBM-pressured member migrates its coldest
        # resident byte-identically (0 refactors, routed requests
        # follow, an injected mid-transfer abort leaves the source
        # serving and retries counted) vs 1 refactor/handle evicted
        "migration_zero_refactor": phases["migration_drill"]["ok"],
        # round 19: a replicated resident eigendecomposition survives
        # its member's death mid-soak — the replica serves with zero
        # refactors and zero lost futures — and a poisoned spectrum
        # (Λ shifted by 10‖A‖ after factoring) is caught by the
        # one-gemm residual probe and demoted to suspect
        "spectral_resident_survives": phases["spectral_drill"]["ok"],
        # round 20: every degrade path of the update verb is a COUNTED
        # refactor with a correct answer — an injected mid-update abort
        # refactors the committed post-mutation operand (and the next
        # update is incremental again), an indefinite downdate is
        # detected and never served, and a stale replica base degrades
        # the delta sync to a counted full re-transfer that puts the
        # fleet back on the delta path
        "update_degrades_counted": phases["update_drill"]["ok"],
        # round 21: the online tuner's whole promotion loop is
        # fault-isolated from serving — an injected regression flags
        # through the watchdog listener seam, injected faults at the
        # tuner.compile site reject a shadow attempt without failing a
        # single live future, the deterministic-win A/B promotes
        # (counted, zero-compile recovery) and the 5% win is refused,
        # re-flag demotes, consecutive failures open the breaker
        "tuner_shadow_isolated": phases["tuner_drill"]["ok"],
        # round 22: the black box is trustworthy — every counted
        # reflex that fired journaled exactly one decision (absolute
        # parity per kind), an injected fault produced exactly ONE
        # incident despite 6 firings + repeated scrapes (dedup and
        # rate-limit counted; the window expires, not latches), the
        # captured documents validate as slate_tpu.incident.v1 with
        # the journal slice riding along, the crash-safe disk twins
        # match, and the journal digest is a pure function of the seed
        "recorder_black_box": phases["recorder_drill"]["ok"],
        # round 23: the forecaster warns BEFORE the peak — a scripted
        # diurnal workload's heat series, sensed through the real
        # attribution -> sampler -> store path, yields a predicted_hot
        # ranking whose top handle, seasonal method, true period, and
        # peak timestamp all hold against the actually-served holdout
        # cycle (>= 2 steps of lead), the telemetry digest is a pure
        # function of the seed, and counter conservation through the
        # store is exact
        "forecast_leads_peak": phases["forecast_drill"]["ok"],
    }
    ok = (all(ph["ok"] for ph in phases.values())
          and invariants["wrong_answers"] == 0
          and invariants["lost_futures"] == 0
          and invariants["conservation_ok"]
          and invariants["slo_consistent"]
          and invariants["fleet_fold_ok"]
          and (reproducible is None or reproducible)
          and len(enabled) >= 4)
    artifact = {
        "bench": "chaos",
        "platform": platform,
        "seed": args.seed,
        "waves": waves,
        "plan": plan.to_dict(),
        "fault_classes": enabled,
        "phases": phases,
        "invariants": invariants,
        "schedule": schedule,
        "caveat": ("CPU smoke (TPU tunnel down since round 5): "
                   "latencies are host-dispatch-bound; the invariant "
                   "and determinism columns are the claim."
                   if platform == "cpu" else None),
        "ok": ok,
    }
    with open(out, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"out": out, "ok": ok,
                      "fault_classes": len(enabled),
                      "fired": schedule["fired_counts"],
                      "invariants": invariants}, sort_keys=True))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
