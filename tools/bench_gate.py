#!/usr/bin/env python
"""Bench-trajectory regression gate over the committed BENCH artifacts.

Seven rounds of ``BENCH_r*.json`` (plus ``BENCH_SERVE_*.json``) sit in
the repo and form a performance trajectory nothing read until now —
regressions were invisible until a human reread PERF.md. This tool:

1. **Normalizes** the three artifact schemas that accumulated across
   rounds into one record shape
   ``{round, source, kind, platform, n, ok, metrics:{name: float}}``:

   * the harness wrapper (rounds 1–5): ``{"n": round, "cmd", "rc",
     "tail", "parsed": {...}}`` — metrics from ``parsed``, platform
     inferred from the tail (the axon warning / ``platform=tpu`` probe
     line / the CPU-fallback notice) when ``parsed`` lacks it;
   * the bare bench.py artifact (rounds 6+, ``--out``): has a
     ``"metric"`` key; ``n`` parsed out of the metric name;
   * the bench_serve artifact: ``{"bench": "serve", "backend", ...}``;
   * the batched-serving A/B rows (round 10, ``bench_serve.py
     --batched`` → ``BENCH_r08.json``): a JSON LIST of
     ``{"bench": "serve_batched", "platform", "op", "n", "batch",
     "batched": {"reqs_per_sec", ...}, "per_request": {...},
     "speedup"}`` rows — one record per row, series additionally keyed
     by the batch size (a B=10⁴ bucket never gates against a B=10²
     one);
   * the MULTICHIP family (round 11): rounds 1–5 are bare
     ``{n_devices, rc, ok, tail}`` dry-run blobs whose per-driver
     residuals hide in the tail text — parsed out as informational
     series; round 6+ is the structured ``{"bench": "multichip",
     "platform", "mesh_shape", "n_devices", "rows": [...]}`` artifact
     (``bench_serve.py --multichip``) — one ``multichip_serve`` record
     per row, series keyed by (op, n), gating
     serve/single-device solves-per-sec and speedup on TPU platforms.

2. **Gates**: for every tracked metric, series are keyed by
   ``(metric, platform, n)`` — numbers from different backends or
   problem sizes are never compared. The newest gateable round is
   compared against the best prior value in the same series; the gate
   FAILS (exit 1) on a drop beyond the tolerance. Policy (PERF.md
   Round 9): only TPU series gate — the CPU smoke rounds are
   dispatch-noise-dominated by the repo's own repeated measurement
   (PERF.md rounds 6–7 call their CPU totals "a wash") and are
   reported informationally. Default tolerance 10 %.

3. **Summarizes**: one JSON line on stdout — rounds seen, series
   tracked, regressions — machine-greppable trajectory state.

``--check-schema`` validates every committed ``BENCH_*.json`` against
the normalized schema and exits nonzero on any unparseable artifact
(this is why the trajectory read as empty: nothing enforced the
files). Wired into examples/run_tests.py beside tools/obs_dump.py.

``--baseline-out PATH`` (round 12) exports the normalized best-prior
series as a committed ``BASELINE_SERIES.json`` artifact — the single
source of truth the live regression watchdog (slate_tpu/obs/
watchdog.py) loads, so the serving runtime and this gate compare
against literally the same numbers; ``--check-schema`` validates a
committed baseline alongside the raw artifacts.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

# metric name -> where to find it in a bare bench.py artifact; every
# tracked metric is higher-is-better (GFLOP/s, solves/s, speedup)
TRACKED_BENCH = ("value", "potrf_gflops", "getrf_gflops",
                 "getrf_calu_gflops", "geqrf_gflops", "gemm_high_gflops")
TRACKED_SERVE = ("serve.solves_per_sec", "speedup")
TRACKED_SERVE_BATCHED = ("batched.reqs_per_sec", "speedup")
# the round-11 structured multichip rows (mesh-sharded serving A/B);
# collective/census columns are structural evidence, not perf series
TRACKED_MULTICHIP = ("serve.solves_per_sec",
                     "single_device.solves_per_sec", "speedup")
# the round-13 mixed-precision serving A/B (bench_serve.py --mixed →
# BENCH_MIXED_r*.json): refined-from-low-precision vs full-precision
# serve. residents_ratio and factor-bytes columns are structural; the
# solves/sec pair and speedup gate on TPU platforms like every serve
# series (CPU rows are convert-materialization smoke — informational)
TRACKED_SERVE_MIXED = ("mixed.solves_per_sec", "full.solves_per_sec",
                       "speedup", "residents_ratio")
# the round-14 overload A/B (bench_serve.py --overload →
# BENCH_OVERLOAD_r*.json): one record per arm (shed / no_shed);
# p99_latency_s classifies as lower-is-better via _direction
TRACKED_OVERLOAD = ("p99_latency_s", "max_oldest_age_s", "completed")
# the round-17 failover A/B (bench_serve.py --failover →
# BENCH_FAILOVER_r*.json): one record per arm (protected / cold);
# the recovery/failover/refactor columns classify lower-is-better via
# _direction, availability higher
TRACKED_FAILOVER = ("failover_s", "recovery_s_max",
                    "refactors_after_crash", "availability")
# the round-18 tenant-isolation A/B (bench_serve.py --tenants-fair →
# BENCH_FAIR_r*.json): one record per (arm, tenant); the latency
# columns classify lower-is-better via _direction (a per-tenant p99
# series entering the baseline inverted would read starvation as an
# improvement), reqs_per_sec higher
TRACKED_FAIR = ("reqs_per_sec", "p50_latency_s", "p99_latency_s",
                "completed")
# the round-19 resident-spectral A/B (bench_serve.py --spectral →
# BENCH_SPECTRAL_r*.json): one record per op row (eig / svd);
# theta-varying applies from a resident eigendecomposition vs the
# full two-stage decomposition per request. The zero-new-compiles and
# two-gemm apply-census columns are structural evidence, not series.
TRACKED_SPECTRAL = ("resident.applies_per_sec",
                    "cold.applies_per_sec", "speedup")
# the round-20 incremental-maintenance A/B (bench_serve.py --updates →
# BENCH_UPDATE_r*.json): one record per (op, n, k) row — rank-k
# updates / QR row appends served from the resident factor vs a full
# evict+refactor per mutation, k riding the batch series slot. The
# sync.* columns (delta-vs-full replica transfer bytes) classify
# lower-is-better via _direction; the refactor arm's rate is kept in
# the row for reading but NOT tracked as a series (its name would
# collide with the lower-is-better "refactor" classification the
# failover counts rely on). Zero-refactor/zero-compile columns are
# structural evidence, not series.
TRACKED_UPDATE = ("update.updates_per_sec", "speedup",
                  "sync.delta_bytes", "sync.ratio")
# the round-21 tuned-serving A/B (bench_serve.py --tuned →
# BENCH_TUNED_r*.json): default-Session vs tuning-table-Session serve
# of the same resident factor. The compile-count and config-provenance
# columns are structural evidence (validated, never series); the
# solves/sec pair and speedup gate on TPU platforms like every serve
# series (CPU rows are dispatch-noise smoke — informational).
TRACKED_TUNED = ("tuned.solves_per_sec", "default.solves_per_sec",
                 "speedup")
# the round-21 offline-search table itself (tools/autotune.py →
# TUNING_r*.json): each entry's measured score enters the trajectory
# as an informational series keyed by (op, n_max, dtype, platform) —
# the committed table is also the runtime's config source, so
# --check-schema holding it to the schema is what keeps the serving
# seam and this gate reading the same document
TRACKED_TUNING = ("tuned.gflops",)
# the round-23 sensing-substrate A/B (bench_serve.py --forecast →
# BENCH_FORECAST_r*.json): the holdout improvement (forecast MAE vs
# naive-last MAE — higher is better) plus the store cost columns
# (record-path ns/sample and the forced-pump serve overhead — both
# classify lower-is-better via _direction). Period detection and the
# aperiodic control are structural evidence, never series.
TRACKED_FORECAST = ("holdout.improvement",
                    "store.record_ns_per_sample",
                    "serve.overhead_pct")
GATED_PLATFORMS = ("tpu", "axon")

# SHARED with bench_serve.py since round 22 (tools/serve_sections.py,
# stdlib-only — this tool stays jax-import-free; the old hand-synced
# mirror pin is now an import-identity test): every section the serve
# artifact currently carries. --check-schema fails a committed fixture
# missing any of them — the round-12/13 stale-fixture class (schema
# grew a section, fixture silently didn't).


def _load_serve_sections():
    """Same fixed-name module load as bench_serve._load_serve_sections
    (one cached module object -> one shared tuple object)."""
    import importlib.util
    name = "slate_tpu_serve_sections"
    mod = sys.modules.get(name)
    if mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "serve_sections.py")
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return mod


SERVE_ARTIFACT_SECTIONS = _load_serve_sections().SERVE_ARTIFACT_SECTIONS
# mirror of obs/attribution.py PLACEMENT_ROW_KEYS + PLACEMENT_SCHEMA
# (same jax-free duplication discipline as the sections tuple above
# and the baseline validators; tests pin the mirrors equal): the
# round-15 placement-snapshot row shape --check-schema holds the
# committed serve fixture's tenants section to. v2 (round 16) adds
# the numerical-health columns (health/condest/growth, nullable).
PLACEMENT_SCHEMA = "slate_tpu.placement_snapshot.v2"
PLACEMENT_ROW_KEYS = ("host", "tenant", "handle", "op", "n", "dtype",
                      "bytes_per_chip", "heat", "last_access",
                      "health", "condest", "growth")
# mirror of obs/numerics.py HEALTH_STATES (tests pin them equal): the
# vocabulary the round-16 numerics section's states must come from
HEALTH_STATES = ("healthy", "degraded", "suspect")
# mirror of slate_tpu/runtime/checkpoint.py (round 17; same jax-free
# duplication discipline as the placement schema — tests pin the
# mirrors equal and feed both validators the same malformed docs): the
# checkpoint manifest a dead member's failover restores from, held to
# its schema by CI without importing the runtime
CHECKPOINT_SCHEMA = "slate_tpu.checkpoint.v1"
CHECKPOINT_RECORD_KEYS = (
    "handle", "handle_type", "op", "m", "n", "band", "dtype", "nb",
    "tenant", "refine", "mesh", "info", "heat", "last_access",
    "health", "operator", "payload")
CHECKPOINT_BLOB_KEYS = ("blob", "shape", "dtype", "nbytes", "sha256")
# mirror of slate_tpu/tuning/table.py (round 21; the same jax-free
# duplication discipline as the checkpoint/placement mirrors — tests
# pin the schema ids and the config-knob vocabulary equal and feed
# both validators the same malformed docs): the committed tuning
# table the serving runtime resolves configs from, held to its schema
# by CI without importing the runtime
TUNING_SCHEMA = "slate_tpu.tuning_table.v1"
TUNING_CONFIG_KEYS = ("nb", "inner_blocking", "lookahead",
                      "wide_panel", "batch_quantum", "width_quantum")
# mirror of slate_tpu/obs/events.py (round 22; same jax-free
# duplication discipline — tests pin the schema id and key tuple
# equal and feed both validators the same malformed docs): the
# incident-snapshot document the flight recorder publishes, held to
# its schema by --check-schema via the serve artifact's embedded
# sample and any committed incident files
INCIDENT_SCHEMA = "slate_tpu.incident.v1"
INCIDENT_KEYS = (
    "schema", "id", "ts", "host", "reason", "key", "context",
    "journal", "flight", "metrics", "numerics", "quotas", "placement",
    "cost_log", "tuning")
DEFAULT_TOLERANCE = 0.10

# round 23: the timeseries/forecast/capacity validators are NOT
# duplicated — slate_tpu/obs/{timeseries,forecast}.py are stdlib-only
# with no relative imports, so this tool loads the REAL modules by
# file path (the serve_sections discipline: one fixed module name,
# shared with tools/capacity_report.py; the drift pin degenerates to
# an import-identity test on __code__.co_filename).
TIMESERIES_SCHEMA = "slate_tpu.timeseries.v1"
FORECAST_SCHEMA = "slate_tpu.forecast.v1"
CAPACITY_SCHEMA = "slate_tpu.capacity_report.v1"


def _load_by_path(fixed_name: str, *relpath: str):
    import importlib.util
    mod = sys.modules.get(fixed_name)
    if mod is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        path = os.path.join(root, *relpath)
        spec = importlib.util.spec_from_file_location(fixed_name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[fixed_name] = mod
        spec.loader.exec_module(mod)
    return mod


validate_timeseries_doc = _load_by_path(
    "slate_tpu_obs_timeseries", "slate_tpu", "obs",
    "timeseries.py").validate_timeseries
validate_forecast_doc = _load_by_path(
    "slate_tpu_obs_forecast", "slate_tpu", "obs",
    "forecast.py").validate_forecast
validate_capacity_doc = _load_by_path(
    "slate_tpu_capacity_report", "tools",
    "capacity_report.py").validate_capacity_report

_N_RE = re.compile(r"_n(\d+)$")
# any committed artifact family named <FAMILY>_r<round>.json (BENCH_,
# MULTICHIP_, BENCH_MIXED_); non-round files (BENCH_SERVE_smoke) get
# round None
_ROUND_RE = re.compile(r"_r(\d+)\.json$")
# the r01–r05 multichip dry-run tails: "posv+hemm OK (max residual
# 4.77e-07), getrf OK (2.38e-07), ..." — the only machine-readable
# signal those rounds recorded (normalized as informational series)
_TAIL_RESID_RE = re.compile(
    r"([\w+]+) OK \((?:max residual )?([0-9.eE+-]+)\)")


class SchemaError(ValueError):
    pass


def _infer_platform_from_tail(tail: str) -> Optional[str]:
    if "CPU fallback" in tail or "cpu-fallback" in tail:
        return "cpu-fallback"
    if "platform=tpu" in tail or "'axon'" in tail.lower():
        return "tpu"
    if "platform=cpu" in tail:
        return "cpu"
    return None


def _flat_metrics(parsed: dict, tracked) -> dict:
    out = {}
    for name in tracked:
        cur = parsed
        for part in name.split("."):
            if not isinstance(cur, dict) or part not in cur:
                cur = None
                break
            cur = cur[part]
        if isinstance(cur, (int, float)) and not isinstance(cur, bool):
            out[name] = float(cur)
    return out


def _load(path: str):
    name = os.path.basename(path)
    try:
        with open(path) as f:
            return name, json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SchemaError(f"{name}: unreadable JSON ({e})")


def normalize(path: str) -> dict:
    """One single-object artifact file -> one normalized record
    (SchemaError when the file fits none of the known schemas; list
    artifacts — the serve_batched row files — go through
    :func:`normalize_all`)."""
    name, obj = _load(path)
    if isinstance(obj, list):
        raise SchemaError(f"{name}: list artifact — use normalize_all")
    if isinstance(obj, dict) and obj.get("bench") in ("multichip",
                                                      "serve_mixed",
                                                      "serve_overload",
                                                      "serve_failover",
                                                      "serve_fair",
                                                      "serve_spectral",
                                                      "serve_update",
                                                      "serve_tuned"):
        raise SchemaError(f"{name}: multi-row {obj['bench']} artifact "
                          "— use normalize_all")
    if isinstance(obj, dict) and obj.get("schema") == TUNING_SCHEMA:
        raise SchemaError(f"{name}: multi-entry tuning table "
                          "— use normalize_all")
    m = _ROUND_RE.search(name)
    rnd = int(m.group(1)) if m else None
    if isinstance(obj, dict) and obj.get("bench") == "chaos":
        return _normalize_chaos(name, obj, rnd)[0]
    return _normalize_obj(name, obj, rnd)


def normalize_all(path: str) -> List[dict]:
    """Every record in one artifact file: a single object yields one
    record, a serve_batched row LIST (or a structured multichip
    artifact's ``rows``) yields one per row."""
    name, obj = _load(path)
    m = _ROUND_RE.search(name)
    rnd = int(m.group(1)) if m else None
    if isinstance(obj, list):
        if not obj:
            raise SchemaError(f"{name}: empty artifact list")
        return [_normalize_obj(f"{name}[{i}]", row, rnd)
                for i, row in enumerate(obj)]
    if isinstance(obj, dict) and obj.get("bench") == "multichip":
        return _normalize_multichip(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_mixed":
        return _normalize_serve_mixed(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_overload":
        return _normalize_serve_overload(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_failover":
        return _normalize_serve_failover(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_fair":
        return _normalize_serve_fair(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_spectral":
        return _normalize_serve_spectral(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_update":
        return _normalize_serve_update(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "serve_tuned":
        return _normalize_serve_tuned(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("schema") == TUNING_SCHEMA:
        return _normalize_tuning(name, obj, rnd)
    if isinstance(obj, dict) and obj.get("bench") == "chaos":
        return _normalize_chaos(name, obj, rnd)
    return [_normalize_obj(name, obj, rnd)]


def _normalize_serve_overload(name: str, obj: dict,
                              rnd: Optional[int]) -> List[dict]:
    """The round-14 shedding A/B artifact: {"bench": "serve_overload",
    "platform", "n", "arms": {"shed": {...}, "no_shed": {...}}, "ok"}
    — one record per arm (the arm label rides the ``op`` series-key
    slot so the two arms never gate against each other)."""
    for k in ("platform", "n", "arms", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_overload artifact "
                              f"missing {k!r}")
    arms = obj["arms"]
    if not isinstance(arms, dict) or set(arms) != {"shed", "no_shed"}:
        raise SchemaError(f"{name}: serve_overload arms must be "
                          "exactly {shed, no_shed}")
    out = []
    for arm, row in sorted(arms.items()):
        for k in ("submitted", "completed", "p99_latency_s",
                  "oldest_age_series_s"):
            if k not in row:
                raise SchemaError(
                    f"{name}[arms.{arm}]: serve_overload arm missing "
                    f"{k!r}")
        out.append({
            "round": rnd, "source": f"{name}[{arm}]",
            "kind": "serve_overload",
            "platform": str(obj["platform"]), "n": int(obj["n"]),
            "op": arm, "ok": bool(obj.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_OVERLOAD),
        })
    return out


def _normalize_serve_failover(name: str, obj: dict,
                              rnd: Optional[int]) -> List[dict]:
    """The round-17 failover A/B artifact: {"bench": "serve_failover",
    "platform", "n", "arms": {"protected": {...}, "cold": {...}},
    "ok"} — one record per arm (arm label in the ``op`` series-key
    slot, the serve_overload convention)."""
    for k in ("platform", "n", "arms", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_failover artifact "
                              f"missing {k!r}")
    arms = obj["arms"]
    if not isinstance(arms, dict) or set(arms) != {"protected", "cold"}:
        raise SchemaError(f"{name}: serve_failover arms must be "
                          "exactly {protected, cold}")
    out = []
    for arm, row in sorted(arms.items()):
        for k in ("affected_handles", "failover_s", "recovery_s_max",
                  "refactors_after_crash", "availability",
                  "wrong_answers"):
            if k not in row:
                raise SchemaError(
                    f"{name}[arms.{arm}]: serve_failover arm missing "
                    f"{k!r}")
        out.append({
            "round": rnd, "source": f"{name}[{arm}]",
            "kind": "serve_failover",
            "platform": str(obj["platform"]), "n": int(obj["n"]),
            "op": arm, "ok": bool(obj.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_FAILOVER),
        })
    return out


def _normalize_serve_fair(name: str, obj: dict,
                          rnd: Optional[int]) -> List[dict]:
    """The round-18 tenant-isolation A/B artifact: {"bench":
    "serve_fair", "platform", "n", "arms": {"fair": {"tenants":
    {tenant: {...}}}, "fifo": {...}}, "ok"} — one record per
    (arm, tenant), the arm.tenant pair in the ``op`` series-key slot
    (the serve_overload convention) so a fair-arm victim series never
    gates against the fifo-arm one."""
    for k in ("platform", "n", "arms", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_fair artifact "
                              f"missing {k!r}")
    arms = obj["arms"]
    if not isinstance(arms, dict) or set(arms) != {"fair", "fifo"}:
        raise SchemaError(f"{name}: serve_fair arms must be exactly "
                          "{fair, fifo}")
    out = []
    for arm, row in sorted(arms.items()):
        tenants = row.get("tenants")
        if not isinstance(tenants, dict) or not tenants:
            raise SchemaError(
                f"{name}[arms.{arm}]: serve_fair arm missing tenants")
        for tenant, trow in sorted(tenants.items()):
            for k in ("submitted", "completed", "quota_rejected",
                      "p99_latency_s", "reqs_per_sec"):
                if k not in trow:
                    raise SchemaError(
                        f"{name}[arms.{arm}.{tenant}]: serve_fair "
                        f"tenant row missing {k!r}")
            out.append({
                "round": rnd, "source": f"{name}[{arm}.{tenant}]",
                "kind": "serve_fair",
                "platform": str(obj["platform"]), "n": int(obj["n"]),
                "op": f"{arm}.{tenant}",
                "ok": bool(obj.get("ok", True)),
                "metrics": _flat_metrics(trow, TRACKED_FAIR),
            })
    return out


def validate_checkpoint_manifest(doc) -> List[str]:
    """Jax-free mirror of slate_tpu/runtime/checkpoint.py's
    ``validate_manifest`` (the placement-schema duplication pattern;
    tests pin the two against the same malformed docs): schema errors
    for one checkpoint manifest, empty list = valid. Accepts a parsed
    dict or a path to a manifest.json / checkpoint directory."""
    if isinstance(doc, str):
        path = doc
        if os.path.isdir(path):
            path = os.path.join(path, "manifest.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return [f"manifest unreadable ({e})"]
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["checkpoint manifest is not an object"]
    if doc.get("schema") != CHECKPOINT_SCHEMA:
        errs.append(f"schema != {CHECKPOINT_SCHEMA!r}")
    if not isinstance(doc.get("host"), str) or not doc.get("host"):
        errs.append("host missing/not a string")
    ga = doc.get("generated_at")
    if not isinstance(ga, (int, float)) or isinstance(ga, bool):
        errs.append("generated_at missing/not a number")
    records = doc.get("records")
    if not isinstance(records, list):
        return errs + ["records missing/not a list"]
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            errs.append(f"records[{i}]: not an object")
            continue
        for k in CHECKPOINT_RECORD_KEYS:
            if k not in rec:
                errs.append(f"records[{i}]: missing {k!r}")
        if rec.get("handle_type") not in ("str", "int"):
            errs.append(f"records[{i}].handle_type: not 'str'/'int'")
        for k in ("op", "dtype"):
            if k in rec and not isinstance(rec[k], str):
                errs.append(f"records[{i}].{k}: not a string")
        for k in ("m", "n", "band", "nb", "info"):
            v = rec.get(k)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool)):
                errs.append(f"records[{i}].{k}: not an int")
        mesh = rec.get("mesh")
        if mesh is not None and (not isinstance(mesh, list)
                                 or len(mesh) != 2):
            errs.append(f"records[{i}].mesh: not [p, q] or null")
        for k in ("operator", "payload"):
            errs.extend(_validate_ckpt_node(rec.get(k),
                                            f"records[{i}].{k}"))
    return errs


def _validate_ckpt_node(desc, where: str) -> List[str]:
    """Mirror of checkpoint._validate_node (see
    validate_checkpoint_manifest)."""
    if not isinstance(desc, dict) or "type" not in desc:
        return [f"{where}: not a node descriptor"]
    t = desc["type"]
    if t == "tuple":
        items = desc.get("items")
        if not isinstance(items, list):
            return [f"{where}.items: missing/not a list"]
        errs = []
        for j, d in enumerate(items):
            errs.extend(_validate_ckpt_node(d, f"{where}[{j}]"))
        return errs
    if t in ("eig_factors", "svd_factors"):
        # round-19 spectral nodes: basis matrices nest as full node
        # descriptors, the spectrum is a direct blob
        nested = ("v",) if t == "eig_factors" else ("u", "v")
        spec = "lam" if t == "eig_factors" else "s"
        errs = []
        for field in nested:
            errs.extend(_validate_ckpt_node(desc.get(field),
                                            f"{where}.{field}"))
        b = desc.get(spec)
        if not isinstance(b, dict):
            errs.append(f"{where}.{spec}: missing blob descriptor")
        else:
            for k in CHECKPOINT_BLOB_KEYS:
                if k not in b:
                    errs.append(f"{where}.{spec}: blob missing {k!r}")
        return errs
    blob_fields = {"array": ("a",), "tiled": ("data",),
                   "packed_band": ("ab",), "qr_factors": ("vr", "t")}
    if t not in blob_fields:
        return [f"{where}.type: unknown {t!r}"]
    errs = []
    for field in blob_fields[t]:
        b = desc.get(field)
        if not isinstance(b, dict):
            errs.append(f"{where}.{field}: missing blob descriptor")
            continue
        for k in CHECKPOINT_BLOB_KEYS:
            if k not in b:
                errs.append(f"{where}.{field}: blob missing {k!r}")
    return errs


def _normalize_serve_spectral(name: str, obj: dict,
                              rnd: Optional[int]) -> List[dict]:
    """The round-19 resident-spectral A/B artifact: {"bench":
    "serve_spectral", "platform", "n", "rows": [{op, resident, cold,
    speedup, one_program, ...}], "ok"} — one record per op row (the
    op in its natural series-key slot). A row that stopped being
    structurally one-program (compiles after warmup, or an apply that
    is no longer two gemms) fails schema validation outright — that
    is a broken serving claim, not a slow one."""
    for k in ("platform", "n", "nb", "requests", "rows", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_spectral artifact "
                              f"missing {k!r}")
    rows = obj["rows"]
    if not isinstance(rows, list) or not rows:
        raise SchemaError(f"{name}: serve_spectral rows missing/empty")
    out = []
    for i, row in enumerate(rows):
        for k in ("op", "n", "resident", "cold", "speedup",
                  "new_compiles_after_warmup", "apply_dot_ops",
                  "census", "max_rel_err", "one_program"):
            if k not in row:
                raise SchemaError(
                    f"{name}[rows.{i}]: serve_spectral row missing "
                    f"{k!r}")
        if row["op"] not in ("eig", "svd"):
            raise SchemaError(f"{name}[rows.{i}]: serve_spectral op "
                              f"{row['op']!r} not eig/svd")
        if not row["one_program"]:
            raise SchemaError(
                f"{name}[rows.{i}]: spectral serving is no longer "
                "one-program (compiles after warmup, or an apply "
                "that is not two gemms)")
        out.append({
            "round": rnd, "source": f"{name}[{row['op']}]",
            "kind": "serve_spectral",
            "platform": str(obj["platform"]), "n": int(row["n"]),
            "op": str(row["op"]), "ok": bool(obj.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_SPECTRAL),
        })
    return out


def _normalize_serve_update(name: str, obj: dict,
                            rnd: Optional[int]) -> List[dict]:
    """The round-20 incremental-maintenance A/B artifact: {"bench":
    "serve_update", "platform", "nb", "rows": [{op, n, k, update,
    refactor, speedup, model_flops, sync, ...}], "sync_totals", "ok"}
    — one record per (op, n, k) row, k riding the batch series slot
    (same discipline as serve_batched's B). A row that paid a full
    refactor or a recompile for a served mutation fails schema
    validation outright — that is a broken incremental-maintenance
    claim, not a slow one; so does a delta sync that costs MORE than
    the full re-transfer it exists to undercut."""
    for k in ("platform", "nb", "rows", "sync_totals", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_update artifact "
                              f"missing {k!r}")
    rows = obj["rows"]
    if not isinstance(rows, list) or not rows:
        raise SchemaError(f"{name}: serve_update rows missing/empty")
    out = []
    for i, row in enumerate(rows):
        for k in ("op", "m", "n", "k", "update", "refactor", "speedup",
                  "model_flops", "sync", "new_compiles_after_warmup",
                  "update_refactors"):
            if k not in row:
                raise SchemaError(
                    f"{name}[rows.{i}]: serve_update row missing {k!r}")
        if row["op"] not in ("chol", "qr"):
            raise SchemaError(f"{name}[rows.{i}]: serve_update op "
                              f"{row['op']!r} not chol/qr")
        if row["update_refactors"] != 0:
            raise SchemaError(
                f"{name}[rows.{i}]: {row['update_refactors']} full "
                "refactors on the served-update path (the happy path "
                "is O(n²k) incremental, never a refactor)")
        if row["new_compiles_after_warmup"] != 0:
            raise SchemaError(
                f"{name}[rows.{i}]: serve_update recorded "
                f"{row['new_compiles_after_warmup']} compiles after "
                "warmup (every rank bucket must be pre-compiled)")
        sync = row["sync"]
        if not isinstance(sync, dict) or "delta_bytes" not in sync \
                or "full_bytes" not in sync:
            raise SchemaError(f"{name}[rows.{i}]: serve_update sync "
                              "split missing delta/full bytes")
        if sync["delta_bytes"] > sync["full_bytes"]:
            raise SchemaError(
                f"{name}[rows.{i}]: delta sync "
                f"({sync['delta_bytes']}B) costs more than the full "
                f"re-transfer ({sync['full_bytes']}B)")
        out.append({
            "round": rnd,
            "source": f"{name}[{row['op']}/n{row['n']}/k{row['k']}]",
            "kind": "serve_update",
            "platform": str(obj["platform"]), "n": int(row["n"]),
            "batch": int(row["k"]), "op": str(row["op"]),
            "ok": bool(row.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_UPDATE),
        })
    return out


def _normalize_chaos(name: str, obj: dict,
                     rnd: Optional[int]) -> List[dict]:
    """The round-14 chaos-soak artifact (tools/chaos_serve.py →
    CHAOS_r*.json): schema-validated so a soak whose invariant or
    schedule sections go stale fails --check-schema; never a perf
    series (the invariants are booleans, not trajectories)."""
    for k in ("platform", "seed", "plan", "fault_classes", "phases",
              "invariants", "schedule", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: chaos artifact missing {k!r}")
    if not isinstance(obj["fault_classes"], list) \
            or not obj["fault_classes"]:
        raise SchemaError(f"{name}: chaos fault_classes missing/empty")
    inv = obj["invariants"]
    for k in ("wrong_answers", "lost_futures", "conservation_ok",
              "slo_consistent", "fleet_fold_ok",
              "schedule_reproducible",
              "noisy_neighbor_isolated", "migration_zero_refactor",
              "recorder_black_box", "forecast_leads_peak"):
        if k not in inv:
            raise SchemaError(f"{name}: chaos invariants missing {k!r}")
    if not isinstance(obj["schedule"], dict) \
            or "digest" not in obj["schedule"]:
        raise SchemaError(f"{name}: chaos schedule.digest missing")
    if "soak" not in obj.get("phases", {}):
        raise SchemaError(f"{name}: chaos phases.soak missing")
    return [{
        "round": rnd, "source": name, "kind": "chaos",
        "platform": str(obj["platform"]), "n": None,
        "ok": bool(obj["ok"]), "metrics": {},
    }]


def _validate_tuning_doc(name: str, obj) -> None:
    """Mirror of slate_tpu/tuning/table.py validate_table (tests pin
    the two validators against the same malformed docs): the committed
    TUNING_r*.json held to its schema without importing the runtime —
    a hand-edited table would otherwise be discovered by a serving
    session resolving garbage, not by CI."""
    if not isinstance(obj, dict):
        raise SchemaError(f"{name}: tuning table is not an object")
    if obj.get("schema") != TUNING_SCHEMA:
        raise SchemaError(f"{name}: schema {obj.get('schema')!r} != "
                          f"{TUNING_SCHEMA!r}")
    entries = obj.get("entries")
    if not isinstance(entries, list) or not entries:
        raise SchemaError(f"{name}: entries missing or empty")
    for i, row in enumerate(entries):
        if not isinstance(row, dict):
            raise SchemaError(f"{name}[entries.{i}]: not an object")
        for k in ("op", "dtype", "platform", "config"):
            if k not in row:
                raise SchemaError(f"{name}[entries.{i}]: missing {k!r}")
        n_max = row.get("n_max")
        if n_max is not None and (not isinstance(n_max, int)
                                  or isinstance(n_max, bool)
                                  or n_max <= 0):
            raise SchemaError(f"{name}[entries.{i}]: n_max must be a "
                              "positive int or null")
        cfg = row["config"]
        if not isinstance(cfg, dict) or not cfg:
            raise SchemaError(f"{name}[entries.{i}]: config missing "
                              "or empty")
        for k, v in cfg.items():
            if k not in TUNING_CONFIG_KEYS:
                raise SchemaError(f"{name}[entries.{i}]: unknown "
                                  f"config knob {k!r}")
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                raise SchemaError(f"{name}[entries.{i}]: config "
                                  f"{k}={v!r} must be a non-negative "
                                  "int or null")


def _normalize_tuning(name: str, obj: dict,
                      rnd: Optional[int]) -> List[dict]:
    """The round-21 committed tuning table (tools/autotune.py →
    TUNING_r*.json): schema-validated (the serving runtime resolves
    configs out of this exact file), each entry's measured search
    score entering the trajectory as an informational series keyed by
    the entry's (op, n_max, dtype, platform)."""
    _validate_tuning_doc(name, obj)
    out = []
    for i, row in enumerate(obj["entries"]):
        score = row.get("score") or {}
        metrics = {}
        if isinstance(score.get("gflops"), (int, float)) \
                and not isinstance(score.get("gflops"), bool):
            metrics["tuned.gflops"] = float(score["gflops"])
        out.append({
            "round": rnd, "source": f"{name}[{i}]", "kind": "tuning",
            "platform": str(row["platform"]),
            "n": row.get("n_max"),
            "op": str(row["op"]),
            "dtype": (None if row["dtype"] in ("*", None)
                      else str(row["dtype"])),
            "ok": True, "metrics": metrics,
        })
    return out


def _normalize_serve_tuned(name: str, obj: dict,
                           rnd: Optional[int]) -> List[dict]:
    """The round-21 tuned-serving A/B artifact: {"bench":
    "serve_tuned", "platform", "table", "rows": [...]} — one
    ``serve_tuned`` record per row, series keyed by the row's
    (op, n, dtype). The compile-count columns are validated
    structural evidence (a tuned arm that compiles on the serve path
    fails schema here, not just the bench's own exit gate)."""
    for k in ("platform", "table", "rows", "ok"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_tuned artifact missing "
                              f"{k!r}")
    if not isinstance(obj["rows"], list) or not obj["rows"]:
        raise SchemaError(f"{name}: serve_tuned artifact with empty "
                          "rows")
    out = []
    for i, row in enumerate(obj["rows"]):
        for k in ("op", "n", "default", "tuned", "speedup", "ok"):
            if k not in row:
                raise SchemaError(
                    f"{name}[rows.{i}]: serve_tuned row missing {k!r}")
        for arm in ("default", "tuned"):
            arm_row = row[arm]
            if not isinstance(arm_row, dict):
                raise SchemaError(f"{name}[rows.{i}]: {arm} arm not "
                                  "an object")
            for k in ("solves_per_sec", "new_compiles_after_warmup",
                      "config"):
                if k not in arm_row:
                    raise SchemaError(
                        f"{name}[rows.{i}]: {arm} arm missing {k!r}")
        out.append({
            "round": rnd, "source": f"{name}[{i}]",
            "kind": "serve_tuned",
            "platform": str(obj["platform"]), "n": int(row["n"]),
            "op": str(row["op"]),
            "dtype": str(row.get("dtype", "")) or None,
            "ok": bool(row.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_TUNED),
        })
    return out


def _normalize_serve_mixed(name: str, obj: dict,
                           rnd: Optional[int]) -> List[dict]:
    """The round-13 mixed-precision serving artifact: {"bench":
    "serve_mixed", "platform", "factor_dtype", "rows": [...]} — one
    ``serve_mixed`` record per row, series keyed by the row's
    (op, n, dtype)."""
    for k in ("platform", "factor_dtype", "rows"):
        if k not in obj:
            raise SchemaError(f"{name}: serve_mixed artifact missing "
                              f"{k!r}")
    if not isinstance(obj["rows"], list) or not obj["rows"]:
        raise SchemaError(f"{name}: serve_mixed artifact with empty rows")
    out = []
    for i, row in enumerate(obj["rows"]):
        for k in ("op", "n", "mixed", "full", "speedup",
                  "factor_bytes_ratio"):
            if k not in row:
                raise SchemaError(
                    f"{name}[rows.{i}]: serve_mixed row missing {k!r}")
        out.append({
            "round": rnd, "source": f"{name}[{i}]",
            "kind": "serve_mixed",
            "platform": str(obj["platform"]), "n": int(row["n"]),
            "op": str(row["op"]),
            "dtype": str(row.get("dtype", "")) or None,
            "ok": bool(row.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_SERVE_MIXED),
        })
    return out


def _normalize_multichip(name: str, obj: dict,
                         rnd: Optional[int]) -> List[dict]:
    """The round-11 structured multichip artifact: {"bench":
    "multichip", "platform", "mesh_shape", "n_devices", "rows": [...]}
    — one ``multichip_serve`` record per row, series keyed by the
    row's (op, n)."""
    for k in ("platform", "mesh_shape", "n_devices", "rows"):
        if k not in obj:
            raise SchemaError(f"{name}: multichip artifact missing {k!r}")
    if not isinstance(obj["rows"], list) or not obj["rows"]:
        raise SchemaError(f"{name}: multichip artifact with empty rows")
    out = []
    for i, row in enumerate(obj["rows"]):
        for k in ("op", "n", "serve", "single_device", "speedup"):
            if k not in row:
                raise SchemaError(
                    f"{name}[rows.{i}]: multichip row missing {k!r}")
        out.append({
            "round": rnd, "source": f"{name}[{i}]",
            "kind": "multichip_serve",
            "platform": str(obj["platform"]), "n": int(row["n"]),
            "op": str(row["op"]),
            # dtype is part of the series key: the artifact carries
            # f32 AND f64 rows per (op, n), and comparing an f64 round
            # against an f32 best-prior would fabricate a regression
            "dtype": str(row.get("dtype", "")) or None,
            "mesh_shape": list(obj["mesh_shape"]),
            "ok": bool(row.get("ok", True)),
            "metrics": _flat_metrics(row, TRACKED_MULTICHIP),
        })
    return out


def _check_tenants_section(name: str, section) -> None:
    """Validate the round-15 serve-artifact ``tenants`` section:
    per-tenant totals, the conservation verdict, and the embedded
    placement snapshot against the committed row schema (the jax-free
    mirror of obs.attribution.validate_placement_snapshot)."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: tenants section is not an object")
    for k in ("enabled", "per_tenant", "conservation",
              "conservation_ok", "placement"):
        if k not in section:
            raise SchemaError(f"{name}: tenants section missing {k!r}")
    if not isinstance(section["per_tenant"], dict):
        raise SchemaError(f"{name}: tenants.per_tenant not an object")
    cons = section["conservation"]
    if not isinstance(cons, dict) or not cons:
        raise SchemaError(f"{name}: tenants.conservation missing/empty")
    for cls, row in cons.items():
        if not isinstance(row, dict) or "ok" not in row:
            raise SchemaError(
                f"{name}: tenants.conservation[{cls!r}] missing 'ok'")
    placement = section["placement"]
    if not isinstance(placement, dict) \
            or placement.get("schema") != PLACEMENT_SCHEMA:
        raise SchemaError(
            f"{name}: tenants.placement schema != {PLACEMENT_SCHEMA!r}")
    rows = placement.get("rows")
    if not isinstance(rows, list):
        raise SchemaError(f"{name}: tenants.placement.rows not a list")
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            raise SchemaError(
                f"{name}: tenants.placement.rows[{i}] not an object")
        for k in PLACEMENT_ROW_KEYS:
            if k not in row:
                raise SchemaError(
                    f"{name}: tenants.placement.rows[{i}] missing {k!r}")


def _check_numerics_section(name: str, section) -> None:
    """Validate the round-16 serve-artifact ``numerics`` section:
    per-handle health rows whose states come from the committed
    vocabulary, the probe counters, and the exit-gated verdict."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: numerics section is not an object")
    for k in ("enabled", "handles", "counters", "ok"):
        if k not in section:
            raise SchemaError(f"{name}: numerics section missing {k!r}")
    handles = section["handles"]
    if not isinstance(handles, dict):
        raise SchemaError(f"{name}: numerics.handles not an object")
    for h, row in handles.items():
        if not isinstance(row, dict) or "state" not in row:
            raise SchemaError(
                f"{name}: numerics.handles[{h!r}] missing 'state'")
        if row["state"] not in HEALTH_STATES:
            raise SchemaError(
                f"{name}: numerics.handles[{h!r}].state "
                f"{row['state']!r} not one of {HEALTH_STATES}")
    if not isinstance(section["counters"], dict):
        raise SchemaError(f"{name}: numerics.counters not an object")


def _check_quotas_section(name: str, section) -> None:
    """Validate the round-18 serve-artifact ``quotas`` section: the
    declared tenant policies, per-tenant resident bytes, and the quota
    counters — a committed fixture whose quota view went missing means
    the bench session's tenant table silently fell off."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: quotas section is not an object")
    for k in ("enabled", "tenants"):
        if k not in section:
            raise SchemaError(f"{name}: quotas section missing {k!r}")
    if not section["enabled"]:
        raise SchemaError(f"{name}: quotas section disabled (the bench "
                          "session must carry a tenant table)")
    for k in ("policies", "counters"):
        if k not in section or not isinstance(section[k], dict):
            raise SchemaError(f"{name}: quotas.{k} missing/not an "
                              "object")
    for t, row in section["tenants"].items():
        if not isinstance(row, dict) or "resident_bytes" not in row:
            raise SchemaError(
                f"{name}: quotas.tenants[{t!r}] missing resident_bytes")


def _check_spectral_section(name: str, section) -> None:
    """Validate the round-19 serve-artifact ``spectral`` section: the
    resident-eigendecomposition structural columns — zero new compiles
    across theta-varying serves, the two-gemm dot census of every
    warmed apply program, and the exit-gated verdict. A committed
    fixture whose spectral serving recompiles per theta (or whose
    apply stopped being two gemms) is a broken serving claim."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: spectral section is not an object")
    for k in ("enabled", "op", "n", "functions",
              "new_compiles_after_warmup", "apply_dot_ops",
              "stage_programs", "solve_rel_err", "ok"):
        if k not in section:
            raise SchemaError(f"{name}: spectral section missing {k!r}")
    if section["new_compiles_after_warmup"] != 0:
        raise SchemaError(
            f"{name}: spectral section recorded "
            f"{section['new_compiles_after_warmup']} compiles after "
            "warmup (theta must be traced, never a recompile)")
    dots = section["apply_dot_ops"]
    if not isinstance(dots, dict) or not dots:
        raise SchemaError(f"{name}: spectral.apply_dot_ops "
                          "missing/empty")
    for fn, d in dots.items():
        if d != 2:
            raise SchemaError(
                f"{name}: spectral apply {fn!r} lowered to {d} dot "
                "ops (the served apply is exactly two gemms + a "
                "diagonal scale)")
    if not isinstance(section["stage_programs"], list) \
            or not section["stage_programs"]:
        raise SchemaError(f"{name}: spectral.stage_programs "
                          "missing/empty")


def _check_updates_section(name: str, section) -> None:
    """Validate the round-20 serve-artifact ``updates`` section: the
    incremental-maintenance structural columns — every mutation served
    on the O(n²k) path (zero full refactors, zero new compiles after
    warmup), nonzero update flops credited, and the exit-gated
    verdict. A committed fixture whose resident pays a refactor per
    served mutation is a broken incremental-maintenance claim."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: updates section is not an object")
    for k in ("enabled", "op", "n", "k", "updates_applied",
              "new_compiles_after_warmup", "update_refactors",
              "refactors_during_updates", "update_flops",
              "solve_rel_err", "ok"):
        if k not in section:
            raise SchemaError(f"{name}: updates section missing {k!r}")
    if section["update_refactors"] != 0 \
            or section["refactors_during_updates"] != 0:
        raise SchemaError(
            f"{name}: updates section recorded a full refactor on the "
            "served-update path (the happy path is incremental)")
    if section["new_compiles_after_warmup"] != 0:
        raise SchemaError(
            f"{name}: updates section recorded "
            f"{section['new_compiles_after_warmup']} compiles after "
            "warmup (the rank bucket must be pre-compiled)")
    if not section["update_flops"] > 0:
        raise SchemaError(f"{name}: updates section credited no "
                          "update flops to the ledger")


def _check_tuning_section(name: str, section) -> None:
    """Validate the round-21 serve-artifact ``tuning`` section: the
    committed-table structural columns — the table loaded, a fresh
    registration resolved its config with provenance, and the warmed
    tuned solve added zero compiles on the serve path. A disabled
    section (no committed table) is valid — the tuning subsystem is
    optional by design — but a PRESENT table that recompiles on the
    serve path is a broken tuning claim."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: tuning section is not an object")
    for k in ("enabled", "table", "resolved",
              "new_compiles_after_warmup", "ok"):
        if k not in section:
            raise SchemaError(f"{name}: tuning section missing {k!r}")
    if not section["enabled"]:
        return
    table = section["table"]
    if not isinstance(table, dict) \
            or table.get("schema") != TUNING_SCHEMA:
        raise SchemaError(f"{name}: tuning.table schema != "
                          f"{TUNING_SCHEMA!r}")
    if section["new_compiles_after_warmup"] != 0:
        raise SchemaError(
            f"{name}: tuning section recorded "
            f"{section['new_compiles_after_warmup']} compiles after "
            "warmup (the table must never put compilation back on "
            "the serve path)")


def validate_incident_doc(doc) -> List[str]:
    """Jax-free mirror of ``slate_tpu.obs.events.validate_incident``
    (tests feed both validators the same malformed docs and pin the
    verdicts equal): returns error strings, empty = valid."""
    errs = []
    if not isinstance(doc, dict):
        return [f"incident: not a dict ({type(doc).__name__})"]
    if doc.get("schema") != INCIDENT_SCHEMA:
        errs.append(f"incident: schema {doc.get('schema')!r} != "
                    f"{INCIDENT_SCHEMA!r}")
    for k in INCIDENT_KEYS:
        if k not in doc:
            errs.append(f"incident: missing key {k!r}")
    if errs:
        return errs
    if not isinstance(doc["id"], str) or not doc["id"]:
        errs.append("incident: id must be a nonempty string")
    if not isinstance(doc["ts"], (int, float)):
        errs.append("incident: ts must be a number")
    if not isinstance(doc["reason"], str) or not doc["reason"]:
        errs.append("incident: reason must be a nonempty string")
    j = doc["journal"]
    if not isinstance(j, dict) or "events" not in j or "counts" not in j:
        errs.append("incident: journal must carry events + counts")
    else:
        if not isinstance(j["events"], list):
            errs.append("incident: journal.events must be a list")
        else:
            for i, ev in enumerate(j["events"]):
                if (not isinstance(ev, dict) or not ev.get("kind")
                        or not isinstance(ev.get("ts"), (int, float))
                        or not isinstance(ev.get("count"),
                                          (int, float))):
                    errs.append(f"incident: journal.events[{i}] "
                                "malformed (kind/ts/count)")
                    break
        if not isinstance(j["counts"], dict):
            errs.append("incident: journal.counts must be a dict")
    fl = doc["flight"]
    if (not isinstance(fl, dict)
            or not isinstance(fl.get("spans"), list)
            or not isinstance(fl.get("samples"), list)):
        errs.append("incident: flight must carry spans + samples lists")
    m = doc["metrics"]
    if (not isinstance(m, dict)
            or not isinstance(m.get("counters"), dict)
            or not isinstance(m.get("gauges"), dict)):
        errs.append("incident: metrics must carry counters + gauges")
    return errs


def _check_incidents_section(name: str, section) -> None:
    """Validate the round-22 serve-artifact ``incidents`` section: the
    decision-journal/counter parity verdicts and one embedded sample
    incident held to ``slate_tpu.incident.v1`` by the mirror validator
    above — a committed fixture whose black box stopped recording (or
    whose parity broke) is a broken recorder, not a slow bench."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: incidents section is not an object")
    for k in ("enabled", "ok", "captured", "journal_recorded",
              "parity", "sample"):
        if k not in section:
            raise SchemaError(f"{name}: incidents section missing {k!r}")
    if not section["enabled"]:
        raise SchemaError(f"{name}: incidents section disabled (the "
                          "bench session must run its recorder)")
    if not isinstance(section["parity"], dict) or not section["parity"]:
        raise SchemaError(f"{name}: incidents.parity missing/empty")
    bad = [k for k, row in section["parity"].items()
           if not (isinstance(row, dict) and row.get("ok"))]
    if bad:
        raise SchemaError(
            f"{name}: incidents.parity broken for {bad} (journal "
            "count != metric counter delta)")
    errs = validate_incident_doc(section["sample"])
    if errs:
        raise SchemaError(f"{name}: incidents.sample invalid: "
                          + "; ".join(errs))
    if not section["ok"]:
        raise SchemaError(f"{name}: incidents section verdict not ok")


def _check_forecast_section(name: str, section) -> None:
    """Validate the round-23 serve-artifact ``forecast`` section: the
    embedded /history payload held to slate_tpu.timeseries.v1, the
    embedded /forecast payload held to slate_tpu.forecast.v1 (both by
    the REAL validators, file-loaded above), and the exact
    counter-conservation table — a committed fixture whose store lost
    a count (or whose payloads fail their own schemas) is a broken
    sensing substrate, not a slow bench."""
    if not isinstance(section, dict):
        raise SchemaError(f"{name}: forecast section is not an object")
    for k in ("enabled", "ok", "series_count", "dropped_series",
              "dropped_samples", "conservation", "history",
              "forecast"):
        if k not in section:
            raise SchemaError(f"{name}: forecast section missing {k!r}")
    if not section["enabled"]:
        raise SchemaError(f"{name}: forecast section disabled (the "
                          "bench session must run its time-series "
                          "store)")
    errs = validate_timeseries_doc(section["history"])
    errs += validate_forecast_doc(section["forecast"])
    if errs:
        raise SchemaError(f"{name}: forecast payloads invalid: "
                          + "; ".join(errs))
    cons = section["conservation"]
    if not isinstance(cons, dict) or not cons:
        raise SchemaError(f"{name}: forecast.conservation missing/"
                          "empty")
    bad = [k for k, row in cons.items()
           if not (isinstance(row, dict) and row.get("ok"))]
    if bad:
        raise SchemaError(
            f"{name}: forecast.conservation broken for {bad} (store "
            "delta sum != live counter)")
    if not section["ok"]:
        raise SchemaError(f"{name}: forecast section verdict not ok")


def _normalize_obj(name: str, obj, fname_round: Optional[int]) -> dict:
    if not isinstance(obj, dict):
        raise SchemaError(f"{name}: top level is not an object")

    if obj.get("bench") == "serve_forecast":
        for k in ("platform", "n", "serve", "store", "holdout", "ok"):
            if k not in obj:
                raise SchemaError(
                    f"{name}: serve_forecast row missing {k!r}")
        hold = obj["holdout"]
        if not isinstance(hold, dict) or "improvement" not in hold:
            raise SchemaError(f"{name}: serve_forecast holdout "
                              "missing improvement")
        if hold.get("aperiodic_period_s") is not None:
            raise SchemaError(f"{name}: serve_forecast claims a "
                              "period on the aperiodic control")
        return {
            "round": fname_round, "source": name,
            "kind": "serve_forecast",
            "platform": str(obj["platform"]), "n": int(obj["n"]),
            "ok": bool(obj["ok"]),
            "metrics": _flat_metrics(obj, TRACKED_FORECAST),
        }

    if obj.get("schema") == CAPACITY_SCHEMA:
        errs = validate_capacity_doc(obj)
        if errs:
            raise SchemaError(f"{name}: " + "; ".join(errs))
        # planning artifact, never a perf series: schema-gated only
        return {
            "round": fname_round, "source": name, "kind": "capacity",
            "platform": "cpu", "n": None, "ok": True, "metrics": {},
        }

    if obj.get("bench") == "serve_batched":
        for k in ("platform", "op", "n", "batch", "batched",
                  "per_request", "speedup"):
            if k not in obj:
                raise SchemaError(
                    f"{name}: serve_batched row missing {k!r}")
        return {
            "round": fname_round, "source": name, "kind": "serve_batched",
            "platform": str(obj["platform"]), "n": int(obj["n"]),
            "batch": int(obj["batch"]), "op": str(obj["op"]), "ok": True,
            "metrics": _flat_metrics(obj, TRACKED_SERVE_BATCHED),
        }

    if obj.get("bench") == "serve":
        # the FULL current section list, not just the gating keys: a
        # committed fixture that predates a schema addition fails here
        # (regenerate with bench_serve.py --regen-smoke)
        for k in SERVE_ARTIFACT_SECTIONS:
            if k not in obj:
                raise SchemaError(
                    f"{name}: serve artifact missing section {k!r} "
                    "(stale smoke fixture? regenerate with "
                    "bench_serve.py --regen-smoke)")
        _check_tenants_section(name, obj["tenants"])
        _check_numerics_section(name, obj["numerics"])
        _check_quotas_section(name, obj["quotas"])
        _check_spectral_section(name, obj["spectral"])
        _check_updates_section(name, obj["updates"])
        _check_tuning_section(name, obj["tuning"])
        _check_incidents_section(name, obj["incidents"])
        _check_forecast_section(name, obj["forecast"])
        return {
            "round": fname_round, "source": name, "kind": "serve",
            "platform": str(obj["backend"]), "n": int(obj["n"]),
            "ok": True, "metrics": _flat_metrics(obj, TRACKED_SERVE),
        }

    if "n_devices" in obj and "rc" in obj and "bench" not in obj \
            and "cmd" not in obj:
        # rounds 1–5 multichip dry-run blob: {n_devices, rc, ok,
        # skipped, tail} with the per-driver residuals buried in the
        # tail string. Normalized as INFORMATIONAL series (the runs
        # are CPU-forced virtual meshes, and residuals are
        # lower-is-better — they never gate; they exist so the
        # trajectory read covers every committed artifact).
        tail = str(obj.get("tail", ""))
        metrics = {}
        if obj.get("ok"):
            for mm in _TAIL_RESID_RE.finditer(tail):
                key = mm.group(1).replace("+", "_")
                metrics[f"residual_{key}"] = float(mm.group(2))
        return {
            "round": fname_round, "source": name,
            "kind": "multichip_dryrun",
            "platform": _infer_platform_from_tail(tail) or "cpu",
            "n": int(obj["n_devices"]), "ok": bool(obj.get("ok")),
            "metrics": metrics,
        }

    if "cmd" in obj and "rc" in obj:  # rounds 1-5 harness wrapper
        rnd = obj.get("n", fname_round)
        if not isinstance(rnd, int):
            raise SchemaError(f"{name}: wrapper round index missing")
        ok = obj["rc"] == 0
        parsed = obj.get("parsed") or {}
        if ok and "metric" not in parsed:
            raise SchemaError(f"{name}: rc=0 wrapper without parsed "
                              "metrics")
        platform = (parsed.get("platform")
                    or _infer_platform_from_tail(str(obj.get("tail", "")))
                    or "unknown")
        n = None
        mm = _N_RE.search(parsed.get("metric", ""))
        if mm:
            n = int(mm.group(1))
        return {
            "round": rnd, "source": name, "kind": "bench",
            "platform": platform, "n": n, "ok": ok,
            "metrics": _flat_metrics(parsed, TRACKED_BENCH) if ok else {},
        }

    if "metric" in obj and "value" in obj:  # bare bench.py artifact
        mm = _N_RE.search(obj["metric"])
        return {
            "round": fname_round, "source": name, "kind": "bench",
            "platform": str(obj.get("platform", "unknown")),
            "n": int(mm.group(1)) if mm else None,
            "ok": "error" not in obj,
            "metrics": _flat_metrics(obj, TRACKED_BENCH),
        }

    raise SchemaError(f"{name}: matches no known BENCH schema "
                      "(wrapper / bench.py / serve / serve_batched)")


def discover(root: str) -> List[str]:
    paths = (glob.glob(os.path.join(root, "BENCH_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_SERVE*.json"))
             + glob.glob(os.path.join(root, "BENCH_MIXED_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_OVERLOAD_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_FAILOVER_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_FAIR_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_SPECTRAL_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_UPDATE_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_TUNED_r*.json"))
             + glob.glob(os.path.join(root, "TUNING_r*.json"))
             + glob.glob(os.path.join(root, "BENCH_FORECAST_r*.json"))
             + glob.glob(os.path.join(root, "CAPACITY_r*.json"))
             + glob.glob(os.path.join(root, "MULTICHIP_r*.json"))
             + glob.glob(os.path.join(root, "CHAOS_r*.json")))
    # bench_serve writes <stem>.metrics.json / <stem>.prom exposition
    # fixtures beside the headline artifact — different schema, not
    # part of the trajectory
    return sorted(p for p in paths if not p.endswith(".metrics.json"))


def _series_key(rec: dict, metric: str):
    # "batch"/"op" (serve_batched rows) and "dtype" (multichip rows)
    # keep batch-size buckets, operator classes, and dtypes in
    # separate series — None for every other schema
    return (rec["kind"], metric, rec["platform"], rec["n"],
            rec.get("batch"), rec.get("op"), rec.get("dtype"))


def gate(records: List[dict], tolerance: float = DEFAULT_TOLERANCE
         ) -> dict:
    """Compare the newest gateable record of every (metric, platform,
    n) series against the best prior value. Only GATED_PLATFORMS fail
    the gate; other platforms are summarized as informational."""
    series: dict = {}
    for rec in sorted(records,
                      key=lambda r: (r["round"] is None, r["round"] or 0)):
        if not rec["ok"]:
            continue
        for metric, value in rec["metrics"].items():
            series.setdefault(_series_key(rec, metric), []).append(
                {"round": rec["round"], "source": rec["source"],
                 "value": value})
    regressions, informational = [], []
    for key, points in series.items():
        if len(points) < 2:
            continue
        *prior, last = points
        best = max(p["value"] for p in prior)
        if best <= 0:
            continue
        drop = (best - last["value"]) / best
        if drop <= tolerance:
            continue
        row = {
            "kind": key[0], "metric": key[1], "platform": key[2],
            "n": key[3], "batch": key[4], "op": key[5],
            "dtype": key[6],
            "best_prior": best, "last": last["value"],
            "drop_pct": round(100 * drop, 1),
            "last_source": last["source"],
        }
        (regressions if key[2] in GATED_PLATFORMS
         else informational).append(row)
    return {
        "rounds": sorted({r["round"] for r in records
                          if r["round"] is not None}),
        "artifacts": len(records),
        "series": len(series),
        "tolerance": tolerance,
        "regressions": regressions,
        "informational_drops": informational,
        "ok": not regressions,
    }


# -- baseline export (round 12: the watchdog's single source of truth) ------

# schema id shared with slate_tpu/obs/watchdog.py (the consumer); the
# file lives at the repo root as BASELINE_SERIES.json
BASELINE_SCHEMA = "slate_tpu.baseline_series.v1"
BASELINE_FILENAME = "BASELINE_SERIES.json"


def _direction(metric: str) -> str:
    """Per-metric regression direction: every tracked series is
    higher-is-better (GFLOP/s, solves/s, speedup) EXCEPT the
    residual_* informational series parsed off the r01–r05 multichip
    tails (smaller residual = healthier) and anything latency-,
    queue-age-, or recovery-shaped (the round-14 overload and round-17
    failover columns) — classified here so a future artifact exporting
    a latency series cannot silently enter the baseline with an
    inverted direction (the watchdog would then read a 10× p99 rise as
    an improvement). The round-20 ``sync.*`` columns (delta-vs-full
    replica transfer bytes and their ratio) are transfer COSTS —
    lower-is-better by the same rule, as are the round-23 forecast
    columns (holdout MAE, store overhead pct, record-path ns/sample
    — error and cost, not throughput)."""
    if metric.startswith("residual_") or metric.startswith("sync.") \
            or "latency" in metric \
            or "age_s" in metric or "recovery" in metric \
            or "failover" in metric or "refactor" in metric \
            or "quota" in metric or "mae" in metric \
            or "overhead" in metric or "ns_per_sample" in metric:
        return "lower"
    return "higher"


def baseline_series(records: List[dict],
                    tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Normalized records -> the BASELINE_SERIES document: one row per
    (kind, metric, platform, n, batch, op, dtype) series with its
    best-prior value — what ``gate`` compares against, exported as a
    committed artifact so ``obs.watchdog`` loads ONE source of truth
    instead of re-deriving it from nine artifact schemas at runtime."""
    series: dict = {}
    for rec in sorted(records,
                      key=lambda r: (r["round"] is None, r["round"] or 0)):
        if not rec["ok"]:
            continue
        for metric, value in rec["metrics"].items():
            series.setdefault(_series_key(rec, metric), []).append(
                {"round": rec["round"], "source": rec["source"],
                 "value": value})
    rows = []
    for key, points in series.items():
        kind, metric, platform, n, batch, op, dtype = key
        values = [p["value"] for p in points]
        direction = _direction(metric)
        best = max(values) if direction == "higher" else min(values)
        rows.append({
            "kind": kind, "metric": metric, "platform": platform,
            "n": n, "batch": batch, "op": op, "dtype": dtype,
            "direction": direction, "best": best,
            "last": values[-1], "points": len(points),
            "rounds": sorted({p["round"] for p in points
                              if p["round"] is not None}),
            "sources": sorted({p["source"] for p in points}),
        })
    rows.sort(key=lambda r: tuple("" if v is None else str(v)
                                  for v in (r["metric"], r["platform"],
                                            r["n"], r["batch"], r["op"],
                                            r["dtype"])))
    return {
        "schema": BASELINE_SCHEMA,
        "tolerance": tolerance,
        "gated_platforms": list(GATED_PLATFORMS),
        "rounds": sorted({r["round"] for r in records
                          if r["round"] is not None}),
        "series": rows,
    }


def validate_baseline_file(path: str):
    """Schema-check a committed BASELINE_SERIES.json (raises
    SchemaError) — ``--check-schema`` covers the baseline artifact like
    every BENCH/MULTICHIP file, so a hand-edited or stale-schema
    baseline fails CI instead of silently blinding the watchdog."""
    name, obj = _load(path)
    if not isinstance(obj, dict) or obj.get("schema") != BASELINE_SCHEMA:
        raise SchemaError(f"{name}: schema != {BASELINE_SCHEMA!r}")
    rows = obj.get("series")
    if not isinstance(rows, list) or not rows:
        raise SchemaError(f"{name}: series missing or empty")
    for i, row in enumerate(rows):
        for k in ("metric", "platform", "best", "direction"):
            if k not in row:
                raise SchemaError(f"{name}[series.{i}]: missing {k!r}")
        if row["direction"] not in ("higher", "lower"):
            raise SchemaError(f"{name}[series.{i}]: bad direction "
                              f"{row['direction']!r}")
        if not isinstance(row["best"], (int, float)) \
                or isinstance(row["best"], bool):
            raise SchemaError(f"{name}[series.{i}]: non-numeric best")


def check_schema(paths: List[str]) -> List[str]:
    """Validate every artifact; returns error strings (empty = clean)."""
    errors = []
    for path in paths:
        try:
            normalize_all(path)
        except SchemaError as e:
            errors.append(str(e))
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default=None,
                   help="artifact directory (default: the repo root, "
                        "i.e. this file's parent's parent)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="max fractional drop vs the best prior round "
                        f"(default {DEFAULT_TOLERANCE})")
    p.add_argument("--check-schema", action="store_true",
                   help="only validate artifact schemas (exit 1 on any "
                        "unparseable BENCH_*.json; a committed "
                        "BASELINE_SERIES.json is validated too)")
    p.add_argument("--baseline-out", default=None, metavar="PATH",
                   help="export the normalized best-prior series as a "
                        "BASELINE_SERIES.json artifact (the single "
                        "source of truth obs/watchdog.py loads) and "
                        "exit")
    args = p.parse_args(argv)
    root = args.dir or os.path.join(os.path.dirname(
        os.path.abspath(__file__)), os.pardir)
    paths = discover(root)
    if not paths:
        print(json.dumps({"ok": False,
                          "error": f"no BENCH_*.json under {root}"}))
        return 1
    errors = check_schema(paths)
    baseline_file = os.path.join(root, BASELINE_FILENAME)
    # an invalid committed baseline must not block --baseline-out:
    # that flag is the only tool that can REGENERATE the file (a
    # schema bump would otherwise chicken-and-egg the operator into
    # hand-deleting the artifact)
    if os.path.exists(baseline_file) and not args.baseline_out:
        try:
            validate_baseline_file(baseline_file)
        except SchemaError as e:
            errors.append(str(e))
    if args.check_schema:
        print(json.dumps({"checked": len(paths)
                          + int(os.path.exists(baseline_file)),
                          "schema_errors": errors, "ok": not errors}))
        return 0 if not errors else 1
    if errors:
        print(json.dumps({"ok": False, "schema_errors": errors}))
        return 1
    records = [rec for p_ in paths for rec in normalize_all(p_)]
    if args.baseline_out:
        doc = baseline_series(records, tolerance=args.tolerance)
        with open(args.baseline_out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps({"baseline_out": args.baseline_out,
                          "series": len(doc["series"]),
                          "rounds": doc["rounds"], "ok": True}))
        return 0
    summary = gate(records, tolerance=args.tolerance)
    print(json.dumps(summary, sort_keys=True))
    for row in summary["regressions"]:
        bat = f", B={row['batch']}" if row.get("batch") else ""
        print(f"!!! regression: {row['metric']} "
              f"[{row['platform']}, n={row['n']}{bat}] "
              f"{row['best_prior']:.1f} -> {row['last']:.1f} "
              f"(-{row['drop_pct']}%, {row['last_source']})",
              file=sys.stderr)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
