#!/usr/bin/env python
"""A/B the potrf inner variants on-chip (round 5).

The round-4 iterative potrf (_potrf_iter) measured SLOWER on the chip
than the round-3 recursion it replaced (218 vs 141 ms/iter at n=16384,
nb=1024) despite doing strictly less redundant work on paper. This
script isolates the cause by timing, with bench.py's slope methodology:

  rec          the 2x2 recursion (_potrf_rec, the r3 default)
  iter         the r4 iterative loop (current default)
  iter_shrink  iterative, but carrying ONLY the shrinking trailing
               block (no full-matrix dynamic_update_slice per step;
               finished panel columns are assembled once at the end) —
               distinguishes "DUS full-array traffic" from "per-step
               kernel latency" as the regression cause
  iter_trsm    the r4 loop with the panel computed by trsm_rec instead
               of trtri_lower_batched + gemm — isolates the batched
               leaf-inverse kernel's cost

Usage: python tools/potrf_ab.py [n] [nb] [variants_csv]
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import apply_env_platforms  # noqa: E402

apply_env_platforms()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from bench import _per_iter_seconds  # noqa: E402


def _variants():
    from slate_tpu.linalg.cholesky import (_potrf_iter, _potrf_rec,
                                           _tile_chol)
    from slate_tpu.ops import blocked

    def iter_shrink(a, nb, prec):
        s = a.shape[0]
        nt = s // nb
        info = jnp.zeros((), jnp.int32)
        cols = []
        t = a
        for k in range(nt):
            lkk, tinfo = _tile_chol(t[:nb, :nb])
            info = jnp.where((info == 0) & (tinfo > 0), k * nb + tinfo,
                             info).astype(jnp.int32)
            if t.shape[0] == nb:
                cols.append(lkk)
                break
            inv = blocked.trtri_lower_batched(lkk)
            pan = blocked.mm(t[nb:, :nb], jnp.conj(inv).T, prec)
            cols.append(jnp.concatenate([lkk, pan], axis=0))
            t = blocked.herk_lower_rec(t[nb:, nb:], pan, prec=prec)
        padded = [jnp.pad(c, ((s - c.shape[0], 0), (0, 0)))
                  for c in cols]
        return jnp.concatenate(padded, axis=1), info

    def iter_trsm(a, nb, prec):
        s = a.shape[0]
        nt = s // nb
        info = jnp.zeros((), jnp.int32)
        for k in range(nt):
            k0, k1 = k * nb, (k + 1) * nb
            lkk, tinfo = _tile_chol(a[k0:k1, k0:k1])
            info = jnp.where((info == 0) & (tinfo > 0), k0 + tinfo,
                             info).astype(jnp.int32)
            a = jax.lax.dynamic_update_slice(a, lkk, (k0, k0))
            if k1 >= s:
                continue
            pan = blocked.trsm_rec(lkk, a[k1:, k0:k1], left=False,
                                   lower=True, conj_a=True, trans_a=True,
                                   prec=prec, base=nb)
            a = jax.lax.dynamic_update_slice(a, pan, (k1, k0))
            trail = blocked.herk_lower_rec(a[k1:, k1:], pan, prec=prec)
            a = jax.lax.dynamic_update_slice(a, trail, (k1, k1))
        return a, info

    return {"rec": _potrf_rec, "iter": _potrf_iter,
            "iter_shrink": iter_shrink, "iter_trsm": iter_trsm}


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    nb = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    names = sys.argv[3].split(",") if len(sys.argv) > 3 else None

    from slate_tpu.matgen import random_spd

    a0 = jnp.tril(random_spd(n, dtype=jnp.float32, seed=3))
    a0 = a0 + n * jnp.eye(n, dtype=jnp.float32)
    plat = jax.devices()[0].platform
    res = {"platform": plat, "n": n, "nb": nb}
    print(f"# platform={plat} n={n} nb={nb}", file=sys.stderr)

    variants = _variants()
    # correctness probe: every variant must factor a small problem to
    # the same residual as the first (run at a probe size so the check
    # is always on — a broken variant must not publish timings)
    np_ = min(n, 2048)
    nbp = min(nb, np_ // 2)
    ap = jnp.tril(random_spd(np_, dtype=jnp.float32, seed=5))
    ap = ap + np_ * jnp.eye(np_, dtype=jnp.float32)
    full = ap + jnp.tril(ap, -1).T
    # the reference residual is ALWAYS the rec variant's — a partial
    # variants_csv must not let a broken variant self-certify
    out, _ = jax.jit(
        lambda x: variants["rec"](x, nbp, "high"))(ap)
    lref = jnp.tril(out)
    ref = float(jnp.linalg.norm(lref @ lref.T - full))
    for name, fn in variants.items():
        if names and name not in names:
            continue
        out, _ = jax.jit(lambda x, f=fn: f(x, nbp, "high"))(ap)
        l = jnp.tril(out)
        r = float(jnp.linalg.norm(l @ l.T - full))
        print(f"# {name}: probe residual {r:.3e}", file=sys.stderr)
        if not (r <= 10 * ref + 1e-30):
            raise SystemExit(f"variant {name} FAILS the probe: "
                             f"residual {r:.3e} vs ref {ref:.3e}")
        def step(c, cs, f=fn):
            out, _ = f(c, nb, "high")
            return c + 1e-30 * out
        t = _per_iter_seconds(step, a0, (), k1=2, k2=6)
        gf = (n ** 3 / 3.0) / 1e9 / t
        res[f"{name}_ms"] = round(t * 1e3, 1)
        res[f"{name}_gflops"] = round(gf, 1)
        print(f"# {name:12s} {t*1e3:8.1f} ms  {gf:9.1f} GFLOP/s",
              file=sys.stderr)

    print(json.dumps(res))


if __name__ == "__main__":
    main()
