"""The serve-artifact section list — ONE source of truth.

Every top-level section the bench_serve artifact carries. The
committed BENCH_SERVE_smoke.json fixture must have ALL of them
(rounds 12 and 13 both tripped on stale fixtures when the schema grew
a section). bench_serve.bench() asserts this at write time;
tools/bench_gate.py --check-schema asserts it on the committed files;
--regen-smoke is the guarded regeneration path.

Rounds 12-21 kept two hand-synced copies (bench_serve.py + the
jax-free mirror in bench_gate.py) pinned equal by test; round 22
unifies them here. Stdlib-only — bench_gate must stay importable
without jax — and loaded by file path under ONE fixed module name
(``_load()`` in both consumers), so the legacy drift pin degenerates
to an import-identity check: both tools hold the SAME tuple object.
"""

SERVE_ARTIFACT_SECTIONS = (
    "bench", "backend", "dtype", "n", "nb", "requests", "max_batch",
    "serve", "per_request", "speedup", "cost_log", "hbm", "slo",
    "tenants", "numerics", "quotas", "spectral", "updates", "tuning",
    "incidents", "forecast")
