#!/usr/bin/env python
"""Offline config search → the committed tuning table (round 21).

Sweeps the per-op knob space the runtime actually consumes —
``nb`` / ``inner_blocking`` / ``lookahead`` / wide-panel width for the
dense one-shot drivers, ``nb`` and the batch/width bucket quantum for
the small batched engine — per (op, pow2-n-bucket, dtype, platform).
Every candidate is AOT-compiled ONCE (compiles are counted into the
artifact — the search's own cost is part of the record), then
slope-timed: seconds(k2 iters) − seconds(k1 iters) over (k2 − k1)
cancels the per-call dispatch constant, the same measurement
discipline bench.py --phases uses. The score joins the measured slope
against the roofline cost model (obs/costs.py ``score_measured`` →
model-flops GFLOP/s, intensity, roof fraction) and the argmax-GFLOP/s
candidate per (op, n-bucket, dtype) becomes one table entry.

The output document (default: the committed repo-root
``TUNING_r01.json``) carries the declared schema
``slate_tpu.tuning_table.v1``; ``tools/bench_gate.py --check-schema``
validates it with a jax-free mirror and ``slate_tpu/tuning/table.py``
loads it at serve time — one file, two readers, one schema.

Determinism: fixed ``--seed`` derives every operand; candidate order
is the declared grid order; ties break to the earlier candidate; the
document carries no timestamps — the same seed on the same platform
writes the same bytes (pinned in tests/test_tuning.py with an
injected measure function).

NEVER run from tier-1: the committed table is the fixture tests load;
regenerating it is a deliberate, platform-stamped act. A table
generated on a host CPU is honestly stamped ``"platform": "cpu"`` —
serving sessions on TPU will not resolve through it (first-match
requires the platform to match or be ``"*"``), which is exactly
right: CPU-smoke timings must never steer TPU configs.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import apply_env_platforms  # noqa: E402

apply_env_platforms()


def main(argv=None) -> int:
    from slate_tpu.tuning.search import (DEFAULT_OPS, run_search)
    from slate_tpu.tuning.table import (TUNING_FILENAME, table_path,
                                        validate_table)

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ops", nargs="+", default=list(DEFAULT_OPS),
                   help=f"ops to sweep (default: {' '.join(DEFAULT_OPS)})")
    p.add_argument("--n", type=int, nargs="+", default=[64],
                   dest="n_buckets", metavar="N",
                   help="pow2 n-bucket ceilings: each table entry "
                        "matches problems with n <= its bucket "
                        "(default: 64 — the tier-1-budget shape)")
    p.add_argument("--dtypes", nargs="+", default=["float32"])
    p.add_argument("--seed", type=int, default=0,
                   help="operand seed (the determinism pin)")
    p.add_argument("--quick", action="store_true",
                   help="reduced candidate grid (CPU-smoke scale)")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="output table path (default: the committed "
                        f"repo-root {TUNING_FILENAME})")
    args = p.parse_args(argv)

    out = table_path() if args.out is None else args.out

    def log(msg):
        print(f"# {msg}", file=sys.stderr)

    doc = run_search(ops=tuple(args.ops),
                     n_buckets=tuple(args.n_buckets),
                     dtypes=tuple(args.dtypes),
                     seed=args.seed, quick=args.quick, log=log)
    errs = validate_table(doc)
    if errs:  # a search emitting an invalid table is a search bug
        print(json.dumps({"ok": False, "schema_errors": errs}))
        return 1
    with open(out, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "out": out, "platform": doc["platform"],
        "entries": len(doc["entries"]),
        "total_compiles": doc["search"]["total_compiles"],
        "ok": True,
    }, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
