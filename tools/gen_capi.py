#!/usr/bin/env python3
"""Generate the full-precision C API surface (s/d/c/z) from one routine
table — the same trick the reference uses (tools/c_api/generate_*.py
emits include/slate/c_api/* and wrappers from the C++ headers).

Emits:
  native/capi_gen.c          — entry points for every (routine, dtype)
  include/slate_tpu_capi_gen.h — prototypes (included by slate_tpu_capi.h)
  fortran/slate_tpu.f90      — BIND(C) interface module, all precisions

Run from the repo root:  python tools/gen_capi.py
The generated files are committed (like the reference ships generated
headers) so users without the generator still build.

Argument spec mini-language per routine (expanded per dtype):
  i:<name>       int64 scalar
  s:<name>       const char* (single-letter LAPACK mode string)
  x:<name>       scalar of the matrix dtype (alpha/beta)
  A:<name>:<cnt> matrix buffer of the dtype, <cnt> elements (C expr)
  R:<name>:<cnt> real-typed buffer (w/sigma; float for s/c, double d/z)
  P:<name>:<cnt> int64 pivot buffer
"""

import os

ROUTINES = [
    # (base, heev_rename, [args])  — heev_rename: s/d use syev name
    ("gesv", None, ["i:n", "i:nrhs", "A:a:lda*n", "i:lda", "P:ipiv:n",
                    "A:b:ldb*nrhs", "i:ldb"]),
    ("potrf", None, ["s:uplo", "i:n", "A:a:lda*n", "i:lda"]),
    ("posv", None, ["s:uplo", "i:n", "i:nrhs", "A:a:lda*n", "i:lda",
                    "A:b:ldb*nrhs", "i:ldb"]),
    ("gels", None, ["i:m", "i:n", "i:nrhs", "A:a:lda*n", "i:lda",
                    "A:b:ldb*nrhs", "i:ldb"]),
    ("getrf", None, ["i:m", "i:n", "A:a:lda*n", "i:lda",
                     "P:ipiv:(m<n?m:n)"]),
    ("getrs", None, ["s:trans", "i:n", "i:nrhs", "A:a:lda*n", "i:lda",
                     "P:ipiv:n", "A:b:ldb*nrhs", "i:ldb"]),
    ("getri", None, ["i:n", "A:a:lda*n", "i:lda", "P:ipiv:n"]),
    ("potrs", None, ["s:uplo", "i:n", "i:nrhs", "A:a:lda*n", "i:lda",
                     "A:b:ldb*nrhs", "i:ldb"]),
    ("heev", {"s": "ssyev", "d": "dsyev", "c": "cheev", "z": "zheev"},
     ["s:jobz", "s:uplo", "i:n", "A:a:lda*n", "i:lda", "R:w:n"]),
    ("gesvd", None, ["s:jobu", "s:jobvt", "i:m", "i:n", "A:a:lda*n",
                     "i:lda", "R:s:(m<n?m:n)", "A:u:ldu*(m<n?m:n)",
                     "i:ldu", "A:vt:ldvt*n", "i:ldvt"]),
    ("gemm", None, ["s:transa", "s:transb", "i:m", "i:n", "i:k", "x:alpha",
                    "A:a:lda*((transa[0]=='n'||transa[0]=='N')?k:m)",
                    "i:lda",
                    "A:b:ldb*((transb[0]=='n'||transb[0]=='N')?n:k)",
                    "i:ldb", "x:beta", "A:c:ldc*n", "i:ldc"]),
    ("trsm", None, ["s:side", "s:uplo", "s:transa", "s:diag", "i:m", "i:n",
                    "x:alpha",
                    "A:a:lda*((side[0]=='l'||side[0]=='L')?m:n)", "i:lda",
                    "A:b:ldb*n", "i:ldb"]),
    ("trmm", None, ["s:side", "s:uplo", "s:transa", "s:diag", "i:m", "i:n",
                    "x:alpha",
                    "A:a:lda*((side[0]=='l'||side[0]=='L')?m:n)", "i:lda",
                    "A:b:ldb*n", "i:ldb"]),
    ("lange", None, ["s:norm", "i:m", "i:n", "A:a:lda*n", "i:lda"]),
    # --- round 5 additions (VERDICT r4 missing #2): toward the
    # reference's full generated surface (src/c_api/wrappers.cc) ----------
    ("potri", None, ["s:uplo", "i:n", "A:a:lda*n", "i:lda"]),
    ("geqrf", None, ["i:m", "i:n", "A:a:lda*n", "i:lda",
                     "A:tau:(m<n?m:n)"]),
    ("gelqf", None, ["i:m", "i:n", "A:a:lda*n", "i:lda",
                     "A:tau:(m<n?m:n)"]),
    ("unmqr", {"s": "sormqr", "d": "dormqr", "c": "cunmqr", "z": "zunmqr"},
     ["s:side", "s:trans", "i:m", "i:n", "i:k",
      "A:a:lda*k", "i:lda", "A:tau:k", "A:c:ldc*n", "i:ldc"]),
    ("unmlq", {"s": "sormlq", "d": "dormlq", "c": "cunmlq", "z": "zunmlq"},
     ["s:side", "s:trans", "i:m", "i:n", "i:k",
      "A:a:lda*((side[0]=='l'||side[0]=='L')?m:n)", "i:lda", "A:tau:k",
      "A:c:ldc*n", "i:ldc"]),
    ("heevd", {"s": "ssyevd", "d": "dsyevd", "c": "cheevd", "z": "zheevd"},
     ["s:jobz", "s:uplo", "i:n", "A:a:lda*n", "i:lda", "R:w:n"]),
    ("symm", None, ["s:side", "s:uplo", "i:m", "i:n", "x:alpha",
                    "A:a:lda*((side[0]=='l'||side[0]=='L')?m:n)", "i:lda",
                    "A:b:ldb*n", "i:ldb", "x:beta", "A:c:ldc*n", "i:ldc"]),
    ("hemm", None, ["s:side", "s:uplo", "i:m", "i:n", "x:alpha",
                    "A:a:lda*((side[0]=='l'||side[0]=='L')?m:n)", "i:lda",
                    "A:b:ldb*n", "i:ldb", "x:beta", "A:c:ldc*n", "i:ldc"],
     "cz"),
    ("syrk", None, ["s:uplo", "s:trans", "i:n", "i:k", "x:alpha",
                    "A:a:lda*((trans[0]=='n'||trans[0]=='N')?k:n)", "i:lda",
                    "x:beta", "A:c:ldc*n", "i:ldc"]),
    ("herk", None, ["s:uplo", "s:trans", "i:n", "i:k", "r:alpha",
                    "A:a:lda*((trans[0]=='n'||trans[0]=='N')?k:n)", "i:lda",
                    "r:beta", "A:c:ldc*n", "i:ldc"], "cz"),
    ("syr2k", None, ["s:uplo", "s:trans", "i:n", "i:k", "x:alpha",
                     "A:a:lda*((trans[0]=='n'||trans[0]=='N')?k:n)",
                     "i:lda",
                     "A:b:ldb*((trans[0]=='n'||trans[0]=='N')?k:n)",
                     "i:ldb", "x:beta", "A:c:ldc*n", "i:ldc"]),
    ("her2k", None, ["s:uplo", "s:trans", "i:n", "i:k", "x:alpha",
                     "A:a:lda*((trans[0]=='n'||trans[0]=='N')?k:n)",
                     "i:lda",
                     "A:b:ldb*((trans[0]=='n'||trans[0]=='N')?k:n)",
                     "i:ldb", "r:beta", "A:c:ldc*n", "i:ldc"], "cz"),
    ("lanhe", {"s": "slansy", "d": "dlansy", "c": "clanhe", "z": "zlanhe"},
     ["s:norm", "s:uplo", "i:n", "A:a:lda*n", "i:lda"]),
    ("lantr", None, ["s:norm", "s:uplo", "s:diag", "i:m", "i:n",
                     "A:a:lda*n", "i:lda"]),
    ("gecon", None, ["s:norm", "i:n", "A:a:lda*n", "i:lda", "r:anorm",
                     "R:rcond:1"]),
    ("pocon", None, ["s:uplo", "i:n", "A:a:lda*n", "i:lda", "r:anorm",
                     "R:rcond:1"]),
    ("trcon", None, ["s:norm", "s:uplo", "s:diag", "i:n", "A:a:lda*n",
                     "i:lda", "R:rcond:1"]),
    ("hesv", {"s": "ssysv", "d": "dsysv", "c": "chesv", "z": "zhesv"},
     ["s:uplo", "i:n", "i:nrhs", "A:a:lda*n", "i:lda", "P:ipiv:n",
      "A:b:ldb*nrhs", "i:ldb"]),
    ("hetrf", {"s": "ssytrf", "d": "dsytrf", "c": "chetrf", "z": "zhetrf"},
     ["s:uplo", "i:n", "A:a:lda*n", "i:lda", "P:ipiv:n"]),
    ("hetrs", {"s": "ssytrs", "d": "dsytrs", "c": "chetrs", "z": "zhetrs"},
     ["s:uplo", "i:n", "i:nrhs", "A:a:lda*n", "i:lda", "P:ipiv:n",
      "A:b:ldb*nrhs", "i:ldb"]),
    ("pbsv", None, ["s:uplo", "i:n", "i:kd", "i:nrhs", "A:ab:ldab*n",
                    "i:ldab", "A:b:ldb*nrhs", "i:ldb"]),
    ("gbsv", None, ["i:n", "i:kl", "i:ku", "i:nrhs", "A:ab:ldab*n",
                    "i:ldab", "P:ipiv:n", "A:b:ldb*nrhs", "i:ldb"]),
    # slate_triangular_inverse / slate_generalized_hermitian_eig /
    # slate_lu_solve_nopiv analogs (reference src/c_api/wrappers.cc)
    ("trtri", None, ["s:uplo", "s:diag", "i:n", "A:a:lda*n", "i:lda"]),
    ("hegv", {"s": "ssygv", "d": "dsygv", "c": "chegv", "z": "zhegv"},
     ["i:itype", "s:jobz", "s:uplo", "i:n", "A:a:lda*n", "i:lda",
      "A:b:ldb*n", "i:ldb", "R:w:n"]),
    ("gesv_nopiv", None, ["i:n", "i:nrhs", "A:a:lda*n", "i:lda",
                          "A:b:ldb*nrhs", "i:ldb"]),
    # --- opaque matrix handles (reference: include/slate/c_api/matrix.h
    # slate_Matrix_create_* + src/c_api/wrappers.cc): keep a
    # device-resident matrix across C calls, no per-call re-packing -------
    ("matrix_create", {dt: f"matrix_create_{dt}" for dt in "sdcz"},
     ["i:m", "i:n", "i:nb"]),
    ("matrix_from_buffer",
     {dt: f"matrix_from_buffer_{dt}" for dt in "sdcz"},
     ["i:m", "i:n", "A:a:lda*n", "i:lda", "i:nb"]),
    ("matrix_to_buffer", {dt: f"matrix_to_buffer_{dt}" for dt in "sdcz"},
     ["i:h", "i:m", "i:n", "A:a:lda*n", "i:lda"]),
    ("matrix_destroy", {"d": "matrix_destroy"}, ["i:h"], "d"),
    ("hgemm", {dt: f"hgemm_{dt}" for dt in "sdcz"},
     ["s:transa", "s:transb", "x:alpha", "i:ha", "i:hb", "x:beta",
      "i:hc"]),
    ("hposv", {dt: f"hposv_{dt}" for dt in "sdcz"},
     ["s:uplo", "i:ha", "i:hb"]),
    ("hpotrf", {dt: f"hpotrf_{dt}" for dt in "sdcz"},
     ["s:uplo", "i:h"]),
    ("hgesv", {dt: f"hgesv_{dt}" for dt in "sdcz"}, ["i:ha", "i:hb"]),
    ("htrsm", {dt: f"htrsm_{dt}" for dt in "sdcz"},
     ["s:side", "s:uplo", "s:transa", "s:diag", "x:alpha", "i:ha",
      "i:hb"]),
    ("hnorm", {dt: f"hnorm_{dt}" for dt in "sdcz"},
     ["s:norm", "i:h", "R:out:1"]),
]

# routines whose return value is the computed norm (double), delivered
# through an appended out-buffer; everything else returns info/handle
NORM_BASES = {"lange", "lanhe", "lantr"}

CTYPE = {"s": "float", "d": "double",
         "c": "float _Complex", "z": "double _Complex"}
RTYPE = {"s": "float", "d": "double", "c": "float", "z": "double"}
ESIZE = {"s": 4, "d": 8, "c": 8, "z": 16}
RSIZE = {"s": 4, "d": 8, "c": 4, "z": 8}
FTYPE = {"s": "real(c_float)", "d": "real(c_double)",
         "c": "complex(c_float_complex)", "z": "complex(c_double_complex)"}
FRTYPE = {"s": "real(c_float)", "d": "real(c_double)",
          "c": "real(c_float)", "z": "real(c_double)"}


def _parse(a):
    parts = a.split(":", 2)
    return (parts[0], parts[1], parts[2] if len(parts) > 2 else None)


def c_sig(base, dt, args):
    ps = []
    for kind, name, _cnt in map(_parse, args):
        if kind == "i":
            ps.append(f"int64_t {name}")
        elif kind == "s":
            ps.append(f"const char* {name}")
        elif kind == "x":
            ps.append(f"{CTYPE[dt]} {name}")
        elif kind == "r":
            ps.append(f"{RTYPE[dt]} {name}")
        elif kind == "A":
            ps.append(f"{CTYPE[dt]}* {name}")
        elif kind == "R":
            ps.append(f"{RTYPE[dt]}* {name}")
        elif kind == "P":
            ps.append(f"int64_t* {name}")
    ret = "double" if base in NORM_BASES else "int64_t"
    return f"{ret} slate_tpu_{dt}{base}({', '.join(ps)})"


def c_body(base, dt, args, glue):
    lines = []
    lines.append("    if (ensure_python()) return -100;")
    lines.append("    PyGILState_STATE g = PyGILState_Ensure();")
    views = []
    prev = None
    for kind, name, cnt in map(_parse, args):
        if kind in ("A", "R", "P"):
            es = {"A": ESIZE[dt], "R": RSIZE[dt], "P": 8}[kind]
            guard = f"{prev} ? " if prev else ""
            alt = " : NULL" if prev else ""
            lines.append(
                f"    PyObject* mv_{name} = {guard}stc_mv({name}, "
                f"({cnt}) * (int64_t){es}){alt};")
            views.append(f"mv_{name}")
            prev = f"mv_{name}"
    # build format string + value list; first arg is the dtype letter
    fmt = ["s"]
    vals = [f'"{dt}"']
    for kind, name, _cnt in map(_parse, args):
        if kind == "i":
            fmt.append("L")
            vals.append(f"(long long){name}")
        elif kind == "s":
            # bound the read to ONE char: Fortran character literals are
            # not NUL-terminated, and every mode string is single-letter
            lines.append(f"    char c1_{name}[2] = "
                         f"{{ {name} ? {name}[0] : 0, 0 }};")
            fmt.append("s")
            vals.append(f"c1_{name}")
        elif kind == "r":
            fmt.append("d")
            vals.append(f"(double){name}")
        elif kind == "x":
            if dt in "cz":
                fmt.append("D")
                lines.append(
                    f"    Py_complex pc_{name} = "
                    f"{{ creal({name}), cimag({name}) }};")
                vals.append(f"&pc_{name}")
            else:
                fmt.append("d")
                vals.append(f"(double){name}")
        else:
            fmt.append("O")
            vals.append(f"mv_{name}")
    cond = " && ".join(views) if views else "1"
    lines.append(f"    PyObject* args = ({cond})")
    lines.append(f"        ? Py_BuildValue(\"({''.join(fmt)})\", "
                 f"{', '.join(vals)})")
    lines.append("        : NULL;")
    drops = ", ".join(views + ["NULL"] * (4 - len(views)))
    if base in NORM_BASES:
        # norm routines return the value through a 1-element out buffer
        # appended to the args tuple
        lines.insert(2, "    double out = -1.0;")
        lines.append("    PyObject* mv_out = stc_mv(&out, 8);")
        lines.append("    PyObject* args2 = NULL;")
        lines.append("    if (args && mv_out) {")
        lines.append("        PyObject* tail = Py_BuildValue(\"(O)\", "
                     "mv_out);")
        lines.append("        if (tail) {")
        lines.append("            args2 = PySequence_Concat(args, tail);")
        lines.append("            Py_DECREF(tail);")
        lines.append("        }")
        lines.append("    }")
        lines.append("    Py_XDECREF(args);")
        drops = ", ".join(views + ["mv_out"]
                          + ["NULL"] * (3 - len(views)))
        lines.append(f"    int64_t rc = stc_run(\"{glue}\", "
                     f"stc_finish(g, args2, {drops}));")
        lines.append("    return rc == 0 ? out : -1.0;")
    else:
        lines.append(f"    return stc_run(\"{glue}\", "
                     f"stc_finish(g, args, {drops}));")
    return "\n".join(lines)


def fortran_iface(base, dt, args):
    name = f"slate_tpu_{dt}{base}"
    fargs = []
    decls = []
    for kind, aname, _cnt in map(_parse, args):
        fargs.append(aname)
        if kind == "i":
            decls.append(f"         integer(c_int64_t), value :: {aname}")
        elif kind == "s":
            decls.append(f"         character(kind=c_char), dimension(*)"
                         f" :: {aname}")
        elif kind == "x":
            decls.append(f"         {FTYPE[dt]}, value :: {aname}")
        elif kind == "r":
            decls.append(f"         {FRTYPE[dt]}, value :: {aname}")
        elif kind == "A":
            decls.append(f"         {FTYPE[dt]}, dimension(*) :: {aname}")
        elif kind == "R":
            decls.append(f"         {FRTYPE[dt]}, dimension(*) :: {aname}")
        elif kind == "P":
            decls.append(f"         integer(c_int64_t), dimension(*)"
                         f" :: {aname}")
    ret = ("real(c_double)" if base in NORM_BASES
           else "integer(c_int64_t)")
    arglist = ", ".join(fargs)
    head = f"      function {name}({arglist}) &"
    lines = [head,
             f"            bind(c, name=\"{name}\") result(r)",
             "         import :: c_int64_t, c_double, c_float, c_char, &",
             "            c_float_complex, c_double_complex"]
    lines += decls
    lines.append(f"         {ret} :: r")
    lines.append(f"      end function {name}")
    return "\n".join(lines)


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cs = ['/* GENERATED by tools/gen_capi.py — do not edit.',
          ' *',
          ' * Full-precision (s/d/c/z) routine-level C API; dispatches',
          ' * into slate_tpu.compat.c_glue through the shared embedding',
          ' * helpers in capi.c. Reference analog: the generated',
          ' * src/c_api/wrappers.cc surface. */',
          '#define PY_SSIZE_T_CLEAN',
          '#include <Python.h>',
          '#include <stdint.h>',
          '#include <complex.h>',
          '#include "capi_common.h"',
          '']
    hs = ['/* GENERATED by tools/gen_capi.py — do not edit. */',
          '#ifndef SLATE_TPU_CAPI_GEN_H',
          '#define SLATE_TPU_CAPI_GEN_H',
          '#include <stdint.h>',
          '#include <complex.h>',
          '#ifdef __cplusplus',
          'extern "C" {',
          '#endif',
          '']
    fs = ['! GENERATED by tools/gen_capi.py — do not edit.',
          '! Fortran 2003 BIND(C) interfaces for the slate-tpu C API,',
          '! all four precisions (reference analog: the generated',
          '! Fortran module, tools/fortran/).',
          'module slate_tpu',
          '   use iso_c_binding, only: c_int64_t, c_double, c_float, &',
          '      c_char, c_float_complex, c_double_complex',
          '   implicit none',
          '   interface',
          '']
    for entry in ROUTINES:
        base, rename, args = entry[:3]
        dts = entry[3] if len(entry) > 3 else "sdcz"
        for dt in dts:
            sym = (rename[dt] if rename else dt + base)
            sig = c_sig(base, dt, args).replace(
                f"slate_tpu_{dt}{base}", f"slate_tpu_{sym}")
            glue = "c_" + base
            body = c_body(base, dt, args, glue)
            cs.append(sig + " {")
            cs.append(body)
            cs.append("}")
            cs.append("")
            hs.append(sig + ";")
            fi = fortran_iface(base, dt, args).replace(
                f"slate_tpu_{dt}{base}", f"slate_tpu_{sym}")
            fs.append(fi)
            fs.append("")
    hs += ["", "#ifdef __cplusplus", "}", "#endif", "#endif"]
    fs += ["   end interface", "end module slate_tpu"]
    with open(os.path.join(root, "native", "capi_gen.c"), "w") as f:
        f.write("\n".join(cs))
    with open(os.path.join(root, "include", "slate_tpu_capi_gen.h"),
              "w") as f:
        f.write("\n".join(hs))
    with open(os.path.join(root, "fortran", "slate_tpu.f90"), "w") as f:
        f.write("\n".join(fs))
    nsym = sum(len(e[3]) if len(e) > 3 else 4 for e in ROUTINES)
    print(f"generated {nsym} C symbols + Fortran interfaces")


if __name__ == "__main__":
    main()
