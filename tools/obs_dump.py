#!/usr/bin/env python
"""Observability smoke: serve a small workload, dump every export.

Drives the full obs surface end to end — tracing ON through
Session/Batcher/Executor, the legacy SVG timeline, the Prometheus
text exposition, and the HTTP endpoint — then writes the artifacts:

  <out-dir>/trace.json    Chrome-trace/Perfetto JSON of the span tree
  <out-dir>/metrics.prom  Prometheus text (same bytes as GET /metrics)
  <out-dir>/metrics.json  Metrics snapshot JSON
  <out-dir>/costs.json    per-shape cost rows (model flops, XLA
                          bytes-accessed, temp/peak HBM, collective
                          census) + bytes ledger + roofline join
  <out-dir>/trace.svg     legacy SVG timeline (utils.trace)

Exit status is nonzero if the Chrome JSON fails schema validation
(obs.validate_chrome_trace: required keys, monotone ts, span nesting),
if the span tree is disconnected, if the HTTP endpoint serves the
wrong payloads, or if the round-9 cost exports are missing/incomplete
(empty cost_log, absent Prometheus bytes/HBM sections, or a mesh run
that credited zero collective bytes) — wired into examples/run_tests.py
as the obs smoke.

Usage: python tools/obs_dump.py [--smoke] [--out-dir DIR]
                                [--n N] [--nb NB] [--requests R]
"""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import (  # noqa: E402
    apply_env_platforms, collective_timeout_flag_if_supported)

apply_env_platforms()

# On the CPU backend, run the smoke on an 8-way virtual-device mesh so
# the MESH-driver cost telemetry (parallel.summa collective bytes —
# round 9 acceptance) is exercised; must land in XLA_FLAGS before jax
# initializes. The rendezvous-timeout raise is probe-gated exactly like
# tests/conftest.py (unknown XLA_FLAGS abort some jaxlib builds).
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        _flags = (_flags + " --xla_force_host_platform_device_count=8"
                  ).strip()
        _flags += collective_timeout_flag_if_supported(
            cache_path=os.path.join(os.path.dirname(__file__), os.pardir,
                                    ".xla_flag_probe.json"))
        os.environ["XLA_FLAGS"] = _flags

import numpy as np  # noqa: E402


def run(out_dir, n=96, nb=32, requests=12, slow_threshold=None):
    import jax

    import slate_tpu as st
    from slate_tpu import obs
    from slate_tpu.runtime import Executor, Session
    from slate_tpu.utils import trace as legacy_trace

    os.makedirs(out_dir, exist_ok=True)
    fails = []

    tracer = obs.Tracer(slow_threshold=slow_threshold)
    tracer.on()
    legacy_trace.Trace.clear()
    legacy_trace.Trace.on()

    rng = np.random.default_rng(5)
    spd = rng.standard_normal((n, n))
    spd = spd @ spd.T + n * np.eye(n)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)

    sess = Session(tracer=tracer)
    h = sess.register(A, op="chol")
    srv = sess.serve_obs()  # opt-in HTTP endpoint, ephemeral port
    try:
        bs = [rng.standard_normal(n) for _ in range(requests)]
        with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
            ex.warmup([h])
            futs = [ex.submit(h, b) for b in bs]
            xs = [f.result(timeout=120) for f in futs]
        resid = max(float(np.abs(spd @ x - b).max()) / n
                    for x, b in zip(xs, bs))
        if not resid < 1e-2:
            fails.append(f"serving residual too large: {resid}")

        # -- mesh-driver cost telemetry (round 9) ---------------------
        # one explicitly-scheduled SUMMA gemm over a 2x2 grid: its
        # compiled program's collective census must land in the bytes
        # ledger (the acceptance's "collective bytes for at least one
        # mesh driver"). Skipped (honestly) below 4 devices.
        mesh_ran = False
        if len(jax.devices()) >= 4:
            from slate_tpu.core.grid import ProcessGrid
            from slate_tpu.parallel.summa import gemm_summa

            g = ProcessGrid.create(2, 2)
            Ag = st.from_dense(rng.standard_normal((n, n)), nb=nb, grid=g)
            Bg = st.from_dense(rng.standard_normal((n, n)), nb=nb, grid=g)
            Cg = st.zeros(n, n, nb, Ag.dtype, grid=g)
            out = gemm_summa(1.0, Ag, Bg, 0.0, Cg)
            gres = float(np.abs(out.to_numpy()
                                - Ag.to_numpy() @ Bg.to_numpy()).max()) / n
            if not gres < 1e-2:
                fails.append(f"summa residual too large: {gres}")
            mesh_ran = True

        # -- exports --------------------------------------------------
        spans = tracer.spans()
        trace_path = os.path.join(out_dir, "trace.json")
        obs.write_chrome_trace(spans, trace_path)
        with open(trace_path) as f:
            errs = obs.validate_chrome_trace(json.load(f))
        if errs:
            fails.append(f"chrome-trace schema: {errs[:3]}")

        # connectedness: every parent_id resolves to a recorded span
        ids = {s.span_id for s in spans}
        dangling = [s for s in spans
                    if s.parent_id is not None and s.parent_id not in ids]
        if dangling:
            fails.append(f"span tree disconnected: {len(dangling)} orphans")
        if not any(s.name == "serve.batch" for s in spans):
            fails.append("no serve.batch span recorded")
        if not any(s.kind == "request" for s in spans):
            fails.append("no request spans recorded")

        sess.metrics.to_json(os.path.join(out_dir, "metrics.json"))
        prom = obs.render_prometheus(sess.metrics)
        with open(os.path.join(out_dir, "metrics.prom"), "w") as f:
            f.write(prom)
        if "slate_tpu_solves_total" not in prom:
            fails.append("prometheus text missing solves_total")
        # round-9 sections: bytes/collective ledgers + HBM gauges
        for needle in ("slate_tpu_driver_bytes_total",
                       "slate_tpu_collective_bytes_total",
                       "slate_tpu_peak_hbm_bytes",
                       "slate_tpu_resident_bytes"):
            if needle not in prom:
                fails.append(f"prometheus text missing {needle}")

        # -- cost exports (round 9): per-shape rows + ledgers ---------
        bytes_snap = obs.costs.BYTES.snapshot()
        costs_doc = {
            "cost_log": sess.cost_log,
            "bytes_ledger": bytes_snap,
            "roofline": obs.roofline.roofline_report(),
        }
        with open(os.path.join(out_dir, "costs.json"), "w") as f:
            json.dump(costs_doc, f, indent=2, sort_keys=True)
            f.write("\n")
        # schema: every AOT-compiled shape exports the full cost row
        if not sess.cost_log:
            fails.append("cost_log empty: AOT seam harvested nothing")
        for row in sess.cost_log:
            for k in ("op", "what", "shape", "model_flops",
                      "bytes_accessed", "temp_bytes", "peak_bytes",
                      "collective_bytes"):
                if k not in row:
                    fails.append(f"cost_log row missing {k!r}")
                    break
        if mesh_ran:
            summa_ops = [op for op in bytes_snap["per_op"]
                         if op.startswith("parallel.summa")]
            if not summa_ops:
                fails.append("mesh driver credited no bytes-ledger op")
            elif not any(bytes_snap["per_op"][op]["collective_bytes"] > 0
                         for op in summa_ops):
                fails.append("mesh driver recorded zero collective bytes")

        svg = legacy_trace.Trace.finish(os.path.join(out_dir, "trace.svg"))
        if svg is None:
            fails.append("SVG timeline empty (span bridge broken)")

        # -- HTTP endpoint --------------------------------------------
        for path, needle in (("/metrics", "slate_tpu_solves_total"),
                             ("/healthz", '"status": "ok"'),
                             ("/trace.json", "traceEvents")):
            body = urllib.request.urlopen(srv.url(path),
                                          timeout=10).read().decode()
            if needle not in body:
                fails.append(f"GET {path}: missing {needle!r}")
    finally:
        sess.close_obs()
        tracer.off()
        legacy_trace.Trace.off()

    summary = {
        "out_dir": out_dir,
        "spans": len(tracer.spans()),
        "requests": requests,
        "schema_errors": 0 if not fails else fails,
        "ok": not fails,
    }
    print(json.dumps(summary))
    for msg in fails:
        print(f"!!! {msg}", file=sys.stderr)
    return 0 if not fails else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="tiny CPU run into a temp dir (CI wiring)")
    p.add_argument("--out-dir", default="obs_dump")
    p.add_argument("--n", type=int, default=96)
    p.add_argument("--nb", type=int, default=32)
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--slow-ms", type=float, default=None,
                   help="slow-request log threshold (milliseconds)")
    args = p.parse_args(argv)
    out_dir = args.out_dir
    if args.smoke and out_dir == "obs_dump":
        out_dir = tempfile.mkdtemp(prefix="slate_tpu_obs_")
    thr = args.slow_ms * 1e-3 if args.slow_ms is not None else None
    return run(out_dir, n=args.n, nb=args.nb, requests=args.requests,
               slow_threshold=thr)


if __name__ == "__main__":
    sys.exit(main())
