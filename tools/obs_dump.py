#!/usr/bin/env python
"""Observability smoke: serve a small workload, dump every export.

Drives the full obs surface end to end — tracing ON through
Session/Batcher/Executor, the legacy SVG timeline, the Prometheus
text exposition, and the HTTP endpoint — then writes the artifacts:

  <out-dir>/trace.json    Chrome-trace/Perfetto JSON of the span tree
  <out-dir>/metrics.prom  Prometheus text (same bytes as GET /metrics)
  <out-dir>/metrics.json  Metrics snapshot JSON
  <out-dir>/costs.json    per-shape cost rows (model flops, XLA
                          bytes-accessed, temp/peak HBM, collective
                          census) + bytes ledger + roofline join
  <out-dir>/trace.svg     legacy SVG timeline (utils.trace)
  <out-dir>/slo.json      /slo burn-rate payload (round 12)
  <out-dir>/watchdog.json live-vs-baseline reports: the real committed
                          history (must be quiet) AND an injected-
                          latency fixture (must flag)
  <out-dir>/fleet.json    2-process aggregation of the run's snapshot
  <out-dir>/fleet.prom    fleet-level Prometheus text (host labels)
  <out-dir>/fleet_trace.json  2-process combined Chrome trace
  <out-dir>/history.json  /history telemetry time-series payload
  <out-dir>/forecast.json /forecast load/heat forecast payload

Exit status is nonzero if the Chrome JSON fails schema validation
(obs.validate_chrome_trace: required keys, monotone ts, span nesting),
if the span tree is disconnected, if the HTTP endpoint serves the
wrong payloads, if the round-9 cost exports are missing/incomplete
(empty cost_log, absent Prometheus bytes/HBM sections, or a mesh run
that credited zero collective bytes), if any round-23 section fails
(the /history payload rejecting its own validator, a served run whose
store recorded no series, forecast output that fails schema, counter
conservation through the store broken, or a 2-process history fold
whose counter totals are not exactly double), or if any round-12
section
fails: /slo payload without computed burn rates, lifecycle-stage
histograms or backpressure gauges missing, the watchdog flagging the
real committed history (or NOT flagging the injected regression),
``padding_waste_flops`` zero on a deliberately under-occupied bucket
or nonzero at full occupancy, any round-13 mixed-refinement assert
(``refine_iterations``/``refine_converged_total``/
``refine_fallbacks_total`` rows absent or zero where a served mixed
workload must move them, the ledger missing the ``serve.refine``
useful-vs-refinement split, or a forced non-convergent solve that
fails to fall back to a correct working-precision answer), or a
2-process aggregation whose counters are not bit-exactly double the
single-process snapshot — wired into examples/run_tests.py as the obs
smoke.

Usage: python tools/obs_dump.py [--smoke] [--out-dir DIR]
                                [--n N] [--nb NB] [--requests R]
"""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import (  # noqa: E402
    apply_env_platforms, collective_timeout_flag_if_supported)

apply_env_platforms()

# On the CPU backend, run the smoke on an 8-way virtual-device mesh so
# the MESH-driver cost telemetry (parallel.summa collective bytes —
# round 9 acceptance) is exercised; must land in XLA_FLAGS before jax
# initializes. The rendezvous-timeout raise is probe-gated exactly like
# tests/conftest.py (unknown XLA_FLAGS abort some jaxlib builds).
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        _flags = (_flags + " --xla_force_host_platform_device_count=8"
                  ).strip()
        _flags += collective_timeout_flag_if_supported(
            cache_path=os.path.join(os.path.dirname(__file__), os.pardir,
                                    ".xla_flag_probe.json"))
        os.environ["XLA_FLAGS"] = _flags

import numpy as np  # noqa: E402


def run(out_dir, n=96, nb=32, requests=12, slow_threshold=None):
    import jax

    import slate_tpu as st
    from slate_tpu import obs
    from slate_tpu.runtime import Executor, Session
    from slate_tpu.utils import trace as legacy_trace

    os.makedirs(out_dir, exist_ok=True)
    fails = []

    tracer = obs.Tracer(slow_threshold=slow_threshold)
    tracer.on()
    legacy_trace.Trace.clear()
    legacy_trace.Trace.on()

    rng = np.random.default_rng(5)
    spd = rng.standard_normal((n, n))
    spd = spd @ spd.T + n * np.eye(n)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)

    # round 18: a declared tenant table through the smoke — the quota
    # gauges, the /tenants "quotas" section, and the fair-share
    # deficit gauges below are exit-gated
    from slate_tpu.runtime import TenantPolicy
    sess = Session(tracer=tracer, tenant_policies={
        "tenant-a": TenantPolicy(weight=2.0),
        "tenant-b": TenantPolicy(weight=1.0,
                                 max_resident_bytes=64 << 20)})
    # round 12: SLO tracking on — default objectives PLUS the round-16
    # residual objective, so the sampled probes below feed a
    # residual-kind burn rate the /slo payload must evaluate
    from slate_tpu.obs.slo import Objective, default_objectives
    sess.enable_slo(default_objectives() + (
        Objective("sampled_residual", "residual", 0.99,
                  threshold_s=1e-2),))
    # round 16: numerical-health telemetry with a probe-every-solve
    # sampler (deterministic) — the handle_health gauges, /numerics
    # payload, and probe counters below are exit-gated
    sess.enable_numerics(sample_fraction=1.0, sample_seed=12)
    # round 15: tenant attribution on BEFORE any traffic (the
    # conservation check below compares per-tenant sums against the
    # session-lifetime global counters, so every credited event must
    # be attributed)
    sess.enable_attribution()
    # round 22: flight recorder + decision journal + incident capture
    # on from the FIRST request (journal/counter parity below is
    # absolute equality, so the recorder must predate any reflex)
    sess.enable_recorder(incident_dir=os.path.join(out_dir,
                                                   "incidents"))
    # round 23: telemetry history on before any traffic (interval 0 so
    # every explicit pump records — the smoke is pump-driven, not
    # wall-clock-throttled); the /history, /forecast, and 2-process
    # fold sections below are exit-gated
    store = sess.enable_timeseries(interval_s=0.0)
    h = sess.register(A, op="chol", tenant="tenant-a")
    srv = sess.serve_obs()  # opt-in HTTP endpoint, ephemeral port
    try:
        bs = [rng.standard_normal(n) for _ in range(requests)]
        with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
            ex.warmup([h])
            futs = [ex.submit(h, b) for b in bs]
            xs = []
            for f in futs:
                xs.append(f.result(timeout=120))
                sess.pump_timeseries()  # per-result history samples
        resid = max(float(np.abs(spd @ x - b).max()) / n
                    for x, b in zip(xs, bs))
        if not resid < 1e-2:
            fails.append(f"serving residual too large: {resid}")

        # -- mesh-driver cost telemetry (round 9) ---------------------
        # one explicitly-scheduled SUMMA gemm over a 2x2 grid: its
        # compiled program's collective census must land in the bytes
        # ledger (the acceptance's "collective bytes for at least one
        # mesh driver"). Skipped (honestly) below 4 devices.
        mesh_ran = False
        if len(jax.devices()) >= 4:
            from slate_tpu.core.grid import ProcessGrid
            from slate_tpu.parallel.summa import gemm_summa

            g = ProcessGrid.create(2, 2)
            Ag = st.from_dense(rng.standard_normal((n, n)), nb=nb, grid=g)
            Bg = st.from_dense(rng.standard_normal((n, n)), nb=nb, grid=g)
            Cg = st.zeros(n, n, nb, Ag.dtype, grid=g)
            out = gemm_summa(1.0, Ag, Bg, 0.0, Cg)
            gres = float(np.abs(out.to_numpy()
                                - Ag.to_numpy() @ Bg.to_numpy()).max()) / n
            if not gres < 1e-2:
                fails.append(f"summa residual too large: {gres}")
            mesh_ran = True

        # -- exports --------------------------------------------------
        spans = tracer.spans()
        trace_path = os.path.join(out_dir, "trace.json")
        obs.write_chrome_trace(spans, trace_path)
        with open(trace_path) as f:
            errs = obs.validate_chrome_trace(json.load(f))
        if errs:
            fails.append(f"chrome-trace schema: {errs[:3]}")

        # connectedness: every parent_id resolves to a recorded span
        ids = {s.span_id for s in spans}
        dangling = [s for s in spans
                    if s.parent_id is not None and s.parent_id not in ids]
        if dangling:
            fails.append(f"span tree disconnected: {len(dangling)} orphans")
        if not any(s.name == "serve.batch" for s in spans):
            fails.append("no serve.batch span recorded")
        if not any(s.kind == "request" for s in spans):
            fails.append("no request spans recorded")

        sess.metrics.to_json(os.path.join(out_dir, "metrics.json"))
        prom = obs.render_prometheus(sess.metrics)
        with open(os.path.join(out_dir, "metrics.prom"), "w") as f:
            f.write(prom)
        if "slate_tpu_solves_total" not in prom:
            fails.append("prometheus text missing solves_total")
        # round-9 sections: bytes/collective ledgers + HBM gauges
        for needle in ("slate_tpu_driver_bytes_total",
                       "slate_tpu_collective_bytes_total",
                       "slate_tpu_peak_hbm_bytes",
                       "slate_tpu_resident_bytes"):
            if needle not in prom:
                fails.append(f"prometheus text missing {needle}")

        # -- cost exports (round 9): per-shape rows + ledgers ---------
        bytes_snap = obs.costs.BYTES.snapshot()
        costs_doc = {
            "cost_log": sess.cost_log,
            "bytes_ledger": bytes_snap,
            "roofline": obs.roofline.roofline_report(),
        }
        with open(os.path.join(out_dir, "costs.json"), "w") as f:
            json.dump(costs_doc, f, indent=2, sort_keys=True)
            f.write("\n")
        # schema: every AOT-compiled shape exports the full cost row
        if not sess.cost_log:
            fails.append("cost_log empty: AOT seam harvested nothing")
        for row in sess.cost_log:
            for k in ("op", "what", "shape", "model_flops",
                      "bytes_accessed", "temp_bytes", "peak_bytes",
                      "collective_bytes"):
                if k not in row:
                    fails.append(f"cost_log row missing {k!r}")
                    break
        if mesh_ran:
            summa_ops = [op for op in bytes_snap["per_op"]
                         if op.startswith("parallel.summa")]
            if not summa_ops:
                fails.append("mesh driver credited no bytes-ledger op")
            elif not any(bytes_snap["per_op"][op]["collective_bytes"] > 0
                         for op in summa_ops):
                fails.append("mesh driver recorded zero collective bytes")

        svg = legacy_trace.Trace.finish(os.path.join(out_dir, "trace.svg"))
        if svg is None:
            fails.append("SVG timeline empty (span bridge broken)")

        # -- SLO payload (round 12) -----------------------------------
        slo_payload = sess.slo.evaluate()
        with open(os.path.join(out_dir, "slo.json"), "w") as f:
            json.dump(slo_payload, f, indent=2, sort_keys=True)
            f.write("\n")
        objs = slo_payload.get("objectives", [])
        if not objs:
            fails.append("/slo payload has no objectives")
        req_rows = [o for o in objs if o["kind"] in ("latency",
                                                     "error_rate")]
        if not any(w["burn_rate"] is not None
                   for o in req_rows for w in o["windows"]):
            fails.append("slo: no burn rate computed over the served "
                         "traffic")
        if "slate_tpu_slo_burn_rate" not in obs.render_prometheus(
                sess.metrics, ledger=False, bytes_ledger=False):
            fails.append("prometheus text missing slo burn-rate gauges")

        # lifecycle stages + backpressure (tentpole c / satellite 1)
        hists = sess.metrics.snapshot()["histograms"]
        for stage in ("stage_queue_wait", "stage_batch_form",
                      "stage_dispatch", "stage_device_execute",
                      "stage_reply"):
            if not hists.get(stage, {}).get("count"):
                fails.append(f"lifecycle stage histogram {stage} empty")
            elif not (hists[stage].get("exemplar") or {}).get("trace_id"):
                fails.append(f"stage {stage}: no exemplar trace-id")
        gsnap = sess.metrics.snapshot()["gauges"]
        for g in ("queue_depth", "queued_buckets", "oldest_request_age_s",
                  "max_bucket_backlog", "inflight_batches"):
            if g not in gsnap:
                fails.append(f"backpressure gauge {g} missing")

        # -- watchdog: real history quiet, injected regression flagged -
        wd = obs.Watchdog(metrics=sess.metrics, tracer=tracer)
        wd.watch_session(sess, platform=jax.default_backend(), n=n)
        # replay every committed series at its own best: on a CPU host
        # the anomalies list is empty BY POLICY (cpu never gates), so
        # the meaningful quiet-check is matched-every-series with zero
        # informational drops — a drop would mean the baseline
        # disagrees with itself
        baseline_doc = obs.watchdog.load_baseline()
        for row in baseline_doc["series"]:
            wd.observe(row["metric"], row["best"], row["platform"],
                       n=row["n"], op=row["op"], batch=row["batch"],
                       dtype=row["dtype"], kind=row["kind"])
        real_rep = wd.check()
        if real_rep["anomalies"] or real_rep["informational"]:
            fails.append("watchdog flagged the real committed history: "
                         f"{(real_rep['anomalies'] or real_rep['informational'])[:2]}")
        if real_rep["matched"] < len(baseline_doc["series"]):
            fails.append(
                f"watchdog matched only {real_rep['matched']} of "
                f"{len(baseline_doc['series'])} committed series")
        injected = {
            "schema": "slate_tpu.baseline_series.v1", "tolerance": 0.10,
            "series": [{"kind": "serve", "metric": "request_latency_p99",
                        "platform": "tpu", "n": n, "batch": None,
                        "op": None, "dtype": None, "direction": "lower",
                        "best": 1e-6}],
        }
        wd2 = obs.Watchdog(baseline=injected, metrics=sess.metrics,
                           tracer=tracer)
        # the injected-latency fixture: live p99 orders of magnitude
        # above the synthetic committed best MUST flag
        lat = sess.metrics.snapshot()["histograms"]["request_latency"]
        wd2.observe("request_latency_p99", max(lat["p99"], 1e-3), "tpu",
                    n=n, kind="serve")
        inj_rep = wd2.check()
        if not inj_rep["anomalies"]:
            fails.append("watchdog missed the injected latency "
                         "regression")
        if not any(s.name == "watchdog.anomaly" for s in tracer.spans()):
            fails.append("no watchdog.anomaly trace event recorded")
        with open(os.path.join(out_dir, "watchdog.json"), "w") as f:
            json.dump({"real_history": real_rep, "injected": inj_rep},
                      f, indent=2, sort_keys=True)
            f.write("\n")

        # -- padding-waste ledger (tentpole c acceptance) ---------------
        # 3 distinct lu_small operators -> pow2 bucket 4 -> one padded
        # lane of REAL flops; a 4-of-4 bucket must credit exactly 0
        rng2 = np.random.default_rng(7)
        under = Session()
        hs = [under.register(rng2.standard_normal((16, 16))
                             + 16 * np.eye(16), op="lu_small")
              for _ in range(3)]
        under.solve_small_batched(hs, [rng2.standard_normal((16, 1))
                                       for _ in hs])
        if not under.metrics.get("padding_waste_flops") > 0:
            fails.append("padding_waste_flops == 0 on an under-occupied "
                         "bucket")
        full = Session()
        hf = [full.register(rng2.standard_normal((16, 16))
                            + 16 * np.eye(16), op="lu_small")
              for _ in range(4)]
        full.solve_small_batched(hf, [rng2.standard_normal((16, 1))
                                      for _ in hf])
        if full.metrics.get("padding_waste_flops") != 0:
            fails.append("padding_waste_flops != 0 at full occupancy")
        if "slate_tpu_padding_waste_flops" not in obs.render_prometheus(
                under.metrics, ledger=False, bytes_ledger=False):
            fails.append("prometheus text missing padding_waste_flops")
        if obs.flops.LEDGER.snapshot()["per_op"].get(
                "padding.waste", 0) <= 0:
            fails.append("process ledger has no padding.waste op")

        # -- mixed-precision refinement telemetry (round 13) ------------
        # a served refined workload must surface: the refine_iterations
        # histogram, the converged counter, the useful-vs-refinement
        # ledger split (serve.refine beside serve.solve), and — from a
        # deliberately non-convergent operator — the counted fallback
        # that still returns a correct solve
        from slate_tpu.refine import RefinePolicy
        rng3 = np.random.default_rng(9)
        mbase = rng3.standard_normal((48, 48)).astype(np.float32)
        mspd = mbase @ mbase.T + 48 * np.eye(48, dtype=np.float32)
        msess = Session()
        mh = msess.register(
            st.hermitian(np.tril(mspd), nb=16, uplo=st.Uplo.Lower),
            op="chol", refine=RefinePolicy(factor_dtype="bfloat16"))
        mb = rng3.standard_normal(48).astype(np.float32)
        mx = msess.solve(mh, mb)
        if not float(np.abs(mspd @ mx - mb).max()) / 48 < 1e-2:
            fails.append("served mixed solve residual too large")
        msnap = msess.metrics.snapshot()
        if not msnap["histograms"].get("refine_iterations",
                                       {}).get("count"):
            fails.append("refine_iterations histogram empty after a "
                         "served mixed solve")
        if not msnap["counters"].get("refine_converged_total"):
            fails.append("refine_converged_total not incremented")
        mprom = obs.render_prometheus(msess.metrics)
        for needle in ("slate_tpu_refine_iterations",
                       "slate_tpu_refine_converged_total",
                       "slate_tpu_refine_flops_total"):
            if needle not in mprom:
                fails.append(f"prometheus text missing {needle}")
        lsnap = obs.flops.LEDGER.snapshot()["per_op"]
        if lsnap.get("serve.refine", 0) <= 0:
            fails.append("process ledger has no serve.refine op (the "
                         "useful-vs-refinement split)")
        # forced non-convergence: an impossible tolerance -> counted
        # fallback through a working-precision refactor, answer still
        # correct
        fh = msess.register(
            st.hermitian(np.tril(mspd), nb=16, uplo=st.Uplo.Lower),
            op="chol",
            refine=RefinePolicy(factor_dtype="bfloat16", max_iters=2,
                                tol=1e-14))
        fx = msess.solve(fh, mb)
        if not float(np.abs(mspd @ fx - mb).max()) / 48 < 1e-2:
            fails.append("refine fallback returned a wrong solve")
        if msess.metrics.get("refine_fallbacks_total") != 1:
            fails.append("refine_fallbacks_total != 1 after a forced "
                         "non-convergent solve")
        if "slate_tpu_refine_fallbacks_total" not in \
                obs.render_prometheus(msess.metrics, ledger=False,
                                      bytes_ledger=False):
            fails.append("prometheus text missing "
                         "refine_fallbacks_total")

        # -- tenant attribution + placement (round 15) ------------------
        # a second tenant's small operator joins the session so the
        # ledger has ≥2 tenants; then: bit-exact conservation per
        # counter class, a schema-valid placement snapshot, the
        # /tenants route, tenant_* prom sections — all exit-gating
        from slate_tpu.obs.attribution import (
            CLASSES, validate_placement_snapshot)
        hb = sess.register(rng.standard_normal((16, 16))
                           + 16 * np.eye(16), op="lu_small",
                           tenant="tenant-b")
        for _ in range(3):
            sess.solve(hb, rng.standard_normal(16))
        att_snap = sess.attribution.snapshot()
        if set(att_snap["tenants"]) < {"tenant-a", "tenant-b"}:
            fails.append("attribution missing a registered tenant: "
                         f"{sorted(att_snap['tenants'])}")
        for cls, counter in CLASSES.items():
            cells = att_snap["totals"].get(cls, 0.0)
            glob = sess.metrics.get(counter)
            if cells != glob:
                fails.append(
                    f"attribution conservation broken for {cls}: "
                    f"per-tenant sum {cells!r} != global {glob!r}")
        placement = sess.placement_snapshot()
        perrs = validate_placement_snapshot(placement)
        if perrs:
            fails.append(f"placement snapshot schema: {perrs[:3]}")
        if not placement["rows"]:
            fails.append("placement snapshot has no resident rows")
        if not any(r["heat"] > 0 for r in placement["rows"]):
            fails.append("placement snapshot rows carry no heat")
        with open(os.path.join(out_dir, "placement.json"), "w") as f:
            json.dump(placement, f, indent=2, sort_keys=True)
            f.write("\n")
        with open(os.path.join(out_dir, "tenants.json"), "w") as f:
            json.dump(sess.tenants_payload(), f, indent=2,
                      sort_keys=True)
            f.write("\n")
        tprom = obs.render_prometheus(sess.metrics, ledger=False,
                                      bytes_ledger=False,
                                      attribution=sess.attribution)
        for needle in ("slate_tpu_tenant_solve_flops_total",
                       'tenant="tenant_b"', "slate_tpu_handle_heat"):
            if needle not in tprom:
                fails.append(f"prometheus text missing {needle}")
        # 2-process fold of the attribution cells + placement rows:
        # counters double bit-exactly, the folded per-tenant rows sum
        # to the folded globals, per-host placement rows survive
        att_fleet = obs.aggregate.merge_attribution_snapshots(
            [att_snap := sess.attribution.snapshot(), att_snap])
        msnap0 = sess.metrics.snapshot()
        for cls, counter in CLASSES.items():
            folded = att_fleet["totals"].get(cls, 0.0)
            want = 2 * msnap0["counters"].get(counter, 0.0)
            if folded != want:
                fails.append(
                    f"fleet attribution conservation broken for {cls}:"
                    f" {folded!r} != 2x global {want!r}")
        pl_fleet = obs.aggregate.merge_placement_snapshots(
            [placement, dict(placement, host="other")])
        if len(pl_fleet["rows"]) != 2 * len(placement["rows"]):
            fails.append("fleet placement fold lost rows")
        if "tenant-a" not in pl_fleet["per_tenant"]:
            fails.append("fleet placement rollup missing tenant-a")

        # -- tenant quotas + weighted-fair dispatch (round 18) ----------
        # the session carries a declared TenantTable: the /tenants
        # payload must expose the quota view, the per-tenant quota
        # gauges must be in the Prometheus text, and a two-tenant
        # ready snapshot pumped through a Batcher must engage the
        # deficit scheduler (fair_share_deficit gauges published)
        tpay = sess.tenants_payload()
        q = tpay.get("quotas", {})
        if not q.get("enabled"):
            fails.append("/tenants payload missing an enabled quotas "
                         "section")
        if "tenant-a" not in q.get("tenants", {}):
            fails.append("quotas section missing tenant-a resident row")
        from slate_tpu.runtime import Batcher
        qbat = Batcher(sess, max_batch=4, max_wait=3600.0)
        qfuts = ([qbat.submit(h, rng.standard_normal(n))
                  for _ in range(4)]
                 + [qbat.submit(hb, rng.standard_normal(16),
                                tenant="tenant-b")
                    for _ in range(4)])
        qbat.flush()
        for f in qfuts:
            f.result(timeout=120)
        qprom = obs.render_prometheus(sess.metrics, ledger=False,
                                      bytes_ledger=False)
        for needle in ("slate_tpu_tenant_quota_resident_bytes_tenant_a",
                       "slate_tpu_fair_share_deficit_tenant_"):
            if needle not in qprom:
                fails.append(f"prometheus text missing {needle}")

        # -- numerical-health telemetry (round 16) ----------------------
        # the served SPD workload above ran with a probe-every-solve
        # sampler and factor-time condest: the handle_health gauge
        # rows must be in the Prometheus text, the /numerics payload
        # must carry the handle's signals (healthy — the operand is
        # well-conditioned by construction), the probe/condest
        # counters must have moved, and the residual SLO objective
        # must have computed a burn rate over the probe stream
        npay = sess.numerics_payload()
        with open(os.path.join(out_dir, "numerics.json"), "w") as f:
            json.dump(npay, f, indent=2, sort_keys=True)
            f.write("\n")
        if not npay.get("enabled") or not npay.get("handles"):
            fails.append("numerics payload empty after a served probed "
                         "workload")
        else:
            hrow = next(iter(npay["handles"].values()))
            if hrow["state"] != "healthy":
                fails.append("well-conditioned operand classified "
                             f"{hrow['state']!r}, not healthy")
            if not hrow.get("condest"):
                fails.append("numerics payload missing the factor-time "
                             "condest")
            if not hrow.get("resid_count"):
                fails.append("numerics payload recorded no sampled "
                             "residuals")
        ncnt = npay.get("counters", {})
        for c in ("residual_probes_total", "condest_runs_total",
                  "condest_solves_total"):
            if not ncnt.get(c):
                fails.append(f"numerics counter {c} did not move")
        nprom = obs.render_prometheus(sess.metrics, ledger=False,
                                      bytes_ledger=False)
        for needle in ("slate_tpu_handle_health",
                       "slate_tpu_sampled_residual",
                       "slate_tpu_residual_probes_total"):
            if needle not in nprom:
                fails.append(f"prometheus text missing {needle}")
        if obs.flops.LEDGER.snapshot()["per_op"].get(
                "numerics.condest", 0) <= 0:
            fails.append("process ledger has no numerics.condest op "
                         "(probe work must be credited, not free)")
        slo_rows2 = sess.slo.evaluate()["objectives"]
        rrow = next((o for o in slo_rows2
                     if o["kind"] == "residual"), None)
        if rrow is None:
            fails.append("/slo payload missing the residual objective")
        elif not any(w["burn_rate"] is not None for w in rrow["windows"]):
            fails.append("residual SLO objective computed no burn rate "
                         "over the probe stream")

        # -- 2-process aggregation (tentpole d) -------------------------
        # same-snapshot fold: the acceptance's bit-exactness check —
        # merging a snapshot with itself must exactly double every
        # counter (and the combined trace must stay schema-valid)
        snap = sess.metrics.snapshot()
        fleet = obs.aggregate.aggregate_processes(
            [snap, snap], flop_snaps=[obs.flops.LEDGER.snapshot()] * 2,
            bytes_snaps=[obs.costs.BYTES.snapshot()] * 2,
            hosts=["proc0", "proc1"])
        merged = fleet["metrics"]["counters"]
        for k2, v2 in snap["counters"].items():
            if merged.get(k2) != 2 * v2:
                fails.append(f"aggregation not bit-exact for {k2}: "
                             f"{merged.get(k2)} != 2*{v2}")
                break
        obs.aggregate.write_fleet(
            fleet, json_path=os.path.join(out_dir, "fleet.json"),
            prom_path=os.path.join(out_dir, "fleet.prom"))
        with open(os.path.join(out_dir, "fleet.prom")) as f:
            fprom = f.read()
        if 'host="proc1"' not in fprom:
            fails.append("fleet prometheus missing host-labeled gauges")
        with open(trace_path) as f:
            one_trace = json.load(f)
        combined = obs.combine_process_traces([one_trace, one_trace],
                                              ["proc0", "proc1"])
        cerrs = obs.validate_chrome_trace(combined)
        if cerrs:
            fails.append(f"combined fleet trace invalid: {cerrs[:2]}")
        pids = {e.get("pid") for e in combined["traceEvents"]}
        if not (pids & set(range(0, 3))) or not (pids & set(range(100,
                                                                 103))):
            fails.append("combined trace pids not namespaced per process")
        with open(os.path.join(out_dir, "fleet_trace.json"), "w") as f:
            json.dump(combined, f, indent=1)
            f.write("\n")

        # -- checkpoint round trip (round 17) ---------------------------
        # the served session's resident state checkpoints to disk, the
        # manifest validates under BOTH the runtime validator and the
        # jax-free bench_gate mirror, a fresh session restores it with
        # ZERO refactors and a bit-identical solve, the restored heat/
        # health carry over, and the checkpoint-derived placement doc
        # folds as a partial host — all exit-gating
        from slate_tpu.runtime import Session as _Session
        from slate_tpu.runtime.checkpoint import validate_manifest
        ckpt_dir = os.path.join(out_dir, "checkpoint")
        manifest = sess.checkpoint(ckpt_dir)
        if not manifest["records"]:
            fails.append("checkpoint wrote no resident records")
        import importlib.util as _ilu
        _bg_spec = _ilu.spec_from_file_location(
            "_bench_gate", os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_gate.py"))
        _bg = _ilu.module_from_spec(_bg_spec)
        _bg_spec.loader.exec_module(_bg)
        for which, errs2 in (
                ("runtime", validate_manifest(manifest)),
                ("bench_gate mirror",
                 _bg.validate_checkpoint_manifest(ckpt_dir))):
            if errs2:
                fails.append(f"checkpoint manifest failed the {which} "
                             f"validator: {errs2[:2]}")
        b_ck = rng.standard_normal(n).astype(np.float64)
        # both comparison solves run UNPROBED (numerics off for the
        # reference, sample_fraction=0 for the restored twin): the
        # fused probe program is a different executable than the plain
        # solve, and the bit-identity claim is plain-vs-plain
        saved_nm, sess.numerics = sess.numerics, None
        x_pre = sess.solve(h, b_ck)
        sess.numerics = saved_nm
        rsess = _Session()
        rsess.enable_attribution()
        rsess.enable_numerics(sample_fraction=0.0)
        rsumm = rsess.restore(ckpt_dir)
        if set(rsumm["restored"]) != {r2["handle"] for r2
                                      in manifest["records"]}:
            fails.append(f"restore summary incomplete: {rsumm}")
        x_post = rsess.solve(h, b_ck)
        if np.asarray(x_pre).tobytes() != np.asarray(x_post).tobytes():
            fails.append("restored resident's solve is not "
                         "bit-identical to the pre-checkpoint solve")
        if rsess.metrics.get("factors_total") != 0:
            fails.append("restore refactored (warm restart must not)")
        if not rsess.attribution.heat(h) > 0:
            fails.append("restored handle carried no heat")
        if rsess.numerics.health(h) is None:
            fails.append("restored handle carried no health state")
        # partial-host fold: the checkpoint stands in for a crashed
        # host whose live snapshot is gone
        part = obs.aggregate.placement_from_checkpoint(manifest,
                                                       host="dead0")
        pl_part = obs.aggregate.merge_placement_snapshots(
            [placement, part])
        if pl_part.get("partial_hosts") != ["dead0"]:
            fails.append("partial-host placement fold did not mark "
                         f"the checkpoint host: {pl_part.get('partial_hosts')}")
        if not any(r2["host"] == "dead0" for r2 in pl_part["rows"]):
            fails.append("partial-host fold lost the checkpoint rows")
        att_part = obs.aggregate.merge_attribution_snapshots(
            [sess.attribution.snapshot(), None])
        if att_part.get("partial_processes") != 1:
            fails.append("attribution fold did not count the partial "
                         "host")

        # -- decision journal + incidents (round 22) --------------------
        # an explicit eviction gives the journal a decision whose
        # counter parity is absolute (recorder predates the register),
        # and a probe incident drives the crash-safe capture path
        sess.evict(h)
        jp = sess.recorder.journal.payload()
        if not jp["events"]:
            fails.append("decision journal empty after an explicit "
                         "evict")
        if jp["counts"].get("eviction") != sess.metrics.get("evictions"):
            fails.append("journal eviction count != evictions counter: "
                         f"{jp['counts'].get('eviction')} != "
                         f"{sess.metrics.get('evictions')}")
        sess.recorder.incident("obs_dump_probe", key="smoke", handle=h)
        ip = sess.recorder.incidents.payload()
        if not ip["incidents"]:
            fails.append("probe incident was not captured")
        else:
            ierrs = obs.validate_incident(ip["incidents"][-1])
            if ierrs:
                fails.append(f"captured incident schema: {ierrs[:3]}")
        idir = os.path.join(out_dir, "incidents")
        on_disk = ([f2 for f2 in os.listdir(idir) if f2.endswith(".json")]
                   if os.path.isdir(idir) else [])
        if not on_disk:
            fails.append("incident capture published no on-disk "
                         "snapshot")
        # 2-process journal fold: counts conserved exactly, events
        # host-labeled (the fleet view of "why did N processes shed")
        jf = obs.aggregate.merge_journal_payloads([jp, jp],
                                                  hosts=["p0", "p1"])
        for k3, v3 in jp["counts"].items():
            if jf["counts"].get(k3) != 2 * v3:
                fails.append(f"journal fold not exact for {k3}: "
                             f"{jf['counts'].get(k3)} != 2*{v3}")
                break
        if jf.get("recorded") != 2 * jp["recorded"]:
            fails.append("journal fold lost recorded totals")
        if jp["events"] and not all(e3.get("host") in ("p0", "p1")
                                    for e3 in jf["events"]):
            fails.append("journal fold events not host-labeled")
        iflt = obs.aggregate.merge_incident_payloads([ip, ip],
                                                     hosts=["p0", "p1"])
        if len(iflt["incidents"]) != 2 * len(ip["incidents"]):
            fails.append("incident fold dropped incidents")

        # -- telemetry history + forecasting (round 23) -----------------
        # the served run above pumped a sample per completed request:
        # the /history payload must self-validate and carry series, the
        # /forecast payload must self-validate, every counter series
        # total must equal the live counter EXACTLY (delta-storage
        # conservation), and a 2-process fold must double the counter
        # totals bit-exactly with host-labeled series
        sess.pump_timeseries(force=True)
        hist = store.payload()
        terrs = obs.validate_timeseries(hist)
        if terrs:
            fails.append(f"/history payload schema: {terrs[:3]}")
        if not hist["series"]:
            fails.append("history store recorded no series over a "
                         "served run")
        with open(os.path.join(out_dir, "history.json"), "w") as f:
            json.dump(hist, f, indent=2, sort_keys=True)
            f.write("\n")
        fc = sess.forecaster.payload(horizon_s=60.0, k=8,
                                     max_series=64, points_limit=8)
        ferrs = obs.validate_forecast(fc)
        if ferrs:
            fails.append(f"/forecast payload schema: {ferrs[:3]}")
        with open(os.path.join(out_dir, "forecast.json"), "w") as f:
            json.dump(fc, f, indent=2, sort_keys=True)
            f.write("\n")
        ctot = store.counter_totals()
        if not ctot:
            fails.append("history store tracked no counter series")
        csnap = sess.metrics.snapshot()["counters"]
        for nm, total in ctot.items():
            if total != csnap.get(nm, 0.0):
                fails.append("history counter conservation broken for "
                             f"{nm}: store {total!r} != live "
                             f"{csnap.get(nm)!r}")
                break
        ts_fleet = obs.aggregate.merge_timeseries_payloads(
            [hist, hist], hosts=["p0", "p1"])
        for nm, total in ts_fleet.get("counter_totals", {}).items():
            if total != 2 * ctot.get(nm, 0.0):
                fails.append("history fold counter totals not exact "
                             f"for {nm}: {total!r} != 2*{ctot.get(nm)!r}")
                break
        if hist["series"] and not any(
                k4.startswith(("p0:", "p1:"))
                for k4 in ts_fleet["series"]):
            fails.append("history fold series not host-labeled")

        # -- HTTP endpoint --------------------------------------------
        for path, needle in (("/metrics", "slate_tpu_solves_total"),
                             ("/healthz", '"status": "ok"'),
                             ("/trace.json", "traceEvents"),
                             ("/slo", '"objectives"'),
                             ("/numerics", '"handles"'),
                             ("/journal", '"slate_tpu.journal.v1"'),
                             ("/incidents",
                              '"slate_tpu.incidents.v1"'),
                             ("/history",
                              '"slate_tpu.timeseries.v1"'),
                             ("/forecast",
                              '"slate_tpu.forecast.v1"')):
            body = urllib.request.urlopen(srv.url(path),
                                          timeout=10).read().decode()
            if needle not in body:
                fails.append(f"GET {path}: missing {needle!r}")
    finally:
        sess.close_obs()
        tracer.off()
        legacy_trace.Trace.off()

    summary = {
        "out_dir": out_dir,
        "spans": len(tracer.spans()),
        "requests": requests,
        "schema_errors": 0 if not fails else fails,
        "ok": not fails,
    }
    print(json.dumps(summary))
    for msg in fails:
        print(f"!!! {msg}", file=sys.stderr)
    return 0 if not fails else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="tiny CPU run into a temp dir (CI wiring)")
    p.add_argument("--out-dir", default="obs_dump")
    p.add_argument("--n", type=int, default=96)
    p.add_argument("--nb", type=int, default=32)
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--slow-ms", type=float, default=None,
                   help="slow-request log threshold (milliseconds)")
    args = p.parse_args(argv)
    out_dir = args.out_dir
    if args.smoke and out_dir == "obs_dump":
        out_dir = tempfile.mkdtemp(prefix="slate_tpu_obs_")
    thr = args.slow_ms * 1e-3 if args.slow_ms is not None else None
    return run(out_dir, n=args.n, nb=args.nb, requests=args.requests,
               slow_threshold=thr)


if __name__ == "__main__":
    sys.exit(main())
