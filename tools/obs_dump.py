#!/usr/bin/env python
"""Observability smoke: serve a small workload, dump every export.

Drives the full obs surface end to end — tracing ON through
Session/Batcher/Executor, the legacy SVG timeline, the Prometheus
text exposition, and the HTTP endpoint — then writes the artifacts:

  <out-dir>/trace.json    Chrome-trace/Perfetto JSON of the span tree
  <out-dir>/metrics.prom  Prometheus text (same bytes as GET /metrics)
  <out-dir>/metrics.json  Metrics snapshot JSON
  <out-dir>/trace.svg     legacy SVG timeline (utils.trace)

Exit status is nonzero if the Chrome JSON fails schema validation
(obs.validate_chrome_trace: required keys, monotone ts, span nesting),
if the span tree is disconnected, or if the HTTP endpoint serves the
wrong payloads — wired into examples/run_tests.py as the obs smoke.

Usage: python tools/obs_dump.py [--smoke] [--out-dir DIR]
                                [--n N] [--nb NB] [--requests R]
"""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import apply_env_platforms  # noqa: E402

apply_env_platforms()

import numpy as np  # noqa: E402


def run(out_dir, n=96, nb=32, requests=12, slow_threshold=None):
    import slate_tpu as st
    from slate_tpu import obs
    from slate_tpu.runtime import Executor, Session
    from slate_tpu.utils import trace as legacy_trace

    os.makedirs(out_dir, exist_ok=True)
    fails = []

    tracer = obs.Tracer(slow_threshold=slow_threshold)
    tracer.on()
    legacy_trace.Trace.clear()
    legacy_trace.Trace.on()

    rng = np.random.default_rng(5)
    spd = rng.standard_normal((n, n))
    spd = spd @ spd.T + n * np.eye(n)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)

    sess = Session(tracer=tracer)
    h = sess.register(A, op="chol")
    srv = sess.serve_obs()  # opt-in HTTP endpoint, ephemeral port
    try:
        bs = [rng.standard_normal(n) for _ in range(requests)]
        with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
            ex.warmup([h])
            futs = [ex.submit(h, b) for b in bs]
            xs = [f.result(timeout=120) for f in futs]
        resid = max(float(np.abs(spd @ x - b).max()) / n
                    for x, b in zip(xs, bs))
        if not resid < 1e-2:
            fails.append(f"serving residual too large: {resid}")

        # -- exports --------------------------------------------------
        spans = tracer.spans()
        trace_path = os.path.join(out_dir, "trace.json")
        obs.write_chrome_trace(spans, trace_path)
        with open(trace_path) as f:
            errs = obs.validate_chrome_trace(json.load(f))
        if errs:
            fails.append(f"chrome-trace schema: {errs[:3]}")

        # connectedness: every parent_id resolves to a recorded span
        ids = {s.span_id for s in spans}
        dangling = [s for s in spans
                    if s.parent_id is not None and s.parent_id not in ids]
        if dangling:
            fails.append(f"span tree disconnected: {len(dangling)} orphans")
        if not any(s.name == "serve.batch" for s in spans):
            fails.append("no serve.batch span recorded")
        if not any(s.kind == "request" for s in spans):
            fails.append("no request spans recorded")

        sess.metrics.to_json(os.path.join(out_dir, "metrics.json"))
        prom = obs.render_prometheus(sess.metrics)
        with open(os.path.join(out_dir, "metrics.prom"), "w") as f:
            f.write(prom)
        if "slate_tpu_solves_total" not in prom:
            fails.append("prometheus text missing solves_total")

        svg = legacy_trace.Trace.finish(os.path.join(out_dir, "trace.svg"))
        if svg is None:
            fails.append("SVG timeline empty (span bridge broken)")

        # -- HTTP endpoint --------------------------------------------
        for path, needle in (("/metrics", "slate_tpu_solves_total"),
                             ("/healthz", '"status": "ok"'),
                             ("/trace.json", "traceEvents")):
            body = urllib.request.urlopen(srv.url(path),
                                          timeout=10).read().decode()
            if needle not in body:
                fails.append(f"GET {path}: missing {needle!r}")
    finally:
        sess.close_obs()
        tracer.off()
        legacy_trace.Trace.off()

    summary = {
        "out_dir": out_dir,
        "spans": len(tracer.spans()),
        "requests": requests,
        "schema_errors": 0 if not fails else fails,
        "ok": not fails,
    }
    print(json.dumps(summary))
    for msg in fails:
        print(f"!!! {msg}", file=sys.stderr)
    return 0 if not fails else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="tiny CPU run into a temp dir (CI wiring)")
    p.add_argument("--out-dir", default="obs_dump")
    p.add_argument("--n", type=int, default=96)
    p.add_argument("--nb", type=int, default=32)
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--slow-ms", type=float, default=None,
                   help="slow-request log threshold (milliseconds)")
    args = p.parse_args(argv)
    out_dir = args.out_dir
    if args.smoke and out_dir == "obs_dump":
        out_dir = tempfile.mkdtemp(prefix="slate_tpu_obs_")
    thr = args.slow_ms * 1e-3 if args.slow_ms is not None else None
    return run(out_dir, n=args.n, nb=args.nb, requests=args.requests,
               slow_threshold=thr)


if __name__ == "__main__":
    sys.exit(main())
