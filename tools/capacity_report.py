#!/usr/bin/env python
"""Fleet capacity report — timeseries payloads in, one planning
artifact out.

Consumes one or more ``slate_tpu.timeseries.v1`` payload files (the
``/history`` route document, one per host — Session.timeseries
.payload() dumped to JSON) and renders the round-23
``slate_tpu.capacity_report.v1`` artifact:

* ``handles``  — per-handle predicted heat peak over the horizon
  (Holt-Winters / seasonal-naive / trend ladder, forecast.py's
  method selection), ranked hottest-first: ROADMAP item 3's
  pre-replication input.
* ``headroom`` — runway projections for the lower-is-worse gauges
  (hbm_headroom + per-tenant quota headroom): seconds until the
  linear trend crosses zero, None when flat/rising.
* ``store``    — fold health: series counts, cardinality-cap drops
  (summed exactly across hosts), counter conservation totals.

Jax-free by construction: ``slate_tpu/__init__`` imports the linalg
stack, so this tool loads ``slate_tpu/obs/forecast.py`` (pure stdlib,
no relative imports) by FILE PATH under one fixed module name — the
same ``_load()`` discipline bench_gate uses for serve_sections. The
small payload fold is local (aggregate.py has relative imports and
cannot be file-loaded); tests pin it against
``merge_timeseries_payloads`` on the same inputs.

Exit status: 0 iff the rendered report passes
``validate_capacity_report`` (and every input passed the timeseries
schema check). ``--selftest`` runs the whole pipeline on a synthetic
two-host diurnal trace under a fixed clock — deterministic, no
inputs needed.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import math
import os
import sys
from typing import Dict, List, Optional, Sequence

CAPACITY_SCHEMA = "slate_tpu.capacity_report.v1"
TIMESERIES_SCHEMA = "slate_tpu.timeseries.v1"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_forecast():
    """File-path load of slate_tpu/obs/forecast.py under ONE fixed
    module name (stdlib-only module, no relative imports — loadable
    without dragging jax in through the package root)."""
    name = "slate_tpu_obs_forecast"
    mod = sys.modules.get(name)
    if mod is None:
        path = os.path.join(_REPO, "slate_tpu", "obs", "forecast.py")
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return mod


_fc = _load_forecast()

# heat / headroom vocabularies come from the loaded module so the
# report and the live Forecaster can never disagree on them
HEAT_PREFIXES = _fc._HEAT_PREFIXES
HEADROOM_SERIES = _fc._HEADROOM_SERIES
HEADROOM_PREFIXES = _fc._HEADROOM_PREFIXES


# -- payload fold (local mirror of aggregate.merge_timeseries_payloads;
#    drift-pinned by tests/test_timeseries.py) ----------------------------


def fold_payloads(payloads: Sequence[Optional[dict]],
                  hosts: Optional[Sequence[str]] = None) -> dict:
    """N per-host timeseries payloads -> one labeled fold. ``None``
    entries are tolerated (partial fleet) and counted. Counter totals
    are summed EXACTLY (pure float adds in file order)."""
    n = len(payloads)
    labels = ([str(h) for h in hosts] if hosts is not None
              else [f"p{i}" for i in range(n)])
    series: Dict[str, dict] = {}
    counter_totals: Dict[str, float] = {}
    dropped_series = 0
    dropped_samples = 0
    partial = 0
    for label, doc in zip(labels, payloads):
        if doc is None:
            partial += 1
            continue
        dropped_series += int(doc.get("dropped_series", 0))
        dropped_samples += int(doc.get("dropped_samples", 0))
        for name, row in (doc.get("series") or {}).items():
            out = dict(row)
            out["host"] = label
            series[f"{label}:{name}"] = out
            if row.get("kind") == "counter":
                counter_totals[name] = (counter_totals.get(name, 0.0)
                                        + float(row.get("total_sum",
                                                        0.0)))
    return {
        "processes": n,
        "partial_processes": partial,
        "hosts": labels,
        "dropped_series": dropped_series,
        "dropped_samples": dropped_samples,
        "series": series,
        "counter_totals": counter_totals,
    }


# -- report ---------------------------------------------------------------


def _series_points(row: dict) -> List[List[float]]:
    return [[float(t), float(v)] for t, v in (row.get("raw") or [])]


def build_report(payloads: Sequence[Optional[dict]],
                 hosts: Optional[Sequence[str]] = None,
                 horizon_s: float = 600.0, k: int = 16,
                 now: Optional[float] = None) -> dict:
    """The capacity artifact. ``now`` defaults to the max sample
    timestamp across the fold (NOT wall clock — the committed artifact
    must be a pure function of its inputs)."""
    fold = fold_payloads(payloads, hosts=hosts)
    last_ts = [row.get("last_ts") for row in fold["series"].values()
               if row.get("last_ts") is not None]
    if now is None:
        now = max(last_ts) if last_ts else 0.0

    handles: List[dict] = []
    headroom: List[dict] = []
    for key in sorted(fold["series"]):
        row = fold["series"][key]
        host = row["host"]
        name = key[len(host) + 1:]
        pfx = next((p for p in HEAT_PREFIXES if name.startswith(p)),
                   None)
        if pfx is not None:
            pts = _series_points(row)
            fc = _fc.forecast_points(pts, horizon_s)
            if not fc["points"]:
                continue
            peak = max(fc["points"], key=lambda p: p[1])
            handles.append({
                "host": host, "series": name,
                "handle": name[len(pfx):],
                "current": fc["last"],
                "predicted_peak": peak[1], "peak_ts": peak[0],
                "method": fc["method"], "period_s": fc["period_s"],
            })
            continue
        if (name in HEADROOM_SERIES
                or any(name.startswith(p)
                       for p in HEADROOM_PREFIXES)):
            pts = _series_points(row)
            runway: Optional[float] = None
            last = pts[-1][1] if pts else None
            if last is not None and len(pts) >= 2:
                fc = _fc.forecast_points(pts, horizon_s=1.0)
                if last <= 0.0:
                    runway = 0.0
                elif fc["slope_per_s"] < 0:
                    runway = last / (-fc["slope_per_s"])
            headroom.append({
                "host": host, "series": name, "current": last,
                "runway_s": runway,
            })

    handles.sort(key=lambda r: (-r["predicted_peak"], r["series"],
                                r["host"]))
    return {
        "schema": CAPACITY_SCHEMA,
        "generated_at": now,
        "horizon_s": float(horizon_s),
        "hosts": fold["hosts"],
        "processes": fold["processes"],
        "partial_processes": fold["partial_processes"],
        "handles": handles[:int(k)],
        "headroom": headroom,
        "store": {
            "series_count": len(fold["series"]),
            "dropped_series": fold["dropped_series"],
            "dropped_samples": fold["dropped_samples"],
            "counter_totals": fold["counter_totals"],
        },
    }


def validate_capacity_report(doc: dict) -> List[str]:
    """Schema errors (empty = valid) — mirrored jax-free in
    tools/bench_gate.py (drift-pinned by test)."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["capacity: top level is not an object"]
    if doc.get("schema") != CAPACITY_SCHEMA:
        errs.append(f"capacity: schema {doc.get('schema')!r} != "
                    f"{CAPACITY_SCHEMA!r}")
    for key in ("generated_at", "horizon_s", "hosts", "handles",
                "headroom", "store"):
        if key not in doc:
            errs.append(f"capacity: missing {key!r}")
    for row in (doc.get("handles") or []
                if isinstance(doc.get("handles"), list) else []):
        for key in ("host", "series", "handle", "predicted_peak",
                    "peak_ts", "method"):
            if not (isinstance(row, dict) and key in row):
                errs.append(f"capacity: handles row missing {key!r}")
                break
    if not isinstance(doc.get("handles"), list):
        errs.append("capacity: handles is not a list")
    for row in (doc.get("headroom") or []
                if isinstance(doc.get("headroom"), list) else []):
        for key in ("host", "series", "runway_s"):
            if not (isinstance(row, dict) and key in row):
                errs.append(f"capacity: headroom row missing {key!r}")
                break
    if not isinstance(doc.get("headroom"), list):
        errs.append("capacity: headroom is not a list")
    store = doc.get("store")
    if not isinstance(store, dict):
        errs.append("capacity: store is not an object")
    else:
        for key in ("series_count", "dropped_series",
                    "dropped_samples", "counter_totals"):
            if key not in store:
                errs.append(f"capacity: store missing {key!r}")
    return errs


# -- selftest -------------------------------------------------------------


def _selftest_payloads() -> List[dict]:
    """Two synthetic host payloads: a diurnal heat wave (host a leads
    host b by half a cycle), a draining hbm_headroom gauge, and one
    counter split across hosts — fully deterministic."""
    t0 = 1_000.0
    period, amp, n = 300.0, 4.0, 120
    hosts = []
    for h, phase in (("a", 0.0), ("b", math.pi)):
        raw_hot = []
        raw_cold = []
        raw_head = []
        for i in range(n):
            t = t0 + 10.0 * i
            hot = 5.0 + amp * math.sin(
                2 * math.pi * (10.0 * i) / period + phase)
            raw_hot.append([t, hot])
            raw_cold.append([t, 0.5])
            raw_head.append([t, 4.0e9 - 2.0e6 * i])
        series = {
            "heat:h0": {"kind": "gauge", "last": raw_hot[-1][1],
                        "last_ts": raw_hot[-1][0],
                        "total_sum": sum(v for _, v in raw_hot),
                        "total_count": n, "raw": raw_hot,
                        "tiers": {"10": [], "60": []}},
            "heat:h1": {"kind": "gauge", "last": 0.5,
                        "last_ts": raw_cold[-1][0],
                        "total_sum": 0.5 * n, "total_count": n,
                        "raw": raw_cold,
                        "tiers": {"10": [], "60": []}},
            "hbm_headroom": {"kind": "gauge", "last": raw_head[-1][1],
                             "last_ts": raw_head[-1][0],
                             "total_sum": sum(v for _, v in raw_head),
                             "total_count": n, "raw": raw_head,
                             "tiers": {"10": [], "60": []}},
            "requests_total": {"kind": "counter", "last": 7.0,
                               "last_ts": t0 + 10.0 * (n - 1),
                               "total_sum": 170.0, "total_count": n,
                               "raw": [], "tiers": {"10": [],
                                                    "60": []}},
        }
        hosts.append({
            "schema": TIMESERIES_SCHEMA, "host": h,
            "now": t0 + 10.0 * n, "max_series": 512,
            "raw_capacity": 240, "tier_widths": [10.0, 60.0],
            "tier_capacities": [360, 360], "series_count": len(series),
            "dropped_series": 0, "dropped_samples": 0,
            "series": series,
        })
    return hosts


def _run_selftest() -> int:
    report = build_report(_selftest_payloads(), hosts=["a", "b"],
                          horizon_s=600.0, k=4)
    errs = validate_capacity_report(report)
    ok = not errs
    # the hot handle must outrank the flat one on BOTH hosts, the
    # seasonal ladder must have engaged (4 cycles retained), and the
    # draining gauge must get a finite runway
    tops = [r for r in report["handles"] if r["handle"] == "h0"]
    if len(tops) != 2:
        errs.append("selftest: expected heat:h0 from both hosts in "
                    "the top-k")
    for r in tops:
        if r["method"] not in ("holt_winters", "seasonal_naive"):
            errs.append(f"selftest: heat:h0@{r['host']} method "
                        f"{r['method']!r}, want seasonal")
        if not (r["predicted_peak"] > 7.0):
            errs.append(f"selftest: heat:h0@{r['host']} peak "
                        f"{r['predicted_peak']:.2f} <= 7.0")
    runways = [r["runway_s"] for r in report["headroom"]
               if r["series"] == "hbm_headroom"]
    if len(runways) != 2 or any(
            rw is None or not (0.0 < rw < 1.0e6) for rw in runways):
        errs.append(f"selftest: hbm runways {runways!r} not finite")
    want_total = 340.0  # 170 per host, summed exactly
    got = report["store"]["counter_totals"].get("requests_total")
    if got != want_total:
        errs.append(f"selftest: counter fold {got!r} != {want_total}")
    ok = ok and not errs
    print(json.dumps({"selftest_ok": ok, "errors": errs,
                      "handles": report["handles"],
                      "headroom": report["headroom"]}, indent=2))
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("payloads", nargs="*",
                    help="timeseries payload JSON files (one per "
                    "host; file stem = host label unless the payload "
                    "carries one)")
    ap.add_argument("--out", default=None,
                    help="write the report here (default: stdout)")
    ap.add_argument("--horizon-s", type=float, default=600.0)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic two-host drill, no inputs needed")
    args = ap.parse_args(argv)

    if args.selftest:
        return _run_selftest()
    if not args.payloads:
        ap.error("no payload files (or --selftest)")

    docs: List[dict] = []
    hosts: List[str] = []
    bad = 0
    for path in args.payloads:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") != TIMESERIES_SCHEMA:
            print(f"capacity_report: {path}: schema "
                  f"{doc.get('schema')!r} != {TIMESERIES_SCHEMA!r}",
                  file=sys.stderr)
            bad += 1
            continue
        docs.append(doc)
        hosts.append(doc.get("host")
                     or os.path.splitext(os.path.basename(path))[0])
    if not docs:
        print("capacity_report: no valid payloads", file=sys.stderr)
        return 1

    report = build_report(docs, hosts=hosts, horizon_s=args.horizon_s,
                          k=args.k)
    errs = validate_capacity_report(report)
    text = json.dumps(report, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"capacity_report: wrote {args.out} "
              f"({len(report['handles'])} handles, "
              f"{len(report['headroom'])} headroom rows)")
    else:
        print(text)
    for e in errs:
        print(f"capacity_report: {e}", file=sys.stderr)
    return 1 if (errs or bad) else 0


if __name__ == "__main__":
    sys.exit(main())
