#!/bin/sh
# On-chip recorded tester sweep (VERDICT r4 weak #2: every committed
# sweep so far is correctness-only at n<=256 on CPU — no per-routine
# GFLOP/s record exists from any round on real hardware).
#
#   sh tools/tpu_sweep.sh            # writes examples/tpu_sweep.log
#
# Tester timings on the axon tunnel include ~100 ms of per-call
# dispatch (sync is a one-element fetch), so rows are honest wall
# times but slightly understate GFLOP/s; at n>=4096 the bias is <5%.
# Two tiers: broad coverage at n=4096, and the headline factorizations
# again at n=8192 for continuity with bench.py's slope-timed numbers.
set -e
cd "$(dirname "$0")/.."
OUT=examples/tpu_sweep.log
TMP=$OUT.tmp

run() {
    # one tester invocation per routine group so a hang/crash costs
    # only its own rows (tunnel sessions can drop mid-sweep); capture
    # to a file first — in a pipeline the tester's own exit status
    # (timeout 124, FAILED rows) would be swallowed by tail's
    RAW=$(mktemp)
    if timeout -k 10 1200 python -m slate_tpu.tester "$@" > "$RAW" 2>/dev/null
    then tail -n +3 "$RAW" >> "$TMP"
    else tail -n +3 "$RAW" >> "$TMP"; echo "# TIMEOUT/FAIL: $*" >> "$TMP"
    fi
    rm -f "$RAW"
}

: > "$TMP"
{
    echo "# On-chip tester sweep ($(python -c 'import jax; print(jax.devices()[0])' 2>/dev/null))"
    echo "# routine               m      n    nb  grid    time(s)    GFLOP/s scaled-err status"
} >> "$TMP"

NB=1024
run --routine gemm,symm,herk,her2k,trmm,trsm --n 4096 --nb $NB --iters 2
run --routine potrf,posv,potri,trtri --n 4096 --nb $NB --iters 2
run --routine getrf,gesv,getri,gesv_calu --n 4096 --nb $NB --iters 2
run --routine geqrf,gelqf,gels,cholqr --n 4096 --nb $NB --iters 2
run --routine posv_mixed,gesv_mixed --n 4096 --nb $NB --iters 2
run --routine hetrf,hesv --n 4096 --nb $NB --iters 2
run --routine genorm,henorm,trnorm,col_norms --n 4096 --nb $NB --iters 2
run --routine heev,svd --n 4096 --nb 512 --iters 1
run --routine gemm,potrf,getrf,geqrf --n 8192 --nb $NB --iters 2

mv "$TMP" "$OUT"
tail -n +1 "$OUT"
