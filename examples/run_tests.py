"""Smoke-run all examples + the tester mesh sweep (reference:
examples/run_tests.py — doubles as an API regression test; the --grid
sweep is the `mpirun -np 8 tester` artifact of SURVEY §4, run on the
8-device virtual CPU mesh)."""

import importlib.util
import os
import pathlib
import subprocess
import sys


def _platform_mod():
    """compat/platform.py loaded standalone (keeps jax out of this
    parent process; the children initialize their own backends)."""
    spec = importlib.util.spec_from_file_location(
        "_slate_tpu_platform",
        str(pathlib.Path(__file__).parent.parent / "slate_tpu" / "compat"
            / "platform.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

# the multi-process tester artifact: a 2×4 virtual-mesh sweep over one
# representative routine per family (VERDICT r3 #7 — the reference's
# tester IS the mpirun evidence; examples/mesh_sweep.log records a run)
MESH_SWEEP = [
    sys.executable, "-u", "-m", "slate_tpu.tester",
    "--routine", "gemm,posv,gesv,gels,heev,hetrf,stedc_grid,redistribute",
    "--n", "256", "--nb", "64", "--p", "2", "--q", "4",
]


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    here = pathlib.Path(__file__).parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(here.parent) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    fails = 0
    if "--mesh-sweep" in argv or "--all" in argv:
        env_sweep = dict(env)
        env_sweep["JAX_PLATFORMS"] = "cpu"
        flags = env_sweep.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            flags = (flags
                     + " --xla_force_host_platform_device_count=8").strip()
            # unknown XLA_FLAGS abort the process on some jaxlib
            # builds; the probe-gated helper adds the rendezvous-
            # timeout raise only where it exists
            flags += _platform_mod().collective_timeout_flag_if_supported(
                cache_path=str(here.parent / ".xla_flag_probe.json"))
            env_sweep["XLA_FLAGS"] = flags
        print("=== tester mesh sweep (2x4 virtual CPU mesh) ===")
        r = subprocess.run(MESH_SWEEP, cwd=here.parent, env=env_sweep)
        if r.returncode != 0:
            fails += 1
            print("!!! mesh sweep FAILED")
        if "--mesh-sweep" in argv:
            return fails
    env_ex = dict(env)
    # smoke runs target the CPU backend: fast compiles, and the
    # complex-dtype paths in ex03/ex04 (zheev, zgesv) hit UNIMPLEMENTED
    # on the axon TPU backend; each example honors this via
    # apply_env_platforms (the sitecustomize ignores plain env vars)
    env_ex.setdefault("JAX_PLATFORMS", "cpu")
    for ex in sorted(here.glob("ex*.py")):
        print(f"=== {ex.name} ===")
        r = subprocess.run([sys.executable, str(ex)], cwd=here.parent,
                           env=env_ex)
        if r.returncode != 0:
            fails += 1
            print(f"!!! {ex.name} FAILED")
    # serving-runtime smoke: exercises Session/Executor/metrics end to
    # end and asserts cached-factor serving beats per-request
    # factor+solve (bench_serve.py exits nonzero otherwise)
    print("=== bench_serve.py --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"), "--smoke"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --smoke FAILED")
    # many-small-problems smoke (round 10): batched vs per-request
    # req/s rows into a throwaway artifact; exits nonzero unless every
    # batched program is structurally one-program (no per-item
    # factorization custom-call loop in the HLO)
    print("=== bench_serve.py --batched --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--batched", "--smoke", "--batched-out",
         "/tmp/BENCH_r08_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --batched --smoke FAILED")
    # pod-scale serving smoke (round 11, --all only: the forced
    # 8-device mesh AOT compiles cost minutes on a small host):
    # mesh-sharded resident serving A/B into a throwaway artifact;
    # exits nonzero unless every row is sharded-resident with a
    # nonzero served-solve collective census (bench_serve.py)
    if "--all" in argv:
        print("=== bench_serve.py --multichip --smoke ===")
        r = subprocess.run(
            [sys.executable, str(here.parent / "bench_serve.py"),
             "--multichip", "--smoke", "--multichip-out",
             "/tmp/MULTICHIP_r06_smoke.json"],
            cwd=here.parent, env=env_ex)
        if r.returncode != 0:
            fails += 1
            print("!!! bench_serve --multichip --smoke FAILED")
    # mixed-precision serving smoke (round 13): refined-from-bf16 vs
    # full-precision serve into a throwaway artifact; exits nonzero
    # unless every row's structural columns hold (half-byte residents,
    # ~2x residents per budget, zero fallbacks on well-conditioned
    # operators)
    print("=== bench_serve.py --mixed --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--mixed", "--smoke", "--mixed-out",
         "/tmp/BENCH_MIXED_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --mixed --smoke FAILED")
    # chaos-soak smoke (round 14): the full serving stack under every
    # injectable fault class at once; exits nonzero unless the
    # invariants hold (zero wrong answers, zero lost futures, request
    # conservation, SLO consistency, fleet fold under snapshot loss)
    # AND the same seed reproduces the identical fault schedule
    print("=== tools/chaos_serve.py --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "tools" / "chaos_serve.py"),
         "--smoke"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! chaos_serve --smoke FAILED")
    # overload shedding A/B smoke (round 14): shedding bounds p99 and
    # queue age under 2x sustained overload; the no-shed arm grows
    print("=== bench_serve.py --overload ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--overload", "--overload-out", "/tmp/BENCH_OVERLOAD_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --overload FAILED")
    # tenant-isolation A/B smoke (round 18): the same 2x overload FIFO
    # vs weighted-fair + quotas — isolation must bound the victim
    # tenant's p99 and quota-reject the aggressor's excess while FIFO
    # starves the victim (bench_serve.py exits nonzero otherwise)
    print("=== bench_serve.py --tenants-fair --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--tenants-fair", "--smoke",
         "--fair-out", "/tmp/BENCH_FAIR_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --tenants-fair FAILED")
    # failover A/B smoke (round 17): kill a fleet member and recover —
    # replication+checkpoint must recover every affected handle with
    # zero refactors while the cold arm pays one per handle (the
    # chaos recovery drill above already exit-gates the fault-injected
    # ladder; this gates the measured A/B artifact)
    print("=== bench_serve.py --failover --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--failover", "--smoke",
         "--failover-out", "/tmp/BENCH_FAILOVER_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --failover FAILED")
    # spectral serving A/B smoke (round 19): resident eigendecomposition
    # applies vs cold factor-per-request — exits nonzero unless every
    # row serves from ONE warmed two-gemm program with zero new
    # compiles after warmup (the structural claim; speeds are CPU smoke)
    print("=== bench_serve.py --spectral --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--spectral", "--smoke",
         "--spectral-out", "/tmp/BENCH_SPECTRAL_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --spectral FAILED")
    # incremental-maintenance A/B smoke (round 20): rank-k update /
    # QR row append+delete vs evict+refactor — exits nonzero unless
    # every row serves its mutations with zero refactors and zero new
    # compiles after warmup, and delta checkpoints ship fewer bytes
    # than full ones (the structural claims; speeds are CPU smoke)
    print("=== bench_serve.py --updates --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--updates", "--smoke",
         "--updates-out", "/tmp/BENCH_UPDATE_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --updates FAILED")
    # tuned-vs-default serving A/B smoke (round 21): both arms resolve
    # through the committed TUNING_r01.json (tuned arm carries config
    # provenance) — exits nonzero unless the tuned arm adds zero new
    # compiles after warmup and both arms answer within tolerance (the
    # structural claims; speedups are CPU smoke, gated only on TPU)
    print("=== bench_serve.py --tuned --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--tuned", "--smoke",
         "--tuned-out", "/tmp/BENCH_TUNED_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --tuned FAILED")
    # telemetry-history + forecasting smoke (round 23): store-on vs
    # store-off serve arms, the record-path micro, and a synthetic
    # periodic holdout — exits nonzero unless the forecaster detects
    # the true period, beats last-value persistence on the held-out
    # cycle, stays silent on an aperiodic control, and every counter
    # conserves exactly through the store's delta samples
    print("=== bench_serve.py --forecast --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "bench_serve.py"),
         "--forecast", "--smoke",
         "--forecast-out", "/tmp/BENCH_FORECAST_smoke.json"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! bench_serve --forecast FAILED")
    # observability smoke: traced served workload -> Chrome-trace JSON
    # (schema-validated), Prometheus text, SVG, and the /metrics HTTP
    # endpoint (tools/obs_dump.py exits nonzero on any export failure —
    # incl. the round-15 tenant/placement sections: attribution
    # conservation, placement-snapshot schema, the /tenants route,
    # tenant_* prom rows, the 2-process attribution/placement fold,
    # and the round-23 /history + /forecast payloads with exact
    # counter conservation through the store)
    print("=== tools/obs_dump.py --smoke ===")
    r = subprocess.run(
        [sys.executable, str(here.parent / "tools" / "obs_dump.py"),
         "--smoke"],
        cwd=here.parent, env=env_ex)
    if r.returncode != 0:
        fails += 1
        print("!!! obs_dump --smoke FAILED")
    # bench-trajectory gate (round 9): every committed BENCH_*.json must
    # parse against the normalized schema, and no tracked TPU series may
    # end in a regression beyond tolerance (tools/bench_gate.py exits
    # nonzero on either; no jax import — runs in-process-cheap)
    for gate_args in (["--check-schema"], []):
        print(f"=== tools/bench_gate.py {' '.join(gate_args) or '(gate)'}"
              " ===")
        r = subprocess.run(
            [sys.executable, str(here.parent / "tools" / "bench_gate.py")]
            + gate_args, cwd=here.parent, env=env)
        if r.returncode != 0:
            fails += 1
            print("!!! bench_gate FAILED")
    return fails


if __name__ == "__main__":
    sys.exit(main())
