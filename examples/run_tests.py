"""Smoke-run all examples (reference: examples/run_tests.py — doubles as
an API regression test)."""

import os
import pathlib
import subprocess
import sys


def main():
    here = pathlib.Path(__file__).parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(here.parent) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    fails = 0
    for ex in sorted(here.glob("ex*.py")):
        print(f"=== {ex.name} ===")
        r = subprocess.run([sys.executable, str(ex)], cwd=here.parent,
                           env=env)
        if r.returncode != 0:
            fails += 1
            print(f"!!! {ex.name} FAILED")
    return fails


if __name__ == "__main__":
    sys.exit(main())
