"""Example 4: the compatibility surfaces — drop-in LAPACK calls,
ScaLAPACK block-cyclic interop, and the generated C API.

Reference analog: examples/ex*_lapack*.c / the lapack_api and
scalapack_api usage patterns (a ScaLAPACK program swaps `-lscalapack`
for the slate shim and keeps its BLACS buffers; here the same data
flows through interop.scalapack and the compat.lapack_api symbols).
"""

import _bootstrap  # noqa: F401  (repo path + platform override)

import os

import ctypes

import numpy as np


def main():
    from slate_tpu.compat import lapack_api as lp
    from slate_tpu.interop import scalapack as sca

    rng = np.random.default_rng(0)
    n, nrhs = 64, 2

    # --- 1. drop-in LAPACK call (dgesv, the s/d/c/z surface) ----------
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, nrhs))
    lu, ipiv, x, info = lp.dgesv(n, nrhs, a.copy(), n, b.copy(), n)
    print("dgesv info", info, "resid",
          float(np.abs(a @ x - b).max()))

    # complex single precision through the same surface
    g = (rng.standard_normal((n, n))
         + 1j * rng.standard_normal((n, n))).astype(np.complex64)
    spd = (g @ g.conj().T / n + 2 * np.eye(n)).astype(np.complex64)
    xz, info = lp.cposv("L", n, nrhs, spd.copy(), n,
                        b.astype(np.complex64), n)
    print("cposv info", info, "resid",
          float(np.abs(spd @ xz - b).max()))

    # --- 2. ScaLAPACK 2D block-cyclic buffers round-trip --------------
    nb, p, q = 16, 2, 2
    import slate_tpu as st
    A = st.from_dense(a, nb=nb)
    locals_ = sca.to_scalapack(A, p, q)   # per-rank BLACS-layout buffers
    print("scalapack locals:", [loc.shape for loc in locals_])
    A2 = sca.from_scalapack(locals_, n, n, nb, p, q)
    # compare against the stored values (from_dense may have cast to
    # f32 when x64 is off) — the pack/unpack itself is bit-exact
    print("block-cyclic round-trip exact:",
          bool(np.abs(A2.to_numpy()
                      - np.asarray(A.to_numpy(), np.float64)).max()
               == 0.0))

    # --- 3. the generated C API, loaded in-process --------------------
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    so = os.path.join(repo, "native", "libslate_tpu_capi.so")
    if os.path.exists(so):
        lib = ctypes.CDLL(so)
        i64 = ctypes.c_int64
        af = np.asfortranarray(a.astype(np.float32))
        bf = np.asfortranarray(b.astype(np.float32))
        ipiv = np.zeros(n, np.int64)
        lib.slate_tpu_sgesv.restype = i64
        rc = lib.slate_tpu_sgesv(
            i64(n), i64(nrhs), af.ctypes.data_as(ctypes.c_void_p), i64(n),
            ipiv.ctypes.data_as(ctypes.c_void_p),
            bf.ctypes.data_as(ctypes.c_void_p), i64(n))
        print("C slate_tpu_sgesv rc", rc, "resid",
              float(np.abs(a.astype(np.float32) @ bf - b).max()))
    else:
        print("C API library not built (run make -C native); skipping")


if __name__ == "__main__":
    main()
