"""Example 2: linear solvers — Cholesky, LU, least squares, mixed
precision.

Reference analog: examples/ex05_blas.cc, ex06_linear_system_lu.cc,
ex07_linear_system_cholesky.cc, ex09_least_squares.cc.
"""

import _bootstrap  # noqa: F401  (repo path + platform override)

import jax.numpy as jnp
import numpy as np

import slate_tpu as st
from slate_tpu.core.types import Options, MethodLU, Uplo
from slate_tpu.matgen import random_spd


def main():
    n, nrhs = 512, 8
    rng = np.random.default_rng(0)

    # SPD solve (posv = potrf + potrs)
    a = np.asarray(random_spd(n, dtype=jnp.float32, seed=1))
    b = rng.standard_normal((n, nrhs)).astype(np.float32)
    A = st.hermitian(np.tril(a), nb=128, uplo=Uplo.Lower)
    B = st.from_dense(b, nb=128)
    X, info = st.posv(A, B)
    print("posv info:", int(info),
          "residual:", float(np.abs(b - a @ X.to_numpy()).max()))

    # general LU solve with method selection (P10 Method dispatch)
    g = rng.standard_normal((n, n)).astype(np.float32) + 4 * np.eye(n, dtype=np.float32)
    G = st.from_dense(g, nb=128)
    for method in (MethodLU.PartialPiv, MethodLU.CALU, MethodLU.RBT):
        X, info = st.gesv(G, B, Options(method_lu=method))
        print(f"gesv[{method.value}] residual:",
              float(np.abs(b - g @ X.to_numpy()).max()))

    # least squares (QR)
    m = 1024
    am = rng.standard_normal((m, n)).astype(np.float32)
    bm = rng.standard_normal((m, nrhs)).astype(np.float32)
    Xl = st.gels(st.from_dense(am, nb=128), st.from_dense(bm, nb=128))
    print("gels normal-eq residual:",
          float(np.abs(am.T @ (am @ Xl.to_numpy()[:n] - bm)).max()))

    # mixed-precision iterative refinement: bf16/f32 factor + refine
    A64 = st.hermitian(np.tril(a).astype(np.float64), nb=128,
                       uplo=Uplo.Lower)
    B64 = st.from_dense(b.astype(np.float64), nb=128)
    try:
        X, info, iters = st.posv_mixed(A64, B64, factor_dtype=jnp.float32)
        print("posv_mixed iters:", iters)
    except Exception as e:  # f64 path needs x64 enabled (CPU)
        print("posv_mixed skipped:", e)


if __name__ == "__main__":
    main()
