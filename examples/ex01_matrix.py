"""Example 1: creating and distributing tiled matrices.

Reference analog: examples/ex01_matrix.cc + ex02_conversion.cc.
"""

import _bootstrap  # noqa: F401  (repo path + platform override)

import jax.numpy as jnp
import numpy as np

import slate_tpu as st
from slate_tpu.core.grid import ProcessGrid
from slate_tpu.core.types import Norm, Uplo


def main():
    # build from dense data; nb is the tile size
    a = np.arange(30.0, dtype=np.float32).reshape(5, 6)
    A = st.from_dense(a, nb=4)
    print("A:", A.shape, "tiles:", A.mt, "x", A.nt, "dtype:", A.dtype)

    # transpose views are zero-copy metadata flips
    print("A.T shape:", A.T.shape)

    # distribute over all local devices (p x q mesh over ICI)
    grid = ProcessGrid.create()
    Ad = st.from_dense(a, nb=4, grid=grid)
    print("distributed over", grid.p, "x", grid.q, "grid")

    # structured kinds: hermitian/symmetric/triangular/band wrap the
    # stored triangle or band
    h = np.tril(np.ones((4, 4), np.float32)) + 3 * np.eye(4, dtype=np.float32)
    H = st.hermitian(h, nb=2, uplo=Uplo.Lower)
    print("hermitian one-norm:", float(st.norm(H, Norm.One)))

    # deterministic test matrices (identical under any distribution)
    G = st.matgen.generate_matrix("svd_geo", 8, 8, jnp.float32, cond=100.0)
    print("matgen svd_geo cond:", float(jnp.linalg.cond(G)))


if __name__ == "__main__":
    main()
