"""Example 3: eigensolvers and SVD.

Reference analog: examples/ex10_svd.cc, ex11_hermitian_eig.cc,
ex12_generalized_hermitian_eig.cc.
"""

import _bootstrap  # noqa: F401  (repo path + platform override)

import jax.numpy as jnp
import numpy as np

import slate_tpu as st
from slate_tpu.core.types import Uplo
from slate_tpu.matgen import generate_matrix, random_spd


def main():
    n = 256
    a = np.asarray(generate_matrix("heev_arith", n, n, jnp.float32,
                                   cond=100.0))
    A = st.hermitian(np.tril(a), nb=64, uplo=Uplo.Lower)
    w, Z = st.heev(A)
    z = Z.to_numpy()
    print("heev residual:",
          float(np.abs(a @ z - z * np.asarray(w)[None, :]).max()))

    # generalized: A x = lambda B x
    b = np.asarray(random_spd(n, dtype=jnp.float32, seed=2))
    Bm = st.hermitian(np.tril(b), nb=64, uplo=Uplo.Lower)
    wg, Xg, info_g = st.hegv(A, Bm)
    xg = Xg.to_numpy()
    print("hegv residual:",
          float(np.abs(a @ xg - (b @ xg) * np.asarray(wg)[None, :]).max()))

    # SVD with vectors
    m = 384
    g = np.asarray(generate_matrix("svd_geo", m, n, jnp.float32, cond=50.0))
    s, U, V = st.svd(st.from_dense(g, nb=64), want_vectors=True)
    recon = (U.to_numpy() * np.asarray(s)[None, :]) @ V.to_numpy().T
    print("svd recon rel err:",
          float(np.linalg.norm(g - recon) / np.linalg.norm(g)))


if __name__ == "__main__":
    main()
