"""Shared example bootstrap: make the repo importable when run from
anywhere and honor JAX_PLATFORMS despite the axon sitecustomize
(compat.platform docstring has the full story)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from slate_tpu.compat.platform import apply_env_platforms  # noqa: E402

apply_env_platforms()
