"""Distributed layout + collective-insertion tests (8-device CPU mesh).

VERDICT round-1 items 5/6: the block-cyclic storage mode must be real
(device tile ownership matching the ScaLAPACK map), factorizations must
keep outputs sharded (not silently replicated) and agree with the 1×1
grid bit-for-bit at the logical level, and collective ops must actually
appear in the compiled HLO (no "GSPMD silently replicates" regression).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.grid import cyclic_permutation
from slate_tpu.core.types import MethodGemm

RNG = np.random.default_rng(11)


def _spd(n, dtype=np.float64):
    a = RNG.standard_normal((n, n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


# -- block-cyclic storage ---------------------------------------------------

def test_cyclic_shard_roundtrip(grid2x4):
    m, n, nb = 144, 208, 16
    a = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=nb).shard(grid2x4, cyclic=True)
    assert A.cyclic
    np.testing.assert_array_equal(A.to_numpy(), a)
    # re-shard back to contiguous
    B = A.shard(grid2x4)
    assert not B.cyclic
    np.testing.assert_array_equal(B.to_numpy(), a)


def test_cyclic_device_ownership(grid2x4):
    """Device (pi, qi) must hold exactly the ScaLAPACK cyclic tile set
    {(i, j) : i mod p == pi, j mod q == qi}."""
    n, nb = 128, 16
    p, q = grid2x4.p, grid2x4.q
    a = np.arange(n * n, dtype=np.float64).reshape(n, n)
    A = st.from_dense(a, nb=nb).shard(grid2x4, cyclic=True)
    mtp = A.data.shape[0] // nb
    ntp = A.data.shape[1] // nb
    perm_r = cyclic_permutation(mtp, p)
    perm_c = cyclic_permutation(ntp, q)
    for shard in A.data.addressable_shards:
        r0, c0 = shard.index[0].start or 0, shard.index[1].start or 0
        local = np.asarray(shard.data)
        # every tile in this shard must be a cyclic-owned logical tile
        for it in range(local.shape[0] // nb):
            for jt in range(local.shape[1] // nb):
                gi = perm_r[r0 // nb + it]
                gj = perm_c[c0 // nb + jt]
                np.testing.assert_array_equal(
                    local[it * nb:(it + 1) * nb, jt * nb:(jt + 1) * nb],
                    a[gi * nb:(gi + 1) * nb, gj * nb:(gj + 1) * nb])
                # and ownership must follow the ScaLAPACK map
                dev_row = (r0 // nb) // (mtp // p)
                dev_col = (c0 // nb) // (ntp // q)
                assert gi % p == dev_row and gj % q == dev_col


@pytest.mark.slow  # ~14 s 3-factorization sweep (round-10 headroom);
# mesh correctness stays pinned by test_grid_matches_single_device
def test_factorizations_accept_cyclic_input(grid2x4):
    n, nb = 192, 16
    a = _spd(n)
    rhs = RNG.standard_normal((n, 3))
    A1 = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    Ac = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower).shard(
        grid2x4, cyclic=True)
    X1, i1 = st.posv(A1, st.from_dense(rhs, nb=nb))
    Xc, ic = st.posv(Ac, st.from_dense(rhs, nb=nb, grid=grid2x4))
    assert int(i1) == int(ic) == 0
    np.testing.assert_allclose(Xc.to_numpy(), X1.to_numpy(), rtol=1e-12,
                               atol=1e-12)


# -- sharded outputs + 1x1-grid agreement ----------------------------------

# getrf/geqrf arms ride the slow lane (round-20 tier-1 budget: each is
# its own n=256 mesh factor compile); the potrf arm keeps the
# outputs-stay-sharded contract tier-1, and grid_matches_single_device
# pins mesh correctness for all three routines
@pytest.mark.parametrize("routine", [
    "potrf",
    pytest.param("getrf", marks=pytest.mark.slow),
    pytest.param("geqrf", marks=pytest.mark.slow),
])
def test_factorization_outputs_stay_sharded(grid2x4, routine):
    n, nb = 256, 32
    if routine == "potrf":
        a = _spd(n)
        A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower,
                         grid=grid2x4)
        out, _ = st.potrf(A)
        data = out.data
    elif routine == "getrf":
        a = RNG.standard_normal((n, n))
        A = st.from_dense(a, nb=nb, grid=grid2x4)
        out, _, _ = st.getrf(A)
        data = out.data
    else:
        a = RNG.standard_normal((n + 64, n))
        A = st.from_dense(a, nb=nb, grid=grid2x4)
        qr = st.geqrf(A)
        data = qr.vr
    assert len(data.sharding.device_set) == 8, \
        f"{routine}: output collapsed to {data.sharding.device_set}"
    assert not data.sharding.is_fully_replicated, \
        f"{routine}: output silently replicated"


@pytest.mark.parametrize("routine", [
    "potrf",
    # the getrf arm (~9 s) rides the slow lane (round-10 headroom):
    # mesh getrf stays pinned by the nb=64 perm-regression test and
    # the fastpaths mesh pivot-fusion bit-identity test; the geqrf arm
    # (~12 s, its own n=256 mesh factor compile) follows in round 22 —
    # mesh geqrf stays pinned by test_qr.py::test_geqrf_jit_and_grid
    pytest.param("getrf", marks=pytest.mark.slow),
    pytest.param("geqrf", marks=pytest.mark.slow)])
def test_grid_matches_single_device(grid2x4, routine):
    n, nb = 256, 32
    if routine == "potrf":
        a = _spd(n)
        M1 = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
        Mg = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower,
                          grid=grid2x4)
        r1 = st.potrf(M1)[0].to_numpy()
        rg = st.potrf(Mg)[0].to_numpy()
    elif routine == "getrf":
        a = RNG.standard_normal((n, n))
        r1 = st.getrf(st.from_dense(a, nb=nb))[0].to_numpy()
        rg = st.getrf(st.from_dense(a, nb=nb, grid=grid2x4))[0].to_numpy()
    else:
        a = RNG.standard_normal((n + 64, n))
        r1 = st.geqrf(st.from_dense(a, nb=nb)).vr
        rg = st.geqrf(st.from_dense(a, nb=nb, grid=grid2x4)).vr
        r1, rg = np.asarray(r1), np.asarray(rg)
    np.testing.assert_allclose(rg, r1, rtol=1e-13, atol=1e-13)


# -- collective insertion asserted on compiled HLO --------------------------

_COLLECTIVES = ("all-gather", "all-reduce", "collective-permute",
                "reduce-scatter", "all-to-all")


def _collective_count(fn, *args):
    txt = jax.jit(fn).lower(*args).compile().as_text()
    return sum(txt.count(c) for c in _COLLECTIVES)


def test_hlo_gemm_has_collectives(grid2x4):
    n, nb = 128, 16
    A = st.from_dense(RNG.standard_normal((n, n)), nb=nb, grid=grid2x4)
    B = st.from_dense(RNG.standard_normal((n, n)), nb=nb, grid=grid2x4)
    C = st.from_dense(np.zeros((n, n)), nb=nb, grid=grid2x4)

    def f(A, B, C):
        return st.gemm(1.0, A, B, 0.0, C).data

    assert _collective_count(f, A, B, C) > 0


def test_hlo_potrf_has_collectives(grid2x4):
    # shares ONE mesh-potrf compile with the two schedule tests below
    # (_scheduled_potrf_entry caches it — the compile is ~40 s here)
    hlo, _ = _scheduled_potrf_entry(grid2x4)
    assert sum(hlo.count(c) for c in _COLLECTIVES) > 0, \
        "potrf compiled without any collective: GSPMD replicated the work"


def test_hlo_hemm_trsm_have_collectives(grid2x4):
    n, nb = 128, 16
    a = _spd(n)
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower, grid=grid2x4)
    L = st.triangular(np.tril(a), nb=nb, uplo=st.Uplo.Lower, grid=grid2x4)
    B = st.from_dense(RNG.standard_normal((n, n)), nb=nb, grid=grid2x4)

    def f_hemm(A, B):
        return st.hemm(st.Side.Left, 1.0, A, B, 0.0, B).data

    def f_trsm(L, B):
        return st.trsm(st.Side.Left, 1.0, L, B).data

    assert _collective_count(f_hemm, A, B) > 0
    assert _collective_count(f_trsm, L, B) > 0


def test_hlo_rank_k_family_has_collectives(grid2x4):
    """VERDICT r2 weak #8: syrk/herk/syr2k/her2k must carry the same grid
    constraints as gemm so standalone trailing-update calls shard rather
    than replicate (reference src/internal/internal_herk.cc)."""
    n, k, nb = 128, 64, 16
    a = RNG.standard_normal((n, k))
    b = RNG.standard_normal((n, k))
    spd = _spd(n)
    A = st.from_dense(a, nb=nb, grid=grid2x4)
    B = st.from_dense(b, nb=nb, grid=grid2x4)
    Ch = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower, grid=grid2x4)
    Cs = st.symmetric(np.tril(spd), nb=nb, uplo=st.Uplo.Lower, grid=grid2x4)

    def f_herk(A, C):
        return st.herk(-1.0, A, 1.0, C).data

    def f_syrk(A, C):
        return st.syrk(-1.0, A, 1.0, C).data

    def f_her2k(A, B, C):
        return st.her2k(-1.0, A, B, 1.0, C).data

    def f_syr2k(A, B, C):
        return st.syr2k(-1.0, A, B, 1.0, C).data

    assert _collective_count(f_herk, A, Ch) > 0, "herk replicated"
    assert _collective_count(f_syrk, A, Cs) > 0, "syrk replicated"
    assert _collective_count(f_her2k, A, B, Ch) > 0, "her2k replicated"
    assert _collective_count(f_syr2k, A, B, Cs) > 0, "syr2k replicated"

    # outputs stay sharded and match the 1x1 grid
    out = st.herk(-1.0, A, 1.0, Ch)
    assert not out.data.sharding.is_fully_replicated
    ref = st.herk(-1.0, st.from_dense(a, nb=nb),
                  1.0, st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower))
    np.testing.assert_allclose(out.to_numpy(), ref.to_numpy(),
                               rtol=1e-12, atol=1e-12)


def test_dist_panel_maxloc_small(grid2x4):
    """Tier-1 sibling of test_dist_panel_maxloc (round-22 budget): the
    same shard_map maxloc-panel contract — LU correctness under the
    pivot collective + collectives present in the compiled HLO — on a
    half-width panel (w=32 halves the unrolled column loop that
    dominates the compile)."""
    import jax.numpy as jnp
    from slate_tpu.parallel.panel import dist_panel_getrf

    rng = np.random.default_rng(22)
    m, w = 256, 32
    a = jnp.asarray(rng.standard_normal((m, w)))
    lu, perm, info = dist_panel_getrf(a, grid2x4)
    lu, perm = np.asarray(lu), np.asarray(perm)
    assert int(info) == 0
    L = np.tril(lu, -1) + np.concatenate(
        [np.eye(w), np.zeros((m - w, w))])
    U = np.triu(lu[:w])
    assert np.abs(np.asarray(a)[perm] - L @ U).max() < 1e-12
    assert _collective_count(lambda x: dist_panel_getrf(x, grid2x4),
                             a) > 0, \
        "maxloc panel compiled without collectives"


@pytest.mark.slow  # ~11 s: the w=64 panel compile + the n=256 mesh
# getrf driver-site agreement are each their own compiles (round-22
# tier-1 budget); tier-1 sibling test_dist_panel_maxloc_small keeps
# the maxloc-panel contract in tier-1
def test_dist_panel_maxloc(grid2x4):
    """VERDICT r3 #7: the explicit shard_map panel (per-column maxloc
    pivot collective + masked-psum row swaps, parallel/panel.py) must
    match the GSPMD panel and compile with collectives; getrf routes to
    it under Options.lu_dist_panel."""
    import jax.numpy as jnp
    from slate_tpu.parallel.panel import dist_panel_getrf

    rng = np.random.default_rng(21)
    m, w = 512, 64
    a = jnp.asarray(rng.standard_normal((m, w)))
    lu, perm, info = dist_panel_getrf(a, grid2x4)
    lu, perm = np.asarray(lu), np.asarray(perm)
    assert int(info) == 0
    L = np.tril(lu, -1) + np.concatenate(
        [np.eye(w), np.zeros((m - w, w))])
    U = np.triu(lu[:w])
    assert np.abs(np.asarray(a)[perm] - L @ U).max() < 1e-12

    assert _collective_count(lambda x: dist_panel_getrf(x, grid2x4),
                             a) > 0, \
        "maxloc panel compiled without collectives"

    # driver call site: getrf(lu_dist_panel=True) agrees with default
    n, nb = 256, 32
    A = st.from_dense(rng.standard_normal((n, n)), nb=nb, grid=grid2x4)
    lu0 = st.getrf(A)[0].to_numpy()
    lu1 = st.getrf(A, st.Options(lu_dist_panel=True))[0].to_numpy()
    np.testing.assert_allclose(lu1, lu0, rtol=1e-10, atol=1e-10)


# -- P3 static evidence: scheduled-HLO collective/compute interleaving ------

import re


_SCHED_CACHE = {}


def _scheduled_potrf_entry(grid, n=256, nb=32):
    """Scheduled HLO (post-optimization, is_scheduled=true) of mesh
    potrf's entry computation, line-classified: 'C' collective,
    'X' compute (fusion/dot/custom-call). The compile is cached across
    the two schedule tests — it is the expensive part."""
    if (n, nb) in _SCHED_CACHE:
        return _SCHED_CACHE[(n, nb)]
    a = _spd(n)
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower, grid=grid)

    def f(A):
        return st.potrf(A)[0].data

    hlo = jax.jit(f).lower(A).compile().as_text()
    m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", hlo, re.S | re.M)
    assert m, "no ENTRY computation in compiled HLO"
    coll = ("all-gather", "all-reduce", "collective-permute",
            "reduce-scatter", "all-to-all")
    comp = ("fusion(", " dot(", "custom-call(", "convolution(")
    seq = []
    for ln in m.group(1).splitlines():
        if any(c + "(" in ln or c + "-start(" in ln or c + "-done(" in ln
               for c in coll):
            seq.append("C")
        elif any(c in ln for c in comp):
            seq.append("X")
    _SCHED_CACHE[(n, nb)] = (hlo, seq)
    return hlo, seq


def test_mesh_potrf_schedule_interleaves_collectives_with_updates(grid2x4):
    """VERDICT r5 'Missing #6' / ISSUE 2 P3 static evidence: in mesh
    potrf's SCHEDULED HLO, collective ops must be interleaved with the
    trailing-update compute (fusions/dots) throughout the instruction
    stream — the compiler-scheduled analog of the reference's lookahead
    (panel broadcast overlapping trailing work, src/potrf.cc:84-195) —
    rather than clumped into a prologue/epilogue. The 8-step n=256
    factorization must show at least 2·nt separate collective runs
    embedded in compute."""
    n, nb = 256, 32
    hlo, seq = _scheduled_potrf_entry(grid2x4, n, nb)
    assert "is_scheduled=true" in hlo, "compiled module is not scheduled"
    ncoll = seq.count("C")
    ncomp = seq.count("X")
    assert ncoll > 0 and ncomp > 0
    runs = sum(1 for i, s in enumerate(seq)
               if s == "C" and (i == 0 or seq[i - 1] != "C"))
    assert runs >= 2 * (n // nb), (
        f"collectives clumped: {ncoll} collectives in only {runs} runs "
        f"against {ncomp} compute ops")


def test_mesh_potrf_async_collective_start_done_interleaving(grid2x4):
    """The stronger TPU-shaped assertion: async collective start/done
    pairs with independent trailing-update compute scheduled BETWEEN
    start and done (true latency hiding). XLA:CPU lowers collectives
    synchronously (zero *-start/done pairs — verified in PERF.md round
    4), so this skips off-TPU and runs on a TPU-attached session."""
    hlo, _ = _scheduled_potrf_entry(grid2x4)
    starts = re.findall(r"%(\S*?(?:all-gather|all-reduce|"
                        r"collective-permute)-start\S*)\s*=", hlo)
    if not starts:
        pytest.skip("backend lowers collectives synchronously (no "
                    "async start/done pairs in scheduled HLO); the "
                    "interleaving assertion needs a TPU backend")
    m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", hlo, re.S | re.M)
    lines = m.group(1).splitlines()
    hidden = 0
    open_since = {}  # start instruction NAME -> schedule index
    for i, ln in enumerate(lines):
        if "-start(" in ln and "=" in ln:
            open_since[ln.split("=")[0].strip().lstrip("%")] = i
        elif "-done(" in ln:
            # a done op references ITS start by name as an operand;
            # the (?!\d) guard keeps %op.1 from matching %op.10
            for sname, j in list(open_since.items()):
                if re.search(re.escape(sname) + r"(?!\d)", ln):
                    if any("fusion(" in s or " dot(" in s
                           for s in lines[j + 1:i]):
                        hidden += 1
                    open_since.pop(sname)
                    break
    assert hidden > 0, ("no compute scheduled inside any async "
                        "collective start/done window")


# -- explicit SUMMA routing -------------------------------------------------

def test_method_gemm_summa_routing(grid2x4):
    n, nb = 128, 16
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, n))
    c = RNG.standard_normal((n, n))
    A = st.from_dense(a, nb=nb, grid=grid2x4)
    B = st.from_dense(b, nb=nb, grid=grid2x4)
    C = st.from_dense(c, nb=nb, grid=grid2x4)
    out = st.gemm(2.0, A, B, -1.0, C,
                  st.Options(method_gemm=MethodGemm.SUMMA))
    np.testing.assert_allclose(out.to_numpy(), 2.0 * a @ b - c,
                               rtol=1e-10, atol=1e-10)


@pytest.mark.slow
def test_hlo_he2hb_has_collectives_and_heev_2stage_runs(grid2x4):
    """VERDICT r4 weak #7: the two-stage heev's stage-1 (he2hb) exists
    for its mesh sharding — assert its compiled HLO actually carries
    collectives on the 2x4 grid, and run the full two-stage eigensolver
    on the mesh end to end. Slow (round-20 tier-1 budget: the full
    n=256 2x4 two-stage pipeline compile). Tier-1 sibling:
    test_spectral.py::test_mesh_census_collective_bytes pins nonzero
    collective bytes for the staged he2hb on a 2x2 grid through the
    Session census."""
    from slate_tpu.core.types import MethodEig, Options

    n, nb = 256, 32
    a = _spd(n)
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower, grid=grid2x4)

    def f_stage1(A):
        band, refl = st.he2hb(A)
        return band.data

    assert _collective_count(f_stage1, A) > 0, \
        "he2hb compiled without any collective: stage-1 replicated"

    w, Z = st.heev(A, Options(method_eig=MethodEig.DC,
                              eig_stage1="two_stage"))
    z = np.asarray(Z.to_numpy(), np.float64)
    wn = np.asarray(w, np.float64)
    res = np.abs(a @ z - z * wn[None, :]).max() / max(np.abs(wn).max(), 1)
    orth = np.abs(z.T @ z - np.eye(n)).max()
    assert res < 5e-5 and orth < 5e-5, (res, orth)
