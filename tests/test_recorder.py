"""Flight recorder + decision journal + incident capture (round 22).

The black-box contract: every counted runtime reflex journals exactly
one structured DecisionEvent (journal-count == counter delta, absolute
equality per kind), finished spans and gauge samples ride bounded
always-on rings, and anomalous transitions (watchdog flag, SLO breach,
breaker open, fault firing) materialize rate-limited, deduped,
crash-safe ``slate_tpu.incident.v1`` snapshots — while the DISABLED
path stays one is-None check with zero allocation.
"""

import gc
import importlib.util
import json
import os
import threading
import tracemalloc

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.obs.events import (DECISION_KINDS, DIGEST_FIELDS,
                                  INCIDENT_KEYS, INCIDENT_SCHEMA,
                                  JOURNAL_SCHEMA, KIND_COUNTERS,
                                  OUTCOME_COUNTERS, DecisionEvent,
                                  journal_digest, validate_incident)
from slate_tpu.obs.recorder import (DecisionJournal, FlightRecorder,
                                    IncidentCapture, Recorder)
from slate_tpu.obs.watchdog import Watchdog
from slate_tpu.runtime import Batcher, Metrics, Session

RNG = np.random.default_rng(22)
N, NB = 32, 16

_ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "_bench_gate", os.path.join(_ROOT, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lu_session(**kw):
    sess = Session(**kw)
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    h = sess.register(st.from_dense(a, nb=NB), op="lu")
    return sess, h, a


def _synthetic_baseline(best=100.0):
    return {"schema": "slate_tpu.baseline_series.v1", "tolerance": 0.10,
            "series": [{"kind": "serve", "metric": "serve.solves_per_sec",
                        "platform": "tpu", "n": N, "batch": None,
                        "op": None, "dtype": None,
                        "direction": "higher", "best": best}]}


def _assert_parity(sess, rec):
    """Absolute equality per kind — including the zero == zero kinds:
    a counter that moved without a journal entry (or vice versa) is a
    seam that forgot the other half."""
    for kind, counter in sorted(KIND_COUNTERS.items()):
        assert rec.journal.count(kind) == sess.metrics.get(counter), (
            f"{kind}: journal {rec.journal.count(kind)} != "
            f"counter {counter}={sess.metrics.get(counter)}")


# -- the tables are closed ---------------------------------------------------


def test_every_decision_kind_maps_to_exactly_one_counter():
    """DECISION_KINDS and KIND_COUNTERS are the same set: a new reflex
    kind without a counter mapping (or a mapping for a kind nobody can
    emit) fails here before it ships unparityable."""
    assert set(DECISION_KINDS) == set(KIND_COUNTERS)
    assert len(set(KIND_COUNTERS.values())) == len(KIND_COUNTERS), \
        "two kinds sharing one counter cannot both hold parity"
    for (kind, _outcome), counter in OUTCOME_COUNTERS.items():
        assert kind in DECISION_KINDS
        assert counter not in KIND_COUNTERS.values(), (
            "an outcome counter that is also a kind counter would be "
            "double-counted by the parity check")
    assert "ts" not in DIGEST_FIELDS and "inputs" not in DIGEST_FIELDS


def test_journal_ring_bounded_counts_monotone():
    """The ring drops oldest events; the per-kind counts do NOT — the
    parity invariant survives eviction from the ring."""
    j = DecisionJournal(capacity=4)
    for i in range(10):
        j.record("eviction", handle=f"h{i}", outcome="explicit")
    assert len(j.events()) == 4
    assert j.count("eviction") == 10
    p = j.payload()
    assert p["schema"] == JOURNAL_SCHEMA
    assert p["recorded"] == 10 and p["dropped"] == 6
    assert [e["handle"] for e in p["events"]] == ["h6", "h7", "h8", "h9"]


def test_multi_victim_decision_counts_as_n():
    """One shed wave / clear_cache is ONE decision with count=N; the
    journal count (what parity compares) advances by N."""
    j = DecisionJournal()
    j.record("shed", outcome="deadline", count=3)
    assert j.count("shed") == 3
    assert len(j.events()) == 1


def test_digest_is_wallclock_free():
    """Two journals recording the same decisions at different times
    digest identically (DIGEST_FIELDS exclude ts/inputs/trace ids) —
    the same-seed chaos reproducibility gate depends on this."""
    rows = [("eviction", "h0", "budget"), ("breaker_open", "h1", "open"),
            ("shed", None, "deadline")]
    digests = []
    for _ in range(2):
        j = DecisionJournal()
        for kind, handle, outcome in rows:
            j.record(kind, handle=handle, outcome=outcome,
                     inputs={"noise": RNG.standard_normal()})
        digests.append(j.digest())
    assert digests[0] == digests[1]
    # and it is order-sensitive: a reordered cascade is a different story
    j2 = DecisionJournal()
    for kind, handle, outcome in reversed(rows):
        j2.record(kind, handle=handle, outcome=outcome)
    assert j2.digest() != digests[0]


# -- journal/counter parity through the real seams ---------------------------


def test_session_reflex_parity():
    """Eviction reflexes through the real Session seams: explicit
    evict, unregister-with-resident, clear_cache (one decision,
    count=n) — every KIND_COUNTERS pair holds with absolute equality,
    including the untouched zero kinds."""
    sess, h, a = _lu_session()
    rec = sess.enable_recorder()
    assert sess.enable_recorder() is rec  # idempotent
    h2 = sess.register(st.from_dense(
        RNG.standard_normal((N, N)) + N * np.eye(N), nb=NB), op="lu")
    b = RNG.standard_normal(N)
    sess.solve(h, b)
    sess.solve(h2, b)
    assert sess.evict(h)
    sess.factor(h)
    sess.clear_cache()
    sess.unregister(h2)  # resident already gone: no double count
    _assert_parity(sess, rec)
    ev = [e for e in rec.journal.events() if e.kind == "eviction"]
    assert ev[0].outcome == "explicit" and ev[0].handle == str(h)
    wave = [e for e in ev if e.outcome == "clear_cache"]
    assert len(wave) == 1 and wave[0].count == 2
    assert rec.journal.count("eviction") == sess.metrics.get("evictions")


def test_deadline_expiry_parity_through_batcher():
    """The serving seam: an already-expired request fails fast AND
    journals one deadline_expired decision per victim."""
    sess, h, a = _lu_session()
    rec = sess.enable_recorder()
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    ok = batcher.submit(h, RNG.standard_normal(N))
    dead = batcher.submit(h, RNG.standard_normal(N), timeout_s=-1.0)
    batcher.flush()
    assert ok.result(timeout=30) is not None
    with pytest.raises(Exception):
        dead.result(timeout=30)
    assert sess.metrics.get("deadline_expired_total") == 1
    _assert_parity(sess, rec)
    (e,) = [e for e in rec.journal.events()
            if e.kind == "deadline_expired"]
    assert e.outcome == "failed_fast" and e.handle == str(h)


# -- incident capture --------------------------------------------------------


def _capture(dir=None, clock=None, **kw):
    j = DecisionJournal()
    kw.setdefault("metrics", Metrics())
    cap = IncidentCapture(j, FlightRecorder(), dir=dir,
                          **({"clock": clock} if clock else {}), **kw)
    return j, cap


def test_incident_dedup_then_rate_limit_then_window_expiry():
    """Same (reason, key) inside the window -> deduped; a DIFFERENT
    key inside the global rate limit -> rate-limited; past the windows
    both capture again. All three outcomes are counted."""
    t = {"now": 0.0}
    j, cap = _capture(clock=lambda: t["now"],
                      rate_limit_s=5.0, dedup_window_s=60.0)
    m = cap.metrics
    assert cap.trigger("fault", key="dispatch") is not None
    t["now"] = 1.0
    assert cap.trigger("fault", key="dispatch") is None  # dedup
    assert cap.trigger("breaker_open", key="h0") is None  # rate limit
    t["now"] = 10.0
    assert cap.trigger("breaker_open", key="h0") is not None
    t["now"] = 70.0  # dedup window expired for the first key
    assert cap.trigger("fault", key="dispatch") is not None
    assert m.get("incidents_captured_total") == 3
    assert m.get("incidents_deduped_total") == 1
    assert m.get("incidents_rate_limited_total") == 1
    assert len(cap.incidents()) == 3


def test_incident_carries_implicated_handle_slice():
    """The tail window can be dominated by other traffic; the
    implicated handle's decisions ride along anyway, merged in seq
    order."""
    j, cap = _capture(journal_slice=8)
    j.record("eviction", handle="victim", outcome="budget")
    for i in range(50):
        j.record("shed", handle=f"noise{i}", outcome="deadline")
    doc = cap.trigger("watchdog_anomaly", key="s", handle="victim")
    handles = [e["handle"] for e in doc["journal"]["events"]]
    assert "victim" in handles
    seqs = [e["seq"] for e in doc["journal"]["events"]]
    assert seqs == sorted(seqs)
    assert validate_incident(doc) == []
    assert doc["journal"]["counts"]["shed"] == 50


def test_incident_publish_is_crash_safe(tmp_path):
    """On-disk snapshots go through tmp + os.replace: after a capture
    the directory holds exactly the finished document (no .tmp
    residue), loadable and identical to the in-ring copy."""
    d = str(tmp_path / "incidents")
    j, cap = _capture(dir=d)
    doc = cap.trigger("fault", key="dispatch")
    files = os.listdir(d)
    assert len(files) == 1 and files[0].endswith(".json")
    assert not [f for f in files if ".tmp" in f]
    with open(os.path.join(d, files[0])) as f:
        assert json.load(f) == json.loads(json.dumps(doc, default=repr))


def test_provider_failure_never_raises_into_the_seam():
    """A broken section provider (dead numerics hook, crashed quota
    payload) must not turn the incident path into a new failure mode:
    the section degrades to an error string, the document still
    validates."""
    j, cap = _capture()
    cap.providers["numerics"] = lambda: 1 / 0
    doc = cap.trigger("fault", key="x")
    assert "ZeroDivisionError" in doc["numerics"]["error"]
    assert validate_incident(doc) == []


def test_watchdog_anomaly_during_served_workload_captures_one_incident(
        tmp_path):
    """THE acceptance path: a served workload, an injected watchdog
    anomaly -> exactly ONE schema-valid incident containing the
    implicated handle's journal slice — and repeated check() scrapes
    (the restorm case) mint nothing new."""
    sess, h, a = _lu_session()
    rec = sess.enable_recorder(incident_dir=str(tmp_path / "inc"))
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    batcher.flush()
    for f in futs:
        f.result(timeout=30)
    sess.evict(h)  # the implicated handle's decision, pre-anomaly
    wd = Watchdog(baseline=_synthetic_baseline(best=1e12),
                  metrics=sess.metrics)
    wd.add_listener(rec.watchdog_listener)
    wd.observe("serve.solves_per_sec", 1.0, "tpu", n=N, kind="serve")
    assert not wd.check()["ok"]
    for _ in range(5):  # scrape loop: still anomalous, still ONE
        wd.check()
    assert sess.metrics.get("incidents_captured_total") == 1
    incidents = rec.incidents.incidents()
    assert len(incidents) == 1
    doc = incidents[0]
    assert validate_incident(doc) == []
    assert doc["reason"] == "watchdog_anomaly"
    assert doc["context"]["metric"] == "serve.solves_per_sec"
    handles = {e["handle"] for e in doc["journal"]["events"]}
    assert str(h) in handles
    assert doc["metrics"]["counters"].get("evictions") == 1
    on_disk = os.listdir(str(tmp_path / "inc"))
    assert len(on_disk) == 1
    _assert_parity(sess, rec)


def test_slo_breach_transition_captures_incident():
    """An SLO breach transition triggers capture at the source (the
    tracker's _breached latch), so scrape-driven publish loops cannot
    restorm; recovery re-arms."""
    from slate_tpu.obs.slo import Objective
    sess, h, a = _lu_session()
    rec = sess.enable_recorder()
    sess.enable_slo((Objective("errors", "error_rate", 0.99),))
    for _ in range(4):
        sess.slo.record_request("lu", N, 1e-3, ok=False)
    for _ in range(3):  # scrape loop: ONE transition, one capture
        sess.slo.evaluate()
    assert sess.metrics.get("slo_breaches_total") == 1
    assert sess.metrics.get("incidents_captured_total") == 1
    (doc,) = rec.incidents.incidents()
    assert doc["reason"] == "slo_breach" and doc["key"] == "errors"


# -- the disabled path -------------------------------------------------------


def test_disabled_recorder_allocates_nothing():
    """Round-8 discipline, pinned with a real allocator trace: with
    ``recorder=None`` a full served workload allocates ZERO bytes from
    recorder.py/events.py (tracemalloc filtered by file), and the
    session/tracer carry no journal, ring, or capture object at all.
    The enabled control proves the instrument measures what we claim."""
    filters = [tracemalloc.Filter(
        True, os.path.join("*", "slate_tpu", "obs", "recorder.py")),
        tracemalloc.Filter(
        True, os.path.join("*", "slate_tpu", "obs", "events.py"))]

    def _serve(sess, h):
        batcher = Batcher(sess, max_batch=4, max_wait=10.0)
        futs = [batcher.submit(h, RNG.standard_normal(N))
                for _ in range(4)]
        batcher.flush()
        for f in futs:
            f.result(timeout=30)
        sess.evict(h)
        sess.clear_cache()

    from slate_tpu.obs.tracing import Tracer
    sess, h, a = _lu_session(tracer=Tracer())  # isolated from the
    # default tracer, which other tests may have wired a recorder onto
    assert sess.recorder is None and sess.tracer.recorder is None
    sess.solve(h, RNG.standard_normal(N))  # warm the compile caches
    gc.collect()
    tracemalloc.start()
    try:
        _serve(sess, h)
        disabled = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    assert sum(s.size for s in disabled.statistics("filename")) == 0

    sess2, h2, _ = _lu_session(tracer=Tracer())
    sess2.enable_recorder()
    sess2.solve(h2, RNG.standard_normal(N))
    gc.collect()
    tracemalloc.start()
    try:
        _serve(sess2, h2)
        enabled = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    assert sum(s.size for s in enabled.statistics("filename")) > 0


# -- fleet folds -------------------------------------------------------------


def test_journal_fold_conserves_counts_and_labels_hosts():
    """merge_journal_payloads: per-kind counts (and recorded/dropped)
    sum EXACTLY, every folded event carries its host, and the merged
    stream is (ts, host, seq)-ordered."""
    j1, j2 = DecisionJournal(), DecisionJournal()
    for i in range(3):
        j1.record("eviction", handle=f"a{i}", outcome="budget")
    j2.record("shed", outcome="deadline", count=5)
    j2.record("eviction", handle="b0", outcome="explicit")
    p1, p2 = j1.payload(), j2.payload()
    fleet = obs.aggregate.merge_journal_payloads([p1, p2],
                                                 hosts=["h0", "h1"])
    assert fleet["schema"] == "slate_tpu.journal.fleet.v1"
    assert fleet["counts"] == {"eviction": 4, "shed": 5}
    assert fleet["recorded"] == 5 and fleet["dropped"] == 0
    assert fleet["processes"] == 2
    hosts = {e["host"] for e in fleet["events"]}
    assert hosts == {"h0", "h1"}
    keys = [(e["ts"], e["host"], e["seq"]) for e in fleet["events"]]
    assert keys == sorted(keys)


def test_incident_fold_preserves_documents():
    _, c1 = _capture()
    _, c2 = _capture(rate_limit_s=0.0)
    c1.trigger("fault", key="x")
    c2.trigger("breaker_open", key="y")
    c2.trigger("slo_breach", key="z")
    fleet = obs.aggregate.merge_incident_payloads(
        [c1.payload(), c2.payload()], hosts=["h0", "h1"])
    assert fleet["schema"] == "slate_tpu.incidents.fleet.v1"
    assert len(fleet["incidents"]) == 3
    assert fleet["captured"] == 3
    assert {d["fold_host"] for d in fleet["incidents"]} == {"h0", "h1"}


# -- exposition routes -------------------------------------------------------


def test_journal_and_incident_routes():
    import urllib.request
    sess, h, a = _lu_session()
    rec = sess.enable_recorder()
    sess.solve(h, RNG.standard_normal(N))
    sess.evict(h)
    rec.incident("probe", key="route-test", handle=h)
    srv = sess.serve_obs()
    try:
        jp = json.loads(urllib.request.urlopen(
            srv.url("/journal"), timeout=10).read().decode())
        assert jp["schema"] == JOURNAL_SCHEMA
        assert jp["counts"]["eviction"] == 1
        ip = json.loads(urllib.request.urlopen(
            srv.url("/incidents"), timeout=10).read().decode())
        assert ip["schema"] == "slate_tpu.incidents.v1"
        assert len(ip["incidents"]) == 1
        assert validate_incident(ip["incidents"][0]) == []
    finally:
        sess.close_obs()


def test_routes_degrade_when_recorder_disabled():
    import urllib.request
    sess, h, a = _lu_session()
    srv = sess.serve_obs()
    try:
        for path in ("/journal", "/incidents"):
            body = json.loads(urllib.request.urlopen(
                srv.url(path), timeout=10).read().decode())
            assert body["enabled"] is False
    finally:
        sess.close_obs()


# -- drift pins vs the jax-free mirror ---------------------------------------


def test_incident_validator_pinned_across_gate_and_runtime():
    """bench_gate validates committed artifacts WITHOUT importing the
    runtime; its incident mirror must reject exactly what the runtime
    validator rejects (same malformed documents, same verdicts)."""
    gate = _bench_gate()
    assert gate.INCIDENT_SCHEMA == INCIDENT_SCHEMA
    assert tuple(gate.INCIDENT_KEYS) == tuple(INCIDENT_KEYS)
    _, cap = _capture()
    good = cap.trigger("fault", key="x")
    good = json.loads(json.dumps(good, default=repr))
    bad_docs = [
        "not a dict",
        {},
        {**good, "schema": "slate_tpu.incident.v0"},
        {k: v for k, v in good.items() if k != "journal"},
        {**good, "journal": {"events": "nope", "counts": {}}},
        {**good, "ts": "yesterday"},
        {**good, "reason": None},
    ]
    for doc in [good] + bad_docs:
        runtime_errs = validate_incident(doc)
        gate_errs = gate.validate_incident_doc(doc)
        assert bool(runtime_errs) == bool(gate_errs), (
            f"validators disagree on {doc!r}: runtime={runtime_errs} "
            f"gate={gate_errs}")
    assert validate_incident(good) == []


def test_decision_event_str_coercion_keeps_payload_jsonable():
    """Handles are arbitrary hashables (tuples, objects); the journal
    str()-coerces at record time so every payload round-trips through
    plain json.dumps."""
    j = DecisionJournal()
    j.record("eviction", handle=("h", 0), op=object(), tenant=7,
             outcome="budget")
    json.dumps(j.payload())  # must not raise
    e = j.payload()["events"][0]
    assert e["handle"] == str(("h", 0)) and e["tenant"] == "7"


def test_concurrent_recording_keeps_parity():
    """Decisions from N threads: the ring and counts stay consistent
    (no lost updates) — the journal sits on serving hot paths."""
    j = DecisionJournal(capacity=64)

    def hammer(i):
        for k in range(200):
            j.record("shed", handle=f"t{i}", outcome="x")

    ts = [threading.Thread(target=hammer, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert j.count("shed") == 800
    assert j.payload()["recorded"] == 800
    assert len(j.events()) == 64
