"""Compat API surfaces: LAPACK-style, ScaLAPACK-style, and the
C-callable embedded API.

Reference: lapack_api/ (drop-in dgesv_ etc.), scalapack_api/ (pdpotrf_
reading BLACS descriptors), tools/c_api + src/c_api/wrappers.cc.
"""

import os
import subprocess
import sysconfig
import textwrap

import numpy as np
import pytest

from slate_tpu.compat import lapack_api as lp
from slate_tpu.compat import scalapack_api as sc
from slate_tpu.interop import to_scalapack
import slate_tpu as st

RNG = np.random.default_rng(9)


def _spd(n, dtype=np.float64):
    a = RNG.standard_normal((n, n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


def _capi_lib():
    """Load (rebuilding if stale) the embedded C API shared library."""
    import ctypes
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    so = os.path.join(native, "libslate_tpu_capi.so")
    srcs = [os.path.join(native, f) for f in ("capi_gen.c", "capi.c")]
    if (not os.path.exists(so)
            or any(os.path.exists(f)
                   and os.path.getmtime(so) < os.path.getmtime(f)
                   for f in srcs)):
        subprocess.run(["make", "-C", native], check=True,
                       capture_output=True)
    return ctypes.CDLL(so)


# -- LAPACK-style Python surface -------------------------------------------

def test_lapack_dgesv_roundtrip():
    n, nrhs = 48, 3
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    lu, ipiv, x, info = lp.dgesv(n, nrhs, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-10)
    # LAPACK ipiv semantics: applying the swaps reproduces P·A = L·U
    l = np.tril(lu, -1) + np.eye(n)
    u = np.triu(lu)
    pa = a.copy()
    for i, p in enumerate(ipiv):
        j = int(p) - 1
        pa[[i, j]] = pa[[j, i]]
    np.testing.assert_allclose(pa, l @ u, atol=1e-10)


def test_lapack_getrs_from_factors():
    n = 40
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, 2))
    lu, ipiv, _, info = lp.dgesv(n, 1, a, n, b[:, :1], n)
    x, info2 = lp.dgetrs("n", n, 2, lu, n, ipiv, b, n)
    assert info2 == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-9)


def test_lapack_dpotrf_dposv():
    n = 40
    a = _spd(n)
    f, info = lp.dpotrf("L", n, a, n)
    assert info == 0
    np.testing.assert_allclose(np.tril(f) @ np.tril(f).T, a, atol=1e-9)
    b = RNG.standard_normal((n, 2))
    x, info = lp.dposv("L", n, 2, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-9)


def test_lapack_sgesv_f32():
    n = 32
    a = RNG.standard_normal((n, n)).astype(np.float32)
    b = RNG.standard_normal((n, 1)).astype(np.float32)
    lu, ipiv, x, info = lp.sgesv(n, 1, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-3)


def test_lapack_zheev_dsyev():
    n = 36
    a = _spd(n)
    w, z, info = lp.dsyev("V", "L", n, a, n)
    wref = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(w, wref, rtol=1e-9, atol=1e-9)
    assert np.abs(a @ z - z * w).max() < 1e-8
    c = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    c = 0.5 * (c + c.conj().T)
    w2, z2, info2 = lp.zheev("N", "L", n, c, n)
    np.testing.assert_allclose(w2, np.linalg.eigvalsh(c), rtol=1e-9,
                               atol=1e-9)
    assert z2 is None


@pytest.mark.slow  # round-10 wall-time headroom: ~4.5 s, the
# dgesvd/dgels lapack_api surface is also covered by the ctypes tests
def test_lapack_dgesvd_dgels():
    m, n = 50, 30
    a = RNG.standard_normal((m, n))
    s, u, vt, info = lp.dgesvd("S", "S", m, n, a, m)
    sref = np.linalg.svd(a, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s)[:n], sref, rtol=1e-9,
                               atol=1e-9)
    b = RNG.standard_normal((m, 2))
    x, info = lp.dgels("n", m, n, 2, a, m, b, m)
    xref, *_ = np.linalg.lstsq(a, b, rcond=None)
    np.testing.assert_allclose(x, xref, atol=1e-8)


# -- ScaLAPACK-style surface ------------------------------------------------

def test_scalapack_pdpotrf_in_place():
    n, nb, p, q = 48, 8, 2, 2
    a = _spd(n)
    A = st.from_dense(a, nb=nb)
    locals_ = [np.array(l) for l in to_scalapack(A, p, q)]
    desc = sc.make_desc(n, n, nb, p, q)
    info = sc.pdpotrf("L", n, locals_, desc)
    assert info == 0
    from slate_tpu.interop import from_scalapack
    F = from_scalapack(locals_, n, n, nb, p, q).to_numpy()
    np.testing.assert_allclose(np.tril(F) @ np.tril(F).T, a, atol=1e-9)
    # untouched triangle preserved (LAPACK in-place convention)
    np.testing.assert_allclose(np.triu(F, 1), np.triu(a, 1), atol=1e-12)


# ~10 s; pdposv/pdpotrs/pdgels + pdpotrf keep the scalapack shim
# covered in tier-1 (round-9 wall-time headroom satellite)
@pytest.mark.slow
def test_scalapack_pdgesv_and_pdgemm():
    n, nrhs, nb, p, q = 40, 2, 8, 2, 2
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    al = [np.array(l) for l in to_scalapack(st.from_dense(a, nb=nb), p, q)]
    bl = [np.array(l) for l in to_scalapack(st.from_dense(b, nb=nb), p, q)]
    da = sc.make_desc(n, n, nb, p, q)
    db = sc.make_desc(n, nrhs, nb, p, q)
    info = sc.pdgesv_(n, nrhs, al, da, bl, db)
    assert info == 0
    from slate_tpu.interop import from_scalapack
    X = from_scalapack(bl, n, nrhs, nb, p, q).to_numpy()
    np.testing.assert_allclose(a @ X, b, atol=1e-9)

    cl = [np.array(l) for l in to_scalapack(
        st.from_dense(np.zeros((n, n)), nb=nb), p, q)]
    dc = sc.make_desc(n, n, nb, p, q)
    sc.pdgemm("n", "t", n, n, n, 1.0, al, da, al, da, 0.0, cl, dc)
    C = from_scalapack(cl, n, n, nb, p, q).to_numpy()
    np.testing.assert_allclose(C, a @ a.T, atol=1e-9)


def test_lapack_dgeqrf_tau_parity():
    # LAPACK semantics: a_out packs V\R, tau are the reflector scalars;
    # rebuilding Q = H_0·H_1·… from (a_out, tau) must reproduce A = Q·R
    m, n = 40, 24
    a = RNG.standard_normal((m, n))
    vr, tau, info = lp.dgeqrf(m, n, a, m)
    assert info == 0
    assert tau.shape == (n,)
    q = np.eye(m)
    for i in range(n):
        v = np.zeros(m)
        v[i] = 1.0
        v[i + 1:] = vr[i + 1:, i]
        q = q @ (np.eye(m) - tau[i] * np.outer(v, v))
    r = np.triu(vr)[:n, :]
    np.testing.assert_allclose(q[:, :n] @ r, a, atol=1e-9)
    np.testing.assert_allclose(q.T @ q, np.eye(m), atol=1e-9)


# -- LAPACK-style breadth (VERDICT r2 missing #5) ---------------------------

def test_lapack_getrf_getri():
    n = 40
    a = RNG.standard_normal((n, n))
    lu, ipiv, info = lp.dgetrf(n, n, a, n)
    assert info == 0
    l = np.tril(lu, -1) + np.eye(n)
    u = np.triu(lu)
    pa = a.copy()
    for i, p in enumerate(ipiv):
        j = int(p) - 1
        pa[[i, j]] = pa[[j, i]]
    np.testing.assert_allclose(pa, l @ u, atol=1e-10)
    inv, info = lp.dgetri(n, lu, n, ipiv)
    assert info == 0
    np.testing.assert_allclose(a @ inv, np.eye(n), atol=1e-9)


def test_lapack_potrs_potri():
    n = 36
    a = _spd(n)
    f, info = lp.dpotrf("L", n, a, n)
    assert info == 0
    b = RNG.standard_normal((n, 2))
    x, info = lp.dpotrs("L", n, 2, np.tril(f), n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-8)
    inv, info = lp.dpotri("L", n, np.tril(f), n)
    assert info == 0
    np.testing.assert_allclose(a @ inv, np.eye(n), atol=1e-8)


def test_lapack_blas3_family():
    m, n, k = 24, 20, 28
    a = RNG.standard_normal((m, k))
    b = RNG.standard_normal((k, n))
    c = RNG.standard_normal((m, n))
    out = lp.dgemm("n", "n", m, n, k, 2.0, a, m, b, k, -1.0, c, m)
    np.testing.assert_allclose(out, 2.0 * a @ b - c, atol=1e-10)
    # transposed operands
    out = lp.dgemm("t", "t", m, n, k, 1.0, a.T, k, b.T, n, 0.0, c, m)
    np.testing.assert_allclose(out, a @ b, atol=1e-10)

    s = _spd(n)
    bn = RNG.standard_normal((n, n))
    out = lp.dsymm("L", "L", n, n, 1.0, s, n, bn, n, 0.0,
                   np.zeros((n, n)), n)
    np.testing.assert_allclose(out, s @ bn, atol=1e-10)

    ak = RNG.standard_normal((n, k))
    cs = _spd(n)
    out = lp.dsyrk("L", "n", n, k, -1.0, ak, n, 1.0, cs, n)
    ref = cs - ak @ ak.T
    np.testing.assert_allclose(np.tril(out), np.tril(ref), atol=1e-10)
    np.testing.assert_allclose(np.triu(out, 1), np.triu(cs, 1))

    bk = RNG.standard_normal((n, k))
    out = lp.dsyr2k("L", "n", n, k, 1.0, ak, n, bk, n, 0.0,
                    np.zeros((n, n)), n)
    np.testing.assert_allclose(np.tril(out),
                               np.tril(ak @ bk.T + bk @ ak.T), atol=1e-10)

    t = np.tril(RNG.standard_normal((n, n))) + n * np.eye(n)
    bn2 = RNG.standard_normal((n, 3))
    out = lp.dtrmm("L", "L", "n", "n", n, 3, 1.0, t, n, bn2, n)
    np.testing.assert_allclose(out, t @ bn2, atol=1e-10)
    out = lp.dtrsm("L", "L", "n", "n", n, 3, 1.0, t, n, bn2, n)
    np.testing.assert_allclose(t @ out, bn2, atol=1e-9)


def test_lapack_complex_hemm_herk():
    n, k = 20, 16
    h = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    h = 0.5 * (h + h.conj().T)
    b = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    out = lp.zhemm("L", "L", n, n, 1.0, h, n, b, n, 0.0,
                   np.zeros((n, n), complex), n)
    np.testing.assert_allclose(out, h @ b, atol=1e-10)
    a = RNG.standard_normal((n, k)) + 1j * RNG.standard_normal((n, k))
    out = lp.zherk("L", "n", n, k, 1.0, a, n, 0.0,
                   np.zeros((n, n), complex), n)
    np.testing.assert_allclose(np.tril(out), np.tril(a @ a.conj().T),
                               atol=1e-10)


@pytest.mark.slow  # round-10 wall-time headroom (~6 s)
def test_lapack_norms_and_cond():
    m, n = 30, 22
    a = RNG.standard_normal((m, n))
    assert np.isclose(lp.dlange("M", m, n, a, m), np.abs(a).max())
    assert np.isclose(lp.dlange("1", m, n, a, m),
                      np.abs(a).sum(axis=0).max())
    assert np.isclose(lp.dlange("I", m, n, a, m),
                      np.abs(a).sum(axis=1).max())
    assert np.isclose(lp.dlange("F", m, n, a, m),
                      np.sqrt((a * a).sum()), rtol=1e-12)
    s = _spd(n)
    assert np.isclose(lp.dlansy("1", "L", n, np.tril(s), n),
                      np.abs(s).sum(axis=0).max())
    t = np.tril(RNG.standard_normal((n, n)))
    assert np.isclose(lp.dlantr("M", "L", "n", n, n, t, n),
                      np.abs(t).max())

    # condition estimates: rcond within a small factor of the truth
    sp = _spd(n)
    anorm = np.abs(sp).sum(axis=0).max()
    lu, ipiv, info = lp.dgetrf(n, n, sp, n)
    rcond, info = lp.dgecon("1", n, lu, n, anorm)
    true_rcond = 1.0 / (anorm * np.abs(np.linalg.inv(sp)).sum(axis=0).max())
    assert 0.1 * true_rcond <= rcond <= 10 * true_rcond
    f, _ = lp.dpotrf("L", n, sp, n)
    rcond2, info = lp.dpocon("L", n, np.tril(f), n, anorm)
    assert 0.1 * true_rcond <= rcond2 <= 10 * true_rcond
    tt = np.tril(RNG.standard_normal((n, n))) + n * np.eye(n)
    rcond3, info = lp.dtrcon("1", "L", "n", n, tt, n)
    assert 0 < rcond3 <= 1.0


def test_lapack_dsyevd_dsgesv():
    n = 48
    a = _spd(n)
    w, z, info = lp.dsyevd("V", "L", n, a, n)
    assert info == 0
    np.testing.assert_allclose(w, np.linalg.eigvalsh(a), rtol=1e-8,
                               atol=1e-8)
    assert np.abs(a @ z - z * w).max() < 1e-7
    b = RNG.standard_normal((n, 2))
    x, iters, info = lp.dsgesv(n, 2, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-9)


# -- ScaLAPACK-style breadth ------------------------------------------------

def _dist(arr, nb, p, q):
    return [np.array(l) for l in to_scalapack(
        st.from_dense(np.ascontiguousarray(arr), nb=nb), p, q)]


def _undist(locals_, m, n, nb, p, q):
    from slate_tpu.interop import from_scalapack
    return from_scalapack(locals_, m, n, nb, p, q).to_numpy()


def test_scalapack_pdgetrf_pdgetrs():
    n, nrhs, nb, p, q = 40, 2, 8, 2, 2
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    al = _dist(a, nb, p, q)
    bl = _dist(b, nb, p, q)
    da = sc.make_desc(n, n, nb, p, q)
    db = sc.make_desc(n, nrhs, nb, p, q)
    ipiv, info = sc.pdgetrf(n, n, al, da)
    assert info == 0
    info = sc.pdgetrs("n", n, nrhs, al, da, ipiv, bl, db)
    assert info == 0
    np.testing.assert_allclose(a @ _undist(bl, n, nrhs, nb, p, q), b,
                               atol=1e-9)


def test_scalapack_pdposv_pdpotrs_pdgels():
    n, nrhs, nb, p, q = 32, 2, 8, 2, 2
    a = _spd(n)
    b = RNG.standard_normal((n, nrhs))
    al = _dist(a, nb, p, q)
    bl = _dist(b, nb, p, q)
    da = sc.make_desc(n, n, nb, p, q)
    db = sc.make_desc(n, nrhs, nb, p, q)
    info = sc.pdposv("L", n, nrhs, al, da, bl, db)
    assert info == 0
    np.testing.assert_allclose(a @ _undist(bl, n, nrhs, nb, p, q), b,
                               atol=1e-8)
    # pdpotrs from the factor pdposv left in al
    bl2 = _dist(b, nb, p, q)
    info = sc.pdpotrs("L", n, nrhs, al, da, bl2, db)
    assert info == 0
    np.testing.assert_allclose(a @ _undist(bl2, n, nrhs, nb, p, q), b,
                               atol=1e-8)
    # pdgels (tall)
    m = 48
    at = RNG.standard_normal((m, n))
    bt = RNG.standard_normal((m, nrhs))
    atl = _dist(at, nb, p, q)
    btl = _dist(bt, nb, p, q)
    dat = sc.make_desc(m, n, nb, p, q)
    dbt = sc.make_desc(m, nrhs, nb, p, q)
    info = sc.pdgels("n", m, n, nrhs, atl, dat, btl, dbt)
    assert info == 0
    x = _undist(btl, m, nrhs, nb, p, q)[:n]
    xref, *_ = np.linalg.lstsq(at, bt, rcond=None)
    np.testing.assert_allclose(x, xref, atol=1e-8)


def test_scalapack_pdsyev_pdgesvd():
    n, nb, p, q = 32, 8, 2, 2
    a = _spd(n)
    al = _dist(a, nb, p, q)
    zl = _dist(np.zeros((n, n)), nb, p, q)
    da = sc.make_desc(n, n, nb, p, q)
    w, info = sc.pdsyev("V", "L", n, al, da, zl, da)
    assert info == 0
    np.testing.assert_allclose(w, np.linalg.eigvalsh(a), rtol=1e-8,
                               atol=1e-8)
    z = _undist(zl, n, n, nb, p, q)
    assert np.abs(a @ z - z * w).max() < 1e-7

    m2, n2 = 40, 24
    g = RNG.standard_normal((m2, n2))
    gl = _dist(g, nb, p, q)
    dg = sc.make_desc(m2, n2, nb, p, q)
    s, info = sc.pdgesvd("n", "n", m2, n2, gl, dg)
    assert info == 0
    np.testing.assert_allclose(np.asarray(s)[:n2],
                               np.linalg.svd(g, compute_uv=False),
                               rtol=1e-8, atol=1e-8)


def test_scalapack_pdtrsm_pdsyrk_pdlange():
    n, k, nb, p, q = 32, 16, 8, 2, 2
    t = np.tril(RNG.standard_normal((n, n))) + n * np.eye(n)
    b = RNG.standard_normal((n, 3))
    tl = _dist(t, nb, p, q)
    bl = _dist(b, nb, p, q)
    dt = sc.make_desc(n, n, nb, p, q)
    db = sc.make_desc(n, 3, nb, p, q)
    sc.pdtrsm("L", "L", "n", "n", n, 3, 1.0, tl, dt, bl, db)
    np.testing.assert_allclose(t @ _undist(bl, n, 3, nb, p, q), b,
                               atol=1e-9)

    ak = RNG.standard_normal((n, k))
    cs = _spd(n)
    akl = _dist(ak, nb, p, q)
    csl = _dist(cs, nb, p, q)
    dak = sc.make_desc(n, k, nb, p, q)
    dcs = sc.make_desc(n, n, nb, p, q)
    sc.pdsyrk("L", "n", n, k, -1.0, akl, dak, 1.0, csl, dcs)
    out = _undist(csl, n, n, nb, p, q)
    np.testing.assert_allclose(np.tril(out), np.tril(cs - ak @ ak.T),
                               atol=1e-9)

    assert np.isclose(sc.pdlange("1", n, k, akl, dak),
                      np.abs(ak).sum(axis=0).max())


# -- C API (embedded interpreter) ------------------------------------------

C_TEST = r"""
#include <stdio.h>
#include <stdlib.h>
#include "slate_tpu_capi.h"

int main(void) {
    const int n = 24, nrhs = 2;
    double *a = malloc(n * n * sizeof(double));
    double *acopy = malloc(n * n * sizeof(double));
    double *b = malloc(n * nrhs * sizeof(double));
    double *bcopy = malloc(n * nrhs * sizeof(double));
    int64_t *ipiv = malloc(n * sizeof(int64_t));
    unsigned s = 12345;
    for (int i = 0; i < n * n; ++i) {
        s = s * 1103515245u + 12345u;
        a[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
    }
    for (int j = 0; j < n; ++j) a[j * n + j] += n;  /* dominant */
    for (int i = 0; i < n * nrhs; ++i) {
        s = s * 1103515245u + 12345u;
        b[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
    }
    for (int i = 0; i < n * n; ++i) acopy[i] = a[i];
    for (int i = 0; i < n * nrhs; ++i) bcopy[i] = b[i];
    int64_t info = slate_tpu_dgesv(n, nrhs, a, n, ipiv, b, n);
    if (info != 0) { printf("info=%lld\n", (long long)info); return 2; }
    /* residual: column-major A (acopy) times X (b) vs bcopy */
    double maxerr = 0.0;
    for (int c = 0; c < nrhs; ++c)
        for (int i = 0; i < n; ++i) {
            double acc = 0.0;
            for (int k = 0; k < n; ++k)
                acc += acopy[k * n + i] * b[c * n + k];
            double e = acc - bcopy[c * n + i];
            if (e < 0) e = -e;
            if (e > maxerr) maxerr = e;
        }
    printf("maxerr=%g\n", maxerr);
    return maxerr < 1e-8 ? 0 : 3;
}
"""


C_TEST2 = r"""
#include <stdio.h>
#include <stdlib.h>
#include <math.h>
#include "slate_tpu_capi.h"

int main(void) {
    const int n = 16, nrhs = 2;
    double *a = malloc(n * n * sizeof(double));
    double *acopy = malloc(n * n * sizeof(double));
    double *b = malloc(n * nrhs * sizeof(double));
    double *bcopy = malloc(n * nrhs * sizeof(double));
    double *r = malloc(n * nrhs * sizeof(double));
    double *w = malloc(n * sizeof(double));
    int64_t *ipiv = malloc(n * sizeof(int64_t));
    unsigned s = 777;
    for (int i = 0; i < n * n; ++i) {
        s = s * 1103515245u + 12345u;
        a[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
    }
    for (int j = 0; j < n; ++j) a[j * n + j] += n;
    for (int i = 0; i < n * nrhs; ++i) {
        s = s * 1103515245u + 12345u;
        b[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
    }
    for (int i = 0; i < n * n; ++i) acopy[i] = a[i];
    for (int i = 0; i < n * nrhs; ++i) bcopy[i] = b[i];

    /* getrf + getrs */
    int64_t info = slate_tpu_dgetrf(n, n, a, n, ipiv);
    if (info != 0) { printf("getrf info=%lld\n", (long long)info); return 2; }
    info = slate_tpu_dgetrs("n", n, nrhs, a, n, ipiv, b, n);
    if (info != 0) { printf("getrs info=%lld\n", (long long)info); return 3; }

    /* residual R = A*X - B via dgemm, measured with dlange */
    for (int i = 0; i < n * nrhs; ++i) r[i] = bcopy[i];
    info = slate_tpu_dgemm("n", "n", n, nrhs, n, 1.0, acopy, n, b, n,
                           -1.0, r, n);
    if (info != 0) return 4;
    double maxerr = slate_tpu_dlange("M", n, nrhs, r, n);
    if (!(maxerr >= 0 && maxerr < 1e-8)) {
        printf("residual=%g\n", maxerr); return 5;
    }

    /* dsyev on A + A^T (symmetric): eigenvalue sum == trace */
    double trace = 0.0;
    for (int i = 0; i < n; ++i)
        for (int j = 0; j < n; ++j)
            a[j * n + i] = acopy[j * n + i] + acopy[i * n + j];
    for (int i = 0; i < n; ++i) trace += a[i * n + i];
    info = slate_tpu_dsyev("V", "L", n, a, n, w);
    if (info != 0) return 6;
    double sum = 0.0;
    for (int i = 0; i < n; ++i) sum += w[i];
    if (fabs(sum - trace) > 1e-7 * (fabs(trace) + 1)) {
        printf("eig sum=%g trace=%g\n", sum, trace); return 7;
    }
    printf("ok maxerr=%g\n", maxerr);
    return 0;
}
"""


def _build_c(tmp_path, src_text, name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    so = os.path.join(native, "libslate_tpu_capi.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", native], check=True,
                       capture_output=True)
    csrc = tmp_path / (name + ".c")
    csrc.write_text(src_text)
    exe = tmp_path / name
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    subprocess.run(
        ["gcc", str(csrc), "-I", os.path.join(repo, "include"),
         "-L", native, "-lslate_tpu_capi", "-lm", "-o", str(exe)],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["LD_LIBRARY_PATH"] = f"{native}:{libdir}:" + env.get(
        "LD_LIBRARY_PATH", "")
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    return exe, env


# ~8 s breadth sweep; the handles/r5/multiprecision/real-C-program
# tests keep the C API covered in tier-1 (round-9 headroom satellite)
@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
def test_c_api_breadth(tmp_path):
    exe, env = _build_c(tmp_path, C_TEST2, "t2")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "ok maxerr=" in r.stdout


F_TEST = r"""
program t
   use slate_tpu
   use iso_c_binding
   implicit none
   integer, parameter :: n = 12, nrhs = 1
   real(c_double) :: a(n, n), acopy(n, n), b(n, nrhs), bcopy(n, nrhs)
   integer(c_int64_t) :: ipiv(n), info
   integer :: i, j
   real(c_double) :: err
   call random_seed()
   call random_number(a)
   do i = 1, n
      a(i, i) = a(i, i) + n
   end do
   call random_number(b)
   acopy = a
   bcopy = b
   info = slate_tpu_dgesv(int(n, c_int64_t), int(nrhs, c_int64_t), a, &
                          int(n, c_int64_t), ipiv, b, int(n, c_int64_t))
   if (info /= 0) stop 2
   err = 0
   do j = 1, nrhs
      do i = 1, n
         err = max(err, abs(dot_product(acopy(i, :), b(:, j)) &
                            - bcopy(i, j)))
      end do
   end do
   if (err > 1e-8) stop 3
   print *, 'fortran ok', err
end program t
"""


@pytest.mark.skipif(__import__("shutil").which("gfortran") is None,
                    reason="no Fortran compiler in this image")
def test_fortran_api(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    fsrc = tmp_path / "t.f90"
    fsrc.write_text(F_TEST)
    exe = tmp_path / "tf"
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    subprocess.run(
        ["gfortran", os.path.join(repo, "fortran", "slate_tpu.f90"),
         str(fsrc), "-J", str(tmp_path), "-L", native,
         "-lslate_tpu_capi", "-o", str(exe)],
        check=True, capture_output=True, cwd=str(tmp_path))
    env = dict(os.environ)
    env["LD_LIBRARY_PATH"] = f"{native}:{libdir}:" + env.get(
        "LD_LIBRARY_PATH", "")
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)


@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
@pytest.mark.slow  # round-10 wall-time headroom: compiles a real C
# program (~6 s); the same ABI surface runs in-process in the ctypes tests
def test_c_api_from_real_c_program(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    so = os.path.join(native, "libslate_tpu_capi.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", native], check=True,
                       capture_output=True)
    csrc = tmp_path / "t.c"
    csrc.write_text(C_TEST)
    exe = tmp_path / "t"
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    subprocess.run(
        ["gcc", str(csrc), "-I", os.path.join(repo, "include"),
         "-L", native, "-lslate_tpu_capi", "-o", str(exe)],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["LD_LIBRARY_PATH"] = f"{native}:{libdir}:" + env.get(
        "LD_LIBRARY_PATH", "")
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "maxerr=" in r.stdout


@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
@pytest.mark.slow  # round-10 wall-time headroom (~6 s)
def test_c_api_multiprecision_ctypes():
    """Drive the GENERATED s/c/z C entry points (tools/gen_capi.py →
    native/capi_gen.c) by loading the library into this process — the
    embedding detects the live interpreter and reuses it, so this
    exercises the same code path as an external C caller without a
    600 s subprocess."""
    import ctypes

    lib = _capi_lib()
    i64 = ctypes.c_int64
    rng = np.random.default_rng(0)

    # --- sgesv (float32) ---------------------------------------------
    n, nrhs = 12, 2
    a = np.asfortranarray(
        rng.standard_normal((n, n)).astype(np.float32) + n * np.eye(
            n, dtype=np.float32))
    a0 = a.copy()
    b = np.asfortranarray(rng.standard_normal((n, nrhs)).astype(np.float32))
    b0 = b.copy()
    ipiv = np.zeros(n, np.int64)
    lib.slate_tpu_sgesv.restype = i64
    rc = lib.slate_tpu_sgesv(
        i64(n), i64(nrhs), a.ctypes.data_as(ctypes.c_void_p), i64(n),
        ipiv.ctypes.data_as(ctypes.c_void_p),
        b.ctypes.data_as(ctypes.c_void_p), i64(n))
    assert rc == 0
    assert np.abs(a0 @ b - b0).max() < 1e-3

    # --- zposv (complex128) ------------------------------------------
    g = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    spd = g @ g.conj().T / n + 2 * np.eye(n)
    az = np.asfortranarray(spd.astype(np.complex128))
    az0 = az.copy()
    bz = np.asfortranarray(
        (rng.standard_normal((n, nrhs))
         + 1j * rng.standard_normal((n, nrhs))).astype(np.complex128))
    bz0 = bz.copy()
    lib.slate_tpu_zposv.restype = i64
    rc = lib.slate_tpu_zposv(
        ctypes.c_char_p(b"L"), i64(n), i64(nrhs),
        az.ctypes.data_as(ctypes.c_void_p), i64(n),
        bz.ctypes.data_as(ctypes.c_void_p), i64(n))
    assert rc == 0
    assert np.abs(az0 @ bz - bz0).max() < 1e-9

    # --- cheev (complex64, values + vectors) -------------------------
    h = (g + g.conj().T).astype(np.complex64) / 2
    ah = np.asfortranarray(h)
    w = np.zeros(n, np.float32)
    lib.slate_tpu_cheev.restype = i64
    rc = lib.slate_tpu_cheev(
        ctypes.c_char_p(b"V"), ctypes.c_char_p(b"L"), i64(n),
        ah.ctypes.data_as(ctypes.c_void_p), i64(n),
        w.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    wref = np.linalg.eigvalsh(h.astype(np.complex128))
    assert np.abs(np.sort(w) - wref).max() < 1e-4 * max(
        1, np.abs(wref).max())
    # eigenvectors overwrote A
    res = np.abs(h.astype(np.complex128) @ ah - ah * w[None, :]).max()
    assert res < 1e-3

    # --- slange ------------------------------------------------------
    m2 = np.asfortranarray(rng.standard_normal((6, 4)).astype(np.float32))
    lib.slate_tpu_slange.restype = ctypes.c_double
    got = lib.slate_tpu_slange(ctypes.c_char_p(b"1"), i64(6), i64(4),
                               m2.ctypes.data_as(ctypes.c_void_p), i64(6))
    assert abs(got - np.linalg.norm(m2, 1)) < 1e-5

    # --- dgetri (round-trips the generated getri path) ---------------
    ad = np.asfortranarray(rng.standard_normal((n, n)) + n * np.eye(n))
    ad0 = ad.copy()
    ipiv = np.zeros(n, np.int64)
    lib.slate_tpu_dgetrf.restype = i64
    rc = lib.slate_tpu_dgetrf(i64(n), i64(n),
                              ad.ctypes.data_as(ctypes.c_void_p), i64(n),
                              ipiv.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    lib.slate_tpu_dgetri.restype = i64
    rc = lib.slate_tpu_dgetri(i64(n),
                              ad.ctypes.data_as(ctypes.c_void_p), i64(n),
                              ipiv.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    assert np.abs(ad0 @ ad - np.eye(n)).max() < 1e-9


C_TEST_R5 = r"""
/* round-5 surface: opaque matrix handles (resident across calls) plus a
 * sweep of the newly generated routine families. */
#include <stdio.h>
#include <math.h>
#include <complex.h>
#include "slate_tpu_capi.h"

int main(void) {
    enum { n = 24, nrhs = 2 };
    static double a[n * n], aspd[n * n], b[n * nrhs], x[n * nrhs],
        r[n * nrhs];
    unsigned s = 12345;
    for (int i = 0; i < n * n; ++i) {
        s = s * 1103515245u + 12345u;
        a[i] = ((double)(s >> 16) / 65536.0) - 0.5;
    }
    /* aspd = a*a^T + n*I, column-major */
    for (int j = 0; j < n; ++j)
        for (int i = 0; i < n; ++i) {
            double acc = (i == j) ? (double)n : 0.0;
            for (int k = 0; k < n; ++k)
                acc += a[i + k * n] * a[j + k * n];
            aspd[i + j * n] = acc;
        }
    for (int i = 0; i < n * nrhs; ++i) {
        s = s * 1103515245u + 12345u;
        b[i] = ((double)(s >> 16) / 65536.0) - 0.5;
    }

    /* --- handles: A and B stay resident; posv then residual gemm --- */
    int64_t ha = slate_tpu_matrix_from_buffer_d(n, n, aspd, n, 0);
    int64_t hb = slate_tpu_matrix_from_buffer_d(n, nrhs, b, n, 0);
    int64_t hr = slate_tpu_matrix_from_buffer_d(n, nrhs, b, n, 0);
    if (ha <= 0 || hb <= 0 || hr <= 0) return 1;
    int64_t info = slate_tpu_hposv_d("L", ha, hb);  /* X replaces hb */
    if (info != 0) return 2;
    /* hr <- A*X - B, all operands resident */
    info = slate_tpu_hgemm_d("n", "n", 1.0, ha, hb, -1.0, hr);
    if (info != 0) return 3;
    if (slate_tpu_matrix_to_buffer_d(hr, n, nrhs, r, n) != 0) return 4;
    double rmax = 0;
    for (int i = 0; i < n * nrhs; ++i)
        if (fabs(r[i]) > rmax) rmax = fabs(r[i]);
    if (rmax > 1e-8) { printf("handle residual %g\n", rmax); return 5; }
    /* to_buffer shape mismatch must fail, destroy twice must fail */
    if (slate_tpu_matrix_to_buffer_d(hr, n, n, r, n) != -2) return 6;
    if (slate_tpu_matrix_destroy(ha) != 0) return 7;
    if (slate_tpu_matrix_destroy(ha) != -1) return 8;
    slate_tpu_matrix_destroy(hb);
    slate_tpu_matrix_destroy(hr);

    /* --- dsysv on an indefinite symmetric matrix --- */
    static double asym[n * n], bs[n * nrhs];
    int64_t ipiv[2 * n];
    for (int j = 0; j < n; ++j)
        for (int i = 0; i < n; ++i)
            asym[i + j * n] = a[i + j * n] + a[j + i * n]
                - ((i == j) ? 3.0 : 0.0);
    for (int i = 0; i < n * nrhs; ++i) bs[i] = b[i];
    static double afac[n * n];
    for (int i = 0; i < n * n; ++i) afac[i] = asym[i];
    info = slate_tpu_dsysv("L", n, nrhs, afac, n, ipiv, bs, n);
    if (info != 0) return 9;
    double emax = 0;
    for (int j = 0; j < nrhs; ++j)
        for (int i = 0; i < n; ++i) {
            double acc = 0;
            for (int k = 0; k < n; ++k)
                acc += asym[i + k * n] * bs[k + j * n];
            double e = fabs(acc - b[i + j * n]);
            if (e > emax) emax = e;
        }
    if (emax > 1e-8) { printf("sysv err %g\n", emax); return 10; }

    /* --- dpbsv (kd=2 band of aspd) --- */
    enum { kd = 2 };
    static double ab[(kd + 1) * n], bb[n * nrhs], aband[n * n];
    for (int j = 0; j < n; ++j)
        for (int i = 0; i < n; ++i)
            aband[i + j * n] =
                (abs(i - j) <= kd) ? aspd[i + j * n] : 0.0;
    for (int j = 0; j < n; ++j)
        for (int t = 0; t <= kd && j + t < n; ++t)
            ab[t + j * (kd + 1)] = aband[(j + t) + j * n];
    for (int i = 0; i < n * nrhs; ++i) bb[i] = b[i];
    info = slate_tpu_dpbsv("L", n, kd, nrhs, ab, kd + 1, bb, n);
    if (info != 0) return 11;
    emax = 0;
    for (int j = 0; j < nrhs; ++j)
        for (int i = 0; i < n; ++i) {
            double acc = 0;
            for (int k = 0; k < n; ++k)
                acc += aband[i + k * n] * bb[k + j * n];
            double e = fabs(acc - b[i + j * n]);
            if (e > emax) emax = e;
        }
    if (emax > 1e-8) { printf("pbsv err %g\n", emax); return 12; }

    /* --- norms + condition: lantr / lanhe / gecon --- */
    double nrm = slate_tpu_dlantr("M", "L", "N", n, n, aspd, n);
    double nrm2 = 0;
    for (int j = 0; j < n; ++j)
        for (int i = j; i < n; ++i)
            if (fabs(aspd[i + j * n]) > nrm2) nrm2 = fabs(aspd[i + j * n]);
    if (fabs(nrm - nrm2) > 1e-9 * nrm2) return 13;
    double one = slate_tpu_dlange("1", n, n, aspd, n);
    double rcond = -1;
    static double acopy[n * n];
    for (int i = 0; i < n * n; ++i) acopy[i] = aspd[i];
    info = slate_tpu_dgecon("1", n, acopy, n, one, &rcond);
    if (info != 0 || rcond <= 0 || rcond > 1) return 14;

    /* --- geqrf + ormqr: Q*R reconstructs A (tall 24x8) --- */
    enum { qn = 8 };
    static double aq[n * qn], tau[qn], qmat[n * qn];
    for (int i = 0; i < n * qn; ++i) aq[i] = a[i];
    info = slate_tpu_dgeqrf(n, qn, aq, n, tau);
    if (info != 0) return 15;
    for (int i = 0; i < n * qn; ++i) qmat[i] = 0;
    for (int i = 0; i < qn; ++i) qmat[i + i * n] = 1.0;
    info = slate_tpu_dormqr("L", "N", n, qn, qn, aq, n, tau, qmat, n);
    if (info != 0) return 16;
    emax = 0;
    for (int j = 0; j < qn; ++j)
        for (int i = 0; i < n; ++i) {
            double acc = 0;
            for (int k = 0; k <= j && k < qn; ++k)
                acc += qmat[i + k * n] * aq[k + j * n];  /* Q * triu(R) */
            double e = fabs(acc - a[i + j * n]);
            if (e > emax) emax = e;
        }
    if (emax > 1e-8) { printf("qr err %g\n", emax); return 17; }

    printf("r5 ok rmax=%g\n", rmax);
    return 0;
}
"""


@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
@pytest.mark.slow  # round-10 wall-time headroom: the single most
# expensive compat test (~13 s of r5-routine breadth); the opaque-handle
# serving path stays tier-1 via test_runtime + the session hit-rate test
def test_c_api_handles_and_r5_routines(tmp_path):
    """Round-5 C API: opaque resident matrix handles + the newly
    generated families (hesv/pbsv/cond/norms/geqrf+ormqr), all driven
    from a genuinely compiled-and-linked C program."""
    exe, env = _build_c(tmp_path, C_TEST_R5, "t5")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=900)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "r5 ok" in r.stdout


def test_c_api_generated_symbol_count():
    """The generated library exports the full routine surface: >=30
    routine families x s/d/c/z plus the handle API (VERDICT r4 missing
    #2 'done' bar)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hdr = open(os.path.join(repo, "include",
                            "slate_tpu_capi_gen.h")).read()
    import re
    syms = set(re.findall(r"slate_tpu_(\w+)\(", hdr))
    assert len(syms) >= 140, len(syms)
    # handle API present in all four precisions + shared destroy
    for dt in "sdcz":
        assert f"matrix_create_{dt}" in syms
        assert f"matrix_from_buffer_{dt}" in syms
        assert f"matrix_to_buffer_{dt}" in syms
        assert f"hgemm_{dt}" in syms
    assert "matrix_destroy" in syms
    # the umbrella header pulls the generated one in (ADVICE r4 medium)
    cap = open(os.path.join(repo, "include", "slate_tpu_capi.h")).read()
    assert '#include "slate_tpu_capi_gen.h"' in cap


def test_lapack_sytrf_sytrs_unaligned_n(monkeypatch):
    """hetrf->hetrs round-trip token with n NOT a multiple of the block
    size (round-5 review repro: the padded perm/factor must shrink to
    LAPACK's n-sized buffers and re-grow losslessly)."""
    monkeypatch.setenv("SLATE_LAPACK_NB", "16")
    from slate_tpu.compat import lapack_api as lp
    rng = np.random.default_rng(41)
    n = 20  # npad = 32 with nb=16
    a = rng.standard_normal((n, n))
    a = a + a.T - 3 * np.eye(n)
    b = rng.standard_normal((n, 2))
    f, piv, info = lp.dsytrf("l", n, a, n)
    assert info == 0
    assert f.shape == (n, n) and piv.shape == (n,)  # LAPACK-shaped
    x, info = lp.dsytrs("l", n, 2, f, n, piv, b, n)
    assert info == 0
    assert np.allclose(a @ x, b, atol=1e-8), np.abs(a @ x - b).max()
    # and the one-shot driver agrees
    f2, piv2, x2, info = lp.dsysv("l", n, 2, a, n, b, n)
    assert info == 0 and np.allclose(a @ x2, b, atol=1e-8)


def test_lapack_pbsv_gbsv_upper_and_packed():
    """pbsv upper-storage path + gbsv ipiv semantics (round-5: LAPACK
    band rows map straight onto PackedBand rows, no dense round-trip)."""
    from slate_tpu.compat import lapack_api as lp
    rng = np.random.default_rng(42)
    n, kd = 40, 3
    m = rng.standard_normal((n, n))
    spd = m @ m.T + n * np.eye(n)
    band = np.zeros_like(spd)
    for t in range(-kd, kd + 1):
        band += np.diag(np.diag(spd, t), t)
    b = rng.standard_normal((n, 2))
    ab_u = np.zeros((kd + 1, n))
    for t in range(kd + 1):
        ab_u[kd - t, t:] = np.diag(band, t)
    x, info = lp.dpbsv("u", n, kd, 2, ab_u, kd + 1, b, n)
    assert info == 0
    assert np.allclose(band @ x, b, atol=1e-7), np.abs(band @ x - b).max()
    kl, ku = 2, 1
    gb = np.zeros((n, n))
    for t in range(-ku, kl + 1):
        gb += np.diag(np.diag(m, -t), -t)
    gb += n * np.eye(n)
    ab = np.zeros((2 * kl + ku + 1, n))
    for t in range(-ku, kl + 1):
        d = np.diag(gb, -t)
        if t >= 0:
            ab[kl + ku + t, : n - t] = d
        else:
            ab[kl + ku + t, -t:] = d
    x2, ipiv, info = lp.dgbsv(n, kl, ku, 2, ab, 2 * kl + ku + 1, b, n)
    assert info == 0
    assert np.allclose(gb @ x2, b, atol=1e-7)
    # LAPACK ipiv semantics: 1-based, row j swapped with ipiv[j],
    # displacement confined to the kl window
    assert ipiv.shape == (n,)
    assert np.all(ipiv >= np.arange(n) + 1)
    assert np.all(ipiv <= np.minimum(np.arange(n) + 1 + kl, n))


def test_lapack_trtri_sygv_hegv():
    """Round-5 C-API-parity additions: ?trtri (slate_triangular_inverse
    analog), ?sygv/?hegv (slate_generalized_hermitian_eig analog)."""
    n = 40
    a = RNG.standard_normal((n, n)) / n
    a[np.arange(n), np.arange(n)] = 2.0 + np.abs(a.diagonal())
    L = np.tril(a)
    inv, info = lp.dtrtri("L", "N", n, L, n)
    assert info == 0
    np.testing.assert_allclose(L @ inv, np.eye(n), atol=1e-10)
    # singular diagonal -> LAPACK info = first zero index
    Ls = L.copy(); Ls[4, 4] = 0.0
    _, info = lp.dtrtri("L", "N", n, Ls, n)
    assert info == 5

    b = _spd(n)
    s = RNG.standard_normal((n, n)); s = (s + s.T) / 2
    w, z, info = lp.dsygv(1, "V", "L", n, s, n, b, n)
    assert info == 0
    # reference via the standard transformation: B = C C^H,
    # eig(C^-1 S C^-H) are the generalized eigenvalues
    c = np.linalg.cholesky(b)
    m = np.linalg.solve(c, np.linalg.solve(c, s).T).T
    wref = np.linalg.eigvalsh((m + m.T) / 2)
    np.testing.assert_allclose(np.sort(w), wref, atol=1e-7 * max(
        1, np.abs(wref).max()))
    # eigenvector residual: S z = w B z
    r = s @ z - b @ z @ np.diag(w)
    assert np.abs(r).max() < 1e-6 * max(1, np.abs(s).max())
    # itype 2 (A·B·x = λ·x) and 3 (B·A·x = λ·x) via the hegst
    # congruence (reference src/hegv.cc supports all three)
    for itype, resid in ((2, lambda z, w: s @ (b @ z) - z @ np.diag(w)),
                         (3, lambda z, w: b @ (s @ z) - z @ np.diag(w))):
        for uplo in ("L", "U"):
            w, z, info = lp.dsygv(itype, "V", uplo, n, s, n, b, n)
            assert info == 0
            assert np.abs(resid(z, w)).max() < 1e-6 * max(
                1, np.abs(s).max(), np.abs(b).max())
    # out-of-range itype rejected with the LAPACK argument-1 code
    _, _, info = lp.dsygv(4, "N", "L", n, s, n, b, n)
    assert info == -1

    g = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    h = (g + g.conj().T) / 2
    bz = g @ g.conj().T / n + 2 * np.eye(n)
    w, z, info = lp.zhegv(1, "N", "L", n, h, n, bz, n)
    assert info == 0
    cz = np.linalg.cholesky(bz)
    mz = np.linalg.solve(cz, np.linalg.solve(cz, h).conj().T).conj().T
    wref = np.linalg.eigvalsh((mz + mz.conj().T) / 2)
    np.testing.assert_allclose(np.sort(w), wref, atol=1e-7 * max(
        1, np.abs(wref).max()))


@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
def test_c_api_trtri_sygv_nopiv_ctypes():
    """New generated C entries: slate_tpu_dtrtri, slate_tpu_dsygv,
    slate_tpu_dgesv_nopiv."""
    import ctypes

    lib = _capi_lib()
    i64 = ctypes.c_int64
    rng = np.random.default_rng(3)
    n = 16

    a = rng.standard_normal((n, n)) / n
    a[np.arange(n), np.arange(n)] = 2.0 + np.abs(a.diagonal())
    L = np.asfortranarray(np.tril(a))
    L0 = L.copy()
    lib.slate_tpu_dtrtri.restype = i64
    rc = lib.slate_tpu_dtrtri(
        ctypes.c_char_p(b"L"), ctypes.c_char_p(b"N"), i64(n),
        L.ctypes.data_as(ctypes.c_void_p), i64(n))
    assert rc == 0
    assert np.abs(L0 @ L - np.eye(n)).max() < 1e-10

    s = rng.standard_normal((n, n)); s = np.asfortranarray((s + s.T) / 2)
    g = rng.standard_normal((n, n))
    b = np.asfortranarray(g @ g.T / n + 2 * np.eye(n))
    s0, b0 = s.copy(), b.copy()
    w = np.zeros(n, np.float64)
    lib.slate_tpu_dsygv.restype = i64
    rc = lib.slate_tpu_dsygv(
        i64(1), ctypes.c_char_p(b"V"), ctypes.c_char_p(b"L"), i64(n),
        s.ctypes.data_as(ctypes.c_void_p), i64(n),
        b.ctypes.data_as(ctypes.c_void_p), i64(n),
        w.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    r = s0 @ s - b0 @ s @ np.diag(w)  # eigenvectors overwrote S
    assert np.abs(r).max() < 1e-6
    # LAPACK exit state: B holds its Cholesky factor (lower here)
    assert np.abs(np.tril(b) @ np.tril(b).T - b0).max() < 1e-8

    an = np.asfortranarray(rng.standard_normal((n, n)) + n * np.eye(n))
    bn = np.asfortranarray(rng.standard_normal((n, 2)))
    an0, bn0 = an.copy(), bn.copy()
    lib.slate_tpu_dgesv_nopiv.restype = i64
    rc = lib.slate_tpu_dgesv_nopiv(
        i64(n), i64(2), an.ctypes.data_as(ctypes.c_void_p), i64(n),
        bn.ctypes.data_as(ctypes.c_void_p), i64(n))
    assert rc == 0
    assert np.abs(an0 @ bn - bn0).max() < 1e-8


@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
def test_c_api_handle_verbs_ctypes():
    """Round-5 handle-verb extensions: hgesv (slate_lu_solve on
    handles), htrsm (slate_triangular_solve), hnorm (slate_norm) —
    a resident matrix flows factor -> solve -> norm with no host
    re-packing between calls."""
    import ctypes

    lib = _capi_lib()
    i64 = ctypes.c_int64
    dbl = ctypes.c_double
    rng = np.random.default_rng(5)
    n, nrhs = 24, 3

    a = np.asfortranarray(
        rng.standard_normal((n, n)) + n * np.eye(n))
    b = np.asfortranarray(rng.standard_normal((n, nrhs)))
    for f in ("matrix_from_buffer_d", "hgesv_d", "htrsm_d", "hnorm_d",
              "matrix_to_buffer_d", "matrix_destroy"):
        getattr(lib, "slate_tpu_" + f).restype = i64
    ha = lib.slate_tpu_matrix_from_buffer_d(
        i64(n), i64(n), a.ctypes.data_as(ctypes.c_void_p), i64(n), i64(8))
    hb = lib.slate_tpu_matrix_from_buffer_d(
        i64(n), i64(nrhs), b.ctypes.data_as(ctypes.c_void_p), i64(n),
        i64(8))
    assert ha > 0 and hb > 0
    # resident solve: X replaces B's handle content
    assert lib.slate_tpu_hgesv_d(i64(ha), i64(hb)) == 0
    x = np.asfortranarray(np.zeros((n, nrhs)))
    assert lib.slate_tpu_matrix_to_buffer_d(
        i64(hb), i64(n), i64(nrhs),
        x.ctypes.data_as(ctypes.c_void_p), i64(n)) == 0
    assert np.abs(a @ x - b).max() < 1e-8

    # resident triangular solve against the lower triangle of A
    hb2 = lib.slate_tpu_matrix_from_buffer_d(
        i64(n), i64(nrhs), b.ctypes.data_as(ctypes.c_void_p), i64(n),
        i64(8))
    assert lib.slate_tpu_htrsm_d(
        ctypes.c_char_p(b"L"), ctypes.c_char_p(b"L"),
        ctypes.c_char_p(b"N"), ctypes.c_char_p(b"N"), dbl(1.0),
        i64(ha), i64(hb2)) == 0
    y = np.asfortranarray(np.zeros((n, nrhs)))
    assert lib.slate_tpu_matrix_to_buffer_d(
        i64(hb2), i64(n), i64(nrhs),
        y.ctypes.data_as(ctypes.c_void_p), i64(n)) == 0
    assert np.abs(np.tril(a) @ y - b).max() < 1e-8

    # resident norm
    out = np.zeros(1, np.float64)
    assert lib.slate_tpu_hnorm_d(
        ctypes.c_char_p(b"1"), i64(ha),
        out.ctypes.data_as(ctypes.c_void_p)) == 0
    assert abs(out[0] - np.abs(a).sum(axis=0).max()) < 1e-9
    for h in (ha, hb, hb2):
        assert lib.slate_tpu_matrix_destroy(i64(h)) == 0


# -- opaque-handle solves share the serving runtime's Session ---------------

def _cm(x):
    """Column-major (LAPACK) buffer: a C-contiguous transpose."""
    return np.ascontiguousarray(np.asarray(x).T)


def test_capi_handle_solves_share_runtime_session():
    """The C-API opaque-handle solve verbs route through the shared
    slate_tpu.runtime Session: the first hgesv/hposv against a handle
    factors (cache miss), every further solve against the same handle
    reuses the resident factor (cache hit-rate climbs), and replacing or
    destroying the handle invalidates its cached factors."""
    from slate_tpu.compat import c_glue
    from slate_tpu.runtime import default_session

    sess = default_session()
    rng = np.random.default_rng(17)
    n, nrhs = 24, 2
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, nrhs))
    ha = c_glue.c_matrix_from_buffer("d", n, n, _cm(a), n, 8)
    hits0 = sess.metrics.get("cache_hits")
    misses0 = sess.metrics.get("cache_misses")

    solves = 4
    for _ in range(solves):
        hb = c_glue.c_matrix_from_buffer("d", n, nrhs, _cm(b), n, 8)
        assert c_glue.c_hgesv("d", ha, hb) == 0
        x = _cm(np.zeros((n, nrhs)))
        assert c_glue.c_matrix_to_buffer("d", hb, n, nrhs, x, n) == 0
        np.testing.assert_allclose(a @ x.T, b, atol=1e-8)
        assert c_glue.c_matrix_destroy("d", hb) == 0
    hits = sess.metrics.get("cache_hits") - hits0
    misses = sess.metrics.get("cache_misses") - misses0
    # one factorization amortized over all solves — each solve is ONE
    # factor-cache access, so hit-rate is exactly 1 - 1/solves
    assert misses == 1
    assert hits == solves - 1
    assert hits / (hits + misses) == 1 - 1 / solves

    # hposv shares the same session through its own (handle, chol) key
    spd = a @ a.T / n + n * np.eye(n)
    hs = c_glue.c_matrix_from_buffer("d", n, n, _cm(spd), n, 8)
    for _ in range(2):
        hb = c_glue.c_matrix_from_buffer("d", n, 1, _cm(b[:, :1]), n, 8)
        assert c_glue.c_hposv("d", "L", hs, hb) == 0
        assert c_glue.c_matrix_destroy("d", hb) == 0
    assert ("capi", hs, "chol", "L") in sess

    # destroying the handle unregisters its operators from the Session
    assert c_glue.c_matrix_destroy("d", hs) == 0
    assert ("capi", hs, "chol", "L") not in sess
    assert c_glue.c_matrix_destroy("d", ha) == 0
    assert ("capi", ha, "lu", None) not in sess
