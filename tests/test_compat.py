"""Compat API surfaces: LAPACK-style, ScaLAPACK-style, and the
C-callable embedded API.

Reference: lapack_api/ (drop-in dgesv_ etc.), scalapack_api/ (pdpotrf_
reading BLACS descriptors), tools/c_api + src/c_api/wrappers.cc.
"""

import os
import subprocess
import sysconfig
import textwrap

import numpy as np
import pytest

from slate_tpu.compat import lapack_api as lp
from slate_tpu.compat import scalapack_api as sc
from slate_tpu.interop import to_scalapack
import slate_tpu as st

RNG = np.random.default_rng(9)


def _spd(n, dtype=np.float64):
    a = RNG.standard_normal((n, n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


# -- LAPACK-style Python surface -------------------------------------------

def test_lapack_dgesv_roundtrip():
    n, nrhs = 48, 3
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    lu, ipiv, x, info = lp.dgesv(n, nrhs, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-10)
    # LAPACK ipiv semantics: applying the swaps reproduces P·A = L·U
    l = np.tril(lu, -1) + np.eye(n)
    u = np.triu(lu)
    pa = a.copy()
    for i, p in enumerate(ipiv):
        j = int(p) - 1
        pa[[i, j]] = pa[[j, i]]
    np.testing.assert_allclose(pa, l @ u, atol=1e-10)


def test_lapack_getrs_from_factors():
    n = 40
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, 2))
    lu, ipiv, _, info = lp.dgesv(n, 1, a, n, b[:, :1], n)
    x, info2 = lp.dgetrs("n", n, 2, lu, n, ipiv, b, n)
    assert info2 == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-9)


def test_lapack_dpotrf_dposv():
    n = 40
    a = _spd(n)
    f, info = lp.dpotrf("L", n, a, n)
    assert info == 0
    np.testing.assert_allclose(np.tril(f) @ np.tril(f).T, a, atol=1e-9)
    b = RNG.standard_normal((n, 2))
    x, info = lp.dposv("L", n, 2, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-9)


def test_lapack_sgesv_f32():
    n = 32
    a = RNG.standard_normal((n, n)).astype(np.float32)
    b = RNG.standard_normal((n, 1)).astype(np.float32)
    lu, ipiv, x, info = lp.sgesv(n, 1, a, n, b, n)
    assert info == 0
    np.testing.assert_allclose(a @ x, b, atol=1e-3)


def test_lapack_zheev_dsyev():
    n = 36
    a = _spd(n)
    w, z, info = lp.dsyev("V", "L", n, a, n)
    wref = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(w, wref, rtol=1e-9, atol=1e-9)
    assert np.abs(a @ z - z * w).max() < 1e-8
    c = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    c = 0.5 * (c + c.conj().T)
    w2, z2, info2 = lp.zheev("N", "L", n, c, n)
    np.testing.assert_allclose(w2, np.linalg.eigvalsh(c), rtol=1e-9,
                               atol=1e-9)
    assert z2 is None


def test_lapack_dgesvd_dgels():
    m, n = 50, 30
    a = RNG.standard_normal((m, n))
    s, u, vt, info = lp.dgesvd("S", "S", m, n, a, m)
    sref = np.linalg.svd(a, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s)[:n], sref, rtol=1e-9,
                               atol=1e-9)
    b = RNG.standard_normal((m, 2))
    x, info = lp.dgels("n", m, n, 2, a, m, b, m)
    xref, *_ = np.linalg.lstsq(a, b, rcond=None)
    np.testing.assert_allclose(x, xref, atol=1e-8)


# -- ScaLAPACK-style surface ------------------------------------------------

def test_scalapack_pdpotrf_in_place():
    n, nb, p, q = 48, 8, 2, 2
    a = _spd(n)
    A = st.from_dense(a, nb=nb)
    locals_ = [np.array(l) for l in to_scalapack(A, p, q)]
    desc = sc.make_desc(n, n, nb, p, q)
    info = sc.pdpotrf("L", n, locals_, desc)
    assert info == 0
    from slate_tpu.interop import from_scalapack
    F = from_scalapack(locals_, n, n, nb, p, q).to_numpy()
    np.testing.assert_allclose(np.tril(F) @ np.tril(F).T, a, atol=1e-9)
    # untouched triangle preserved (LAPACK in-place convention)
    np.testing.assert_allclose(np.triu(F, 1), np.triu(a, 1), atol=1e-12)


def test_scalapack_pdgesv_and_pdgemm():
    n, nrhs, nb, p, q = 40, 2, 8, 2, 2
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    al = [np.array(l) for l in to_scalapack(st.from_dense(a, nb=nb), p, q)]
    bl = [np.array(l) for l in to_scalapack(st.from_dense(b, nb=nb), p, q)]
    da = sc.make_desc(n, n, nb, p, q)
    db = sc.make_desc(n, nrhs, nb, p, q)
    info = sc.pdgesv_(n, nrhs, al, da, bl, db)
    assert info == 0
    from slate_tpu.interop import from_scalapack
    X = from_scalapack(bl, n, nrhs, nb, p, q).to_numpy()
    np.testing.assert_allclose(a @ X, b, atol=1e-9)

    cl = [np.array(l) for l in to_scalapack(
        st.from_dense(np.zeros((n, n)), nb=nb), p, q)]
    dc = sc.make_desc(n, n, nb, p, q)
    sc.pdgemm("n", "t", n, n, n, 1.0, al, da, al, da, 0.0, cl, dc)
    C = from_scalapack(cl, n, n, nb, p, q).to_numpy()
    np.testing.assert_allclose(C, a @ a.T, atol=1e-9)


def test_lapack_dgeqrf_tau_parity():
    # LAPACK semantics: a_out packs V\R, tau are the reflector scalars;
    # rebuilding Q = H_0·H_1·… from (a_out, tau) must reproduce A = Q·R
    m, n = 40, 24
    a = RNG.standard_normal((m, n))
    vr, tau, info = lp.dgeqrf(m, n, a, m)
    assert info == 0
    assert tau.shape == (n,)
    q = np.eye(m)
    for i in range(n):
        v = np.zeros(m)
        v[i] = 1.0
        v[i + 1:] = vr[i + 1:, i]
        q = q @ (np.eye(m) - tau[i] * np.outer(v, v))
    r = np.triu(vr)[:n, :]
    np.testing.assert_allclose(q[:, :n] @ r, a, atol=1e-9)
    np.testing.assert_allclose(q.T @ q, np.eye(m), atol=1e-9)


# -- C API (embedded interpreter) ------------------------------------------

C_TEST = r"""
#include <stdio.h>
#include <stdlib.h>
#include "slate_tpu_capi.h"

int main(void) {
    const int n = 24, nrhs = 2;
    double *a = malloc(n * n * sizeof(double));
    double *acopy = malloc(n * n * sizeof(double));
    double *b = malloc(n * nrhs * sizeof(double));
    double *bcopy = malloc(n * nrhs * sizeof(double));
    int64_t *ipiv = malloc(n * sizeof(int64_t));
    unsigned s = 12345;
    for (int i = 0; i < n * n; ++i) {
        s = s * 1103515245u + 12345u;
        a[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
    }
    for (int j = 0; j < n; ++j) a[j * n + j] += n;  /* dominant */
    for (int i = 0; i < n * nrhs; ++i) {
        s = s * 1103515245u + 12345u;
        b[i] = ((double)(s >> 8) / (1u << 24)) - 0.5;
    }
    for (int i = 0; i < n * n; ++i) acopy[i] = a[i];
    for (int i = 0; i < n * nrhs; ++i) bcopy[i] = b[i];
    int64_t info = slate_tpu_dgesv(n, nrhs, a, n, ipiv, b, n);
    if (info != 0) { printf("info=%lld\n", (long long)info); return 2; }
    /* residual: column-major A (acopy) times X (b) vs bcopy */
    double maxerr = 0.0;
    for (int c = 0; c < nrhs; ++c)
        for (int i = 0; i < n; ++i) {
            double acc = 0.0;
            for (int k = 0; k < n; ++k)
                acc += acopy[k * n + i] * b[c * n + k];
            double e = acc - bcopy[c * n + i];
            if (e < 0) e = -e;
            if (e > maxerr) maxerr = e;
        }
    printf("maxerr=%g\n", maxerr);
    return maxerr < 1e-8 ? 0 : 3;
}
"""


@pytest.mark.skipif(os.environ.get("SLATE_TPU_SKIP_CAPI") == "1",
                    reason="C toolchain test disabled")
def test_c_api_from_real_c_program(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    so = os.path.join(native, "libslate_tpu_capi.so")
    if not os.path.exists(so):
        subprocess.run(["make", "-C", native], check=True,
                       capture_output=True)
    csrc = tmp_path / "t.c"
    csrc.write_text(C_TEST)
    exe = tmp_path / "t"
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    subprocess.run(
        ["gcc", str(csrc), "-I", os.path.join(repo, "include"),
         "-L", native, "-lslate_tpu_capi", "-o", str(exe)],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["LD_LIBRARY_PATH"] = f"{native}:{libdir}:" + env.get(
        "LD_LIBRARY_PATH", "")
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    r = subprocess.run([str(exe)], env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "maxerr=" in r.stdout
