"""Round-6 factorization fast-path tests.

Covers the two composed mechanisms of the round-6 rework (ISSUE 2):

(a) PIVOT-FUSED LU trailing updates — the per-level row permutation is
    folded into the trailing-update gemm reads (gather-as-you-read +
    deferred left swaps, linalg/lu.py) instead of materializing a
    full-width permuted copy per level. Guarded here by bit-level
    equivalence against the materialized-copy reference arm
    (Options(lu_pivot_fusion=False)) across dtypes and the 8-device
    mesh, and by an HLO-level assertion that NO gather in the lowered
    program materializes a full-width row block.

(b) IN-PLACE ITERATIVE outer loops at large n for potrf (and the same
    recipe in geqrf) — trailing updates written slab-wise via
    dynamic_update_slice (blocked.herk_trailing_inplace), no per-level
    concatenation copies, with the Pallas tile/panel kernels as the
    base at every step. Guarded by dispatch-policy probes (the
    n=16384/nb=1024 headline shape must route to the iterative loop
    without compiling anything), HLO assertions (dynamic-update-slice
    present, no full-matrix concatenate), reassociation-tolerance
    parity against the legacy 2×2 recursion, and a wiring check that
    the Pallas bases sit on the default dispatch when a TPU backend is
    present.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import MethodLU, Options, Uplo
from slate_tpu.linalg import cholesky as chol_mod
from slate_tpu.linalg import lu as lu_mod
from slate_tpu.matgen import random_spd
from slate_tpu.ops import blocked, pallas_ops

RNG = np.random.default_rng(61)

_LEGACY = Options(lu_pivot_fusion=False)


def _randn(m, n, dtype):
    a = RNG.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * RNG.standard_normal((m, n))
    return np.asarray(a, dtype)


# -- (a) pivot fusion: bit-level equivalence --------------------------------

@pytest.mark.parametrize("dtype,n,nb", [
    (np.float32, 96, 32),
    # the ragged 136 arm (~7 s, its own padded-shape compile) rides
    # the slow lane (round-22 tier-1 budget); ragged/pad isolation
    # stays pinned by test_uneven_grid.py, and f32 fusion bit-identity
    # by the 96 arm above
    pytest.param(np.float32, 136, 32, marks=pytest.mark.slow),
    (np.float64, 64, 32),  # 2 panels: trailing + suffix fix-up both hit
    (np.complex64, 64, 32), (np.complex128, 64, 32),
])
def test_getrf_pivot_fusion_bit_identical(dtype, n, nb):
    """Fused vs materialized must agree BIT FOR BIT: the fusion only
    reorders row reads (gathers are exact) — every arithmetic op sees
    the same values in the same order."""
    a = _randn(n, n, dtype)
    A = st.from_dense(a, nb=nb)
    LUf, pf, inf_f = st.getrf(A)
    LUm, pm, inf_m = st.getrf(A, _LEGACY)
    assert int(inf_f) == int(inf_m)
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(pm))
    np.testing.assert_array_equal(np.asarray(LUf.data), np.asarray(LUm.data))


@pytest.mark.parametrize("dtype", [
    # f32 arm (~10 s) rides the slow lane (round-10 headroom); the
    # f64 arm (~11 s) follows in round 22 — tntpiv numerics stay
    # pinned by test_lu.py::test_getrf_tntpiv and pivot-fusion
    # bit-identity by the plain-getrf f64 arm of
    # test_getrf_pivot_fusion_bit_identical
    pytest.param(np.float32, marks=pytest.mark.slow),
    pytest.param(np.float64, marks=pytest.mark.slow)])
def test_getrf_tntpiv_pivot_fusion_bit_identical(dtype):
    """Same guarantee for the CALU/tournament driver."""
    n, nb = 128, 32
    a = _randn(n, n, dtype)
    A = st.from_dense(a, nb=nb)
    calu = Options(method_lu=MethodLU.CALU)
    LUf, pf, _ = st.getrf(A, calu)
    LUm, pm, _ = st.getrf(A, calu.replace(lu_pivot_fusion=False))
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(pm))
    np.testing.assert_array_equal(np.asarray(LUf.data), np.asarray(LUm.data))


def test_getrf_threshold_pivot_fusion_bit_identical():
    """And for the PivotThreshold (tournament-panel) arm of the
    iterative loop."""
    n, nb = 96, 32
    a = _randn(n, n, np.float64)
    A = st.from_dense(a, nb=nb)
    thr = Options(pivot_threshold=0.5)
    LUf, pf, _ = st.getrf(A, thr)
    LUm, pm, _ = st.getrf(A, thr.replace(lu_pivot_fusion=False))
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(pm))
    np.testing.assert_array_equal(np.asarray(LUf.data), np.asarray(LUm.data))


def test_gesv_getrs_through_fused_factors():
    """getrs/gesv threaded through the fused factors solve correctly
    and identically to the materialized arm (the b[perm] gather of
    getrs reads the SAME total permutation either way)."""
    n, nb, nrhs = 128, 32, 4
    a = _randn(n, n, np.float64)
    b = _randn(n, nrhs, np.float64)
    A, B = st.from_dense(a, nb=nb), st.from_dense(b, nb=nb)
    Xf, inf_f = st.gesv(A, B)
    Xm, inf_m = st.gesv(A, B, _LEGACY)
    np.testing.assert_array_equal(np.asarray(Xf.data), np.asarray(Xm.data))
    res = np.abs(a @ np.asarray(Xf.to_numpy()) - b).max() / (
        np.linalg.norm(a, 1) * np.finfo(np.float64).eps * n)
    assert res < 30.0
    # trans solve through the fused factor
    LU, perm, _ = st.getrf(A)
    Xt = lu_mod.getrs(LU, perm, B, trans=True)
    np.testing.assert_allclose(np.asarray(Xt.to_numpy()),
                               np.linalg.solve(a.T, b),
                               rtol=1e-9, atol=1e-10)


@pytest.mark.slow
def test_getrf_pivot_fusion_bit_identical_mesh(grid2x4):
    """Bit-level equivalence must survive the 8-device mesh (the
    deferred-left-swap suffix gathers become collective traffic there),
    and the mesh result must match the 1×1 grid. Slow (round-20 tier-1
    budget: two n=256 8-device factor compiles). Tier-1 siblings: the
    single-device pivot-fusion bit-identity params above, and
    test_distribution.py::test_grid_matches_single_device[getrf] for
    mesh-getrf agreement."""
    # nb=32 keeps this test on the round-6 shape; the (256, nb=64)
    # corruption recorded here as an open item was ROOT-CAUSED AND
    # FIXED in round 7 (two pre-0.6 partitioner mis-lowerings:
    # blocked.lift_tail_perm + blocked.replicate_on_grid) and is
    # regression-pinned at nb=64 in tests/test_lookahead.py.
    n, nb = 256, 32
    a = _randn(n, n, np.float64)
    Ag = st.from_dense(a, nb=nb, grid=grid2x4)
    LUf, pf, _ = st.getrf(Ag)
    LUm, pm, _ = st.getrf(Ag, _LEGACY)
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(pm))
    np.testing.assert_array_equal(np.asarray(LUf.data), np.asarray(LUm.data))
    LU1, p1, _ = st.getrf(st.from_dense(a, nb=nb))
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(p1))
    np.testing.assert_allclose(np.asarray(LUf.to_numpy()),
                               np.asarray(LU1.to_numpy()),
                               rtol=1e-13, atol=1e-13)


# -- (a) pivot fusion: HLO-level traffic guard ------------------------------

_GATHER_RE = re.compile(
    r'stablehlo\.gather.*->\s*tensor<(\d+)x(\d+)x(f32|f64)>')


def _fullwidth_gather_count(opts, n=192, nb=64):
    """Count 2-D gathers in the LOWERED getrf program whose result is a
    FULL-width (npad-column) row block — the materialized permuted copy
    the fused path must never create. Lowered (pre-fusion) StableHLO is
    the right level: the property is structural, not an artifact of the
    backend's fusion decisions."""
    a = RNG.standard_normal((n, n)).astype(np.float32)
    A = st.from_dense(a, nb=nb)

    def f(A):
        return st.getrf(A, opts)[0].data

    txt = jax.jit(f).lower(A).as_text()
    widths = [int(m.group(2)) for m in _GATHER_RE.finditer(txt)]
    return sum(1 for w in widths if w == n)


def test_hlo_getrf_fused_has_no_fullwidth_permuted_copy():
    """THE traffic assertion of ISSUE 2(a): the default getrf's
    per-level trailing update contains NO materialized full-width
    permuted row block — its gathers are the nb-row pivot read, the
    (n−k1)-wide trailing read fused into the Schur subtract, and the
    nb-wide deferred-left-swap blocks. The legacy arm (the same
    program with lu_pivot_fusion=False) must show the per-level
    full-width gather, proving the probe detects what it claims to."""
    assert _fullwidth_gather_count(Options()) == 0
    assert _fullwidth_gather_count(_LEGACY) >= 1


def test_hlo_getrf_tntpiv_fused_has_no_fullwidth_permuted_copy():
    assert _fullwidth_gather_count(Options(method_lu=MethodLU.CALU)) == 0
    assert _fullwidth_gather_count(
        Options(method_lu=MethodLU.CALU, lu_pivot_fusion=False)) >= 1


# -- (b) in-place iterative outer loops -------------------------------------

def test_iter_dispatch_policy_covers_headline_shapes():
    """The round-6 dispatch must route the BENCH headline shapes
    (n=16384, nb=1024 — and every nt ≤ 64 shape) to the iterative
    in-place loop; the recursion survives only past the HLO-size guard.
    Pure policy probe: nothing is compiled."""
    assert chol_mod._iter_eligible(16384, 1024)
    assert lu_mod._iter_eligible(16384, 1024)
    assert chol_mod._iter_eligible(65536, 1024)   # nt = 64, boundary
    assert not chol_mod._iter_eligible(16384, 128)  # nt = 128 > guard
    assert not lu_mod._iter_eligible(16384 + 512, 1024)  # ragged width


def test_potrf_dispatch_routes_to_iter_by_default(monkeypatch):
    calls = {"iter": 0, "rec": 0}
    for name in ("_potrf_iter", "_potrf_rec"):
        orig = getattr(chol_mod, name)
        key = name.split("_")[-1]

        def spy(*a, _o=orig, _k=key, **kw):
            calls[_k] += 1
            return _o(*a, **kw)

        monkeypatch.setattr(chol_mod, name, spy)
    a = np.asarray(random_spd(192, dtype=jnp.float64, seed=5))
    A = st.hermitian(np.tril(a), nb=64, uplo=Uplo.Lower)
    st.potrf(A)
    assert calls["iter"] == 1 and calls["rec"] == 0
    st.potrf(A, Options(factor_iter_large=False))
    assert calls["rec"] >= 1


def test_potrf_iter_matches_recursion_within_reassociation(monkeypatch):
    """The in-place iterative loop reassociates the trailing update
    (slab gemms vs the recursion's split gemms), so the two dispatches
    agree to factorization accuracy, not bitwise. Force the TRUE
    recursion (crossover to 0 so its iterative base case never runs)
    and compare."""
    n, nb = 128, 32
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=13))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
    L1, i1 = st.potrf(A)
    monkeypatch.setattr(chol_mod, "_POTRF_ITER_BASE", 0)
    L0, i0 = st.potrf(A, Options(factor_iter_large=False))
    assert int(i1) == int(i0) == 0
    scale = np.linalg.norm(a, 1) * n * np.finfo(np.float64).eps
    assert np.abs(L1.to_numpy() - L0.to_numpy()).max() < 10 * scale


def test_hlo_potrf_iter_updates_in_place_no_full_concat(monkeypatch):
    """ISSUE 2(b) HLO guard: the default potrf outer loop updates the
    trailing matrix via dynamic_update_slice and builds NO full-matrix
    concatenation (the recursion's per-level copies). The legacy
    recursion arm (crossover forced to 0 so its iterative base case
    never runs) must show the full-size concatenate, proving the probe
    detects it."""
    n, nb = 256, 32
    a = np.asarray(random_spd(n, dtype=jnp.float32, seed=3))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)

    def lower_text(opts):
        def f(A):
            return st.potrf(A, opts)[0].data
        return jax.jit(f).lower(A).as_text()

    cat = re.compile(r'stablehlo\.concatenate.*->\s*tensor<'
                     + str(n) + r'x' + str(n) + r'xf32>')
    txt = lower_text(Options())
    assert "stablehlo.dynamic_update_slice" in txt
    assert not cat.search(txt), \
        "default potrf still concatenates a full-size trailing copy"
    monkeypatch.setattr(chol_mod, "_POTRF_ITER_BASE", 0)
    legacy = lower_text(Options(factor_iter_large=False))
    assert cat.search(legacy), "probe lost its reference signal"


def test_hlo_geqrf_updates_in_place():
    """geqrf mirrors the recipe: panel + trailing writes are
    dynamic_update_slice into the resident matrix; no full-size
    concatenate appears in the lowered program."""
    m, n, nb = 256, 192, 64
    a = RNG.standard_normal((m, n)).astype(np.float32)
    A = st.from_dense(a, nb=nb)

    def f(A):
        return st.geqrf(A).vr

    txt = jax.jit(f).lower(A).as_text()
    assert "stablehlo.dynamic_update_slice" in txt
    assert not re.search(r'stablehlo\.concatenate.*->\s*tensor<'
                         + str(m) + r'x' + str(n) + r'xf32>', txt)


def test_herk_trailing_inplace_matches_reference():
    """blocked.herk_trailing_inplace == the masked dense update on the
    lower trapezoid (strict upper of the trailing block is untouched
    garbage by contract — compare tril only)."""
    s, k1, nb = 160, 32, 32
    a = RNG.standard_normal((s, s))
    pan = RNG.standard_normal((s - k1, nb))
    out = np.asarray(blocked.herk_trailing_inplace(
        jnp.asarray(a), jnp.asarray(pan), k1, nb))
    ref = a.copy()
    ref[k1:, k1:] -= pan @ pan.T
    np.testing.assert_allclose(np.tril(out[k1:, k1:]),
                               np.tril(ref[k1:, k1:]),
                               rtol=1e-12, atol=1e-12)
    # region above/left of the trailing block is untouched
    np.testing.assert_array_equal(out[:k1, :], a[:k1, :])
    np.testing.assert_array_equal(out[:, :k1], a[:, :k1])


def test_pallas_tile_bases_sit_on_default_dispatch(monkeypatch):
    """Wiring check (CPU host): with a TPU backend reported, the
    eligibility gates admit the bench headline tile/panel shapes, and
    the default potrf dispatch consults the Pallas tile base at EVERY
    panel step of the iterative loop."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert pallas_ops.chol_eligible(1024, jnp.float32.dtype)
    assert pallas_ops.lu_panel_eligible(8192, 32, jnp.float32.dtype)
    assert pallas_ops.qr_panel_eligible(8192, 32, jnp.float32.dtype)

    # (1) the iterative loop invokes the tile base once PER STEP: spy on
    # the (jit-cached) _tile_chol entry the loop calls eagerly
    steps = {"tile_chol": 0}
    orig_tile = chol_mod._tile_chol

    def spy_tile(akk, _o=orig_tile):
        steps["tile_chol"] += 1
        return _o(akk)

    monkeypatch.setattr(chol_mod, "_tile_chol", spy_tile)
    # (2) the tile base consults the Pallas gate/kernel (trace-time —
    # jit caches mean the consult happens once per shape, so clear it)
    consults = {"eligible": 0}

    def fake_eligible(b, dtype):
        consults["eligible"] += 1
        return True

    def fake_chol_tile(a, **kw):
        # stand-in so the "kernel" path executes on this CPU host
        return jnp.tril(jax.lax.linalg.cholesky(a, symmetrize_input=False))

    monkeypatch.setattr(pallas_ops, "chol_eligible", fake_eligible)
    monkeypatch.setattr(pallas_ops, "chol_tile", fake_chol_tile)
    try:
        orig_tile.clear_cache()
    except AttributeError:
        pass
    try:
        n, nb = 256, 64
        a = np.asarray(random_spd(n, dtype=jnp.float32, seed=21))
        A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
        L, info = st.potrf(A)
        assert int(info) == 0
        assert steps["tile_chol"] == n // nb, \
            "iterative loop must hit the tile base at every panel step"
        assert consults["eligible"] >= 1, \
            "tile base never consulted the Pallas gate"
        ln = np.tril(L.to_numpy())
        r = np.linalg.norm(a - ln @ ln.T, 1) / (
            np.linalg.norm(a, 1) * n * np.finfo(np.float32).eps)
        assert r < 30.0
    finally:
        # drop the fake-kernel trace so later tests re-trace the real one
        try:
            orig_tile.clear_cache()
        except AttributeError:
            pass
