"""QR/LQ/least-squares tests — ‖A − QR‖ and ‖QᴴQ − I‖ residuals like the
reference's test/test_geqrf.cc and test/test_gels.cc.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import MethodGels, Options, Side

RNG = np.random.default_rng(31)
EPS = np.finfo(float).eps


def _check_qr(a, Q, R, tol=50.0):
    m, n = a.shape
    q = Q.to_numpy()
    r = np.triu(R.to_numpy())
    assert np.linalg.norm(a - q @ r, 1) / (np.linalg.norm(a, 1) * m * EPS) < tol
    assert np.linalg.norm(q.T.conj() @ q - np.eye(q.shape[1]), 1) / (m * EPS) < tol


@pytest.mark.parametrize("m,n,nb", [
    (48, 48, 16), (50, 30, 16),
    # multi-panel small-nb arm (~6 s) rides the slow lane (round-10
    # headroom); square + rectangular arms keep QR/unmqr in tier-1
    pytest.param(40, 24, 8, marks=pytest.mark.slow)])
def test_geqrf_unmqr(m, n, nb):
    a = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=nb)
    QR = st.geqrf(A)
    Q = st.qr_multiply_explicit(QR)
    _check_qr(a, Q, QR.r_matrix)


def test_geqrf_complex():
    m, n = 32, 20
    a = RNG.standard_normal((m, n)) + 1j * RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=8)
    QR = st.geqrf(A)
    Q = st.qr_multiply_explicit(QR)
    q = Q.to_numpy()
    r = np.triu(QR.r_matrix.to_numpy())
    assert np.linalg.norm(a - q @ r) / np.linalg.norm(a) < 1e-13
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-13


def test_unmqr_right_and_roundtrip():
    m, n = 36, 24
    a = RNG.standard_normal((m, n))
    QR = st.geqrf(st.from_dense(a, nb=8))
    c = RNG.standard_normal((m, 5))
    C = st.from_dense(c, nb=8)
    QtC = st.unmqr(Side.Left, QR, C, trans=True)
    back = st.unmqr(Side.Left, QR, QtC, trans=False)
    np.testing.assert_allclose(back.to_numpy(), c, rtol=1e-10, atol=1e-12)
    # right-side: D·Q then (D·Q)·Qᴴ roundtrip
    d = RNG.standard_normal((5, m))
    D = st.from_dense(d, nb=8)
    DQ = st.unmqr(Side.Right, QR, D, trans=False)
    back2 = st.unmqr(Side.Right, QR, DQ, trans=True)
    np.testing.assert_allclose(back2.to_numpy(), d, rtol=1e-10, atol=1e-12)


def test_gelqf_unmlq():
    m, n = 20, 44
    a = RNG.standard_normal((m, n))
    LQ = st.gelqf(st.from_dense(a, nb=8))
    # L = Rᴴ of the QR of Aᴴ
    l = np.tril(LQ.r_matrix.H.to_numpy())
    # reconstruct: A = L·Qlq where Qlq rows orthonormal
    eye_rows = -(-n // 8) * 8
    I = st.from_dense(np.eye(eye_rows, m), nb=8,
                      logical_shape=(n, m))
    Qlq_H = st.unmlq(Side.Left, LQ, I, trans=True)  # Qlqᴴ·I = Qlqᴴ (n×m)
    qlq = Qlq_H.to_numpy().T.conj()  # (m × n)
    assert np.linalg.norm(a - l @ qlq, 1) / (np.linalg.norm(a, 1) * n * EPS) < 100


def test_cholqr():
    m, n = 60, 12
    a = RNG.standard_normal((m, n))
    Q, R = st.cholqr(st.from_dense(a, nb=12))
    _check_qr(a, Q, R)


def test_tsqr():
    m, n = 128, 8
    a = RNG.standard_normal((m, n))
    Q, R = st.tsqr(st.from_dense(a, nb=8))
    _check_qr(a, Q, R)


def test_tsqr_matches_reference_r():
    # |R| from tsqr must match |R| from numpy QR (up to sign)
    m, n = 64, 8
    a = RNG.standard_normal((m, n))
    _, R = st.tsqr(st.from_dense(a, nb=8))
    r_ref = np.linalg.qr(a, mode="r")
    np.testing.assert_allclose(np.abs(np.triu(R.to_numpy())), np.abs(r_ref),
                               rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("method", [MethodGels.QR, MethodGels.CholQR])
def test_gels_overdetermined(method):
    m, n, nrhs = 50, 20, 3
    a = RNG.standard_normal((m, n))
    b = RNG.standard_normal((m, nrhs))
    X = st.gels(st.from_dense(a, nb=8), st.from_dense(b, nb=8),
                Options(method_gels=method))
    x_ref, *_ = np.linalg.lstsq(a, b, rcond=None)
    np.testing.assert_allclose(X.to_numpy()[:n], x_ref, rtol=1e-8, atol=1e-9)


def test_gels_underdetermined():
    m, n, nrhs = 18, 40, 2
    a = RNG.standard_normal((m, n))
    b = RNG.standard_normal((m, nrhs))
    X = st.gels(st.from_dense(a, nb=8), st.from_dense(b, nb=8))
    x_ref, *_ = np.linalg.lstsq(a, b, rcond=None)  # minimum-norm solution
    np.testing.assert_allclose(X.to_numpy()[:n], x_ref, rtol=1e-8, atol=1e-9)


def test_geqrf_jit_and_grid(grid2x2):
    m, n = 64, 32
    a = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=16, grid=grid2x2)

    @jax.jit
    def f(A):
        return st.geqrf(A)

    QR = f(A)
    Q = st.qr_multiply_explicit(QR)
    _check_qr(a, Q, QR.r_matrix)


@pytest.mark.parametrize("dtype,w,n", [
    # the large f64 arm (~5 s) rides the slow lane (round-10
    # headroom); the complex arm keeps the closed form pinned
    pytest.param(np.float64, 128, 512, marks=pytest.mark.slow),
    (np.complex128, 96, 300)])
def test_larft_closed_form_matches_recurrence(dtype, w, n):
    """larft's closed form T = D·(I + striu(VᴴV)·D)⁻¹ must reproduce
    LAPACK's column recurrence (_larft_base) to machine precision,
    including exact zeros for degenerate (tau = 0) columns."""
    from slate_tpu.ops import blocked
    a = RNG.standard_normal((n, w)).astype(dtype)
    if np.iscomplexobj(a):
        a = a + 1j * RNG.standard_normal((n, w))
    vr, taus = blocked._panel_geqrf_base(jnp.asarray(a))
    v = blocked._split_v(vr, w)
    t_new = np.asarray(blocked.larft(v, taus))
    t_ref = np.asarray(blocked._larft_base(v, taus))
    assert np.abs(t_new - t_ref).max() / np.abs(t_ref).max() < 1e-13
    taus0 = jnp.zeros((w,), dtype)
    assert np.abs(np.asarray(blocked.larft(v, taus0))).max() == 0
