"""Core data-model tests.

Mirrors the reference's unit_test/test_Matrix.cc (constructors, views,
sub, slice, transpose) and test_func.cc (distribution index maps).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.grid import (cyclic_permutation, inverse_permutation,
                                 num_tiles, tile_dim, tile_rank_2d)
from slate_tpu.core.types import Diag, MatrixKind, Op, Uplo


def test_num_tiles_and_dim():
    assert num_tiles(100, 32) == 4
    assert num_tiles(96, 32) == 3
    assert tile_dim(3, 100, 32) == 4
    assert tile_dim(0, 100, 32) == 32
    assert tile_dim(2, 96, 32) == 32


def test_tile_rank_2d():
    # 2D block-cyclic: tile (i, j) -> (i mod p, j mod q) (func.hh:100)
    p, q = 2, 3
    ranks = {(i, j): tile_rank_2d(i, j, p, q) for i in range(4) for j in range(6)}
    assert ranks[(0, 0)] == ranks[(2, 0)] == ranks[(0, 3)]
    assert len(set(ranks.values())) == p * q


def test_cyclic_permutation_roundtrip():
    for nt, p in [(7, 2), (8, 4), (5, 3), (1, 4)]:
        perm = cyclic_permutation(nt, p)
        inv = inverse_permutation(perm)
        for i in range(nt):
            assert perm[inv[i]] == i
        per = -(-nt // p)
        for pi in range(p):
            chunk = perm[pi * per:(pi + 1) * per]
            owned = [t for t in chunk if t >= 0]
            assert all(t % p == pi for t in owned)


def test_from_dense_roundtrip():
    a = np.arange(30.0).reshape(5, 6)
    A = st.from_dense(a, nb=4)
    assert A.data.shape == (8, 8)  # padded
    assert A.shape == (5, 6)
    assert A.mt == 2 and A.nt == 2
    np.testing.assert_array_equal(A.to_numpy(), a)


def test_transpose_views():
    a = np.arange(12.0).reshape(3, 4)
    A = st.from_dense(a, nb=2)
    At = A.T
    assert At.shape == (4, 3)
    np.testing.assert_array_equal(At.to_numpy(), a.T)
    np.testing.assert_array_equal(At.T.to_numpy(), a)
    # conj transpose on complex
    c = (a + 1j * a).astype(np.complex64)
    C = st.from_dense(c, nb=2)
    np.testing.assert_array_equal(C.H.to_numpy(), c.conj().T)
    np.testing.assert_array_equal(C.H.H.to_numpy(), c)
    np.testing.assert_array_equal(C.T.H.to_numpy(), c.conj())


def test_tile_access():
    a = np.arange(64.0).reshape(8, 8)
    A = st.from_dense(a, nb=4)
    np.testing.assert_array_equal(np.asarray(A.tile(1, 0)), a[4:8, 0:4])
    B = A.with_tile(0, 1, jnp.zeros((4, 4)))
    out = B.to_numpy()
    assert (out[0:4, 4:8] == 0).all()
    assert (out[4:8, 0:4] == a[4:8, 0:4]).all()


def test_sub_and_slice():
    a = np.arange(81.0).reshape(9, 9)
    A = st.from_dense(a, nb=3)
    S = A.sub(1, 2, 0, 1)
    np.testing.assert_array_equal(S.to_numpy(), a[3:9, 0:6])
    Z = A.slice(2, 6, 1, 7)
    np.testing.assert_array_equal(Z.to_numpy(), a[2:7, 1:8])


def test_full_dense_symmetric_hermitian():
    a = np.triu(np.arange(16.0).reshape(4, 4)) + 4 * np.eye(4)
    A = st.symmetric(a, nb=2, uplo=Uplo.Upper)
    f = np.asarray(A.full_dense())
    np.testing.assert_array_equal(f, np.triu(a) + np.triu(a, 1).T)

    c = (np.tril(np.arange(16.0).reshape(4, 4)) + 1j * np.tril(np.ones((4, 4)), -1))
    c = c.astype(np.complex128)
    H = st.hermitian(c, nb=2, uplo=Uplo.Lower)
    f = np.asarray(H.full_dense())
    np.testing.assert_allclose(f, np.tril(c) + np.tril(c, -1).conj().T)
    assert np.allclose(np.imag(np.diagonal(f)), 0)


def test_full_dense_triangular_unit():
    a = np.arange(16.0).reshape(4, 4) + 1
    T = st.triangular(a, nb=2, uplo=Uplo.Lower, diag=Diag.Unit)
    f = np.asarray(T.full_dense())
    expect = np.tril(a, -1) + np.eye(4)
    np.testing.assert_array_equal(f, expect)


def test_band_mask():
    a = np.ones((6, 6))
    B = st.band(a, nb=2, kl=1, ku=2)
    f = np.asarray(B.full_dense())[:6, :6]
    r, c = np.indices((6, 6))
    expect = ((c - r <= 2) & (r - c <= 1)).astype(float)
    np.testing.assert_array_equal(f, expect)


def test_shard_2x2(grid2x2):
    a = np.arange(64.0).reshape(8, 8)
    A = st.from_dense(a, nb=2, grid=grid2x2)
    assert len(A.data.sharding.device_set) == 4
    np.testing.assert_array_equal(A.to_numpy(), a)


def test_pytree_jit_roundtrip():
    a = np.arange(16.0).reshape(4, 4)
    A = st.from_dense(a, nb=2)

    @jax.jit
    def f(M: st.TiledMatrix):
        return M.with_data(M.data * 2.0)

    B = f(A)
    np.testing.assert_array_equal(B.to_numpy(), 2 * a)
    assert B.nb == 2 and B.shape == (4, 4)


def test_pad_diag_identity():
    a = np.eye(5) * 3.0
    A = st.from_dense(a, nb=4)  # padded to 8x8
    P = st.pad_diag_identity(A)
    d = np.asarray(P.data)
    assert (np.diagonal(d)[5:] == 1.0).all()
    np.testing.assert_array_equal(P.to_numpy(), a)


@pytest.mark.slow  # ~14 s (round-10 headroom); trtri stays covered by
# the compat trtri test and every trsm-consuming factorization suite
def test_trtri_lower_batched_matches_recursion():
    """The batched-leaf inverse (round-4 panel kernel) against the plain
    recursion and numpy, unit and non-unit, aligned and fallback.
    Inputs carry garbage in the strict upper triangle (must be ignored)
    and a non-unit stored diagonal in the unit case (unit=True must
    ignore the stored diagonal)."""
    from slate_tpu.ops import blocked

    rng = np.random.default_rng(0)
    for n, leaf in ((256, 64), (1024, 64), (96, 64)):  # 96: fallback
        # scale off-diagonals down: a random triangle's inverse grows
        # exponentially in n, which would swamp any entrywise check
        l = np.tril(rng.standard_normal((n, n))) / np.sqrt(n)
        l[np.arange(n), np.arange(n)] = 2.0 + np.abs(l.diagonal())
        # garbage above the diagonal: only the lower triangle is read
        lu = l + np.triu(rng.standard_normal((n, n)), 1) * 1e3
        for unit in (False, True):
            got = np.asarray(blocked.trtri_lower_batched(
                jnp.asarray(lu, jnp.float64), unit=unit, leaf=leaf))
            # the effective matrix: stored diagonal for non-unit,
            # implicit ones (stored diagonal IGNORED) for unit
            tl = np.tril(lu)
            if unit:
                tl = np.tril(lu, -1) + np.eye(n)
            res = np.abs(tl @ got - np.eye(n)).max()
            bound = n * 1e-14 * np.linalg.norm(tl, 1) * np.linalg.norm(
                got, 1)
            assert res < bound, (n, leaf, unit, res, bound)
            rec = np.asarray(blocked.trtri_lower_rec(
                jnp.asarray(lu, jnp.float64), unit=unit))
            rel = np.abs(got - rec).max() / max(np.abs(rec).max(), 1.0)
            assert rel < n * 1e-14


def test_trtri_lower_batched_complex():
    from slate_tpu.ops import blocked

    rng = np.random.default_rng(1)
    n = 128
    l = np.tril(rng.standard_normal((n, n))
                + 1j * rng.standard_normal((n, n))) / np.sqrt(n)
    l[np.arange(n), np.arange(n)] = 2.0 + np.abs(l.diagonal())
    got = np.asarray(blocked.trtri_lower_batched(
        jnp.asarray(l, jnp.complex128)))
    res = np.abs(l @ got - np.eye(n)).max()
    assert res < n * 1e-14 * np.linalg.norm(l, 1) * np.linalg.norm(got, 1)
