"""Packed band storage + band-aware factorization tests.

Reference: src/pbtrf.cc, src/gbtrf.cc, src/tbsm.cc (in-band-only
compute). VERDICT round-1 item 8: storage must be O(n·(kl+ku)) and the
kernels must never densify.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.linalg import band_packed as bp

RNG = np.random.default_rng(3)


def _spd_band(n, kd):
    a = np.zeros((n, n))
    for off in range(kd + 1):
        d = RNG.standard_normal(n - off)
        a += np.diag(d, -off) + (np.diag(d, off) if off else 0)
    return a + (2 * kd + 4) * np.eye(n)


def _gen_band(n, kl, ku, dominant=True):
    a = np.zeros((n, n))
    for off in range(-ku, kl + 1):
        a += np.diag(RNG.standard_normal(n - abs(off)), -off)
    if dominant:
        a += (kl + ku + 3) * np.diag(np.sign(RNG.standard_normal(n)))
    return a


@pytest.mark.parametrize("n,kd,nb", [
    # the largest-n arm (~5 s) rides the slow lane (round-10
    # headroom); four arms incl. kd>nb and kd=0 stay tier-1
    pytest.param(200, 12, 16, marks=pytest.mark.slow),
    (150, 7, 8), (64, 0, 8), (100, 30, 16), (129, 5, 16)])
def test_pbtrf_pbsv_packed(n, kd, nb):
    a = _spd_band(n, kd)
    A = bp.pb_pack(a, kd)
    assert A.ab.shape == (kd + 1, n)  # O(n·kd) storage
    np.testing.assert_allclose(np.asarray(A.to_dense()), a, atol=1e-14)
    L, info = bp.pbtrf(A, nb=nb)
    assert int(info) == 0
    np.testing.assert_allclose(np.tril(np.asarray(L.to_dense())),
                               np.linalg.cholesky(a), atol=1e-11)
    b = RNG.standard_normal((n, 3))
    x, _ = bp.pbsv(A, b, nb=nb)
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-10)


def test_pbtrf_not_spd_info():
    n, kd = 64, 4
    a = _spd_band(n, kd)
    a[30, 30] = -100.0  # break positive definiteness
    L, info = bp.pbtrf(bp.pb_pack(a, kd), nb=8)
    assert int(info) > 0


def test_pbsv_large_n_packed_memory():
    """n=16384, kd=64: packed storage is ~8 MB f64 where dense would be
    2 GB — the whole point of the packed path (VERDICT item 8)."""
    n, kd = 16384, 64
    diag = 4.0 * (2 * kd + 1) * np.ones(n)
    ab = np.concatenate([diag[None, :],
                         RNG.standard_normal((kd, n))])
    A = bp.PackedBand(jnp.asarray(ab), n, kd, 0, hermitian=True)
    assert A.ab.size * 8 < 20e6
    b = RNG.standard_normal(n)
    x, info = bp.pbsv(A, b, nb=64)
    assert int(info) == 0
    # verify the residual band-wise (no dense materialization)
    xd = np.asarray(x)
    r = diag * xd
    for i in range(1, kd + 1):
        sub = np.asarray(ab[i, : n - i])
        r[i:] += sub * xd[: n - i]
        r[: n - i] += sub * xd[i:]
    assert np.abs(r - b).max() < 1e-8


def test_tbsm_packed():
    n, kd = 120, 9
    lmat = np.tril(RNG.standard_normal((n, n)))
    lmat = np.where(np.subtract.outer(np.arange(n), np.arange(n)) > kd, 0,
                    lmat)
    np.fill_diagonal(lmat, 3 + np.abs(lmat.diagonal()))
    ab = jnp.stack([jnp.pad(jnp.diagonal(jnp.asarray(lmat), offset=-i),
                            (0, i)) for i in range(kd + 1)])
    Lp = bp.PackedBand(ab, n, kd, 0)
    b = RNG.standard_normal((n, 2))
    x = st.tbsm_packed(Lp, b, nb=8)
    np.testing.assert_allclose(lmat @ np.asarray(x), b, atol=1e-12)
    xh = st.tbsm_packed(Lp, b, conj_trans=True, nb=8)
    np.testing.assert_allclose(lmat.T @ np.asarray(xh), b, atol=1e-12)


@pytest.mark.parametrize("n,kl,ku", [(150, 5, 3), (100, 1, 1), (80, 7, 0),
                                     (90, 0, 4), (77, 3, 6)])
def test_gbtrf_gbsv_packed(n, kl, ku):
    a = _gen_band(n, kl, ku)
    A = bp.gb_pack(a, kl, ku)
    assert A.ab.shape == (kl + ku + 1, n)
    np.testing.assert_allclose(np.asarray(A.to_dense()), a, atol=1e-14)
    b = RNG.standard_normal((n, 2))
    x, info = bp.gbsv(A, b)
    assert int(info) == 0
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-9)
    # and against the dense LU for the factorization itself
    xref = np.linalg.solve(a, b)
    np.testing.assert_allclose(np.asarray(x), xref, rtol=1e-8, atol=1e-9)


def test_gbtrf_pivoting_actually_pivots():
    """A matrix that no-pivot LU cannot factor (zero leading pivot)."""
    n, kl, ku = 40, 2, 1
    a = _gen_band(n, kl, ku)
    a[0, 0] = 0.0  # forces a pivot at the first column
    A = bp.gb_pack(a, kl, ku)
    b = RNG.standard_normal(n)
    x, info = bp.gbsv(A, b)
    assert int(info) == 0
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-9)
    assert int(np.asarray(bp.gbtrf(A)[0].pivots)[0]) > 0


def test_tbsm_pivots_standalone():
    """tbsm_pivots is the standalone pivoted L-solve (slate::tbsm's
    pivoted path): back-substituting its output through the banded U
    reproduces the full gbtrs solution."""
    n, kl, ku = 120, 4, 3
    a = _gen_band(n, kl, ku)
    a[0, 0] = 0.0  # force at least one real swap
    F, info = bp.gbtrf(bp.gb_pack(a, kl, ku))
    assert int(info) == 0
    b = RNG.standard_normal((n, 3))
    y = np.asarray(st.tbsm_pivots(F, b))
    # dense U from the factor rows: U[j, j+t] = urows[j, t]
    U = np.zeros((n, n))
    urows = np.asarray(F.urows)
    for t in range(urows.shape[1]):
        U += np.diag(urows[: n - t, t], k=t)
    x = np.linalg.solve(U, y)
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8,
                               atol=1e-9)
    # 1-D rhs round-trips with the same shape convention
    y1 = np.asarray(st.tbsm_pivots(F, b[:, 0]))
    np.testing.assert_allclose(y1, y[:, 0], atol=0)


def test_public_dispatch_accepts_packed():
    """st.pbsv / st.gbsv route PackedBand inputs to the packed path."""
    n, kd = 96, 6
    a = _spd_band(n, kd)
    b = RNG.standard_normal((n, 2))
    x, info = st.pbsv(bp.pb_pack(a, kd), b)
    assert int(info) == 0
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-10)
    g = _gen_band(n, 3, 2)
    xg, ig = st.gbsv(bp.gb_pack(g, 3, 2), b)
    np.testing.assert_allclose(g @ np.asarray(xg), b, atol=1e-9)
