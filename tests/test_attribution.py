"""Tenant/handle attribution ledger (slate_tpu.obs.attribution) +
runtime threading (round 15).

The acceptance pins: for EVERY counter class, the per-(tenant, handle)
rows sum BIT-EXACTLY (`==`, no approx) to the corresponding global
counter — on one host, and after a 2-process fleet fold, including
under a round-14 ``snapshot_drop``; grouped small-op dispatch produces
the same tenant-labeled hit/miss/flop tallies as per-request (the
"1 miss + B−1 hits" pin, tenant-labeled, incl. the mixed lane); the
heat EWMA math is hand-pinned under an injected clock; the placement
snapshot validates against its committed schema and round-trips
through the aggregate fold; attribution disabled allocates nothing
(the round-8 discipline extended).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import slate_tpu as st  # noqa: F401 — jax/platform init via conftest
from slate_tpu import obs
from slate_tpu.obs.attribution import (
    CLASSES, DEFAULT_TENANT, PLACEMENT_ROW_KEYS, AttributionLedger,
    fl_grid, s_grid, validate_placement_snapshot)
from slate_tpu.obs.slo import Objective, SloTracker
from slate_tpu.runtime import Batcher, Executor, Session

RNG = np.random.default_rng(47)
N = 8  # small-problem engine: tiny bucket programs, no dense compiles


def _small_op(seed=0):
    rng = np.random.default_rng(100 + seed)
    return np.asarray(rng.standard_normal((N, N)) + N * np.eye(N))


def _assert_conservation(sess):
    """THE acceptance check: per-tenant rows sum bit-exactly (==) to
    the corresponding global counter for every class."""
    snap = sess.attribution.snapshot()
    for cls, counter in CLASSES.items():
        cells = snap["totals"].get(cls, 0.0)
        glob = sess.metrics.get(counter)
        assert cells == glob, (
            f"{cls}: per-tenant sum {cells!r} != global "
            f"{counter}={glob!r}")
    return snap


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -- grids (the exactness substrate) ----------------------------------------


def test_grids_are_exact_dyadics():
    assert fl_grid(2 / 3 * 8 ** 3) == 341.0
    assert fl_grid(128.0) == 128.0
    v = s_grid(0.123456789)
    # on the 2^-20 grid: scaling back up is a whole number
    assert (v * (1 << 20)) == round(v * (1 << 20))
    # grid values accumulate exactly under ANY grouping
    xs = [s_grid(0.001 * i) for i in range(1, 40)]
    seq = 0.0
    for x in xs:
        seq += x
    assert seq == sum(xs[:20]) + sum(xs[20:])


def test_ledger_rejects_unknown_class_and_outcome():
    led = AttributionLedger()
    with pytest.raises(ValueError):
        led.record("nope", "t", 1, 1.0)
    with pytest.raises(ValueError):
        led.record_outcome("t", 1, "cancelled")
    with pytest.raises(ValueError):
        AttributionLedger(halflife_s=0.0)


# -- heat EWMA (hand-pinned under an injected clock) ------------------------


def test_heat_ewma_halflife_pin():
    clk = _FakeClock(0.0)
    led = AttributionLedger(halflife_s=10.0, clock=clk,
                            wall=lambda: 123.0)
    led.access("a", "h1", hit=False)
    assert led.heat("h1") == pytest.approx(1.0)
    clk.t = 10.0  # one halflife: 1.0 decays to 0.5, +1 on access
    led.access("a", "h1", hit=True)
    assert led.heat("h1") == pytest.approx(1.5)
    clk.t = 20.0  # decay only (no access): 1.5 -> 0.75
    assert led.heat("h1") == pytest.approx(0.75)
    # eviction advances the clock without the +1
    led.touch_eviction("h1")
    clk.t = 30.0
    assert led.heat("h1") == pytest.approx(0.375)
    assert led.last_access("h1") == 123.0
    # the hit/miss cells recorded alongside
    snap = led.snapshot()
    cell = snap["tenants"]["a"]["handles"]["'h1'"]
    assert cell["cache_misses"] == 1.0 and cell["cache_hits"] == 1.0


def test_residency_byte_seconds_accounting():
    clk = _FakeClock(0.0)
    led = AttributionLedger(halflife_s=10.0, clock=clk)
    assert led.touch_residency("a", "h", 1000, now=0.0) == 0.0
    assert led.touch_residency("a", "h", 1000, now=2.0) == 2000.0
    clk.t = 5.0
    assert led.end_residency("h") == 3000.0
    assert led.end_residency("h") == 0.0  # closed: no double accrual
    snap = led.snapshot()
    assert snap["totals"]["residency_byte_seconds"] == 5000.0


# -- conservation: one host -------------------------------------------------


def test_conservation_small_engine_two_tenants():
    """Served small-op traffic from two tenants (registered tenants +
    per-request overrides, grouped AND per-request dispatch): every
    counter class conserves bit-exactly."""
    sess = Session()
    sess.enable_attribution(halflife_s=5.0)
    ha = sess.register(_small_op(0), op="lu_small", tenant="alice")
    hb = sess.register(_small_op(1), op="lu_small", tenant="bob")
    hc = sess.register(_small_op(2), op="lu_small")  # default tenant
    bt = Batcher(sess, max_batch=8, max_wait=60.0)
    futs = [bt.submit(h, RNG.standard_normal(N))
            for h in (ha, hb, hc, ha, hb)]
    # an explicit per-request override rides its own bucket
    futs.append(bt.submit(ha, RNG.standard_normal(N), tenant="carol"))
    bt.flush()
    for f in futs:
        f.result(timeout=0)
    # per-request path on top
    sess.solve(hb, RNG.standard_normal(N))
    snap = _assert_conservation(sess)
    tenants = snap["tenants"]
    assert set(tenants) == {"alice", "bob", "carol", DEFAULT_TENANT}
    # the override attributed alice's operator work to carol
    assert tenants["carol"]["totals"]["solve_flops"] > 0
    # completed outcomes partition across tenants
    assert sum(t["totals"].get("completed", 0.0)
               for t in tenants.values()) == 6.0


def test_conservation_dense_session():
    """Dense chol serving (factor + AOT solve + width padding):
    conservation across the dense seams, incl. device seconds and
    residency byte-seconds."""
    n, nb = 24, 8
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, n))
    spd = a @ a.T + n * np.eye(n)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)
    sess = Session()
    sess.enable_attribution()
    h = sess.register(A, op="chol", tenant="dense-t")
    bt = Batcher(sess, max_batch=8, max_wait=60.0, pad_widths=True)
    futs = [bt.submit(h, rng.standard_normal(n)) for _ in range(3)]
    bt.flush()
    xs = [f.result(timeout=0) for f in futs]
    for x, f in zip(xs, futs):
        assert x.shape == (n,)
    sess.evict(h)  # closes the residency interval
    snap = _assert_conservation(sess)
    cell = snap["tenants"]["dense-t"]["handles"][repr(h)]
    assert cell["factor_flops"] > 0 and cell["solve_flops"] > 0
    assert cell["device_seconds"] > 0
    assert cell.get("residency_byte_seconds", 0.0) >= 0.0


# -- grouped parity (satellite 1) -------------------------------------------


def test_grouped_tenant_tallies_match_per_request():
    """The round-10 '1 miss + B−1 hits' duplicate-handle pin, tenant-
    labeled: grouped dispatch and B sequential per-request solves
    produce IDENTICAL per-(tenant, handle) hit/miss/flop cells."""
    a0, a1 = _small_op(5), _small_op(6)
    bs = [RNG.standard_normal((N, 1)) for _ in range(3)]

    grouped = Session()
    grouped.enable_attribution()
    g0 = grouped.register(a0, op="lu_small", tenant="alice", handle="h0")
    g1 = grouped.register(a1, op="lu_small", tenant="bob", handle="h1")
    xs, infos = grouped.solve_small_batched([g0, g0, g1], bs)
    assert infos == [0, 0, 0]

    per_req = Session()
    per_req.enable_attribution()
    p0 = per_req.register(a0, op="lu_small", tenant="alice", handle="h0")
    p1 = per_req.register(a1, op="lu_small", tenant="bob", handle="h1")
    for h, b in zip([p0, p0, p1], bs):
        per_req.solve(h, b)

    gsnap = grouped.attribution.snapshot()
    psnap = per_req.attribution.snapshot()
    for tenant in ("alice", "bob"):
        for cls in ("cache_hits", "cache_misses", "factor_flops",
                    "solve_flops"):
            gv = gsnap["tenants"][tenant]["totals"].get(cls, 0.0)
            pv = psnap["tenants"][tenant]["totals"].get(cls, 0.0)
            assert gv == pv, (tenant, cls, gv, pv)
    # alice's duplicate handle: exactly 1 miss + 1 hit either way
    acell = gsnap["tenants"]["alice"]["handles"]["'h0'"]
    assert acell["cache_misses"] == 1.0 and acell["cache_hits"] == 1.0
    _assert_conservation(grouped)
    _assert_conservation(per_req)


def test_grouped_mixed_lane_tenant_tallies():
    """The mixed/refine lane of the parity satellite: a refined
    (f64→f32) grouped bucket credits tenant-labeled refine_flops and
    conserves. n=32 matches the round-13 bucket configs already in
    tier-1 (single-panel regime)."""
    n = 32
    rng = np.random.default_rng(9)
    ops = []
    for i in range(2):
        a = rng.standard_normal((n, n))
        ops.append(np.asarray(a @ a.T + n * np.eye(n)))
    sess = Session()
    sess.enable_attribution()
    hs = [sess.register(ops[i], op="chol_small", refine=True,
                        tenant=("alice" if i == 0 else "bob"))
          for i in range(2)]
    bs = [rng.standard_normal((n, 1)) for _ in range(2)]
    xs, infos = sess.solve_small_batched(hs, bs)
    assert infos == [0, 0]
    snap = _assert_conservation(sess)
    for tenant in ("alice", "bob"):
        tot = snap["tenants"][tenant]["totals"]
        assert tot["solve_flops"] > 0
        assert tot.get("refine_flops", 0.0) >= 0.0
    # the refine work that was credited globally is fully attributed
    assert snap["totals"].get("refine_flops", 0.0) == \
        sess.metrics.get("refine_flops_total")


# -- outcomes (shed / expired / failed) -------------------------------------


def test_outcome_attribution_shed_and_expired():
    from slate_tpu.runtime import ShedPolicy
    sess = Session()
    sess.enable_attribution()
    h = sess.register(_small_op(7), op="lu_small", tenant="alice")
    bt = Batcher(sess, max_batch=64, max_wait=60.0,
                 shed_policy=ShedPolicy(max_age_s=0.0,
                                        shed_fraction=1.0,
                                        min_queue_depth=1))
    futs = [bt.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    # one request with an already-passed deadline expires at pop
    fexp = bt.submit(h, RNG.standard_normal(N), timeout_s=-1.0)
    bt.pop_ready()  # fails the expired request
    assert fexp.done() and not fexp.cancelled()
    shed = bt.maybe_shed(now=1e18)  # age trigger certainly fires
    assert shed >= 1
    snap = _assert_conservation(sess)
    cell = snap["tenants"]["alice"]["totals"]
    assert cell["expired"] == 1.0
    assert cell["shed"] == float(shed)
    assert cell["shed"] == sess.metrics.get("shed_requests_total")


# -- placement snapshot -----------------------------------------------------


def test_placement_snapshot_schema_and_content():
    sess = Session()
    sess.enable_attribution()
    ha = sess.register(_small_op(8), op="lu_small", tenant="alice")
    sess.solve(ha, RNG.standard_normal(N))
    doc = sess.placement_snapshot(host="hostA")
    assert validate_placement_snapshot(doc) == []
    (row,) = doc["rows"]
    assert set(PLACEMENT_ROW_KEYS) <= set(row)
    assert row["tenant"] == "alice" and row["op"] == "lu_small"
    assert row["n"] == N and row["bytes_per_chip"] > 0
    assert row["heat"] > 0 and row["last_access"] is not None
    # validator negatives
    bad = json.loads(json.dumps(doc))
    del bad["rows"][0]["heat"]
    assert any("heat" in e for e in validate_placement_snapshot(bad))
    assert validate_placement_snapshot({"schema": "x"})
    assert validate_placement_snapshot([1, 2])


def test_placement_fold_round_trip():
    sess = Session()
    sess.enable_attribution()
    ha = sess.register(_small_op(9), op="lu_small", tenant="alice")
    hb = sess.register(_small_op(10), op="lu_small", tenant="bob")
    for h in (ha, hb):
        sess.solve(h, RNG.standard_normal(N))
    d0 = sess.placement_snapshot(host="p0")
    d1 = json.loads(json.dumps(sess.placement_snapshot(host="p1")))
    fleet = obs.aggregate.merge_placement_snapshots([d0, d1])
    assert fleet["schema"] == "slate_tpu.fleet_placement.v1"
    assert len(fleet["rows"]) == 4
    assert fleet["per_tenant"]["alice"]["handles"] == 2
    assert sorted(fleet["per_tenant"]["alice"]["hosts"]) == ["p0", "p1"]
    # rows sort hottest-first within a tenant
    heats = [r["heat"] for r in fleet["rows"]
             if r["tenant"] == "alice"]
    assert heats == sorted(heats, reverse=True)


# -- 2-process fold + snapshot_drop -----------------------------------------


def test_two_process_fold_conservation_under_snapshot_drop():
    """The fleet fold keeps the invariant: fold N processes' metric +
    attribution snapshots, per-tenant sums == folded globals — and a
    round-14 snapshot_drop that loses one process loses BOTH its
    snapshots, so the surviving fold still conserves."""
    from slate_tpu.runtime.faults import (FaultInjector, FaultPlan,
                                          FaultSpec)
    sessions = []
    for p in range(2):
        sess = Session()
        sess.enable_attribution()
        h = sess.register(_small_op(20 + p), op="lu_small",
                          tenant=f"t{p}")
        for _ in range(2 + p):
            sess.solve(h, RNG.standard_normal(N))
        sessions.append(sess)
    msnaps = [s.metrics.snapshot() for s in sessions]
    asnaps = [json.loads(json.dumps(s.attribution.snapshot()))
              for s in sessions]
    # full 2-process fold conserves
    fleet = obs.aggregate.aggregate_processes(
        msnaps, hosts=["p0", "p1"], attribution_snaps=asnaps)
    for cls, counter in CLASSES.items():
        folded_cells = fleet["attribution"]["totals"].get(cls, 0.0)
        folded_global = fleet["metrics"]["counters"].get(counter, 0.0)
        assert folded_cells == folded_global, (cls, folded_cells,
                                               folded_global)
    # snapshot_drop: the injector drops process 1's snapshots (metrics
    # AND attribution together — the consistency that keeps the
    # invariant); the survivor fold still conserves
    inj = FaultInjector(FaultPlan(
        seed=7, specs=(FaultSpec("snapshot_drop", rate=1.0, count=1),)))
    kept_m, kept_a, dropped = [], [], 0
    for m, a in zip(msnaps, asnaps):
        if inj.fire("snapshot"):
            dropped += 1
            continue
        kept_m.append(m)
        kept_a.append(a)
    assert dropped == 1 and len(kept_m) == 1
    fleet2 = obs.aggregate.aggregate_processes(
        kept_m, attribution_snaps=kept_a)
    for cls, counter in CLASSES.items():
        assert fleet2["attribution"]["totals"].get(cls, 0.0) == \
            fleet2["metrics"]["counters"].get(counter, 0.0)


def test_attribution_fleet_doubles_bit_exactly():
    """Same-snapshot merge doubles every cell bit-exactly (the
    round-12 aggregation acceptance, extended to attribution)."""
    sess = Session()
    sess.enable_attribution()
    h = sess.register(_small_op(30), op="lu_small", tenant="alice")
    sess.solve(h, RNG.standard_normal(N))
    snap = sess.attribution.snapshot()
    merged = obs.aggregate.merge_attribution_snapshots([snap, snap])
    for cls, v in snap["totals"].items():
        assert merged["totals"][cls] == 2 * v
    cell = merged["tenants"]["alice"]["handles"][repr(h)]
    base = snap["tenants"]["alice"]["handles"][repr(h)]
    assert cell["solve_flops"] == 2 * base["solve_flops"]
    # heat sums (fleet heat = total access rate), last_access = newest
    assert cell["heat"] == pytest.approx(2 * base["heat"], rel=1e-6)
    assert cell["last_access"] == base["last_access"]


# -- exposition: /tenants route + tenant_* prom -----------------------------


def test_tenants_route_and_prometheus_sections():
    sess = Session()
    sess.enable_attribution()
    h = sess.register(_small_op(40), op="lu_small", tenant="alice")
    sess.solve(h, RNG.standard_normal(N))
    srv = sess.serve_obs()
    try:
        body = urllib.request.urlopen(srv.url("/tenants"),
                                      timeout=10).read().decode()
        payload = json.loads(body)
        assert payload["enabled"]
        assert "alice" in payload["tenants"]
        assert payload["placement"]["rows"]
        prom = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=10).read().decode()
        assert "slate_tpu_tenant_solve_flops_total" in prom
        assert 'tenant="alice"' in prom
        assert "slate_tpu_tenant_handles" in prom
        assert "slate_tpu_handle_heat" in prom
    finally:
        sess.close_obs()


def test_tenants_route_disabled_payload():
    sess = Session()
    srv = sess.serve_obs()
    try:
        body = urllib.request.urlopen(srv.url("/tenants"),
                                      timeout=10).read().decode()
        # round 18: the disabled payload also carries the (disabled)
        # quota view — both halves off is the full disabled contract
        assert json.loads(body) == {
            "enabled": False, "tenants": {},
            "quotas": {"enabled": False, "tenants": {}}}
    finally:
        sess.close_obs()


def test_tenants_concurrent_scrapes_during_serving():
    """Satellite: /tenants (which walks the session cache under the
    session lock) and /metrics hammered from two threads while an
    Executor serves — no crash, every response well-formed."""
    sess = Session()
    sess.enable_attribution()
    hs = [sess.register(_small_op(50 + i), op="lu_small",
                        tenant=f"t{i % 2}") for i in range(4)]
    srv = sess.serve_obs()
    errs = []
    stop = threading.Event()

    def scrape(path):
        while not stop.is_set():
            try:
                body = urllib.request.urlopen(
                    srv.url(path), timeout=10).read().decode()
                if path == "/tenants":
                    json.loads(body)
                elif "slate_tpu_" not in body:
                    errs.append(f"{path}: malformed body")
            except Exception as e:  # noqa: BLE001 — the test's verdict
                errs.append(f"{path}: {e!r}")
                return

    threads = [threading.Thread(target=scrape, args=(p,), daemon=True)
               for p in ("/tenants", "/metrics")]
    try:
        for t in threads:
            t.start()
        with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
            futs = [ex.submit(h, RNG.standard_normal(N))
                    for _ in range(6) for h in hs]
            for f in futs:
                f.result(timeout=120)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        sess.close_obs()
    assert not errs, errs[:3]
    _assert_conservation(sess)


# -- SLO tenant scoping -----------------------------------------------------


def test_slo_objective_tenant_scoping():
    """Objective(tenant=...) sees only that tenant's events; unscoped
    objectives see everything (None-labeled events only match
    unscoped)."""
    scoped = Objective("alice_errors", "error_rate", 0.9,
                       windows=(60.0,), tenant="alice")
    unscoped = Objective("all_errors", "error_rate", 0.9,
                         windows=(60.0,))
    t = SloTracker([scoped, unscoped])
    for i in range(4):
        t.record_request("lu", N, 0.01, ok=False, t=10.0,
                         tenant="alice")
    t.record_request("lu", N, 0.01, ok=True, t=10.0, tenant="bob")
    t.record_request("lu", N, 0.01, ok=True, t=10.0)  # unlabeled
    rows = {r["name"]: r for r in t.evaluate(now=11.0)["objectives"]}
    assert rows["alice_errors"]["windows"][0]["total"] == 4
    assert rows["alice_errors"]["windows"][0]["bad"] == 4
    assert rows["alice_errors"]["breached"]
    assert rows["all_errors"]["windows"][0]["total"] == 6
    assert rows["alice_errors"]["tenant"] == "alice"


def test_served_slo_events_carry_tenant():
    """The runtime labels SLO request events with the resolved tenant
    when attribution is on, so a tenant-scoped objective breaches on
    exactly that tenant's traffic."""
    sess = Session(slo=SloTracker([
        Objective("bob_lat", "latency", 0.5, threshold_s=1e-9,
                  windows=(3600.0,), source="solve", tenant="bob"),
        Objective("alice_lat", "latency", 0.5, threshold_s=1e-9,
                  windows=(3600.0,), source="solve", tenant="alice"),
    ]))
    sess.enable_attribution()
    hb = sess.register(_small_op(60), op="lu_small", tenant="bob")
    sess.solve(hb, RNG.standard_normal(N))
    rows = {r["name"]: r for r in sess.slo.evaluate()["objectives"]}
    # bob served traffic (and any real latency > 1ns => breach);
    # alice saw nothing
    assert rows["bob_lat"]["windows"][0]["total"] >= 1
    assert rows["alice_lat"]["windows"][0]["total"] == 0


# -- bucket-key tenant split ------------------------------------------------


def test_explicit_tenant_splits_buckets_default_does_not():
    sess = Session()
    h = sess.register(_small_op(70), op="lu_small")
    bt = Batcher(sess, max_batch=8, max_wait=60.0)
    bt.submit(h, RNG.standard_normal(N))
    bt.submit(h, RNG.standard_normal(N))  # same (default) bucket
    assert len(bt._buckets) == 1
    bt.submit(h, RNG.standard_normal(N), tenant="x")
    assert len(bt._buckets) == 2  # explicit tenant = its own bucket
    # both buckets dispatch fine
    bt.flush()
    assert bt.pending() == 0


def test_heat_gauge_cardinality_bounded_by_residency():
    """Review fix: per-handle heat gauges exist only while the handle
    is RESIDENT — eviction drops the gauge (state kept for re-access
    decay), unregister drops the state too — so handle churn cannot
    grow /metrics cardinality or ledger memory without bound."""
    sess = Session()
    sess.enable_attribution()
    h = sess.register(_small_op(90), op="lu_small", tenant="alice")
    sess.solve(h, RNG.standard_normal(N))
    gname = f"handle_heat:alice:{h!r}"
    assert gname in sess.metrics.snapshot()["gauges"]
    sess.evict(h)
    assert gname not in sess.metrics.snapshot()["gauges"]
    # re-access re-publishes (decayed state survived the eviction)
    sess.solve(h, RNG.standard_normal(N))
    assert gname in sess.metrics.snapshot()["gauges"]
    assert sess.attribution.heat(h) > 1.0  # decayed prior + new hits
    # unregister forgets the clocks entirely
    sess.unregister(h)
    assert gname not in sess.metrics.snapshot()["gauges"]
    assert sess.attribution.heat(h) == 0.0
    # ... but the billing cells survive
    snap = sess.attribution.snapshot()
    assert snap["tenants"]["alice"]["totals"]["solve_flops"] > 0
    _assert_conservation(sess)


# -- disabled path (round-8 discipline extended) ----------------------------


def test_disabled_path_records_nothing():
    """No AttributionLedger: a served workload leaves zero tenant
    counters, zero heat gauges, no seconds counters — the hot path's
    only new cost is `attribution is None` checks (and the flop-grid
    snap, which is value-identical whether or not attribution is on —
    pinned by the cross-session comparison below)."""
    sess = Session()
    assert sess.attribution is None
    h = sess.register(_small_op(80), op="lu_small")
    bt = Batcher(sess, max_batch=4, max_wait=60.0)
    futs = [bt.submit(h, RNG.standard_normal(N)) for _ in range(3)]
    bt.flush()
    for f in futs:
        f.result(timeout=0)
    snap = sess.metrics.snapshot()
    assert not any(k.startswith("handle_heat") for k in snap["gauges"])
    for c in ("device_seconds_total", "queue_seconds_total",
              "residency_byte_seconds_total"):
        assert c not in snap["counters"]

    # enabling attribution does NOT change the global flop counters: a
    # twin session with the ledger serves the identical workload and
    # lands on identical flop/count values
    twin = Session()
    twin.enable_attribution()
    h2 = twin.register(_small_op(80), op="lu_small")
    bt2 = Batcher(twin, max_batch=4, max_wait=60.0)
    futs2 = [bt2.submit(h2, RNG.standard_normal(N)) for _ in range(3)]
    bt2.flush()
    for f in futs2:
        f.result(timeout=0)
    for c in ("solve_flops_total", "factor_flops_total", "cache_hits",
              "cache_misses", "completed_requests", "solves_total"):
        assert sess.metrics.get(c) == twin.metrics.get(c), c
