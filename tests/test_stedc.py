"""stedc divide & conquer + he2td tridiagonalization + heev DC path.

Reference: src/stedc*.cc (distributed D&C), src/he2hb.cc + src/hb2st.cc
(the reduction the TPU build performs as one direct blocked
tridiagonalization — see eig._he2td_jit docstring).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import MethodEig
from slate_tpu.linalg.stedc import stedc

RNG = np.random.default_rng(7)


def _tridiag(d, e):
    t = np.diag(d)
    if len(e):
        t = t + np.diag(e, 1) + np.diag(e, -1)
    return t


@pytest.mark.parametrize("case", [
    "random", "gk_zero_diag", "glued_wilkinson", "ties", "decoupled",
])
def test_stedc_accuracy(case):
    n = 180
    if case == "random":
        d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    elif case == "gk_zero_diag":
        d, e = np.zeros(n), np.ones(n - 1)
    elif case == "glued_wilkinson":
        m = 21
        wd = np.abs(np.arange(m) - (m - 1) / 2.0)
        d = np.concatenate([wd] * 8)
        e = np.ones(d.size - 1)
        e[m - 1::m] = 1e-9
    elif case == "ties":
        d, e = np.ones(n), 1e-12 * np.ones(n - 1)
    else:
        d, e = np.arange(n) * 1.0, np.zeros(n - 1)
    w, z = stedc(d, e)
    t = _tridiag(d, e)
    nn = d.size
    np.testing.assert_allclose(w, np.linalg.eigvalsh(t),
                               rtol=1e-12, atol=1e-12 * max(1, np.abs(
                                   np.linalg.eigvalsh(t)).max()))
    assert np.abs(z.T @ z - np.eye(nn)).max() < nn * 1e-14
    assert np.abs(t @ z - z * w).max() < nn * 1e-13 * max(1.0, np.abs(w).max())


def test_stedc_values_only():
    n = 100
    d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    w, z = stedc(d, e, compute_z=False)
    assert z is None
    np.testing.assert_allclose(w, np.linalg.eigvalsh(_tridiag(d, e)),
                               rtol=1e-12, atol=1e-12)


def test_stedc_tiny():
    w, z = stedc(np.array([3.0]), np.array([]))
    assert w.shape == (1,) and z.shape == (1, 1)


def test_he2td_reduction_invariants():
    """Qᴴ·A·Q must equal tridiag(d, e) and Q must be unitary."""
    from slate_tpu.linalg.eig import he2td, unmtr_he2td
    n, nb = 112, 16  # ragged tiles
    a = RNG.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    d, e, Vs, Ts = he2td(A)
    npad = Vs.shape[1]
    Q = np.asarray(unmtr_he2td(Vs, Ts, jnp.eye(npad, dtype=A.dtype)))
    assert np.abs(Q.conj().T @ Q - np.eye(npad)).max() < n * 1e-13
    apad = np.pad(a, ((0, npad - n), (0, npad - n)))
    apad[range(n, npad), range(n, npad)] = 1.0
    t = Q.conj().T @ apad @ Q
    ref = _tridiag(np.asarray(d)[:n], np.asarray(e)[:n - 1])
    assert np.abs(t[:n, :n] - ref).max() < n * 1e-13


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_heev_dc_matches_dense(dtype):
    n, nb = 160, 32
    a = RNG.standard_normal((n, n)).astype(dtype)
    if np.iscomplexobj(a):
        a = a + 1j * RNG.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A, st.Options(method_eig=MethodEig.DC))
    wref = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(np.asarray(w), wref, rtol=1e-10,
                               atol=1e-10 * np.abs(wref).max())
    z = Z.to_numpy()
    res = np.abs(a @ z - z * np.asarray(w)).max()
    orth = np.abs(z.conj().T @ z - np.eye(n)).max()
    assert res < n * 1e-12 * max(1.0, np.abs(wref).max())
    assert orth < n * 1e-13


def test_heev_qr_method():
    n, nb = 48, 16
    a = RNG.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A, st.Options(method_eig=MethodEig.QR))
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(a),
                               rtol=1e-9, atol=1e-9)
    z = Z.to_numpy()
    assert np.abs(a @ z - z * np.asarray(w)).max() < 1e-9


def test_heev_dc_values_only():
    n, nb = 96, 16
    a = RNG.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A, st.Options(method_eig=MethodEig.DC),
                   want_vectors=False)
    assert Z is None
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(a),
                               rtol=1e-11, atol=1e-11)


@pytest.mark.parametrize("shape", [(128, 128), (150, 110), (110, 150)])
def test_svd_dc_matches_dense(shape):
    from slate_tpu.core.types import MethodSVD
    m, n = shape
    a = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=32)
    s, U, V = st.svd(A, st.Options(method_svd=MethodSVD.DC),
                     want_vectors=True)
    k = min(m, n)
    sref = np.linalg.svd(a, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s), sref, rtol=1e-11,
                               atol=1e-11 * sref.max())
    u, v = U.to_numpy(), V.to_numpy()
    assert np.abs(u @ np.diag(np.asarray(s)) @ v.T - a).max() \
        < k * 1e-12 * sref.max()
    assert np.abs(u.T @ u - np.eye(k)).max() < k * 1e-13
    assert np.abs(v.T @ v - np.eye(k)).max() < k * 1e-13


def test_svd_dc_values_only():
    from slate_tpu.core.types import MethodSVD
    a = RNG.standard_normal((90, 90))
    s, U, V = st.svd(st.from_dense(a, nb=16),
                     st.Options(method_svd=MethodSVD.DC))
    assert U is None and V is None
    np.testing.assert_allclose(np.asarray(s),
                               np.linalg.svd(a, compute_uv=False),
                               rtol=1e-11, atol=1e-11)


def test_bdsqr_no_densify_agrees():
    """bdsqr via the Golub-Kahan permuted tridiagonal must reproduce
    the singular values/vectors of the bidiagonal."""
    from slate_tpu.linalg.svd import bdsqr
    n = 60
    d = RNG.standard_normal(n)
    e = RNG.standard_normal(n - 1)
    B = np.diag(d) + np.diag(e, 1)
    s, u, vt = bdsqr(d, e, compute_uv=True)
    sref = np.linalg.svd(B, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s), sref, rtol=1e-12, atol=1e-12)
    un, vtn = np.asarray(u), np.asarray(vt)
    assert np.abs(B @ vtn.T - un * np.asarray(s)).max() < 1e-11
    assert np.abs(un.T @ un - np.eye(n)).max() < 1e-11


def test_svd_dc_rank_deficient_orthonormal():
    """σ ≈ 0 columns must still form orthonormal null-space bases (the
    GK ±0 eigenspace mixes u/v pairs; bdsqr rebuilds the deficient
    columns by orthonormal completion)."""
    from slate_tpu.core.types import MethodSVD
    m, n, r = 90, 90, 5
    a = (RNG.standard_normal((m, r)) @ RNG.standard_normal((r, n)))
    s, U, V = st.svd(st.from_dense(a, nb=16),
                     st.Options(method_svd=MethodSVD.DC),
                     want_vectors=True)
    u, v, sn = U.to_numpy(), V.to_numpy(), np.asarray(s)
    assert np.abs(u.T @ u - np.eye(n)).max() < n * 1e-12
    assert np.abs(v.T @ v - np.eye(n)).max() < n * 1e-12
    assert np.abs(u @ np.diag(sn) @ v.T - a).max() < n * 1e-11 * sn.max()
    assert (sn[r:] < sn.max() * 1e-10).all()


def test_bdsqr_complex_raises():
    import pytest as _pytest
    from slate_tpu.linalg.svd import bdsqr
    with _pytest.raises(Exception, match="real"):
        bdsqr(np.ones(4) + 1j, np.ones(3))


def test_hegv_with_dc():
    n, nb = 96, 16
    a = RNG.standard_normal((n, n)); a = (a + a.T) / 2
    b = RNG.standard_normal((n, n)); b = b @ b.T + n * np.eye(n)
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    B = st.hermitian(np.tril(b), nb=nb, uplo=st.Uplo.Lower)
    w, X, info = st.hegv(A, B, st.Options(method_eig=MethodEig.DC))
    assert int(info) == 0
    x = X.to_numpy()
    res = np.abs(a @ x - (b @ x) * np.asarray(w)).max()
    assert res < n * 1e-11 * max(1.0, np.abs(np.asarray(w)).max())
