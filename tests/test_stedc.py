"""stedc divide & conquer + he2td tridiagonalization + heev DC path.

Reference: src/stedc*.cc (distributed D&C), src/he2hb.cc + src/hb2st.cc
(the reduction the TPU build performs as one direct blocked
tridiagonalization — see eig._he2td_jit docstring).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import MethodEig
from slate_tpu.linalg.stedc import stedc

RNG = np.random.default_rng(7)


def _tridiag(d, e):
    t = np.diag(d)
    if len(e):
        t = t + np.diag(e, 1) + np.diag(e, -1)
    return t


@pytest.mark.parametrize("case", [
    "random", "gk_zero_diag", "glued_wilkinson", "ties", "decoupled",
])
def test_stedc_accuracy(case):
    n = 180
    if case == "random":
        d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    elif case == "gk_zero_diag":
        d, e = np.zeros(n), np.ones(n - 1)
    elif case == "glued_wilkinson":
        m = 21
        wd = np.abs(np.arange(m) - (m - 1) / 2.0)
        d = np.concatenate([wd] * 8)
        e = np.ones(d.size - 1)
        e[m - 1::m] = 1e-9
    elif case == "ties":
        d, e = np.ones(n), 1e-12 * np.ones(n - 1)
    else:
        d, e = np.arange(n) * 1.0, np.zeros(n - 1)
    w, z = stedc(d, e)
    t = _tridiag(d, e)
    nn = d.size
    np.testing.assert_allclose(w, np.linalg.eigvalsh(t),
                               rtol=1e-12, atol=1e-12 * max(1, np.abs(
                                   np.linalg.eigvalsh(t)).max()))
    assert np.abs(z.T @ z - np.eye(nn)).max() < nn * 1e-14
    assert np.abs(t @ z - z * w).max() < nn * 1e-13 * max(1.0, np.abs(w).max())


def test_stedc_values_only():
    n = 100
    d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    w, z = stedc(d, e, compute_z=False)
    assert z is None
    np.testing.assert_allclose(w, np.linalg.eigvalsh(_tridiag(d, e)),
                               rtol=1e-12, atol=1e-12)


def test_stedc_tiny():
    w, z = stedc(np.array([3.0]), np.array([]))
    assert w.shape == (1,) and z.shape == (1, 1)


def test_stedc_device_matches_host():
    """VERDICT r3 #1c: the device-resident merge scheme must agree with
    the host recursion exactly (same scalar stages; the basis GEMM is
    the only device op, f64 on the CPU mesh)."""
    n = 500
    d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    w_h, z_h = stedc(d, e, use_device=False)
    w_d, z_d = stedc(d, e, use_device=True)
    z_d = np.asarray(z_d)
    np.testing.assert_allclose(w_d, w_h, rtol=0, atol=0)
    t = _tridiag(d, e)
    assert np.abs(z_d.T @ z_d - np.eye(n)).max() < n * 1e-14
    assert np.abs(t @ z_d - z_d * w_d).max() < n * 1e-13 * max(
        1.0, np.abs(w_d).max())


def test_stedc_grid_merge_has_collectives(grid2x4):
    """VERDICT r3 #3: merge GEMMs sharded over the mesh — the compiled
    merge shows collectives, and the result still checks out."""
    import jax
    from slate_tpu.linalg.stedc import _merge_apply_jit

    n = 512
    d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    w, z = stedc(d, e, use_device=True, grid=grid2x4)
    z = np.asarray(z)
    t = _tridiag(d, e)
    assert np.abs(t @ z - z * w).max() < n * 1e-13 * max(1.0,
                                                         np.abs(w).max())
    # HLO of the sharded merge kernel, with the child bases 2D-sharded
    # as the previous merge level leaves them (out spec P(p, q)) — the
    # row/col-panel constraints then force the gather collectives
    sh = jax.sharding.NamedSharding(grid2x4.mesh, grid2x4.spec_2d())
    q1 = jax.device_put(jnp.zeros((256, 256)), sh)
    q2 = jax.device_put(jnp.zeros((256, 256)), sh)
    T = jax.device_put(jnp.zeros((512, 512)), sh)
    txt = jax.jit(_merge_apply_jit, static_argnames=("grid",)).lower(
        q1, q2, T, grid=grid2x4).compile().as_text()
    colls = ("all-gather", "all-reduce", "collective-permute",
             "reduce-scatter", "all-to-all")
    assert sum(txt.count(c) for c in colls) > 0, \
        "stedc merge compiled without collectives"


@pytest.mark.slow
@pytest.mark.parametrize("spectrum,cond", [
    ("heev_cluster0", 1e6), ("heev_cluster1", 1e6),
    ("heev_geo", 1e8), ("heev_logrand", 1e6),
])
def test_stedc_torture_clustered_spectra(spectrum, cond):
    """VERDICT r2 weak #4: the bespoke secular solver must survive tight
    clusters and high condition numbers — orthogonality and eigenvalue
    error checked against eigh_tridiagonal on the he2td tridiagonal of a
    matgen matrix with the requested spectrum. Slow (round-20 tier-1
    budget: n=1024 he2td + 6-level stedc per spectrum). Tier-1
    siblings: test_secular_device_matches_host pins the secular solver
    against the host reference per spectrum shape, and
    test_hb2td_two_stage_pipeline / test_svd_dc_matches_dense pin the
    stedc pipeline end to end at tier-1 sizes."""
    from scipy.linalg import eigh_tridiagonal as _scipy_eigh_td
    n, nb = 1024, 128
    a = np.asarray(st.matgen.generate_matrix(
        spectrum, n, n, dtype=jnp.float64, seed=11, cond=cond))
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    d, e, _, _ = st.he2td(A)
    dn = np.asarray(d, np.float64)[:n]
    en = np.asarray(e, np.float64)[: n - 1]
    w, z = stedc(dn, en)
    z = np.asarray(z)
    wref = _scipy_eigh_td(dn, en, eigvals_only=True)
    scale = max(1.0, np.abs(wref).max())
    np.testing.assert_allclose(w, wref, rtol=0, atol=n * 1e-13 * scale)
    assert np.abs(z.T @ z - np.eye(n)).max() < n * 1e-13
    t = _tridiag(dn, en)
    assert np.abs(t @ z - z * w).max() < n * 1e-12 * scale


@pytest.mark.slow
def test_stedc_torture_large_random():
    """n=4096 random tridiagonal: the deep recursion (7 merge levels)
    keeps orthogonality at f64 roundoff. Slow (round-20 tier-1
    budget); tier-1 siblings as in test_stedc_torture_clustered_spectra."""
    n = 4096
    d, e = RNG.standard_normal(n), RNG.standard_normal(n - 1)
    w, z = stedc(d, e)
    z = np.asarray(z)
    assert np.abs(z.T @ z - np.eye(n)).max() < n * 1e-13
    # spot-check extreme eigenpairs by residual
    t = _tridiag(d, e)
    for j in (0, 1, n // 2, n - 2, n - 1):
        r = t @ z[:, j] - w[j] * z[:, j]
        assert np.abs(r).max() < n * 1e-13 * max(1.0, np.abs(w).max())


@pytest.mark.parametrize("dtype", [
    np.float64,
    # complex arm (~6 s) rides the slow lane (round-10 headroom);
    # the f64 arm keeps the two-stage pipeline in tier-1
    pytest.param(np.complex128, marks=pytest.mark.slow)])
def test_hb2td_two_stage_pipeline(dtype):
    """VERDICT r3 #1b: band→tridiag on O(n·b)-touched data (he2hb +
    hb2td bulge chase) — eigenvalues and the full back-transform must
    match the dense solver for real AND complex inputs."""
    from slate_tpu.core.types import MethodEig, Options

    n, nb = 160, 16
    rng = np.random.default_rng(13)
    a = rng.standard_normal((n, n)).astype(np.float64)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    h = 0.5 * (a + np.conj(a).T)
    A = st.hermitian(np.tril(h), nb=nb, uplo=st.Uplo.Lower)
    band, refl = st.he2hb(A)
    d, e, Vh, Th, phase = st.hb2td(band)
    dn, en = np.asarray(d), np.asarray(e)
    t = np.diag(dn) + np.diag(en, 1) + np.diag(en, -1)
    bf = np.asarray(band.full_dense_canonical())
    np.testing.assert_allclose(np.sort(np.linalg.eigvalsh(t)),
                               np.sort(np.linalg.eigvalsh(bf)),
                               rtol=1e-11, atol=1e-11)
    wt, z2 = np.linalg.eigh(t)
    zb = np.asarray(st.unmtr_hb2td(Vh, Th, jnp.asarray(z2, bf.dtype),
                                   phase))
    zf = np.asarray(st.unmtr_he2hb(refl, jnp.asarray(zb)))
    af = np.asarray(A.full_dense_canonical())
    assert np.abs(af @ zf - zf * wt[None, :]).max() < n * 1e-13 * max(
        1.0, np.abs(wt).max())

    # driver-level: heev with the two-stage stage-1 matches dense eigh
    w2s, Z2s = st.heev(A, Options(method_eig=MethodEig.DC,
                                  eig_stage1="two_stage"))
    wref = np.linalg.eigvalsh(h)
    np.testing.assert_allclose(np.asarray(w2s), wref, rtol=1e-10,
                               atol=1e-10 * max(1, np.abs(wref).max()))
    z = Z2s.to_numpy()
    assert np.abs(h @ z - z * np.asarray(w2s)[None, :]).max() \
        < n * 1e-12 * max(1.0, np.abs(wref).max())


def test_he2td_reduction_invariants():
    """Qᴴ·A·Q must equal tridiag(d, e) and Q must be unitary."""
    from slate_tpu.linalg.eig import he2td, unmtr_he2td
    n, nb = 112, 16  # ragged tiles
    a = RNG.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    d, e, Vs, Ts = he2td(A)
    npad = Vs.shape[1]
    Q = np.asarray(unmtr_he2td(Vs, Ts, jnp.eye(npad, dtype=A.dtype)))
    assert np.abs(Q.conj().T @ Q - np.eye(npad)).max() < n * 1e-13
    apad = np.pad(a, ((0, npad - n), (0, npad - n)))
    apad[range(n, npad), range(n, npad)] = 1.0
    t = Q.conj().T @ apad @ Q
    ref = _tridiag(np.asarray(d)[:n], np.asarray(e)[:n - 1])
    assert np.abs(t[:n, :n] - ref).max() < n * 1e-13


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_heev_dc_matches_dense(dtype):
    n, nb = 160, 32
    a = RNG.standard_normal((n, n)).astype(dtype)
    if np.iscomplexobj(a):
        a = a + 1j * RNG.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A, st.Options(method_eig=MethodEig.DC))
    wref = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(np.asarray(w), wref, rtol=1e-10,
                               atol=1e-10 * np.abs(wref).max())
    z = Z.to_numpy()
    res = np.abs(a @ z - z * np.asarray(w)).max()
    orth = np.abs(z.conj().T @ z - np.eye(n)).max()
    assert res < n * 1e-12 * max(1.0, np.abs(wref).max())
    assert orth < n * 1e-13


def test_heev_qr_method():
    n, nb = 48, 16
    a = RNG.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A, st.Options(method_eig=MethodEig.QR))
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(a),
                               rtol=1e-9, atol=1e-9)
    z = Z.to_numpy()
    assert np.abs(a @ z - z * np.asarray(w)).max() < 1e-9


def test_heev_dc_values_only():
    n, nb = 96, 16
    a = RNG.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    w, Z = st.heev(A, st.Options(method_eig=MethodEig.DC),
                   want_vectors=False)
    assert Z is None
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(a),
                               rtol=1e-11, atol=1e-11)


@pytest.mark.parametrize("shape", [(128, 128), (150, 110), (110, 150)])
def test_svd_dc_matches_dense(shape):
    from slate_tpu.core.types import MethodSVD
    m, n = shape
    a = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=32)
    s, U, V = st.svd(A, st.Options(method_svd=MethodSVD.DC),
                     want_vectors=True)
    k = min(m, n)
    sref = np.linalg.svd(a, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s), sref, rtol=1e-11,
                               atol=1e-11 * sref.max())
    u, v = U.to_numpy(), V.to_numpy()
    assert np.abs(u @ np.diag(np.asarray(s)) @ v.T - a).max() \
        < k * 1e-12 * sref.max()
    assert np.abs(u.T @ u - np.eye(k)).max() < k * 1e-13
    assert np.abs(v.T @ v - np.eye(k)).max() < k * 1e-13


def test_svd_dc_values_only():
    from slate_tpu.core.types import MethodSVD
    a = RNG.standard_normal((90, 90))
    s, U, V = st.svd(st.from_dense(a, nb=16),
                     st.Options(method_svd=MethodSVD.DC))
    assert U is None and V is None
    np.testing.assert_allclose(np.asarray(s),
                               np.linalg.svd(a, compute_uv=False),
                               rtol=1e-11, atol=1e-11)


def test_bdsqr_no_densify_agrees():
    """bdsqr via the Golub-Kahan permuted tridiagonal must reproduce
    the singular values/vectors of the bidiagonal."""
    from slate_tpu.linalg.svd import bdsqr
    n = 60
    d = RNG.standard_normal(n)
    e = RNG.standard_normal(n - 1)
    B = np.diag(d) + np.diag(e, 1)
    s, u, vt = bdsqr(d, e, compute_uv=True)
    sref = np.linalg.svd(B, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s), sref, rtol=1e-12, atol=1e-12)
    un, vtn = np.asarray(u), np.asarray(vt)
    assert np.abs(B @ vtn.T - un * np.asarray(s)).max() < 1e-11
    assert np.abs(un.T @ un - np.eye(n)).max() < 1e-11


def test_svd_dc_rank_deficient_orthonormal():
    """σ ≈ 0 columns must still form orthonormal null-space bases (the
    GK ±0 eigenspace mixes u/v pairs; bdsqr rebuilds the deficient
    columns by orthonormal completion)."""
    from slate_tpu.core.types import MethodSVD
    m, n, r = 90, 90, 5
    a = (RNG.standard_normal((m, r)) @ RNG.standard_normal((r, n)))
    s, U, V = st.svd(st.from_dense(a, nb=16),
                     st.Options(method_svd=MethodSVD.DC),
                     want_vectors=True)
    u, v, sn = U.to_numpy(), V.to_numpy(), np.asarray(s)
    assert np.abs(u.T @ u - np.eye(n)).max() < n * 1e-12
    assert np.abs(v.T @ v - np.eye(n)).max() < n * 1e-12
    assert np.abs(u @ np.diag(sn) @ v.T - a).max() < n * 1e-11 * sn.max()
    assert (sn[r:] < sn.max() * 1e-10).all()


def test_bdsqr_complex_raises():
    import pytest as _pytest
    from slate_tpu.linalg.svd import bdsqr
    with _pytest.raises(Exception, match="real"):
        bdsqr(np.ones(4) + 1j, np.ones(3))


def test_hegv_with_dc():
    n, nb = 96, 16
    a = RNG.standard_normal((n, n)); a = (a + a.T) / 2
    b = RNG.standard_normal((n, n)); b = b @ b.T + n * np.eye(n)
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    B = st.hermitian(np.tril(b), nb=nb, uplo=st.Uplo.Lower)
    w, X, info = st.hegv(A, B, st.Options(method_eig=MethodEig.DC))
    assert int(info) == 0
    x = X.to_numpy()
    res = np.abs(a @ x - (b @ x) * np.asarray(w)).max()
    assert res < n * 1e-11 * max(1.0, np.abs(np.asarray(w)).max())


# ---------------------------------------------------------------------------
# df32 device secular stage (round 4: VERDICT r3 #3)
# ---------------------------------------------------------------------------

def test_doublefloat_primitives():
    """two_sum/two_prod are error-free; df ops hold ~2^-48 accuracy."""
    import jax.numpy as jnp
    from slate_tpu.ops import doublefloat as df

    rng = np.random.default_rng(3)
    a64 = rng.standard_normal(1000)
    b64 = rng.standard_normal(1000) * 1e-3
    ah, al = df.from_f64(a64)
    bh, bl = df.from_f64(b64)
    # representation error of the split itself
    assert np.abs(df.to_f64(ah, al) - a64).max() < 3e-15 * np.abs(a64).max()
    for op, ref in [(df.add, a64 + b64), (df.sub, a64 - b64),
                    (df.mul, a64 * b64), (df.div, a64 / b64)]:
        h, l = op(jnp.asarray(ah), jnp.asarray(al),
                  jnp.asarray(bh), jnp.asarray(bl))
        got = df.to_f64(h, l)
        rel = np.abs(got - ref) / np.maximum(np.abs(ref), 1e-300)
        assert rel.max() < 1e-13, (op.__name__, rel.max())
    # accurate tree reduction: condition the sum badly on purpose
    x = np.concatenate([np.full(512, 1.0), np.full(512, 1e-9),
                        np.full(512, -1.0)])
    xh, xl = df.from_f64(x)
    sh, sl = df.df_sum(jnp.asarray(xh)[None, :], jnp.asarray(xl)[None, :],
                       axis=1)
    assert abs(df.to_f64(sh, sl)[0] - x.sum()) < 1e-11


@pytest.mark.parametrize("case", ["uniform", "clustered", "geometric"])
def test_secular_device_matches_host(case):
    from slate_tpu.linalg import stedc as S

    rng = np.random.default_rng(11)
    if case == "uniform":
        delta = np.sort(rng.uniform(-1, 1, 700))
    elif case == "clustered":
        delta = np.sort(np.concatenate([
            np.full(400, 0.3) + rng.uniform(0, 1e-9, 400),
            rng.uniform(-2, 2, 400)]))
    else:
        delta = np.sort(np.geomspace(1e-8, 1.0, 600))
    # post-deflation invariant: gaps exceed the df32 deflation tol
    tol = 8 * 2.0 ** -48 * np.abs(delta).max()
    delta = delta[np.concatenate([[True], np.diff(delta) > tol])]
    k = delta.size
    z = rng.standard_normal(k)
    z /= np.linalg.norm(z)
    z2 = z * z + 1e-300
    rho = 0.7
    s_h, mu_h = S._secular_roots(delta, z2, rho)
    s_d, mu_d = S._secular_roots_device(delta, z2, rho)
    scale = np.abs(delta).max() + rho
    lam_h = delta[s_h] + mu_h
    lam_d = delta[s_d] + mu_d
    # compare reconstructed roots, not shift indices: when a root sits
    # near an interval midpoint the f64 and df32 evaluations may pick
    # different (both valid) shift poles
    assert np.abs(lam_h - lam_d).max() < 5e-14 * scale


def test_stedc_device_secular_end_to_end(monkeypatch):
    """Forced df32 secular stage: f32-grade vectors, f64-grade values."""
    monkeypatch.setenv("SLATE_TPU_SECULAR_DEVICE", "1")
    monkeypatch.setenv("SLATE_TPU_STEDC_MIN_K", "128")
    rng = np.random.default_rng(5)
    n = 768
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1) * 0.5 + 1.0
    w, z = stedc(d, e, use_device=True)
    z = np.asarray(z, np.float64)
    t = _tridiag(d, e)
    wref = np.linalg.eigvalsh(t)
    assert np.abs(w - wref).max() < 1e-12 * np.abs(wref).max()
    assert np.abs(z.T @ z - np.eye(n)).max() < n * 1e-8
    assert np.abs(t @ z - z * w).max() < n * 1e-8 * max(1.0,
                                                        np.abs(w).max())


@pytest.mark.slow  # ~25 s, the single heaviest tier-1 test (round-10
# wall-time headroom); mesh secular stays covered by
# test_secular_device_matches_host + the grid-free stedc suite
def test_stedc_sharded_secular_on_grid(grid2x4, monkeypatch):
    """Multi-host stedc (VERDICT r4 missing #5): the secular sweep's
    ROOT axis shards over every device of the 2x4 mesh (shard_map; the
    analog of the reference distributing dlaed4 calls over the Q
    process grid, src/stedc_secular.cc) while pole vectors replicate.
    The Laplacian tridiagonal deflates almost nothing, so the top
    merges keep k ~ n and genuinely engage the sharded kernel — pinned
    via its compile cache. Analytic eigenvalues give an exact check."""
    import numpy as np
    from slate_tpu.linalg import stedc as sm

    monkeypatch.setenv("SLATE_TPU_SECULAR_DEVICE", "1")
    n = 2048
    d = np.full(n, 2.0)
    e = np.full(n - 1, -1.0)
    sm._secular_sharded_fn.cache_clear()
    w, z = sm.stedc(d, e, use_device=True, grid=grid2x4)
    assert sm._secular_sharded_fn.cache_info().currsize > 0, \
        "sharded secular kernel never engaged (k stayed below the gate)"
    wref = 2 - 2 * np.cos(np.arange(1, n + 1) * np.pi / (n + 1))
    assert np.abs(np.sort(w) - wref).max() < 1e-11  # df32 secular level
    z = np.asarray(z)
    t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    epsz = np.finfo(z.dtype).eps
    res = np.abs(t @ z - z * w).max() / (epsz * n * max(np.abs(w).max(), 1))
    orth = np.abs(z.T @ z - np.eye(n)).max() / (epsz * n)
    assert res < 100 and orth < 100, (res, orth)
