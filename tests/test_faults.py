"""Round 14: deterministic fault injection + serving reflexes.

The tentpole contract under test: failure is a reproducible INPUT
(seeded FaultInjector — identical seed, identical schedule), and every
reflex it exercises — per-request deadlines, admission control + load
shedding, exponential-backoff retry, the circuit breaker walking the
declared degradation ladder — resolves every future exactly once,
keeps the request-conservation identity, and never produces a wrong
answer. Cancellation races (satellite): a client cancel between bucket
detach and dispatch, during a backoff sleep, and during a degraded
per-request replay must not double-count or double-resolve.

All CPU-mesh, tier-1; shapes at the test-suite standard (n ≤ 64).
"""

import importlib.util
import pathlib
import threading
import time

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.runtime import (DEGRADATION_LADDER, Batcher,
                               DeadlineExceeded, Executor, FaultInjector,
                               FaultPlan, FaultSpec, RequestShed,
                               Session, ShedPolicy,
                               TransientDispatchError)
from slate_tpu.runtime import faults as faults_mod

RNG = np.random.default_rng(14)
N, NB = 64, 32
_REPO = pathlib.Path(__file__).resolve().parent.parent


def _spd(n=N, dtype=np.float64):
    a = RNG.standard_normal((n, n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


def _chol_handle(sess, n=N):
    spd = _spd(n)
    A = st.hermitian(np.tril(spd), nb=NB, uplo=st.Uplo.Lower)
    return sess.register(A, op="chol"), spd


def _small_handles(sess, k=3, n=16):
    mats = [(RNG.standard_normal((n, n)) + n * np.eye(n))
            for _ in range(k)]
    return [sess.register(m, op="lu_small") for m in mats], mats


def _conservation_holds(m):
    return m.get("requests_total") == (
        m.get("completed_requests") + m.get("failed_requests_total")
        + m.get("shed_requests_total")
        + m.get("admission_rejected_total")
        + m.get("deadline_expired_total") + m.get("cancelled_requests"))


# -- the injector: determinism --------------------------------------------


def test_injector_schedule_is_pure_function_of_seed():
    plan = FaultPlan(seed=42, specs=(
        FaultSpec("dispatch_error", rate=0.3),
        FaultSpec("slow_device", rate=0.2, latency_s=0.0),
        FaultSpec("hbm_exhaustion", rate=0.5, after=2, count=3),
    ))
    runs = []
    for _ in range(2):
        inj = FaultInjector(plan)
        for _ in range(50):
            inj.fire("dispatch")
        for _ in range(20):
            inj.fire("hbm")
        runs.append((inj.schedule(), inj.schedule_digest(),
                     inj.fired_counts()))
    assert runs[0] == runs[1]  # identical seed -> identical schedule
    assert runs[0][0], "plan at these rates must fire at least once"
    # `after` skips the first opportunities; `count` caps firings
    hbm = [s for s in runs[0][0] if s[1] == "hbm_exhaustion"]
    assert len(hbm) == 3 and all(seq >= 2 for _, _, seq in hbm)
    # a different seed is a different schedule
    inj2 = FaultInjector(FaultPlan(seed=43, specs=plan.specs))
    for _ in range(50):
        inj2.fire("dispatch")
    for _ in range(20):
        inj2.fire("hbm")
    assert inj2.schedule() != runs[0][0]
    # one site's draws never shift another's: dispatch-only replay
    # reproduces the dispatch sub-schedule exactly
    inj3 = FaultInjector(plan)
    for _ in range(50):
        inj3.fire("dispatch")
    assert ([s for s in runs[0][0] if s[0] == "dispatch"]
            == inj3.schedule())


def test_fault_plan_validation_and_roundtrip():
    with pytest.raises(ValueError):
        FaultSpec("nope", rate=0.5)
    with pytest.raises(ValueError):
        FaultSpec("dispatch_error", rate=1.5)
    with pytest.raises(ValueError):
        FaultPlan(seed=1, specs=(FaultSpec("dispatch_error", 0.1),
                                 FaultSpec("dispatch_error", 0.2)))
    plan = FaultPlan(seed=9, specs=(
        FaultSpec("compile_stall", rate=0.5, latency_s=1e-3),))
    assert FaultPlan.from_dict(plan.to_dict()) == plan
    # the ladder is DECLARED policy — pin it
    assert DEGRADATION_LADDER == {
        "grouped": "per_request", "mixed": "working_precision",
        "dense": "per_request", "mesh": "reject"}


def test_faults_disabled_is_never_consulted(monkeypatch):
    """The zero-overhead acceptance: with ``session.faults is None``
    the injector is NEVER called on the serving path — pinned by
    making any call explode."""
    def boom(*a, **k):
        raise AssertionError("FaultInjector consulted with faults=None")
    monkeypatch.setattr(FaultInjector, "fire", boom)
    monkeypatch.setattr(FaultInjector, "uniform", boom)
    sess = Session(hbm_budget=1 << 20)  # small budget: eviction path runs
    assert sess.faults is None
    h, spd = _chol_handle(sess)
    hs, _ = _small_handles(sess, k=2)
    sess.warmup(h)
    with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
        futs = [ex.submit(h, RNG.standard_normal(N)) for _ in range(4)]
        futs += [ex.submit(hs[i % 2], RNG.standard_normal(16))
                 for i in range(4)]
        for f in futs:
            f.result(timeout=60)


# -- injected dispatch failures: backoff retry ----------------------------


def test_injected_dispatch_error_retried_with_deterministic_backoff():
    def run():
        sess = Session()
        sess.enable_faults(FaultPlan(seed=7, specs=(
            FaultSpec("dispatch_error", rate=1.0, count=2),)))
        h, spd = _chol_handle(sess)
        sess.warmup(h)
        with Executor(sess, max_batch=4, max_wait=1e-3, retries=3,
                      backoff_base=1e-3, backoff_max=8e-3) as ex:
            b = RNG.standard_normal(N)
            x = ex.submit(h, b).result(timeout=60)
        assert np.abs(spd @ x - b).max() < 1e-8  # correct after retry
        snap = sess.metrics.snapshot()
        return (snap["counters"]["retries"],
                snap["counters"]["fault:dispatch_error"],
                snap["histograms"]["retry_backoff_s"]["count"],
                snap["histograms"]["retry_backoff_s"]["sum"])
    a, b = run(), run()
    assert a[0] == 2 and a[1] == 2 and a[2] == 2
    # injector-keyed jitter: the backoff schedule itself replays
    assert a == b
    # exponential: total sleep of 2 attempts stays within the caps
    assert 1e-3 <= a[3] <= 8e-3 + 4e-3


def test_transient_error_class_is_retryable_slate_error_is_not():
    assert issubclass(TransientDispatchError, RuntimeError)
    assert not issubclass(TransientDispatchError, SlateError)
    assert issubclass(DeadlineExceeded, SlateError)
    assert issubclass(RequestShed, SlateError)


# -- per-request deadlines -------------------------------------------------


def test_deadline_expired_fails_fast_without_occupying_a_lane():
    sess = Session()
    sess.enable_slo()
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    bat = Batcher(sess, max_batch=8, max_wait=60.0)
    dead = bat.submit(h, RNG.standard_normal(N), timeout_s=0.0)
    live = bat.submit(h, RNG.standard_normal(N))
    # the expired request leaves the queue at pop time WITHOUT a
    # dispatch — even though its bucket is neither full nor past
    # max_wait
    time.sleep(0.002)
    popped = bat.pop_ready()
    assert popped == []  # live bucket not ready; expired one drained
    with pytest.raises(DeadlineExceeded):
        dead.result(timeout=0)
    assert not live.done()
    assert sess.metrics.get("deadline_expired_total") == 1
    assert sess.metrics.get("batches_total") == 0
    bat.flush()
    assert live.result(timeout=0).shape == (N,)
    assert _conservation_holds(sess.metrics)
    # the expiry is an SLO error event on the request stream
    err = next(o for o in sess.slo.evaluate()["objectives"]
               if o["name"] == "request_errors")
    win = max(err["windows"], key=lambda w: w["window_s"])
    assert win["bad"] == 1 and win["total"] == 2


def test_deadline_wakes_idle_worker():
    """The worker waits on min(bucket deadline, request deadline): an
    expiring request fails fast even when its bucket would otherwise
    sit for max_wait=60s."""
    sess = Session()
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    with Executor(sess, max_batch=64, max_wait=60.0) as ex:
        t0 = time.monotonic()
        f = ex.submit(h, RNG.standard_normal(N), timeout_s=0.05)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=30)
        assert time.monotonic() - t0 < 10.0  # not the 60 s bucket wait
    assert sess.metrics.get("deadline_expired_total") == 1


def test_batcher_next_deadline_includes_request_deadlines():
    sess = Session()
    h, _ = _chol_handle(sess)
    bat = Batcher(sess, max_batch=8, max_wait=60.0)
    assert bat.next_deadline() is None
    bat.submit(h, RNG.standard_normal(N))
    bucket_dl = bat.next_deadline()
    assert bucket_dl is not None  # ~ t_submit + 60
    bat.submit(h, RNG.standard_normal(N), timeout_s=0.5)
    assert bat.next_deadline() < bucket_dl  # the request deadline wins
    bat.flush()


# -- admission control + load shedding ------------------------------------


def test_admission_control_rejects_at_the_door():
    sess = Session()
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    bat = Batcher(sess, max_batch=64, max_wait=60.0,
                  shed_policy=ShedPolicy(max_queue_depth=3))
    futs = [bat.submit(h, RNG.standard_normal(N)) for _ in range(5)]
    rejected = [f for f in futs if f.done()]
    assert len(rejected) == 2
    for f in rejected:
        assert isinstance(f.exception(), RequestShed)
    assert sess.metrics.get("admission_rejected_total") == 2
    bat.flush()
    assert sum(1 for f in futs if f.exception() is None) == 3
    assert _conservation_holds(sess.metrics)


def test_load_shedding_drops_cheapest_to_recompute_first():
    """Requests against a RESIDENT factor re-cost one solve; a cold
    operator re-costs factor + solve — so under overload the resident
    handle's requests shed first (the round-9 cost-log ordering)."""
    sess = Session()
    warm, _ = _chol_handle(sess)
    cold, _ = _chol_handle(sess)
    sess.warmup(warm)  # warm is resident; cold never factored
    assert sess.recompute_cost(warm) < sess.recompute_cost(cold)
    bat = Batcher(sess, max_batch=64, max_wait=60.0,
                  shed_policy=ShedPolicy(max_age_s=0.01,
                                         shed_fraction=0.5,
                                         min_queue_depth=2))
    warm_futs = [bat.submit(warm, RNG.standard_normal(N))
                 for _ in range(4)]
    cold_futs = [bat.submit(cold, RNG.standard_normal(N))
                 for _ in range(4)]
    time.sleep(0.05)
    assert bat.maybe_shed() == 4
    assert all(isinstance(f.exception(), RequestShed)
               for f in warm_futs)          # cheapest shed first
    assert not any(f.done() for f in cold_futs)
    assert sess.metrics.get("shed_requests_total") == 4
    assert sess.metrics.get("load_sheds_total") == 1
    bat.flush()
    assert all(f.result(timeout=0).shape == (N,) for f in cold_futs)
    assert _conservation_holds(sess.metrics)


def test_shed_no_trigger_is_free_and_inactive():
    sess = Session()
    h, _ = _chol_handle(sess)
    bat = Batcher(sess, max_batch=64, max_wait=60.0)  # no policy
    assert bat.maybe_shed() == 0  # one is-None check
    bat2 = Batcher(sess, max_batch=64, max_wait=60.0,
                   shed_policy=ShedPolicy(max_age_s=10.0))
    bat2.submit(h, RNG.standard_normal(N))
    bat2.submit(h, RNG.standard_normal(N))
    assert bat2.maybe_shed() == 0  # young queue: no trigger
    assert sess.metrics.get_gauge("shedding_active") == 0.0
    bat2.flush()


def test_slo_worst_burn_rate_signal():
    from slate_tpu.obs.slo import Objective, SloTracker
    clock = [1000.0]
    tr = SloTracker([Objective("errs", "error_rate", 0.99,
                               windows=(10.0, 100.0))],
                    clock=lambda: clock[0])
    assert tr.worst_burn_rate() == 0.0
    for i in range(8):
        tr.record_request("chol", 64, 0.01, ok=True)
    tr.record_request("chol", 64, 0.01, ok=False)
    tr.record_request("chol", 64, 0.01, ok=False)
    # 2 bad / 10 over budget 0.01 -> burn 20
    assert tr.worst_burn_rate() == pytest.approx(20.0)


# -- cancelled requests must not pin backpressure (satellite) --------------


def test_backpressure_excludes_cancelled_requests():
    sess = Session()
    h, _ = _chol_handle(sess)
    bat = Batcher(sess, max_batch=8, max_wait=60.0)
    f_old = bat.submit(h, RNG.standard_normal(N))
    time.sleep(0.05)
    f_new = bat.submit(h, RNG.standard_normal(N))
    age_with = bat.backpressure()["oldest_request_age_s"]
    assert age_with >= 0.05
    assert f_old.cancel()
    # the cancelled head no longer pins the age gauge high (it would
    # otherwise trigger spurious shedding forever)
    age_without = bat.backpressure()["oldest_request_age_s"]
    assert age_without < 0.05
    # the exact-recompute path agrees
    bat._update_backpressure_locked()
    assert (sess.metrics.get_gauge("oldest_request_age_s")
            < 0.05 + 0.02)
    assert not f_new.done()
    bat.flush()
    assert f_new.result(timeout=0).shape == (N,)


# -- circuit breaker + degradation ladder ----------------------------------


def test_breaker_trips_and_degrades_grouped_bucket_to_per_request():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=3, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=4),)))
    hs, mats = _small_handles(sess, k=3, n=16)
    with Executor(sess, max_batch=4, max_wait=1e-3, retries=0,
                  breaker_threshold=2, breaker_cooldown=60.0) as ex:
        futs, rhs = [], []
        for i in range(12):
            b = RNG.standard_normal(16)
            rhs.append((hs[i % 3], mats[i % 3], b))
            futs.append(ex.submit(hs[i % 3], b))
        outcomes = []
        for f in futs:
            try:
                outcomes.append(("ok", f.result(timeout=60)))
            except Exception as e:  # noqa: BLE001
                outcomes.append((type(e).__name__, None))
    m = sess.metrics
    assert m.get("breaker_trips_total") >= 1
    assert m.get("degraded_dispatches_total") >= 1
    # after the fault budget (4) is exhausted, the degraded lane serves
    # correct per-request answers
    served = [(x, a, b) for (o, x), (h, a, b) in zip(outcomes, rhs)
              if o == "ok"]
    assert served
    for x, a, b in served:
        assert np.abs(a @ x - b).max() < 1e-6
    assert _conservation_holds(m)
    assert m.get_gauge("circuit_breakers_open") >= 1


def test_breaker_mixed_rung_demotes_to_working_precision():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=5, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=4),)))
    n = 48
    spd = _spd(n, np.float32)
    h = sess.register(st.hermitian(np.tril(spd), nb=16,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=True)
    sess.warmup(h)
    assert sess._ops[h].refine is not None
    with Executor(sess, max_batch=4, max_wait=1e-3, retries=0,
                  breaker_threshold=2, breaker_cooldown=60.0) as ex:
        rhs = [RNG.standard_normal(n).astype(np.float32)
               for _ in range(8)]
        futs = [ex.submit(h, b) for b in rhs]
        for f in futs:
            f.exception(timeout=60)  # wait for resolution either way
    m = sess.metrics
    assert m.get("refine_demotions_total") == 1
    assert sess._ops[h].refine is None  # demoted, stays demoted
    assert m.get("breaker_trips_total") >= 1
    assert _conservation_holds(m)
    served = [(f.result(), b) for f, b in zip(futs, rhs)
              if f.exception() is None]
    assert served  # the working-precision lane served correct answers
    for x, b in served:
        assert np.abs(spd @ x - b).max() / n < 1e-3


def test_breaker_mesh_rung_rejects_with_clear_error(monkeypatch):
    """mesh→reject: a sharded program has no single-chip degraded form
    — the breaker fails the bucket fast with a clear error instead of
    retry-storming. (The mesh classification is monkeypatched onto a
    dense session: the rung under test is Executor policy, and a real
    mesh register costs multi-device AOT compiles tier-1 can't
    afford.)"""
    sess = Session()
    sess.enable_faults(FaultPlan(seed=11, specs=(
        FaultSpec("dispatch_error", rate=1.0),)))
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    monkeypatch.setattr(Session, "degrade_class",
                        lambda self, handle: "mesh")
    with Executor(sess, max_batch=4, max_wait=1e-3, retries=0,
                  breaker_threshold=2, breaker_cooldown=60.0) as ex:
        futs = [ex.submit(h, RNG.standard_normal(N)) for _ in range(8)]
        errs = [f.exception(timeout=60) for f in futs]
    # pre-trip buckets carry the transient error; once the breaker is
    # open every bucket is REJECTED with the ladder-naming SlateError
    assert all(e is not None for e in errs)  # nothing served, none lost
    rejected = [e for e in errs if isinstance(e, SlateError)
                and "mesh" in str(e) and "reject" in str(e)]
    assert rejected, "breaker rejection must name the ladder rung"
    assert sess.metrics.get("breaker_rejections_total") >= 1
    assert _conservation_holds(sess.metrics)


def test_breaker_half_open_probe_closes_on_success():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=3, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=2),)))
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    with Executor(sess, max_batch=2, max_wait=1e-3, retries=0,
                  breaker_threshold=2, breaker_cooldown=0.05) as ex:
        # two failing buckets trip the breaker (faults exhausted after)
        for _ in range(2):
            fs = [ex.submit(h, RNG.standard_normal(N))
                  for _ in range(2)]
            for f in fs:
                f.exception(timeout=60)
        assert sess.metrics.get("breaker_trips_total") == 1
        time.sleep(0.08)  # past the cooldown -> next bucket is a probe
        f = ex.submit(h, RNG.standard_normal(N))
        assert f.result(timeout=60).shape == (N,)
    m = sess.metrics
    assert m.get("breaker_probes_total") >= 1
    assert m.get("breaker_closes_total") == 1
    assert m.get_gauge("circuit_breakers_open") == 0
    assert _conservation_holds(m)


def test_admission_reject_callback_may_reenter_submit():
    """Futures must NEVER be resolved while the Executor's lock is
    held: the reject message tells clients to retry, and the natural
    implementation is a done-callback that calls submit() again —
    which deadlocks on the non-reentrant lock if the rejection were
    resolved inside it. The rejected future is already done when the
    callback attaches, so the re-entry runs inline on the submitting
    thread — inside submit()'s own call frame before the fix."""
    sess = Session()
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    resubmitted = []
    with Executor(sess, max_batch=64, max_wait=1e-3,
                  shed_policy=ShedPolicy(max_queue_depth=2)) as ex:
        def retry_once(f):
            if isinstance(f.exception(), RequestShed) and not resubmitted:
                resubmitted.append(ex.submit(h, RNG.standard_normal(N)))
        futs = [ex.submit(h, RNG.standard_normal(N)) for _ in range(2)]
        rej = ex.submit(h, RNG.standard_normal(N))  # admission-rejected
        rej.add_done_callback(retry_once)  # fires inline (already done)
        with pytest.raises(RequestShed):
            rej.result(timeout=30)
        assert resubmitted  # the re-entrant retry ran, no deadlock
        for f in futs:
            assert f.result(timeout=30) is not None
        resubmitted[0].exception(timeout=30)  # resolved either way
        assert resubmitted[0].done()


def test_expiry_callback_may_reenter_submit_on_worker_thread():
    """The worker fails expired futures AFTER releasing its lock: a
    deadline-expiry done-callback that re-enters submit() runs on the
    worker thread and must not deadlock."""
    sess = Session()
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    resubmitted = []
    with Executor(sess, max_batch=64, max_wait=0.2) as ex:
        def retry_once(f):
            if isinstance(f.exception(), DeadlineExceeded) \
                    and not resubmitted:
                resubmitted.append(ex.submit(h, RNG.standard_normal(N),
                                             timeout_s=60.0))
        exp = ex.submit(h, RNG.standard_normal((N, 2)), timeout_s=0.0)
        exp.add_done_callback(retry_once)
        with pytest.raises(DeadlineExceeded):
            exp.result(timeout=30)
        t0 = time.monotonic()
        while not resubmitted and time.monotonic() - t0 < 30:
            time.sleep(0.005)
        assert resubmitted  # re-entered from the worker, no deadlock
        assert resubmitted[0].result(timeout=30).shape == (N,)


def test_shed_respects_min_queue_depth_floor():
    sess = Session()
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    bat = Batcher(sess, max_batch=64, max_wait=60.0,
                  shed_policy=ShedPolicy(max_age_s=0.01,
                                         shed_fraction=1.0,
                                         min_queue_depth=4))
    futs = [bat.submit(h, RNG.standard_normal(N)) for _ in range(6)]
    time.sleep(0.05)
    # fraction 1.0 wants all 6; the floor keeps 4 live
    assert bat.maybe_shed() == 2
    assert sum(1 for f in futs if f.done()) == 2
    # a drained-below-floor queue is no longer "shedding"
    assert bat.maybe_shed() == 0
    assert sess.metrics.get_gauge("shedding_active") == 0.0
    bat.flush()
    assert sum(1 for f in futs if f.exception() is None) == 4


# -- cancellation races under injected faults (satellite) ------------------


def test_cancel_between_detach_and_dispatch():
    sess = Session()
    h, _ = _chol_handle(sess)
    sess.warmup(h)
    bat = Batcher(sess, max_batch=4, max_wait=60.0)
    futs = [bat.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    popped = bat.pop_ready(force=True)
    assert len(popped) == 1
    assert futs[1].cancel()  # between detach and dispatch
    bat.run(*popped[0])
    assert futs[1].cancelled()
    for i in (0, 2, 3):
        assert futs[i].result(timeout=0).shape == (N,)
    m = sess.metrics
    # caught by the pre-dispatch done() filter: not a cancellation
    # inside resolution, so cancelled_requests stays 0 (the round-6
    # pinned convention) and the SLO stream records exactly the three
    # served requests — the client-cancelled future is the one legal
    # gap in the conservation identity (the client resolved it, not
    # the runtime)
    assert m.get("cancelled_requests") == 0
    assert m.get("completed_requests") == 3
    assert m.get("requests_total") == 4


def test_cancel_during_backoff_sleep():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=7, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=1),)))
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    with Executor(sess, max_batch=2, max_wait=1e-3, retries=2,
                  backoff_base=0.3, backoff_max=0.3) as ex:
        f_cancel = ex.submit(h, RNG.standard_normal(N))
        b = RNG.standard_normal(N)
        f_live = ex.submit(h, b)
        # wait for attempt 0 to fail (retries counter moves), i.e. the
        # worker is inside its backoff sleep
        t0 = time.monotonic()
        while sess.metrics.get("retries") < 1:
            assert time.monotonic() - t0 < 30
            time.sleep(0.005)
        assert f_cancel.cancel()
        x = f_live.result(timeout=60)  # the retry serves the survivor
        assert np.abs(spd @ x - b).max() < 1e-8
    m = sess.metrics
    assert f_cancel.cancelled()
    assert m.get("completed_requests") == 1
    assert m.get("retries") == 1
    # resolved exactly once each: no InvalidStateError double-count
    assert m.get("cancelled_requests") == 0
    # SLO/metrics never saw the cancelled request as served or failed
    assert m.get("failed_requests_total") == 0


def test_cancel_during_degraded_per_request_replay():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=3, specs=(
        FaultSpec("dispatch_error", rate=1.0, count=2),)))
    hs, mats = _small_handles(sess, k=2, n=16)
    bat = Batcher(sess, max_batch=4, max_wait=60.0)
    futs = [bat.submit(hs[i % 2], RNG.standard_normal(16))
            for i in range(4)]
    popped = bat.pop_ready(force=True)
    assert len(popped) == 1  # one grouped bucket
    assert futs[2].cancel()  # cancel before the degraded replay
    bat.run_degraded(*popped[0])
    # exactly-once resolution: the cancelled future stays cancelled,
    # the rest are resolved by the replay (some may carry the injected
    # per-item dispatch fault — failed, not lost)
    assert futs[2].cancelled()
    m = sess.metrics
    assert m.get("degraded_dispatches_total") == 1
    for i in (0, 1, 3):
        assert futs[i].done() and not futs[i].cancelled()
    resolved = sum(1 for i in (0, 1, 3)
                   if futs[i].exception() is None)
    failed = m.get("failed_requests_total")
    assert resolved == m.get("completed_requests")
    assert resolved + failed == 3
    assert m.get("cancelled_requests") == 0  # skipped pre-dispatch


# -- conservation under a mixed soak ---------------------------------------


def test_conservation_and_correctness_under_injected_soak():
    """A miniature chaos soak inside tier-1: dispatch faults + slow
    device + deadline lane; every future resolves, every completed
    answer is right, and the conservation identity holds exactly."""
    sess = Session()
    sess.enable_slo()
    sess.enable_faults(FaultPlan(seed=2, specs=(
        FaultSpec("dispatch_error", rate=0.25, count=6),
        FaultSpec("slow_device", rate=0.2, latency_s=1e-3),)))
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    futs = []
    with Executor(sess, max_batch=4, max_wait=1e-3, retries=1,
                  backoff_base=1e-3, breaker_threshold=3,
                  breaker_cooldown=60.0) as ex:
        for i in range(24):
            b = RNG.standard_normal(N)
            futs.append((ex.submit(h, b), b))
        for _ in range(3):
            futs.append((ex.submit(h, RNG.standard_normal((N, 2)),
                                   timeout_s=0.0), None))
        ex.flush()
        assert all(f.done() for f, _ in futs)  # zero lost futures
    wrong = sum(1 for f, b in futs
                if b is not None and f.exception() is None
                and np.abs(spd @ f.result() - b).max() >= 1e-8)
    assert wrong == 0  # zero wrong answers
    m = sess.metrics
    assert m.get("deadline_expired_total") == 3
    assert _conservation_holds(m)
    # SLO request stream agrees with the conservation counters
    err = next(o for o in sess.slo.evaluate()["objectives"]
               if o["name"] == "request_errors")
    win = max(err["windows"], key=lambda w: w["window_s"])
    assert win["total"] == (m.get("completed_requests")
                            + m.get("failed_requests_total")
                            + m.get("deadline_expired_total"))
    assert win["bad"] == (m.get("failed_requests_total")
                          + m.get("deadline_expired_total"))


# -- refine fault seams ----------------------------------------------------


def test_injected_lo_factor_failure_takes_counted_fallback():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=1, specs=(
        FaultSpec("lo_factor_fail", rate=1.0, count=1),)))
    n = 48
    spd = _spd(n, np.float32)
    h = sess.register(st.hermitian(np.tril(spd), nb=16,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=True)
    b = RNG.standard_normal(n).astype(np.float32)
    x = sess.solve(h, b)  # fallback refactors at working precision
    assert np.abs(spd @ x - b).max() / n < 1e-3
    assert sess.metrics.get("refine_fallbacks_total") == 1
    assert sess.metrics.get("fault:lo_factor_fail") == 1
    assert sess._ops[h].refine is None


def test_injected_refine_non_convergence_takes_counted_fallback():
    sess = Session()
    sess.enable_faults(FaultPlan(seed=1, specs=(
        FaultSpec("refine_no_converge", rate=1.0, count=1),)))
    n = 48
    spd = _spd(n, np.float32)
    h = sess.register(st.hermitian(np.tril(spd), nb=16,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=True)
    sess.warmup(h)
    b = RNG.standard_normal(n).astype(np.float32)
    x = sess.solve(h, b)
    assert np.abs(spd @ x - b).max() / n < 1e-3
    assert sess.metrics.get("refine_fallbacks_total") == 1
    assert sess.metrics.get("fault:refine_no_converge") == 1


def test_injected_hbm_exhaustion_forces_eviction_under_pressure():
    sess = Session()  # UNBOUNDED: only the injected pressure evicts
    sess.enable_faults(FaultPlan(seed=1, specs=(
        FaultSpec("hbm_exhaustion", rate=1.0, after=1, count=1),)))
    h1, _ = _chol_handle(sess)
    h2, _ = _chol_handle(sess)
    sess.solve(h1, RNG.standard_normal(N))  # insert 0: clean
    # h2's insert hits the injected exhaustion: h1 evicted, h2 kept,
    # and the overflow counted exactly like a genuinely full budget
    sess.solve(h2, RNG.standard_normal(N))
    assert sess.cached_handles() == [h2]
    assert sess.metrics.get("evictions") == 1
    assert sess.metrics.get("budget_overflows") == 1
    assert sess.metrics.get("fault:hbm_exhaustion") == 1


# -- artifact-schema satellites --------------------------------------------


def test_serve_artifact_sections_pinned_across_tools():
    """Round 22 unified the two hand-synced SERVE_ARTIFACT_SECTIONS
    copies into tools/serve_sections.py, loaded by both consumers
    under ONE fixed module name — so the old tuple-equality pin
    strengthens to import IDENTITY: both tools hold the same object,
    and drift is structurally impossible."""
    def load(path, name):
        spec = importlib.util.spec_from_file_location(name, str(path))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    gate = load(_REPO / "tools" / "bench_gate.py", "bench_gate_pin")
    serve = load(_REPO / "bench_serve.py", "bench_serve_pin")
    assert gate.SERVE_ARTIFACT_SECTIONS is serve.SERVE_ARTIFACT_SECTIONS
    shared = load(_REPO / "tools" / "serve_sections.py",
                  "serve_sections_pin")
    assert (tuple(gate.SERVE_ARTIFACT_SECTIONS)
            == tuple(shared.SERVE_ARTIFACT_SECTIONS))
    assert "incidents" in gate.SERVE_ARTIFACT_SECTIONS


def test_committed_chaos_artifact_validates_and_holds():
    spec = importlib.util.spec_from_file_location(
        "bench_gate_chaos", str(_REPO / "tools" / "bench_gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)
    path = _REPO / "CHAOS_r01.json"
    assert path.exists(), "committed chaos artifact missing"
    recs = gate.normalize_all(str(path))
    assert len(recs) == 1 and recs[0]["kind"] == "chaos"
    assert recs[0]["ok"] is True
    import json
    doc = json.loads(path.read_text())
    assert len(doc["fault_classes"]) >= 4  # the acceptance floor
    assert doc["invariants"]["schedule_reproducible"] is True
    assert doc["invariants"]["wrong_answers"] == 0
    assert doc["invariants"]["lost_futures"] == 0


def test_committed_overload_artifact_validates_and_holds():
    spec = importlib.util.spec_from_file_location(
        "bench_gate_ovl", str(_REPO / "tools" / "bench_gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)
    path = _REPO / "BENCH_OVERLOAD_r01.json"
    assert path.exists(), "committed overload artifact missing"
    recs = gate.normalize_all(str(path))
    assert {r["op"] for r in recs} == {"shed", "no_shed"}
    by_arm = {r["op"]: r for r in recs}
    # the acceptance shape: shedding bounds p99; no-shed grows
    assert (by_arm["shed"]["metrics"]["p99_latency_s"]
            < by_arm["no_shed"]["metrics"]["p99_latency_s"] / 2)
    import json
    doc = json.loads(path.read_text())
    assert doc["ok"] is True and doc["no_shed_age_grows"] is True
