"""SLO objectives + rolling-window burn rates (slate_tpu.obs.slo).

The burn-rate math is pinned by hand (events at explicit timestamps,
hand-computed bad/total/budget ratios), the multi-window conjunctive
breach rule is exercised in both directions (short-dirty/long-clean
must NOT page), the Session/Batcher event feed is verified over the
small-problem engine (cheap programs), and the round-8 acceptance —
disabled path allocates nothing — is extended to this module.
"""

import json
import urllib.request

import numpy as np
import pytest

import slate_tpu as st  # noqa: F401 — jax/platform init via conftest
from slate_tpu import obs
from slate_tpu.obs.slo import (DEFAULT_WINDOWS, Objective, SloTracker,
                               default_objectives, n_bucket)
from slate_tpu.runtime import Batcher, Metrics, Session

RNG = np.random.default_rng(31)
N = 8  # small-problem engine: tiny bucket programs, no dense compiles


def _small_session(**kw):
    sess = Session(**kw)
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    h = sess.register(np.asarray(a), op="lu_small")
    return sess, h


# -- burn-rate math (hand-pinned) -------------------------------------------


def test_burn_rate_formula_pins():
    """burn = (bad/total) / (1 - target), per window."""
    obj = Objective("lat", "latency", 0.9, threshold_s=0.1,
                    windows=(60.0,))
    t = SloTracker([obj])
    # 10 events at t=100: 4 over threshold -> error rate 0.4,
    # budget 0.1 -> burn 4.0
    for i in range(10):
        t.record_request("lu", 8, 0.5 if i < 4 else 0.01, t=100.0)
    row = t.evaluate(now=101.0)["objectives"][0]
    w = row["windows"][0]
    assert w["total"] == 10 and w["bad"] == 4
    assert w["good_fraction"] == pytest.approx(0.6)
    assert w["burn_rate"] == pytest.approx(4.0)
    assert row["breached"]  # 4.0 > burn_threshold 1.0
    # observed latency at the target quantile is reported
    assert w["latency_at_target_quantile_s"] == pytest.approx(0.5)


def test_error_rate_and_failed_requests_count_bad():
    obj = Objective("err", "error_rate", 0.99, windows=(60.0,))
    t = SloTracker([obj])
    for i in range(4):
        t.record_request("chol", 8, 0.01, ok=(i != 0), t=10.0)
    w = t.evaluate(now=11.0)["objectives"][0]["windows"][0]
    assert w["bad"] == 1 and w["total"] == 4
    assert w["burn_rate"] == pytest.approx(0.25 / 0.01)


def test_window_pruning_excludes_old_events():
    obj = Objective("lat", "latency", 0.9, threshold_s=0.1,
                    windows=(60.0,))
    t = SloTracker([obj])
    t.record_request("lu", 8, 9.9, t=10.0)    # bad, but ancient
    t.record_request("lu", 8, 0.01, t=500.0)  # good, in window
    w = t.evaluate(now=520.0)["objectives"][0]["windows"][0]
    assert w["total"] == 1 and w["bad"] == 0
    assert w["burn_rate"] == 0.0


def test_multi_window_breach_requires_every_window():
    """The conjunctive multi-window rule: a burst that is dirty over
    the short window but diluted below threshold over the long one
    must NOT breach; dirty over both must."""
    obj = Objective("lat", "latency", 0.9, threshold_s=0.1,
                    windows=(60.0, 3600.0))
    t = SloTracker([obj])
    # 200 good events spread over the past hour, 5 bad just now:
    # short window: 5/5 bad -> burn 10; long: 5/205 -> burn ~0.24
    for i in range(200):
        t.record_request("lu", 8, 0.01, t=1000.0 + i * 10)
    for _ in range(5):
        t.record_request("lu", 8, 5.0, t=3590.0)
    row = t.evaluate(now=3600.0)["objectives"][0]
    short, long_ = row["windows"]
    assert short["burn_rate"] > 1.0 > long_["burn_rate"]
    assert not row["breached"]
    # now make the long window dirty too
    for _ in range(50):
        t.record_request("lu", 8, 5.0, t=3595.0)
    row = t.evaluate(now=3600.0)["objectives"][0]
    assert all(w["burn_rate"] > 1.0 for w in row["windows"])
    assert row["breached"]


def test_empty_window_never_breaches():
    obj = Objective("lat", "latency", 0.9, threshold_s=0.1,
                    windows=(60.0,))
    t = SloTracker([obj])
    row = t.evaluate(now=100.0)["objectives"][0]
    assert row["windows"][0]["total"] == 0
    assert row["windows"][0]["burn_rate"] is None
    assert not row["breached"]


def test_scoped_objectives_filter_op_and_bucket():
    scoped = Objective("lu_only", "error_rate", 0.9, op="lu",
                       n_bucket=n_bucket(100), windows=(60.0,))
    t = SloTracker([scoped])
    t.record_request("lu", 100, 0.1, ok=False, t=10.0)   # matches
    t.record_request("chol", 100, 0.1, ok=False, t=10.0)  # wrong op
    t.record_request("lu", 9, 0.1, ok=False, t=10.0)      # wrong bucket
    w = t.evaluate(now=11.0)["objectives"][0]["windows"][0]
    assert w["total"] == 1
    # bucket quantization: 65..128 -> 128
    assert n_bucket(100) == 128 and n_bucket(128) == 128
    assert n_bucket(129) == 256


def test_cache_and_oom_kinds():
    objs = [Objective("hits", "cache_hit_rate", 0.5, windows=(60.0,)),
            Objective("oom", "oom_risk", 0.5, windows=(60.0,))]
    t = SloTracker(objs)
    t.record_cache(True, t=1.0)
    t.record_cache(False, t=1.0)
    t.record_oom(True, t=1.0)
    rows = t.evaluate(now=2.0)["objectives"]
    assert rows[0]["windows"][0]["bad"] == 1  # one miss
    assert rows[0]["windows"][0]["burn_rate"] == pytest.approx(1.0)
    assert rows[1]["windows"][0]["bad"] == 0


def test_breach_transition_publishes_metrics_and_warns(caplog):
    obj = Objective("lat", "latency", 0.9, threshold_s=0.1,
                    windows=(60.0,))
    m = Metrics()
    t = SloTracker([obj], metrics=m)
    t.record_request("lu", 8, 5.0, t=10.0)
    with caplog.at_level("WARNING", logger="slate_tpu.obs"):
        t.evaluate(now=11.0)
    assert any("SLO breach" in r.message for r in caplog.records)
    assert m.get("slo_breaches_total") == 1.0
    assert m.get_gauge("slo_breached:lat") == 1.0
    assert m.get_gauge("slo_burn_rate:lat:w60") == pytest.approx(10.0)
    # still breached on re-evaluation: counter must NOT double-count
    t.evaluate(now=12.0)
    assert m.get("slo_breaches_total") == 1.0


def test_objective_validation():
    with pytest.raises(ValueError):
        Objective("x", "nope", 0.9)
    with pytest.raises(ValueError):
        Objective("x", "latency", 0.9)  # no threshold
    with pytest.raises(ValueError):
        Objective("x", "error_rate", 1.5)


# -- runtime integration -----------------------------------------------------


def test_session_feeds_request_cache_and_stage_events():
    """A served small-problem workload populates the solve stream, the
    cache stream, and the lifecycle stage histograms."""
    sess, h = _small_session(hbm_budget=1 << 20)
    slo = sess.enable_slo(default_objectives(windows=(60.0,)))
    assert sess.enable_slo() is slo  # idempotent
    for _ in range(3):
        sess.solve(h, RNG.standard_normal(N))
    payload = slo.evaluate()
    rows = {o["name"]: o for o in payload["objectives"]}
    # solve-source events are not in the default "request" source
    # objectives; cache + oom streams ARE fed
    hits = rows["factor_cache_hit_rate"]["windows"][0]
    assert hits["total"] == 3 and hits["bad"] == 1  # 1 miss, 2 hits
    oom = rows["hbm_oom_risk"]["windows"][0]
    assert oom["total"] >= 1 and oom["bad"] == 0
    snap = sess.metrics.snapshot()
    for stage in ("stage_dispatch", "stage_device_execute"):
        assert snap["histograms"][stage]["count"] == 3


def test_batcher_feeds_request_stream_and_solve_objective():
    sess, h = _small_session()
    slo = sess.enable_slo([
        Objective("req", "error_rate", 0.9, windows=(60.0,)),
        Objective("solve", "error_rate", 0.9, source="solve",
                  windows=(60.0,)),
    ])
    bt = Batcher(sess, max_batch=8, max_wait=60.0)
    futs = [bt.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    bt.flush()
    for f in futs:
        f.result(timeout=0)
    rows = {o["name"]: o for o in slo.evaluate()["objectives"]}
    assert rows["req"]["windows"][0]["total"] == 4      # Batcher feed
    assert rows["req"]["windows"][0]["bad"] == 0
    assert rows["solve"]["windows"][0]["total"] == 4    # Session feed


def test_singular_item_records_error_event():
    sess, h = _small_session()
    bad = sess.register(np.zeros((N, N)), op="lu_small")
    slo = sess.enable_slo([Objective("req", "error_rate", 0.9,
                                     windows=(60.0,))])
    bt = Batcher(sess, max_batch=8, max_wait=60.0)
    f_ok = bt.submit(h, RNG.standard_normal(N))
    f_bad = bt.submit(bad, RNG.standard_normal(N))
    bt.flush()
    f_ok.result(timeout=0)
    with pytest.raises(Exception):
        f_bad.result(timeout=0)
    w = slo.evaluate()["objectives"][0]["windows"][0]
    assert w["total"] == 2 and w["bad"] == 1


def test_slo_endpoint_serves_payload_and_prometheus_gauges():
    sess, h = _small_session()
    sess.enable_slo(default_objectives(windows=(60.0,)))
    sess.solve(h, RNG.standard_normal(N))
    srv = sess.serve_obs()
    try:
        body = urllib.request.urlopen(srv.url("/slo"),
                                      timeout=10).read().decode()
        payload = json.loads(body)
        assert payload["enabled"]
        assert {o["name"] for o in payload["objectives"]} >= {
            "request_latency", "factor_cache_hit_rate"}
        prom = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=10).read().decode()
        assert "slate_tpu_slo_burn_rate" in prom
        assert "slate_tpu_slo_breached" in prom
    finally:
        sess.close_obs()


def test_slo_endpoint_disabled_payload():
    sess, h = _small_session()
    srv = sess.serve_obs()
    try:
        body = urllib.request.urlopen(srv.url("/slo"),
                                      timeout=10).read().decode()
        assert json.loads(body) == {"enabled": False, "objectives": []}
    finally:
        sess.close_obs()


def test_disabled_path_zero_allocation_extended():
    """Round-8 acceptance extended to round 12: with tracing off and
    NO SloTracker attached, a served workload records zero spans, zero
    SLO gauges, and zero SLO/watchdog counters — the hot path's only
    new cost is `session.slo is not None` checks."""
    tracer = obs.Tracer()  # off
    sess, h = _small_session(tracer=tracer)
    assert sess.slo is None
    bt = Batcher(sess, max_batch=4, max_wait=60.0)
    futs = [bt.submit(h, RNG.standard_normal(N)) for _ in range(3)]
    bt.flush()
    for f in futs:
        f.result(timeout=0)
    assert tracer.spans() == []
    snap = sess.metrics.snapshot()
    assert not any(k.startswith("slo_") for k in snap["gauges"])
    assert not any(k.startswith("slo_") or k.startswith("watchdog")
                   for k in snap["counters"])
