"""Test configuration: 8 virtual CPU devices.

The reference tests multi-node behavior by actually running
``mpirun -np N`` (SURVEY §4). Our analog: an 8-device virtual CPU mesh via
--xla_force_host_platform_device_count, so every collective/sharding path
runs in CI without a TPU pod — the same trick the driver's
dryrun_multichip uses. Single-device degeneracy is tested with 1×1 grids.
"""

import importlib.util
import os

# load compat/platform.py standalone (importing the slate_tpu package
# here would initialize jax before XLA_FLAGS is finalized)
_spec = importlib.util.spec_from_file_location(
    "_slate_tpu_platform",
    os.path.join(os.path.dirname(__file__), os.pardir, "slate_tpu",
                 "compat", "platform.py"))
_platform = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_platform)

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
_probe_cache = os.path.join(os.path.dirname(__file__), os.pardir,
                            ".xla_flag_probe.json")
if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
    # ROOT CAUSE of the round-2 intermittent hard-crash: XLA CPU
    # cross-module collectives rendezvous with a 40 s termination
    # timeout and ABORT the process ("Exiting to ensure a consistent
    # program state", rendezvous.cc) when any virtual device's thread is
    # starved past it — which happens under CPU oversubscription (other
    # test processes / BLAS threads). Reproduced deliberately in round 3
    # by running the suite next to a busy bench process. Raise the
    # timeout so a loaded CI box degrades to slow instead of crashing.
    # GUARDED by a support probe: jaxlib builds that dropped this flag
    # ABORT on unknown XLA_FLAGS (parse_flags_from_env.cc), which used
    # to kill the entire suite at CPU-client creation.
    flags = (flags + _platform.collective_timeout_flag_if_supported(
        cache_path=_probe_cache))
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The axon sitecustomize (TPU tunnel) forces jax_platforms="axon,cpu" via
# jax.config at interpreter start; override back to cpu before any backend
# is initialized so tests get the 8-device virtual mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# Round-2 advisor: a 1-in-4 interpreter hard-crash was seen running
# test_compat.py + test_distribution.py in one process. Root-caused in
# round 3 to the XLA CPU collective rendezvous termination timeout (see
# the XLA_FLAGS comment above); the timeout is raised now. Keep a
# persistent faulthandler trace armed so any new crash class leaves a
# full C-level stack in tests/.faulthandler.log.
import faulthandler  # noqa: E402

_fh_log = open(os.path.join(os.path.dirname(__file__),
                            ".faulthandler.log"), "w")
faulthandler.enable(file=_fh_log, all_threads=True)

# Round-12 stall forensics: the round-11 futex-stall class (XLA:CPU
# collective rendezvous starved under host load) hangs the suite until
# the tier-1 harness's `timeout -k` SIGKILLs it — leaving NO evidence
# of where the threads sat. Arm a dump-traceback watchdog below the
# 870 s tier-1 budget so a stalled run writes every thread's Python
# stack into tests/.faulthandler.log BEFORE the kill (repeat=True: a
# run that stalls twice dumps twice). Tunable/disable-able via env
# (0 disables) for interactive long runs; cancelled on clean session
# finish so post-suite teardown never dumps spuriously.
_STALL_DUMP_S = float(os.environ.get("SLATE_TPU_TIER1_STALL_DUMP_S",
                                     "780"))
if _STALL_DUMP_S > 0:
    faulthandler.dump_traceback_later(_STALL_DUMP_S, repeat=True,
                                      file=_fh_log, exit=False)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: large/expensive cases excluded from the tier-1 "
        "budget (run explicitly with -m slow)")


def pytest_sessionfinish(session, exitstatus):
    # a finished (even failed) session is not a stall
    faulthandler.cancel_dump_traceback_later()


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) >= 8, f"expected 8 virtual devices, got {len(d)}"
    return d


@pytest.fixture(scope="session")
def grid2x2():
    from slate_tpu.core.grid import ProcessGrid
    return ProcessGrid.create(2, 2)


@pytest.fixture(scope="session")
def grid2x4():
    from slate_tpu.core.grid import ProcessGrid
    return ProcessGrid.create(2, 4)


@pytest.fixture(scope="session")
def grid1x1():
    from slate_tpu.core.grid import ProcessGrid
    return ProcessGrid.create(1, 1)
