"""Test configuration: 8 virtual CPU devices.

The reference tests multi-node behavior by actually running
``mpirun -np N`` (SURVEY §4). Our analog: an 8-device virtual CPU mesh via
--xla_force_host_platform_device_count, so every collective/sharding path
runs in CI without a TPU pod — the same trick the driver's
dryrun_multichip uses. Single-device degeneracy is tested with 1×1 grids.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The axon sitecustomize (TPU tunnel) forces jax_platforms="axon,cpu" via
# jax.config at interpreter start; override back to cpu before any backend
# is initialized so tests get the 8-device virtual mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# Round-2 advisor: a 1-in-4 interpreter hard-crash was once seen running
# test_compat.py + test_distribution.py in one process (suspected XLA CPU
# collective/threading interaction). Six back-to-back reruns in round 3
# did not reproduce it; keep a persistent faulthandler trace armed so any
# recurrence leaves a full C-level stack in tests/.faulthandler.log for
# root-causing rather than a bare 'Fatal Python error'.
import faulthandler  # noqa: E402

_fh_log = open(os.path.join(os.path.dirname(__file__),
                            ".faulthandler.log"), "w")
faulthandler.enable(file=_fh_log, all_threads=True)


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) >= 8, f"expected 8 virtual devices, got {len(d)}"
    return d


@pytest.fixture(scope="session")
def grid2x2():
    from slate_tpu.core.grid import ProcessGrid
    return ProcessGrid.create(2, 2)


@pytest.fixture(scope="session")
def grid2x4():
    from slate_tpu.core.grid import ProcessGrid
    return ProcessGrid.create(2, 4)


@pytest.fixture(scope="session")
def grid1x1():
    from slate_tpu.core.grid import ProcessGrid
    return ProcessGrid.create(1, 1)
