"""Live regression watchdog vs the committed baseline
(slate_tpu.obs.watchdog + tools/bench_gate.py --baseline-out).

Injected-regression detection (both directions), quiet-on-real-history
over the committed BASELINE_SERIES.json, bench_gate's tolerance policy
reused (10 % vs best-prior; only tpu/axon gate), anomaly events into
trace + /metrics, and the baseline artifact's single-source-of-truth
contract (bench_gate exports exactly what the watchdog loads).
"""

import importlib.util
import json
import os
import types

import pytest

from slate_tpu import obs
from slate_tpu.obs.watchdog import (Watchdog, baseline_path,
                                    load_baseline, validate_baseline)
from slate_tpu.runtime import Metrics

_ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "_bench_gate", os.path.join(_ROOT, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _synthetic(metric="serve.solves_per_sec", platform="tpu", best=100.0,
               direction="higher", n=512, kind="serve", **extra):
    row = {"kind": kind, "metric": metric, "platform": platform, "n": n,
           "batch": None, "op": None, "dtype": None,
           "direction": direction, "best": best}
    row.update(extra)
    return {"schema": "slate_tpu.baseline_series.v1", "tolerance": 0.10,
            "series": [row]}


# -- the committed artifact --------------------------------------------------


def test_committed_baseline_loads_and_validates():
    doc = load_baseline()
    assert doc["tolerance"] == 0.10
    assert doc["gated_platforms"] == ["tpu", "axon"]
    assert len(doc["series"]) > 20
    assert validate_baseline(doc) == []
    # direction annotation: residual, latency, queue-age (round 14
    # overload columns), recovery/failover/refactor series (round 17
    # failover columns), sync.* transfer-byte series (round 20 delta
    # replication), and the round-23 forecast columns (holdout MAE,
    # store overhead pct, record-path ns/sample — error and cost) are
    # lower-is-better, everything else higher
    for row in doc["series"]:
        want = ("lower" if (row["metric"].startswith("residual_")
                            or row["metric"].startswith("sync.")
                            or "latency" in row["metric"]
                            or "age_s" in row["metric"]
                            or "recovery" in row["metric"]
                            or "failover" in row["metric"]
                            or "refactor" in row["metric"]
                            or "mae" in row["metric"]
                            or "overhead" in row["metric"]
                            or "ns_per_sample" in row["metric"])
                else "higher")
        assert row["direction"] == want, row["metric"]
    # real tpu history exists (rounds 1–5 on-chip runs) — the series
    # the first on-chip session will self-verify against
    assert any(r["platform"] == "tpu" for r in doc["series"])


def test_baseline_is_bench_gates_own_export(tmp_path):
    """Single source of truth: regenerating via bench_gate reproduces
    the committed file's series exactly (a stale committed baseline
    would silently blind the watchdog)."""
    bg = _bench_gate()
    records = [rec for p in bg.discover(_ROOT)
               for rec in bg.normalize_all(p)]
    doc = bg.baseline_series(records)
    committed = load_baseline()
    assert doc["series"] == committed["series"]
    # and the exporter's output validates under the watchdog's loader
    out = tmp_path / "BASELINE_SERIES.json"
    out.write_text(json.dumps(doc))
    assert validate_baseline(load_baseline(str(out))) == []


def test_malformed_baseline_rejected(tmp_path):
    bad = tmp_path / "b.json"
    bad.write_text(json.dumps({"schema": "wrong", "series": []}))
    with pytest.raises(ValueError):
        load_baseline(str(bad))
    with pytest.raises(ValueError):
        Watchdog(baseline={"schema": "slate_tpu.baseline_series.v1",
                           "series": [{"metric": "m"}]})


def test_default_path_points_at_repo_root():
    assert os.path.abspath(baseline_path()) == os.path.abspath(
        os.path.join(_ROOT, "BASELINE_SERIES.json"))


# -- detection ---------------------------------------------------------------


def test_injected_throughput_regression_detected():
    m = Metrics()
    wd = Watchdog(baseline=_synthetic(best=100.0), metrics=m)
    wd.observe("serve.solves_per_sec", 50.0, "tpu", n=512, kind="serve")
    rep = wd.check()
    assert not rep["ok"] and len(rep["anomalies"]) == 1
    row = rep["anomalies"][0]
    assert row["drop_pct"] == pytest.approx(50.0)
    assert row["gated"] and row["direction"] == "higher"
    assert m.get("watchdog_anomalies_total") == 1.0
    assert m.get_gauge("watchdog_anomaly_count") == 1.0


def test_injected_latency_rise_detected():
    """The injected-latency fixture: lower-is-better series, live p99
    10× the committed best -> anomaly."""
    wd = Watchdog(baseline=_synthetic(metric="request_latency_p99",
                                      best=1e-3, direction="lower"))
    wd.observe("request_latency_p99", 1e-2, "tpu", n=512, kind="serve")
    rep = wd.check()
    assert len(rep["anomalies"]) == 1
    assert rep["anomalies"][0]["drop_pct"] == pytest.approx(900.0)


def test_within_tolerance_is_quiet():
    wd = Watchdog(baseline=_synthetic(best=100.0))
    wd.observe("serve.solves_per_sec", 91.0, "tpu", n=512, kind="serve")
    rep = wd.check()
    assert rep["ok"] and rep["matched"] == 1


def test_cpu_platform_reports_informationally():
    """bench_gate policy reused: the same 50 % drop on a CPU-smoke
    series must not page — it lands in the informational list."""
    wd = Watchdog(baseline=_synthetic(platform="cpu", best=100.0))
    wd.observe("serve.solves_per_sec", 50.0, "cpu", n=512, kind="serve")
    rep = wd.check()
    assert rep["ok"] and not rep["anomalies"]
    assert len(rep["informational"]) == 1


def test_window_best_is_charitable():
    """A warmup transient inside an otherwise healthy window is not a
    regression: the live number is the window's best value."""
    wd = Watchdog(baseline=_synthetic(best=100.0))
    wd.observe("serve.solves_per_sec", 5.0, "tpu", n=512, kind="serve",
               t=10.0)  # cold start
    wd.observe("serve.solves_per_sec", 99.0, "tpu", n=512, kind="serve",
               t=11.0)
    assert wd.check(now=12.0)["ok"]
    # but an out-of-window recovery does not save a currently-bad series
    assert not wd.check(now=11.0 + wd.window_s + 1000)["matched"]


def test_quiet_on_real_history():
    """Replaying every committed series at its own best value against
    the committed baseline flags nothing."""
    doc = load_baseline()
    wd = Watchdog()
    for row in doc["series"]:
        wd.observe(row["metric"], row["best"], row["platform"],
                   n=row["n"], op=row["op"], batch=row["batch"],
                   dtype=row["dtype"], kind=row["kind"])
    rep = wd.check()
    assert rep["matched"] == len(doc["series"])
    assert rep["ok"] and not rep["informational"]


def test_unmatched_live_series_counted_not_flagged():
    wd = Watchdog(baseline=_synthetic())
    wd.observe("no.such.metric", 1.0, "tpu", n=4)
    rep = wd.check()
    assert rep["unmatched"] == 1 and rep["matched"] == 0 and rep["ok"]


def test_anomaly_emits_trace_event():
    tracer = obs.Tracer().on()
    wd = Watchdog(baseline=_synthetic(best=100.0), tracer=tracer)
    wd.observe("serve.solves_per_sec", 10.0, "tpu", n=512, kind="serve")
    wd.check()
    events = [s for s in tracer.spans() if s.name == "watchdog.anomaly"]
    assert len(events) == 1 and events[0].kind == "anomaly"
    assert events[0].attrs["metric"] == "serve.solves_per_sec"
    assert events[0].attrs["series_kind"] == "serve"
    tracer.off()


def test_watch_session_derives_headline_series():
    """watch_session reads only session.metrics — the serving headline
    numbers land as live observations under the caller's platform."""
    m = Metrics()
    m.inc("cache_hits", 3)
    m.inc("solves_total", 10)
    m.inc("solve_flops_total", 1e9)
    m.observe("solve_latency", 0.5)
    m.observe("request_latency", 0.01)
    wd = Watchdog(baseline=_synthetic(best=100.0, n=96))
    wd.watch_session(types.SimpleNamespace(metrics=m), platform="tpu",
                     n=96)
    assert ("serve", "serve.solves_per_sec", "tpu", 96, None, None,
            None) in wd._live
    assert ("serve", "request_latency_p99", "tpu", 96, None, None,
            None) in wd._live
    # live 10/0.5 = 20 solves/s vs best 100 -> anomaly
    rep = wd.check()
    assert len(rep["anomalies"]) == 1


def test_baseline_validators_agree_across_gate_and_watchdog(tmp_path):
    """The schema rules exist twice on purpose (bench_gate stays
    jax-import-free and standalone; watchdog needs package context) —
    this pin keeps the two rule sets from drifting: same schema id,
    same filename, and the same malformed documents rejected by both."""
    bg = _bench_gate()
    from slate_tpu.obs import watchdog as wmod
    assert bg.BASELINE_SCHEMA == wmod.BASELINE_SCHEMA
    assert bg.BASELINE_FILENAME == wmod.BASELINE_FILENAME
    sid = wmod.BASELINE_SCHEMA
    bad_docs = [
        {"schema": "wrong", "series": [{"metric": "m", "platform": "p",
                                        "best": 1.0,
                                        "direction": "higher"}]},
        {"schema": sid, "series": []},
        {"schema": sid, "series": [{"metric": "m", "platform": "p",
                                    "best": True, "direction": "higher"}]},
        {"schema": sid, "series": [{"metric": "m", "platform": "p",
                                    "best": 1.0,
                                    "direction": "sideways"}]},
        {"schema": sid, "series": [{"platform": "p", "best": 1.0,
                                    "direction": "higher"}]},
    ]
    for i, doc in enumerate(bad_docs):
        path = tmp_path / f"bad{i}.json"
        path.write_text(json.dumps(doc))
        with pytest.raises(bg.SchemaError):
            bg.validate_baseline_file(str(path))
        assert validate_baseline(doc) != [], doc
    # and a good doc passes both
    good = _synthetic()
    gp = tmp_path / "good.json"
    gp.write_text(json.dumps(good))
    bg.validate_baseline_file(str(gp))
    assert validate_baseline(good) == []


def test_direction_classifier_covers_latency_series():
    """A latency metric entering the baseline must come out
    lower-is-better — an inverted direction would make the watchdog
    read a p99 blowup as an improvement."""
    bg = _bench_gate()
    assert bg._direction("request_latency_p99") == "lower"
    assert bg._direction("serve.p99_latency_ms") == "lower"
    assert bg._direction("residual_posv_hemm") == "lower"
    assert bg._direction("serve.solves_per_sec") == "higher"
    assert bg._direction("potrf_gflops") == "higher"
    # round 23: forecast-error, store-overhead, and record-path-cost
    # series are lower-is-better
    assert bg._direction("holdout_mae") == "lower"
    assert bg._direction("store_overhead_pct") == "lower"
    assert bg._direction("record_ns_per_sample") == "lower"


# -- history-backed mode (round 23) ------------------------------------------


def test_history_mode_window_mean_catches_what_charity_hides():
    """The satellite window-fix: a window that spent most of its time
    regressed with one healthy spike PASSES the charitable deque path
    (window best) but FAILS the history-backed path (true window
    mean) — same observations, same baseline."""
    from slate_tpu.obs.timeseries import TimeseriesStore

    samples = [(float(t), 50.0) for t in range(10, 20)]  # regressed
    samples.append((20.0, 99.0))                         # one spike

    deque_wd = Watchdog(baseline=_synthetic(best=100.0))
    for t, v in samples:
        deque_wd.observe("serve.solves_per_sec", v, "tpu", n=512,
                         kind="serve", t=t)
    assert deque_wd.check(now=21.0)["ok"]  # charity: best-of-window

    store = TimeseriesStore(clock=lambda: 0.0)
    hist_wd = Watchdog(baseline=_synthetic(best=100.0), store=store)
    for t, v in samples:
        hist_wd.observe("serve.solves_per_sec", v, "tpu", n=512,
                        kind="serve", t=t)
    rep = hist_wd.check(now=21.0)
    assert not rep["ok"] and len(rep["anomalies"]) == 1
    row = rep["anomalies"][0]
    assert row["aggregate"] == "window_mean"
    # live is the exact mean (10*50 + 99) / 11
    assert row["live"] == pytest.approx((10 * 50.0 + 99.0) / 11)


def test_history_mode_observations_land_in_the_store():
    """One resident history, no duplicated deque state: observations
    go to the TimeseriesStore under the wd:-prefixed key vocabulary
    and the deque map stays empty."""
    from slate_tpu.obs.timeseries import TimeseriesStore

    store = TimeseriesStore(clock=lambda: 0.0)
    wd = Watchdog(baseline=_synthetic(best=100.0), store=store)
    wd.observe("serve.solves_per_sec", 95.0, "tpu", n=512, kind="serve",
               t=5.0)
    assert not wd._live
    names = store.names()
    assert len(names) == 1 and names[0].startswith("wd:")
    assert store.points(names[0]) == [(5.0, 95.0)]
    # the /history view of watchdog traffic is queryable like any series
    assert wd.check(now=6.0)["ok"]


def test_history_mode_matches_deque_verdict_on_clean_series():
    """Parity pin: on a steady series the two modes agree in verdict
    and (to float exactness on a constant window) in the live value —
    store=None stays the byte-identical round-12 path."""
    from slate_tpu.obs.timeseries import TimeseriesStore

    for live_v, want_ok in ((95.0, True), (50.0, False)):
        deque_wd = Watchdog(baseline=_synthetic(best=100.0))
        store_wd = Watchdog(baseline=_synthetic(best=100.0),
                            store=TimeseriesStore(clock=lambda: 0.0))
        for wd in (deque_wd, store_wd):
            for t in range(10, 15):
                wd.observe("serve.solves_per_sec", live_v, "tpu", n=512,
                           kind="serve", t=float(t))
            rep = wd.check(now=15.0)
            assert rep["ok"] is want_ok
            assert rep["matched"] == 1
        assert deque_wd._live and store_wd.store is not None


def test_history_mode_window_uses_tier_fallback():
    """A raw ring too small for the window still yields the TRUE
    window mean (the finest tier covers the forgotten prefix) — the
    whole point of backing the watchdog with the store."""
    from slate_tpu.obs.timeseries import TimeseriesStore

    store = TimeseriesStore(raw_capacity=4, tier_capacities=(100, 100),
                            clock=lambda: 0.0)
    wd = Watchdog(baseline=_synthetic(best=100.0), store=store,
                  window_s=300.0)
    # 20 samples at 10 s spacing, all regressed; ring holds only 4
    for i in range(20):
        wd.observe("serve.solves_per_sec", 50.0, "tpu", n=512,
                   kind="serve", t=float(10 * i))
    rep = wd.check(now=200.0)
    assert len(rep["anomalies"]) == 1
    assert rep["anomalies"][0]["live"] == pytest.approx(50.0)


def test_baseline_out_regenerates_over_invalid_committed_file(tmp_path):
    """--baseline-out must not be blocked by an invalid EXISTING
    baseline (it is the only tool that can regenerate one)."""
    import shutil
    bg = _bench_gate()
    root = tmp_path / "root"
    root.mkdir()
    # one real artifact + a corrupt committed baseline
    shutil.copy(os.path.join(_ROOT, "BENCH_SERVE_smoke.json"),
                root / "BENCH_SERVE_smoke.json")
    (root / "BASELINE_SERIES.json").write_text('{"schema": "stale"}')
    out = root / "BASELINE_SERIES.json"
    rc = bg.main(["--dir", str(root), "--baseline-out", str(out)])
    assert rc == 0
    assert validate_baseline(load_baseline(str(out))) == []
    # without --baseline-out the corrupt file DOES fail the gate
    (root / "BASELINE_SERIES.json").write_text('{"schema": "stale"}')
    assert bg.main(["--dir", str(root), "--check-schema"]) == 1


def test_watchdog_concurrent_observe_and_check():
    """Producer/consumer safety: observes from one thread while
    another loops check() — no 'mutated during iteration' crashes."""
    import threading
    wd = Watchdog(baseline=_synthetic(best=100.0))
    stop = threading.Event()
    errs = []

    def producer():
        i = 0
        while not stop.is_set():
            wd.observe("serve.solves_per_sec", 99.0, "tpu", n=512,
                       kind="serve")
            wd.observe(f"metric{i % 50}", 1.0, "cpu", n=i % 7)
            i += 1

    def consumer():
        try:
            for _ in range(200):
                wd.check()
        except Exception as e:  # pragma: no cover — the failure mode
            errs.append(e)

    t1 = threading.Thread(target=producer)
    t2 = threading.Thread(target=consumer)
    t1.start(); t2.start()
    t2.join(timeout=30)
    stop.set()
    t1.join(timeout=10)
    assert not errs


def test_persistent_anomaly_counts_once_per_transition():
    """A regression that persists across N check() calls (scrape-
    driven) is ONE regression: counter/log/trace fire on the
    ok -> anomalous transition only; recovery re-arms the series."""
    m = Metrics()
    tracer = obs.Tracer().on()
    wd = Watchdog(baseline=_synthetic(best=100.0), metrics=m,
                  tracer=tracer)
    wd.observe("serve.solves_per_sec", 50.0, "tpu", n=512, kind="serve",
               t=10.0)
    assert not wd.check(now=11.0)["ok"]
    assert m.get("watchdog_anomalies_total") == 1.0
    # still anomalous on the next scrape: reported, NOT re-counted
    rep = wd.check(now=12.0)
    assert len(rep["anomalies"]) == 1
    assert m.get("watchdog_anomalies_total") == 1.0
    assert len([s for s in tracer.spans()
                if s.name == "watchdog.anomaly"]) == 1
    assert m.get_gauge("watchdog_anomaly_count") == 1.0
    # recovery re-arms...
    wd.observe("serve.solves_per_sec", 99.0, "tpu", n=512, kind="serve",
               t=13.0)
    assert wd.check(now=14.0)["ok"]
    assert m.get_gauge("watchdog_anomaly_count") == 0.0
    # ...so a NEW regression (old samples aged out of the window)
    # counts again
    wd.observe("serve.solves_per_sec", 50.0, "tpu", n=512, kind="serve",
               t=200.0)
    assert not wd.check(now=201.0)["ok"]
    assert m.get("watchdog_anomalies_total") == 2.0
    tracer.off()


def test_listener_exception_counted_and_logged_once(caplog):
    """Round 22: a crashing listener (a dead incident hook) must not
    break the check loop OR silently vanish — every failure is counted
    in watchdog_listener_errors_total, the traceback logs ONCE per
    listener, and healthy listeners keep receiving rows."""
    import logging

    m = Metrics()
    wd = Watchdog(baseline=_synthetic(best=100.0), metrics=m)
    seen = []

    def bad(row):
        raise RuntimeError("dead incident hook")

    wd.add_listener(bad)
    wd.add_listener(seen.append)
    wd.observe("serve.solves_per_sec", 50.0, "tpu", n=512, kind="serve",
               t=10.0)
    with caplog.at_level(logging.ERROR, logger="slate_tpu.obs"):
        assert not wd.check(now=11.0)["ok"]  # does not raise
        assert m.get("watchdog_listener_errors_total") == 1.0
        assert len(seen) == 1  # the healthy listener still ran
        # recovery re-arms, a NEW transition fails the listener again:
        # counted again, NOT logged again
        wd.observe("serve.solves_per_sec", 99.0, "tpu", n=512,
                   kind="serve", t=13.0)
        assert wd.check(now=14.0)["ok"]
        wd.observe("serve.solves_per_sec", 50.0, "tpu", n=512,
                   kind="serve", t=200.0)
        assert not wd.check(now=201.0)["ok"]
    assert m.get("watchdog_listener_errors_total") == 2.0
    assert len(seen) == 2
    logged = [r for r in caplog.records
              if "watchdog listener" in r.getMessage()]
    assert len(logged) == 1
    assert "dead incident hook" in logged[0].exc_text
