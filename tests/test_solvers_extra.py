"""Band solvers, condition estimation, indefinite solvers, simplified API,
trace/printing utilities.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import Norm, Options, Side, Uplo
from slate_tpu.matgen import random_spd
from slate_tpu.utils import trace

RNG = np.random.default_rng(41)


def test_gbsv():
    n, kl, ku, nrhs = 40, 3, 2, 2
    a = RNG.standard_normal((n, n)) + 6 * np.eye(n)
    r, c = np.indices((n, n))
    ab = np.where((c - r <= ku) & (r - c <= kl), a, 0.0)
    A = st.band(ab, nb=8, kl=kl, ku=ku)
    b = RNG.standard_normal((n, nrhs))
    X, info = st.gbsv(A, st.from_dense(b, nb=8))
    assert int(info) == 0
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(ab, b),
                               rtol=1e-8, atol=1e-10)


def test_pbsv():
    n, kd, nrhs = 36, 4, 3
    g = RNG.standard_normal((n, n))
    spd = g @ g.T / n + 4 * np.eye(n)
    r, c = np.indices((n, n))
    ab = np.where(np.abs(r - c) <= kd, spd, 0.0)
    # make the banded matrix SPD again (diag dominant)
    ab = ab + 2 * np.eye(n) * np.abs(ab).sum(1).max() / n
    A = st.hermitian_band(np.tril(ab), nb=8, kd=kd, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, nrhs))
    X, info = st.pbsv(A, st.from_dense(b, nb=8))
    assert int(info) == 0
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(ab, b),
                               rtol=1e-8, atol=1e-10)


def test_gecondest():
    n = 32
    a = RNG.standard_normal((n, n)) + 5 * np.eye(n)
    A = st.from_dense(a, nb=8)
    LU, perm, info = st.getrf(A)
    anorm = float(st.norm(A, Norm.One))
    rcond = st.gecondest(LU, perm, anorm)
    true_rcond = 1.0 / (np.linalg.norm(a, 1)
                        * np.linalg.norm(np.linalg.inv(a), 1))
    # estimator must be within ~10x of truth and never above 1
    assert 0 < rcond <= 1.01
    assert true_rcond / 15 < rcond < true_rcond * 15


def test_pocondest_trcondest():
    n = 32
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=8))
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    L, _ = st.potrf(A)
    anorm = float(st.norm(A, Norm.One))
    rcond = st.pocondest(L, anorm)
    true_rcond = 1.0 / (np.linalg.norm(a, 1)
                        * np.linalg.norm(np.linalg.inv(a), 1))
    assert true_rcond / 15 < rcond < true_rcond * 15
    t = np.tril(RNG.standard_normal((n, n))) + 4 * np.eye(n)
    T = st.triangular(t, nb=8, uplo=Uplo.Lower)
    rc = st.trcondest(T)
    assert 0 < rc <= 1.01


def test_hesv():
    n, nrhs = 48, 3
    g = RNG.standard_normal((n, n))
    a = (g + g.T) / 2  # indefinite symmetric
    A = st.symmetric(np.tril(a), nb=16, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, nrhs))
    X, info = st.hesv(A, st.from_dense(b, nb=16))
    res = np.linalg.norm(b - a @ X.to_numpy(), 1) / (
        np.linalg.norm(a, 1) * np.linalg.norm(X.to_numpy(), 1))
    assert res < 1e-10


def test_hesv_rbt_method():
    from slate_tpu.core.types import MethodHesv, Options
    n, nrhs = 40, 2
    g = RNG.standard_normal((n, n))
    a = (g + g.T) / 2
    A = st.symmetric(np.tril(a), nb=8, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, nrhs))
    X, info = st.hesv(A, st.from_dense(b, nb=8),
                      Options(method_hesv=MethodHesv.RBT))
    res = np.linalg.norm(b - a @ X.to_numpy(), 1) / (
        np.linalg.norm(a, 1) * np.linalg.norm(X.to_numpy(), 1))
    assert res < 1e-10


def test_hesv_complex_hermitian():
    n, nrhs = 36, 2
    g = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    a = (g + g.conj().T) / 2  # indefinite Hermitian
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, nrhs)) + 1j * RNG.standard_normal((n, nrhs))
    X, info = st.hesv(A, st.from_dense(b, nb=8))
    res = np.linalg.norm(b - a @ X.to_numpy(), 1) / (
        np.linalg.norm(a, 1) * np.linalg.norm(X.to_numpy(), 1))
    assert res < 1e-10


def test_hesv_zero_diagonal_stability():
    """The no-pivot LDLH killer: a saddle matrix with a ZERO diagonal.
    Pivoted Aasen must solve it deterministically (no RBT luck)."""
    n = 32
    a = np.zeros((n, n))
    # antidiagonal blocks: [[0, I], [I, 0]] plus noise in the corners
    h = n // 2
    a[:h, h:] = np.eye(h)
    a[h:, :h] = np.eye(h)
    a[h:, h:] = 0.01 * np.eye(h)
    A = st.symmetric(np.tril(a), nb=8, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, 2))
    X, info = st.hesv(A, st.from_dense(b, nb=8))
    assert int(info) == 0
    res = np.linalg.norm(b - a @ X.to_numpy(), 1) / (
        np.linalg.norm(a, 1) * np.linalg.norm(X.to_numpy(), 1))
    assert res < 1e-10


def test_hesv_clustered_spectrum():
    """Clustered indefinite spectrum via eigendecomposition matgen."""
    n = 64
    q, _ = np.linalg.qr(RNG.standard_normal((n, n)))
    lam = np.concatenate([np.full(n // 2, 1.0),
                          np.full(n // 4, -1e-4),
                          np.full(n - n // 2 - n // 4, -1.0)])
    a = (q * lam) @ q.T
    a = (a + a.T) / 2
    A = st.symmetric(np.tril(a), nb=16, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, 2))
    X, info = st.hesv(A, st.from_dense(b, nb=16))
    res = np.linalg.norm(b - a @ X.to_numpy(), 1) / (
        np.linalg.norm(a, 1) * np.linalg.norm(X.to_numpy(), 1))
    assert res < 1e-9


def test_hetrf_hetrs_spd_case():
    n = 32
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=12))
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    LT, perm, info = st.hetrf(A)
    assert int(info) == 0
    b = RNG.standard_normal((n, 2))
    X = st.hetrs(LT, perm, st.from_dense(b, nb=8))
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8, atol=1e-9)


def test_hetrf_singular_info():
    n = 16
    a = np.zeros((n, n))  # exactly singular
    A = st.symmetric(np.tril(a), nb=8, uplo=Uplo.Lower)
    LT, perm, info = st.hetrf(A)
    assert int(info) > 0


def test_simplified_api():
    n = 24
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=3))
    b = RNG.standard_normal((n, 2))
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    B = st.from_dense(b, nb=8)
    X = st.chol_solve(A, B)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8)
    g = RNG.standard_normal((n, n)) + 4 * np.eye(n)
    X2 = st.lu_solve(st.from_dense(g, nb=8), B)
    np.testing.assert_allclose(X2.to_numpy(), np.linalg.solve(g, b),
                               rtol=1e-8)
    C = st.from_dense(np.zeros((n, 2)), nb=8)
    Y = st.multiply(1.0, A, B, 0.0, C)
    full = np.tril(a) + np.tril(a, -1).T
    np.testing.assert_allclose(Y.to_numpy(), full @ b, rtol=1e-10)
    m = 40
    aa = RNG.standard_normal((m, n))
    bb = RNG.standard_normal((m, 2))
    Xl = st.least_squares_solve(st.from_dense(aa, nb=8),
                                st.from_dense(bb, nb=8))
    ref, *_ = np.linalg.lstsq(aa, bb, rcond=None)
    np.testing.assert_allclose(Xl.to_numpy()[:n], ref, rtol=1e-7, atol=1e-9)


def test_trace_svg(tmp_path):
    trace.Trace.clear()
    trace.Trace.on()
    with trace.Block("gemm"):
        pass
    with trace.Block("potrf", lane=1):
        pass
    trace.Trace.off()
    p = trace.Trace.finish(str(tmp_path / "trace.svg"))
    assert p and os.path.exists(p)
    svg = open(p).read()
    assert "gemm" in svg and "potrf" in svg and "<svg" in svg
    with trace.timer("phase1"):
        pass
    assert "phase1" in trace.timers


def test_print_and_debug(capsys):
    a = RNG.standard_normal((5, 4))
    A = st.from_dense(a, nb=2)
    out = st.utils.print_matrix("A", A, Options(print_verbose=2))
    assert "5x4" in out
    dbg = st.utils.debug_dump(A)
    assert "nb=2" in dbg


def test_hetrf_hetrs_complex_direct():
    """Factor-level complex Hermitian check (NO hesv IR/fallback in the
    way — the round-4 tester caught a conj-transposition in T's band LU
    that hesv's fallback masked)."""
    n, nb = 36, 8
    g = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    a = (g + g.conj().T) / 2
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
    b = RNG.standard_normal((n, 2)) + 1j * RNG.standard_normal((n, 2))
    LT, perm, info = st.hetrf(A)
    assert int(info) == 0
    X = st.hetrs(LT, perm, st.from_dense(b, nb=nb))
    assert np.abs(a @ X.to_numpy() - b).max() < n * 1e-12


def test_hetrf_packing_tag_mismatch_raises():
    """ADVICE r4: an RBT/no-pivot LDL factor passed to the Aasen hetrs
    (or vice versa) must raise loudly, not compute a wrong X."""
    import pytest
    import slate_tpu as st
    from slate_tpu.core.exceptions import SlateError
    from slate_tpu.core.types import MethodHesv, Options, Uplo

    n = 32
    rng = np.random.default_rng(5)
    a = rng.standard_normal((n, n))
    a = a + a.T + n * np.eye(n)  # SPD: no-pivot LDL succeeds
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    b = rng.standard_normal((n, 2))
    B = st.from_dense(b, nb=8)

    LD, perm_id, info = st.hetrf(A, Options(method_hesv=MethodHesv.RBT))
    assert LD.packing == "ldl"
    with pytest.raises(SlateError, match="hetrs_nopiv"):
        st.hetrs(LD, perm_id, B)
    X = st.hetrs_nopiv(LD, B)  # the right solver accepts it
    assert np.abs(a @ X.to_numpy() - b).max() < 1e-6 * n

    LT, perm, info = st.hetrf(A)
    assert LT.packing == "aasen"
    with pytest.raises(SlateError, match="hetrs\\b"):
        st.hetrs_nopiv(LT, B)
    X2 = st.hetrs(LT, perm, B)
    assert np.abs(a @ X2.to_numpy() - b).max() < 1e-6 * n
