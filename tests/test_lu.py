"""LU family tests — residuals per the reference's test/test_gesv.cc:
‖PA − LU‖ and backward error of solves.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import MethodLU, Options
from slate_tpu.linalg import lu as lu_mod
from slate_tpu.matgen import generate_matrix

RNG = np.random.default_rng(23)


def _solve_residual(a, b, x):
    return (np.linalg.norm(b - a @ x, 1)
            / (np.linalg.norm(a, 1) * np.linalg.norm(x, 1)
               * a.shape[0] * np.finfo(float).eps))


@pytest.mark.parametrize("n,nb", [(48, 16), (50, 16), (33, 8)])
def test_getrf_residual(n, nb):
    a = RNG.standard_normal((n, n))
    A = st.from_dense(a, nb=nb)
    LU, perm, info = lu_mod.getrf(A)
    assert int(info) == 0
    lu = LU.to_numpy()
    l = np.tril(lu, -1) + np.eye(lu.shape[0])
    u = np.triu(lu)
    pa = np.pad(a, ((0, len(perm) - n), (0, len(perm) - n)))
    pa = lu_mod._pad_identity_diag(jnp.asarray(pa), n, n)
    pa = np.asarray(pa)[np.asarray(perm)][:n, :n]
    lfull = np.tril(np.asarray(LU.dense_canonical()), -1) + np.eye(len(perm))
    ufull = np.triu(np.asarray(LU.dense_canonical()))
    err = np.linalg.norm(pa - (lfull @ ufull)[:n, :n], 1) / (
        np.linalg.norm(a, 1) * n * np.finfo(float).eps)
    assert err < 10.0


@pytest.mark.parametrize("n,nb,nrhs", [(64, 16, 4), (37, 8, 3)])
def test_gesv(n, nb, nrhs):
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    X, info = st.gesv(st.from_dense(a, nb=nb), st.from_dense(b, nb=nb))
    assert int(info) == 0
    assert _solve_residual(a, b, X.to_numpy()) < 10.0


def test_gesv_trans():
    n, nrhs = 32, 2
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    LU, perm, info = lu_mod.getrf(st.from_dense(a, nb=8))
    X = lu_mod.getrs(LU, perm, st.from_dense(b, nb=8), trans=True)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a.T, b),
                               rtol=1e-8, atol=1e-10)


@pytest.mark.slow  # ~5 s (round-10 headroom); mesh factor+solve stays
# covered by test_grid_matches_single_device + the getrs grid tests
def test_gesv_on_grid(grid2x2):
    n, nrhs = 64, 8
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, nrhs))
    A = st.from_dense(a, nb=16, grid=grid2x2)
    B = st.from_dense(b, nb=16, grid=grid2x2)
    X, info = st.gesv(A, B)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8, atol=1e-10)


def test_gesv_jit():
    n = 24
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, 2))

    @jax.jit
    def f(A, B):
        return st.gesv(A, B)

    X, info = f(st.from_dense(a, nb=8), st.from_dense(b, nb=8))
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8, atol=1e-10)


def test_getrf_nopiv_dominant():
    n = 40
    a = np.asarray(generate_matrix("rand_dominant", n, n, jnp.float64, seed=4))
    b = RNG.standard_normal((n, 3))
    X, info = lu_mod.gesv_nopiv(st.from_dense(a, nb=16), st.from_dense(b, nb=16))
    assert int(info) == 0
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8, atol=1e-10)


def test_getrf_info_singular():
    n = 16
    a = RNG.standard_normal((n, n))
    a[:, 3] = 0.0  # exactly singular
    LU, perm, info = lu_mod.getrf(st.from_dense(a, nb=8))
    assert int(info) > 0


def test_getrf_tntpiv():
    n, nb = 64, 16
    a = RNG.standard_normal((n, n))
    b = RNG.standard_normal((n, 4))
    A = st.from_dense(a, nb=nb)
    LU, perm, info = lu_mod.getrf_tntpiv(A)
    assert int(info) == 0
    X = lu_mod.getrs(LU, perm, st.from_dense(b, nb=nb))
    assert _solve_residual(a, b, X.to_numpy()) < 50.0


@pytest.mark.slow  # ~9 s multi-method compile bill (round-10 headroom);
# each method keeps its own dedicated numerics test in tier-1
def test_gesv_method_dispatch():
    n = 32
    a = np.asarray(generate_matrix("rand_dominant", n, n, jnp.float64, seed=6))
    b = RNG.standard_normal((n, 2))
    for m in [MethodLU.PartialPiv, MethodLU.CALU, MethodLU.NoPiv, MethodLU.RBT]:
        X, info = st.gesv(st.from_dense(a, nb=8), st.from_dense(b, nb=8),
                          Options(method_lu=m))
        np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                                   rtol=1e-6, atol=1e-8, err_msg=str(m))


def test_gesv_rbt():
    n = 64
    a = RNG.standard_normal((n, n))  # general, needs pivoting normally
    b = RNG.standard_normal((n, 2))
    X, info = lu_mod.gesv_rbt(st.from_dense(a, nb=16), st.from_dense(b, nb=16))
    res = _solve_residual(a, b, X.to_numpy())
    assert res < 1e4  # RBT trades stability for speed; IR recovers most


def test_getri():
    n = 30
    a = RNG.standard_normal((n, n)) + 5 * np.eye(n)
    LU, perm, info = lu_mod.getrf(st.from_dense(a, nb=8))
    Ainv = lu_mod.getri(LU, perm)
    np.testing.assert_allclose(Ainv.to_numpy(), np.linalg.inv(a),
                               rtol=1e-7, atol=1e-9)


def test_gesv_mixed():
    n = 48
    a = RNG.standard_normal((n, n)) + 8 * np.eye(n)
    b = RNG.standard_normal((n, 2))
    A = st.from_dense(a, nb=16)
    B = st.from_dense(b, nb=16)
    X, info, iters = lu_mod.gesv_mixed(A, B, factor_dtype=jnp.float32)
    assert int(info) == 0 and iters != 0
    res = np.linalg.norm(b - a @ X.to_numpy(), np.inf) / (
        np.linalg.norm(a, np.inf) * np.linalg.norm(X.to_numpy(), np.inf))
    assert res < 1e-13


@pytest.mark.slow  # ~6 s n=192/nb=64 compile (round-22 tier-1
# budget); tier-1 sibling test_getrf_pivot_threshold_recursive_base
# keeps the CALU tournament path pinned on a tall single panel
def test_getrf_pivot_threshold_tournament():
    """pivot_threshold < 1 (the Option::PivotThreshold analog) swaps the
    panel's argmax/swap chain for the vmap-batched CALU tournament."""
    from slate_tpu.core.types import Options
    n = 192
    rng = np.random.default_rng(9)
    a = rng.standard_normal((n, n))
    A = st.from_dense(a, nb=64)
    LU, perm, info = st.getrf(A, Options(pivot_threshold=0.5))
    lu = np.asarray(LU.dense_canonical(), np.float64)
    npad = lu.shape[0]
    l = np.tril(lu, -1) + np.eye(npad)
    u = np.triu(lu)
    pa = np.asarray(A.dense_canonical(), np.float64)[np.asarray(perm)]
    assert np.abs(pa - l @ u).max() < n * 1e-13
    b = rng.standard_normal((n, 3))
    X = st.getrs(LU, perm, st.from_dense(b, nb=64))
    assert np.abs(a @ X.to_numpy() - b).max() < n * 1e-12


def test_getrf_pivot_threshold_recursive_base():
    """Tall single-panel shape routes through _getrf_rec's tournament
    base (the iterative path needs k % nb == 0 AND k//nb > 1)."""
    from slate_tpu.core.types import Options
    m, n, nb = 160, 32, 32
    rng = np.random.default_rng(11)
    a = rng.standard_normal((m, n))
    A = st.from_dense(a, nb=nb)
    LU, perm, info = st.getrf(A, Options(pivot_threshold=0.5))
    lu = np.asarray(LU.dense_canonical(), np.float64)
    mpad = lu.shape[0]
    l = np.tril(lu, -1)[:, :n] + np.eye(mpad, n)
    u = np.triu(lu)[:n, :]
    pa = np.asarray(A.dense_canonical(), np.float64)[np.asarray(perm)]
    assert np.abs(pa - l @ u).max() < m * 1e-13


def test_getrf_rec_iter_base_dispatch(monkeypatch):
    """Round-5 hybrid dispatch — now the LEGACY arm
    (Options(factor_iter_large=False); the round-6 default routes every
    nt ≤ 64 width straight to the pivot-fused iterative loop): the
    width recursion above the iter crossover, the flat iterative loop
    as its base case. With the crossover lowered to 64, n=128 must
    split once in _getrf_rec and factor each 64-wide half with
    _getrf_iter. Verifies the residual AND the solve built on it."""
    monkeypatch.setattr(lu_mod, "_GETRF_ITER_BASE", 64)
    calls = {"iter": 0, "rec": 0}
    for name in ("_getrf_iter", "_getrf_rec"):
        orig = getattr(lu_mod, name)
        key = name.split("_")[-1]

        def spy(*a, _o=orig, _k=key, **kw):
            calls[_k] += 1
            return _o(*a, **kw)

        monkeypatch.setattr(lu_mod, name, spy)

    n, nb = 128, 16  # 128 > 64 -> rec splits; halves 64 <= 64 -> iter
    a = RNG.standard_normal((n, n))
    A = st.from_dense(a, nb=nb)
    LU, perm, info = lu_mod.getrf(A, Options(factor_iter_large=False))
    assert int(info) == 0
    assert calls["rec"] >= 1 and calls["iter"] == 2
    lu = np.asarray(LU.dense_canonical())
    l = np.tril(lu, -1) + np.eye(len(perm))
    u = np.triu(lu)
    pa = np.asarray(lu_mod._pad_identity_diag(
        jnp.asarray(np.pad(a, ((0, len(perm) - n), (0, len(perm) - n)))),
        n, n))[np.asarray(perm)]
    err = np.linalg.norm(pa[:n, :n] - (l @ u)[:n, :n], 1) / (
        np.linalg.norm(a, 1) * n * np.finfo(float).eps)
    assert err < 10.0
    b = RNG.standard_normal((n, 3))
    X = lu_mod.getrs(LU, jnp.asarray(perm), st.from_dense(b, nb=nb))
    assert _solve_residual(a, b, X.to_numpy()) < 30.0


@pytest.mark.slow  # ~10 s (round-10 headroom); threshold/tournament
# pivoting stays pinned by test_getrf_pivot_threshold_tournament
def test_getrf_rec_tournament_threshold(monkeypatch):
    """pivot_threshold < 1 with the crossover lowered: the recursion's
    full-gather permutation composition (threshold < 1 path) composes
    with _getrf_iter's tournament (compaction-perm) panels — pin that
    composition stays correct."""
    monkeypatch.setattr(lu_mod, "_GETRF_ITER_BASE", 64)
    n, nb = 128, 16
    a = RNG.standard_normal((n, n))
    A = st.from_dense(a, nb=nb)
    LU, perm, info = lu_mod.getrf(
        A, Options(pivot_threshold=0.5, factor_iter_large=False))
    assert int(info) == 0
    lu = np.asarray(LU.dense_canonical())
    l = np.tril(lu, -1) + np.eye(len(perm))
    u = np.triu(lu)
    pa = np.asarray(lu_mod._pad_identity_diag(
        jnp.asarray(np.pad(a, ((0, len(perm) - n), (0, len(perm) - n)))),
        n, n))[np.asarray(perm)]
    # tournament pivot growth is weaker than partial pivoting's; keep a
    # looser bound (same spirit as test_getrf_pivot_threshold_tournament)
    err = np.linalg.norm(pa[:n, :n] - (l @ u)[:n, :n], 1) / (
        np.linalg.norm(a, 1) * n * np.finfo(float).eps)
    assert err < 100.0
