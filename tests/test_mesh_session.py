"""Pod-scale mesh-native Session (round 11, ISSUE 8).

The serving runtime on the forced 8-device CPU mesh: a dense operator
registered with ``Session(mesh=...)`` keeps its factor RESIDENT AS A
SHARDED ARRAY (asserted via the sharding spec), every served solve runs
as one analyzed sharded AOT program whose collective census is nonzero
and credits measured ICI bytes per execution, the LRU budget charges
PER-CHIP bytes (max-per-shard resident + per-device program transient),
and the Batcher dispatches sharded handles like any other. The
numerical contract vs the single-device arm is equality at dtype
tolerance — mesh collectives reorder reductions, so bit-identity is
NOT claimed here (the drivers' own bit-identity assertions are
fastpath-vs-legacy on a FIXED placement, tests/test_fastpaths.py).

Compile budget: the module-scoped sessions amortize the mesh AOT
compiles across tests; the c64 sweep is ``-m slow`` (its cheap f32/f64
siblings stay tier-1 — ISSUE 8 tier-1 satellite).
"""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.core.grid import ProcessGrid, as_grid
from slate_tpu.linalg.band_packed import pb_pack
from slate_tpu.runtime import Batcher, Session

RNG = np.random.default_rng(23)
N, NB = 64, 16


def _spd(n=N, dtype=np.float64):
    a = RNG.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * RNG.standard_normal((n, n)).astype(dtype)
        return (a @ a.conj().T + n * np.eye(n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


SPD = _spd()
DIAG_DOM = RNG.standard_normal((N, N)) + N * np.eye(N)


def _chol_operand(dtype=np.float64, grid=None):
    spd = SPD.astype(dtype) if dtype != np.float64 else SPD
    return st.hermitian(np.tril(spd), nb=NB, uplo=st.Uplo.Lower,
                        grid=grid), spd


@pytest.fixture(scope="module")
def mesh_sess(grid2x4):
    """One warmed mesh session with a chol and an lu operator — the
    expensive sharded AOT compiles are shared by every test below."""
    sess = Session(mesh=grid2x4)
    A, _ = _chol_operand()
    hc = sess.register(A, op="chol")
    hl = sess.register(st.from_dense(DIAG_DOM, nb=NB), op="lu")
    sess.warmup(hc)
    return sess, hc, hl


@pytest.fixture(scope="module")
def single_sess():
    sess = Session()
    A, _ = _chol_operand()
    hc = sess.register(A, op="chol")
    hl = sess.register(st.from_dense(DIAG_DOM, nb=NB), op="lu")
    return sess, hc, hl


# -- resident sharding (the tentpole claim) --------------------------------


def test_factor_stays_resident_sharded(mesh_sess, grid2x4):
    sess, hc, _ = mesh_sess
    res = sess.factor(hc)
    L = res.payload[0]
    sharding = L.data.sharding
    # the factor is mesh-placed storage, not a gathered copy: a real
    # NamedSharding over BOTH grid axes, one shard per device
    assert not sharding.is_fully_replicated
    spec = tuple(sharding.spec)
    assert "p" in spec and "q" in spec
    assert len(sharding.device_set) == grid2x4.size == 8
    # the registered operand itself is mesh-resident too
    assert not sess._ops[hc].A.data.sharding.is_fully_replicated


def test_per_chip_charge_is_max_per_shard(mesh_sess, grid2x4):
    sess, hc, _ = mesh_sess
    res = sess.factor(hc)
    # evenly sharded payload: the per-chip budget charge is exactly
    # the aggregate over the 8 devices' shards
    assert res.nbytes * grid2x4.size == res.nbytes_total
    assert res.nbytes_total == N * N * 8  # f64 padded dense factor
    # gauges publish both views
    assert sess.metrics.get_gauge("resident_bytes") < \
        sess.metrics.get_gauge("resident_bytes_total")


# -- one AOT program per shape, census per served solve --------------------


def test_warmup_aot_compiles_sharded_programs(mesh_sess):
    sess, _, _ = mesh_sess
    assert sess.metrics.get("factor_aot_compiles") >= 1
    whats = {(r["op"], r["what"]) for r in sess.cost_log}
    assert ("chol", "factor") in whats and ("chol", "solve") in whats


def test_served_solve_census_nonzero_and_credited_per_solve(mesh_sess):
    sess, hc, _ = mesh_sess
    solve_rows = [r for r in sess.cost_log
                  if r["op"] == "chol" and r["what"] == "solve"]
    assert solve_rows and all(r["collective_bytes"] > 0
                              for r in solve_rows)
    # scheduled-HLO census: real collective instructions in the solve
    kinds = set()
    for r in solve_rows:
        kinds |= set(r["collectives"])
    assert kinds & {"all-reduce", "all-gather", "collective-permute",
                    "all-to-all"}
    # same census through the ProgramCosts summary the artifact uses
    assert any(sum(pc.collective_counts().values()) > 0
               for pc in sess._program_costs.values())
    b = RNG.standard_normal(N)
    compiles0 = sess.metrics.get("aot_compiles")
    c0 = sess.metrics.get("solve_collective_bytes_total")
    x1 = sess.solve(hc, b)
    c1 = sess.metrics.get("solve_collective_bytes_total")
    x2 = sess.solve(hc, b)
    c2 = sess.metrics.get("solve_collective_bytes_total")
    # ICI bytes move once PER EXECUTED SOLVE (same program, so equal
    # increments), with no new program compiled (one per shape)
    assert c1 > c0 and (c2 - c1) == (c1 - c0) > 0
    assert sess.metrics.get("aot_compiles") == compiles0
    assert np.array_equal(x1, x2)


def test_unwarmed_mesh_solve_compiles_aot_on_request_path(grid2x4):
    # no warmup: the first solve must still go through the analyzed
    # AOT seam (never the plain-jit fallback) so the census is
    # credited from request one
    sess = Session(mesh=grid2x4)
    A, spd = _chol_operand()
    h = sess.register(A, op="chol")
    b = RNG.standard_normal(N)
    x = sess.solve(h, b)
    assert np.abs(spd @ x - b).max() < 1e-8
    assert sess.metrics.get("factor_aot_compiles") == 1
    assert sess.metrics.get("aot_compiles") == 1
    assert sess.metrics.get("collective_bytes_total") > 0


# -- sharded solve ≡ single-device solve -----------------------------------


@pytest.mark.slow
def test_sharded_solve_matches_single_device_f64(mesh_sess, single_sess):
    """Slow (round-18 tier-1 budget): the (N, 2)-width f64 sharded
    solve programs for BOTH op kinds are their own GSPMD compiles;
    tier-1 sibling test_sharded_solve_matches_single_device_f32 pins
    the mesh ≡ single-device class (and the c64 arm was already
    slow-marked in round 11)."""
    msess, mhc, mhl = mesh_sess
    ssess, shc, shl = single_sess
    b = RNG.standard_normal((N, 2))
    for mh, sh, a in ((mhc, shc, SPD), (mhl, shl, DIAG_DOM)):
        xm = msess.solve(mh, b)
        xs = ssess.solve(sh, b)
        assert np.abs(a @ xm - b).max() < 1e-8
        np.testing.assert_allclose(xm, xs, rtol=1e-12, atol=1e-12)


def test_sharded_solve_matches_single_device_f32(grid2x4):
    Am, spd = _chol_operand(np.float32)
    A1, _ = _chol_operand(np.float32)
    msess = Session(mesh=grid2x4)
    ssess = Session()
    mh = msess.register(Am, op="chol")
    sh = ssess.register(A1, op="chol")
    b = RNG.standard_normal(N).astype(np.float32)
    xm = msess.solve(mh, b)
    xs = ssess.solve(sh, b)
    assert np.abs(spd @ xm - b).max() / N < 1e-3
    np.testing.assert_allclose(xm, xs, rtol=2e-4, atol=2e-4)
    assert not msess.factor(mh).payload[0].data \
        .sharding.is_fully_replicated


@pytest.mark.slow  # c64 mesh AOT compile is the expensive arm; the
# f32/f64 siblings above keep the cross-dtype claim pinned in tier-1
def test_sharded_solve_matches_single_device_c64(grid2x4):
    spd = _spd(dtype=np.complex64)
    msess = Session(mesh=grid2x4)
    ssess = Session()
    mh = msess.register(st.hermitian(np.tril(spd), nb=NB,
                                     uplo=st.Uplo.Lower), op="chol")
    sh = ssess.register(st.hermitian(np.tril(spd), nb=NB,
                                     uplo=st.Uplo.Lower), op="chol")
    b = (RNG.standard_normal(N) + 1j * RNG.standard_normal(N)
         ).astype(np.complex64)
    xm = msess.solve(mh, b)
    xs = ssess.solve(sh, b)
    np.testing.assert_allclose(xm, xs, rtol=2e-3, atol=2e-3)


# -- Batcher over a sharded handle -----------------------------------------


def test_batcher_dispatches_sharded_handle(mesh_sess):
    sess, hc, _ = mesh_sess
    batches0 = sess.metrics.get("batches_total")
    bt = Batcher(sess, max_batch=4, max_wait=60.0, pad_widths=True)
    bs = [RNG.standard_normal(N) for _ in range(3)]
    futs = [bt.submit(hc, b) for b in bs]
    bt.flush()
    xs = [f.result(timeout=60) for f in futs]
    assert sess.metrics.get("batches_total") == batches0 + 1
    for x, b in zip(xs, bs):
        np.testing.assert_allclose(x, sess.solve(hc, b),
                                   rtol=1e-12, atol=1e-12)


def test_batcher_pad_widths_single_device(single_sess):
    # pow2 width quantization keeps per-request results intact (the
    # solve verbs are column-independent); cheap single-device pin
    sess, hc, _ = single_sess
    bt = Batcher(sess, max_batch=8, max_wait=60.0, pad_widths=True)
    bs = [RNG.standard_normal(N) for _ in range(3)]  # pads 3 -> 4
    solves0 = sess.metrics.get("solves_total")
    futs = [bt.submit(hc, b) for b in bs]
    bt.flush()
    # the padded zero column is executed work, NOT a served request:
    # solves_total counts client columns only
    assert sess.metrics.get("solves_total") == solves0 + 3
    for f, b in zip(futs, bs):
        np.testing.assert_allclose(f.result(timeout=60),
                                   sess.solve(hc, b),
                                   rtol=1e-12, atol=1e-12)


# -- per-chip budget: eviction + OOM telemetry over sharded residents ------


def test_per_chip_budget_eviction_sharded(grid2x4):
    sess = Session(mesh=grid2x4)
    mats = [_spd() for _ in range(3)]
    hs = [sess.register(st.hermitian(np.tril(m), nb=NB,
                                     uplo=st.Uplo.Lower), op="chol")
          for m in mats]
    res0 = sess.factor(hs[0])
    per = res0.nbytes
    assert per * grid2x4.size == res0.nbytes_total
    sess.factor(hs[1])  # LRU order now [hs[0], hs[1]]
    peak_two = sess.metrics.get_gauge("peak_hbm_bytes")
    # budget below holding THREE sharded residents (but above two):
    # inserting the third must evict the LRU one, per-chip accounted
    sess.hbm_budget = int(peak_two + per - 1)
    sess.factor(hs[2])
    assert sess.metrics.get("evictions") == 1
    assert sess.metrics.get("evicted_bytes") == per
    assert sess.cached_handles() == [hs[1], hs[2]]
    assert sess.hbm_headroom() is not None and sess.hbm_headroom() >= 0
    # a budget below even ONE resident + program transient: the factor
    # is kept (serving must continue) and the OOM telemetry fires
    sess.clear_cache()
    sess.hbm_budget = per - 1
    sess.factor(hs[0])
    assert sess.metrics.get("budget_overflows") >= 1
    assert sess.metrics.get("oom_risk_warnings") >= 1
    assert sess.hbm_headroom() < 0


# -- registration surface ---------------------------------------------------


def test_mesh_register_rejects_non_dense_ops(grid2x4):
    sess = Session(mesh=grid2x4)
    ab = np.eye(8) * 4 + np.diag(np.ones(7), -1) + np.diag(np.ones(7), 1)
    with pytest.raises(SlateError, match="mesh serving"):
        sess.register(pb_pack(ab, kd=1), op="band_chol")
    with pytest.raises(SlateError, match="mesh serving"):
        sess.register(np.asarray(SPD[:8, :8]), op="lu_small")


def test_register_infers_mesh_from_sharded_operand(grid2x4):
    # a pre-sharded operand (no mesh argument anywhere) is served
    # mesh-native: the probe path users already had keeps working and
    # now gets per-chip accounting + the AOT census discipline
    sess = Session()
    A, spd = _chol_operand(grid=grid2x4)
    h = sess.register(A, op="chol")
    assert sess._ops[h].grid is grid2x4
    x = sess.solve(h, RNG.standard_normal(N))
    assert sess.metrics.get("collective_bytes_total") > 0
    res = sess.factor(h)
    assert res.nbytes * grid2x4.size == res.nbytes_total


def test_register_explicit_single_device_override(grid2x4):
    # the per-operator mesh overrides the session mesh in BOTH
    # directions: an explicit 1x1 grid means single-device placement
    # even on a mesh session (it used to be silently re-meshed)
    sess = Session(mesh=grid2x4)
    A, spd = _chol_operand()
    h = sess.register(A, op="chol", mesh=ProcessGrid.create(1, 1))
    assert sess._ops[h].grid is None
    b = RNG.standard_normal(N)
    x = sess.solve(h, b)
    assert np.abs(spd @ x - b).max() < 1e-8
    assert sess.metrics.get("collective_bytes_total") == 0


def test_as_grid_coercions(grid2x4):
    from jax.sharding import Mesh
    assert as_grid(None) is None
    assert as_grid(grid2x4) is grid2x4
    g = as_grid(grid2x4.mesh)
    assert isinstance(g, ProcessGrid) and g.p == 2 and g.q == 4
    assert as_grid(ProcessGrid.create(1, 1)) is None
    with pytest.raises(TypeError):
        as_grid("2x4")


# -- satellite pins ---------------------------------------------------------


def test_bf16_chol_tile_base_runs_and_rounds(grid1x1):
    # round-11 fix: the lax.linalg.cholesky tile base has no bf16
    # LAPACK kernel — the tile is now factored in f32 and rounded
    # back, which is what posv_mixed(factor_dtype=bf16) needs to run
    import jax.numpy as jnp
    spd32 = _spd(n=32, dtype=np.float32)
    A_lo = st.hermitian(jnp.tril(jnp.asarray(spd32, jnp.bfloat16)),
                        nb=16, uplo=st.Uplo.Lower)
    L, info = st.chol_factor(A_lo)
    assert int(info) == 0
    l = np.asarray(L.to_numpy(), np.float32)
    ref = np.linalg.cholesky(spd32.astype(np.float64))
    assert np.isfinite(l).all()
    # bf16 has ~3 decimal digits; the factor must round-trip close
    assert np.abs(np.tril(l) - ref).max() / np.abs(ref).max() < 0.05


def test_mixed_verbs_join_intensity_in_gflops_report():
    # ISSUE 8 satellite: once the bytes ledger knows the mixed verbs
    # (bench.py --phases credits the composed component-program bytes
    # under the verb name), gflops_report renders the intensity column
    # beside the flop-ledger row the instrumented wrapper credits
    from slate_tpu.obs.costs import BYTES
    from slate_tpu.obs.flops import LEDGER
    A, _ = _chol_operand(np.float32)
    B = st.from_dense(np.ones((N, 1), np.float32), nb=NB)
    x, info, iters = st.posv_mixed(A, B, factor_dtype=np.float16)
    assert int(info) == 0
    BYTES.record("posv_mixed", 12345.0)
    row = LEDGER.gflops_report()["per_op"]["posv_mixed"]
    assert row["intensity"] is not None and row["intensity"] > 0
