"""Pallas tile-triangle herk kernel (ops/pallas_ops.herk_lower_update).

Reference analog: the batched lower-triangle herk tiles of
src/internal/internal_herk.cc:351 + device_regions_build. The kernel is
exercised here in Pallas interpreter mode (runs on the CPU mesh), the
same code path Mosaic compiles on a real TPU; the jnp fallback and the
blocked.herk_lower_rec routing are covered alongside.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ops import blocked, pallas_ops

RNG = np.random.default_rng(31)


def _ref_lower(c, a):
    full = c - a @ a.T
    # lower tile triangle updated, strictly-upper tiles pass through
    return full


@pytest.mark.parametrize("n,k,block", [(256, 128, 128), (512, 256, 128),
                                       (384, 128, 128)])
def test_herk_lower_update_interpret(n, k, block):
    c = RNG.standard_normal((n, n)).astype(np.float32)
    a = RNG.standard_normal((n, k)).astype(np.float32)
    out = np.asarray(pallas_ops.herk_lower_update(
        jnp.asarray(c), jnp.asarray(a), block, interpret=True, force=True))
    ref = _ref_lower(c, a)
    nt = n // block
    for i in range(nt):
        for j in range(nt):
            blk = np.s_[i * block:(i + 1) * block, j * block:(j + 1) * block]
            if i >= j:  # lower tile pair: updated
                np.testing.assert_allclose(out[blk], ref[blk], atol=1e-4)
            else:       # strictly upper tile: aliased through unchanged
                np.testing.assert_array_equal(out[blk], c[blk])


def test_herk_lower_update_fallback_matches():
    # ineligible shapes (k not divisible) take the jnp fallback
    n, k = 256, 100
    c = RNG.standard_normal((n, n)).astype(np.float32)
    a = RNG.standard_normal((n, k)).astype(np.float32)
    out = np.asarray(pallas_ops.herk_lower_update(jnp.asarray(c),
                                                  jnp.asarray(a)))
    np.testing.assert_allclose(out, c - a @ a.T, atol=1e-4)


def test_herk_eligibility_gates(monkeypatch):
    f32 = jnp.float32
    # opt-in route (round 3: measured no win, default off) — without the
    # env enable the route must be off on ANY backend
    monkeypatch.delenv("SLATE_TPU_PALLAS_HERK", raising=False)
    assert not pallas_ops.herk_eligible(512, 256, f32, 128)
    monkeypatch.setenv("SLATE_TPU_PALLAS_HERK", "1")
    # shape gates are backend-independent: indivisible n/k never eligible
    assert not pallas_ops.herk_eligible(500, 256, f32, 128)
    assert not pallas_ops.herk_eligible(512, 100, f32, 128)


def test_herk_lower_rec_unchanged_by_routing():
    # the blocked recursion (the route's fallback) computes the same
    # lower triangle the Pallas kernel produces
    n, k = 320, 128
    c = RNG.standard_normal((n, n)).astype(np.float32)
    a = RNG.standard_normal((n, k)).astype(np.float32)
    rec = np.asarray(blocked.herk_lower_rec(jnp.asarray(c), jnp.asarray(a),
                                            base=128))
    ker = np.asarray(pallas_ops.herk_lower_update(
        jnp.asarray(c), jnp.asarray(a), 64, interpret=True, force=True))
    np.testing.assert_allclose(np.tril(rec), np.tril(ker), atol=1e-4)
