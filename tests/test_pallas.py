"""Pallas tile-triangle herk kernel (ops/pallas_ops.herk_lower_update).

Reference analog: the batched lower-triangle herk tiles of
src/internal/internal_herk.cc:351 + device_regions_build. The kernel is
exercised here in Pallas interpreter mode (runs on the CPU mesh), the
same code path Mosaic compiles on a real TPU; the jnp fallback and the
blocked.herk_lower_rec routing are covered alongside.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.ops import blocked, pallas_ops

RNG = np.random.default_rng(31)


def _ref_lower(c, a):
    full = c - a @ a.T
    # lower tile triangle updated, strictly-upper tiles pass through
    return full


@pytest.mark.parametrize("n,k,block", [(256, 128, 128), (512, 256, 128),
                                       (384, 128, 128)])
def test_herk_lower_update_interpret(n, k, block):
    c = RNG.standard_normal((n, n)).astype(np.float32)
    a = RNG.standard_normal((n, k)).astype(np.float32)
    out = np.asarray(pallas_ops.herk_lower_update(
        jnp.asarray(c), jnp.asarray(a), block, interpret=True, force=True))
    ref = _ref_lower(c, a)
    nt = n // block
    for i in range(nt):
        for j in range(nt):
            blk = np.s_[i * block:(i + 1) * block, j * block:(j + 1) * block]
            if i >= j:  # lower tile pair: updated
                # rtol term: interpret-mode matmul reduction order
                # differs across jaxlib CPU builds; accumulated |C| at
                # k=512 puts a few f32 ulps past a bare 1e-4 atol
                np.testing.assert_allclose(out[blk], ref[blk], atol=1e-4,
                                           rtol=2e-6)
            else:       # strictly upper tile: aliased through unchanged
                np.testing.assert_array_equal(out[blk], c[blk])


def test_herk_lower_update_fallback_matches():
    # ineligible shapes (k not divisible) take the jnp fallback
    n, k = 256, 100
    c = RNG.standard_normal((n, n)).astype(np.float32)
    a = RNG.standard_normal((n, k)).astype(np.float32)
    out = np.asarray(pallas_ops.herk_lower_update(jnp.asarray(c),
                                                  jnp.asarray(a)))
    np.testing.assert_allclose(out, c - a @ a.T, atol=1e-4)


def test_herk_eligibility_gates(monkeypatch):
    f32 = jnp.float32
    # opt-in route (round 3: measured no win, default off) — without the
    # env enable the route must be off on ANY backend
    monkeypatch.delenv("SLATE_TPU_PALLAS_HERK", raising=False)
    assert not pallas_ops.herk_eligible(512, 256, f32, 128)
    monkeypatch.setenv("SLATE_TPU_PALLAS_HERK", "1")
    # shape gates are backend-independent: indivisible n/k never eligible
    assert not pallas_ops.herk_eligible(500, 256, f32, 128)
    assert not pallas_ops.herk_eligible(512, 100, f32, 128)


def test_herk_lower_rec_unchanged_by_routing():
    # the blocked recursion (the route's fallback) computes the same
    # lower triangle the Pallas kernel produces
    n, k = 320, 128
    c = RNG.standard_normal((n, n)).astype(np.float32)
    a = RNG.standard_normal((n, k)).astype(np.float32)
    rec = np.asarray(blocked.herk_lower_rec(jnp.asarray(c), jnp.asarray(a),
                                            base=128))
    ker = np.asarray(pallas_ops.herk_lower_update(
        jnp.asarray(c), jnp.asarray(a), 64, interpret=True, force=True))
    np.testing.assert_allclose(np.tril(rec), np.tril(ker), atol=1e-4)


def _chol_tile_interpret_case(b, junk_upper):
    x = RNG.standard_normal((b, b)).astype(np.float32)
    a = (x @ x.T + b * np.eye(b)).astype(np.float32)
    if junk_upper:
        a = np.tril(a) + 1e6 * np.triu(
            RNG.standard_normal((b, b)).astype(np.float32), 1)
    lk = np.asarray(pallas_ops.chol_tile(jnp.asarray(a), interpret=True))
    lref = np.linalg.cholesky(
        np.tril(a).astype(np.float64)
        + np.tril(a, -1).astype(np.float64).T)
    assert np.abs(lk - lref).max() / np.abs(lref).max() < 1e-5
    assert np.allclose(np.triu(lk, 1), 0.0)


@pytest.mark.slow  # ~14 s interpret-mode numerics (round-10 headroom);
# the dispatch-wiring spy tests keep the Pallas seam in tier-1
def test_chol_tile_kernel_interpret():
    """In-VMEM blocked Cholesky kernel (round 5): interpret-mode
    correctness vs LAPACK-precision numpy, including the strict-upper
    zeroing contract. b=128 exercises a single 128-panel with all four
    32-micro steps (the b=256 cross-panel case runs under -m slow —
    interpret-mode dispatch makes it ~30 s of the tier-1 budget)."""
    _chol_tile_interpret_case(128, junk_upper=False)


@pytest.mark.slow
def test_chol_tile_kernel_interpret_cross_panel():
    """b=256 adds the cross-panel left/top trailing update (the
    `if jb:` branch), with junk in the strict upper triangle to pin
    the lower-only read contract."""
    _chol_tile_interpret_case(256, junk_upper=True)


@pytest.mark.slow  # ~19 s interpret-mode dispatch (round-22 headroom);
# tier-1 sibling: test_chol_tile_nan_poisons_nonspd_single_micro
def test_chol_tile_nan_poisons_nonspd():
    """Non-SPD input must NaN-poison (the _tile_chol info contract) —
    b=128 breaks in a LATER micro step, so the poison must propagate
    through the trailing updates."""
    b = 128
    x = RNG.standard_normal((b, b)).astype(np.float32)
    a = (x @ x.T + b * np.eye(b)).astype(np.float32)
    a[40, 40] = -a[40, 40] - abs(a).sum()
    lk = np.asarray(pallas_ops.chol_tile(jnp.asarray(a), interpret=True))
    assert np.isnan(lk[40:, 40:]).any()


def test_chol_tile_nan_poisons_nonspd_single_micro():
    """Tier-1 sibling of the b=128 case above: the same poison contract
    at its source — the 32-micro factorization (_chol_cols_unrolled),
    where rsqrt of the negative pivot first goes NaN. (chol_tile itself
    requires b >= _CHOL_IB=128, which is interpret-mode-slow; the kernel
    builds its panels out of exactly this micro step.)"""
    m = 32
    x = RNG.standard_normal((m, m)).astype(np.float32)
    a = (x @ x.T + m * np.eye(m)).astype(np.float32)
    a[10, 10] = -a[10, 10] - abs(a).sum()
    lk = np.asarray(pallas_ops._chol_cols_unrolled(jnp.asarray(a), m))
    assert np.isnan(lk[10:, 10:]).any()
    # healthy columns before the bad pivot stay finite
    assert np.isfinite(lk[:, :10]).all()


def test_chol_eligibility_gates(monkeypatch):
    f32 = jnp.float32.dtype
    # default-on route, env kill switch
    monkeypatch.setenv("SLATE_TPU_PALLAS_CHOL", "0")
    assert not pallas_ops.chol_eligible(512, f32)
    monkeypatch.delenv("SLATE_TPU_PALLAS_CHOL")
    # shape/dtype gates are backend-independent
    assert not pallas_ops.chol_eligible(100, f32)
    assert not pallas_ops.chol_eligible(2048, f32)
    assert not pallas_ops.chol_eligible(512, jnp.float64)
    assert not pallas_ops.chol_eligible(512, jnp.complex64)


def test_lu_panel_kernel_interpret():
    """In-VMEM pivoted LU panel base (round 5): interpret-mode parity
    with the fori base — identical LU content, identical gather perm,
    identical info, including the zero-column keep-diagonal case."""
    for (h, w) in ((128, 32), (256, 16)):
        a = RNG.standard_normal((h, w)).astype(np.float32)
        lu_k, p_k, i_k = pallas_ops.lu_panel_base(
            jnp.asarray(a), interpret=True)
        lu_r, p_r, i_r = blocked._panel_getrf_base(jnp.asarray(a))
        assert int(i_k) == int(i_r) == 0
        np.testing.assert_array_equal(np.asarray(p_k), np.asarray(p_r))
        np.testing.assert_allclose(np.asarray(lu_k), np.asarray(lu_r),
                                   atol=1e-5)
        lm = np.tril(np.asarray(lu_k), -1)[:, :w]
        lm[np.arange(w), np.arange(w)] = 1.0
        u = np.triu(np.asarray(lu_k))[:w, :]
        np.testing.assert_allclose(a[np.asarray(p_k)], lm @ u, atol=1e-4)
    a = RNG.standard_normal((64, 8)).astype(np.float32)
    a[:, 3] = 0.0
    _, _, i_k = pallas_ops.lu_panel_base(jnp.asarray(a), interpret=True)
    assert int(i_k) == 4


def test_lu_panel_eligibility_gates(monkeypatch):
    f32 = jnp.float32.dtype
    monkeypatch.setenv("SLATE_TPU_PALLAS_LU", "0")
    assert not pallas_ops.lu_panel_eligible(1024, 32, f32)
    monkeypatch.delenv("SLATE_TPU_PALLAS_LU")
    assert not pallas_ops.lu_panel_eligible(1024, 4, f32)       # w too small
    assert not pallas_ops.lu_panel_eligible(16, 32, f32)        # h < w
    assert not pallas_ops.lu_panel_eligible(10 ** 6, 32, f32)   # VMEM
    assert not pallas_ops.lu_panel_eligible(1024, 32, jnp.float64)


def test_qr_panel_kernel_interpret():
    """In-VMEM Householder QR panel base (round 5): interpret-mode
    parity with the fori base — identical packed V\\R and taus,
    including the degenerate zero-tail column (tau = 0, H = I)."""
    for (h, w) in ((128, 32), (256, 16)):
        a = RNG.standard_normal((h, w)).astype(np.float32)
        vr_k, tau_k = pallas_ops.qr_panel_base(jnp.asarray(a),
                                               interpret=True)
        vr_r, tau_r = blocked._panel_geqrf_base(jnp.asarray(a))
        np.testing.assert_allclose(np.asarray(tau_k), np.asarray(tau_r),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(vr_k), np.asarray(vr_r),
                                   atol=1e-4)
        # reconstruction: Q·R == A
        v = np.tril(np.asarray(vr_k), -1)[:, :w]
        v[np.arange(w), np.arange(w)] = 1.0
        r = np.triu(np.asarray(vr_k))[:w, :]
        q = np.eye(h, dtype=np.float64)
        for j in range(w - 1, -1, -1):
            vj = v[:, j].astype(np.float64)
            q = q - float(tau_k[j]) * np.outer(vj, vj @ q)
        np.testing.assert_allclose(q[:, :w] @ r.astype(np.float64), a,
                                   atol=5e-4)
    a = RNG.standard_normal((64, 8)).astype(np.float32)
    a[:, 3] = 0.0  # whole column zero -> tau[3] == 0 after updates
    vr_k, tau_k = pallas_ops.qr_panel_base(jnp.asarray(a), interpret=True)
    vr_r, tau_r = blocked._panel_geqrf_base(jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(tau_k), np.asarray(tau_r),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(vr_k), np.asarray(vr_r),
                               atol=1e-4)


def test_qr_panel_eligibility_gates(monkeypatch):
    f32 = jnp.float32.dtype
    monkeypatch.setenv("SLATE_TPU_PALLAS_QR", "0")
    assert not pallas_ops.qr_panel_eligible(1024, 32, f32)
    monkeypatch.delenv("SLATE_TPU_PALLAS_QR")
    assert not pallas_ops.qr_panel_eligible(1024, 4, f32)       # w too small
    assert not pallas_ops.qr_panel_eligible(16, 32, f32)        # h < w
    assert not pallas_ops.qr_panel_eligible(10 ** 6, 32, f32)   # VMEM
    assert not pallas_ops.qr_panel_eligible(1024, 32, jnp.float64)


# -- round 7: deeper-unrolled WIDE panel bases ------------------------------

@pytest.mark.slow  # ~6 s interpret-mode numerics (round-10 headroom)
def test_qr_panel_wide_kernel_interpret():
    """Micro-blocked wide QR panel kernel (round 7): interpret-mode
    correctness at 64/128-wide bases — f32-level agreement with the
    fori base (the compact-WY deferral reassociates, so tolerance, not
    bit parity) and a float64 Q·R reconstruction, plus the degenerate
    zero-column contract (tau = 0)."""
    for (h, w) in ((128, 64), (192, 64), (256, 128)):
        a = RNG.standard_normal((h, w)).astype(np.float32)
        vr_k, tau_k = pallas_ops.qr_panel_base_wide(jnp.asarray(a),
                                                    interpret=True)
        vr_r, tau_r = blocked._panel_geqrf_base(jnp.asarray(a))
        vr_k, tau_k = np.asarray(vr_k), np.asarray(tau_k)
        np.testing.assert_allclose(tau_k, np.asarray(tau_r), atol=2e-6)
        np.testing.assert_allclose(vr_k, np.asarray(vr_r), atol=2e-4)
        v = np.tril(vr_k, -1)[:, :w]
        v[np.arange(w), np.arange(w)] = 1.0
        r = np.triu(vr_k)[:w, :]
        q = np.eye(h, dtype=np.float64)
        for j in range(w - 1, -1, -1):
            vj = v[:, j].astype(np.float64)
            q = q - float(tau_k[j]) * np.outer(vj, vj @ q)
        np.testing.assert_allclose(q[:, :w] @ r.astype(np.float64), a,
                                   atol=1e-3)
    a = RNG.standard_normal((128, 64)).astype(np.float32)
    a[:, 37] = 0.0
    _, tau_k = pallas_ops.qr_panel_base_wide(jnp.asarray(a),
                                             interpret=True)
    assert float(tau_k[37]) == 0.0


def test_lu_panel_kernel_wide_interpret():
    """The LU base kernel at WIDE widths (round-7 dispatch widening):
    its column loop is arithmetic-identical to the fori base at any
    width, so a 64/128-wide invocation must match bit-for-bit."""
    for (h, w) in ((128, 64), (256, 128)):
        a = RNG.standard_normal((h, w)).astype(np.float32)
        lu_k, p_k, i_k = pallas_ops.lu_panel_base(
            jnp.asarray(a), interpret=True)
        lu_r, p_r, i_r = blocked._panel_getrf_base(jnp.asarray(a))
        assert int(i_k) == int(i_r)
        np.testing.assert_array_equal(np.asarray(p_k), np.asarray(p_r))
        np.testing.assert_array_equal(np.asarray(lu_k), np.asarray(lu_r))


def test_wide_panel_dispatch_policy(monkeypatch):
    """With a TPU backend reported, panel_getrf/panel_geqrf route a
    wide (64/128-wide, short) base to ONE kernel invocation instead of
    recursing into 32-wide bases; tall panels stay on the recursion
    (cells gate)."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    f32 = jnp.float32.dtype
    assert pallas_ops.qr_panel_wide_eligible(2048, 128, f32)
    assert pallas_ops.qr_panel_wide_eligible(4096, 64, f32)
    assert not pallas_ops.qr_panel_wide_eligible(4096, 128, f32)  # cells
    assert not pallas_ops.qr_panel_wide_eligible(2048, 32, f32)   # base kern
    assert not pallas_ops.qr_panel_wide_eligible(2048, 80, f32)   # MB align
    assert pallas_ops.lu_panel_eligible(2048, 128, f32)

    calls = {"qr_wide": 0, "lu_wide": 0}

    def fake_qr_wide(a, **kw):
        calls["qr_wide"] += 1
        return blocked._panel_geqrf_base(a)

    def fake_lu_wide(a, **kw):
        calls["lu_wide"] += 1
        return blocked._panel_getrf_base(a)

    monkeypatch.setattr(pallas_ops, "qr_panel_base_wide", fake_qr_wide)
    monkeypatch.setattr(pallas_ops, "lu_panel_base", fake_lu_wide)
    a = jnp.asarray(RNG.standard_normal((256, 128)).astype(np.float32))
    blocked.panel_geqrf(a)
    assert calls["qr_wide"] == 1, "wide QR base did not own the panel"
    blocked.panel_getrf(a)
    assert calls["lu_wide"] == 1, "wide LU base did not own the panel"
