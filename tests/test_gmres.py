"""GMRES-IR mixed-precision solver tests.

Reference semantics: src/gesv_mixed_gmres.cc, src/posv_mixed_gmres.cc.
The key acceptance test (VERDICT round 1, item 7): an ill-conditioned
system that plain iterative refinement CANNOT solve from an f32 factor
must converge under FGMRES-IR to working-precision accuracy.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st

RNG = np.random.default_rng(42)


def _cond_matrix(n, cond, rng=RNG, spd=False, complex_=False):
    """Matrix with prescribed 2-norm condition number via SVD synthesis."""
    if complex_:
        u, _ = np.linalg.qr(rng.standard_normal((n, n))
                            + 1j * rng.standard_normal((n, n)))
        v, _ = np.linalg.qr(rng.standard_normal((n, n))
                            + 1j * rng.standard_normal((n, n)))
    else:
        u, _ = np.linalg.qr(rng.standard_normal((n, n)))
        v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -np.log10(cond), n)
    if spd:
        a = (u * s) @ np.conj(u).T
        return 0.5 * (a + np.conj(a).T)
    return (u * s) @ np.conj(v).T


def test_gesv_mixed_gmres_well_conditioned():
    n, nb = 64, 16
    a = _cond_matrix(n, 1e3)
    x_true = RNG.standard_normal((n, 1))
    b = a @ x_true
    X, info, iters = st.gesv_mixed_gmres(
        st.from_dense(a, nb=nb), st.from_dense(b, nb=nb))
    assert int(info) == 0 and iters >= 0
    np.testing.assert_allclose(X.to_numpy(), x_true, rtol=1e-9, atol=1e-9)


def test_gesv_mixed_gmres_beats_plain_ir():
    """cond ≈ 1e9: plain IR from an f32 factor diverges (the correction
    equation amplifies the error); FGMRES-IR must converge to the
    attainable forward accuracy ~cond·ε (the reason the routine exists —
    src/gesv_mixed_gmres.cc:29-33). nb = 32 so the reference's
    restart = min(30, itermax, nb−1) rule gives the full restart of 30."""
    n, nb = 96, 32
    rng = np.random.default_rng(0)  # premise verified for this seed
    a = _cond_matrix(n, 1e9, rng=rng)
    x_true = rng.standard_normal((n, 1))
    b = a @ x_true
    A = st.from_dense(a, nb=nb)
    B = st.from_dense(b, nb=nb)
    opts = st.Options(use_fallback_solver=False, max_iterations=90)

    X1, _, it_plain = st.gesv_mixed(A, B, opts, factor_dtype=jnp.float32)
    err_plain = np.linalg.norm(X1.to_numpy() - x_true) / np.linalg.norm(
        x_true)
    X, info, iters = st.gesv_mixed_gmres(A, B, opts,
                                         factor_dtype=jnp.float32)
    err = np.linalg.norm(X.to_numpy() - x_true) / np.linalg.norm(x_true)
    assert int(info) == 0
    assert iters >= 0, "FGMRES-IR failed to converge"
    assert err < 1e-5, f"FGMRES-IR err {err}"
    # plain IR must have actually failed — guards the test's premise
    assert not (err_plain < 1e-5), f"plain IR unexpectedly fine: {err_plain}"


def test_gesv_mixed_gmres_multiple_rhs():
    n, nb, nrhs = 64, 16, 3
    a = _cond_matrix(n, 1e6)
    x_true = RNG.standard_normal((n, nrhs))
    b = a @ x_true
    X, info, iters = st.gesv_mixed_gmres(
        st.from_dense(a, nb=nb), st.from_dense(b, nb=nb))
    assert int(info) == 0 and iters >= 0
    np.testing.assert_allclose(X.to_numpy(), x_true, rtol=1e-6, atol=1e-8)


@pytest.mark.slow  # ~5 s (round-10 headroom); GMRES-IR stays tier-1
# via the well-conditioned + beats-plain-IR real-dtype tests
def test_gesv_mixed_gmres_complex():
    n, nb = 64, 16
    a = _cond_matrix(n, 1e6, complex_=True)
    x_true = RNG.standard_normal((n, 1)) + 1j * RNG.standard_normal((n, 1))
    b = a @ x_true
    X, info, iters = st.gesv_mixed_gmres(
        st.from_dense(a, nb=nb), st.from_dense(b, nb=nb),
        factor_dtype=jnp.complex64)
    assert int(info) == 0 and iters >= 0
    np.testing.assert_allclose(X.to_numpy(), x_true, rtol=1e-6, atol=1e-8)


def test_gesv_mixed_gmres_singular_low_factor():
    """Exactly singular matrix: iter = −3 (reference code, .cc:77) and the
    fallback reports the singularity when disabled."""
    n = 8
    A = st.from_dense(np.zeros((n, n)), nb=8)
    B = st.from_dense(np.ones((n, 1)), nb=8)
    _, info, iters = st.gesv_mixed_gmres(
        A, B, st.Options(use_fallback_solver=False))
    assert iters == -3 and int(info) > 0


def test_posv_mixed_gmres_ill_conditioned():
    n, nb = 96, 16
    a = _cond_matrix(n, 1e8, spd=True)
    x_true = RNG.standard_normal((n, 2))
    b = a @ x_true
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    B = st.from_dense(b, nb=nb)
    X, info, iters = st.posv_mixed_gmres(
        A, B, st.Options(use_fallback_solver=False),
        factor_dtype=jnp.float32)
    err = np.linalg.norm(X.to_numpy() - x_true) / np.linalg.norm(x_true)
    assert int(info) == 0 and iters >= 0
    assert err < 1e-7, f"posv FGMRES-IR err {err}"


def test_posv_mixed_gmres_same_dtype_short_circuits():
    n, nb = 32, 8
    a = _cond_matrix(n, 10, spd=True).astype(np.float32)
    A = st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
    b = RNG.standard_normal((n, 1)).astype(np.float32)
    X, info, iters = st.posv_mixed_gmres(A, st.from_dense(b, nb=nb),
                                         factor_dtype=jnp.float32)
    assert iters == 0 and int(info) == 0
    np.testing.assert_allclose(a @ X.to_numpy(), b, atol=1e-4)


def test_gesv_mixed_gmres_fallback():
    """With the fallback enabled a hopeless low-precision factor still
    produces a correct solution (iter < 0 reports the failure)."""
    n, nb = 64, 16
    a = _cond_matrix(n, 1e15)  # beyond f32: GMRES-IR itself fails
    x_true = RNG.standard_normal((n, 1))
    b = a @ x_true
    X, info, iters = st.gesv_mixed_gmres(
        st.from_dense(a, nb=nb), st.from_dense(b, nb=nb),
        st.Options(use_fallback_solver=True))
    assert iters < 0
    # fallback = full-precision partial-pivot solve; backward error check
    r = np.linalg.norm(a @ X.to_numpy() - b) / (
        np.linalg.norm(a) * np.linalg.norm(X.to_numpy()))
    assert r < 1e-12
