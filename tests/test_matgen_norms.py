"""matgen determinism + norm correctness.

Reference analogs: unit_test/test_norm.cc and the matgen
distribution-independence property (matgen/random.cc, CHANGELOG.md:77-79).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import Norm, NormScope, Uplo
from slate_tpu.matgen import generate_matrix, random_spd


def test_matgen_deterministic_and_distribution_independent(grid2x2):
    a1 = np.asarray(generate_matrix("randn", 12, 12, jnp.float64, seed=7))
    a2 = np.asarray(generate_matrix("randn", 12, 12, jnp.float64, seed=7))
    np.testing.assert_array_equal(a1, a2)
    # same values regardless of nb and grid (counter-based keyed on logical
    # shape — matgen/random.cc property)
    A_nb4 = st.from_dense(a1, nb=4)
    A_nb5 = st.from_dense(a1, nb=5, grid=grid2x2)
    np.testing.assert_array_equal(A_nb4.to_numpy(), A_nb5.to_numpy())


def test_matgen_kinds_shapes():
    for kind in ["zeros", "ones", "identity", "minij", "hilb", "gcdmat",
                 "rand", "rands", "randn", "randb", "rand_dominant",
                 "svd_arith", "svd_geo", "svd_cluster0", "heev_arith",
                 "poev_logrand", "diag_arith"]:
        a = generate_matrix(kind, 8, 8, jnp.float64)
        assert a.shape == (8, 8), kind
        assert np.isfinite(np.asarray(a)).all(), kind


def test_matgen_spectra():
    cond = 100.0
    a = generate_matrix("svd_geo", 16, 16, jnp.float64, cond=cond)
    s = np.linalg.svd(np.asarray(a), compute_uv=False)
    assert abs(s[0] - 1.0) < 1e-8
    assert abs(s[-1] - 1.0 / cond) < 1e-8
    h = generate_matrix("heev_arith", 16, 16, jnp.float64, cond=cond)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h).T, atol=1e-12)


def test_norms_general():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((7, 9))
    A = st.from_dense(a, nb=4)  # padding must not affect norms
    assert np.isclose(float(st.norm(A, Norm.Max)), np.abs(a).max())
    assert np.isclose(float(st.norm(A, Norm.One)), np.abs(a).sum(0).max())
    assert np.isclose(float(st.norm(A, Norm.Inf)), np.abs(a).sum(1).max())
    assert np.isclose(float(st.norm(A, Norm.Fro)), np.linalg.norm(a, "fro"))


def test_norms_structured():
    rng = np.random.default_rng(1)
    a = rng.standard_normal((6, 6))
    S = st.symmetric(np.tril(a), nb=4, uplo=Uplo.Lower)
    full = np.tril(a) + np.tril(a, -1).T
    assert np.isclose(float(st.norm(S, Norm.One)), np.abs(full).sum(0).max())
    T = st.triangular(a, nb=4, uplo=Uplo.Upper)
    assert np.isclose(float(st.norm(T, Norm.Fro)),
                      np.linalg.norm(np.triu(a), "fro"))


def test_norm_nan_propagates():
    a = np.ones((4, 4))
    a[2, 1] = np.nan
    A = st.from_dense(a, nb=2)
    assert np.isnan(float(st.norm(A, Norm.Max)))
    assert np.isnan(float(st.norm(A, Norm.One)))


def test_col_norms():
    rng = np.random.default_rng(2)
    a = rng.standard_normal((5, 7))
    A = st.from_dense(a, nb=3)
    np.testing.assert_allclose(np.asarray(st.col_norms(A, Norm.Max)),
                               np.abs(a).max(0), rtol=1e-12)


def test_random_spd_is_spd():
    a = np.asarray(random_spd(16, dtype=jnp.float64))
    w = np.linalg.eigvalsh(a)
    assert w.min() > 0
