"""BLAS-3 driver tests vs numpy references.

Mirrors the reference's test/test_gemm.cc family and
unit_test/test_internal_blas.cc (internal kernels vs serial BLAS).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import (Diag, MethodGemm, Norm, Options, Side, Uplo)

RNG = np.random.default_rng(7)


def _mk(m, n, nb=16, grid=None):
    a = RNG.standard_normal((m, n))
    return a, st.from_dense(a, nb=nb, grid=grid)


@pytest.mark.parametrize("opa", ["n", "t"])
@pytest.mark.parametrize("opb", ["n", "t"])
def test_gemm_ops(opa, opb):
    m, n, k = 37, 25, 41
    a, A = _mk(*((m, k) if opa == "n" else (k, m)))
    b, B = _mk(*((k, n) if opb == "n" else (n, k)))
    c, C = _mk(m, n)
    Av = A if opa == "n" else A.T
    Bv = B if opb == "n" else B.T
    out = st.gemm(2.0, Av, Bv, -0.5, C)
    ref = 2.0 * (a if opa == "n" else a.T) @ (b if opb == "n" else b.T) - 0.5 * c
    np.testing.assert_allclose(out.to_numpy(), ref, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("method", [MethodGemm.A, MethodGemm.C])
def test_gemm_methods_on_grid(grid2x2, method):
    m, n, k = 64, 48, 80
    a, A = _mk(m, k, nb=16, grid=grid2x2)
    b, B = _mk(k, n, nb=16, grid=grid2x2)
    c, C = _mk(m, n, nb=16, grid=grid2x2)
    out = st.gemm(1.0, A, B, 1.0, C, Options(method_gemm=method))
    # distributed reductions reorder sums; allow a bit more slack
    np.testing.assert_allclose(out.to_numpy(), a @ b + c, rtol=1e-9,
                               atol=1e-10)


def test_gemm_probabilistic_residual_check():
    # the reference's self-check: ||(C - (alpha A B + beta C0)) X|| small
    # for random X (test/test_gemm.cc:135-279)
    m, n, k = 50, 40, 30
    a, A = _mk(m, k)
    b, B = _mk(k, n)
    c0, C0 = _mk(m, n)
    alpha, beta = 0.7, -1.3
    C = st.gemm(alpha, A, B, beta, C0)
    x = RNG.standard_normal((n, 2))
    lhs = C.to_numpy() @ x
    rhs = alpha * (a @ (b @ x)) + beta * (c0 @ x)
    err = np.linalg.norm(lhs - rhs) / np.linalg.norm(rhs)
    assert err < 3 * np.finfo(np.float64).eps * max(m, n, k)


def test_symm_hemm():
    n, m = 33, 21
    s = RNG.standard_normal((n, n))
    S = st.symmetric(np.tril(s), nb=8, uplo=Uplo.Lower)
    full = np.tril(s) + np.tril(s, -1).T
    b, B = _mk(n, m, nb=8)
    c, C = _mk(n, m, nb=8)
    out = st.symm(Side.Left, 1.5, S, B, 0.5, C)
    np.testing.assert_allclose(out.to_numpy(), 1.5 * full @ b + 0.5 * c,
                               rtol=1e-12)
    # right side
    b2, B2 = _mk(m, n, nb=8)
    c2, C2 = _mk(m, n, nb=8)
    out2 = st.symm(Side.Right, 2.0, S, B2, 1.0, C2)
    np.testing.assert_allclose(out2.to_numpy(), 2.0 * b2 @ full + c2,
                               rtol=1e-12)

    h = (RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n)))
    hfull = np.tril(h) + np.tril(h, -1).conj().T
    np.fill_diagonal(hfull, np.real(np.diagonal(hfull)))
    H = st.hermitian(np.tril(h), nb=8, uplo=Uplo.Lower)
    bc = RNG.standard_normal((n, m)) + 1j * RNG.standard_normal((n, m))
    Bc = st.from_dense(bc, nb=8)
    Cc = st.from_dense(np.zeros_like(bc), nb=8)
    outc = st.hemm(Side.Left, 1.0, H, Bc, 0.0, Cc)
    np.testing.assert_allclose(outc.to_numpy(), hfull @ bc, rtol=1e-12)


def test_syrk_herk():
    n, k = 29, 17
    a, A = _mk(n, k, nb=8)
    c = RNG.standard_normal((n, n))
    C = st.symmetric(c, nb=8, uplo=Uplo.Lower)
    out = st.syrk(1.0, A, 2.0, C)
    ref = np.tril(a @ a.T + 2.0 * c)
    np.testing.assert_allclose(np.tril(out.to_numpy()), ref, rtol=1e-12)
    # upper
    Cu = st.symmetric(c, nb=8, uplo=Uplo.Upper)
    outu = st.syrk(1.0, A, 0.0, Cu)
    np.testing.assert_allclose(np.triu(outu.to_numpy()), np.triu(a @ a.T),
                               rtol=1e-12)
    # herk complex
    ac = a + 1j * RNG.standard_normal((n, k))
    Ac = st.from_dense(ac, nb=8)
    Cc = st.hermitian(np.zeros((n, n), complex), nb=8, uplo=Uplo.Lower)
    outc = st.herk(1.0, Ac, 0.0, Cc)
    np.testing.assert_allclose(np.tril(outc.to_numpy()),
                               np.tril(ac @ ac.conj().T), rtol=1e-12)


def test_syr2k_her2k():
    n, k = 19, 11
    a, A = _mk(n, k, nb=8)
    b, B = _mk(n, k, nb=8)
    C = st.symmetric(np.zeros((n, n)), nb=8, uplo=Uplo.Lower)
    out = st.syr2k(1.0, A, B, 0.0, C)
    ref = a @ b.T + b @ a.T
    np.testing.assert_allclose(np.tril(out.to_numpy()), np.tril(ref),
                               rtol=1e-12, atol=1e-12)
    Ch = st.hermitian(np.zeros((n, n), complex), nb=8, uplo=Uplo.Lower)
    ac = a + 1j * b
    bc = b - 2j * a
    Ac, Bc = st.from_dense(ac, nb=8), st.from_dense(bc, nb=8)
    outh = st.her2k(1.0 + 0.5j, Ac, Bc, 0.0, Ch)
    alpha = 1.0 + 0.5j
    refh = alpha * ac @ bc.conj().T + np.conj(alpha) * bc @ ac.conj().T
    np.testing.assert_allclose(np.tril(outh.to_numpy()), np.tril(refh),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_trsm_trmm(side, uplo):
    n, m = 24, 13
    t = RNG.standard_normal((n, n)) + 3 * np.eye(n)
    tri = np.tril(t) if uplo is Uplo.Lower else np.triu(t)
    T = st.triangular(t, nb=8, uplo=uplo)
    shape = (n, m) if side is Side.Left else (m, n)
    b, B = _mk(*shape, nb=8)
    X = st.trsm(side, 2.0, T, B)
    if side is Side.Left:
        ref = np.linalg.solve(tri, 2.0 * b)
    else:
        ref = (2.0 * b) @ np.linalg.inv(tri)
    np.testing.assert_allclose(X.to_numpy(), ref, rtol=1e-9)
    Bm = st.trmm(side, 1.0, T, st.from_dense(ref, nb=8))
    np.testing.assert_allclose(Bm.to_numpy(), 2.0 * b, rtol=1e-9)


def test_trsm_transposed_view():
    n, m = 16, 5
    t = np.tril(RNG.standard_normal((n, n))) + 3 * np.eye(n)
    T = st.triangular(t, nb=8, uplo=Uplo.Lower)
    b, B = _mk(n, m, nb=8)
    X = st.trsm(Side.Left, 1.0, T.T, B)  # solve Lᵀ X = B
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(t.T, b),
                               rtol=1e-9)


def test_band_gbmm_tbsm():
    n = 20
    a = RNG.standard_normal((n, n))
    Ab = st.band(a, nb=8, kl=2, ku=1)
    r, c = np.indices((n, n))
    banded = np.where((c - r <= 1) & (r - c <= 2), a, 0.0)
    b, B = _mk(n, 7, nb=8)
    C = st.from_dense(np.zeros((n, 7)), nb=8)
    out = st.gbmm(1.0, Ab, B, 0.0, C)
    np.testing.assert_allclose(out.to_numpy(), banded @ b, rtol=1e-12,
                               atol=1e-12)
    # triangular band solve
    tb = np.tril(a, 0) + 5 * np.eye(n)
    Tb = st.triangular_band(tb, nb=8, kd=2, uplo=Uplo.Lower)
    tb_masked = np.where((r - c <= 2) & (r - c >= 0), tb, 0.0)
    Xb = st.tbsm(Side.Left, 1.0, Tb, B)
    np.testing.assert_allclose(Xb.to_numpy(), np.linalg.solve(tb_masked, b),
                               rtol=1e-9)


def test_elementwise():
    a, A = _mk(10, 12, nb=4)
    b, B = _mk(10, 12, nb=4)
    out = st.add(2.0, A, -1.0, B)
    np.testing.assert_allclose(out.to_numpy(), 2 * a - b, rtol=1e-12)
    C = st.copy(A, dtype=jnp.float32)
    assert C.dtype == jnp.float32
    S = st.scale(3.0, 2.0, A)
    np.testing.assert_allclose(S.to_numpy(), 1.5 * a, rtol=1e-12)
    r = np.arange(1.0, 11.0)
    c = np.arange(1.0, 13.0)
    RC = st.scale_row_col(jnp.asarray(r), jnp.asarray(c), A)
    np.testing.assert_allclose(RC.to_numpy(), a * r[:, None] * c[None, :],
                               rtol=1e-12)
    Z = st.set_matrix(1.0, 5.0, A)
    zn = Z.to_numpy()
    assert (np.diagonal(zn) == 5.0).all()
    assert zn[0, 1] == 1.0
    L = st.set_lambda(lambda i, j: i * 100 + j, A)
    assert L.to_numpy()[3, 4] == 304


def test_redistribute(grid2x2, grid2x4):
    a, A = _mk(32, 32, nb=8, grid=grid2x2)
    B = st.redistribute(A, grid2x4)
    assert len(B.data.sharding.device_set) == 8
    np.testing.assert_array_equal(B.to_numpy(), a)


def test_method_trsm_dispatch():
    """MethodTrsm.B (substitution) and Auto (gemm-based recursion) must
    agree (reference trsmA/trsmB split, src/trsmA.cc / src/trsmB.cc)."""
    import numpy as np
    from slate_tpu.core.types import MethodTrsm, Options, Side, Uplo
    rng = np.random.default_rng(5)
    n = 96
    l = np.tril(rng.standard_normal((n, n)))
    np.fill_diagonal(l, 2 + np.abs(l.diagonal()))
    b = rng.standard_normal((n, 8))
    L = st.triangular(l, nb=16, uplo=Uplo.Lower)
    B = st.from_dense(b, nb=16)
    xa = st.trsm(Side.Left, 1.0, L, B).to_numpy()
    xb = st.trsm(Side.Left, 1.0, L, B,
                 Options(method_trsm=MethodTrsm.B)).to_numpy()
    np.testing.assert_allclose(xa, xb, rtol=1e-10, atol=1e-10)
    np.testing.assert_allclose(l @ xb, b, atol=1e-10)


def test_method_hemm_dispatch(grid2x4):
    """MethodHemm.A (stationary-A reduce) and .C (stationary-C bcast)
    must agree on the grid (reference hemmA/hemmC)."""
    import numpy as np
    from slate_tpu.core.types import MethodHemm, Options, Side, Uplo
    rng = np.random.default_rng(6)
    n = 128
    a = rng.standard_normal((n, n)); a = 0.5 * (a + a.T)
    b = rng.standard_normal((n, n))
    A = st.hermitian(np.tril(a), nb=16, uplo=Uplo.Lower, grid=grid2x4)
    B = st.from_dense(b, nb=16, grid=grid2x4)
    C = st.from_dense(np.zeros((n, n)), nb=16, grid=grid2x4)
    outs = {}
    for meth in (MethodHemm.A, MethodHemm.C):
        outs[meth] = st.hemm(Side.Left, 1.0, A, B, 0.0, C,
                             Options(method_hemm=meth)).to_numpy()
    np.testing.assert_allclose(outs[MethodHemm.A], a @ b, atol=1e-10)
    np.testing.assert_allclose(outs[MethodHemm.C], a @ b, atol=1e-10)
