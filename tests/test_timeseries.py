"""Telemetry time-series store (round 23): bounded rings, downsample
tiers, counter-delta conservation, cardinality caps, the pump()-style
session sampler, the /history route, the 2-process fleet fold, and the
zero-allocation disabled path.

The conservation invariant under test everywhere: a counter series
stores DELTAS, and its lifetime sum equals the live cumulative counter
exactly — bit-exact, not approximately — which is what makes the fleet
fold's summed totals meaningful.
"""

import gc
import importlib.util
import json
import os
import tracemalloc
import urllib.request

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.obs.aggregate import merge_timeseries_payloads
from slate_tpu.obs.timeseries import (TIMESERIES_SCHEMA, TIER_WIDTHS,
                                      SessionSampler, TimeseriesStore,
                                      validate_timeseries)
from slate_tpu.runtime import Batcher, Metrics, Session

RNG = np.random.default_rng(23)
N, NB = 32, 16

_ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "_bench_gate", os.path.join(_ROOT, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _capacity_report():
    spec = importlib.util.spec_from_file_location(
        "_capacity_report",
        os.path.join(_ROOT, "tools", "capacity_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _clocked(start=0.0, **kw):
    t = {"now": float(start)}
    store = TimeseriesStore(clock=lambda: t["now"], **kw)
    return store, t


# -- the store ---------------------------------------------------------------


def test_gauge_samples_land_in_raw_and_tiers():
    store, t = _clocked()
    for i in range(5):
        t["now"] = float(i)
        store.record_gauge("queue_depth", 2.0 * i)
    assert store.names() == ["queue_depth"]
    assert store.kind("queue_depth") == "gauge"
    assert store.points("queue_depth") == [(float(i), 2.0 * i)
                                           for i in range(5)]
    # all 5 samples fall in one 10 s bucket: min/max/sum/count folded
    b10 = store.buckets("queue_depth", tier=0)
    assert b10 == [[0.0, 0.0, 8.0, 20.0, 5]]
    assert store.buckets("queue_depth", tier=1) == [[0.0, 0.0, 8.0,
                                                     20.0, 5]]


def test_counter_stored_as_deltas_with_exact_conservation():
    store, t = _clocked()
    cumulative = 0.0
    for i, inc in enumerate([3, 0, 7, 1, 12]):
        t["now"] = float(i)
        cumulative += inc
        store.record_counter("solves_total", cumulative)
    assert store.kind("solves_total") == "counter"
    # stored samples are the per-observation deltas...
    assert [v for _, v in store.points("solves_total")] == [3.0, 0.0,
                                                            7.0, 1.0,
                                                            12.0]
    # ...and the lifetime sum IS the cumulative counter, exactly
    assert store.counter_totals() == {"solves_total": cumulative}


def test_counter_reset_reads_as_restart():
    """A decrease is a process restart: the new cumulative IS the
    delta (the Prometheus rate() rule) — conservation then tracks the
    sum across both incarnations."""
    store, t = _clocked()
    store.record_counter("solves_total", 100.0)
    t["now"] = 1.0
    store.record_counter("solves_total", 40.0)   # restarted process
    assert [v for _, v in store.points("solves_total")] == [100.0, 40.0]
    assert store.counter_totals()["solves_total"] == 140.0


def test_tiers_conserve_counter_deltas_after_raw_ring_wraps():
    """The compaction claim: integer deltas pushed far past the raw
    ring's capacity are still fully accounted in the tier buckets (and
    in total_sum) — the raw ring forgets, the tiers do not."""
    store, t = _clocked(raw_capacity=16, tier_capacities=(1000, 1000))
    total = 0
    for i in range(400):
        t["now"] = float(i)          # 400 s of 1 Hz traffic
        total += (i % 5)
        store.record_counter("requests_total", float(total))
    assert len(store.points("requests_total")) == 16  # wrapped
    for tier in (0, 1):
        bucket_sum = sum(b[3] for b in store.buckets("requests_total",
                                                     tier=tier))
        bucket_count = sum(b[4] for b in store.buckets("requests_total",
                                                       tier=tier))
        assert bucket_sum == float(total)
        assert bucket_count == 400
    assert store.counter_totals()["requests_total"] == float(total)


def test_tier_bucket_rings_are_bounded():
    store, t = _clocked(raw_capacity=8, tier_capacities=(4, 2))
    for i in range(1000):
        t["now"] = float(10 * i)     # one sample per 10 s bucket
        store.record_gauge("g", 1.0)
    assert len(store.buckets("g", tier=0)) == 4
    assert len(store.buckets("g", tier=1)) == 2


def test_series_cardinality_cap_counts_drops():
    store, t = _clocked(max_series=4)
    for i in range(4):
        store.record_gauge(f"keep{i}", 1.0)
    assert store.dropped_series == 0
    # churned handle names beyond the cap: dropped and counted, never
    # stored — repeats of one refused name count samples, not series
    for _ in range(3):
        store.record_gauge("churn0", 1.0)
    store.record_counter("churn1", 5.0)
    assert len(store.names()) == 4
    assert store.dropped_series == 2
    assert store.dropped_samples == 4
    assert "churn0" not in store.names()
    # existing series keep recording under the cap
    store.record_gauge("keep0", 2.0)
    assert len(store.points("keep0")) == 2


def test_refused_name_set_is_itself_bounded():
    """The drop accounting must not become the unbounded thing it
    counts: distinct refused names are tracked up to 4x max_series,
    then a single overflow marker stands in for the rest."""
    store, t = _clocked(max_series=2)
    store.record_gauge("a", 1.0)
    store.record_gauge("b", 1.0)
    for i in range(100):
        store.record_gauge(f"churn{i}", 1.0)
    assert store.dropped_samples == 100
    assert store.dropped_series == 4 * 2 + 1  # capped set + overflow
    assert len(store._refused) == 8


def test_window_stats_spans_raw_and_tier_history():
    """Once the raw ring has forgotten the window's prefix, the finest
    tier's buckets cover it: the over-window aggregate stays TRUE (sum
    and count exact) instead of silently shrinking to the ring."""
    store, t = _clocked(raw_capacity=4, tier_capacities=(100, 100))
    for i in range(20):
        t["now"] = float(10 * i)
        store.record_gauge("lat", float(i))
    # raw ring holds only the last 4 samples (t >= 160)
    assert store.points("lat")[0][0] == 160.0
    stats = store.window_stats("lat", lo=0.0, hi=190.0)
    assert stats["count"] == 20
    assert stats["sum"] == sum(range(20))
    assert stats["min"] == 0.0 and stats["max"] == 19.0
    assert stats["mean"] == pytest.approx(sum(range(20)) / 20)


def test_counter_rate_over_window():
    store, t = _clocked()
    cum = 0.0
    for i in range(10):
        t["now"] = float(i)
        cum += 5.0
        store.record_counter("solves_total", cum)
    # the window (4.5, 9.5] holds the 5 deltas at t=5..9 -> 5 solves/s
    assert store.rate("solves_total", window_s=5.0,
                      now=9.5) == pytest.approx(5.0)
    assert store.rate("nope", 5.0) is None
    store.record_gauge("g", 1.0)
    assert store.rate("g", 5.0) is None   # gauges have no rate


def test_payload_validates_and_filters():
    store, t = _clocked()
    store.record_gauge("g", 1.0)
    store.record_counter("c", 2.0)
    doc = store.payload()
    assert doc["schema"] == TIMESERIES_SCHEMA
    assert validate_timeseries(doc) == []
    assert set(doc["series"]) == {"g", "c"}
    assert doc["series"]["c"]["kind"] == "counter"
    assert doc["series"]["c"]["total_sum"] == 2.0
    assert list(doc["tier_widths"]) == list(TIER_WIDTHS)
    only_g = store.payload(series=["g", "missing"])
    assert set(only_g["series"]) == {"g"}
    json.dumps(doc)  # JSON-able as-is


def test_validator_rejects_malformed_docs():
    good = TimeseriesStore().payload()
    assert validate_timeseries(good) == []
    assert validate_timeseries([]) != []
    assert validate_timeseries({"schema": "wrong"}) != []
    bad_kind = TimeseriesStore()
    bad_kind.record_gauge("g", 1.0)
    doc = bad_kind.payload()
    doc["series"]["g"]["kind"] = "sideways"
    assert any("kind" in e for e in validate_timeseries(doc))
    doc2 = bad_kind.payload()
    doc2["series"]["g"]["tiers"]["10"] = [[0.0, 1.0, 1.0]]  # not len-5
    assert validate_timeseries(doc2) != []


# -- the session sampler -----------------------------------------------------


def _lu_session(**kw):
    sess = Session(**kw)
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    h = sess.register(st.from_dense(a, nb=NB), op="lu")
    return sess, h, a


def test_sampler_pump_throttles_and_forces():
    t = {"now": 0.0}
    clock = lambda: t["now"]  # noqa: E731
    sess = Session(metrics=Metrics(clock=clock))
    store = sess.enable_timeseries(interval_s=10.0, clock=clock)
    assert sess.enable_timeseries() is store  # idempotent
    sess.metrics.inc("solves_total", 3)
    assert sess.pump_timeseries() > 0
    t["now"] = 5.0
    assert sess.pump_timeseries() == 0          # throttled
    assert sess.pump_timeseries(force=True) > 0
    t["now"] = 15.0
    assert sess.pump_timeseries() > 0           # interval elapsed


def test_gauges_sampled_at_their_stamped_timestamps():
    """The round-23 satellite: a gauge sample carries the time the
    value was LAST TRUE (its set-time stamp), not the scrape time — a
    late pump must not shift history."""
    t = {"now": 7.0}
    clock = lambda: t["now"]  # noqa: E731
    sess = Session(metrics=Metrics(clock=clock))
    store = sess.enable_timeseries(interval_s=0.0, clock=clock)
    sess.metrics.set_gauge("queue_depth", 4.0)      # stamped at t=7
    t["now"] = 100.0
    sess.pump_timeseries(force=True)
    assert store.points("queue_depth") == [(7.0, 4.0)]
    # counters carry the pump time (deltas are interval quantities)
    sess.metrics.inc("solves_total", 2)
    t["now"] = 101.0
    sess.pump_timeseries(force=True)
    assert store.points("solves_total")[-1][0] == 101.0


def test_sampler_covers_heat_and_conserves_counters():
    sess, h, a = _lu_session()
    sess.enable_attribution()
    store = sess.enable_timeseries(interval_s=0.0)
    for _ in range(3):
        sess.solve(h, RNG.standard_normal(N))
        sess.pump_timeseries(force=True)
    heat_series = [nm for nm in store.names() if nm.startswith("heat:")]
    assert heat_series, store.names()
    assert all(v >= 0 for _, v in store.points(heat_series[0]))
    # EXACT conservation across every sampled counter
    counters = sess.metrics.snapshot()["counters"]
    totals = store.counter_totals()
    assert totals
    for nm, total in totals.items():
        assert total == counters.get(nm, 0.0), nm


def test_disabled_path_allocates_nothing():
    """Round-8 discipline: with timeseries never enabled, a served
    workload allocates ZERO bytes from obs/timeseries.py and
    pump_timeseries() is a single is-None check returning 0. The
    enabled control proves the instrument measures what we claim."""
    filters = [tracemalloc.Filter(
        True, os.path.join("*", "slate_tpu", "obs", "timeseries.py"))]

    def _serve(sess, h):
        batcher = Batcher(sess, max_batch=4, max_wait=10.0)
        futs = [batcher.submit(h, RNG.standard_normal(N))
                for _ in range(4)]
        batcher.flush()
        for f in futs:
            f.result(timeout=30)
        assert sess.pump_timeseries() == 0

    sess, h, _ = _lu_session()
    assert sess.timeseries is None
    sess.solve(h, RNG.standard_normal(N))  # warm the compile caches
    gc.collect()
    tracemalloc.start()
    try:
        _serve(sess, h)
        disabled = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    assert sum(s.size for s in disabled.statistics("filename")) == 0

    sess2, h2, _ = _lu_session()
    sess2.enable_timeseries(interval_s=0.0)
    sess2.solve(h2, RNG.standard_normal(N))
    gc.collect()
    tracemalloc.start()
    try:
        batcher = Batcher(sess2, max_batch=4, max_wait=10.0)
        futs = [batcher.submit(h2, RNG.standard_normal(N))
                for _ in range(4)]
        batcher.flush()
        for f in futs:
            f.result(timeout=30)
        assert sess2.pump_timeseries(force=True) > 0
        enabled = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    assert sum(s.size for s in enabled.statistics("filename")) > 0


# -- the /history route ------------------------------------------------------


def test_history_and_forecast_routes_serve_schema_valid_payloads():
    sess, h, _ = _lu_session()
    sess.enable_attribution()
    sess.enable_timeseries(interval_s=0.0)
    srv = sess.serve_obs()
    try:
        for _ in range(2):
            sess.solve(h, RNG.standard_normal(N))
            sess.pump_timeseries(force=True)
        hist = json.loads(urllib.request.urlopen(
            srv.url("/history"), timeout=10).read().decode())
        assert validate_timeseries(hist) == []
        assert hist["series"]
        # ?series= filters
        assert "solves_total" in hist["series"]
        filt = json.loads(urllib.request.urlopen(
            srv.url("/history?series=solves_total"),
            timeout=10).read().decode())
        assert set(filt["series"]) == {"solves_total"}
        fc = json.loads(urllib.request.urlopen(
            srv.url("/forecast"), timeout=10).read().decode())
        assert fc["schema"] == "slate_tpu.forecast.v1"
        assert obs.validate_forecast(fc) == []
    finally:
        sess.close_obs()


# -- fleet fold --------------------------------------------------------------


def _two_host_payloads():
    docs = []
    for host, base in (("p0", 10.0), ("p1", 20.0)):
        store, t = _clocked(host=host)
        cum = 0.0
        for i in range(6):
            t["now"] = float(i)
            store.record_gauge("queue_depth", base + i)
            cum += 3.0
            store.record_counter("solves_total", cum)
        docs.append(store.payload())
    return docs


def test_fleet_fold_is_host_labeled_with_exact_conservation():
    docs = _two_host_payloads()
    fleet = merge_timeseries_payloads(docs, hosts=["p0", "p1"])
    assert fleet["schema"] == "slate_tpu.timeseries.fleet.v1"
    # one queue-depth history per member, not one mush
    assert "p0:queue_depth" in fleet["series"]
    assert "p1:queue_depth" in fleet["series"]
    # counter totals are the exact sum across members
    assert fleet["counter_totals"]["solves_total"] == 36.0
    # folding a payload with itself doubles every total bit-exactly
    twice = merge_timeseries_payloads([docs[0], docs[0]],
                                      hosts=["a", "b"])
    assert twice["counter_totals"]["solves_total"] == 2 * 18.0


def test_fleet_fold_tolerates_a_lost_member():
    docs = _two_host_payloads()
    fleet = merge_timeseries_payloads([docs[0], None], hosts=["p0",
                                                              "dead"])
    assert fleet["partial_processes"] == 1
    assert fleet["counter_totals"]["solves_total"] == 18.0


def test_capacity_report_fold_matches_runtime_fold():
    """tools/capacity_report.py re-implements the fold jax-free for
    exported payload files — this pin keeps the two from drifting:
    same series keys, same counter totals, same drop accounting."""
    cr = _capacity_report()
    docs = _two_host_payloads()
    ours = merge_timeseries_payloads(docs, hosts=["p0", "p1"])
    theirs = cr.fold_payloads(docs, hosts=["p0", "p1"])
    assert set(theirs["series"]) == set(ours["series"])
    assert theirs["counter_totals"] == ours["counter_totals"]
    assert (theirs["dropped_samples"], theirs["dropped_series"]) == \
        (ours["dropped_samples"], ours["dropped_series"])


# -- bench_gate mirrors ------------------------------------------------------


def test_bench_gate_binds_the_real_validators():
    """bench_gate stays jax-free by FILE-LOADING obs/timeseries.py and
    obs/forecast.py under fixed module names — import identity, not a
    duplicated rule set. The pin: its bound validators are the very
    functions defined in this package's source files."""
    bg = _bench_gate()
    from slate_tpu.obs import forecast as fmod
    from slate_tpu.obs import timeseries as tmod
    assert (bg.validate_timeseries_doc.__code__.co_filename
            == tmod.validate_timeseries.__code__.co_filename)
    assert (bg.validate_forecast_doc.__code__.co_filename
            == fmod.validate_forecast.__code__.co_filename)
    # and they behave identically on the same malformed docs
    for doc in ({"schema": "wrong"}, {}, {"schema": TIMESERIES_SCHEMA}):
        assert bool(bg.validate_timeseries_doc(doc)) == \
            bool(validate_timeseries(doc))


def test_bench_gate_checks_serve_forecast_section():
    """The serve artifact's forecast section is exit-gated: a
    conservation row with store != counter must fail the schema
    check."""
    bg = _bench_gate()
    store, t = _clocked()
    store.record_counter("solves_total", 5.0)
    store.record_gauge("queue_depth", 1.0)
    from slate_tpu.obs.forecast import Forecaster
    section = {
        "enabled": True, "ok": True, "series_count": 2,
        "dropped_series": 0, "dropped_samples": 0,
        "conservation": {"solves_total": {"store": 5.0, "counter": 5.0,
                                          "ok": True}},
        "history": store.payload(),
        "forecast": Forecaster(store).payload(horizon_s=10.0),
    }
    bg._check_forecast_section("t", dict(section))   # passes
    broken = dict(section)
    broken["conservation"] = {"solves_total": {
        "store": 5.0, "counter": 6.0, "ok": False}}
    broken["ok"] = False
    with pytest.raises(bg.SchemaError):
        bg._check_forecast_section("t", broken)
    with pytest.raises(bg.SchemaError):
        bg._check_forecast_section("t", {"enabled": False})
