"""Checkpoint/restore of resident factors (round 17, ISSUE 14).

Pins the warm-restart contract: a restored handle's solve is
BIT-IDENTICAL to the pre-checkpoint resident's solve with ZERO
refactors (dense, small-bucket, and refined-bf16 entries), mesh
residents restore re-sharded onto the current grid (bit-identity not
claimed across placements — the round-11 rule), heat/health/tenant
carry over, corruption is caught by the per-blob checksum and degrades
to refactor-on-miss (never a wrong answer), and the manifest schema is
mirror-pinned against the jax-free tools/bench_gate.py validator.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.refine import RefinePolicy
from slate_tpu.runtime import (FaultInjector, FaultPlan, FaultSpec,
                               Session)
from slate_tpu.runtime import checkpoint as ckpt


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "_bg_for_ckpt", os.path.join(os.path.dirname(__file__),
                                     os.pardir, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _spd(rng, n, dtype=np.float32):
    a = rng.standard_normal((n, n)).astype(dtype)
    return (a @ a.T + n * np.eye(n)).astype(dtype)


def _diag_dom(rng, n, dtype=np.float32):
    return (rng.standard_normal((n, n))
            + n * np.eye(n)).astype(dtype)


def _residual(a, x, b):
    x = np.asarray(x, dtype=np.float64)
    return float(np.abs(a.astype(np.float64) @ x
                        - np.asarray(b, np.float64)).max()) \
        / (a.shape[0] * max(float(np.abs(x).max()), 1.0))


class TestManifestSchema:
    def test_mirror_pinned_against_bench_gate(self):
        """The jax-free bench_gate validator and the runtime validator
        share schema id, record keys, and blob keys — the placement-
        schema duplication discipline."""
        bg = _bench_gate()
        assert bg.CHECKPOINT_SCHEMA == ckpt.CHECKPOINT_SCHEMA
        assert bg.CHECKPOINT_RECORD_KEYS == ckpt.CHECKPOINT_RECORD_KEYS
        assert bg.CHECKPOINT_BLOB_KEYS == ckpt.CHECKPOINT_BLOB_KEYS

    def test_both_validators_reject_same_malformed_docs(self):
        bg = _bench_gate()
        good_rec = {k: None for k in ckpt.CHECKPOINT_RECORD_KEYS}
        good_rec.update(handle="h", handle_type="str", op="chol",
                        m=4, n=4, band=0, dtype="float32", nb=2,
                        info=0, heat=0.0,
                        operator={"type": "tuple", "items": []},
                        payload={"type": "tuple", "items": []})
        bad_docs = [
            {"schema": "wrong.schema", "host": "x",
             "generated_at": 0.0, "records": []},
            {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "",
             "generated_at": 0.0, "records": []},
            {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
             "generated_at": 0.0, "records": [{"handle": "h"}]},
            {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
             "generated_at": 0.0,
             "records": [dict(good_rec, handle_type="float")]},
            {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
             "generated_at": 0.0,
             "records": [dict(good_rec, mesh=[2])]},
            {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
             "generated_at": 0.0,
             "records": [dict(good_rec, op=5)]},
            {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
             "generated_at": 0.0,
             "records": [dict(good_rec, dtype=32)]},
        ]
        for doc in bad_docs:
            assert ckpt.validate_manifest(doc), doc
            assert bg.validate_checkpoint_manifest(doc), doc
        good = {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
                "generated_at": 0.0, "records": [good_rec]}
        assert ckpt.validate_manifest(good) == []
        assert bg.validate_checkpoint_manifest(good) == []


class TestWarmRestart:
    def test_dense_restore_bit_identical_no_refactor(self, tmp_path):
        rng = np.random.default_rng(0)
        n, nb = 32, 16
        spd = _spd(rng, n)
        sess = Session()
        h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                       uplo=st.Uplo.Lower),
                          op="chol", handle="d0")
        b = rng.standard_normal(n).astype(np.float32)
        x1 = sess.solve(h, b)
        manifest = sess.checkpoint(str(tmp_path / "ck"))
        assert ckpt.validate_manifest(manifest) == []
        sess2 = Session()
        summary = sess2.restore(str(tmp_path / "ck"))
        assert summary["restored"] == ["d0"]
        assert sess2.metrics.get("restored_residents_total") == 1
        x2 = sess2.solve(h, b)
        # warm restart: bit-identical AND zero refactors
        assert np.asarray(x1).tobytes() == np.asarray(x2).tobytes()
        assert sess2.metrics.get("factors_total") == 0
        assert sess2.metrics.get("cache_hits") == 1

    def test_crash_mid_save_keeps_prior_checkpoint(self, tmp_path,
                                                   monkeypatch):
        """A save that dies before its manifest lands must not corrupt
        the previous checkpoint: blobs go into a fresh generation dir
        and the old manifest keeps naming the old, intact blobs (the
        crash a checkpoint exists to survive cannot destroy the only
        durable copy)."""
        rng = np.random.default_rng(11)
        a = _diag_dom(rng, 16)
        sess = Session()
        h = sess.register(a, op="lu_small", handle="g0")
        b = rng.standard_normal(16).astype(np.float32)
        x1 = sess.solve(h, b)
        path = str(tmp_path / "ck")
        man1 = sess.checkpoint(path)
        # crash mid-save #2: every blob written, manifest replace dies
        real_replace = os.replace

        def boom(src, dst, *a_, **k_):
            if str(dst).endswith("manifest.json"):
                raise OSError("simulated crash before manifest publish")
            return real_replace(src, dst, *a_, **k_)

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            sess.checkpoint(path)
        monkeypatch.undo()
        # the surviving manifest is generation 1, fully restorable
        sess2 = Session()
        summary = sess2.restore(path)
        assert summary["restored"] == ["g0"]
        assert summary["corrupt"] == []
        x2 = sess2.solve(h, b)
        assert np.asarray(x1).tobytes() == np.asarray(x2).tobytes()
        # a completed re-save prunes the superseded generation
        man3 = sess.checkpoint(path)
        assert man3["blobs"] != man1["blobs"]
        dirs = [d for d in os.listdir(path) if d.startswith("blobs")]
        assert dirs == [man3["blobs"]]

    def test_small_restore_bit_identical_no_refactor(self, tmp_path):
        rng = np.random.default_rng(1)
        n = 16
        a = _diag_dom(rng, n)
        sess = Session()
        h = sess.register(a, op="lu_small", handle="s0")
        b = rng.standard_normal(n).astype(np.float32)
        x1 = sess.solve(h, b)
        sess.checkpoint(str(tmp_path / "ck"))
        sess2 = Session()
        sess2.restore(str(tmp_path / "ck"))
        x2 = sess2.solve(h, b)
        assert np.asarray(x1).tobytes() == np.asarray(x2).tobytes()
        assert sess2.metrics.get("factors_total") == 0

    @pytest.mark.slow
    def test_refined_bf16_restore_policy_and_charge(self, tmp_path):
        """Satellite pin: a refined-bf16 resident restores with its
        policy active AND its half-HBM budget charge intact — and the
        refined solve is bit-identical with zero refactors. Slow
        (round-18 tier-1 budget): the refined dense start/step
        programs are their own compiles; tier-1 siblings —
        test_dense_restore_bit_identical_no_refactor pins the
        restore-without-refactor bit-identity class, and
        TestCarryover::test_heat_and_tenant_carry_over pins the
        metadata carryover."""
        rng = np.random.default_rng(2)
        n, nb = 32, 16
        spd = _spd(rng, n)
        pol = RefinePolicy(factor_dtype="bfloat16")
        sess = Session()
        h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                       uplo=st.Uplo.Lower),
                          op="chol", handle="r0", refine=pol)
        b = rng.standard_normal(n).astype(np.float32)
        x1 = sess.solve(h, b)
        res1 = sess._cache[h]
        # the lo resident charges HALF the full-precision bytes
        full = Session()
        hf = full.register(st.hermitian(np.tril(spd), nb=nb,
                                        uplo=st.Uplo.Lower),
                           op="chol", handle="f0")
        full.factor(hf)
        assert res1.nbytes * 2 == full._cache[hf].nbytes
        sess.checkpoint(str(tmp_path / "ck"))
        sess2 = Session()
        sess2.restore(str(tmp_path / "ck"))
        entry = sess2._ops[h]
        assert entry.refine == pol          # policy survived
        res2 = sess2._cache[h]
        assert res2.nbytes == res1.nbytes   # half-charge survived
        x2 = sess2.solve(h, b)
        assert np.asarray(x1).tobytes() == np.asarray(x2).tobytes()
        assert sess2.metrics.get("factors_total") == 0
        assert sess2.metrics.get("refine_converged_total") >= 1

    @pytest.mark.slow
    def test_mesh_restore_resharded_on_current_grid(self, tmp_path):
        """Mesh residents restore RE-SHARDED onto the restoring
        session's grid with zero refactors; correctness (not
        bit-identity) is the cross-placement claim (round-11 rule).
        Slow (round-18 tier-1 budget): two DIFFERENT-grid sharded AOT
        solve compiles dominate; tier-1 sibling —
        test_dense_restore_bit_identical_no_refactor pins the
        restore-without-refactor class single-device (the re-shard
        itself is the round-11 mesh rule, pinned in
        tests/test_mesh_session.py)."""
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs >= 4 devices")
        from slate_tpu.core.grid import ProcessGrid
        rng = np.random.default_rng(3)
        n, nb = 32, 8
        ge = _diag_dom(rng, n)
        grid = ProcessGrid.create(2, 2)
        sess = Session(mesh=grid)
        h = sess.register(st.from_dense(ge, nb=nb), op="lu",
                          handle="m0")
        sess.warmup(h)
        b = rng.standard_normal(n).astype(np.float32)
        sess.solve(h, b)
        manifest = sess.checkpoint(str(tmp_path / "ck"))
        assert manifest["records"][0]["mesh"] == [2, 2]
        sess2 = Session(mesh=grid)
        summary = sess2.restore(str(tmp_path / "ck"))
        assert summary["restored"] == ["m0"]
        entry = sess2._ops[h]
        assert entry.grid is not None and (entry.grid.p,
                                           entry.grid.q) == (2, 2)
        lu = sess2._cache[h].payload[0]
        # the restored factor is genuinely mesh-resident again
        assert len(lu.data.sharding.device_set) == 4
        x2 = sess2.solve(h, b)
        assert _residual(ge, x2, b) < 1e-3
        assert sess2.metrics.get("factors_total") == 0

    def test_only_filter_and_conflict(self, tmp_path):
        rng = np.random.default_rng(4)
        a0, a1 = _diag_dom(rng, 16), _diag_dom(rng, 16)
        sess = Session()
        h0 = sess.register(a0, op="lu_small", handle="k0")
        h1 = sess.register(a1, op="lu_small", handle="k1")
        sess.factor(h0)
        sess.factor(h1)
        manifest = sess.checkpoint(str(tmp_path / "ck"), only=[h0])
        assert [r["handle"] for r in manifest["records"]] == ["k0"]
        # restoring into a session that already serves the handle is a
        # counted conflict — the live operator wins
        sess2 = Session()
        sess2.register(a1, op="lu_small", handle="k0")
        summary = sess2.restore(str(tmp_path / "ck"))
        assert summary["conflicts"] == ["k0"]
        assert sess2.metrics.get("restore_conflicts_total") == 1


class TestCarryover:
    def test_heat_and_tenant_carry_over(self, tmp_path):
        rng = np.random.default_rng(5)
        a = _diag_dom(rng, 16)
        sess = Session()
        sess.enable_attribution()
        h = sess.register(a, op="lu_small", handle="t0",
                          tenant="tenant-x")
        for _ in range(3):
            sess.solve(h, rng.standard_normal(16).astype(np.float32))
        heat_pre = sess.attribution.heat(h)
        assert heat_pre > 0
        sess.checkpoint(str(tmp_path / "ck"))
        sess2 = Session()
        sess2.enable_attribution()
        sess2.restore(str(tmp_path / "ck"))
        assert sess2._ops[h].tenant == "tenant-x"
        # imported heat starts at the decayed-to-checkpoint value
        assert sess2.attribution.heat(h) == pytest.approx(heat_pre,
                                                          rel=0.05)
        row = sess2.placement_snapshot(host="x")["rows"][0]
        assert row["tenant"] == "tenant-x" and row["heat"] > 0

    def test_suspect_health_carries_and_loses_tiebreak(self, tmp_path):
        """Satellite pin: a suspect handle STAYS suspect across
        restore and keeps losing eviction tie-breaks."""
        rng = np.random.default_rng(6)
        a0, a1 = _diag_dom(rng, 16), _diag_dom(rng, 16)
        sess = Session()
        sess.enable_numerics(sample_fraction=0.0,
                             condest_on_factor=False)
        h0 = sess.register(a0, op="lu_small", handle="u0")
        h1 = sess.register(a1, op="lu_small", handle="u1")
        sess.factor(h0)
        sess.factor(h1)
        # drive u0 suspect through the monitor's own seam (a condest
        # far past f32's breakdown point)
        sess.numerics.record_factor(h0, "lu_small", "float32")
        sess.numerics.record_condest(h0, 1e30)
        assert sess.numerics.health(h0) == "suspect"
        sess.checkpoint(str(tmp_path / "ck"))
        sess2 = Session()
        sess2.enable_numerics(sample_fraction=0.0,
                              condest_on_factor=False)
        sess2.restore(str(tmp_path / "ck"))
        assert sess2.numerics.health(h0) == "suspect"
        assert sess2.numerics.health(h1) == "healthy"
        # suspect handles lose eviction tie-breaks after restore too:
        # u0 leads the eviction order although u1 is older in LRU
        order = sess2._eviction_order()
        assert order[0] == h0
        # and the restored placement row reports the suspect state
        rows = {r["handle"]: r for r in
                sess2.placement_snapshot(host="x")["rows"]}
        assert rows[repr(h0)]["health"] == "suspect"


class TestCorruption:
    def test_tampered_blob_degrades_to_refactor(self, tmp_path):
        rng = np.random.default_rng(7)
        a = _diag_dom(rng, 16)
        sess = Session()
        h = sess.register(a, op="lu_small", handle="c0")
        sess.factor(h)
        manifest = sess.checkpoint(str(tmp_path / "ck"))
        # tamper with the PAYLOAD's first blob on disk (the factor)
        blob = manifest["records"][0]["payload"]["items"][0]["a"]["blob"]
        bpath = tmp_path / "ck" / manifest["blobs"] / blob
        raw = bytearray(bpath.read_bytes())
        raw[0] ^= 0xFF
        bpath.write_bytes(bytes(raw))
        sess2 = Session()
        summary = sess2.restore(str(tmp_path / "ck"))
        assert summary["corrupt"] == ["c0"]
        assert summary["registered"] == ["c0"]
        assert sess2.metrics.get("restore_corrupt_total") == 1
        # the handle still serves — via a refactor, never corrupt bits
        b = rng.standard_normal(16).astype(np.float32)
        x = sess2.solve(h, b)
        assert _residual(a, x, b) < 1e-3
        assert sess2.metrics.get("factors_total") == 1

    def test_injected_restore_corrupt_fault(self, tmp_path):
        """The restore_corrupt fault class fires at the "restore" seam
        and the checksum must catch it — deterministic under the
        seeded plan (the chaos drill's gate, pinned at unit level)."""
        rng = np.random.default_rng(8)
        a = _diag_dom(rng, 16)
        sess = Session()
        h = sess.register(a, op="lu_small", handle="c1")
        sess.factor(h)
        sess.checkpoint(str(tmp_path / "ck"))
        sess2 = Session()
        sess2.faults = FaultInjector(FaultPlan(seed=1, specs=(
            FaultSpec("restore_corrupt", rate=1.0, count=1),)))
        summary = sess2.restore(str(tmp_path / "ck"))
        assert summary["corrupt"] == ["c1"]
        assert sess2.metrics.get("restore_corrupt_total") == 1
        assert sess2.metrics.get("fault:restore_corrupt") == 1
        # a second restore into a THIRD session under the same plan but
        # exhausted count restores cleanly (count=1 spent above is per
        # injector; a fresh injector with after=1 skips record 0)
        sess3 = Session()
        sess3.faults = FaultInjector(FaultPlan(seed=1, specs=(
            FaultSpec("restore_corrupt", rate=1.0, after=1,
                      count=1),)))
        summary3 = sess3.restore(str(tmp_path / "ck"))
        assert summary3["restored"] == ["c1"]


class TestClose:
    def test_close_flushes_checkpoint_and_placement(self, tmp_path):
        """Satellite pin: Session.close() with a configured
        checkpoint_dir flushes a final checkpoint + placement snapshot
        (before round 17, close dropped both on the floor)."""
        rng = np.random.default_rng(9)
        a = _diag_dom(rng, 16)
        cdir = str(tmp_path / "state")
        with Session(checkpoint_dir=cdir) as sess:
            h = sess.register(a, op="lu_small", handle="z0")
            sess.solve(h, rng.standard_normal(16).astype(np.float32))
        # the context-manager exit called close(): both artifacts exist
        manifest = ckpt.load_manifest(os.path.join(cdir, "checkpoint"))
        assert [r["handle"] for r in manifest["records"]] == ["z0"]
        with open(os.path.join(cdir, "placement.json")) as f:
            placement = json.load(f)
        from slate_tpu.obs.attribution import (
            validate_placement_snapshot)
        assert validate_placement_snapshot(placement) == []
        # and a fresh session warm-restarts from the flushed state
        sess2 = Session()
        assert sess2.restore(
            os.path.join(cdir, "checkpoint"))["restored"] == ["z0"]
        assert sess2.metrics.get("factors_total") == 0

    def test_close_without_dir_is_noop(self):
        sess = Session()
        sess.close()  # no checkpoint_dir: nothing to flush, no error
