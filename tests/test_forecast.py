"""Load/heat forecasting over the history store (round 23):
periodicity detection (detrended ACF), the method ladder
(last -> trend -> seasonal_naive -> holt_winters), confidence bands,
holdout accuracy vs last-value persistence, the predicted-hot ranking,
exhaustion runways, the /forecast payload, and bit-for-bit
determinism — the contract the chaos drill digests.
"""

import json
import math

import numpy as np
import pytest

from slate_tpu.obs.forecast import (FORECAST_SCHEMA, Forecaster,
                                    detect_period, forecast_points,
                                    validate_forecast)
from slate_tpu.obs.timeseries import TimeseriesStore

RNG = np.random.default_rng(123)


def _diurnal(cycles=5, period=24, amp=3.0, base=5.0, noise=0.15,
             dt=10.0, rng=None):
    """(ts, value) samples of a noisy periodic load curve."""
    rng = RNG if rng is None else rng
    pts = []
    for i in range(cycles * period):
        t = i * dt
        v = (base + amp * math.sin(2 * math.pi * i / period)
             + float(rng.normal(0.0, noise)))
        pts.append((t, v))
    return pts


# -- periodicity --------------------------------------------------------------


def test_detect_period_finds_the_diurnal_cycle():
    vals = [v for _, v in _diurnal(cycles=5, period=24)]
    assert detect_period(vals) == 24


def test_detect_period_silent_on_noise():
    vals = [float(RNG.normal(0, 1)) for _ in range(120)]
    assert detect_period(vals) is None


def test_detect_period_not_fooled_by_a_ramp():
    """A monotone ramp autocorrelates strongly at every lag — the
    detrend step must keep it from reading as seasonality."""
    vals = [0.5 * i for i in range(120)]
    assert detect_period(vals) is None
    drifting = [0.5 * i + float(RNG.normal(0, 0.2))
                for i in range(120)]
    assert detect_period(drifting) is None


def test_detect_period_needs_two_cycles():
    one_cycle = [math.sin(2 * math.pi * i / 40) for i in range(50)]
    assert detect_period(one_cycle) is None


# -- the method ladder ---------------------------------------------------------


def test_ladder_last_under_min_points():
    fc = forecast_points([(0.0, 2.0), (1.0, 4.0)], horizon_s=5.0)
    assert fc["method"] == "last"
    assert all(p[1] == 4.0 for p in fc["points"])
    assert fc["slope_per_s"] == 0.0


def test_ladder_trend_on_aperiodic_drift():
    pts = [(float(i), 1.0 + 0.5 * i) for i in range(20)]
    fc = forecast_points(pts, horizon_s=5.0)
    assert fc["method"] == "trend"
    assert fc["period_s"] is None
    assert fc["slope_per_s"] == pytest.approx(0.5)
    # the line extrapolates: five steps of dt=1 past the last sample
    assert [round(p[1], 6) for p in fc["points"]] == [
        pytest.approx(1.0 + 0.5 * (19 + h)) for h in range(1, 6)]
    assert fc["sigma"] == pytest.approx(0.0, abs=1e-9)


def test_ladder_seasonal_naive_under_three_cycles():
    pts = _diurnal(cycles=2, period=16, noise=0.0)
    fc = forecast_points(pts, horizon_s=160.0)
    assert fc["method"] == "seasonal_naive"
    assert fc["period_s"] == pytest.approx(16 * 10.0)


def test_ladder_holt_winters_with_three_cycles():
    pts = _diurnal(cycles=4, period=16, noise=0.0)
    fc = forecast_points(pts, horizon_s=160.0)
    assert fc["method"] == "holt_winters"
    assert fc["period_s"] == pytest.approx(16 * 10.0)
    # noise-free periodic signal: tight residuals, tight band
    assert fc["sigma"] < 0.5


def test_empty_series():
    fc = forecast_points([], horizon_s=10.0)
    assert fc["method"] == "empty" and fc["points"] == []
    assert validate_forecast  # (the payload path covers empties)


def test_confidence_band_brackets_the_prediction():
    pts = _diurnal(cycles=4, period=16, noise=0.3)
    fc = forecast_points(pts, horizon_s=160.0)
    assert fc["sigma"] > 0
    for t, yhat, lo, hi in fc["points"]:
        assert lo <= yhat <= hi
        assert hi - yhat == pytest.approx(1.96 * fc["sigma"])


def test_horizon_bounds_the_forecast_grid():
    """points never extend past horizon_s beyond the last sample (the
    chaos drill's lead-time invariant leans on this)."""
    pts = _diurnal(cycles=4, period=16, noise=0.0)
    fc = forecast_points(pts, horizon_s=80.0)
    last_ts = pts[-1][0]
    assert fc["points"][0][0] > last_ts
    assert fc["points"][-1][0] <= last_ts + 80.0 + fc["dt"]


def test_resample_carries_gaps_forward():
    """A missed pump must not shift every later sample's phase: the
    gap is filled with the previous value at the median-dt grid."""
    pts = [(float(i), float(i)) for i in range(10)]
    del pts[5]  # one missed pump
    fc = forecast_points(pts, horizon_s=3.0)
    assert fc["dt"] == 1.0
    assert fc["last_ts"] == 9.0


# -- holdout accuracy ----------------------------------------------------------


def test_seasonal_forecast_beats_persistence_on_holdout():
    """The accuracy claim bench_serve --forecast gates: on a held-out
    cycle of a periodic load curve, the seasonal forecast's MAE beats
    last-value persistence."""
    rng = np.random.default_rng(7)
    period, dt, cycles = 24, 10.0, 5
    pts = _diurnal(cycles=cycles, period=period, dt=dt, rng=rng)
    train = pts[:-period]
    test = pts[-period:]
    fc = forecast_points(train, horizon_s=period * dt)
    assert fc["method"] in ("holt_winters", "seasonal_naive")
    pred = {round(p[0], 6): p[1] for p in fc["points"]}
    matched = [(v, pred[round(t, 6)]) for t, v in test
               if round(t, 6) in pred]
    assert len(matched) == period
    mae = sum(abs(v - p) for v, p in matched) / len(matched)
    naive = train[-1][1]
    naive_mae = sum(abs(v - naive) for v, _ in matched) / len(matched)
    assert mae < naive_mae / 2  # at least 2x better than persistence


# -- forecaster queries --------------------------------------------------------


def _store_with(series):
    t = {"now": 0.0}
    store = TimeseriesStore(clock=lambda: t["now"])
    for name, pts in series.items():
        for ts, v in pts:
            store.record_gauge(name, v, t=ts)
            t["now"] = max(t["now"], ts)
    return store, t


def test_predicted_hot_ranks_by_predicted_peak():
    hot = [(float(10 * i), 5.0 + 3.0 * math.sin(2 * math.pi * i / 16))
           for i in range(64)]
    cold = [(float(10 * i), 0.5) for i in range(64)]
    store, _ = _store_with({"heat:'a'": hot, "heat:'b'": cold,
                            "handle_heat:default:'a'": hot,
                            "queue_depth": hot})  # not a heat series
    f = Forecaster(store)
    rows = f.predicted_hot(k=4, horizon_s=160.0)
    assert [r["series"] for r in rows[:2]] == [
        "handle_heat:default:'a'", "heat:'a'"]  # tie -> name order
    assert rows[0]["handle"] == "default:'a'"
    assert rows[1]["handle"] == "'a'"
    assert all(r["series"] != "queue_depth" for r in rows)
    assert rows[0]["predicted_peak"] > rows[-1]["predicted_peak"]
    assert rows[0]["method"] == "holt_winters"
    # peak_ts lands at the seasonal crest, within the horizon
    assert 630.0 < rows[0]["peak_ts"] <= 630.0 + 160.0 + 10.0


def test_time_to_exhaustion_projects_the_zero_crossing():
    draining = [(float(i), 100.0 - 2.0 * i) for i in range(20)]
    flat = [(float(i), 50.0) for i in range(20)]
    rising = [(float(i), 50.0 + i) for i in range(20)]
    gone = [(float(i), -1.0) for i in range(20)]
    store, _ = _store_with({"hbm_headroom": draining, "flat": flat,
                            "up": rising, "gone": gone})
    f = Forecaster(store)
    # last=62 at t=19, slope -2/s -> 31 s of runway
    assert f.time_to_exhaustion("hbm_headroom") == pytest.approx(
        31.0, rel=0.05)
    assert f.time_to_exhaustion("flat") is None
    assert f.time_to_exhaustion("up") is None
    assert f.time_to_exhaustion("gone") == 0.0
    assert f.time_to_exhaustion("missing") is None


def test_payload_validates_and_is_bounded():
    hot = [(float(10 * i), 5.0 + 3.0 * math.sin(2 * math.pi * i / 16))
           for i in range(64)]
    store, t = _store_with({"heat:'a'": hot,
                            "hbm_headroom": [(float(i), 100.0 - i)
                                             for i in range(20)]})
    store.record_counter("solves_total", 9.0)  # counters not forecast
    f = Forecaster(store)
    doc = f.payload(horizon_s=60.0, k=2, max_series=8, points_limit=3)
    assert doc["schema"] == FORECAST_SCHEMA
    assert validate_forecast(doc) == []
    assert "solves_total" not in doc["series"]
    assert all(len(row["points"]) <= 3 for row in doc["series"].values())
    assert doc["predicted_hot"][0]["series"] == "heat:'a'"
    assert doc["exhaustion"]["hbm_headroom"] == pytest.approx(
        81.0, rel=0.05)
    json.dumps(doc)


def test_validator_rejects_malformed_docs():
    assert validate_forecast([]) != []
    assert validate_forecast({"schema": "wrong"}) != []
    store, _ = _store_with({"g": [(0.0, 1.0), (1.0, 2.0)]})
    doc = Forecaster(store).payload(horizon_s=5.0)
    assert validate_forecast(doc) == []
    bad = json.loads(json.dumps(doc))
    bad["series"]["g"]["method"] = "oracle"
    assert any("method" in e for e in validate_forecast(bad))
    bad2 = json.loads(json.dumps(doc))
    bad2["predicted_hot"] = [{"series": "g"}]  # missing predicted_peak
    assert validate_forecast(bad2) != []


# -- determinism ---------------------------------------------------------------


def test_forecast_is_bit_deterministic():
    """Same ring contents -> same forecast, bit for bit (no RNG, no
    wall clock): the digest contract the chaos drill pins end to end."""
    pts = _diurnal(cycles=4, period=16, noise=0.3,
                   rng=np.random.default_rng(11))
    a = forecast_points(pts, horizon_s=160.0)
    b = forecast_points(list(pts), horizon_s=160.0)
    assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                       sort_keys=True)
