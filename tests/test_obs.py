"""Observability layer (slate_tpu.obs): span model, Chrome-trace
export + schema validation, FLOP ledger, Prometheus exposition, HTTP
endpoint, device-trace merger, and the satellite fixes (Trace lock,
Histogram empty-snapshot nulls).

Reference analog: include/slate/internal/Trace.hh Block/SVG grown into
structured spans + trace_event export; the tester's --timer-level
timers map grown into Metrics histograms + Prometheus text. Fast: the
jax-touching tests use one tiny (n=32, nb=16) LU operator; everything
else is pure-host.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.obs import flops as model_flops
from slate_tpu.obs.tracing import Tracer
from slate_tpu.runtime import Batcher, Executor, Metrics, Session
from slate_tpu.utils import trace as legacy_trace

RNG = np.random.default_rng(23)
N, NB = 32, 16


def _lu_session(tracer=None):
    sess = Session(tracer=tracer)
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    h = sess.register(st.from_dense(a, nb=NB), op="lu")
    return sess, h, a


# -- span model -------------------------------------------------------------


def test_zero_spans_when_tracing_disabled():
    """Acceptance: with tracing disabled the runtime records zero
    spans (the span() fast path hands out one shared no-op object)."""
    tracer = Tracer()  # disabled by default
    assert tracer.span("anything") is obs.NOOP_SPAN  # no allocation
    sess, h, a = _lu_session(tracer=tracer)
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(3)]
    batcher.flush()
    for f in futs:
        f.result(timeout=0)
    assert tracer.spans() == []


def test_span_tree_through_batcher_coalescing():
    """Acceptance: a served solve yields a CONNECTED span tree —
    batched request spans share the batch span as parent; the
    factor/solve (and dispatch/block) spans nest under the batch."""
    tracer = Tracer().on()
    sess, h, a = _lu_session(tracer=tracer)
    batcher = Batcher(sess, max_batch=8, max_wait=10.0)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    batcher.flush()
    for f in futs:
        f.result(timeout=0)
    spans = tracer.spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    (batch,) = by_name["serve.batch"]
    reqs = by_name["serve.request"]
    assert len(reqs) == 4
    # the satellite contract: batched request spans share the batch
    # span as parent (and its trace id)
    assert all(r.parent_id == batch.span_id for r in reqs)
    assert all(r.trace_id == batch.trace_id for r in reqs)
    assert all(r.kind == "request" for r in reqs)
    assert all("queue_s" in r.attrs and "total_s" in r.attrs for r in reqs)
    # factor + solve nest under the batch; dispatch/block under solve
    (solve,) = by_name["serve.solve"]
    (factor,) = by_name["serve.factor"]
    assert solve.parent_id == batch.span_id
    assert factor.parent_id == batch.span_id
    assert by_name["serve.dispatch"][0].parent_id == solve.span_id
    assert by_name["serve.block"][0].parent_id == solve.span_id
    # attribute vocabulary (op, shape, dtype, nb, cache hit/miss, handle)
    assert solve.attrs["op"] == "lu" and solve.attrs["n"] == N
    assert solve.attrs["nb"] == NB and solve.attrs["cache_hit"] is False
    assert "lookahead" in solve.attrs and "handle" in solve.attrs
    # connectedness: one root (the batch), every parent resolves
    ids = {s.span_id for s in spans}
    roots = [s for s in spans if s.parent_id is None]
    assert roots == [batch]
    assert all(s.parent_id in ids for s in spans if s.parent_id is not None)


def test_chrome_trace_schema_valid():
    tracer = Tracer().on()
    sess, h, a = _lu_session(tracer=tracer)
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    for _ in range(2):
        batcher.submit(h, RNG.standard_normal(N))
    batcher.flush()
    obj = obs.chrome_trace(tracer.spans())
    assert obs.validate_chrome_trace(obj) == []
    xev = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert xev, "no events exported"
    # required keys + monotone ts, re-checked directly (not only via
    # the validator under test)
    for e in xev:
        for k in ("ph", "ts", "dur", "pid", "tid", "name", "args"):
            assert k in e
    ts = [e["ts"] for e in xev]
    assert ts == sorted(ts)
    # both views: a thread lane (pid 0) and a phase-class lane (pid 1)
    assert {e["pid"] for e in xev} == {0, 1}
    # round-trips through json
    assert obs.validate_chrome_trace(json.loads(json.dumps(obj))) == []


def test_chrome_trace_validator_catches_violations():
    good = {"ph": "X", "ts": 1.0, "dur": 2.0, "pid": 0, "tid": 0,
            "name": "a", "args": {"span_id": 1, "parent_id": None}}
    assert obs.validate_chrome_trace({"traceEvents": [good]}) == []
    missing = {k: v for k, v in good.items() if k != "dur"}
    assert obs.validate_chrome_trace({"traceEvents": [missing]})
    non_monotone = [dict(good, ts=5.0), dict(good, ts=1.0)]
    assert any("monotone" in e for e in
               obs.validate_chrome_trace({"traceEvents": non_monotone}))
    # child escaping its parent's interval
    parent = dict(good, args={"span_id": 1, "parent_id": None})
    child = dict(good, ts=2.0, dur=10.0,
                 args={"span_id": 2, "parent_id": 1})
    assert any("nested" in e for e in
               obs.validate_chrome_trace({"traceEvents": [parent, child]}))


def test_error_capture_and_slow_request_log():
    tracer = Tracer(slow_threshold=0.0).on()  # everything is "slow"
    sess, h, a = _lu_session(tracer=tracer)
    with Executor(sess, max_batch=4, max_wait=1e-3, retries=0) as ex:
        ok = ex.submit(h, RNG.standard_normal(N))
        assert ok.result(timeout=60).shape == (N,)
        bad = ex.submit("ghost", RNG.standard_normal(N))
        with pytest.raises(Exception):
            bad.result(timeout=60)
    spans = tracer.spans()
    errored = [s for s in spans if s.status == "error"]
    assert errored, "failed dispatch recorded no error spans"
    assert any("unknown handle" in (s.error or "") for s in errored)
    # the slow-request log captured the (threshold-0) request spans
    assert len(tracer.slow_log) >= 1
    assert all(s.kind == "request" for s in tracer.slow_log)


def test_span_bridges_to_legacy_timers_and_svg(tmp_path):
    """The span model subsumes utils.trace.phase: finishing a span
    feeds the coarse timers map and (when Trace is on) the SVG."""
    tracer = Tracer().on()
    legacy_trace.Trace.clear()
    legacy_trace.Trace.on()
    try:
        before = legacy_trace.timers.get("obs.bridge", 0.0)
        with tracer.span("obs.bridge"):
            time.sleep(0.002)
        assert legacy_trace.timers["obs.bridge"] > before
        assert any(e.name == "obs.bridge"
                   for e in legacy_trace.Trace.events())
        path = legacy_trace.Trace.finish(str(tmp_path / "t.svg"))
        assert path and "obs.bridge" in open(path).read()
    finally:
        legacy_trace.Trace.off()
        legacy_trace.Trace.clear()


# -- satellite: Trace thread-safety -----------------------------------------


def test_trace_record_thread_safe_under_concurrent_writers():
    """Two threads hammer Trace.record (as Executor worker + main do)
    while a third snapshots/clears: no lost events in the final tally,
    no exceptions from mutation-during-iteration."""
    legacy_trace.Trace.clear()
    legacy_trace.Trace.on()
    try:
        per_thread = 2000
        errs = []

        def writer(lane):
            try:
                for i in range(per_thread):
                    legacy_trace.Trace.record(f"w{lane}", float(i),
                                              float(i) + 0.5, lane)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        def reader():
            try:
                for _ in range(200):
                    legacy_trace.Trace.events()
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(2)] + [threading.Thread(target=reader)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        assert len(legacy_trace.Trace.events()) == 2 * per_thread
    finally:
        legacy_trace.Trace.off()
        legacy_trace.Trace.clear()


# -- satellite: Histogram empty snapshot ------------------------------------


def test_histogram_empty_snapshot_reports_null_min_max():
    """Empty histogram: min/max/mean are None (JSON null), NOT 0.0 —
    a real zero-latency sample must stay distinguishable."""
    m = Metrics()
    m._hists["empty"] = __import__(
        "slate_tpu.runtime.metrics", fromlist=["Histogram"]).Histogram()
    snap = m.snapshot()["histograms"]["empty"]
    assert snap["count"] == 0
    assert snap["min"] is None and snap["max"] is None
    assert snap["mean"] is None
    # ...and survives JSON round-trip as null
    assert json.loads(json.dumps(snap))["max"] is None
    # a REAL 0.0 sample is distinguishable from emptiness
    m.observe("real", 0.0)
    real = m.snapshot()["histograms"]["real"]
    assert real["min"] == 0.0 and real["max"] == 0.0 and real["count"] == 1


# -- FLOP ledger ------------------------------------------------------------


def test_flop_ledger_centralizes_model_formulas():
    # the formulas the three call sites used to duplicate
    assert model_flops.potrf(100) == 100 ** 3 / 3.0
    assert model_flops.getrf(100) == 2 * 100 ** 3 / 3.0
    assert model_flops.geqrf(200, 100) == 2 * 200 * 100 ** 2 - 2 * 100 ** 3 / 3
    assert model_flops.gemm(2, 3, 4) == 48
    assert model_flops.heev(10) == pytest.approx(4 / 3 * 1000)
    assert model_flops.heev(10, vectors=True) == pytest.approx(
        (4 / 3 + 2) * 1000)
    assert model_flops.svd(10, 10) == pytest.approx(8 / 3 * 1000)
    # the session accounting entry points
    assert model_flops.factor_flops("chol", 64, 64) == 64 ** 3 / 3.0
    assert model_flops.solve_flops("lu", 64, 64, 3) == 2 * 64 * 64 * 3
    assert model_flops.solve_flops("qr", 96, 48, 2) == (
        4 * 96 * 48 - 2 * 48 * 48) * 2
    # the tester's (m, n) table agrees with the canonical functions
    assert model_flops.tester_model("potrf")(64, 64) == model_flops.potrf(64)
    assert model_flops.tester_model("gemm")(8, 4) == 2.0 * 8 * 8 * 4


def test_driver_calls_increment_process_ledger():
    ledger = model_flops.LEDGER
    base = ledger.snapshot()
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    A = st.from_dense(a, nb=NB)
    LU, perm, info = st.lu_factor(A)
    X = st.lu_solve_using_factor(
        LU, perm, st.from_dense(RNG.standard_normal((N, 2)), nb=NB))
    snap = ledger.snapshot()
    assert snap["flops_total"] >= base["flops_total"] + model_flops.getrf(N)
    got = (snap["per_op"].get("lu_factor", 0.0)
           - base["per_op"].get("lu_factor", 0.0))
    assert got == pytest.approx(model_flops.getrf(N))
    got = (snap["per_op"].get("lu_solve_using_factor", 0.0)
           - base["per_op"].get("lu_solve_using_factor", 0.0))
    assert got == pytest.approx(model_flops.solve_flops("lu", N, N, 2))
    # gflops_report joins the ledger against the phase timers map
    rep = ledger.gflops_report({"api.lu_factor": 1.0})
    assert rep["per_op"]["lu_factor"]["gflops"] is not None


# -- Prometheus + HTTP endpoint ---------------------------------------------


def _fake_metrics():
    m = Metrics()
    m.inc("solves_total", 5)
    m.inc("cache_hits", 3)
    m.inc("cache_misses", 2)
    for v in (0.01, 0.02, 0.03):
        m.observe("solve_latency", v)
    return m


def test_prometheus_rendering():
    text = obs.render_prometheus(_fake_metrics())
    assert "# TYPE slate_tpu_solves_total counter" in text
    assert "slate_tpu_solves_total 5.0" in text
    assert 'slate_tpu_solve_latency{quantile="0.5"} 0.02' in text
    assert "slate_tpu_solve_latency_count 3" in text
    assert "slate_tpu_solve_latency_sum" in text
    assert "slate_tpu_cache_hit_rate 0.6" in text
    assert "slate_tpu_driver_flops_total" in text
    # exposition-format discipline: every non-comment line is
    # "name{labels} value"
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        float(value)
        assert name[0].isalpha()
    # empty histograms render no min/max (the null contract)
    m = Metrics()
    from slate_tpu.runtime.metrics import Histogram
    m._hists["empty"] = Histogram()
    text = obs.render_prometheus(m)
    assert "empty_min" not in text and "empty_max" not in text
    assert "slate_tpu_empty_count 0" in text


def test_http_endpoint_serves_metrics_healthz_trace():
    tracer = Tracer().on()
    with tracer.span("serve.solve", op="lu"):
        pass
    m = _fake_metrics()
    with obs.ObsServer(m, tracer=tracer) as srv:
        body = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=10).read().decode()
        assert "slate_tpu_solves_total 5.0" in body
        health = json.loads(urllib.request.urlopen(
            srv.url("/healthz"), timeout=10).read().decode())
        assert health["status"] == "ok" and health["tracing"] is True
        tr = json.loads(urllib.request.urlopen(
            srv.url("/trace.json"), timeout=10).read().decode())
        assert obs.validate_chrome_trace(tr) == []
        assert any(e.get("name") == "serve.solve"
                   for e in tr["traceEvents"])
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url("/nope"), timeout=10)


def test_session_serve_obs_endpoint():
    sess, h, a = _lu_session()
    sess.solve(h, RNG.standard_normal(N))
    srv = sess.serve_obs()
    try:
        assert srv is sess.serve_obs()  # idempotent
        body = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=10).read().decode()
        assert "slate_tpu_solves_total 1.0" in body
        assert "slate_tpu_jit_cache_misses" in body
    finally:
        sess.close_obs()


# -- compile-time observability ---------------------------------------------


def test_warmup_records_compile_observability():
    sess, h, a = _lu_session()
    sess.warmup(h)
    snap = sess.metrics.snapshot()
    assert snap["counters"]["jit_cache_misses"] >= 2  # factor + solve
    lower = snap["histograms"]["warmup_lower_latency"]
    comp = snap["histograms"]["warmup_compile_latency"]
    assert lower["count"] == 2 and comp["count"] == 2  # factor + solve
    assert lower["min"] > 0 and comp["min"] > 0
    # per-shape compile log: factor program + solve program
    whats = sorted(e["what"] for e in sess.compile_log)
    assert whats == ["factor", "solve"]
    for e in sess.compile_log:
        assert e["op"] == "lu" and e["shape"] and e["lower_s"] > 0


# -- device-trace merger / lookahead overlap --------------------------------


def _dev_event(name, ts_us, dur_us):
    return {"ph": "X", "ts": ts_us, "dur": dur_us, "pid": 9, "tid": 1,
            "name": f"jit__potrf/{name}/fusion.1", "args": {}}


def test_lookahead_overlap_metric():
    # level-1 lookahead tile factor [10, 30] runs under level-0
    # trail_rest [0, 100]: fully hidden. level-2 lookahead [150, 170]
    # has NO concurrent level-1 trail_rest (it ran [100, 140]): exposed.
    events = [
        _dev_event("potrf_l0_trail_rest", 0, 100),
        _dev_event("potrf_l1_tile_lookahead", 10, 20),
        _dev_event("potrf_l1_trail_rest", 100, 40),
        _dev_event("potrf_l2_tile_lookahead", 150, 20),
    ]
    ov = obs.lookahead_overlap(events, driver="potrf")
    assert ov["levels"]["1"]["hidden_fraction"] == pytest.approx(1.0)
    assert ov["levels"]["2"]["hidden_fraction"] == pytest.approx(0.0)
    assert ov["panel_s"] == pytest.approx(40e-6)
    assert ov["hidden_s"] == pytest.approx(20e-6)
    assert ov["overlap_fraction"] == pytest.approx(0.5)
    # a lookahead=0 trace (no lookahead scopes) reports empty, not junk
    ov0 = obs.lookahead_overlap([_dev_event("potrf_l0_trail", 0, 10)])
    assert ov0["levels"] == {} and ov0["overlap_fraction"] == 0.0
    # TPU xplane exports carry the scope in args, not the name
    args_events = [
        {"ph": "X", "ts": 0, "dur": 100, "pid": 9, "tid": 1,
         "name": "fusion.7",
         "args": {"long_name": "jit__potrf/potrf_l0_trail_rest/dot"}},
        {"ph": "X", "ts": 10, "dur": 20, "pid": 9, "tid": 1,
         "name": "fusion.9",
         "args": {"long_name": "jit__potrf/potrf_l1_tile_lookahead/x"}},
    ]
    ova = obs.lookahead_overlap(args_events, driver="potrf")
    assert ova["overlap_fraction"] == pytest.approx(1.0)


def test_merge_traces_rebases_device_lane():
    tracer = Tracer().on()
    with tracer.span("serve.factor"):
        time.sleep(0.001)
    host = obs.chrome_trace(tracer.spans())
    dev = [_dev_event("potrf_l0_panel", 5000, 100)]
    merged = obs.merge_traces(host, dev, anchor="serve.factor")
    ev = merged["traceEvents"]
    dev_x = [e for e in ev if e["pid"] == 2 and e.get("ph") == "X"]
    host_factor = [e for e in ev if e.get("name") == "serve.factor"]
    assert dev_x and host_factor
    # earliest device event aligned onto the anchor span's start
    assert dev_x[0]["ts"] == pytest.approx(host_factor[0]["ts"])
    assert any(e["pid"] == 2 and e.get("name") == "process_name"
               for e in ev)


# -- review-fix regression pins ---------------------------------------------


def test_served_solves_credit_ledger_per_execution():
    """The api.* verbs inside the Session's jitted factor/solve
    programs run only at jax-trace time and credit NOTHING (obs.driver
    is a no-op under a trace); the executed work lands in the process
    ledger as serve.factor/serve.solve — one credit PER solve, not per
    compiled shape."""
    ledger = model_flops.LEDGER
    sess, h, a = _lu_session()
    base = ledger.snapshot()["per_op"].get("serve.solve", 0.0)
    n_solves = 4
    for _ in range(n_solves):
        sess.solve(h, RNG.standard_normal(N))
    got = ledger.snapshot()["per_op"]["serve.solve"] - base
    assert got == pytest.approx(
        n_solves * model_flops.solve_flops("lu", N, N, 1))


def test_start_span_accepts_noop_parent():
    """A parent captured while tracing was off is the shared NOOP span
    (e.g. the Batcher's batch context before on()); start_span must
    treat it like an absent parent, not dereference its trace_id."""
    t = Tracer().on()
    sp = t.start_span("req", parent=obs.NOOP_SPAN)
    assert sp is not None and sp.parent_id is None
    t.finish_span(sp, parent=obs.NOOP_SPAN)  # finish side stays guarded
    assert t.spans()[0].parent_id is None


def test_render_prometheus_falsy_ledger_disables_section():
    text = obs.render_prometheus(Metrics(), ledger=False)
    assert "driver_flops" not in text
    assert "slate_tpu_uptime_seconds" in text


def test_legacy_timers_accumulate_thread_safe():
    """timers[k] += d is a load-add-store interleaving hazard across
    the Executor worker and submitting threads; add_timer serializes
    it, so the concurrent sum must be exact."""
    key = "obs_test_timer_race"
    legacy_trace.timers.pop(key, None)
    per_thread, dur = 2000, 0.001
    def work():
        for _ in range(per_thread):
            legacy_trace.add_timer(key, dur)
    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    got = legacy_trace.timers.pop(key)
    assert got == pytest.approx(4 * per_thread * dur)


def test_band_flop_models_consistent_across_verbs():
    """_band_of understands every band container (it used to return 0
    for band-kind TiledMatrix), and chol_factor/chol_solve on the SAME
    HermitianBand operand credit the same kd-band model (chol_solve
    used to credit dense potrf beside chol_factor's band_factor)."""
    from slate_tpu.api import _band_of
    from slate_tpu.linalg.band_packed import PackedBand
    kd, n, nb = 2, 16, 8
    a = np.eye(n) * (n + 4.0)
    for d in range(1, kd + 1):
        a += np.diag(np.ones(n - d), -d) + np.diag(np.ones(n - d), d)
    H = st.hermitian_band(a, nb, kd, st.Uplo.Lower)
    Bk = st.band(a, nb, 1, 2)
    pb = PackedBand(np.zeros((kd + 1, n)), n, kd, 0, hermitian=True)
    assert _band_of(H) == kd        # was 0 (TiledMatrix fell through)
    assert _band_of(Bk) == 3        # Band kind: kl+ku
    assert _band_of(pb) == kd       # packed hermitian-lower unchanged
    ledger = model_flops.LEDGER
    b0 = ledger.snapshot()["per_op"]
    st.chol_factor(H)
    f_factor = (ledger.snapshot()["per_op"]["chol_factor"]
                - b0.get("chol_factor", 0.0))
    assert f_factor == pytest.approx(model_flops.band_factor(n, kd))
    B = st.from_dense(RNG.standard_normal((n, 2)), nb=nb)
    b1 = ledger.snapshot()["per_op"]
    st.chol_solve(H, B)
    f_solve = (ledger.snapshot()["per_op"]["chol_solve"]
               - b1.get("chol_solve", 0.0))
    assert f_solve == pytest.approx(
        model_flops.band_factor(n, kd)
        + model_flops.solve_flops("band_chol", n, n, 2, band=kd))


def test_band_factor_credits_ledger_once():
    """Band factors run through the EAGER api verbs (whose driver hook
    credits the ledger); Session.factor must not credit serve.factor on
    top — one band factorization, exactly one ledger credit."""
    from slate_tpu.linalg.band_packed import pb_pack
    n, kd = 32, 2
    a = np.eye(n) * (n + 4.0)
    for d in range(1, kd + 1):
        a += np.diag(np.ones(n - d), -d) + np.diag(np.ones(n - d), d)
    sess = Session()
    h = sess.register(pb_pack(a, kd), op="auto")
    base = model_flops.LEDGER.snapshot()["flops_total"]
    sess.factor(h)
    delta = model_flops.LEDGER.snapshot()["flops_total"] - base
    assert delta == pytest.approx(model_flops.band_factor(n, kd))


def test_errored_attempt_trace_stays_validly_nested():
    """A failed dispatch attempt closes its request spans INSIDE the
    batch span's scope (Batcher.run) — children ending after their
    parent used to fail the package's own Chrome-trace nesting check
    on any retried workload."""
    tracer = Tracer().on()
    sess, h, a = _lu_session(tracer=tracer)
    calls = {"n": 0}
    orig = sess.solve_matrix
    def flaky(handle, B):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient tunnel failure")
        return orig(handle, B)
    sess.solve_matrix = flaky
    from slate_tpu.runtime import Executor
    with Executor(sess, max_batch=4, max_wait=0.01, retries=2) as ex:
        futs = [ex.submit(h, RNG.standard_normal(N)) for _ in range(3)]
        for f in futs:
            f.result(timeout=120)
    assert calls["n"] == 2  # one failure, one retry
    spans = tracer.spans()
    errored = {s.name for s in spans if s.status == "error"}
    assert "serve.batch" in errored and "serve.request" in errored
    assert obs.validate_chrome_trace(obs.chrome_trace(spans)) == []


# -- round 12: request lifecycle stages, backpressure, padding waste --------


def test_lifecycle_stage_histograms_with_exemplar_trace_ids():
    """Tentpole (c): a served request decomposes into per-stage
    histograms (queue wait, batch formation, dispatch, device execute,
    reply), each carrying the worst sample's exemplar trace-id — the
    join key from /metrics back into the trace."""
    tracer = Tracer().on()
    sess, h, a = _lu_session(tracer=tracer)
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(3)]
    batcher.flush()
    for f in futs:
        f.result(timeout=0)
    snap = sess.metrics.snapshot()
    hists = snap["histograms"]
    assert hists["stage_queue_wait"]["count"] == 3   # one per request
    assert hists["stage_batch_form"]["count"] == 1   # one per batch
    assert hists["stage_dispatch"]["count"] == 1
    assert hists["stage_device_execute"]["count"] == 1
    assert hists["stage_reply"]["count"] == 1
    batch = [s for s in tracer.spans() if s.name == "serve.batch"][0]
    for stage in ("stage_queue_wait", "stage_batch_form", "stage_reply"):
        assert hists[stage]["exemplar"]["trace_id"] == batch.trace_id
    # dispatch/execute exemplars come from the solve span's trace —
    # the same trace (solve nests under the batch)
    assert hists["stage_dispatch"]["exemplar"]["trace_id"] == \
        batch.trace_id
    # the exemplar renders as a plain gauge in the exposition
    prom = obs.render_prometheus(sess.metrics, ledger=False,
                                 bytes_ledger=False)
    assert "slate_tpu_stage_queue_wait_exemplar_trace_id" in prom
    tracer.off()


def test_stage_histograms_populate_with_tracing_off():
    """The stage decomposition is metrics, not tracing: with the
    tracer disabled the histograms still fill (exemplar absent)."""
    sess, h, a = _lu_session()
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    batcher.submit(h, RNG.standard_normal(N))
    batcher.flush()
    hists = sess.metrics.snapshot()["histograms"]
    assert hists["stage_dispatch"]["count"] == 1
    assert hists["stage_dispatch"]["exemplar"] is None


def test_backpressure_gauges_track_queue_state():
    """Satellite: queue depth, queued buckets, oldest-request age and
    max per-bucket backlog are /metrics gauges, updated on every
    enqueue/pop — plus the labeled per-bucket breakdown."""
    sess, h, a = _lu_session()
    batcher = Batcher(sess, max_batch=8, max_wait=10.0)
    for _ in range(3):
        batcher.submit(h, RNG.standard_normal(N))
    m = sess.metrics
    assert m.get_gauge("queue_depth") == 3.0
    assert m.get_gauge("queued_buckets") == 1.0
    assert m.get_gauge("max_bucket_backlog") == 3.0
    assert m.get_gauge("oldest_request_age_s") >= 0.0
    bp = batcher.backpressure()
    assert bp["queue_depth"] == 3 and len(bp["per_bucket"]) == 1
    (bucket,) = bp["per_bucket"].values()
    assert bucket["backlog"] == 3 and bucket["oldest_age_s"] >= 0.0
    batcher.flush()
    assert m.get_gauge("queue_depth") == 0.0
    assert m.get_gauge("max_bucket_backlog") == 0.0
    # and the Executor's in-flight gauge exists after a served batch
    from slate_tpu.runtime import Executor
    with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
        ex.submit(h, RNG.standard_normal(N)).result(timeout=120)
        ex.flush()
    assert m.get_gauge("inflight_batches") == 0.0


def test_width_padding_waste_split_exactly():
    """Tentpole (c): pad_widths quantizes 3 coalesced columns to 4 —
    the executed fourth column's flops move to padding_waste_flops /
    the ledger's padding.waste op, solve_flops_total keeps ONLY the
    served columns, and their sum is the executed total."""
    sess, h, a = _lu_session()
    base = model_flops.LEDGER.snapshot()["per_op"].get("padding.waste",
                                                       0.0)
    batcher = Batcher(sess, max_batch=8, max_wait=10.0, pad_widths=True)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(3)]
    batcher.flush()
    for f in futs:
        f.result(timeout=0)
    m = sess.metrics
    per_col = model_flops.solve_flops("lu", N, N, 1)
    assert m.get("padding_waste_flops") == pytest.approx(per_col)
    assert m.get("solve_flops_total") == pytest.approx(3 * per_col)
    assert m.get("flops_total") - m.get("factor_flops_total") == \
        pytest.approx(4 * per_col)  # executed = useful + waste
    assert m.get("solves_total") == 3.0  # client columns only
    assert m.get_gauge("width_bucket_efficiency") == pytest.approx(0.75)
    delta = model_flops.LEDGER.snapshot()["per_op"]["padding.waste"] - base
    assert delta == pytest.approx(per_col)


def test_width_padding_waste_zero_at_pow2_occupancy():
    sess, h, a = _lu_session()
    batcher = Batcher(sess, max_batch=8, max_wait=10.0, pad_widths=True)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    batcher.flush()
    for f in futs:
        f.result(timeout=0)
    assert sess.metrics.get("padding_waste_flops") == 0.0


def test_batch_bucket_padding_waste_counters():
    """The pow2 batch bucket of the small-problem engine: 3 distinct
    operators -> bucket 4 -> one padded lane's factor+solve flops in
    padding_waste_flops; a full 4-bucket credits exactly 0. The
    process ledger's padding.waste op moves at the linalg/batched
    layer (where the padding happens)."""
    nn = 8
    base = model_flops.LEDGER.snapshot()["per_op"].get("padding.waste",
                                                       0.0)
    sess = Session()
    hs = [sess.register(RNG.standard_normal((nn, nn)) + nn * np.eye(nn),
                        op="lu_small") for _ in range(3)]
    xs, infos = sess.solve_small_batched(
        hs, [RNG.standard_normal((nn, 1)) for _ in hs])
    assert infos == [0, 0, 0]
    waste = sess.metrics.get("padding_waste_flops")
    # one padded lane: solve (client width model) + miss-factor share.
    # Session counters live on the round-15 integer flop grid (the
    # attribution conservation invariant — runtime/session.py
    # _factor_flops/_solve_flops wrappers), so the model values are
    # rounded per call before summing.
    assert waste == (round(model_flops.solve_flops("lu", nn, nn, 1))
                     + round(model_flops.getrf(nn)))
    assert sess.metrics.get_gauge("batch_bucket_efficiency") == \
        pytest.approx(0.75)
    assert model_flops.LEDGER.snapshot()["per_op"]["padding.waste"] > base
    full = Session()
    hf = [full.register(RNG.standard_normal((nn, nn)) + nn * np.eye(nn),
                        op="lu_small") for _ in range(4)]
    full.solve_small_batched(hf, [RNG.standard_normal((nn, 1))
                                  for _ in hf])
    assert full.metrics.get("padding_waste_flops") == 0.0
    assert full.metrics.get_gauge("batch_bucket_efficiency") == 1.0


def test_bucket_bytes_split_between_verb_and_padding_waste():
    """_run_bucket splits the executed program's bytes by occupancy:
    verb share + padding.waste share = the full program bytes the
    round-9 crediting used to put on the verb alone."""
    from slate_tpu.linalg import batched as batched_mod
    from slate_tpu.obs import costs as costs_mod
    nn = 8
    a = np.stack([RNG.standard_normal((nn, nn)) + nn * np.eye(nn)
                  for _ in range(3)])
    b = np.stack([RNG.standard_normal((nn, 1)) for _ in range(3)])
    batched_mod.gesv_batched(a, b)  # warm the bucket program
    snap0 = costs_mod.BYTES.snapshot()
    batched_mod.gesv_batched(a, b)
    snap1 = costs_mod.BYTES.snapshot()
    verb = (snap1["per_op"]["gesv_batched"]["bytes"]
            - snap0["per_op"]["gesv_batched"]["bytes"])
    waste = (snap1["per_op"].get("padding.waste", {"bytes": 0.0})["bytes"]
             - snap0["per_op"].get("padding.waste", {"bytes": 0.0})["bytes"])
    if verb + waste > 0:  # XLA:CPU may report no bytes — skip honestly
        assert waste == pytest.approx((verb + waste) * 0.25)
