"""Incremental factor maintenance (round 20): rank-k Cholesky
up/downdates, QR row append/delete, delta-checkpoint replication.

The contract under test is the tentpole's: a mutated operator serves
from an UPDATED resident with zero full refactors on the happy path
(counter-pinned), every degraded path is a counted refactor that never
serves a wrong answer, and replica propagation ships only the blobs an
update changed.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.core.exceptions import SlateError
from slate_tpu.core.tiled_matrix import from_dense, hermitian
from slate_tpu.core.types import Uplo
from slate_tpu.linalg import update as upd
from slate_tpu.obs import numerics as num
from slate_tpu.runtime import checkpoint as ck
from slate_tpu.runtime.faults import FaultInjector, FaultPlan, FaultSpec
from slate_tpu.runtime.fleet import Fleet
from slate_tpu.runtime.session import Session

RNG = np.random.default_rng(20)


def _spd(n, complex_=False):
    a = RNG.standard_normal((n, n))
    if complex_:
        a = a + 1j * RNG.standard_normal((n, n))
    a = a @ a.conj().T + n * np.eye(n)
    return a


def _counters(s):
    return s.metrics.snapshot()["counters"]


class TestCholUpdateKernel:
    @pytest.mark.parametrize("k", [1, 3])
    def test_update_matches_refactor(self, k):
        n = 24
        a = _spd(n)
        w = RNG.standard_normal((n, k))
        l = np.linalg.cholesky(a)
        l2, info = jax.jit(upd.chol_update_dense,
                           static_argnums=(2,))(l, w, +1)
        assert int(info) == 0
        np.testing.assert_allclose(np.tril(np.asarray(l2)),
                                   np.linalg.cholesky(a + w @ w.T),
                                   rtol=1e-10, atol=1e-12)

    def test_complex_update(self):
        n = 16
        a = _spd(n, complex_=True)
        w = RNG.standard_normal((n, 2)) + 1j * RNG.standard_normal((n, 2))
        l = np.linalg.cholesky(a)
        l2, info = upd.chol_update_dense(l, w, +1)
        assert int(info) == 0
        ref = np.linalg.cholesky(a + w @ w.conj().T)
        # column phases are a sweep choice; compare L·Lᴴ
        got = np.tril(np.asarray(l2))
        np.testing.assert_allclose(got @ got.conj().T,
                                   ref @ ref.conj().T,
                                   rtol=1e-10, atol=1e-12)

    def test_downdate_roundtrip_and_indefinite_guard(self):
        n = 20
        a = _spd(n)
        w = RNG.standard_normal((n, 2))
        l = np.linalg.cholesky(a + w @ w.T)
        l2, info = upd.chol_update_dense(l, w, -1)
        assert int(info) == 0
        np.testing.assert_allclose(np.tril(np.asarray(l2)),
                                   np.linalg.cholesky(a),
                                   rtol=1e-8, atol=1e-10)
        # downdating past positivity must FLAG, and stay finite (the
        # guard is what turns this into a counted refactor upstream)
        _, info = upd.chol_update_dense(np.linalg.cholesky(a),
                                        10.0 * w, -1)
        assert int(info) > 0
        assert np.isfinite(np.asarray(_)).all()

    def test_batched_matches_single(self):
        n, k, B = 16, 2, 3
        ls = np.stack([np.linalg.cholesky(_spd(n)) for _ in range(B)])
        ws = RNG.standard_normal((B, n, k))
        lb, infos = upd.chol_update_batched(jnp.asarray(ls),
                                            jnp.asarray(ws), +1)
        assert np.asarray(infos).max() == 0
        for i in range(B):
            l1, _ = upd.chol_update_dense(ls[i], ws[i], +1)
            np.testing.assert_allclose(np.tril(np.asarray(lb[i])),
                                       np.tril(np.asarray(l1)),
                                       rtol=1e-12, atol=1e-13)


class TestSessionCholUpdate:
    def test_serves_without_refactor_counter_pinned(self):
        n, nb = 32, 16
        a = _spd(n)
        s = Session()
        s.register(hermitian(a, nb, Uplo.Lower), op="chol", handle="c")
        s.warmup("c", nrhs=2, update_k=2)
        acc = a.copy()
        b = RNG.standard_normal((n, 2))
        for k in (1, 2):
            w = RNG.standard_normal((n, k))
            out = s.update("c", w)
            assert out["applied"] and not out["refactored"], out
            acc = acc + w @ w.T
            np.testing.assert_allclose(s.solve("c", b),
                                       np.linalg.solve(acc, b),
                                       rtol=1e-9, atol=1e-11)
        c = _counters(s)
        # THE happy-path pin: one initial factorization, zero since
        assert c.get("factors_total") == 1, c
        assert c.get("update_refactors_total", 0) == 0, c
        assert c.get("updates_total") == 2, c
        assert c.get("update_flops_total", 0) > 0, c

    def test_k_bucket_compile_once(self):
        n, nb = 32, 16
        s = Session()
        s.register(hermitian(_spd(n), nb, Uplo.Lower), op="chol",
                   handle="c")
        s.factor("c")
        s.update("c", RNG.standard_normal((n, 3)))
        after_first = _counters(s).get("update_aot_compiles", 0)
        assert after_first == 1
        # k=4 lands in the SAME pow2 bucket as k=3 -> zero new programs
        s.update("c", RNG.standard_normal((n, 4)))
        assert _counters(s).get("update_aot_compiles", 0) == after_first

    def test_pad_parity_odd_n(self):
        n, nb = 20, 16  # npad=32: the update must ignore pad lanes
        a = _spd(n)
        s = Session()
        s.register(hermitian(a, nb, Uplo.Lower), op="chol", handle="c")
        # no resident yet: the update DEFERS (commits the operator,
        # the next factor() absorbs it — no wasted sweep program)
        w0 = RNG.standard_normal((n, 1))
        out = s.update("c", w0)
        assert out["deferred"] and not out["applied"], out
        a0 = a + w0 @ w0.T
        s.factor("c")
        w = RNG.standard_normal((n, 2))
        out = s.update("c", w)
        assert out["applied"], out
        b = RNG.standard_normal(n)
        np.testing.assert_allclose(s.solve("c", b),
                                   np.linalg.solve(a0 + w @ w.T, b),
                                   rtol=1e-9, atol=1e-11)

    def test_indefinite_downdate_is_counted_never_served(self):
        n, nb = 24, 16
        a = _spd(n)
        s = Session()
        s.register(hermitian(a, nb, Uplo.Lower), op="chol", handle="c")
        s.factor("c")
        out = s.update("c", 10.0 * RNG.standard_normal((n, 2)),
                       downdate=True)
        assert out["refactored"] and out["reason"] == "downdate_indefinite"
        c = _counters(s)
        assert c.get("update_downdate_failures_total") == 1, c
        assert c.get("update_refactors_total") == 1, c
        # A' is indefinite: the authoritative refactor reports it and
        # the solve REFUSES — detected, never a wrong answer
        with pytest.raises(SlateError):
            s.solve("c", RNG.standard_normal(n))

    def test_small_batched_verb_matches_refactor(self):
        n = 16
        s = Session()
        mats, hs, ws = [], [], []
        for i in range(3):
            a = _spd(n)
            h = f"h{i}"
            s.register(np.ascontiguousarray(a), op="chol_small",
                       handle=h)
            mats.append(a)
            hs.append(h)
            ws.append(RNG.standard_normal((n, i + 1)))
        outs = s.update_small_batched(hs, ws)
        b = RNG.standard_normal(n)
        for i, h in enumerate(hs):
            assert outs[i]["applied"], outs[i]
            np.testing.assert_allclose(
                s.solve(h, b),
                np.linalg.solve(mats[i] + ws[i] @ ws[i].T, b),
                rtol=1e-9, atol=1e-11)
        assert _counters(s).get("updates_total") == 3

    def test_update_budget_triggers_counted_refactor(self):
        n, nb = 24, 16
        s = Session()
        s.enable_numerics(num.NumericsConfig(update_budget=3.0,
                                             condest_on_factor=False))
        s.register(hermitian(_spd(n), nb, Uplo.Lower), op="chol",
                   handle="c")
        s.factor("c")
        reasons = []
        for _ in range(4):
            out = s.update("c", 1e-3 * RNG.standard_normal((n, 1)))
            reasons.append(out.get("reason"))
        # each rank-1 update weighs >= 1: the 4th crosses budget=3
        assert reasons[:3] == [None, None, None] and \
            reasons[3] == "update_budget", reasons
        assert _counters(s).get("update_budget_refactors_total") == 1

    def test_injected_update_abort_degrades_to_counted_refactor(self):
        n, nb = 24, 16
        a = _spd(n)
        s = Session(faults=FaultInjector(FaultPlan(9, (FaultSpec(
            "update_abort", rate=1.0, count=1),))))
        s.register(hermitian(a, nb, Uplo.Lower), op="chol", handle="c")
        s.factor("c")
        w = RNG.standard_normal((n, 2))
        out = s.update("c", w)
        assert out["refactored"] and out["reason"] == "abort", out
        c = _counters(s)
        assert c.get("update_aborts_total") == 1, c
        # the refactor is the authority: the answer is still right
        b = RNG.standard_normal(n)
        np.testing.assert_allclose(s.solve("c", b),
                                   np.linalg.solve(a + w @ w.T, b),
                                   rtol=1e-9, atol=1e-11)


class TestSessionQrUpdate:
    def test_append_matches_lstsq_zero_compiles_after_warmup(self):
        m, n, nb = 48, 24, 16
        aq = RNG.standard_normal((m, n))
        s = Session()
        s.register(from_dense(aq, nb), op="qr", handle="q")
        s.warmup("q", nrhs=2, update_k=2)
        before = _counters(s).get("aot_compiles", 0)
        u = RNG.standard_normal((2, n))
        out = s.update("q", u)
        assert out["applied"] and not out["refactored"], out
        b = RNG.standard_normal((m + 2, 2))
        xref, *_ = np.linalg.lstsq(np.vstack([aq, u]), b, rcond=None)
        np.testing.assert_allclose(s.solve("q", b), xref,
                                   rtol=1e-8, atol=1e-10)
        c = _counters(s)
        assert c.get("aot_compiles", 0) == before, \
            "append or its solve compiled post-warmup"
        assert c.get("factors_total") == 1, c

    def test_delete_appended_and_back_to_base(self):
        m, n, nb = 32, 16, 16
        aq = RNG.standard_normal((m, n))
        s = Session()
        s.register(from_dense(aq, nb), op="qr", handle="q")
        s.factor("q")
        u = RNG.standard_normal((2, n))
        s.update("q", u)
        out = s.update("q", delete=[m])  # drop the first appended row
        assert out["applied"], out
        b = RNG.standard_normal((m + 1, 1))
        xref, *_ = np.linalg.lstsq(np.vstack([aq, u[1:]]), b,
                                   rcond=None)
        np.testing.assert_allclose(s.solve("q", b), xref,
                                   rtol=1e-8, atol=1e-10)
        out = s.update("q", delete=[m])  # back to the base factors
        assert out["applied"] and out["k_bucket"] == 0, out
        b = RNG.standard_normal((m, 1))
        xref, *_ = np.linalg.lstsq(aq, b, rcond=None)
        np.testing.assert_allclose(s.solve("q", b), xref,
                                   rtol=1e-8, atol=1e-10)
        assert _counters(s).get("factors_total") == 1

    def test_base_row_delete_degrades_to_counted_refactor(self):
        m, n, nb = 32, 16, 16
        aq = RNG.standard_normal((m, n))
        s = Session()
        s.register(from_dense(aq, nb), op="qr", handle="q")
        s.factor("q")
        out = s.update("q", delete=[0])
        assert out["refactored"] and out["reason"] == "base_delete", out
        assert _counters(s).get("update_refactors_total") == 1
        b = RNG.standard_normal((m - 1, 1))
        xref, *_ = np.linalg.lstsq(aq[1:], b, rcond=None)
        np.testing.assert_allclose(s.solve("q", b), xref,
                                   rtol=1e-8, atol=1e-10)


class TestDeltaCheckpoint:
    def _session_with_updates(self):
        n, nb, m = 24, 16, 32
        s = Session()
        a = _spd(n)
        s.register(hermitian(a, nb, Uplo.Lower), op="chol", handle="c")
        aq = RNG.standard_normal((m, n))
        s.register(from_dense(aq, nb), op="qr", handle="q")
        s.factor("c")
        s.factor("q")
        return s, a, aq, n, m

    def test_delta_ships_only_changed_blobs(self, tmp_path):
        s, a, aq, n, m = self._session_with_updates()
        base = str(tmp_path / "base")
        delta = str(tmp_path / "delta")
        base_manifest = ck.save_session(s, base, host="p")
        w = RNG.standard_normal((n, 1))
        u = RNG.standard_normal((1, n))
        s.update("c", w)
        s.update("q", u)
        manifest, stats = ck.save_session_delta(s, delta,
                                                base_manifest, host="p")
        assert stats["reused_blobs"] > 0, stats
        assert stats["sync_bytes"] < stats["full_bytes"], stats
        # the qr append NEVER rewrites the base factors
        qr_rec = [r for r in manifest["records"]
                  if r["handle"] == "q"][0]
        assert any(b.get("base")
                   for b in ck._iter_blob_descs(qr_rec["payload"])), \
            "append rewrote the base factor blobs"
        # restore side: fresh session, bit-identical resident, solve
        # parity with the UPDATED operators, zero refactors
        s2 = Session()
        summary = ck.restore_session_delta(s2, delta, base)
        assert set(summary["restored"]) == {"c", "q"}, summary
        b = RNG.standard_normal((n, 1))
        np.testing.assert_allclose(
            s2.solve("c", b), np.linalg.solve(a + w @ w.T, b),
            rtol=1e-9, atol=1e-11)
        bq = RNG.standard_normal((m + 1, 1))
        xref, *_ = np.linalg.lstsq(np.vstack([aq, u]), bq, rcond=None)
        np.testing.assert_allclose(s2.solve("q", bq), xref,
                                   rtol=1e-8, atol=1e-10)
        assert _counters(s2).get("factors_total", 0) == 0
        for x1, x2 in zip(
                jax.tree_util.tree_leaves(s._cache["q"].payload),
                jax.tree_util.tree_leaves(s2._cache["q"].payload)):
            np.testing.assert_array_equal(np.asarray(x1),
                                          np.asarray(x2))
        assert _counters(s2).get("delta_restores_total") == 1

    def test_delta_schema_validation(self, tmp_path):
        s, *_ = self._session_with_updates()
        base = str(tmp_path / "base")
        delta = str(tmp_path / "delta")
        base_manifest = ck.save_session(s, base)
        manifest, _ = ck.save_session_delta(s, delta, base_manifest)
        assert manifest["schema"] == ck.DELTA_SCHEMA
        assert not ck.validate_manifest(manifest,
                                        schema=ck.DELTA_SCHEMA)
        # a delta manifest is NOT a valid full checkpoint, and a delta
        # cannot chain off another delta
        assert ck.validate_manifest(manifest)
        with pytest.raises(SlateError):
            ck.save_session_delta(s, str(tmp_path / "d2"), manifest)


class TestFleetUpdateReplication:
    def test_update_delta_syncs_replicas_and_survives_failover(self):
        n, nb, m = 24, 16, 32
        f = Fleet({"a": Session(), "b": Session()})
        try:
            aq = RNG.standard_normal((m, n))
            h = f.register(from_dense(aq, nb), op="qr", handle="q")
            f.member(f.placement_of(h)[0]).factor(h)
            assert f.replicate(h) is not None
            u = RNG.standard_normal((2, n))
            out = f.update(h, u)
            assert out["applied"], out
            c = f.snapshot()["counters"]
            assert c.get("fleet_delta_replications_total") == 1, c
            assert c.get("fleet_delta_sync_bytes") \
                < c.get("fleet_full_sync_bytes"), c
            bq = RNG.standard_normal((m + 2, 1))
            xref, *_ = np.linalg.lstsq(np.vstack([aq, u]), bq,
                                       rcond=None)
            replica = f.placement_of(h)[1]
            before = f.member(replica).metrics.snapshot()[
                "counters"].get("factors_total", 0)
            f.kill(f.placement_of(h)[0])
            fut = f.submit(h, bq)
            f.flush()
            np.testing.assert_allclose(fut.result(timeout=60), xref,
                                       rtol=1e-8, atol=1e-10)
            after = f.member(replica).metrics.snapshot()[
                "counters"].get("factors_total", 0)
            assert after == before, \
                "failover refactored a delta-synced replica"
        finally:
            f.close()

    def test_stale_base_falls_back_to_counted_full_transfer(self):
        n, nb = 24, 16
        f = Fleet({"a": Session(), "b": Session()},
                  faults=FaultInjector(FaultPlan(5, (FaultSpec(
                      "replica_stale", rate=1.0, count=1),))))
        try:
            a = _spd(n)
            h = f.register(hermitian(a, nb, Uplo.Lower), op="chol",
                           handle="c")
            f.member(f.placement_of(h)[0]).factor(h)
            f.replicate(h)
            w = RNG.standard_normal((n, 1))
            assert f.update(h, w)["applied"]
            c = f.snapshot()["counters"]
            assert c.get("fleet_delta_base_stale_total") == 1, c
            assert c.get("fleet_full_replications_total") == 1, c
            b = RNG.standard_normal(n)
            xref = np.linalg.solve(a + w @ w.T, b)
            for name_ in ("a", "b"):
                if h in f.member(name_):
                    np.testing.assert_allclose(
                        f.member(name_).solve(h, b), xref,
                        rtol=1e-9, atol=1e-11)
            # the full transfer re-established a trusted base: the
            # NEXT update rides the delta path again
            assert f.update(h, RNG.standard_normal((n, 1)))["applied"]
            c = f.snapshot()["counters"]
            assert c.get("fleet_delta_replications_total") == 1, c
        finally:
            f.close()


class TestUpdateFlopsModels:
    def test_models_positive_and_monotone(self):
        assert num.update_weight(1, 1.0, 10.0) >= 1.0
        from slate_tpu.obs import flops as fl
        assert fl.update_flops("chol", 32, 32, 2) \
            == fl.update_chol(32, 2)
        assert fl.update_flops("qr", 48, 24, 2) \
            == fl.update_qr(48, 24, 2)
        assert fl.update_chol(32, 4) > fl.update_chol(32, 1)
        assert fl.update_qr(48, 24, 4) > fl.update_qr(48, 24, 1)
