"""Mixed-precision resident factors + iterative-refinement serving
(round 13, ISSUE 10 — slate_tpu/refine/).

The acceptance surface: served mixed solves meet the growth-scaled
working-precision bounds across f32/f64 (c128→c64 for the complex
pair) on single-device AND the 8-device mesh; a forced non-convergent
system falls back to a working-precision refactor, returns a correct
solve, and increments ``refine_fallbacks_total``; a bf16-factored
resident charges ~half the f32 factor bytes and a budget sized for N
f32 residents holds ~2N bf16 residents; the batched mixed bucket at
B=1 is bit-identical to the per-request mixed path.

Compile budget: the mesh session is module-scoped (sharded AOT
compiles amortized); the heavier convergence sweeps are ``-m slow``
with a cheap tier-1 sibling pin per class (tier-1 satellite).
"""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.linalg import batched as lb
from slate_tpu.refine import (PolicyTable, RefinePolicy,
                              default_factor_dtype, solve_refined)
from slate_tpu.runtime import Batcher, Session

RNG = np.random.default_rng(41)
N, NB = 48, 16


def _spd(n=N, dtype=np.float32, seed=None):
    rng = RNG if seed is None else np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
        return (a @ a.conj().T + n * np.eye(n)).astype(dtype)
    return (a @ a.T + n * np.eye(n)).astype(dtype)


def _diagdom(n=N, dtype=np.float32, seed=None):
    rng = RNG if seed is None else np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    return (a + n * np.eye(n)).astype(dtype)


def _scaled_err(a, x, b):
    """The tester's growth-agnostic scaled backward error
    ‖b−Ax‖/(ε·n·‖A‖·‖x‖) in f64/c128 — served mixed solves must meet
    the same ≤ 30 bound the tester's mixed rows register."""
    a64 = np.asarray(a, dtype=np.complex128 if np.iscomplexobj(a)
                     else np.float64)
    x64 = np.asarray(x, dtype=a64.dtype)
    b64 = np.asarray(b, dtype=a64.dtype)
    eps = float(np.finfo(np.asarray(a).dtype).eps)
    num = np.linalg.norm(b64 - a64 @ x64, 1)
    den = eps * a64.shape[1] * np.linalg.norm(a64, 1) * max(
        np.linalg.norm(x64, 1), 1e-300)
    return float(num / max(den, 1e-300))


# -- policy -----------------------------------------------------------------


def test_dtype_ladder():
    assert default_factor_dtype("float32") == "bfloat16"
    assert default_factor_dtype("float64") == "float32"
    assert default_factor_dtype("complex128") == "complex64"
    assert default_factor_dtype("complex64") is None


def test_policy_validation_and_hashability():
    pol = RefinePolicy(factor_dtype="bfloat16")
    assert hash(pol) == hash(RefinePolicy(factor_dtype="bfloat16"))
    with pytest.raises(ValueError):  # factor dtype == working dtype
        pol.validate_for("bfloat16")
    with pytest.raises(ValueError):
        RefinePolicy(factor_dtype="float32").validate_for("complex64")
    with pytest.raises(ValueError):
        RefinePolicy(strategy="nope")
    with pytest.raises(ValueError):
        RefinePolicy(max_iters=0)


def test_policy_table_first_match_and_default():
    t = PolicyTable()
    t.add(None, op="lu", n_max=64)          # explicit full-precision hole
    t.add(RefinePolicy(factor_dtype="bfloat16", max_iters=7), op="lu")
    assert t.resolve("lu", 32, "float32") is None
    assert t.resolve("lu", 128, "float32").max_iters == 7
    # no rule -> ladder default; c64 has no ladder entry
    assert t.resolve("chol", 32, "float32").factor_dtype == "bfloat16"
    assert t.resolve("chol", 32, "complex64") is None


# -- engine (eager) ---------------------------------------------------------


@pytest.mark.parametrize("strategy", ["ir", "gmres"])
def test_engine_solve_refined_lu(strategy):
    a = _diagdom(seed=1)
    A = st.from_dense(a, nb=NB)
    b = RNG.standard_normal((N, 2)).astype(np.float32)
    B = st.from_dense(b, nb=NB)
    X, info, iters, conv = solve_refined(
        A, B, op="lu",
        policy=RefinePolicy(factor_dtype="bfloat16", strategy=strategy))
    assert info == 0 and conv and iters >= 1
    assert _scaled_err(a, X.to_numpy(), b) < 30


def test_engine_solve_refined_chol_f64():
    spd = _spd(dtype=np.float64, seed=2)
    A = st.hermitian(np.tril(spd), nb=NB, uplo=st.Uplo.Lower)
    b = np.ones((N, 1))
    X, info, iters, conv = solve_refined(
        A, st.from_dense(b, nb=NB), op="chol",
        policy=RefinePolicy(factor_dtype="float32"))
    assert info == 0 and conv
    assert _scaled_err(spd, X.to_numpy(), b) < 30


# -- batched mixed drivers --------------------------------------------------


# batched tests run at n=32 (= the single-panel small-problem regime,
# default_nb): the fused mixed bucket kernels at multi-panel n compile
# whole-IR-loop graphs that cost minutes of tier-1 budget on this
# host; the multi-panel arm is covered by the slow cross-bucket sweep
BN = 32


@pytest.mark.slow
def test_batched_mixed_correctness_and_per_item_info():
    """Slow (round-18 tier-1 budget: this test pays the first fused
    gesv_mixed_batched bucket compiles of the file). The b1-lane
    bit-identity and per-item-isolation pins moved to the slow lane
    too in round 20 (each fused mixed config is its own ~30 s compile
    on this host); the tier-1 pins for the class are named in their
    docstrings (test_batched.py bit-identity family,
    test_attribution.py grouped-mixed tallies, the counted-fallback
    pins in this file and test_faults.py)."""
    bsz = 5
    a = np.stack([_diagdom(n=BN, seed=10 + i) for i in range(bsz)])
    b = RNG.standard_normal((bsz, BN, 2)).astype(np.float32)
    a_bad = a.copy()
    a_bad[3] = 0.0  # singular item: flags itself, neighbors untouched
    x, info, iters = st.gesv_mixed_batched(a_bad, b, fallback=False)
    info = np.asarray(info)
    assert info[3] > 0 and (info[np.arange(bsz) != 3] == 0).all()
    x, info, iters = st.gesv_mixed_batched(a, b)
    assert (np.asarray(info) == 0).all()
    assert (np.asarray(iters) > 0).all()
    for i in range(bsz):
        assert _scaled_err(a[i], np.asarray(x)[i], b[i]) < 30


@pytest.mark.slow
def test_batched_mixed_b1_bit_identical_to_lane():
    """The linalg/batched contract extended to the mixed kernels: a
    B=1 run is bit-identical to its lane of a bucket (the
    optimization-barrier'd cast-up pins the low-precision rounding —
    without it XLA:CPU fuses the upcast batch-shape-dependently).
    Slow (round-20 tier-1 budget: the two fused mixed-kernel configs
    it compares are ~30 s of XLA:CPU compile each). Tier-1 siblings:
    test_batched.py's bucket_padding/bit-identity pins hold the
    b1-lane contract for the batched kernel family, and
    test_attribution.py::test_grouped_mixed_lane_tenant_tallies
    executes the grouped mixed bucket kernels at the Session seam."""
    bsz = 5
    a = np.stack([_diagdom(n=BN, seed=20 + i) for i in range(bsz)])
    b = RNG.standard_normal((bsz, BN, 2)).astype(np.float32)
    xs, _, _ = lb.gesv_mixed_batched(a, b)
    x1, _, _ = lb.gesv_mixed_batched(a[2:3], b[2:3])
    assert (np.asarray(xs[2]) == np.asarray(x1[0])).all()


@pytest.mark.slow
def test_batched_mixed_b1_bit_identical_chol_slow():
    """Chol arm of the lane bit-identity (tier-1 sibling: the LU arm
    above; the grouped ≡ per-request pin — also exercising refined
    solve kernels at B=1 vs bucket — moved to slow in round 18, its
    tier-1 coverage named in its own docstring)."""
    bsz = 5
    b = RNG.standard_normal((bsz, BN, 2)).astype(np.float32)
    spd = np.stack([_spd(n=BN, seed=30 + i) for i in range(bsz)])
    ys, _, _ = lb.posv_mixed_batched(np.tril(spd), b)
    y1, _, _ = lb.posv_mixed_batched(np.tril(spd)[1:2], b[1:2])
    assert (np.asarray(ys[1]) == np.asarray(y1[0])).all()


@pytest.mark.slow
def test_batched_mixed_fallback_splices_working_precision_slow():
    """A non-convergent item (impossible tolerance) is re-solved at
    working precision by the api fallback and keeps its negative
    iters marker. Slow: the (max_iters=1, tol=1e-14) config is its own
    bucket-program compile; tier-1 pins for the fallback class are
    test_lo_factor_failure_falls_back_per_request and test_faults.py's
    injected-non-convergence counted fallback (the grouped per-item
    isolation pin rides the slow lane since round 20)."""
    bsz = 3
    a = np.stack([_diagdom(n=BN, seed=40 + i) for i in range(bsz)])
    b = RNG.standard_normal((bsz, BN, 2)).astype(np.float32)
    x, info, iters = st.gesv_mixed_batched(a, b, max_iters=1, tol=1e-14)
    iters = np.asarray(iters)
    assert (iters < 0).all()  # nobody converges at tol=1e-14 in 1 iter
    assert (np.asarray(info) == 0).all()
    for i in range(bsz):
        assert _scaled_err(a[i], np.asarray(x)[i], b[i]) < 30


# -- served: single device --------------------------------------------------


@pytest.mark.parametrize("dtype,lo", [(np.float32, "bfloat16"),
                                      (np.float64, "float32")])
def test_served_mixed_chol_meets_bound(dtype, lo):
    spd = _spd(dtype=dtype, seed=3)
    sess = Session()
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=RefinePolicy(factor_dtype=lo))
    b = RNG.standard_normal(N).astype(dtype)
    x = sess.solve(h, b)
    assert _scaled_err(spd, x, b) < 30
    snap = sess.metrics.snapshot()
    assert snap["histograms"]["refine_iterations"]["count"] == 1
    assert snap["counters"]["refine_converged_total"] == 1
    assert snap["counters"].get("refine_fallbacks_total", 0) == 0
    # the resident really is the low-precision factor
    res = sess.factor(h)
    assert str(res.payload[0].dtype) == lo


def test_served_mixed_lu_f32_and_ledger_split():
    a = _diagdom(seed=4)
    sess = Session()
    h = sess.register(st.from_dense(a, nb=NB), op="lu",
                      refine=RefinePolicy(factor_dtype="bfloat16"))
    from slate_tpu.obs.flops import LEDGER
    before = LEDGER.snapshot()["per_op"].get("serve.refine", 0.0)
    b = RNG.standard_normal((N, 2)).astype(np.float32)
    x = sess.solve(h, b)
    assert _scaled_err(a, x, b) < 30
    # useful-vs-refinement split: both ledger ops moved
    per_op = LEDGER.snapshot()["per_op"]
    assert per_op.get("serve.refine", 0.0) > before
    assert sess.metrics.get("refine_flops_total") > 0
    assert sess.metrics.get("solve_flops_total") > 0


def test_served_mixed_complex128_to_complex64():
    spd = _spd(dtype=np.complex128, seed=5)
    sess = Session()
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol",
                      refine=RefinePolicy(factor_dtype="complex64"))
    b = (RNG.standard_normal(N) + 1j * RNG.standard_normal(N))
    x = sess.solve(h, b)
    assert _scaled_err(spd, x, b) < 30
    assert str(sess.factor(h).payload[0].dtype) == "complex64"


def test_served_gmres_strategy():
    a = _diagdom(seed=6)
    sess = Session()
    h = sess.register(st.from_dense(a, nb=NB), op="lu",
                      refine=RefinePolicy(factor_dtype="bfloat16",
                                          strategy="gmres"))
    b = RNG.standard_normal(N).astype(np.float32)
    x = sess.solve(h, b)
    assert _scaled_err(a, x, b) < 30
    assert sess.metrics.snapshot()["histograms"][
        "refine_iterations"]["count"] == 1


def test_register_true_resolves_from_table():
    spd = _spd(seed=7)
    sess = Session(refine_policies=PolicyTable().add(
        RefinePolicy(factor_dtype="bfloat16", max_iters=9), op="chol"))
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=True)
    assert sess._ops[h].refine.max_iters == 9
    b = RNG.standard_normal(N).astype(np.float32)
    assert _scaled_err(spd, sess.solve(h, b), b) < 30


def test_register_refine_rejections():
    sess = Session()
    tall = st.from_dense(RNG.standard_normal((2 * N, N)).astype(
        np.float32), nb=NB)
    with pytest.raises(SlateError):  # qr not refinable
        sess.register(tall, op="qr",
                      refine=RefinePolicy(factor_dtype="bfloat16"))
    spd = _spd()
    with pytest.raises(SlateError):  # factor dtype == working dtype
        sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol",
                      refine=RefinePolicy(factor_dtype="float32"))
    with pytest.raises(SlateError):  # c64 has no ladder entry
        sess.register(
            st.hermitian(np.tril(_spd(dtype=np.complex64)), nb=NB,
                         uplo=st.Uplo.Lower), op="chol", refine=True)
    with pytest.raises(SlateError):  # gmres is dense-single-device only
        sess.register(_spd(), op="chol_small",
                      refine=RefinePolicy(factor_dtype="bfloat16",
                                          strategy="gmres"))


# -- fallback (the acceptance pin) ------------------------------------------


def test_forced_nonconvergence_falls_back_counted():
    """Impossible tolerance ⇒ IR cannot converge ⇒ the Session evicts
    the lo resident, refactors at working precision, serves a CORRECT
    solve, and counts exactly one fallback; the handle serves
    full-precision thereafter."""
    spd = _spd(seed=8)
    sess = Session()
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol",
                      refine=RefinePolicy(factor_dtype="bfloat16",
                                          max_iters=2, tol=1e-14))
    b = RNG.standard_normal(N).astype(np.float32)
    x = sess.solve(h, b)
    assert _scaled_err(spd, x, b) < 30
    assert sess.metrics.get("refine_fallbacks_total") == 1
    # the resident is now the working-precision factor and later
    # solves do not re-count fallbacks
    assert str(sess.factor(h).payload[0].dtype) == "float32"
    sess.solve(h, b)
    assert sess.metrics.get("refine_fallbacks_total") == 1


def test_fallback_disabled_raises():
    spd = _spd(seed=9)
    sess = Session()
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol",
                      refine=RefinePolicy(factor_dtype="bfloat16",
                                          max_iters=1, tol=1e-14,
                                          fallback=False))
    with pytest.raises(SlateError):
        sess.solve(h, RNG.standard_normal(N).astype(np.float32))


@pytest.mark.slow
def test_small_nonconvergence_falls_back_counted():
    """The *_small arm of the same pin. Slow (round-18 tier-1
    budget): the (max_iters=1, tol=1e-14) chol_small bf16 config is
    its own bucket-program compile; the tier-1 sibling
    test_forced_nonconvergence_falls_back_counted pins the counted
    fallback class on the dense path, and the chaos soak's
    refine_no_converge injection exercises the small arm end to end
    in examples/run_tests.py."""
    a = _spd(n=24, seed=10)
    sess = Session()
    h = sess.register(a, op="chol_small",
                      refine=RefinePolicy(factor_dtype="bfloat16",
                                          max_iters=1, tol=1e-14))
    b = RNG.standard_normal(24).astype(np.float32)
    x = sess.solve(h, b)
    assert _scaled_err(a, x, b) < 30
    assert sess.metrics.get("refine_fallbacks_total") == 1
    assert sess._ops[h].refine is None  # deactivated


# -- HBM accounting (the acceptance pin) ------------------------------------


def test_bf16_resident_charges_half():
    spd = _spd(seed=11)
    mixed, full = Session(), Session()
    hm = mixed.register(st.hermitian(np.tril(spd), nb=NB,
                                     uplo=st.Uplo.Lower), op="chol",
                        refine=RefinePolicy(factor_dtype="bfloat16"))
    hf = full.register(st.hermitian(np.tril(spd), nb=NB,
                                    uplo=st.Uplo.Lower), op="chol")
    assert mixed.factor(hm).nbytes * 2 == full.factor(hf).nbytes


@pytest.mark.slow
def test_budget_for_n_f32_residents_holds_2n_bf16():
    """A budget sized for N f32 small residents holds 2N bf16-factored
    ones before eviction (the *_small engine's residents carry no
    analyzed-program transient, so the arithmetic is exact: the
    bf16 LU payload is n²·2 + perm bytes vs n²·4 + perm). Slow
    (round-18 tier-1 budget): the 16-register fill experiment is the
    expensive redundant arm; the tier-1 sibling
    test_small_bf16_resident_charges_half pins the same half-charge
    arithmetic directly (and BENCH_MIXED_r01's residents_ratio column
    records the fit experiment)."""
    n, count = 32, 4
    mats = [_diagdom(n=n, seed=50 + i) for i in range(2 * count)]
    b = RNG.standard_normal(n).astype(np.float32)

    def fill(policy, budget):
        sess = Session(hbm_budget=budget)
        hs = [sess.register(m, op="lu_small", refine=policy)
              for m in mats]
        for h in hs:
            sess.solve(h, b)
        return sess

    probe = Session()
    hp = probe.register(mats[0], op="lu_small")
    f32_bytes = probe.factor(hp).nbytes
    budget = count * f32_bytes
    full = fill(None, budget)
    mixed = fill(RefinePolicy(factor_dtype="bfloat16"), budget)
    assert len(full.cached_handles()) == count
    assert len(mixed.cached_handles()) >= 2 * count - 1
    assert mixed.metrics.get("refine_fallbacks_total") == 0


# -- batched B=1 ≡ per-request (the acceptance pin) -------------------------


@pytest.mark.slow
def test_grouped_mixed_bit_identical_to_per_request():
    """The Batcher's grouped mixed dispatch (ONE batched refined
    program over stacked lo residents) returns bit-identical results
    to the per-request mixed path (the same bucket programs at B=1).
    Slow (round-18 tier-1 budget): tier-1 siblings —
    test_grouped_mixed_per_item_fallback_isolates_neighbors drives
    the SAME grouped mixed dispatch path (with the harder fallback
    branch), test_batched_mixed_b1_bit_identical_to_lane pins the
    B=1 ≡ lane bit-identity of the underlying kernels, and
    test_attribution.py's test_grouped_mixed_lane_tenant_tallies pins
    grouped ≡ per-request tallies at n=8."""
    n = 32
    pol = RefinePolicy(factor_dtype="bfloat16")
    mats = [_diagdom(n=n, seed=60 + i) for i in range(3)]
    bs = [RNG.standard_normal(n).astype(np.float32) for _ in range(3)]

    grouped = Session()
    hs = [grouped.register(m, op="lu_small", refine=pol) for m in mats]
    bat = Batcher(grouped, max_batch=8, max_wait=60.0)
    futs = [bat.submit(h, b) for h, b in zip(hs, bs)]
    bat.flush()
    xs = [f.result() for f in futs]

    for m, b, x in zip(mats, bs, xs):
        per = Session()
        hp = per.register(m, op="lu_small", refine=pol)
        assert (per.solve(hp, b) == x).all()
    snap = grouped.metrics.snapshot()
    assert snap["counters"]["batched_programs"] == 2  # factor + solve
    assert snap["histograms"]["refine_iterations"]["count"] == 3


def test_grouped_mixed_does_not_coalesce_with_plain():
    """Mixed and plain small entries never share a bucket (the policy
    rides in the group key)."""
    sess = Session()
    a = _diagdom(n=32, seed=70)
    hm = sess.register(a, op="lu_small",
                       refine=RefinePolicy(factor_dtype="bfloat16"))
    hp = sess.register(a.copy(), op="lu_small")
    km, kp = sess.small_group_key(hm), sess.small_group_key(hp)
    assert kp == ("lu_small", 32, "float32")  # round-10 pin unchanged
    assert km != kp and km[:3] == kp


@pytest.mark.slow
def test_grouped_mixed_per_item_fallback_isolates_neighbors():
    """One non-convergent item in a grouped mixed bucket takes the
    working-precision fallback alone; its neighbors' solutions are the
    refined ones, bit-identical to a clean grouped run. Slow (round-20
    tier-1 budget: the impossible-tolerance policy is its own grouped
    bucket-program compile). Tier-1 siblings:
    test_lo_factor_failure_falls_back_per_request pins the counted
    working-precision fallback, and test_faults.py::
    test_injected_refine_non_convergence_takes_counted_fallback pins
    non-convergence degrading to a counted fallback at the Session
    seam."""
    n = 32
    pol = RefinePolicy(factor_dtype="bfloat16", max_iters=2, tol=1e-14)
    ok_pol = RefinePolicy(factor_dtype="bfloat16")
    mats = [_diagdom(n=n, seed=80 + i) for i in range(2)]
    bs = [RNG.standard_normal(n).astype(np.float32) for _ in range(2)]
    sess = Session()
    hs = [sess.register(m, op="lu_small", refine=pol) for m in mats]
    xs, infos = sess.solve_small_batched(hs, [b[:, None] for b in bs])
    assert infos == [0, 0]
    assert sess.metrics.get("refine_fallbacks_total") == 2
    for m, b, x in zip(mats, bs, xs):
        assert _scaled_err(m, x[:, 0], b) < 30
    del ok_pol


def _bf16_indefinite_spd(n=16):
    """SPD in f32, exactly singular after bf16 rounding: J + 1e-3·I —
    the bf16 cast rounds the diagonal's 1.001 to 1.0 (eps ≈ 7.8e-3),
    so the low-precision Cholesky fails (info=2) while f32 succeeds."""
    return np.ones((n, n), np.float32) + 1e-3 * np.eye(n,
                                                       dtype=np.float32)


def test_lo_factor_failure_falls_back_per_request():
    """A lo factor that fails outright (bf16-indefinite SPD) takes the
    counted working-precision fallback on the per-request path."""
    a = _bf16_indefinite_spd()
    sess = Session()
    h = sess.register(a, op="chol_small",
                      refine=RefinePolicy(factor_dtype="bfloat16"))
    b = RNG.standard_normal(16).astype(np.float32)
    x = sess.solve(h, b)
    assert _scaled_err(a, x, b) < 30
    assert sess.metrics.get("refine_fallbacks_total") == 1
    assert sess._ops[h].refine is None
    assert str(sess.factor(h).payload[0].dtype) == "float32"


@pytest.mark.slow  # ~12 s of grouped-bucket + per-request compiles
# (round-22 tier-1 budget); tier-1 siblings —
# test_lo_factor_failure_falls_back_per_request pins the counted
# lo-factor fallback, and the grouped-bucket serving path stays pinned
# by test_tenancy.py::test_grouped_tenant_parity_with_policies
def test_grouped_lo_factor_failure_no_cache_poison():
    """Review fix: a failed LOW-precision batched factor in a grouped
    mixed bucket must NOT cache the bad resident or fail futures — the
    bucket degrades to the per-request path, whose factor() owns the
    counted fallback; later per-request solves against the same handle
    serve normally (parity with pure per-request serving)."""
    good = _spd(n=16, seed=90)
    bad = _bf16_indefinite_spd()
    pol = RefinePolicy(factor_dtype="bfloat16")
    sess = Session()
    hg = sess.register(good, op="chol_small", refine=pol)
    hb = sess.register(bad, op="chol_small", refine=pol)
    bs = [RNG.standard_normal((16, 1)).astype(np.float32)
          for _ in range(2)]
    xs, infos = sess.solve_small_batched([hg, hb], bs)
    assert infos == [0, 0]
    assert _scaled_err(good, xs[0][:, 0], bs[0][:, 0]) < 30
    assert _scaled_err(bad, xs[1][:, 0], bs[1][:, 0]) < 30
    assert sess.metrics.get("refine_fallbacks_total") == 1
    # the bad handle's cached resident is the WORKING-precision factor
    # (no poison) and keeps serving per-request
    assert str(sess.factor(hb).payload[0].dtype) == "float32"
    x2 = sess.solve(hb, bs[1][:, 0])
    assert _scaled_err(bad, x2, bs[1][:, 0]) < 30
    assert sess.metrics.get("refine_fallbacks_total") == 1  # no re-count


def test_policy_table_hole_registers_unrefined():
    """Review fix: PolicyTable.add(None, ...) is an explicit
    full-precision carve-out — register(refine=True) against a matched
    hole registers UNREFINED instead of raising the (wrong)
    no-lower-precision error."""
    spd = _spd(seed=91)
    table = PolicyTable()
    table.add(None, op="chol", n_max=1024)
    table.add(RefinePolicy(factor_dtype="bfloat16"))
    sess = Session(refine_policies=table)
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower),
                      op="chol", refine=True)
    assert sess._ops[h].refine is None
    b = RNG.standard_normal(N).astype(np.float32)
    assert _scaled_err(spd, sess.solve(h, b), b) < 30
    assert sess.metrics.snapshot()["histograms"].get(
        "refine_iterations", {}).get("count", 0) == 0
    # lookup() exposes the distinction the register path relies on
    assert table.lookup("chol", N, "float32") == (True, None)
    assert PolicyTable().lookup("lu", N, "complex64")[0] is False


def test_batched_mixed_complex_kind_guards():
    """Review fix: the batched mixed verbs must never silently cast
    complex to real (jax's astype drops the imaginary part). c64 has
    no ladder entry — the default raises; an explicit real factor
    dtype on a complex stack raises; c128 defaults to c64."""
    from slate_tpu.api import _mixed_batched_factor_dtype
    c64 = np.ones((2, 8, 8), np.complex64)
    with pytest.raises(SlateError):
        st.gesv_mixed_batched(c64, np.ones((2, 8, 1), np.complex64))
    with pytest.raises(SlateError):
        st.posv_mixed_batched(c64, np.ones((2, 8, 1), np.complex64),
                              factor_dtype="bfloat16")
    with pytest.raises(SlateError):  # linalg layer guards too
        lb.getrf_mixed_batched(c64, "bfloat16")
    assert _mixed_batched_factor_dtype(
        np.ones((2, 8, 8), np.complex128), None, "t") == "complex64"
    assert _mixed_batched_factor_dtype(
        np.ones((2, 8, 8), np.float64), None, "t") == "float32"


# -- warmup -----------------------------------------------------------------

def test_warmup_covers_refined_programs():
    spd = _spd(seed=12)
    sess = Session()
    h = sess.register(st.hermitian(np.tril(spd), nb=NB,
                                   uplo=st.Uplo.Lower), op="chol",
                      refine=RefinePolicy(factor_dtype="bfloat16"))
    sess.warmup(h)
    compiles = sess.metrics.get("aot_compiles") + sess.metrics.get(
        "factor_aot_compiles")
    b = RNG.standard_normal(N).astype(np.float32)
    sess.solve(h, b)
    sess.solve(h, b)
    after = sess.metrics.get("aot_compiles") + sess.metrics.get(
        "factor_aot_compiles")
    assert after == compiles  # warmup covered start+step+factor


# -- mesh (module-scoped session: sharded AOT compiles amortized) -----------


@pytest.fixture(scope="module")
def mesh_refined(grid2x4):
    spd = _spd(dtype=np.float32, seed=13)
    dd64 = _diagdom(dtype=np.float64, seed=14)
    sess = Session(mesh=grid2x4)
    hc = sess.register(
        st.hermitian(np.tril(spd), nb=8, uplo=st.Uplo.Lower), op="chol",
        refine=RefinePolicy(factor_dtype="bfloat16"))
    hl = sess.register(
        st.from_dense(dd64, nb=8), op="lu",
        refine=RefinePolicy(factor_dtype="float32"))
    return sess, hc, hl, spd, dd64


def test_mesh_served_mixed_f32(mesh_refined):
    sess, hc, _, spd, _ = mesh_refined
    b = RNG.standard_normal(N).astype(np.float32)
    x = sess.solve(hc, b)
    assert _scaled_err(spd, x, b) < 30
    res = sess.factor(hc)
    leaf = res.payload[0].data
    assert str(leaf.dtype) == "bfloat16"
    assert not leaf.sharding.is_fully_replicated
    # per-chip charge is the max shard: total/8 on the even 2x4 grid
    assert res.nbytes == res.nbytes_total // 8


@pytest.mark.slow
def test_mesh_served_mixed_f64(mesh_refined):
    """Slow (round-18 tier-1 budget): the f64 sharded refine
    start/step programs are their own GSPMD compiles; tier-1 sibling
    test_mesh_served_mixed_f32 pins the mesh-refined serving class."""
    sess, _, hl, _, dd64 = mesh_refined
    b = RNG.standard_normal(N)
    x = sess.solve(hl, b)
    assert _scaled_err(dd64, x, b) < 30
    assert str(sess.factor(hl).payload[0].data.dtype) == "float32"


def test_mesh_refined_census_credits_per_execution(mesh_refined):
    """Every refined mesh solve executes analyzed sharded programs:
    the collective census moves per execution, with zero new
    compiles between two identical solves (collective-aware residual
    gemms — the ISSUE 10 mesh acceptance)."""
    sess, hc, _, spd, _ = mesh_refined
    b = RNG.standard_normal(N).astype(np.float32)
    sess.solve(hc, b)
    c0 = sess.metrics.get("collective_bytes_total")
    n0 = sess.metrics.get("aot_compiles") + sess.metrics.get(
        "factor_aot_compiles")
    sess.solve(hc, b)
    c1 = sess.metrics.get("collective_bytes_total")
    n1 = sess.metrics.get("aot_compiles") + sess.metrics.get(
        "factor_aot_compiles")
    assert c1 > c0 and n1 == n0
    steps = [r for r in sess.cost_log if r["what"] == "refine_step"]
    assert steps and any(r["collective_bytes"] > 0 for r in steps)


# -- heavier convergence sweeps (slow; cheap siblings above) ----------------


@pytest.mark.slow
def test_served_mixed_convergence_sweep_slow():
    """Wider (n, dtype, op, strategy) convergence sweep — the cheap
    tier-1 siblings are the parametrized f32/f64 chol test and the
    single lu/gmres tests above."""
    for n in (96, 160):
        for dtype, lo in ((np.float32, "bfloat16"),
                          (np.float64, "float32")):
            spd = _spd(n=n, dtype=dtype, seed=100 + n)
            dd = _diagdom(n=n, dtype=dtype, seed=200 + n)
            for op, a in (("chol", spd), ("lu", dd)):
                for strategy in ("ir", "gmres"):
                    sess = Session()
                    A = (st.hermitian(np.tril(a), nb=32,
                                      uplo=st.Uplo.Lower)
                         if op == "chol" else st.from_dense(a, nb=32))
                    h = sess.register(
                        A, op=op,
                        refine=RefinePolicy(factor_dtype=lo,
                                            strategy=strategy))
                    b = RNG.standard_normal(n).astype(dtype)
                    x = sess.solve(h, b)
                    assert _scaled_err(a, x, b) < 30, (n, dtype, op,
                                                      strategy)


@pytest.mark.slow
def test_batched_mixed_cross_bucket_sweep_slow():
    """Cross-bucket bit-identity at more batch sizes (tier-1 sibling:
    test_batched_mixed_b1_bit_identical_to_lane)."""
    for bsz in (2, 7, 9):
        a = np.stack([_diagdom(seed=300 + i) for i in range(bsz)])
        b = RNG.standard_normal((bsz, N, 2)).astype(np.float32)
        xs, _, _ = lb.gesv_mixed_batched(a, b)
        for i in range(bsz):
            x1, _, _ = lb.gesv_mixed_batched(a[i:i + 1], b[i:i + 1])
            assert (np.asarray(xs[i]) == np.asarray(x1[0])).all()
