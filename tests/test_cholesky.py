"""Cholesky family tests with the reference's residual self-checks.

Reference: test/test_posv.cc — residual ‖B − A·X‖ / (‖A‖·‖X‖·n·ε) and
factor residual ‖A − L·Lᴴ‖ / (‖A‖·n·ε).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import Norm, Options, Uplo
from slate_tpu.matgen import random_spd

RNG = np.random.default_rng(11)


def _residual_factor(a, L):
    l = np.tril(L.to_numpy())
    return (np.linalg.norm(a - l @ l.conj().T, 1)
            / (np.linalg.norm(a, 1) * a.shape[0] * np.finfo(a.real.dtype).eps))


@pytest.mark.parametrize("n,nb", [(50, 16), (64, 16), (33, 8)])
def test_potrf_lower(n, nb):
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=n))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
    L, info = st.potrf(A)
    assert int(info) == 0
    assert _residual_factor(a, L) < 3.0


def test_potrf_upper():
    n = 40
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=2))
    A = st.hermitian(np.triu(a), nb=16, uplo=Uplo.Upper)
    U, info = st.potrf(A)
    assert int(info) == 0
    u = np.triu(U.to_numpy())
    err = np.linalg.norm(a - u.conj().T @ u, 1) / (
        np.linalg.norm(a, 1) * n * np.finfo(float).eps)
    assert err < 3.0


def test_potrf_complex():
    n = 24
    g = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    a = g @ g.conj().T / n + np.eye(n)
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    L, info = st.potrf(A)
    assert int(info) == 0
    assert _residual_factor(a, L) < 3.0


def test_potrf_not_spd_info():
    n = 16
    a = np.eye(n)
    a[5, 5] = -1.0  # indefinite
    A = st.hermitian(a, nb=8, uplo=Uplo.Lower)
    L, info = st.potrf(A)
    assert int(info) == 6  # 1-based index of failing minor


def test_posv_residual():
    n, nrhs = 60, 4
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=5))
    b = RNG.standard_normal((n, nrhs))
    A = st.hermitian(np.tril(a), nb=16, uplo=Uplo.Lower)
    B = st.from_dense(b, nb=16)
    X, info = st.posv(A, B)
    assert int(info) == 0
    x = X.to_numpy()
    res = np.linalg.norm(b - a @ x, 1) / (
        np.linalg.norm(a, 1) * np.linalg.norm(x, 1) * n * np.finfo(float).eps)
    assert res < 3.0


@pytest.mark.slow  # ~7 s mesh posv compile (round-22 tier-1 budget);
# tier-1 siblings — test_posv_residual (posv numerics) and
# test_uneven_grid.py::test_posv_uneven_grid (posv on a mesh)
def test_posv_on_grid(grid2x2):
    n, nrhs = 64, 8
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=9))
    b = RNG.standard_normal((n, nrhs))
    A = st.hermitian(np.tril(a), nb=16, uplo=Uplo.Lower, grid=grid2x2)
    B = st.from_dense(b, nb=16, grid=grid2x2)
    X, info = st.posv(A, B)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b), rtol=1e-8)


def test_posv_jit():
    n, nrhs = 32, 3
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=13))
    b = RNG.standard_normal((n, nrhs))
    A = st.hermitian(np.tril(a), nb=16, uplo=Uplo.Lower)
    B = st.from_dense(b, nb=16)

    @jax.jit
    def solve(A, B):
        return st.posv(A, B)

    X, info = solve(A, B)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b), rtol=1e-8)


def test_trtri_potri():
    n = 28
    t = np.tril(RNG.standard_normal((n, n))) + 4 * np.eye(n)
    T = st.triangular(t, nb=8, uplo=Uplo.Lower)
    Tinv = st.trtri(T)
    np.testing.assert_allclose(np.tril(Tinv.to_numpy()), np.linalg.inv(t),
                               rtol=1e-9, atol=1e-10)
    # potri: A^-1 from factor
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=3))
    A = st.hermitian(np.tril(a), nb=8, uplo=Uplo.Lower)
    L, _ = st.potrf(A)
    Ainv = st.potri(L)
    np.testing.assert_allclose(Ainv.full_dense()[:n, :n], np.linalg.inv(a),
                               rtol=1e-7, atol=1e-9)


def test_posv_mixed():
    n, nrhs = 48, 2
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=21))
    b = RNG.standard_normal((n, nrhs))
    A = st.hermitian(np.tril(a), nb=16, uplo=Uplo.Lower)
    B = st.from_dense(b, nb=16)
    X, info, iters = st.posv_mixed(A, B, factor_dtype=jnp.float32)
    assert int(info) == 0
    assert iters != 0  # at least one refinement step happened
    x = X.to_numpy()
    # converged to double-precision accuracy despite f32 factorization
    res = np.linalg.norm(b - a @ x, np.inf) / (
        np.linalg.norm(a, np.inf) * np.linalg.norm(x, np.inf))
    assert res < 1e-13


@pytest.mark.slow  # ~8 s dispatch-policy probe (round-10 headroom);
# potrf numerics and the fastpaths dispatch probes stay tier-1
def test_potrf_rec_iter_base_dispatch(monkeypatch):
    """Round-5 hybrid dispatch — now the LEGACY arm
    (Options(factor_iter_large=False); the round-6 default routes every
    nt ≤ 64 size straight to the in-place iterative loop): 2x2
    recursion above the crossover, iterative loop as its base case.
    With the crossover lowered to 64, n=128 must split once in
    _potrf_rec and factor each 64-half with _potrf_iter."""
    from slate_tpu.linalg import cholesky as chol_mod

    monkeypatch.setattr(chol_mod, "_POTRF_ITER_BASE", 64)
    calls = {"iter": 0, "rec": 0}
    for name in ("_potrf_iter", "_potrf_rec"):
        orig = getattr(chol_mod, name)
        key = name.split("_")[-1]

        def spy(*a, _o=orig, _k=key, **kw):
            calls[_k] += 1
            return _o(*a, **kw)

        monkeypatch.setattr(chol_mod, name, spy)

    n, nb = 128, 16  # 128 > 64 -> rec splits; 64-halves -> iter
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=77))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
    L, info = st.potrf(A, Options(factor_iter_large=False))
    assert int(info) == 0
    assert calls["rec"] >= 1 and calls["iter"] == 2
    assert _residual_factor(a, L) < 3.0


@pytest.mark.slow  # ~6 s: two n=128 dispatch-variant compiles
# (round-22 tier-1 budget); tier-1 siblings — test_potrf_not_spd_info
# (the info contract) and test_potrf_rec_iter_base_dispatch (the
# hybrid rec->iter dispatch wiring)
def test_potrf_hybrid_info_offset(monkeypatch):
    """Non-SPD pivot inside the SECOND recursion half reports the
    correct absolute 1-based LAPACK info index through the hybrid
    rec->iter dispatch — and identically through the round-6 default
    (iterative in-place) dispatch."""
    from slate_tpu.linalg import cholesky as chol_mod

    monkeypatch.setattr(chol_mod, "_POTRF_ITER_BASE", 64)
    n, nb = 128, 16  # halves cover columns [0,64) [64,128)
    a = np.array(random_spd(n, dtype=jnp.float64, seed=79))
    bad = 100  # 0-based, inside the second half
    a[bad, bad] = -(abs(a).sum())  # dominate: leading minor fails there
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
    L, info = st.potrf(A, Options(factor_iter_large=False))
    assert int(info) == bad + 1
    L, info = st.potrf(A)  # round-6 default: iterative in-place loop
    assert int(info) == bad + 1


def test_potrf_complex_ignores_imag_diagonal():
    """zpotrf contract: imaginary parts of the diagonal are assumed
    zero and ignored. The de-mirrored driver (round 5) must realify
    explicitly — full_dense used to do it implicitly."""
    n, nb = 96, 32
    x = RNG.standard_normal((n, n)) + 1j * RNG.standard_normal((n, n))
    a = (x @ x.conj().T + n * np.eye(n)).astype(np.complex128)
    stray = np.tril(a).copy()
    stray[np.arange(n), np.arange(n)] += 1j * RNG.standard_normal(n)
    A = st.hermitian(stray, nb=nb, uplo=Uplo.Lower)
    L, info = st.potrf(A)
    assert int(info) == 0
    l = L.to_numpy()
    r = np.linalg.norm(a - l @ l.conj().T) / (
        n * np.finfo(np.float64).eps * np.linalg.norm(a))
    assert r < 10
