"""The round-21 autotuner: committed tuning tables, consultation
seams, the online shadow tuner, and the offline search contract.

Covers the PR's pinned claims:

- first-match (op, n-bucket, dtype, platform) resolution with the
  documented fallback (no match / no table → caller keeps defaults);
- ZERO behavior change with no table: the register seam is one
  ``tuning is None`` check (``entry.opts is sess.opts`` — not even an
  allocation) and deactivating a table restores bit-identical solves;
- tuned registration stamps provenance into span attrs and the
  cost_log, and the serve path after warmup is zero new compiles;
- ``Options.lookahead`` depths > 1 clamp to 1 with a one-time warning
  and a bit-identical schedule; negative depths are rejected;
- shadow refinement promotes ONLY on a ≥10% measured win, demotes on
  watchdog re-flag, and an injected fault at the ``tuner.compile``
  site can never fail a live solve;
- the offline search is deterministic under a fixed seed (injected
  pure measure → byte-identical documents, ties to the earlier
  candidate);
- the jax-free validator mirror in tools/bench_gate.py is
  drift-pinned against slate_tpu/tuning/table.py (round-12
  convention: same schema id, same knob vocabulary, same verdict on
  the same malformed documents).

Tuner A/B probes run real programs at n ≤ 48 (tier-1 budget); the
offline search itself never runs here — the committed TUNING_r01.json
is the fixture.
"""

import copy
import dataclasses
import importlib.util
import json
import os
import warnings

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import tuning as tn
from slate_tpu.core import types as types_mod
from slate_tpu.core.types import Options, normalize_lookahead
from slate_tpu.linalg import batched
from slate_tpu.runtime import FaultPlan, FaultSpec, Session
from slate_tpu.tuning import (ShadowTuner, TunedConfig, TuningTable,
                              activate_table, active_table, as_table,
                              table_path, validate_table)
from slate_tpu.tuning.search import config_space, run_search

_ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "_bench_gate", os.path.join(_ROOT, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _isolated_table():
    """Every test starts and ends untuned: the active table is a
    process-global seam (batched._PROGRAMS is process-global), so a
    leaked activation would silently re-tune sibling test files."""
    prev = activate_table(None)
    yield
    activate_table(prev)


def _doc(entries):
    return {"schema": "slate_tpu.tuning_table.v1", "entries": entries}


def _entry(op="lu_small", n_max=64, dtype="*", platform="*", **config):
    return {"op": op, "n_max": n_max, "dtype": dtype,
            "platform": platform, "config": config}


def _spd(n, rng, dtype=np.float32):
    a = rng.standard_normal((n, n)).astype(dtype)
    return (a @ a.T + n * np.eye(n)).astype(dtype)


# -- table: first match, wildcards, fallback ---------------------------------


def test_first_match_wins_in_file_order():
    t = TuningTable(_doc([
        _entry(op="chol", n_max=32, platform="cpu", nb=8),
        _entry(op="chol", n_max=None, platform="*", nb=64),
    ]))
    assert t.resolve("chol", 32, "float32", "cpu").nb == 8
    # past the first row's n_max: falls through to the catch-all
    assert t.resolve("chol", 48, "float32", "cpu").nb == 64
    # unbounded n_max matches any n
    assert t.resolve("chol", 10_000, "float32", "tpu").nb == 64


def test_wildcards_and_no_match_fallback():
    t = TuningTable(_doc([
        _entry(op="lu", n_max=64, dtype="float32", platform="tpu", nb=16),
    ]))
    assert t.resolve("lu", 64, "float32", "tpu").nb == 16
    assert t.resolve("lu", 64, "float64", "tpu") is None   # dtype miss
    assert t.resolve("lu", 64, "float32", "cpu") is None   # platform miss
    assert t.resolve("qr", 64, "float32", "tpu") is None   # op miss
    assert t.resolve("lu", 65, "float32", "tpu") is None   # n > n_max


def test_resolution_is_memoized():
    t = TuningTable(_doc([_entry(op="chol", nb=8)]))
    a = t.resolve("chol", 32, "float32", "cpu")
    t.entries.clear()  # a second scan would now miss
    assert t.resolve("chol", 32, "float32", "cpu") is a


def test_quantum_accessors_default_to_one():
    t = TuningTable(_doc([
        _entry(op="lu_small", batch_quantum=3, width_quantum=3),
        _entry(op="chol_small", nb=8),
    ]))
    assert t.batch_quantum("lu_small", 16, "float32", "cpu") == 3
    assert t.width_quantum("lu_small", 16, "float32", "cpu") == 3
    # matched entry that doesn't set the quantum: plain pow2
    assert t.batch_quantum("chol_small", 16, "float32", "cpu") == 1
    # no match at all: plain pow2
    assert t.batch_quantum("qr_small", 16, "float32", "cpu") == 1


def test_tuned_config_apply_and_label():
    cfg = TunedConfig(nb=16, lookahead=0, source="T#0")
    opts = cfg.apply(Options())
    assert opts.block_size == 16 and opts.lookahead == 0
    # unset knobs keep the caller's values
    assert opts.inner_blocking == Options().inner_blocking
    assert cfg.label() == "T#0[nb=16,lookahead=0]"
    # the all-None config is the identity (same object, no allocation)
    base = Options()
    assert TunedConfig().apply(base) is base


def test_validate_table_negatives():
    good = _doc([_entry(op="chol", nb=16)])
    assert validate_table(good) == []
    assert validate_table([]) != []
    assert validate_table({"schema": "nope", "entries": [_entry()]})
    assert validate_table(_doc([])) != []
    bad_nmax = _doc([_entry(op="chol", n_max=0, nb=16)])
    assert any("n_max" in e for e in validate_table(bad_nmax))
    unknown = _doc([_entry(op="chol", warp_speed=9)])
    assert any("unknown config" in e for e in validate_table(unknown))
    non_int = _doc([_entry(op="chol", nb="big")])
    assert any("non-integer" in e for e in validate_table(non_int))
    missing = _doc([{"op": "chol", "config": {"nb": 16}}])
    assert validate_table(missing) != []


def test_as_table_coercions():
    assert as_table(None) is None and as_table(False) is None
    t = as_table(_doc([_entry(op="chol", nb=8)]))
    assert isinstance(t, TuningTable) and len(t) == 1
    assert as_table(t) is t
    with pytest.raises(TypeError):
        as_table(42)
    with pytest.raises(ValueError):
        as_table({"schema": "nope"})


def test_committed_table_loads_and_resolves():
    """The committed repo-root artifact is the fixture: it validates,
    and resolution over it honors its own platform stamp (a CPU-smoke
    table must never steer another platform's configs)."""
    t = TuningTable.from_path()
    assert validate_table(t.doc) == []
    plat = t.doc["platform"]
    cfg = t.resolve("chol", 64, "float32", plat)
    assert cfg is not None and cfg.nb is not None
    assert cfg.source.startswith(os.path.basename(table_path()))
    assert t.resolve("chol", 64, "float32", "definitely-not-" + plat) \
        is None


# -- the disabled path: zero overhead, bit-identical --------------------------


def test_no_table_zero_overhead_register():
    """With tuning disabled the register seam must not even allocate:
    the entry's Options IS the session's (one `tuning is None`
    check — the round-8 disabled-path discipline)."""
    sess = Session()
    assert sess.tuning is None
    h = sess.register(_spd(16, np.random.default_rng(0)), op="lu_small")
    e = sess._ops[h]
    assert e.opts is sess.opts
    assert e.tuned is None


def test_no_table_batched_helpers_are_defaults():
    assert active_table() is None
    assert batched.resolved_nb("lu_small", 48, np.float32) \
        == batched.default_nb(48)
    assert batched.resolved_nb("lu_small", 48, np.float32, nb=8) == 8
    assert batched.resolved_quantum("lu_small", 48, np.float32) == 1
    assert batched.batch_bucket(5) == 8
    assert batched.batch_bucket(5, 3) == 6


def test_deactivating_table_restores_bit_identical_solves():
    """The pinned fallback: activate a table (different nb, different
    bucket quantum → different compiled programs), deactivate, and
    the untuned solve is BIT-identical to the never-tuned one."""
    rng = np.random.default_rng(7)
    n, bsz = 16, 5
    a = np.stack([_spd(n, rng) for _ in range(bsz)])
    b = rng.standard_normal((bsz, n)).astype(np.float32)
    x0 = np.asarray(batched.posv_batched(a, b)[0])
    activate_table(TuningTable(_doc([
        _entry(op="chol_small", nb=4, batch_quantum=3)])))
    x1 = np.asarray(batched.posv_batched(a, b)[0])
    activate_table(None)
    x2 = np.asarray(batched.posv_batched(a, b)[0])
    assert x0.tobytes() == x2.tobytes()
    # and the tuned arm was still a correct solve
    for i in range(bsz):
        assert np.allclose(a[i] @ x1[i], b[i], atol=1e-3)


def test_batched_resolves_through_active_table():
    t = TuningTable(_doc([_entry(op="lu_small", n_max=32, nb=4,
                                 batch_quantum=3)]))
    activate_table(t)
    assert batched.resolved_nb("lu_small", 16, np.float32) == 4
    # explicit nb always wins over the table
    assert batched.resolved_nb("lu_small", 16, np.float32, nb=8) == 8
    assert batched.resolved_quantum("lu_small", 16, np.float32) == 3
    # past the entry's n_max: defaults again
    assert batched.resolved_nb("lu_small", 64, np.float32) \
        == batched.default_nb(64)
    rng = np.random.default_rng(3)
    a = np.stack([rng.standard_normal((16, 16)).astype(np.float32)
                  + 16 * np.eye(16, dtype=np.float32) for _ in range(5)])
    b = rng.standard_normal((5, 16)).astype(np.float32)
    x = np.asarray(batched.gesv_batched(a, b)[0])
    for i in range(5):
        assert np.allclose(a[i] @ x[i], b[i], atol=1e-3)


# -- session consultation: provenance + zero compiles after warmup -----------


def test_session_register_resolves_and_stamps_provenance():
    rng = np.random.default_rng(11)
    n = 32
    doc = _doc([_entry(op="chol", n_max=64, nb=16, inner_blocking=16,
                       lookahead=0)])
    sess = Session(tuning=doc)
    try:
        spd = _spd(n, rng)
        h = sess.register(st.hermitian(np.tril(spd), nb=16,
                                       uplo=st.Uplo.Lower), op="chol")
        e = sess._ops[h]
        assert e.opts.block_size == 16
        assert e.opts.inner_blocking == 16
        assert e.opts.lookahead == 0
        assert "nb=16" in e.tuned and "lookahead=0" in e.tuned
        sess.warmup(h)
        # tuned provenance rides the cost_log rows...
        assert sess.cost_log
        assert all(r["tuned_config"] == e.tuned for r in sess.cost_log)
        # ...and the span attrs
        assert sess._span_attrs(e, h)["tuned_config"] == e.tuned
        # warmup compiled the TUNED program: the serve path after
        # warmup is zero new compiles (the acceptance pin)
        before = len(sess.compile_log)
        b = rng.standard_normal(n).astype(np.float32)
        x = sess.solve(h, b)
        assert len(sess.compile_log) == before
        assert np.allclose(spd @ np.asarray(x), b, atol=1e-3)
        # an op the table doesn't speak for keeps its defaults
        ge = (rng.standard_normal((n, n))
              + n * np.eye(n)).astype(np.float32)
        h2 = sess.register(st.from_dense(ge, nb=16), op="lu")
        assert sess._ops[h2].tuned is None
    finally:
        activate_table(None)


def test_tuned_width_quantum_seam():
    rng = np.random.default_rng(13)
    sess = Session()
    h = sess.register(_spd(16, rng), op="lu_small")
    assert sess.tuned_width_quantum(h) == 1  # disabled: plain pow2
    doc = _doc([_entry(op="lu_small", n_max=32, width_quantum=3)])
    sess2 = Session(tuning=doc)
    try:
        h2 = sess2.register(_spd(16, rng), op="lu_small")
        assert sess2.tuned_width_quantum(h2) == 3
    finally:
        activate_table(None)


# -- satellite: the lookahead depth contract ---------------------------------


def test_lookahead_negative_rejected():
    with pytest.raises(ValueError):
        normalize_lookahead(-1)


def test_lookahead_deep_clamps_with_one_warning():
    types_mod._LOOKAHEAD_WARNED = False
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert normalize_lookahead(2) == 1
        assert normalize_lookahead(7) == 1
    assert len([x for x in w if "clamps to 1" in str(x.message)]) == 1
    assert normalize_lookahead(0) == 0
    assert normalize_lookahead(1) == 1


def test_lookahead_clamped_schedule_bit_identical():
    """depth-3 used to silently schedule as depth-1; now it clamps —
    and the clamp must be a true no-op vs an explicit depth-1 run."""
    rng = np.random.default_rng(17)
    n = 32
    spd = _spd(n, rng)
    A = st.hermitian(np.tril(spd), nb=16, uplo=st.Uplo.Lower)
    types_mod._LOOKAHEAD_WARNED = True  # quiet; warning pinned above
    l1, i1 = st.potrf(A, opts=Options(block_size=16, lookahead=1))
    l3, i3 = st.potrf(A, opts=Options(block_size=16, lookahead=3))
    assert int(np.asarray(i1)) == int(np.asarray(i3)) == 0
    assert np.asarray(l1.data).tobytes() \
        == np.asarray(l3.data).tobytes()


# -- the online shadow tuner -------------------------------------------------


class _FixedTimes(ShadowTuner):
    """Real A/B executions (the agreement check runs both arms), with
    deterministically injected timings: live arm 1.0, candidate arm
    ``cand_scale`` — the promotion rule under test, not CPU jitter."""

    cand_scale = 0.5

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._mcalls = 0

    def _measure(self, exe, A):
        super()._measure(exe, A)
        self._mcalls += 1
        return 1.0 if self._mcalls % 2 == 1 else float(self.cand_scale)


def _chol_session(rng, n=32, faults=None):
    sess = Session()
    if faults is not None:
        sess.enable_faults(faults)
    spd = _spd(n, rng)
    h = sess.register(st.hermitian(np.tril(spd), nb=16,
                                   uplo=st.Uplo.Lower), op="chol")
    sess.warmup(h)
    return sess, h, spd


def test_shadow_promotes_only_on_win_then_demotes_on_reflag():
    rng = np.random.default_rng(19)
    sess, h, spd = _chol_session(rng)
    tuner = _FixedTimes(sess, probes=1)
    tuner.flag(h)
    assert tuner.poll()["compiled"] == 1
    assert tuner.poll()["promoted"] == 1
    g = sess.metrics.get
    assert g("tuner_shadow_compiles_total") == 1
    assert g("tuner_promotions_total") == 1
    assert sess._ops[h].tuned.startswith("tuner:")
    # promotion installed the shadow program under the session's own
    # key: the recovery refactor and the next solve compile NOTHING
    before = len(sess.compile_log)
    b = rng.standard_normal(32).astype(np.float32)
    x = sess.solve(h, b)
    assert len(sess.compile_log) == before
    assert np.allclose(spd @ np.asarray(x), b, atol=1e-3)
    # watchdog re-flag of a promoted handle: counted demotion back to
    # the previous config, zero new compiles (program still resident)
    tuner.on_anomaly({"n": 32, "op": "chol"})
    assert g("tuner_demotions_total") == 1
    assert sess._ops[h].tuned is None
    sess.factor(h)
    assert len(sess.compile_log) == before
    x2 = sess.solve(h, b)
    assert np.allclose(spd @ np.asarray(x2), b, atol=1e-3)


def test_shadow_sub_bar_win_rejected():
    rng = np.random.default_rng(23)
    sess, h, _spd_ = _chol_session(rng)
    tuner = _FixedTimes(sess, probes=1)
    tuner.cand_scale = 0.95  # a 5% win: below the 10% bar
    tuner.flag(h)
    tuner.poll()
    assert tuner.poll()["rejected"] == 1
    g = sess.metrics.get
    assert g("tuner_promotions_total") == 0
    assert g("tuner_rejections_total") == 1
    assert sess._ops[h].tuned is None  # config untouched


def test_shadow_fault_never_fails_live_and_breaker_opens():
    """Injected compile_stall + dispatch_error at the tuner.compile
    site: the shadow attempt is a counted rejection, the live solve
    between attempts still answers, and consecutive failures open the
    breaker (counted, poll short-circuits)."""
    rng = np.random.default_rng(29)
    sess, h, spd = _chol_session(rng, faults=FaultPlan(seed=5, specs=(
        FaultSpec("compile_stall", rate=1.0, latency_s=1e-3, count=1),
        FaultSpec("dispatch_error", rate=1.0, count=2),
    )))
    tuner = ShadowTuner(sess, breaker_limit=2)
    tuner.flag(h)
    tuner.poll()  # rung 0: injected failure
    g = sess.metrics.get
    assert g("tuner_rejections_total") == 1
    assert not tuner.breaker_open
    tuner.poll()  # rung 1: second injected failure -> breaker
    assert g("tuner_rejections_total") == 2
    assert tuner.breaker_open
    assert g("tuner_breaker_open_total") == 1
    # both fault budgets were consumed AT the tuner.compile site: the
    # live solve never saw one, and it still answers correctly
    b = rng.standard_normal(32).astype(np.float32)
    x = sess.solve(h, b)
    assert np.allclose(spd @ np.asarray(x), b, atol=1e-3)
    assert g("failed_requests_total") == 0
    assert tuner.poll() == {"breaker_open": True, "pending": 1}
    tuner.reset_breaker()
    assert not tuner.breaker_open


def test_shadow_ignores_small_engine_ops():
    rng = np.random.default_rng(31)
    sess = Session()
    h = sess.register(_spd(16, rng), op="lu_small")
    tuner = ShadowTuner(sess)
    tuner.flag(h)
    assert tuner.pending() == 0


def test_watchdog_listener_fires_on_transition_only():
    from slate_tpu.obs.watchdog import Watchdog
    base = {"schema": "slate_tpu.baseline_series.v1", "series": [{
        "kind": "serve", "metric": "serve.solves_per_sec",
        "platform": "tpu", "n": 32, "batch": None, "op": "chol",
        "dtype": None, "best": 100.0, "direction": "higher"}]}
    wd = Watchdog(baseline=base)
    rows = []
    wd.add_listener(rows.append)
    wd.observe("serve.solves_per_sec", 10.0, platform="tpu", n=32,
               op="chol", kind="serve")
    wd.check()
    wd.check()  # persistent anomaly: no second listener call
    assert len(rows) == 1
    assert rows[0]["op"] == "chol" and rows[0]["n"] == 32


def test_watchdog_listener_exception_swallowed():
    from slate_tpu.obs.watchdog import Watchdog
    base = {"schema": "slate_tpu.baseline_series.v1", "series": [{
        "kind": "serve", "metric": "serve.solves_per_sec",
        "platform": "tpu", "n": 32, "batch": None, "op": "chol",
        "dtype": None, "best": 100.0, "direction": "higher"}]}
    wd = Watchdog(baseline=base)
    wd.add_listener(lambda row: 1 / 0)
    got = []
    wd.add_listener(got.append)
    wd.observe("serve.solves_per_sec", 10.0, platform="tpu", n=32,
               op="chol", kind="serve")
    rep = wd.check()  # must not raise; later listeners still run
    assert not rep["ok"] and len(got) == 1


# -- the offline search contract ---------------------------------------------


def _pure_measure(op, n, dtype, config, seed):
    """Deterministic stand-in for measure_config: a pure function of
    the candidate (faster with bigger nb; seed shifts everything)."""
    s = 1e-3 / (config["nb"] + seed + 1)
    return {"seconds_per_iter": s, "model_flops": 1e6,
            "bytes_accessed": 1e5, "compiles": 1, "live_items": 1}


def test_search_deterministic_under_fixed_seed():
    kw = dict(ops=("chol", "lu_small"), n_buckets=(64,),
              dtypes=("float32",), platform="cpu", seed=3,
              measure=_pure_measure)
    d1, d2 = run_search(**kw), run_search(**kw)
    assert json.dumps(d1, sort_keys=True) == json.dumps(d2, sort_keys=True)
    assert validate_table(d1) == []
    assert d1["seed"] == 3 and d1["platform"] == "cpu"
    # argmax: the pure measure makes the biggest nb fastest (the
    # grid caps nb at the n-bucket: 64 for chol, 32 for the small op)
    by_op = {e["op"]: e for e in d1["entries"]}
    assert by_op["chol"]["config"]["nb"] == 64
    assert by_op["lu_small"]["config"]["nb"] == 32
    assert d1["search"]["total_compiles"] == sum(
        e["score"]["compiles"] for e in d1["entries"])


def test_search_ties_break_to_earlier_candidate():
    def flat(op, n, dtype, config, seed):
        return {"seconds_per_iter": 1e-3, "model_flops": 1e6,
                "bytes_accessed": None, "compiles": 1, "live_items": 1}
    doc = run_search(ops=("chol",), platform="cpu", measure=flat)
    first = config_space("chol", 64)[0]
    got = doc["entries"][0]["config"]
    assert got == {k: v for k, v in first.items() if v is not None}


def test_config_space_respects_n_and_quick():
    assert all(c["nb"] <= 32 for c in config_space("chol", 32))
    full = config_space("lu", 256)
    quick = config_space("lu", 256, quick=True)
    assert len(quick) < len(full)
    assert all(c["batch_quantum"] == c["width_quantum"]
               for c in config_space("lu_small", 64))
    with pytest.raises(ValueError):
        config_space("eig", 64)


# -- the jax-free mirror (round-12 drift pin) --------------------------------


def test_tuning_mirror_drift_pinned():
    """bench_gate's standalone validator must stay in lockstep with
    the package's: same schema id, same knob vocabulary, and the same
    verdict on the same malformed documents (the baseline-validator
    precedent). The SERVE_ARTIFACT_SECTIONS twin pin (now including
    'tuning') lives in test_faults.py."""
    from slate_tpu.tuning import table as table_mod
    gate = _bench_gate()
    assert gate.TUNING_SCHEMA == table_mod.TUNING_SCHEMA
    assert tuple(gate.TUNING_CONFIG_KEYS) == tuple(table_mod._CONFIG_FIELDS)
    committed = json.load(open(table_path()))
    malformed = [
        {"schema": "nope", "entries": committed["entries"]},
        _doc([]),
        _doc([_entry(op="chol", warp_speed=9)]),
        _doc([_entry(op="chol", nb="big")]),
        _doc([_entry(op="chol", n_max=0, nb=8)]),
        _doc([{"op": "chol", "config": {"nb": 8}}]),
        _doc([{"op": "chol", "dtype": "*", "platform": "*",
               "config": {}}]),
    ]
    for doc in [committed] + malformed:
        ours = validate_table(copy.deepcopy(doc))
        theirs = []
        try:
            gate._validate_tuning_doc("t", copy.deepcopy(doc))
        except gate.SchemaError as e:
            theirs = [str(e)]
        assert bool(ours) == bool(theirs), (doc, ours, theirs)


def test_committed_table_discovered_by_gate():
    gate = _bench_gate()
    names = [os.path.basename(p) for p in gate.discover(_ROOT)]
    assert "TUNING_r01.json" in names
    assert "BENCH_TUNED_r01.json" in names
