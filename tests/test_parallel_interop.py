"""Collectives layer, explicit SUMMA, and ScaLAPACK/native interop tests.

Reference analogs: the comm layer property tests SURVEY §7.3 calls for
(shard_map collectives vs single-device reference on the virtual CPU
mesh — replacing the reference's `mpirun -np 4` testing), plus
unit-level checks of the scalapack_api-style interchange.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

import slate_tpu as st
from slate_tpu.core.grid import ProcessGrid, ROW_AXIS, COL_AXIS
from slate_tpu.parallel import (bcast_from, gemm_summa, maxloc, reduce_sum,
                                ring_shift)
from slate_tpu.interop import (bc_pack, bc_unpack, from_lapack,
                               from_scalapack, have_native, tile_pack,
                               tile_unpack, to_scalapack)

RNG = np.random.default_rng(77)


def _mesh1d(devices):
    import numpy as onp
    from jax.sharding import Mesh
    return Mesh(onp.asarray(devices[:8]).reshape(8), ("x",))


def test_bcast_from(devices):
    mesh = _mesh1d(devices)
    x = jnp.arange(8.0 * 4).reshape(8, 4)

    @functools.partial(shard_map, mesh=mesh, in_specs=P("x", None),
                       out_specs=P("x", None))
    def f(blk):
        return bcast_from(blk, 3, "x")

    out = np.asarray(f(x))
    for i in range(8):
        np.testing.assert_array_equal(out[i], np.asarray(x)[3])


def test_reduce_and_maxloc(devices):
    mesh = _mesh1d(devices)
    vals = jnp.asarray(RNG.standard_normal((8, 5)))

    @functools.partial(shard_map, mesh=mesh, in_specs=P("x", None),
                       out_specs=P("x", None))
    def f(blk):
        s = reduce_sum(blk, "x")
        gmax, owner, idx = maxloc(jnp.abs(blk[0]), "x")
        return jnp.concatenate(
            [s[0], gmax[None], owner.astype(s.dtype)[None],
             idx.astype(s.dtype)[None]])[None]

    out = np.asarray(f(vals))
    np.testing.assert_allclose(out[0, :5], np.asarray(vals).sum(0),
                               rtol=1e-12)
    flat = np.abs(np.asarray(vals))
    o, i = np.unravel_index(np.argmax(flat), flat.shape)
    assert out[0, 5] == pytest.approx(flat[o, i])
    assert int(out[0, 6]) == o and int(out[0, 7]) == i


def test_ring_shift(devices):
    mesh = _mesh1d(devices)
    x = jnp.arange(8.0)[:, None]

    @functools.partial(shard_map, mesh=mesh, in_specs=P("x", None),
                       out_specs=P("x", None))
    def f(blk):
        return ring_shift(blk, "x", 1)

    out = np.asarray(f(x)).ravel()
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


@pytest.mark.parametrize("shape", [(64, 64, 64), (64, 48, 80)])
def test_gemm_summa(grid2x2, shape):
    m, n, k = shape
    a = RNG.standard_normal((m, k))
    b = RNG.standard_normal((k, n))
    c = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=16, grid=grid2x2)
    B = st.from_dense(b, nb=16, grid=grid2x2)
    C = st.from_dense(c, nb=16, grid=grid2x2)
    out = gemm_summa(1.5, A, B, -0.5, C)
    np.testing.assert_allclose(out.to_numpy(), 1.5 * a @ b - 0.5 * c,
                               rtol=1e-10, atol=1e-10)


def test_gemm_summa_rect_grid(grid2x4):
    m, n, k = 64, 64, 64
    a = RNG.standard_normal((m, k))
    b = RNG.standard_normal((k, n))
    A = st.from_dense(a, nb=8, grid=grid2x4)
    B = st.from_dense(b, nb=8, grid=grid2x4)
    C = st.from_dense(np.zeros((m, n)), nb=8, grid=grid2x4)
    out = gemm_summa(1.0, A, B, 0.0, C)
    np.testing.assert_allclose(out.to_numpy(), a @ b, rtol=1e-10,
                               atol=1e-10)


# -- interop ---------------------------------------------------------------

def test_native_lib_available():
    assert have_native()  # g++ is in the image; the build must succeed


def test_bc_pack_unpack_all_ranks():
    m, n, nb, p, q = 45, 61, 8, 3, 2
    a = RNG.standard_normal((m, n))
    out = np.zeros((m, n))
    for pi in range(p):
        for qi in range(q):
            out = bc_unpack(bc_pack(a, nb, p, q, pi, qi), m, n, nb, p, q,
                            pi, qi, out=out)
    np.testing.assert_array_equal(out, a)


def test_bc_pack_matches_scalapack_definition():
    """bc_pack must produce byte-compatible ScaLAPACK local arrays:
    column-major (numroc × numroc) with the INDXG2P/INDXG2L index maps
    (ScaLAPACK TOOLS; reference wraps such buffers zero-copy in
    Matrix::fromScaLAPACK, include/slate/Matrix.hh:347)."""
    from slate_tpu.interop import numroc
    m, n, nb, p, q = 45, 61, 8, 3, 2
    a = RNG.standard_normal((m, n))
    for pi in range(p):
        for qi in range(q):
            loc = bc_pack(a, nb, p, q, pi, qi)
            assert loc.shape == (numroc(m, nb, pi, p), numroc(n, nb, qi, q))
            assert loc.flags.f_contiguous or 1 in loc.shape
            for gi in range(m):
                for gj in range(n):
                    if (gi // nb) % p == pi and (gj // nb) % q == qi:
                        li = (gi // nb // p) * nb + gi % nb  # INDXG2L − 1
                        lj = (gj // nb // q) * nb + gj % nb
                        assert loc[li, lj] == a[gi, gj]


def test_bc_unpack_flat_with_lld_slack():
    """A flat BLACS buffer with lld > mloc (descriptor LLD_ slack) must
    unpack identically to the exact-size array."""
    from slate_tpu.interop import numroc
    m, n, nb, p, q, pi, qi = 40, 24, 8, 2, 2, 1, 0
    a = RNG.standard_normal((m, n))
    loc = bc_pack(a, nb, p, q, pi, qi)
    mloc, nloc = loc.shape
    lld = mloc + 5
    padded = np.zeros((lld, nloc))
    padded[:mloc] = loc
    out = bc_unpack(padded.ravel(order="F"), m, n, nb, p, q, pi, qi,
                    lld=lld)
    ref = bc_unpack(loc, m, n, nb, p, q, pi, qi)
    np.testing.assert_array_equal(out, ref)


def test_tile_pack_unpack():
    m, n, nb = 37, 29, 8
    a = RNG.standard_normal((m, n))
    t = tile_pack(a, nb)
    assert t.shape == (-(-m // nb), -(-n // nb), nb, nb)
    np.testing.assert_array_equal(tile_unpack(t, m, n), a)
    # tile content spot check
    np.testing.assert_array_equal(t[1, 2, :8, :8], a[8:16, 16:24])


def test_from_to_scalapack_roundtrip(grid2x2):
    m, n, nb, p, q = 40, 56, 8, 2, 2
    a = RNG.standard_normal((m, n))
    A = st.from_dense(a, nb=nb)
    locals_ = to_scalapack(A, p, q)
    B = from_scalapack(locals_, m, n, nb, p, q, grid=grid2x2)
    np.testing.assert_array_equal(B.to_numpy(), a)
    # solve through the interop path end-to-end (scalapack_api analog)
    spd = np.asarray(st.matgen.random_spd(32, dtype=jnp.float64, seed=1))
    S = st.hermitian(np.tril(spd), nb=8, uplo=st.Uplo.Lower)
    locs = to_scalapack(S, p, q)
    S2 = from_scalapack(locs, 32, 32, 8, p, q)
    S2 = st.hermitian(S2.to_numpy(), nb=8, uplo=st.Uplo.Lower)
    rhs = RNG.standard_normal((32, 2))
    X, info = st.posv(S2, st.from_dense(rhs, nb=8))
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(spd, rhs),
                               rtol=1e-8)


def test_from_lapack():
    m, n = 20, 12
    a = np.asfortranarray(RNG.standard_normal((m, n)))
    A = from_lapack(a, nb=8)
    np.testing.assert_array_equal(A.to_numpy(), a)


@pytest.mark.parametrize("dtype", [np.float32, np.complex64,
                                   np.complex128])
def test_bc_pack_unpack_multiprecision(dtype):
    """Round 5: the native block-cyclic packers are element-size
    templated — s/c/z round-trip exactly (byte-compatible with the f64
    golden path's layout)."""
    from slate_tpu.interop import bc_pack, bc_unpack

    rng = np.random.default_rng(17)
    m, n, nb, p, q = 37, 29, 8, 2, 3
    a = rng.standard_normal((m, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n)).astype(a.real.dtype)
    out = np.zeros((m, n), dtype)
    for pi in range(p):
        for qi in range(q):
            loc = bc_pack(a, nb, p, q, pi, qi)
            assert loc.dtype == np.dtype(dtype)
            bc_unpack(loc, m, n, nb, p, q, pi, qi, out=out)
    np.testing.assert_array_equal(out, a)


def test_bc_pack_f32_matches_f64_layout():
    """Same values packed as f32 and f64 land in the same slots (the
    esize-generic kernel preserves the golden-path layout)."""
    from slate_tpu.interop import bc_pack

    rng = np.random.default_rng(18)
    m, n, nb, p, q = 23, 31, 4, 3, 2
    a64 = np.round(rng.standard_normal((m, n)) * 8) / 8  # f32-exact
    a32 = a64.astype(np.float32)
    for pi in range(p):
        for qi in range(q):
            l64 = bc_pack(a64, nb, p, q, pi, qi)
            l32 = bc_pack(a32, nb, p, q, pi, qi)
            np.testing.assert_array_equal(l32.astype(np.float64), l64)


@pytest.mark.parametrize("dtype", [np.float32, np.complex128])
def test_tile_pack_unpack_multiprecision(dtype):
    from slate_tpu.interop import tile_pack, tile_unpack

    rng = np.random.default_rng(19)
    m, n, nb = 21, 13, 8
    a = rng.standard_normal((m, n)).astype(dtype)
    t = tile_pack(a, nb)
    assert t.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(tile_unpack(t, m, n), a)


def test_scalapack_roundtrip_complex(grid2x2):
    """from_scalapack/to_scalapack keep complex dtypes end to end
    (lifts the r4 f64-only restriction, VERDICT missing #4)."""
    import slate_tpu as st
    from slate_tpu.interop import from_scalapack, to_scalapack

    rng = np.random.default_rng(20)
    m, n, nb = 24, 20, 8
    a = (rng.standard_normal((m, n))
         + 1j * rng.standard_normal((m, n))).astype(np.complex64)
    A = st.from_dense(a, nb=nb)
    locals_ = to_scalapack(A, 2, 2)
    assert all(l.dtype == np.complex64 for l in locals_)
    B = from_scalapack(locals_, m, n, nb, 2, 2)
    np.testing.assert_array_equal(np.asarray(B.to_numpy()), a)


def test_tester_origin_scalapack_complex():
    """tester --origin scalapack now runs complex dtypes (r4 raised)."""
    from slate_tpu.tester import Ctx

    ctx = Ctx(m=20, n=20, nb=8, grid=None, dtype=np.complex64, seed=1,
              iters=1, origin="scalapack")
    rng = np.random.default_rng(21)
    a = (rng.standard_normal((20, 20))
         + 1j * rng.standard_normal((20, 20))).astype(np.complex64)
    out = ctx.origin_array(a)
    np.testing.assert_array_equal(np.asarray(out), a)
