"""Multi-process telemetry aggregation (slate_tpu.obs.aggregate +
obs.merge.combine_process_traces).

The acceptance contract: merging two copies of the SAME snapshot
reproduces exactly double every counter (bit-exact float doubling —
x + x is always exact in binary FP), histograms merge count/sum/
min/max correctly, gauges come back labeled per host, the mirrored
derived formulas agree with runtime.Metrics._derive, and the combined
Chrome trace stays schema-valid with disjoint per-process pid
namespaces.
"""

import pytest

from slate_tpu import obs
from slate_tpu.obs import aggregate as agg
from slate_tpu.runtime import Metrics


def _snapshot():
    m = Metrics()
    m.inc("solves_total", 7)
    m.inc("cache_hits", 3)
    m.inc("cache_misses", 1)
    m.inc("solve_flops_total", 0.1 + 0.2)  # a non-representable float
    m.observe("solve_latency", 0.25)
    m.observe("solve_latency", 0.75)
    m.observe("request_latency", 0.5, exemplar=42)
    m.set_gauge("resident_bytes", 1024.0)
    m.set_gauge("hbm_headroom", 5.0)
    return m.snapshot()


# -- counters ----------------------------------------------------------------


def test_same_snapshot_merge_doubles_counters_bit_exactly():
    snap = _snapshot()
    merged = agg.merge_metrics_snapshots([snap, snap])
    for k, v in snap["counters"].items():
        assert merged["counters"][k] == 2 * v  # exact equality, no approx
    assert merged["processes"] == 2
    assert merged["hosts"] == ["proc0", "proc1"]


def test_distinct_snapshots_sum():
    a, b = _snapshot(), _snapshot()
    b["counters"]["solves_total"] = 13.0
    b["counters"]["only_in_b"] = 2.0
    merged = agg.merge_metrics_snapshots([a, b])
    assert merged["counters"]["solves_total"] == 20.0
    assert merged["counters"]["only_in_b"] == 2.0


# -- histograms --------------------------------------------------------------


def test_histogram_merge_counts_sums_extremes():
    snap = _snapshot()
    h = agg.merge_histograms([snap["histograms"]["solve_latency"],
                              snap["histograms"]["solve_latency"]])
    assert h["count"] == 4
    assert h["sum"] == 2 * snap["histograms"]["solve_latency"]["sum"]
    assert h["min"] == 0.25 and h["max"] == 0.75
    assert h["mean"] == pytest.approx(0.5)
    # weighted quantile of identical inputs is the input quantile
    assert h["p99"] == snap["histograms"]["solve_latency"]["p99"]


def test_histogram_merge_handles_empty_and_exemplar():
    empty = Metrics().snapshot()  # no histograms at all
    snap = _snapshot()
    merged = agg.merge_metrics_snapshots([snap, empty])
    assert merged["histograms"]["solve_latency"]["count"] == 2
    ex = merged["histograms"]["request_latency"]["exemplar"]
    assert ex["trace_id"] == 42
    e = agg.merge_histograms([])
    assert e["count"] == 0 and e["min"] is None and e["mean"] is None


# -- gauges ------------------------------------------------------------------


def test_gauges_labeled_per_host_and_summable_totals():
    snap = _snapshot()
    merged = agg.merge_metrics_snapshots([snap, snap], hosts=["h0", "h1"])
    assert merged["gauges_per_host"]["h0"]["resident_bytes"] == 1024.0
    assert merged["gauges_per_host"]["h1"]["hbm_headroom"] == 5.0
    # summable capacity gauges aggregate under fleet_*
    assert merged["gauges"]["fleet_resident_bytes"] == 2048.0
    # headroom is per-chip truth — never summed
    assert "fleet_hbm_headroom" not in merged["gauges"]
    with pytest.raises(ValueError):
        agg.merge_metrics_snapshots([snap, snap], hosts=["only-one"])


# -- derived -----------------------------------------------------------------


def test_merged_derived_matches_runtime_formula():
    """The mirrored derive formulas (module docstring) pinned against
    runtime.Metrics._derive on the merged inputs."""
    snap = _snapshot()
    merged = agg.merge_metrics_snapshots([snap, snap])
    c, h = merged["counters"], merged["histograms"]
    want = Metrics._derive(c["cache_hits"], c["cache_misses"],
                           c["solves_total"], c["solve_flops_total"],
                           h["solve_latency"]["sum"])
    assert merged["derived"] == want


# -- ledgers -----------------------------------------------------------------


def test_flop_and_bytes_ledger_merge():
    fsnap = {"flops_total": 100.0, "per_op": {"serve.solve": 90.0,
                                              "padding.waste": 10.0},
             "calls": {"serve.solve": 3, "padding.waste": 1}}
    merged = agg.merge_flop_snapshots([fsnap, fsnap])
    assert merged["flops_total"] == 200.0
    assert merged["per_op"]["padding.waste"] == 20.0
    assert merged["calls"]["serve.solve"] == 6
    bsnap = {"bytes_total": 50.0, "collective_bytes_total": 8.0,
             "per_op": {"x": {"bytes": 50.0, "collective_bytes": 8.0,
                              "calls": 2}},
             "per_collective": {"all-reduce": {"bytes": 8.0, "count": 4}}}
    bm = agg.merge_bytes_snapshots([bsnap, bsnap])
    assert bm["bytes_total"] == 100.0
    assert bm["per_op"]["x"]["calls"] == 4
    assert bm["per_collective"]["all-reduce"]["count"] == 8


# -- fleet rendering ---------------------------------------------------------


def test_fleet_prometheus_renders_host_labels_and_totals():
    snap = _snapshot()
    fleet = agg.aggregate_processes(
        [snap, snap],
        flop_snaps=[{"flops_total": 5.0, "per_op": {}, "calls": {}}] * 2,
        bytes_snaps=[{"bytes_total": 7.0, "collective_bytes_total": 1.0,
                      "per_op": {}, "per_collective": {}}] * 2,
        hosts=["h0", "h1"])
    text = agg.render_fleet_prometheus(fleet)
    assert 'slate_tpu_resident_bytes{host="h0"} 1024.0' in text
    assert 'slate_tpu_resident_bytes{host="h1"} 1024.0' in text
    assert "slate_tpu_fleet_driver_flops_total 10.0" in text
    assert "slate_tpu_fleet_driver_bytes_total 14.0" in text
    assert "slate_tpu_solves_total 14.0" in text  # summed counter


def test_write_fleet_round_trips(tmp_path):
    import json
    snap = _snapshot()
    fleet = agg.aggregate_processes([snap, snap])
    agg.write_fleet(fleet, json_path=str(tmp_path / "fleet.json"),
                    prom_path=str(tmp_path / "fleet.prom"))
    doc = json.loads((tmp_path / "fleet.json").read_text())
    assert doc["metrics"]["counters"]["solves_total"] == 14.0
    assert "slate_tpu_solves_total" in (tmp_path / "fleet.prom"
                                        ).read_text()


# -- trace combine -----------------------------------------------------------


def _one_trace():
    tracer = obs.Tracer().on()
    with tracer.span("serve.batch", batch_size=2):
        with tracer.span("serve.solve"):
            pass
    tracer.off()
    return obs.chrome_trace(tracer.spans())


def test_combine_process_traces_namespaces_pids_and_ids():
    tr = _one_trace()
    combined = obs.combine_process_traces([tr, tr], ["h0", "h1"])
    assert obs.validate_chrome_trace(combined) == []
    xev = [e for e in combined["traceEvents"] if e.get("ph") == "X"]
    pids = {e["pid"] for e in xev}
    assert pids & {0, 1} and pids & {100, 101}  # disjoint namespaces
    hosts = {e["args"]["host"] for e in xev}
    assert hosts == {"h0", "h1"}
    # span identities are host-prefixed: no cross-process aliasing
    ids = {(e["pid"], e["args"]["span_id"]) for e in xev}
    assert len(ids) == len(xev) // 1  # all distinct per (pid, span)
    assert all(str(e["args"]["span_id"]).startswith(("h0/", "h1/"))
               for e in xev)
    # parent links stay intra-process after prefixing
    for e in xev:
        p = e["args"].get("parent_id")
        if p is not None:
            assert p.split("/")[0] == e["args"]["host"]
    # process_name metadata rewritten per host
    names = [e["args"]["name"] for e in combined["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert any(n.startswith("h0:") for n in names)
    assert any(n.startswith("h1:") for n in names)


# -- partial-host folds (round 17: the crash window) ------------------------


def _attr_snap():
    """A minimal attribution snapshot with one cell."""
    return {
        "schema": "slate_tpu.attribution.v1", "halflife_s": 300.0,
        "tenants": {"t": {"totals": {"solve_flops": 8.0},
                          "handles": {"'h'": {"solve_flops": 8.0}}}},
        "totals": {"solve_flops": 8.0},
    }


def test_attribution_fold_tolerates_partial_host():
    """Satellite pin: a host inside the crash window (live attribution
    snapshot gone, checkpoint survives) folds as a SKIPPED partial
    process — conservation over the surviving snapshots is untouched
    and the partial count is surfaced. Before round 17 only the
    all-or-nothing snapshot_drop case (both sides absent) was pinned."""
    full = agg.merge_attribution_snapshots([_attr_snap(), _attr_snap()])
    part = agg.merge_attribution_snapshots([_attr_snap(), None,
                                            _attr_snap()])
    assert part["partial_processes"] == 1
    assert part["processes"] == 2
    # the fold over the survivors is bit-identical to the no-partial one
    assert part["totals"] == full["totals"]
    assert part["tenants"] == full["tenants"]


def _placement_doc(host, partial=False, heat=1.0):
    doc = {
        "schema": "slate_tpu.placement_snapshot.v2", "host": host,
        "generated_at": 1.0,
        "rows": [{"host": host, "tenant": "t", "handle": "'h'",
                  "op": "chol", "n": 32, "dtype": "float32",
                  "bytes_per_chip": 128, "heat": heat,
                  "last_access": 1.0, "health": "healthy",
                  "condest": None, "growth": None}],
    }
    if partial:
        doc["partial"] = True
    return doc


def test_placement_fold_marks_partial_hosts_and_keeps_rows():
    merged = agg.merge_placement_snapshots(
        [_placement_doc("live0"), _placement_doc("dead0", partial=True),
         None])
    assert merged["partial_hosts"] == ["dead0"]
    assert merged["processes"] == 2  # None tolerated, not counted
    assert {r["host"] for r in merged["rows"]} == {"live0", "dead0"}
    # partial rows still roll up per tenant (labeled, not dropped)
    assert merged["per_tenant"]["t"]["handles"] == 2


def test_placement_from_checkpoint_is_fold_compatible():
    """A checkpoint manifest becomes a schema-shaped partial placement
    doc: handle reprs, heat, health, and blob byte totals carry into
    the fold exactly where live rows put them."""
    manifest = {
        "schema": "slate_tpu.checkpoint.v1", "host": "pX",
        "generated_at": 2.0, "blobs": "blobs",
        "records": [{
            "handle": "d0", "handle_type": "str", "op": "chol",
            "m": 32, "n": 32, "band": 0, "dtype": "float32", "nb": 16,
            "tenant": "t", "refine": None, "mesh": None, "info": 0,
            "heat": 3.5, "last_access": 2.0,
            "health": {"state": "suspect", "condest": 1e9,
                       "growth": None},
            "operator": {"type": "tiled", "data": {
                "blob": "b0.bin", "shape": [32, 32],
                "dtype": "float32", "nbytes": 4096, "sha256": "x"}},
            "payload": {"type": "tuple", "items": [
                {"type": "tiled", "data": {
                    "blob": "b1.bin", "shape": [32, 32],
                    "dtype": "float32", "nbytes": 4096,
                    "sha256": "y"}}]},
        }],
    }
    doc = agg.placement_from_checkpoint(manifest, host="dead1")
    assert doc["partial"] is True and doc["host"] == "dead1"
    row = doc["rows"][0]
    assert row["handle"] == repr("d0")
    assert row["bytes_per_chip"] == 4096  # payload blobs only
    assert row["health"] == "suspect" and row["condest"] == 1e9
    merged = agg.merge_placement_snapshots(
        [_placement_doc("live0"), doc])
    assert merged["partial_hosts"] == ["dead1"]
    assert merged["per_tenant"]["t"]["suspect_handles"] == 1
