"""Round-7 lookahead-pipeline + batched-CALU + mesh-perm tests (ISSUE 3).

Covers:

(a) LOOKAHEAD-1 PIPELINE (Options.lookahead, default 1) — at step k the
    trailing update is split at the next-panel slab and panel k+1 is
    factored between the slab and the remainder, so the serial panel
    chain of step k+1 carries NO data edge to step k's remainder gemms.
    Guarded by: bit-identity lookahead=1 vs lookahead=0 across dtypes
    and the 8-device mesh (the ops are identical — only the order of
    independent ops changes, and gemm column splits leave each output
    element's contraction unchanged); a JAXPR dependence probe proving
    the decoupling structurally (with the sequential arm as the
    positive control); and a scheduled-HLO interleaving guard that
    needs a backend whose scheduler actually reorders (skips on CPU,
    like test_distribution's async-collective test).

(b) BATCHED CALU TOURNAMENT ROUNDS (Options.lu_tournament_batched,
    default on) — each round is ONE batched panel LU
    (blocked.panel_getrf_batched) instead of vmap(lax.linalg.lu)'s
    sequential per-block custom-call loop. Guarded by a dispatch-policy
    spy and an HLO probe (no lapack getrf custom-call in the lowered
    tournament; the legacy arm shows it — the probe's positive
    control).

(c) MESH PERM CORRUPTION, ROOT-CAUSED (the CHANGES.md round-6 open
    item): two pre-0.6 SPMD partitioner mis-lowerings — the
    concatenate in perm composition (blocked.lift_tail_perm is the
    fix) and the permutation gathers of a ROW-SHARDED panel operand
    (blocked.replicate_on_grid — the panel broadcast — is the fix).
    Regression tests pin both, at the minimal-repro level and through
    the full mesh getrf at the previously-failing (n=256, nb=64)
    shape. The lookahead restructure does NOT change the lowering
    class: both lookahead arms were corrupted identically before the
    fix and are correct identically after (asserted below).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import MethodLU, Options, Uplo
from slate_tpu.linalg import cholesky as chol_mod
from slate_tpu.linalg import lu as lu_mod
from slate_tpu.matgen import random_spd
from slate_tpu.ops import blocked

RNG = np.random.default_rng(71)

_SEQ = Options(lookahead=0)


def _randn(m, n, dtype):
    a = RNG.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * RNG.standard_normal((m, n))
    return np.asarray(a, dtype)


# -- (a) bit-identity: lookahead=1 vs lookahead=0 ---------------------------

@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128])
def test_potrf_lookahead_bit_identical(dtype):
    """Pure op reordering: every slab gemm of the pipeline is the same
    op as in the sequential schedule, so the factors must agree BIT
    FOR BIT. (n = 4 panels: ≥ 2 pipelined steps with a non-empty
    remainder each — the smallest shape where every pipeline branch
    runs; tier-1 budget.)"""
    n, nb = 128, 32
    a = np.asarray(random_spd(n, dtype=dtype, seed=9))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower)
    L1, i1 = st.potrf(A)
    L0, i0 = st.potrf(A, _SEQ)
    assert int(i1) == int(i0) == 0
    np.testing.assert_array_equal(np.asarray(L1.data), np.asarray(L0.data))


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128])
def test_getrf_lookahead_bit_identical(dtype):
    n, nb = 128, 32
    a = _randn(n, n, dtype)
    A = st.from_dense(a, nb=nb)
    LU1, p1, i1 = st.getrf(A)
    LU0, p0, i0 = st.getrf(A, _SEQ)
    assert int(i1) == int(i0)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p0))
    np.testing.assert_array_equal(np.asarray(LU1.data), np.asarray(LU0.data))


@pytest.mark.parametrize("dtype", [np.float32, np.complex128])
def test_geqrf_lookahead_bit_identical(dtype):
    m, n, nb = 160, 128, 32  # kt = 4: the pipeline splits twice
    a = _randn(m, n, dtype)
    A = st.from_dense(a, nb=nb)
    q1 = st.geqrf(A)
    q0 = st.geqrf(A, _SEQ)
    np.testing.assert_array_equal(np.asarray(q1.vr), np.asarray(q0.vr))
    np.testing.assert_array_equal(np.asarray(q1.t), np.asarray(q0.t))


def test_lookahead_bit_identical_mesh(grid2x4):
    """The pipeline must survive GSPMD partitioning bit-for-bit too
    (same ops, same shardings — rebalance constraints are applied per
    slab in both schedules). One mesh driver pair (getrf — the richest
    composition: pivot-fused gathers + split gemms + deferred swaps)
    keeps this inside the tier-1 budget; potrf/geqrf mesh runs are
    covered by test_distribution's grid-vs-1×1 agreement."""
    n, nb = 128, 32
    a = _randn(n, n, np.float64)
    Ag = st.from_dense(a, nb=nb, grid=grid2x4)
    LU1, p1, _ = st.getrf(Ag)
    LU0, p0, _ = st.getrf(Ag, _SEQ)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p0))
    np.testing.assert_array_equal(np.asarray(LU1.data), np.asarray(LU0.data))


# -- (a) structural dependence guard (jaxpr reachability) -------------------

def _ancestor_eqns(jaxpr, target_idx):
    """Indices of eqns reachable backwards from eqn ``target_idx``."""
    eqns = jaxpr.eqns
    producer = {}
    for i, e in enumerate(eqns):
        for v in e.outvars:
            producer[v] = i
    seen, stack = set(), [target_idx]
    while stack:
        i = stack.pop()
        if i in seen:
            continue
        seen.add(i)
        for v in eqns[i].invars:
            if getattr(v, "count", None) is None:
                continue  # Literal operands have no producer
            j = producer.get(v)
            if j is not None:
                stack.append(j)
    return seen


def _pjit_indices(jaxpr, name):
    out = []
    for i, e in enumerate(jaxpr.eqns):
        if e.primitive.name in ("pjit", "closed_call", "core_call"):
            if e.params.get("name") == name:
                out.append(i)
    return out


def _max_ancestor_dot_width(jaxpr, target_idx):
    """Widest 2-D dot_general output among the target eqn's ancestors
    (0 if none) — the probe's measure of which trailing slabs the
    panel factor depends on."""
    widths = [0]
    for i in _ancestor_eqns(jaxpr, target_idx):
        e = jaxpr.eqns[i]
        if e.primitive.name == "dot_general":
            shp = e.outvars[0].aval.shape
            if len(shp) == 2:
                widths.append(shp[1])
    return max(widths)


def _ancestor_remainder_dots(jaxpr, target_idx, s, nb):
    """Count 2-D ancestor dots that are REMAINDER slabs of step 0 —
    potrf's trailing slabs are all nb wide, so the discriminator is
    the shrinking ROW count: the next-panel slab has s−nb rows, the
    remainder slabs s−2nb, s−3nb, …"""
    count = 0
    for i in _ancestor_eqns(jaxpr, target_idx):
        e = jaxpr.eqns[i]
        if e.primitive.name == "dot_general":
            shp = e.outvars[0].aval.shape
            if len(shp) == 2 and shp[1] == nb and shp[0] <= s - 2 * nb:
                count += 1
    return count


def test_jaxpr_potrf_panel_decoupled_from_remainder():
    """THE structural lookahead assertion: the step-1 tile factor of
    the pipeline depends on the next-panel slab ONLY — no remainder
    slab dot (rows ≤ s−2nb) among its ancestors; in the sequential
    schedule the remainder slabs ARE ancestors (the probe's positive
    control)."""
    nb = 32
    s = 4 * nb
    a = jnp.eye(s, dtype=jnp.float32) * s

    def tile_indices(lookahead):
        jaxpr = jax.make_jaxpr(
            lambda x: chol_mod._potrf_iter(x, nb, "high", lookahead))(
                a).jaxpr
        idx = _pjit_indices(jaxpr, "_tile_chol")
        assert len(idx) >= 2, "probe lost the tile-factor call sites"
        return jaxpr, idx

    jx1, idx1 = tile_indices(1)
    assert _ancestor_remainder_dots(jx1, idx1[1], s, nb) == 0, (
        "lookahead tile factor depends on a remainder slab")
    jx0, idx0 = tile_indices(0)
    assert _ancestor_remainder_dots(jx0, idx0[1], s, nb) > 0, (
        "positive control: sequential tile factor should depend on the "
        "remainder slabs")


def test_jaxpr_getrf_panel_decoupled_from_remainder():
    """Same decoupling for LU: the step-1 panel factorization's
    ancestor dots are at most nb wide under lookahead=1; the
    sequential schedule shows the (w−nb)-wide full trailing dot."""
    nb = 32
    w = 4 * nb
    a = jnp.asarray(RNG.standard_normal((w, w)).astype(np.float32))

    def panel_indices(lookahead):
        jaxpr = jax.make_jaxpr(
            lambda x: lu_mod._getrf_iter(x, nb, "high",
                                         lookahead=lookahead))(a).jaxpr
        idx = _pjit_indices(jaxpr, "panel_getrf_jit")
        assert len(idx) >= 2
        return jaxpr, idx

    jx1, idx1 = panel_indices(1)
    assert _max_ancestor_dot_width(jx1, idx1[1]) <= nb
    jx0, idx0 = panel_indices(0)
    assert _max_ancestor_dot_width(jx0, idx0[1]) > nb


# -- (a) scheduled-HLO interleaving (needs a reordering scheduler) ----------

def _scheduled_positions(n=256, nb=32):
    """Compiled (scheduled) potrf entry at lookahead=1, mapping each
    line to the named scopes the ops carry (jax.named_scope metadata
    survives into compiled-HLO op_name)."""
    spd = np.asarray(random_spd(n, dtype=jnp.float32, seed=5))
    A = st.hermitian(np.tril(spd), nb=nb, uplo=Uplo.Lower)

    def f(A):
        return st.potrf(A)[0].data

    hlo = jax.jit(f).lower(A).compile().as_text()
    m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", hlo, re.S | re.M)
    assert m, "no ENTRY computation"
    return hlo, m.group(1).splitlines()


def test_scheduled_hlo_lookahead_panel_interleaved():
    """The schedule-level assertion (test_distribution P3 technique):
    some panel-(k+1) lookahead op must be SCHEDULED before the last
    remainder op of step k. XLA:CPU's sequential scheduler keeps data
    order, so (like the async-collective test) this skips when the
    property doesn't hold on a CPU backend; it is the standing check
    for a TPU-attached session."""
    hlo, lines = _scheduled_positions()
    nt = 256 // 32
    interleaved = 0
    for k in range(nt - 1):
        first_panel = last_rest = None
        for i, ln in enumerate(lines):
            if f"potrf_l{k + 1}_tile_lookahead" in ln and first_panel is None:
                first_panel = i
            if f"potrf_l{k}_trail_rest" in ln:
                last_rest = i
        if first_panel is not None and last_rest is not None \
                and first_panel < last_rest:
            interleaved += 1
    if interleaved == 0:
        if jax.default_backend() != "tpu":
            pytest.skip("backend scheduler keeps trace order (no "
                        "panel/remainder interleaving in scheduled "
                        "HLO); the assertion needs a TPU backend")
        assert interleaved > 0, (
            "TPU schedule never hoisted a lookahead panel before the "
            "previous step's remainder")
    # whichever backend: the lookahead scopes must exist in the
    # compiled module at all (the pipeline actually traced)
    assert "tile_lookahead" in hlo


def test_lookahead_scopes_absent_in_sequential_program():
    """lookahead=0 must reproduce the round-6 program: no lookahead
    scope appears anywhere in its compiled module."""
    n, nb = 128, 32
    spd = np.asarray(random_spd(n, dtype=jnp.float32, seed=6))
    A = st.hermitian(np.tril(spd), nb=nb, uplo=Uplo.Lower)

    def f(A):
        return st.potrf(A, _SEQ)[0].data

    hlo = jax.jit(f).lower(A).compile().as_text()
    assert "tile_lookahead" not in hlo


def test_herk_trailing_inplace_split_equals_whole():
    """The j_start/j_stop slab-range split the pipeline relies on:
    next-slab call + remainder call == one whole-range call, bitwise
    (identical slab gemms, only call boundaries differ)."""
    s, k1, nb = 160, 32, 32
    a = jnp.asarray(RNG.standard_normal((s, s)))
    pan = jnp.asarray(RNG.standard_normal((s - k1, nb)))
    whole = blocked.herk_trailing_inplace(a, pan, k1, nb)
    split = blocked.herk_trailing_inplace(a, pan, k1, nb,
                                          j_stop=k1 + nb)
    split = blocked.herk_trailing_inplace(split, pan, k1, nb,
                                          j_start=k1 + nb)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(split))


# -- (b) batched CALU tournament rounds -------------------------------------

def test_calu_batched_dispatch_policy(monkeypatch):
    """Default CALU routes every tournament round through the batched
    panel LU; the legacy arm routes none (and falls back to
    vmap(lax.linalg.lu))."""
    calls = {"batched": 0}
    orig = blocked.panel_getrf_batched

    def spy(stack, _o=orig):
        calls["batched"] += 1
        return _o(stack)

    monkeypatch.setattr(blocked, "panel_getrf_batched", spy)
    n, nb = 96, 32
    a = _randn(n, n, np.float64)
    A = st.from_dense(a, nb=nb)
    st.getrf(A, Options(method_lu=MethodLU.CALU))
    assert calls["batched"] > 0, "batched rounds never consulted"
    calls["batched"] = 0
    st.getrf(A, Options(method_lu=MethodLU.CALU,
                        lu_tournament_batched=False))
    assert calls["batched"] == 0, "legacy arm leaked into batched rounds"


def test_hlo_calu_rounds_have_no_lu_custom_call():
    """ISSUE 3 acceptance: the lowered default CALU program contains
    NO lax.linalg.lu custom-call (the per-block sequential loop); the
    legacy arm shows it — the probe's positive control."""
    n, nb = 128, 32
    a = _randn(n, n, np.float32)
    A = st.from_dense(a, nb=nb)

    def lower_text(opts):
        def f(A):
            return st.getrf(A, opts)[0].data
        return jax.jit(f).lower(A).as_text()

    assert "getrf_ffi" not in lower_text(Options(method_lu=MethodLU.CALU))
    assert "getrf_ffi" in lower_text(
        Options(method_lu=MethodLU.CALU, lu_tournament_batched=False)), \
        "probe lost its reference signal"


def test_panel_getrf_batched_matches_sequential():
    """The batched round kernel == per-chunk fori base, chunk by
    chunk (it IS vmap of the same base)."""
    stack = jnp.asarray(RNG.standard_normal((3, 64, 16)))
    lus, perms, infos = blocked.panel_getrf_batched(stack)
    for b in range(3):
        lu_r, p_r, i_r = blocked._panel_getrf_base(stack[b])
        np.testing.assert_array_equal(np.asarray(perms[b]), np.asarray(p_r))
        np.testing.assert_allclose(np.asarray(lus[b]), np.asarray(lu_r),
                                   rtol=1e-13, atol=1e-13)
        assert int(infos[b]) == int(i_r)


# -- (c) mesh perm corruption: root cause pinned ----------------------------

def test_compose_tail_sharded(grid2x4):
    """Minimal repro of the round-6 open item, now a regression guard:
    composing perms with a SHARDED tail must stay a valid permutation.
    (The old concatenate formulation produced out-of-range indices
    under the pre-0.6 partitioner — lift_tail_perm's docstring.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from slate_tpu.core.grid import ROW_AXIS

    p1 = jnp.asarray(RNG.permutation(256).astype(np.int32))
    p2 = jnp.asarray(RNG.permutation(224).astype(np.int32))
    ref = np.asarray(blocked._compose_tail(p1, p2, 32))
    sh = NamedSharding(grid2x4.mesh, P(ROW_AXIS))
    out = np.asarray(jax.jit(blocked._compose_tail, static_argnums=2)(
        jax.device_put(p1, sh), jax.device_put(p2, sh), 32))
    assert sorted(out.tolist()) == list(range(256))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_mesh_getrf_nb64_perm_regression(grid2x4):
    """The full previously-failing shape (n=256, nb=64): the perm must
    be a valid permutation, match the 1×1 grid, and factor correctly —
    under BOTH lookahead arms (the restructure does not change the
    lowering class: the corruption lived in perm composition and the
    sharded-panel gathers, fixed by lift_tail_perm +
    replicate_on_grid). Slow (round-20 tier-1 budget: two n=256 mesh
    factor compiles). Tier-1 siblings: test_compose_tail_sharded pins
    the root-cause perm-composition contract on the same grid, and
    test_distribution.py's grid_matches_single_device[getrf] pins
    mesh-getrf correctness."""
    n, nb = 256, 64
    a = _randn(n, n, np.float64)
    Ag = st.from_dense(a, nb=nb, grid=grid2x4)
    p_ref = np.asarray(st.getrf(st.from_dense(a, nb=nb))[1])
    for opts in (Options(), _SEQ):
        LU, perm, info = st.getrf(Ag, opts)
        perm = np.asarray(perm)
        assert sorted(perm.tolist()) == list(range(n)), \
            "mesh perm is not a permutation (round-6 corruption back?)"
        np.testing.assert_array_equal(perm, p_ref)
        lu = LU.to_numpy()
        L = np.tril(lu, -1) + np.eye(n)
        U = np.triu(lu)
        resid = np.abs(a[perm] - L @ U).max() / (
            np.linalg.norm(a, 1) * n * np.finfo(np.float64).eps)
        assert resid < 30.0


def test_mesh_calu_nb64(grid2x4):
    """CALU on the mesh at the formerly-failing block size (its
    tournament perms ride the same composition machinery)."""
    n, nb = 128, 64
    a = _randn(n, n, np.float64)
    LU, perm, info = st.getrf(st.from_dense(a, nb=nb, grid=grid2x4),
                              Options(method_lu=MethodLU.CALU))
    perm = np.asarray(perm)
    assert sorted(perm.tolist()) == list(range(n))
    lu = LU.to_numpy()
    L = np.tril(lu, -1) + np.eye(n)
    U = np.triu(lu)
    resid = np.abs(a[perm] - L @ U).max() / (
        np.linalg.norm(a, 1) * n * np.finfo(np.float64).eps)
    assert resid < 30.0
