"""Numerical-health telemetry (round 16, obs/numerics.py + Session).

The sensing layer for "never a wrong answer" in production: growth
bounds promoted out of the tester (one source of truth), the
Hager-Higham condest driven through the RESIDENT factor's own solve
programs, deterministic sampled-residual probes, refine-iteration
drift, and the healthy/degraded/suspect classification with counted
reflexes (suspect handles demote off the refine ladder and lose
eviction tie-breaks).

Pinned here: condest within 10× of the true κ₁ on known-cond matgen
operands across dtypes (in practice it lands within ~1%); the probed
solve program carries EXACTLY one more gemm than the plain one (HLO)
and an unprobed workload compiles zero probe programs; sampler
determinism under a seed; grouped/batched ≡ per-request health parity;
mesh condest with zero new compiles after warmup; the disabled path
(numerics=None) allocating nothing — the round-8 assertion extended.
Compile budget: everything at n ≤ 48 / single-panel nb (the standing
tier-1 caveat); the mesh case rides the module-scoped conftest grid.
"""

import json
import re

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.matgen import cond_targeted
from slate_tpu.obs import numerics as num
from slate_tpu.obs.attribution import (PLACEMENT_ROW_KEYS,
                                       validate_placement_snapshot)
from slate_tpu.refine import RefinePolicy
from slate_tpu.runtime import Session

RNG = np.random.default_rng(16)


def _spd(n=32, dtype=np.float64, seed=1):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(dtype)
    return (a @ a.T + n * np.eye(n)).astype(dtype)


# -- growth dedup (satellite: one source of truth) --------------------------


def test_growth_machinery_single_source_of_truth():
    """tester.py's growth functions ARE obs.numerics' (import
    identity, not copies) — ROADMAP item 2's update-vs-refactor bound
    and the serving health signals read the same formulas."""
    from slate_tpu import tester
    assert tester._chol_growth is num.chol_growth
    assert tester._lu_growth is num.lu_growth
    assert tester._lu_growth_arr is num.lu_growth_arr
    assert tester._aasen_growth is num.aasen_growth


def test_growth_values():
    a = _spd(16)
    l = np.linalg.cholesky(a)
    g = num.chol_growth(l, a)
    assert 1.0 <= g < 10.0  # SPD Cholesky: growth ~ 1
    # identity factor of the identity: exactly the clamp
    assert num.lu_growth(np.eye(8), np.eye(8)) == 1.0


# -- the estimator loop -----------------------------------------------------


def test_norm1est_exact_on_diagonal():
    """For D = diag(1..n), ‖D⁻¹‖₁ = 1 and Hager finds it exactly."""
    d = np.arange(1.0, 9.0)
    solve = lambda x: x / d[:, None]
    est, solves = num.norm1est(solve, solve, 8)
    assert est == pytest.approx(1.0)
    assert solves >= 2  # the crediting contract: solves are counted


def test_scaled_residual_formula():
    assert num.scaled_residual(0.0, 1.0, 1.0, 1.0) == 0.0
    assert num.scaled_residual(2.0, 1.0, 1.0, 3.0) == pytest.approx(0.5)
    assert num.scaled_residual(1.0, 0.0, 0.0, 1.0) == float("inf")


# -- sampler determinism ----------------------------------------------------


def test_sampler_deterministic_and_calibrated():
    s1 = num.ResidualSampler(0.25, seed=7)
    s2 = num.ResidualSampler(0.25, seed=7)
    seq1 = [s1.decide() for _ in range(400)]
    seq2 = [s2.peek(i) for i in range(400)]
    assert seq1 == seq2  # decide() IS peek(i) in consumption order
    frac = sum(seq1) / len(seq1)
    assert 0.2 < frac < 0.3  # low-discrepancy: converges fast
    assert num.ResidualSampler(1.0).decide() is True
    assert num.ResidualSampler(0.0).decide() is False
    # a different seed probes a different schedule
    assert [num.ResidualSampler(0.25, seed=8).peek(i)
            for i in range(400)] != seq2


# -- condest through the resident factor ------------------------------------


@pytest.mark.parametrize("op,dtype,cond", [
    ("chol", np.float64, 1e8),
    ("lu", np.float64, 1e8),
    ("lu", np.float32, 1e4),
])
def test_condest_within_10x_of_truth(op, dtype, cond):
    """The acceptance pin: condest on a known-cond matgen operand
    reports within 10× of the true κ₁, via the resident factor,
    credited per execution to the ledgers."""
    from slate_tpu.obs.flops import LEDGER
    n, nb = 32, 16
    a = np.asarray(cond_targeted(n, cond, dtype=dtype, seed=3,
                                 spd=(op == "chol")))
    truth = float(np.linalg.cond(a.astype(np.float64), 1))
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0, condest_on_factor=False)
    A = (st.hermitian(np.tril(a), nb=nb, uplo=st.Uplo.Lower)
         if op == "chol" else st.from_dense(a, nb=nb))
    h = sess.register(A, op=op)
    led0 = LEDGER.snapshot()["per_op"].get("numerics.condest", 0.0)
    est = sess.condest(h)
    assert 0.1 * truth <= est <= 10.0 * truth
    # probe work is credited: counters + the dedicated ledger op
    assert sess.metrics.get("condest_runs_total") == 1
    assert sess.metrics.get("condest_solves_total") >= 2
    assert sess.metrics.get("numerics_flops_total") > 0
    assert LEDGER.snapshot()["per_op"]["numerics.condest"] > led0
    # recorded into the monitor + exported as a health gauge
    assert sess.numerics.health(h) is not None
    snap = sess.metrics.snapshot()
    assert any(k.startswith("handle_health:") for k in snap["gauges"])


def test_condest_small_ops():
    """The *_small engine arm: chol_small through its B=1 bucket
    program, lu_small's transpose solve host-side from the gathered
    factor — both within 10× of the true κ₁."""
    n = 16
    for op, spd in (("chol_small", True), ("lu_small", False)):
        a = np.asarray(cond_targeted(n, 1e6, dtype=np.float64, seed=5,
                                     spd=spd))
        truth = float(np.linalg.cond(a, 1))
        sess = Session()
        h = sess.register(np.ascontiguousarray(a), op=op)
        est = sess.condest(h)
        assert 0.1 * truth <= est <= 10.0 * truth, (op, est, truth)


def test_condest_rejects_unsupported_ops():
    sess = Session()
    a = RNG.standard_normal((24, 12))
    h = sess.register(st.from_dense(a, nb=12), op="qr")
    with pytest.raises(SlateError, match="condest"):
        sess.condest(h)


# -- factor-time signals + health classification ----------------------------


def test_factor_time_signals_healthy_operand():
    a = _spd(32)
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0)
    h = sess.register(st.hermitian(np.tril(a), nb=16,
                                   uplo=st.Uplo.Lower), op="chol")
    sess.factor(h)  # growth + condest ride the factor (config default)
    row = sess.numerics.snapshot()["handles"][repr(h)]
    assert row["state"] == "healthy"
    assert row["growth"] is not None and row["growth"] >= 1.0
    assert row["condest"] is not None and row["condest"] > 0
    assert sess.metrics.get("condest_runs_total") == 1


def test_suspect_classification_and_placement_columns():
    """A κ≈1e12 operand in f32: u·κ̂ is orders past the breakdown
    point — suspect, and the state/condest/growth land on the
    placement-snapshot row (schema v2)."""
    a = np.asarray(cond_targeted(32, 1e12, dtype=np.float32, seed=5))
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0)
    h = sess.register(st.from_dense(a, nb=16), op="lu")
    sess.factor(h)
    assert sess.numerics.health(h) == "suspect"
    doc = sess.placement_snapshot(host="t")
    assert validate_placement_snapshot(doc) == []
    (row,) = doc["rows"]
    assert set(PLACEMENT_ROW_KEYS) <= set(row)
    assert row["health"] == "suspect"
    assert row["condest"] > 0 and row["growth"] >= 1.0
    # a bogus health value fails the committed validator
    bad = json.loads(json.dumps(doc))
    bad["rows"][0]["health"] = "fine"
    assert any("health" in e for e in validate_placement_snapshot(bad))


def test_suspect_demotion_reflex():
    """The counted reflex: a suspect refined handle is demoted off the
    refine ladder (refine_demotions_total AND health_demotions_total)
    and the demoted solve still returns a residual-correct answer —
    never silent, never wrong."""
    a = np.asarray(cond_targeted(32, 1e12, dtype=np.float32, seed=5))
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0)
    h = sess.register(st.from_dense(a, nb=16), op="lu",
                      refine=RefinePolicy(factor_dtype="bfloat16"))
    b = RNG.standard_normal(32).astype(np.float32)
    x = sess.solve(h, b)
    assert sess.numerics.health(h) == "suspect"
    assert sess.metrics.get("refine_demotions_total") >= 1
    assert sess.metrics.get("health_demotions_total") >= 1
    assert sess._ops[h].refine is None  # off the ladder
    resid = float(np.abs(a.astype(np.float64) @ x - b).max())
    assert resid / (32 * max(1.0, float(np.abs(x).max()))) < 1e-3


def test_eviction_prefers_suspect_handles():
    """Suspect residents lose eviction tie-breaks: with both factors
    resident and the suspect one MOST recently used, a budget squeeze
    still evicts the suspect factor first."""
    good = _spd(32, np.float32, seed=2).astype(np.float32)
    badm = np.asarray(cond_targeted(32, 1e12, dtype=np.float32, seed=5))
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0)
    hg = sess.register(st.hermitian(np.tril(good), nb=16,
                                    uplo=st.Uplo.Lower), op="chol")
    hb = sess.register(st.from_dense(badm, nb=16), op="lu")
    sess.factor(hg)
    sess.factor(hb)  # suspect AND most-recently-used
    assert sess.numerics.health(hb) == "suspect"
    assert set(sess.cached_handles()) == {hg, hb}
    sess.hbm_budget = sess._cache[hg].nbytes + 1  # room for one
    sess._evict_to_budget(keep=hg)
    assert sess.cached_handles() == [hg]  # LRU alone would keep hb


# -- sampled residual probes ------------------------------------------------


def test_probe_program_adds_exactly_one_gemm_hlo():
    """The acceptance pin, structurally: the probed solve program's
    optimized HLO carries EXACTLY one more dot than the plain solve
    program (the residual gemm — the norms are reductions, not
    contractions), for both lu and chol."""
    n = nb = 32
    ge = RNG.standard_normal((n, n)) + n * np.eye(n)
    spd = _spd(n)
    for op, A in (("lu", st.from_dense(ge, nb=nb)),
                  ("chol", st.hermitian(np.tril(spd), nb=nb,
                                        uplo=st.Uplo.Lower))):
        sess = Session()
        sess.enable_numerics(sample_fraction=1.0)
        h = sess.register(A, op=op)
        sess.warmup(h)  # compiles factor + solve + probe (+ condest_t)
        probe = solve = None
        for key, exe in sess._compiled.items():
            if key[0] == "probe":
                probe = exe
            elif key[0] not in ("factor", "condest_t"):
                solve = exe
        assert probe is not None and solve is not None
        pd = probe.as_text().count("dot(")
        sd = solve.as_text().count("dot(")
        assert pd == sd + 1, (op, pd, sd)


def test_unprobed_workload_compiles_zero_probe_programs():
    """fraction=0.0: the sampler consumes decisions but every solve
    runs the PLAIN program — no probe compile, no probe counters."""
    a = _spd(32)
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0, condest_on_factor=False)
    h = sess.register(st.hermitian(np.tril(a), nb=16,
                                   uplo=st.Uplo.Lower), op="chol")
    for _ in range(4):
        sess.solve(h, RNG.standard_normal(32))
    assert sess.metrics.get("residual_probes_total") == 0
    assert not any(k[0] == "probe" for k in sess._compiled)
    assert not any(r["what"] == "probe" for r in sess.compile_log)
    assert sess.numerics.sampler.consumed == 4  # stream still advances


def test_probe_records_residual_and_slo():
    from slate_tpu.obs.slo import Objective
    a = _spd(32)
    sess = Session()
    sess.enable_slo((Objective("resid", "residual", 0.9,
                               threshold_s=1e-2),))
    sess.enable_numerics(sample_fraction=1.0, condest_on_factor=False)
    h = sess.register(st.hermitian(np.tril(a), nb=16,
                                   uplo=st.Uplo.Lower), op="chol")
    for _ in range(3):
        sess.solve(h, RNG.standard_normal(32))
    assert sess.metrics.get("residual_probes_total") == 3
    row = sess.numerics.snapshot()["handles"][repr(h)]
    assert row["resid_count"] == 3
    assert 0 <= row["resid_ewma"] < 1e-10  # f64 SPD: ~eps
    assert row["state"] == "healthy"
    hist = sess.metrics.snapshot()["histograms"]["sampled_residual"]
    assert hist["count"] == 3
    # the residual SLO stream computed a burn rate (all good here)
    (obj,) = sess.slo.evaluate()["objectives"]
    assert obj["kind"] == "residual"
    assert any(w["burn_rate"] == 0.0 for w in obj["windows"])


def test_residual_slo_objective_burns_on_bad_probes():
    from slate_tpu.obs.slo import Objective, SloTracker
    t = [0.0]
    tr = SloTracker((Objective("resid", "residual", 0.9,
                               threshold_s=1e-6, windows=(60.0,)),),
                    clock=lambda: t[0])
    for rho in (1e-9, 1e-9, 1e-3, 1e-3):  # 2 good, 2 over threshold
        tr.record_residual(rho)
    (row,) = tr.evaluate()["objectives"]
    (w,) = row["windows"]
    assert w["total"] == 4 and w["bad"] == 2
    assert w["burn_rate"] == pytest.approx(0.5 / 0.1)


def test_residual_objective_requires_threshold():
    from slate_tpu.obs.slo import Objective
    with pytest.raises(ValueError, match="threshold"):
        Objective("r", "residual", 0.9)


# -- grouped/batched ≡ per-request parity -----------------------------------


def test_grouped_vs_per_request_health_parity():
    """The same operands, the same request stream, the same sampler
    seed: the grouped dispatch must record bit-identical residual
    signals (same solution bits — the linalg/batched contract — and
    the same host gemm) and land every handle in the same state."""
    n = 16
    mats = [np.ascontiguousarray(
        RNG.standard_normal((n, n)) + n * np.eye(n))
        for _ in range(4)]
    rhs = [np.ascontiguousarray(RNG.standard_normal((n, 1)))
           for _ in range(4)]

    def build():
        sess = Session()
        sess.enable_numerics(sample_fraction=1.0, sample_seed=9,
                             condest_on_factor=False)
        hs = [sess.register(m, op="lu_small") for m in mats]
        for h in hs:
            sess.factor(h)  # identical factor-time signals both sides
        return sess, hs

    s1, h1 = build()
    for h, b in zip(h1, rhs):
        s1.solve(h, b)
    s2, h2 = build()
    s2.solve_small_batched(h2, rhs)
    r1 = s1.numerics.snapshot()["handles"]
    r2 = s2.numerics.snapshot()["handles"]
    assert list(r1) == list(r2)
    for k in r1:
        assert r1[k]["resid_last"] == r2[k]["resid_last"], k  # bit-equal
        assert r1[k]["resid_count"] == r2[k]["resid_count"] == 1
        assert r1[k]["state"] == r2[k]["state"]
    assert (s1.metrics.get("residual_probes_total")
            == s2.metrics.get("residual_probes_total") == 4)


# -- mesh: zero new compiles after warmup -----------------------------------


def test_mesh_condest_zero_new_compiles_after_warmup(grid2x2):
    """Mesh acceptance pin: the condest probe drives the SAME analyzed
    sharded solve program the serving path warmed up — a warmed mesh
    operator's condest adds zero compiles and credits the collective
    census per apply. n=32 single-panel scale (the standing tier-1
    compile-budget caveat)."""
    from slate_tpu.obs import costs as costs_mod
    n, nb = 32, 16
    spd = _spd(n)
    sess = Session(mesh=grid2x2)
    sess.enable_numerics(sample_fraction=0.0, condest_on_factor=False)
    h = sess.register(st.hermitian(np.tril(spd), nb=nb,
                                   uplo=st.Uplo.Lower), op="chol")
    sess.warmup(h)
    compiles0 = (sess.metrics.get("aot_compiles")
                 + sess.metrics.get("factor_aot_compiles"))
    log0 = len(sess.compile_log)
    bytes0 = costs_mod.BYTES.snapshot()["per_op"].get(
        "numerics.condest", {}).get("bytes", 0.0)
    est = sess.condest(h)
    truth = float(np.linalg.cond(spd, 1))
    assert 0.1 * truth <= est <= 10.0 * truth
    assert (sess.metrics.get("aot_compiles")
            + sess.metrics.get("factor_aot_compiles")) == compiles0
    assert len(sess.compile_log) == log0
    # per-execution crediting: the probe applies moved the bytes
    # ledger under the numerics.condest op
    assert costs_mod.BYTES.snapshot()["per_op"].get(
        "numerics.condest", {}).get("bytes", 0.0) >= bytes0


# -- disabled path: the round-8 zero-allocation pin, extended ---------------


def test_disabled_path_zero_allocation_extended():
    """Session without numerics: zero numerics counters, gauges,
    histograms, compile-log rows, and no monitor state — the hot
    path's only new cost is `numerics is None` checks."""
    a = _spd(32)
    sess = Session()
    assert sess.numerics is None
    h = sess.register(st.hermitian(np.tril(a), nb=16,
                                   uplo=st.Uplo.Lower), op="chol")
    for _ in range(3):
        sess.solve(h, RNG.standard_normal(32))
    snap = sess.metrics.snapshot()
    for k in snap["counters"]:
        assert not k.startswith(("condest_", "residual_probes",
                                 "numerics_", "health_")), k
    assert not any(k.startswith(("handle_health", "handles_su",
                                 "handles_de")) for k in snap["gauges"])
    assert "sampled_residual" not in snap["histograms"]
    assert not any(k[0] in ("probe", "condest_t") for k in sess._compiled)
    assert sess.numerics_payload() == {"enabled": False, "handles": {}}


def test_unregister_forgets_health_row_and_gauge():
    a = _spd(32)
    sess = Session()
    sess.enable_numerics(sample_fraction=0.0)
    h = sess.register(st.hermitian(np.tril(a), nb=16,
                                   uplo=st.Uplo.Lower), op="chol")
    sess.factor(h)
    assert any(k.startswith("handle_health:")
               for k in sess.metrics.snapshot()["gauges"])
    sess.unregister(h)
    assert not any(k.startswith("handle_health:")
                   for k in sess.metrics.snapshot()["gauges"])
    assert sess.numerics.snapshot()["handles"] == {}


# -- refine drift -----------------------------------------------------------


def test_refine_drift_flags_degraded():
    m = num.NumericsMonitor(num.NumericsConfig(
        ewma_alpha=1.0, refine_drift_degraded=4.0))
    h = "h"
    m.record_factor(h, "chol", "float32", factor_dtype="bfloat16")
    for _ in range(3):
        old, new = m.record_refine(h, 2)  # floor = 2
    assert new == "healthy"
    old, new = m.record_refine(h, 9)  # 9 > 4 x floor
    assert new == "degraded"
    assert old == "healthy"


def test_nonfinite_is_suspect():
    m = num.NumericsMonitor()
    _, new = m.record_factor("h", "lu", "float32",
                             growth=float("inf"))
    assert new == "suspect"
    m2 = num.NumericsMonitor()
    _, new2 = m2.record_residual("h", float("nan"))
    assert new2 == "suspect"


# -- matgen satellite -------------------------------------------------------


def test_cond_targeted_matgen():
    for spd in (True, False):
        a = np.asarray(cond_targeted(24, 1e6, dtype=np.float64,
                                     seed=7, spd=spd))
        k2 = float(np.linalg.cond(a, 2))
        assert 0.5e6 < k2 < 2e6, (spd, k2)
        if spd:
            assert np.allclose(a, a.T)
            assert np.linalg.eigvalsh(a).min() > 0


# -- mirrors ----------------------------------------------------------------


def test_health_states_mirror_pinned():
    """bench_gate's jax-free HEALTH_STATES mirror must equal the
    obs.numerics vocabulary (the PLACEMENT_ROW_KEYS pin discipline)."""
    import importlib.util
    import pathlib
    path = (pathlib.Path(__file__).resolve().parents[1] / "tools"
            / "bench_gate.py")
    spec = importlib.util.spec_from_file_location("_bg", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert tuple(mod.HEALTH_STATES) == tuple(num.HEALTH_STATES)
    from slate_tpu.obs.attribution import _HEALTH_STATES
    assert tuple(_HEALTH_STATES) == tuple(num.HEALTH_STATES)
