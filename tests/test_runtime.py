"""Serving runtime (slate_tpu.runtime): resident-factor Session with the
HBM-budget LRU cache, request batcher, async executor, and metrics.

Reference analog: the tester's persistent-matrix amortization via
``*_solve_using_factor`` (include/slate/simplified_api.hh) — here grown
into a serving subsystem, so the tests check serving semantics: cache
hit/evict-under-budget, batched == per-request bit-identity, counters,
and future resolution under concurrent submits. All CPU-mesh, tier-1.
"""

import threading

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.linalg.band_packed import pb_pack
from slate_tpu.runtime import Batcher, Executor, Metrics, Session

RNG = np.random.default_rng(11)
N, NB = 64, 32


def _spd(n=N, dtype=np.float64):
    a = RNG.standard_normal((n, n)).astype(dtype)
    return a @ a.T + n * np.eye(n, dtype=dtype)


def _chol_handle(sess, n=N):
    spd = _spd(n)
    A = st.hermitian(np.tril(spd), nb=NB, uplo=st.Uplo.Lower)
    return sess.register(A, op="chol"), spd


def _lu_handle(sess, n=N):
    a = RNG.standard_normal((n, n)) + n * np.eye(n)
    return sess.register(st.from_dense(a, nb=NB), op="lu"), a


# -- Session: cache semantics ----------------------------------------------


def test_cache_hit_then_refactor_on_miss():
    sess = Session()
    h, spd = _chol_handle(sess)
    b = RNG.standard_normal(N)
    x1 = sess.solve(h, b)
    assert np.abs(spd @ x1 - b).max() < 1e-8
    x2 = sess.solve(h, b)
    assert np.array_equal(x1, x2)
    assert sess.metrics.get("cache_misses") == 1
    assert sess.metrics.get("cache_hits") == 1
    assert sess.metrics.get("factors_total") == 1
    # explicit eviction forces a refactor on the next solve
    assert sess.evict(h)
    x3 = sess.solve(h, b)
    assert np.abs(spd @ x3 - b).max() < 1e-8
    assert sess.metrics.get("cache_misses") == 2
    assert sess.metrics.get("evictions") == 1


def test_lru_eviction_respects_hbm_budget():
    sess = Session()
    handles = [_chol_handle(sess)[0] for _ in range(3)]
    b = RNG.standard_normal(N)
    sess.solve(handles[0], b)
    per_factor = sess.factor(handles[0]).nbytes
    assert per_factor > 0
    # budget fits exactly two factors
    sess.hbm_budget = 2 * per_factor
    for h in handles[1:]:
        sess.solve(h, b)
    assert sess.cached_bytes <= sess.hbm_budget
    # LRU order: the first operator was least recently used → evicted
    assert sess.cached_handles() == handles[1:]
    assert sess.metrics.get("evictions") == 1
    # refactor-on-miss brings it back, evicting the now-LRU second one
    sess.solve(handles[0], b)
    assert sess.cached_handles() == [handles[2], handles[0]]
    assert sess.cached_bytes <= sess.hbm_budget


def test_single_factor_over_budget_is_kept():
    sess = Session(hbm_budget=1)  # nothing fits
    h, spd = _chol_handle(sess)
    b = RNG.standard_normal(N)
    x = sess.solve(h, b)
    assert np.abs(spd @ x - b).max() < 1e-8
    assert len(sess.cached_handles()) == 1  # kept despite the budget
    assert sess.metrics.get("budget_overflows") == 1


def test_unknown_handle_and_reregister():
    sess = Session()
    with pytest.raises(SlateError):
        sess.solve("nope", np.zeros(N))
    h, _ = _lu_handle(sess)
    with pytest.raises(SlateError):
        sess.register(st.from_dense(np.eye(N), nb=NB), handle=h)
    sess.unregister(h)
    assert h not in sess
    # wide operators are rejected at registration (no LQ-resident path)
    with pytest.raises(SlateError):
        sess.register(st.from_dense(RNG.standard_normal((32, 64)), nb=16),
                      op="auto")
    # auto-allocated handles skip caller-chosen integers
    sess2 = Session()
    h1 = sess2.register(st.from_dense(np.eye(N), nb=NB), handle=1)
    h2 = sess2.register(st.from_dense(2 * np.eye(N), nb=NB))
    assert h1 == 1 and h2 != 1 and h2 in sess2


def test_per_operator_opts_not_shared():
    from slate_tpu.core.types import Options
    sess = Session()
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    h1 = sess.register(st.from_dense(a, nb=NB), op="lu")
    h2 = sess.register(st.from_dense(a, nb=NB), op="lu",
                       opts=Options(update_precision="highest"))
    assert sess._solve_fn(sess._ops[h1]) is not sess._solve_fn(
        sess._ops[h2])  # distinct closures: opts are part of the key
    b = RNG.standard_normal(N)
    for h in (h1, h2):
        assert np.abs(a @ sess.solve(h, b) - b).max() < 1e-8


# -- Session: operator kinds -----------------------------------------------


def test_qr_and_band_operators():
    sess = Session()
    # overdetermined least squares via resident QR
    m, n = 96, 48
    a = RNG.standard_normal((m, n))
    hq = sess.register(st.from_dense(a, nb=NB), op="auto")
    assert sess._ops[hq].op == "qr"
    b = RNG.standard_normal(m)
    x = sess.solve(hq, b)
    assert x.shape == (n,)
    # least-squares optimality: residual orthogonal to range(A)
    assert np.abs(a.T @ (a @ x - b)).max() < 1e-8
    # Hermitian positive-definite band via packed storage
    nb_, kd = 64, 3
    spd_band = np.tril(np.triu(_spd(nb_), -kd), kd)
    hb = sess.register(pb_pack(spd_band, kd), op="auto")
    assert sess._ops[hb].op == "band_chol"
    bb = RNG.standard_normal(nb_)
    xb = sess.solve(hb, bb)
    assert np.abs(spd_band @ xb - bb).max() < 1e-8


# -- Batching --------------------------------------------------------------


def test_batched_bucket_bit_matches_individual():
    """Acceptance: a batched bucket of K same-shape solves is identical
    to K individual *_solve_using_factor calls."""
    sess = Session()
    h, a = _lu_handle(sess)
    bs = [RNG.standard_normal(N) for _ in range(6)]
    individual = [sess.solve(h, b) for b in bs]
    # the individual path IS lu_solve_using_factor on the resident factor:
    res = sess.factor(h)
    direct = st.lu_solve_using_factor(
        res.payload[0], res.payload[1], st.from_dense(bs[0][:, None], nb=NB))
    np.testing.assert_allclose(direct.to_numpy()[:, 0], individual[0],
                               rtol=0, atol=1e-12)
    batcher = Batcher(sess, max_batch=8, max_wait=10.0)
    futs = [batcher.submit(h, b) for b in bs]
    batcher.flush()
    batched = [f.result(timeout=0) for f in futs]
    for ind, bat in zip(individual, batched):
        assert np.array_equal(ind, bat)  # bit-identical, not just close
    assert sess.metrics.get("batches_total") == 1
    # bucketing: different shapes never coalesce
    f1 = batcher.submit(h, RNG.standard_normal(N))
    f2 = batcher.submit(h, RNG.standard_normal((N, 2)))
    batcher.flush()
    assert f1.result(timeout=0).shape == (N,)
    assert f2.result(timeout=0).shape == (N, 2)
    assert sess.metrics.get("batches_total") == 3


def test_batcher_max_batch_splits():
    sess = Session()
    h, _ = _lu_handle(sess)
    batcher = Batcher(sess, max_batch=4, max_wait=10.0)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(10)]
    ready = batcher.pop_ready()  # two full buckets ready before deadline
    assert [len(r) for _, r in ready] == [4, 4]
    for key, reqs in ready:
        batcher.run(key, reqs)
    batcher.flush()  # deadline-flush the remaining 2
    assert all(f.result(timeout=0).shape == (N,) for f in futs)


# -- Executor --------------------------------------------------------------


def test_executor_futures_under_concurrent_submits():
    sess = Session()
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    bs = [RNG.standard_normal(N) for _ in range(24)]
    results = [None] * len(bs)
    with Executor(sess, max_batch=8, max_wait=1e-3) as ex:
        def client(lo, hi):
            futs = [(i, ex.submit(h, bs[i])) for i in range(lo, hi)]
            for i, f in futs:
                results[i] = f.result(timeout=60)
        threads = [threading.Thread(target=client, args=(i * 8, (i + 1) * 8))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for b, x in zip(bs, results):
        assert x is not None
        assert np.abs(spd @ x - b).max() < 1e-8
    m = sess.metrics
    assert m.get("requests_total") == 24
    assert m.get("solves_total") == 24
    # batching actually coalesced (fewer dispatches than requests)
    assert m.get("batches_total") < 24


def test_executor_deadline_flush_and_failfast():
    sess = Session()
    h, _ = _lu_handle(sess)
    with Executor(sess, max_batch=64, max_wait=5e-3) as ex:
        f = ex.submit(h, RNG.standard_normal(N))
        # far below max_batch: only the max-wait deadline can flush it
        assert f.result(timeout=60).shape == (N,)
        # an unregistered handle is a DETERMINISTIC failure: no retries
        bad = ex.submit("ghost", RNG.standard_normal(N))
        with pytest.raises(SlateError):
            bad.result(timeout=60)
    assert sess.metrics.get("retries") == 0
    assert sess.metrics.get("failed_batches") == 1


def test_executor_retries_transient_failures():
    sess = Session()
    h, _ = _lu_handle(sess)
    real_solve = sess.solve
    fail_left = [2]

    def flaky(handle, b):
        if fail_left[0]:
            fail_left[0] -= 1
            raise RuntimeError("transient dispatch failure")
        return real_solve(handle, b)

    sess.solve = flaky
    try:
        with Executor(sess, max_batch=4, max_wait=1e-3, retries=2) as ex:
            f = ex.submit(h, RNG.standard_normal(N))
            assert f.result(timeout=60).shape == (N,)  # 3rd attempt wins
    finally:
        sess.solve = real_solve
    assert sess.metrics.get("retries") == 2
    assert sess.metrics.get("failed_batches") == 0


def test_batcher_skips_cancelled_requests():
    sess = Session()
    h, a = _lu_handle(sess)
    batcher = Batcher(sess, max_batch=8, max_wait=10.0)
    futs = [batcher.submit(h, RNG.standard_normal(N)) for _ in range(4)]
    assert futs[1].cancel()  # client gives up before dispatch
    batcher.flush()
    for i, f in enumerate(futs):
        if i == 1:
            assert f.cancelled()
        else:
            assert f.result(timeout=0).shape == (N,)
    assert sess.metrics.get("cancelled_requests") == 0  # caught pre-solve
    # running the same (already-resolved) bucket again is a no-op
    snap_before = sess.metrics.get("batches_total")
    ready = batcher.pop_ready(force=True)
    assert ready == []
    assert sess.metrics.get("batches_total") == snap_before


def test_executor_flush_waits_for_inflight():
    sess = Session()
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    with Executor(sess, max_batch=4, max_wait=1e-4) as ex:
        futs = [ex.submit(h, RNG.standard_normal(N)) for _ in range(8)]
        ex.flush()
        # flush's contract: everything submitted before it is solved
        assert all(f.done() for f in futs)
        assert all(f.result(timeout=0).shape == (N,) for f in futs)


def test_executor_no_lost_wakeup_with_large_max_wait():
    """Round-23 regression pin: a submit/flush notify that lands while
    the worker is mid-dispatch (after popping, before re-waiting) must
    not be lost — the worker re-checks the `_kick` flag before
    sleeping. Without it, this loop stalls out a full max_wait (here
    3600 s) the first time the race hits; the chaos forecast drill hit
    it within ~100 iterations. The production default max_wait=2e-3
    masked the bug as a ≤2 ms blip."""
    sess = Session()
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    with Executor(sess, max_batch=1, max_wait=3600.0) as ex:
        for i in range(150):
            b = RNG.standard_normal(N)
            f = ex.submit(h, b)
            ex.flush()
            assert f.done(), f"submit {i} slept into max_wait"


def test_executor_warmup_aot():
    sess = Session()
    h, spd = _chol_handle(sess)
    with Executor(sess, max_wait=1e-3) as ex:
        ex.warmup([h])
        assert sess.metrics.get("aot_compiles") == 1
        assert sess.metrics.get("factors_total") == 1  # factored off-path
        b = RNG.standard_normal(N)
        x = ex.submit(h, b).result(timeout=60)
    assert np.abs(spd @ x - b).max() < 1e-8
    # warmup executable served the request-path solve bit-identically
    sess2 = Session()
    h2 = sess2.register(st.hermitian(np.tril(spd), nb=NB,
                                     uplo=st.Uplo.Lower), op="chol")
    assert np.array_equal(x, sess2.solve(h2, b))


def test_warmup_compiles_factor_program():
    """Round 7: warmup AOT-compiles the whole-factor program (the
    lookahead-pipeline driver) per operand shape, so refactor-on-miss
    after an eviction reuses the executable — no request-path tracing
    or compilation."""
    sess = Session()
    h, spd = _chol_handle(sess)
    sess.warmup(h)
    assert sess.metrics.get("factor_aot_compiles") == 1
    assert sess.metrics.get("aot_compiles") == 1  # the solve program
    sess.warmup(h)  # idempotent: same shapes, no recompiles
    assert sess.metrics.get("factor_aot_compiles") == 1
    assert sess.evict(h)
    b = RNG.standard_normal(N)
    x = sess.solve(h, b)  # refactor-on-miss rides the AOT executable
    assert np.abs(spd @ x - b).max() < 1e-8
    assert sess.metrics.get("factors_total") == 2


def test_factor_program_bit_identical_warmed_vs_cold():
    """The AOT factor executable and the on-demand jitted factor are
    the same program: factors (hence solves) agree bit for bit."""
    spd = _spd()
    A = st.hermitian(np.tril(spd), nb=NB, uplo=st.Uplo.Lower)
    b = RNG.standard_normal(N)
    warm = Session()
    hw = warm.register(A, op="chol")
    warm.warmup(hw)
    cold = Session()
    hc = cold.register(A, op="chol")
    assert np.array_equal(warm.solve(hw, b), cold.solve(hc, b))


# -- Metrics ---------------------------------------------------------------


def test_metrics_counters_histograms_json(tmp_path):
    sess = Session()
    h, _ = _chol_handle(sess)
    for _ in range(3):
        sess.solve(h, RNG.standard_normal(N))
    snap = sess.metrics.snapshot()
    assert snap["counters"]["solves_total"] == 3
    assert snap["counters"]["cache_misses"] == 1
    assert snap["counters"]["flops_total"] > 0
    lat = snap["histograms"]["solve_latency"]
    assert lat["count"] == 3 and 0 < lat["p50"] <= lat["p99"] <= lat["max"]
    assert snap["derived"]["cache_hit_rate"] == pytest.approx(2 / 3)
    assert snap["derived"]["solves_per_sec"] > 0
    assert snap["derived"]["gflops"] > 0
    out = tmp_path / "metrics.json"
    text = sess.metrics.to_json(str(out))
    import json
    roundtrip = json.loads(out.read_text())
    assert roundtrip == json.loads(text)
    assert roundtrip["histograms"]["factor_latency"]["count"] == 1


def test_histogram_percentiles():
    m = Metrics()
    for v in range(1, 101):
        m.observe("lat", float(v))
    h = m.snapshot()["histograms"]["lat"]
    assert h["p50"] == pytest.approx(50, abs=1)
    assert h["p99"] == pytest.approx(99, abs=1)
    assert h["count"] == 100 and h["max"] == 100
