"""Regression tests: grids whose tile counts don't divide evenly.

shard() rounds storage up to grid multiples (e.g. n=48, nb=16, 2×2 grid
→ 64-row storage); every driver must reconcile storage-sized and
canonical-sized operands. These cases crashed before the canonicalization
pass (code-review findings on blas3/lu/elementwise).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.types import Side, Uplo
from slate_tpu.matgen import random_spd

RNG = np.random.default_rng(55)
N, NB = 48, 16  # mt = 3, not divisible by p = 2


def test_posv_uneven_grid(grid2x2):
    a = np.asarray(random_spd(N, dtype=jnp.float64, seed=1))
    b = RNG.standard_normal((N, 4))
    A = st.hermitian(np.tril(a), nb=NB, uplo=Uplo.Lower, grid=grid2x2)
    B = st.from_dense(b, nb=NB, grid=grid2x2)
    X, info = st.posv(A, B)
    assert int(info) == 0
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8, atol=1e-10)


def test_gesv_nopiv_uneven_grid(grid2x2):
    a = RNG.standard_normal((N, N)) + 10 * np.eye(N)
    b = RNG.standard_normal((N, 2))
    A = st.from_dense(a, nb=NB, grid=grid2x2)
    B = st.from_dense(b, nb=NB, grid=grid2x2)
    X, info = st.gesv_nopiv(A, B)
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-7, atol=1e-9)


def test_symm_trmm_uneven_grid(grid2x2):
    s = RNG.standard_normal((N, N))
    S = st.symmetric(np.tril(s), nb=NB, uplo=Uplo.Lower, grid=grid2x2)
    full = np.tril(s) + np.tril(s, -1).T
    b = RNG.standard_normal((N, NB))
    B = st.from_dense(b, nb=NB, grid=grid2x2)
    C = st.from_dense(np.zeros((N, NB)), nb=NB, grid=grid2x2)
    out = st.symm(Side.Left, 1.0, S, B, 0.0, C)
    np.testing.assert_allclose(out.to_numpy(), full @ b, rtol=1e-10)
    t = np.tril(s) + 4 * np.eye(N)
    T = st.triangular(t, nb=NB, uplo=Uplo.Lower, grid=grid2x2)
    out2 = st.trmm(Side.Left, 1.0, T, B)
    np.testing.assert_allclose(out2.to_numpy(), np.tril(t) @ b, rtol=1e-10)


def test_set_lambda_uneven_grid(grid2x2):
    A = st.from_dense(np.zeros((N, N)), nb=NB, grid=grid2x2)
    L = st.set_lambda(lambda i, j: i + j, A)
    assert L.to_numpy()[5, 7] == 12
    Z = st.set_matrix(1.0, 3.0, A)
    assert Z.to_numpy()[0, 0] == 3.0 and Z.to_numpy()[0, 1] == 1.0


def test_gels_uneven_grid(grid2x2):
    m, n = 80, 48
    a = RNG.standard_normal((m, n))
    b = RNG.standard_normal((m, 2))
    A = st.from_dense(a, nb=NB, grid=grid2x2)
    B = st.from_dense(b, nb=NB, grid=grid2x2)
    X = st.gels(A, B)
    ref, *_ = np.linalg.lstsq(a, b, rcond=None)
    np.testing.assert_allclose(X.to_numpy()[:n], ref, rtol=1e-7, atol=1e-9)


def test_gecondest_complex():
    # purely imaginary matrix: rcond must be ~1, not 0 (complex-safe sign)
    n = 8
    a = 1j * np.eye(n)
    A = st.from_dense(a.astype(np.complex128), nb=4)
    LU, perm, info = st.getrf(A)
    rcond = st.gecondest(LU, perm, 1.0)
    assert 0.5 < rcond <= 1.01


# -- DESIGN.md P2 edge cases: raggedness where padded-uniform could
#    silently go wrong ------------------------------------------------------

# primes: maximally ragged tiles; n=53 rides the slow lane (round-20
# tier-1 budget — same class, n=37 keeps the all-drivers ragged pin)
@pytest.mark.parametrize("n", [37, pytest.param(
    53, marks=pytest.mark.slow)])
def test_prime_sizes_all_drivers(grid2x2, n):
    nb = 16
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=n))
    b = RNG.standard_normal((n, 3))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower, grid=grid2x2)
    B = st.from_dense(b, nb=nb, grid=grid2x2)
    X, info = st.posv(A, B)
    assert int(info) == 0
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-8, atol=1e-9)
    g = RNG.standard_normal((n, n))
    Xg, info = st.gesv(st.from_dense(g, nb=nb, grid=grid2x2),
                       st.from_dense(b, nb=nb, grid=grid2x2))
    assert int(info) == 0
    np.testing.assert_allclose(Xg.to_numpy(), np.linalg.solve(g, b),
                               rtol=1e-7, atol=1e-8)
    w, Z = st.heev(A)
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(a),
                               rtol=1e-9, atol=1e-9)


def test_nb_larger_than_n(grid2x2):
    """nb > n: one padded tile holds the whole matrix."""
    n, nb = 11, 32
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=2))
    b = RNG.standard_normal((n, 2))
    A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower, grid=grid2x2)
    X, info = st.posv(A, st.from_dense(b, nb=nb, grid=grid2x2))
    assert int(info) == 0
    np.testing.assert_allclose(X.to_numpy(), np.linalg.solve(a, b),
                               rtol=1e-9, atol=1e-10)


@pytest.mark.slow  # ~6 s: three nb-variant mesh posv compiles
# (round-22 tier-1 budget); tier-1 siblings — test_posv_uneven_grid
# (uneven mesh posv) and test_nb_larger_than_n (the extreme-padding
# case: one padded tile holds the whole matrix)
def test_padding_isolated_from_results(grid2x2):
    """The same logical matrix under different padding amounts (nb
    choices → different pad sizes and grid roundings) must produce the
    same logical results: padding is owned by the constructors and
    never leaks into logical entries. (Raw storage poisoning via
    with_data is OUT of contract — with_data requires canonical
    padding, which the constructors maintain.)"""
    n = 40
    a = np.asarray(random_spd(n, dtype=jnp.float64, seed=3))
    b = RNG.standard_normal((n, 2))
    results = []
    norms = []
    for nb in (8, 16, 32):  # pad 0/8/24 rows + grid rounding
        A = st.hermitian(np.tril(a), nb=nb, uplo=Uplo.Lower, grid=grid2x2)
        B = st.from_dense(b, nb=nb, grid=grid2x2)
        X, info = st.posv(A, B)
        assert int(info) == 0
        results.append(X.to_numpy())
        norms.append(float(st.norm(A, st.Norm.One)))
    for r in results[1:]:
        np.testing.assert_allclose(r, results[0], rtol=1e-9, atol=1e-10)
    for m in norms:
        assert np.isclose(m, np.abs(a).sum(axis=0).max())
