"""Round 19: mesh-sharded two-stage heev/svd served as resident
eigendecompositions (slate_tpu/spectral/).

Covers the four acceptance pins of the round:
  * staged mesh heev/svd match a single-device run to growth-scaled
    tolerance (NO cross-placement bit claim — stedc merge order and
    collective reduction order differ by placement);
  * a served apply is numerically the eager ``V f(Λ) Vᴴ b``;
  * after ``warmup`` a spectral resident serves every catalog
    function at any theta with ZERO new compiles, and every warmed
    apply program lowers to exactly TWO gemms + a diagonal scale
    (HLO dot census);
  * the staged factor programs flow through the round-9 cost census
    (mesh stages carry nonzero collective bytes) and the round-15
    tenant ledger conserves with spectral traffic in the mix.

Checkpoint/restore of ``eig_factors``/``svd_factors`` nodes is pinned
bit-identical on same placement, and the jax-free bench_gate mirror
validator is drift-pinned against the runtime one on the same
malformed spectral nodes (the round-17 duplication discipline).

Tier-1 sizes stay at n ≤ 64 (compile-heavy staged pipelines); the
larger mesh sweep runs under ``-m slow`` with the n=48 tier-1 sibling
``test_heev_mesh_matches_single_device`` covering the same seam.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.core.tiled_matrix import from_dense
from slate_tpu.core.types import MatrixKind
from slate_tpu.runtime import checkpoint as ckpt
from slate_tpu.runtime.session import Session
from slate_tpu import spectral as sp

_REPO = pathlib.Path(__file__).resolve().parent.parent


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate_spectral_test",
        str(_REPO / "tools" / "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _sym(rng, n, dtype=np.float64):
    a = rng.standard_normal((n, n)).astype(dtype)
    return ((a + a.T) / 2).astype(dtype)


def _growth_tol(n, dtype):
    # growth-scaled: the two-stage pipeline touches each entry O(n)
    # times through blocked reflector applies
    return 50.0 * n * np.finfo(np.dtype(dtype)).eps


# -- staged decompositions vs references ------------------------------------


def test_heev_staged_matches_numpy():
    rng = np.random.default_rng(0)
    n, nb = 48, 16
    a = _sym(rng, n)
    A = from_dense(a, nb, kind=MatrixKind.Hermitian)
    w, Z = st.heev_mesh(A)
    w_ref = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(np.asarray(w), w_ref,
                               rtol=1e-9, atol=1e-9)
    V = Z.to_numpy()
    # orthonormal columns + the eigen-relation
    np.testing.assert_allclose(V.T @ V, np.eye(n), atol=1e-10)
    assert np.abs(a @ V - V * np.asarray(w)[None, :]).max() \
        < _growth_tol(n, a.dtype) * np.abs(w_ref).max()


def test_svd_staged_matches_numpy():
    rng = np.random.default_rng(1)
    m, n, nb = 64, 48, 16
    g = rng.standard_normal((m, n))
    G = from_dense(g, nb)
    s, U, V = st.svd_mesh(G)
    s_ref = np.linalg.svd(g, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s), s_ref,
                               rtol=1e-9, atol=1e-9 * s_ref[0])
    Un, Vn = U.to_numpy(), V.to_numpy()
    assert np.abs(g @ Vn - Un * np.asarray(s)[None, :]).max() \
        < _growth_tol(max(m, n), g.dtype) * s_ref[0]


def test_svd_staged_rejects_wide():
    rng = np.random.default_rng(2)
    G = from_dense(rng.standard_normal((16, 32)), 16)
    with pytest.raises(SlateError):
        st.svd_mesh(G)


def test_heev_mesh_matches_single_device(grid2x2):
    """Mesh ≡ single-device to growth-scaled tolerance (values AND
    the subspace via the eigen-relation; no bit claim across
    placements)."""
    rng = np.random.default_rng(3)
    n, nb = 48, 16
    a = _sym(rng, n)
    w1, _ = st.heev_mesh(from_dense(a, nb, kind=MatrixKind.Hermitian))
    Am = from_dense(a, nb, kind=MatrixKind.Hermitian, grid=grid2x2)
    wm, Zm = st.heev_mesh(Am)
    tol = _growth_tol(n, a.dtype) * max(np.abs(np.asarray(w1)).max(),
                                        1.0)
    assert np.abs(np.asarray(wm) - np.asarray(w1)).max() < tol
    Vm = Zm.to_numpy()
    assert np.abs(a @ Vm - Vm * np.asarray(wm)[None, :]).max() < tol


def test_svd_mesh_matches_single_device(grid2x2):
    rng = np.random.default_rng(4)
    m, n, nb = 64, 48, 16
    g = rng.standard_normal((m, n))
    s1, _, _ = st.svd_mesh(from_dense(g, nb))
    sm, Um, Vm = st.svd_mesh(from_dense(g, nb, grid=grid2x2))
    tol = _growth_tol(max(m, n), g.dtype) * float(np.asarray(s1)[0])
    assert np.abs(np.asarray(sm) - np.asarray(s1)).max() < tol
    Un, Vn = Um.to_numpy(), Vm.to_numpy()
    assert np.abs(g @ Vn - Un * np.asarray(sm)[None, :]).max() < tol


@pytest.mark.slow
def test_heev_mesh_larger_sweep(grid2x4):
    """The -m slow sweep at n=128 (tier-1 sibling:
    test_heev_mesh_matches_single_device at n=48)."""
    rng = np.random.default_rng(5)
    n, nb = 128, 32
    a = _sym(rng, n)
    wm, Zm = st.heev_mesh(from_dense(a, nb, kind=MatrixKind.Hermitian,
                                     grid=grid2x4))
    w_ref = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(np.asarray(wm), w_ref,
                               rtol=1e-8, atol=1e-8 * np.abs(w_ref).max())
    Vm = Zm.to_numpy()
    assert np.abs(a @ Vm - Vm * np.asarray(wm)[None, :]).max() \
        < _growth_tol(n, a.dtype) * np.abs(w_ref).max()


# -- served applies ---------------------------------------------------------


@pytest.fixture(scope="module")
def served():
    """One warmed session serving an eig and an svd resident (f64,
    n ≤ 64) — shared across the apply/compile/checkpoint tests so the
    staged pipelines compile once per module, not once per test."""
    rng = np.random.default_rng(7)
    n, nb = 48, 16
    a = _sym(rng, n)
    m = 64
    g = rng.standard_normal((m, n))
    sess = Session()
    sess.enable_attribution()
    he = sess.register(from_dense(a, nb, kind=MatrixKind.Hermitian),
                       op="eig", tenant="t-eig")
    hs = sess.register(from_dense(g, nb), op="svd", tenant="t-svd")
    sess.warmup(he, nrhs=3)
    sess.warmup(hs, nrhs=3)
    return {"sess": sess, "he": he, "hs": hs, "a": a, "g": g,
            "n": n, "m": m}


def _eager_eig(a, fn, theta, b):
    w, v = np.linalg.eigh(a)
    wf, _fwd = sp.EIG_FUNCTIONS[fn]
    return v @ (np.asarray(wf(w, theta)) * (v.T @ b).T).T


def test_apply_parity_vs_eager(served):
    """sess.apply == eager V f(Λ) Vᴴ b for every eig catalog
    function (the two-gemm program is numerically the eager
    factored apply)."""
    sess, he, a, n = (served["sess"], served["he"], served["a"],
                      served["n"])
    rng = np.random.default_rng(8)
    b = rng.standard_normal((n, 3))
    for fn in sorted(sp.EIG_FUNCTIONS):
        theta = {"solve": 0.37, "truncate": 5.0}.get(fn, 0.25)
        x = sess.apply(he, b, fn=fn, theta=theta)
        x_ref = _eager_eig(a, fn, theta, b)
        assert np.abs(x - x_ref).max() < 1e-8 * max(
            np.abs(x_ref).max(), 1.0), fn


def test_svd_apply_directions(served):
    """svd solve/whiten take m-row rhs (pinv direction), truncate an
    n-row one (forward) — and each matches the eager reference."""
    sess, hs, g = served["sess"], served["hs"], served["g"]
    m, n = served["m"], served["n"]
    rng = np.random.default_rng(9)
    u, s, vt = np.linalg.svd(g, full_matrices=False)
    bm = rng.standard_normal((m, 2))
    theta = 0.2
    x = sess.apply(hs, bm, fn="solve", theta=theta)
    w = s / (s * s + theta * theta)
    x_ref = vt.T @ (w[:, None] * (u.T @ bm))
    assert np.abs(x - x_ref).max() < 1e-9 * max(np.abs(x_ref).max(),
                                                1.0)
    bn = rng.standard_normal((n, 2))
    r = 5
    y = sess.apply(hs, bn, fn="truncate", theta=float(r))
    wr = np.where(np.arange(s.size) < r, s, 0.0)
    y_ref = u @ (wr[:, None] * (vt @ bn))
    assert np.abs(y - y_ref).max() < 1e-9 * max(np.abs(y_ref).max(),
                                                1.0)


def test_eigvals_and_sigma(served):
    sess = served["sess"]
    w = sess.eigvals(served["he"])
    np.testing.assert_allclose(w, np.linalg.eigvalsh(served["a"]),
                               rtol=1e-9, atol=1e-9)
    assert np.all(np.diff(w) >= 0)  # ascending (heev convention)
    s = sess.eigvals(served["hs"])
    s_ref = np.linalg.svd(served["g"], compute_uv=False)
    np.testing.assert_allclose(s, s_ref, rtol=1e-9,
                               atol=1e-9 * s_ref[0])
    assert np.all(np.diff(s) <= 0)  # descending (svd convention)


def test_zero_new_compiles_and_two_gemm_pin(served):
    """The serving pins: after warmup, every catalog function at any
    theta executes with zero new compiles, and every warmed apply
    program's HLO contains exactly two dot ops (two gemms + a
    diagonal scale — the round-19 program-shape claim)."""
    import re

    sess = served["sess"]
    rng = np.random.default_rng(10)
    n0 = len(sess.compile_log)
    b = rng.standard_normal((served["n"], 3))
    bm = rng.standard_normal((served["m"], 3))
    for theta in (0.0, 0.31, -2.5, 7.0):
        for fn in sorted(sp.EIG_FUNCTIONS):
            sess.apply(served["he"], b, fn=fn, theta=theta)
        for fn in sorted(sp.SVD_FUNCTIONS):
            rows = bm if not sp.SVD_FUNCTIONS[fn][1] else b
            sess.apply(served["hs"], rows, fn=fn, theta=abs(theta))
    assert len(sess.compile_log) == n0, \
        "spectral serving recompiled after warmup"
    dots = {}
    for key, exe in sess._compiled.items():
        if isinstance(key, tuple) and key \
                and key[0] == "spectral.apply":
            dots[(key[2], key[1])] = len(
                re.findall(r"dot\(", exe.as_text()))
    # every (op, function) pair warmed, every program exactly 2 gemms
    assert set(dots) == (
        {("eig", f) for f in sp.EIG_FUNCTIONS}
        | {("svd", f) for f in sp.SVD_FUNCTIONS})
    assert all(v == 2 for v in dots.values()), dots


def test_tenant_conservation_with_spectral_traffic(served):
    """Per-tenant ledger rows still sum bit-exactly to the global
    counters with eig/svd factor+apply traffic in the mix, and the
    spectral tenants hold attributed flops."""
    sess = served["sess"]
    snap = sess.attribution.snapshot()
    from slate_tpu.obs.attribution import CLASSES
    for cls, counter in CLASSES.items():
        assert snap["totals"].get(cls, 0.0) \
            == sess.metrics.get(counter), cls
    per = {t: row["totals"] for t, row in snap["tenants"].items()}
    assert per["t-eig"].get("factor_flops", 0) > 0
    assert per["t-svd"].get("factor_flops", 0) > 0
    assert per["t-eig"].get("solve_flops", 0) > 0


def test_spectral_census_rows(served):
    """Every staged factor program went through the round-9 AOT cost
    census with a nonzero per-stage model numerator."""
    rows = {r["what"]: r for r in served["sess"].cost_log
            if r["what"].startswith("spectral.")}
    assert {"spectral.he2hb", "spectral.hb2td",
            "spectral.unmtr"} <= set(rows)
    assert {"spectral.ge2tb", "spectral.tb2bd",
            "spectral.unmbr"} <= set(rows)
    for what, r in rows.items():
        assert r["model_flops"] > 0, what
        assert "collective_bytes" in r, what


def test_mesh_census_collective_bytes(grid2x2):
    """On a 2x2 mesh the staged heev programs really run sharded:
    the scheduled-HLO collective census carries nonzero bytes."""
    rng = np.random.default_rng(11)
    n, nb = 64, 16
    a = _sym(rng, n, np.float32)
    sess = Session()
    h = sess.register(from_dense(a, nb, kind=MatrixKind.Hermitian,
                                 grid=grid2x2), op="eig")
    sess.factor(h)
    rows = [r for r in sess.cost_log
            if r["what"].startswith("spectral.")]
    assert rows
    assert sum(r["collective_bytes"] for r in rows) > 0
    assert any(r["collectives"] for r in rows)
    # and the mesh resident still serves correctly
    b = rng.standard_normal(n).astype(np.float32)
    x = sess.apply(h, b, fn="solve", theta=0.5)
    xd = np.linalg.solve(a.astype(np.float64) - 0.5 * np.eye(n), b)
    assert np.abs(x - xd).max() < 1e-3 * max(np.abs(xd).max(), 1.0)


# -- registration validation ------------------------------------------------


def test_register_validation():
    rng = np.random.default_rng(12)
    sess = Session()
    # eig requires a Hermitian/Symmetric square operand
    with pytest.raises(SlateError):
        sess.register(from_dense(rng.standard_normal((32, 32)), 16),
                      op="eig")
    # svd rejects wide (register the transpose)
    with pytest.raises(SlateError):
        sess.register(from_dense(rng.standard_normal((16, 32)), 16),
                      op="svd")
    # apply() is a spectral-only verb; fn must come from the catalog
    a = _sym(rng, 32, np.float32)
    spd = (a @ a.T / 32 + 32 * np.eye(32)).astype(np.float32)
    hc = sess.register(from_dense(spd, 16, kind=MatrixKind.Hermitian),
                       op="chol")
    with pytest.raises(SlateError):
        sess.apply(hc, np.zeros(32, np.float32))
    he = sess.register(from_dense(a, 16, kind=MatrixKind.Hermitian),
                      op="eig")
    with pytest.raises(SlateError):
        sess.apply(he, np.zeros(32, np.float32), fn="sqrtm")


# -- checkpoint / restore ---------------------------------------------------


def test_checkpoint_restore_bit_identical(served, tmp_path):
    """Save/restore of eig_factors/svd_factors nodes: the restored
    resident applies BIT-identically on the same placement with zero
    refactors, and the manifest passes both the runtime validator and
    the jax-free bench_gate mirror."""
    sess = served["sess"]
    rng = np.random.default_rng(13)
    b = rng.standard_normal((served["n"], 2))
    bm = rng.standard_normal((served["m"], 2))
    x0 = sess.apply(served["he"], b, fn="solve", theta=0.4)
    y0 = sess.apply(served["hs"], bm, fn="solve", theta=0.4)
    man = ckpt.save_session(sess, str(tmp_path))
    assert ckpt.validate_manifest(man) == []
    assert _bench_gate().validate_checkpoint_manifest(
        str(tmp_path)) == []
    sess2 = Session()
    ckpt.restore_session(sess2, str(tmp_path))
    assert sess2.metrics.get("factors_total") == 0
    x1 = sess2.apply(served["he"], b, fn="solve", theta=0.4)
    y1 = sess2.apply(served["hs"], bm, fn="solve", theta=0.4)
    assert np.array_equal(x0, x1)
    assert np.array_equal(y0, y1)
    assert sess2.metrics.get("factors_total") == 0


def test_checkpoint_mirror_rejects_malformed_spectral_nodes():
    """Both validators (runtime + jax-free mirror) reject the same
    malformed eig_factors/svd_factors nodes — the round-17 drift
    discipline extended to the round-19 node types."""
    bg = _bench_gate()
    blob = {k: None for k in ckpt.CHECKPOINT_BLOB_KEYS}
    tiled = {"type": "tiled", "data": dict(blob)}
    good_rec = {k: None for k in ckpt.CHECKPOINT_RECORD_KEYS}
    good_rec.update(handle="h", handle_type="str", op="eig",
                    m=4, n=4, band=0, dtype="float64", nb=2,
                    info=0, heat=0.0,
                    operator=dict(tiled),
                    payload={"type": "eig_factors", "v": dict(tiled),
                             "lam": dict(blob)})
    good = {"schema": ckpt.CHECKPOINT_SCHEMA, "host": "x",
            "generated_at": 0.0, "records": [good_rec]}
    assert ckpt.validate_manifest(good) == []
    assert bg.validate_checkpoint_manifest(good) == []
    svd_rec = dict(good_rec, op="svd",
                   payload={"type": "svd_factors", "u": dict(tiled),
                            "s": dict(blob), "v": dict(tiled)})
    good_svd = dict(good, records=[svd_rec])
    assert ckpt.validate_manifest(good_svd) == []
    assert bg.validate_checkpoint_manifest(good_svd) == []
    bad_payloads = [
        {"type": "eig_factors", "v": dict(tiled)},           # no lam
        {"type": "eig_factors", "v": {"data": dict(blob)},   # v not a
         "lam": dict(blob)},                                 # node
        {"type": "eig_factors", "v": dict(tiled),
         "lam": {"blob": "x"}},                              # short blob
        {"type": "svd_factors", "u": dict(tiled),
         "s": dict(blob)},                                   # no v
        {"type": "svd_factors", "u": None, "s": dict(blob),
         "v": dict(tiled)},
    ]
    for p in bad_payloads:
        doc = dict(good, records=[dict(good_rec, payload=p)])
        assert ckpt.validate_manifest(doc), p
        assert bg.validate_checkpoint_manifest(doc), p


# -- batched / executor path ------------------------------------------------


def test_executor_serves_spectral_default_solve():
    """Fleet citizenship at the dispatch layer: a spectral handle
    submitted through the Executor/Batcher (the fleet's path) serves
    the default solve apply — per-handle bucket, zero special-casing
    in the batching engine."""
    from slate_tpu.runtime import Executor

    rng = np.random.default_rng(14)
    n, nb = 32, 16
    a = _sym(rng, n, np.float32)
    sess = Session()
    h = sess.register(from_dense(a, nb, kind=MatrixKind.Hermitian),
                      op="eig")
    with Executor(sess, max_batch=4, max_wait=3600.0) as ex:
        ex.warmup([h])
        futs = [ex.submit(h, rng.standard_normal(n).astype(np.float32))
                for _ in range(4)]
        xs = [f.result(timeout=600) for f in futs]
    assert all(x.shape == (n,) for x in xs)
    # theta=0 solve: x = A^{-1} b through the eigenbasis
    # (spot check the last one; recompute the rng draw sequence)
    rng2 = np.random.default_rng(14)
    rng2.standard_normal((n, n))  # skip the operand draw
    draws = [rng2.standard_normal(n).astype(np.float32)
             for _ in range(4)]
    xd = np.linalg.solve(a.astype(np.float64), draws[-1])
    assert np.abs(xs[-1] - xd).max() < 1e-3 * max(np.abs(xd).max(),
                                                  1.0)
