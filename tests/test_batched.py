"""Many-small-problems engine (ISSUE 6 tentpole): batched tiled
factorizations/solves over [B, n, n] stacks, the pow2 batch-bucket
program cache, the api verbs' B×model ledger crediting, and the
Batcher's distinct-operator grouped dispatch.

The load-bearing invariant everywhere: the hand-batched kernels'
arithmetic is batch-independent, so a batched program's per-item lanes
are BIT-IDENTICAL to a loop of B=1 runs — which is what lets the
serving runtime swap per-request dispatch for one batched program per
bucket without changing a single bit of any response.
"""

import re

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.exceptions import SlateError
from slate_tpu.linalg import batched as lb
from slate_tpu.obs.flops import LEDGER, getrf as fl_getrf, \
    potrf as fl_potrf, geqrf as fl_geqrf, gels as fl_gels, solve_flops
from slate_tpu.runtime import Executor, Session

RNG = np.random.default_rng(1007)
# complex64 params of the cross-bucket sweeps carry the biggest compile
# bills and pin the few-ulp CPU caveat rather than the exact guarantee;
# they run under -m slow (tier-1 keeps c64 WITHIN-bucket exactness via
# test_bucket_padding_never_changes_bits)
C64_SLOW = pytest.param(np.complex64, marks=pytest.mark.slow)
DTYPES_FAST = [np.float32, np.float64, np.complex64]


def _stack(b, m, n, dtype):
    a = RNG.standard_normal((b, m, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = (a + 1j * RNG.standard_normal((b, m, n))).astype(dtype)
    return a


def _spd_stack(b, n, dtype):
    a = _stack(b, n, n, dtype)
    return (a @ np.conj(np.swapaxes(a, 1, 2))
            + n * np.eye(n, dtype=dtype)).astype(dtype)


def _assert_lane_matches(dtype, got, want):
    """Cross-BUCKET lane comparison. Real dtypes: exact. Complex:
    XLA:CPU contracts the real mul/add pairs inside fused complex
    arithmetic into FMAs differently at different batch shapes (a
    single complex multiply reproduces it — NOT a reduction-order
    effect, and optimization_barrier does not stop it), so a c64 lane
    agrees with its B=1 run only to a few ulp across buckets. WITHIN a
    bucket program, complex lanes are exact too
    (test_bucket_padding_never_changes_bits). On TPU complex matmuls
    lower to real MXU pairs — this is a CPU-backend caveat, documented
    in PERF.md Round 10."""
    got, want = np.asarray(got), np.asarray(want)
    if np.issubdtype(dtype, np.complexfloating):
        eps = np.finfo(np.zeros(1, dtype).real.dtype).eps
        np.testing.assert_allclose(got, want, rtol=64 * eps,
                                   atol=64 * eps * np.abs(want).max())
    else:
        assert np.array_equal(got, want)


# -- bit-identity: batched vs loop of singles, across dtypes ---------------


@pytest.mark.parametrize("dtype", [pytest.param(
    np.float32, marks=pytest.mark.slow), np.float64, C64_SLOW])
def test_gesv_batched_bit_identical_to_singles(dtype):
    b, n = 5, 32                       # B=5: pads to the 8-bucket
    a = _stack(b, n, n, dtype)
    rhs = _stack(b, n, 2, dtype)
    x, info = lb.gesv_batched(a, rhs)
    assert np.all(np.asarray(info) == 0)
    for i in range(b):
        xi, _ = lb.gesv_batched(a[i:i + 1], rhs[i:i + 1])
        _assert_lane_matches(dtype, x[i], xi[0])
    # and it actually solves
    eps = np.finfo(np.zeros(1, dtype).real.dtype).eps
    resid = np.linalg.norm(a @ np.asarray(x) - rhs)
    assert resid / np.linalg.norm(rhs) < 100 * n * eps


@pytest.mark.parametrize("dtype", [pytest.param(
    np.float32, marks=pytest.mark.slow), np.float64, C64_SLOW])
def test_posv_batched_bit_identical_to_singles(dtype):
    b, n = 5, 32
    a = _spd_stack(b, n, dtype)
    rhs = _stack(b, n, 2, dtype)
    x, info = lb.posv_batched(a, rhs)
    assert np.all(np.asarray(info) == 0)
    for i in range(b):
        xi, _ = lb.posv_batched(a[i:i + 1], rhs[i:i + 1])
        _assert_lane_matches(dtype, x[i], xi[0])


@pytest.mark.parametrize("dtype", [pytest.param(
    np.float32, marks=pytest.mark.slow), np.float64, C64_SLOW])
def test_gels_batched_bit_identical_and_correct(dtype):
    b, m, n = 5, 48, 32
    a = _stack(b, m, n, dtype)
    rhs = _stack(b, m, 2, dtype)
    x, info = lb.gels_batched(a, rhs)
    assert np.all(np.asarray(info) == 0)
    for i in range(b):
        xi, _ = lb.gels_batched(a[i:i + 1], rhs[i:i + 1])
        _assert_lane_matches(dtype, x[i], xi[0])
    ref = np.stack([np.linalg.lstsq(a[i], rhs[i], rcond=None)[0]
                    for i in range(b)])
    tol = 1e-4 if np.dtype(dtype).itemsize <= 8 else 1e-10
    assert np.abs(np.asarray(x) - ref).max() < tol


@pytest.mark.parametrize("dtype", DTYPES_FAST)
def test_bucket_padding_never_changes_bits(dtype):
    # the same leading items through different paddings of the SAME
    # pow2 bucket: identical lanes for every dtype (one program, lanes
    # are independent — the padding cannot perturb a live lane)
    n = 32
    a = _stack(8, n, n, dtype)
    rhs = _stack(8, n, 2, dtype)
    x8, _ = lb.gesv_batched(a, rhs)                    # exact bucket
    x5, _ = lb.gesv_batched(a[:5], rhs[:5])            # padded 5 -> 8
    x6, _ = lb.gesv_batched(a[:6], rhs[:6])            # padded 6 -> 8
    assert np.array_equal(np.asarray(x8)[:5], np.asarray(x5))
    assert np.array_equal(np.asarray(x8)[:6], np.asarray(x6))
    # a DIFFERENT bucket (3 -> 4) is a different compiled shape: exact
    # for real dtypes, few-ulp for complex (see _assert_lane_matches)
    x3, _ = lb.gesv_batched(a[:3], rhs[:3])
    _assert_lane_matches(dtype, np.asarray(x8)[:3], np.asarray(x3))


def test_vector_rhs_matches_matrix_rhs_column():
    # [B, n] vectors go through the k>=2 pad internally and come back
    # rank-2; bits equal the same column solved as a [B, n, 1] stack
    n = 32
    a = _stack(4, n, n, np.float64)
    rhs = _stack(4, n, 1, np.float64)
    xm, _ = lb.gesv_batched(a, rhs)
    xv, _ = lb.gesv_batched(a, rhs[:, :, 0])
    assert xv.shape == (4, n)
    assert np.array_equal(np.asarray(xm)[:, :, 0], np.asarray(xv))


# -- factor/solve-using-factor drivers -------------------------------------


def test_getrf_getrs_batched_roundtrip():
    b, n = 4, 40
    a = _stack(b, n, n, np.float64)
    lu, perm, info = lb.getrf_batched(a)
    assert np.all(np.asarray(info) == 0)
    # gather semantics: a[perm] = L @ U per item
    lum = np.asarray(lu)
    l = np.tril(lum, -1) + np.eye(n)
    u = np.triu(lum)
    ap = np.take_along_axis(a, np.asarray(perm)[:, :, None], axis=1)
    assert np.abs(l @ u - ap).max() < 1e-10 * n
    rhs = _stack(b, n, 3, np.float64)
    x = lb.getrs_batched(lu, perm, rhs)
    assert np.abs(a @ np.asarray(x) - rhs).max() < 1e-9 * n
    # multi-panel (n > nb) batch-independence: lanes of the B=4 factor
    # equal a B=1 run bit-for-bit (the dtype sweep pins n=32 = one
    # panel; this is the blocked outer loop's pin)
    lu1, perm1, _ = lb.getrf_batched(a[1:2])
    assert np.array_equal(np.asarray(lu[1]), np.asarray(lu1[0]))
    assert np.array_equal(np.asarray(perm[1]), np.asarray(perm1[0]))


@pytest.mark.slow  # ~6 s (round-22 tier-1 budget); tier-1 sibling —
# the float64 arm of test_posv_batched_bit_identical_to_singles runs
# the same potrf_batched/potrs_batched pair lane-for-lane
def test_potrf_potrs_batched_roundtrip():
    b, n = 4, 40
    a = _spd_stack(b, n, np.float64)
    l, info = lb.potrf_batched(a)
    assert np.all(np.asarray(info) == 0)
    lm = np.asarray(l)
    assert np.abs(lm @ np.swapaxes(lm, 1, 2) - a).max() < 1e-10 * n
    rhs = _stack(b, n, 3, np.float64)
    x = lb.potrs_batched(l, rhs)
    assert np.abs(a @ np.asarray(x) - rhs).max() < 1e-9 * n


def test_geqrf_batched_factor_and_solve():
    b, m, n = 3, 48, 40
    a = _stack(b, m, n, np.float64)
    vr, taus, ts = lb.geqrf_batched(a)
    assert vr.shape == (b, m, n) and taus.shape == (b, n)
    # R's diagonal blocks live in the packed upper triangle
    r = np.triu(np.asarray(vr)[:, :n, :n])
    rhs = _stack(b, m, 2, np.float64)
    x = lb.gels_batched_using_factor(vr, taus, ts, rhs)
    ref = np.stack([np.linalg.lstsq(a[i], rhs[i], rcond=None)[0]
                    for i in range(b)])
    assert np.abs(np.asarray(x) - ref).max() < 1e-9
    # |diag R| matches numpy's QR up to sign
    rq = np.stack([np.abs(np.diag(np.linalg.qr(a[i], mode="r")))
                   for i in range(b)])
    assert np.abs(np.abs(np.diagonal(r, axis1=1, axis2=2)) - rq).max() \
        < 1e-9 * m


# -- per-item failure isolation --------------------------------------------


def test_singular_item_flags_itself_only():
    b, n = 5, 32
    a = _stack(b, n, n, np.float64)
    rhs = _stack(b, n, 2, np.float64)
    x_ref, _ = lb.gesv_batched(a, rhs)
    bad = a.copy()
    bad[2] = 0.0
    x, info = lb.gesv_batched(bad, rhs)
    info = np.asarray(info)
    assert info[2] != 0 and np.all(info[[0, 1, 3, 4]] == 0)
    for i in (0, 1, 3, 4):
        assert np.array_equal(np.asarray(x[i]), np.asarray(x_ref[i]))


@pytest.mark.slow  # ~8 s (round-10 headroom); per-item isolation stays
# tier-1 via the LU arm + the Batcher grouped-singular test
def test_non_spd_item_flags_itself_only():
    b, n = 4, 32
    a = _spd_stack(b, n, np.float64)
    rhs = _stack(b, n, 2, np.float64)
    x_ref, _ = lb.posv_batched(a, rhs)
    bad = a.copy()
    bad[1] = -bad[1]
    x, info = lb.posv_batched(bad, rhs)
    info = np.asarray(info)
    assert info[1] == 1 and np.all(info[[0, 2, 3]] == 0)
    for i in (0, 2, 3):
        assert np.array_equal(np.asarray(x[i]), np.asarray(x_ref[i]))


# -- pow2 bucket compilation + HLO structure -------------------------------


def test_bucket_compiles_once_per_pow2_bucket():
    lb.clear_programs()
    n = 32
    a = _stack(8, n, n, np.float32)
    rhs = _stack(8, n, 2, np.float32)
    lb.gesv_batched(a[:5], rhs[:5])        # 5 -> bucket 8: compile 1
    c1 = lb.bucket_stats()["compiles"]
    lb.gesv_batched(a[:6], rhs[:6])        # 6 -> bucket 8: cache hit
    lb.gesv_batched(a[:8], rhs[:8])        # 8 -> bucket 8: cache hit
    assert lb.bucket_stats()["compiles"] == c1
    lb.gesv_batched(a[:3], rhs[:3])        # 3 -> bucket 4: compile 2
    assert lb.bucket_stats()["compiles"] == c1 + 1


def test_batched_hlo_has_no_per_item_factorization_custom_call():
    # THE lowering claim (round 7's measurement, generalized): the
    # batched program must not contain per-item factorization custom
    # calls (a vmapped lax.linalg.lu lowers to a sequential per-item
    # custom-call loop). Batch parallelism lives inside fused ops.
    lb.clear_programs()
    n = 32
    a = _stack(4, n, n, np.float32)
    rhs = _stack(4, n, 2, np.float32)
    lb.gesv_batched(a, rhs)
    texts = lb.bucket_hlo("gesv_batched")
    assert texts, "expected a cached batched program"
    pat = re.compile(r"custom-call.*(getrf|potrf|geqrf|lu|cholesky)",
                     re.IGNORECASE)
    for t in texts:
        assert not pat.search(t)


# -- api verbs: B x model ledger crediting ---------------------------------


def test_api_batched_gesv_credits_b_times_model():
    """Tier-1 sibling of the 4-verb sweep below (round-22 budget):
    one verb pins the B x model-formula crediting contract."""
    b, n, k = 3, 16, 2
    LEDGER.reset()
    st.gesv_batched(_stack(b, n, n, np.float32), _stack(b, n, k,
                                                        np.float32))
    assert LEDGER.snapshot()["per_op"]["gesv_batched"] == b * (
        fl_getrf(n) + solve_flops("lu", n, n, k))


@pytest.mark.slow  # ~8 s: four verb compiles (round-22 tier-1
# budget); tier-1 siblings — test_api_batched_gesv_credits_b_times_model
# (the B x model contract) and test_api_batched_verbs_validate_shapes
# (the API surface)
def test_api_batched_verbs_credit_b_times_model():
    b, m, n, k = 3, 24, 16, 2
    LEDGER.reset()
    a = _stack(b, n, n, np.float32)
    rhs = _stack(b, n, k, np.float32)
    st.gesv_batched(a, rhs)
    st.posv_batched(_spd_stack(b, n, np.float32), rhs)
    ta = _stack(b, m, n, np.float32)
    st.geqrf_batched(ta)
    st.gels_batched(ta, _stack(b, m, k, np.float32))
    per_op = LEDGER.snapshot()["per_op"]
    assert per_op["gesv_batched"] == b * (
        fl_getrf(n) + solve_flops("lu", n, n, k))
    assert per_op["posv_batched"] == b * (
        fl_potrf(n) + solve_flops("chol", n, n, k))
    assert per_op["geqrf_batched"] == b * fl_geqrf(m, n)
    assert per_op["gels_batched"] == b * fl_gels(m, n)


def test_api_batched_verbs_validate_shapes():
    with pytest.raises(SlateError):
        st.gesv_batched(np.zeros((4, 4)), np.zeros((4, 1)))  # no batch dim
    with pytest.raises(SlateError):
        st.gels_batched(np.zeros((2, 3, 8)), np.zeros((2, 3, 1)))  # m < n


# -- api mixed-precision verbs (satellite: ROADMAP item 2 first step) ------


def test_api_mixed_verbs_surface_iters_and_credit_ledger():
    n, nb = 32, 16
    a = RNG.standard_normal((n, n))
    spd = a @ a.T + n * np.eye(n)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)
    B = st.from_dense(RNG.standard_normal((n, 2)), nb=nb)
    LEDGER.reset()
    X, info, iters = st.api.posv_mixed(A, B)
    assert int(info) == 0 and isinstance(iters, int) and iters > 0
    assert np.abs(spd @ X.to_numpy() - B.to_numpy()).max() < 1e-10 * n
    per_op = LEDGER.snapshot()["per_op"]
    assert per_op["posv_mixed"] > 0


@pytest.mark.slow
def test_api_gesv_mixed_surfaces_iters():
    n, nb = 32, 16
    a = RNG.standard_normal((n, n))
    B = st.from_dense(RNG.standard_normal((n, 2)), nb=nb)
    LEDGER.reset()
    Ag = st.from_dense(a + n * np.eye(n), nb=nb)
    X2, info2, iters2 = st.api.gesv_mixed(Ag, B)
    assert int(info2) == 0 and iters2 > 0
    assert LEDGER.snapshot()["per_op"]["gesv_mixed"] > 0


@pytest.mark.slow
def test_api_mixed_gmres_verbs_surface_iters():
    n, nb = 32, 16
    a = RNG.standard_normal((n, n))
    spd = a @ a.T + n * np.eye(n)
    A = st.hermitian(np.tril(spd), nb=nb, uplo=st.Uplo.Lower)
    B = st.from_dense(RNG.standard_normal((n, 2)), nb=nb)
    LEDGER.reset()
    Ag = st.from_dense(a + n * np.eye(n), nb=nb)
    X3, info3, iters3 = st.api.gesv_mixed_gmres(Ag, B)
    assert int(info3) == 0 and iters3 > 0
    X4, info4, iters4 = st.api.posv_mixed_gmres(A, B)
    assert int(info4) == 0 and iters4 > 0
    per_op = LEDGER.snapshot()["per_op"]
    for verb in ("gesv_mixed_gmres", "posv_mixed_gmres"):
        assert per_op[verb] > 0


# -- serving: Session small ops + Batcher grouped dispatch ------------------


def _ops_and_rhs(nops=6, n=32, dtype=np.float32, spd=False):
    if spd:
        mats = [m for m in _spd_stack(nops, n, dtype)]
    else:
        mats = [m for m in _stack(nops, n, n, dtype)]
    rhs = [RNG.standard_normal(n).astype(dtype) for _ in range(nops)]
    return mats, rhs


def test_session_small_op_per_request_solve():
    mats, rhs = _ops_and_rhs(2)
    sess = Session()
    h = sess.register(mats[0])            # auto -> lu_small
    assert sess.small_group_key(h) == ("lu_small", 32, "float32")
    x = sess.solve(h, rhs[0])
    assert np.abs(mats[0] @ x - rhs[0]).max() < 1e-2
    # factor is resident now; a second solve hits
    sess.solve(h, rhs[1])
    snap = sess.metrics.snapshot()["counters"]
    assert snap["cache_hits"] == 1 and snap["cache_misses"] == 1
    with pytest.raises(SlateError):
        sess.solve_matrix(h, st.from_dense(rhs[0][:, None], nb=16))


def test_session_register_small_validation():
    sess = Session()
    with pytest.raises(SlateError):
        sess.register(np.zeros((4, 6)))               # not square
    with pytest.raises(SlateError):
        sess.register(np.zeros((4, 4)), op="lu")      # dense op, array
    with pytest.raises(SlateError):                   # small op, matrix
        sess.register(st.from_dense(np.eye(8), nb=4), op="lu_small")


@pytest.mark.parametrize("op,spd", [
    ("lu_small", False),
    pytest.param("chol_small", True, marks=pytest.mark.slow)])
def test_batcher_grouped_dispatch_bit_identical_to_per_request(op, spd):
    mats, rhs = _ops_and_rhs(6, spd=spd)
    # per-request reference: each request solved alone
    s_ref = Session()
    h_ref = [s_ref.register(m, op=op) for m in mats]
    ref = [s_ref.solve(h, b) for h, b in zip(h_ref, rhs)]
    # grouped: distinct operators coalesce into ONE bucket per shape
    sess = Session()
    hs = [sess.register(m, op=op) for m in mats]
    with Executor(sess, max_batch=16, max_wait=0.05) as ex:
        futs = [ex.submit(h, b) for h, b in zip(hs, rhs)]
        xs = [f.result(timeout=120) for f in futs]
    for a, b in zip(ref, xs):
        assert np.array_equal(a, b)      # cold: batched factor + solve
    with Executor(sess, max_batch=16, max_wait=0.05) as ex:
        futs = [ex.submit(h, b) for h, b in zip(hs, rhs)]
        xs2 = [f.result(timeout=120) for f in futs]
    for a, b in zip(ref, xs2):
        assert np.array_equal(a, b)      # warm: stacked resident solve
    c = sess.metrics.snapshot()["counters"]
    # cold bucket = 2 batched programs (factor the misses + solve all),
    # warm bucket = 1 (solve only); 6 misses then 6 hits
    assert c["batched_programs"] == 3
    assert c["cache_misses"] == 6 and c["cache_hits"] == 6
    occ = sess.metrics.snapshot()["histograms"]["bucket_occupancy"]
    assert occ["count"] == 2 and abs(occ["mean"] - 6 / 8) < 1e-9


def test_batcher_grouped_singular_item_fails_only_its_future():
    mats, rhs = _ops_and_rhs(5)
    ref = [Session() for _ in mats]
    h_ref = [s.register(m) for s, m in zip(ref, mats)]
    ref_x = [s.solve(h, b) for s, h, b in zip(ref, h_ref, rhs)]
    mats[2] = np.zeros_like(mats[2])
    sess = Session()
    hs = [sess.register(m) for m in mats]
    with Executor(sess, max_batch=16, max_wait=0.05) as ex:
        futs = [ex.submit(h, b) for h, b in zip(hs, rhs)]
        outs = []
        for f in futs:
            try:
                outs.append(f.result(timeout=120))
            except SlateError as e:
                outs.append(e)
    assert isinstance(outs[2], SlateError) and "info" in str(outs[2])
    for i in (0, 1, 3, 4):
        assert np.array_equal(outs[i], ref_x[i])


def test_batcher_same_operator_requests_still_batch():
    # N requests against ONE small operator: grouped dispatch stacks
    # the same resident factor N times — still one program, still
    # bit-identical to per-request
    mats, rhs = _ops_and_rhs(1)
    s_ref = Session()
    h0 = s_ref.register(mats[0])
    ref = [s_ref.solve(h0, b) for b in rhs * 3]
    sess = Session()
    h = sess.register(mats[0])
    with Executor(sess, max_batch=8, max_wait=0.05) as ex:
        futs = [ex.submit(h, b) for b in rhs * 3]
        xs = [f.result(timeout=120) for f in futs]
    for a, b in zip(ref, xs):
        assert np.array_equal(a, b)
    c = sess.metrics.snapshot()["counters"]
    assert c["batches_total"] == 1
    # duplicate-handle tallies must match B sequential per-request
    # solves: 1 miss (the first cold request) + 2 hits (review fix)
    assert c["cache_misses"] == 1 and c["cache_hits"] == 2


def test_session_warmup_small_op_primes_bucket_programs():
    from slate_tpu.obs.costs import BYTES
    mats, rhs = _ops_and_rhs(1)
    sess = Session()
    h = sess.register(mats[0])
    before = BYTES.snapshot()["per_op"].get("getrs_batched")
    sess.warmup(h)
    # the zero-rhs warmup PROBE populates the solve bucket program but
    # must not credit the bytes ledger as served traffic (review fix;
    # the factor is real cached work and IS credited)
    assert BYTES.snapshot()["per_op"].get("getrs_batched") == before
    c0 = lb.bucket_stats()["compiles"]
    x = sess.solve(h, rhs[0])           # must hit the primed programs
    assert lb.bucket_stats()["compiles"] == c0
    assert np.abs(mats[0] @ x - rhs[0]).max() < 1e-2
    assert BYTES.snapshot()["per_op"].get("getrs_batched") != before


def test_public_mixed_verbs_are_instrumented_wrappers():
    # st.gesv_mixed must be the api wrapper (flop-ledger crediting like
    # every public verb), not the raw linalg driver (review fix)
    from slate_tpu.linalg import lu as lu_mod
    assert st.gesv_mixed is st.api.gesv_mixed
    assert st.posv_mixed is st.api.posv_mixed
    assert st.gesv_mixed_gmres is st.api.gesv_mixed_gmres
    assert st.posv_mixed_gmres is st.api.posv_mixed_gmres
    assert st.gesv_mixed is not lu_mod.gesv_mixed


def test_bucket_hlo_filters_by_batch_and_n():
    # the bench's per-row structural flag asserts about the ROW's own
    # bucket program — the filter must single it out (review fix)
    lb.clear_programs()
    n = 32
    a = _stack(3, n, n, np.float32)
    rhs = _stack(3, n, 2, np.float32)
    lb.gesv_batched(a, rhs)              # 3 -> bucket 4
    assert len(lb.bucket_hlo("gesv_batched", batch=4, n=n)) == 1
    assert lb.bucket_hlo("gesv_batched", batch=8, n=n) == []
    assert lb.bucket_hlo("gesv_batched", batch=4, n=64) == []
