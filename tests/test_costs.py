"""Cost-model observability (ISSUE 5): the HBM/bytes ledger, the
collective-traffic census, roofline reporting, and the Session's
peak-memory-truth HBM accounting.

Counterpart of tests/test_obs.py (the round-8 span/flops half). Fast:
one tiny (n=32, nb=16) LU session is warmed once per test that needs
jax; the census/roofline/ledger math is pure-host.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import obs
from slate_tpu.obs import costs as costs_mod
from slate_tpu.obs import flops as flops_mod
from slate_tpu.obs import roofline as roofline_mod
from slate_tpu.obs.tracing import Tracer
from slate_tpu.runtime import Executor, Session
from slate_tpu.runtime.session import _tree_nbytes

RNG = np.random.default_rng(31)
N, NB = 32, 16


def _lu_session(tracer=None, hbm_budget=None):
    sess = Session(tracer=tracer, hbm_budget=hbm_budget)
    a = RNG.standard_normal((N, N)) + N * np.eye(N)
    h = sess.register(st.from_dense(a, nb=NB), op="lu")
    return sess, h, a


# -- collective census / traffic model (pure host) --------------------------


def test_collective_traffic_model():
    # ring all-reduce: 2*(g-1)/g * payload per participant
    assert costs_mod.collective_traffic("all-reduce", 128, 4) == 192
    # all-gather / reduce-scatter: (g-1)/g of the gathered buffer
    assert costs_mod.collective_traffic("all-gather", 64, 4) == 48
    assert costs_mod.collective_traffic("reduce-scatter", 64, 4) == 48
    # permute / all-to-all: the payload crosses the link once
    assert costs_mod.collective_traffic("collective-permute", 16, 2) == 16
    # a single-participant (or unparsed) group moves nothing — for
    # EVERY kind (review pin: permute used to credit payload at g=1)
    assert costs_mod.collective_traffic("all-reduce", 128, 1) == 0
    assert costs_mod.collective_traffic("collective-permute", 16, 1) == 0


def test_parse_collectives_census():
    hlo = "\n".join([
        "HloModule jit_f",
        "  %p = f32[8,4]{1,0} parameter(0)",
        "  %ar = f32[8,4]{1,0} all-reduce(%p), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
        "  %ag = f32[16]{0} all-gather(f32[4]{0} %x), "
        "replica_groups={{0,1,2,3}}, dimensions={0}",
        "  %cp = f32[4]{0} collective-permute(%x), "
        "source_target_pairs={{0,1},{1,0}}",
        "  %dot = f32[8,8]{1,0} dot(%p, %p)",  # not a collective
    ])
    census = costs_mod.parse_collectives(hlo)
    assert sorted(census) == ["all-gather", "all-reduce",
                              "collective-permute"]
    ar = census["all-reduce"]
    assert ar.count == 1 and ar.group_size == 4
    assert ar.payload_bytes == 8 * 4 * 4  # f32[8,4]
    assert ar.traffic_bytes == 2 * 3 * ar.payload_bytes // 4
    ag = census["all-gather"]
    assert ag.payload_bytes == 16 * 4  # the gathered f32[16] result
    assert ag.traffic_bytes == 3 * ag.payload_bytes // 4
    cp = census["collective-permute"]
    assert cp.group_size == 2 and cp.traffic_bytes == 4 * 4


def test_parse_collectives_iota_replica_groups():
    # the TPU spelling: replica_groups=[n_groups, group_size]<=[total]
    # (review pin: the brace-only regex read these as group=1 -> zero
    # modeled traffic on exactly the backend the telemetry targets)
    hlo = ("  %ar = f32[8,4]{1,0} all-reduce(%p), "
           "replica_groups=[2,4]<=[8], to_apply=%add")
    census = costs_mod.parse_collectives(hlo)
    ar = census["all-reduce"]
    assert ar.group_size == 4
    assert ar.traffic_bytes == 2 * 3 * (8 * 4 * 4) // 4


def test_parse_collectives_while_trip_count_multiplies():
    # round 10: a collective inside a while BODY whose instruction
    # carries known_trip_count is credited once per iteration; a
    # data-dependent while (no trip count) keeps the counted-once
    # lower-bound fallback
    hlo = "\n".join([
        "HloModule m",
        "",
        "%region_0.24 (arg.25: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {",
        "  %ar.1 = f32[8,8]{1,0} all-reduce(%x), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
        "  ROOT %t = (s32[], f32[8,8]) tuple(%i, %ar.1)",
        "}",
        "",
        "%region_1.30 (arg.31: (s32[], f32[4])) -> (s32[], f32[4]) {",
        "  %ar.2 = f32[4]{0} all-reduce(%y), "
        "replica_groups={{0,1}}, to_apply=%add",
        "  ROOT %t2 = (s32[], f32[4]) tuple(%j, %ar.2)",
        "}",
        "",
        "ENTRY %main.40 (p0: f32[8,8]) -> f32[8,8] {",
        "  %w1 = (s32[], f32[8,8]) while(%init), condition=%cond.1, "
        "body=%region_0.24, "
        "backend_config={\"known_trip_count\":{\"n\":\"5\"}}",
        "  %w2 = (s32[], f32[4]) while(%init2), condition=%cond.2, "
        "body=%region_1.30",
        "  %ar.3 = f32[2,2]{1,0} all-reduce(%z), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
        "  ROOT %r = f32[8,8] get-tuple-element(%w1), index=1",
        "}",
    ])
    assert costs_mod.while_trip_counts(hlo) == {"region_0.24": 5}
    ar = costs_mod.parse_collectives(hlo)["all-reduce"]
    # counted body: 5 iterations x 256B ring g=4; data-dependent body:
    # once (16B g=2); entry: once (16B g=4)
    assert ar.count == 5 + 1 + 1
    body0 = 2 * 3 * (8 * 8 * 4) // 4
    assert ar.traffic_bytes == 5 * body0 + 2 * 1 * 16 // 2 + \
        2 * 3 * 16 // 4
    assert ar.payload_bytes == 5 * 256 + 16 + 16


def test_program_costs_never_raises_on_hostile_backend():
    class Hostile:
        def cost_analysis(self):
            raise NotImplementedError("no analysis on this backend")

        def as_text(self):
            raise RuntimeError("no HLO either")
        # no memory_analysis attribute at all

    pc = costs_mod.program_costs(Hostile())
    assert pc.flops is None and pc.bytes_accessed is None
    assert pc.temp_bytes is None and pc.partial is True
    assert pc.transient_bytes == 0 and pc.intensity() is None
    # the list-wrapped cost_analysis shape some jax versions return
    class Listy(Hostile):
        def cost_analysis(self):
            return [{"flops": 10.0, "bytes accessed": 5.0}]

    pc = costs_mod.program_costs(Listy())
    assert pc.flops == 10.0 and pc.intensity() == 2.0


def test_program_costs_real_compiled_program():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((16, 16), jnp.float32)
    pc = costs_mod.program_costs(
        jax.jit(lambda a: a @ a).lower(x).compile())
    # XLA:CPU (and every real backend) reports flops + bytes-accessed
    assert pc.flops and pc.flops >= 2 * 16 ** 3
    assert pc.bytes_accessed and pc.bytes_accessed > 0
    d = pc.to_dict()
    assert d["intensity"] == pytest.approx(pc.flops / pc.bytes_accessed)
    assert "transient_bytes" in d and "collectives" in d


# -- the bytes ledger -------------------------------------------------------


def test_bytes_ledger_accumulates_per_op_and_per_kind():
    led = costs_mod.BytesLedger()
    cc = costs_mod.CollectiveCost("all-reduce", count=2,
                                  payload_bytes=100, traffic_bytes=150)
    led.record("summa", bytes_accessed=1000.0, collective_bytes=150.0,
               collectives={"all-reduce": cc})
    led.record("summa", bytes_accessed=1000.0, collective_bytes=150.0,
               collectives={"all-reduce": cc})
    snap = led.snapshot()
    assert snap["bytes_total"] == 2000.0
    assert snap["collective_bytes_total"] == 300.0
    assert snap["per_op"]["summa"]["calls"] == 2
    assert snap["per_collective"]["all-reduce"] == {
        "bytes": 300.0, "count": 4}
    led.reset()
    assert led.snapshot()["bytes_total"] == 0.0


def test_call_analyzed_credits_per_call_and_caches_analysis():
    import jax.numpy as jnp

    led = costs_mod.BytesLedger()
    x = jnp.ones((8, 8), jnp.float32)
    f = lambda a: a @ a + 1.0  # noqa: E731
    r1 = costs_mod.call_analyzed(f, (x,), label="test.ca", ledger=led)
    r2 = costs_mod.call_analyzed(f, (x,), label="test.ca", ledger=led)
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    snap = led.snapshot()
    # every CALL credits; the AOT analysis ran once (cached by shape)
    assert snap["per_op"]["test.ca"]["calls"] == 2
    assert snap["per_op"]["test.ca"]["bytes"] > 0
    assert len(costs_mod.analyzed_costs("test.ca")) == 1


def test_call_analyzed_degrades_to_plain_call_under_trace():
    import jax
    import jax.numpy as jnp

    led = costs_mod.BytesLedger()

    @jax.jit
    def outer(a):
        # composed into a larger jitted program: the outer compile owns
        # the analysis; the inner driver must not credit or re-jit
        return costs_mod.call_analyzed(
            lambda y: y * 2.0, (a,), label="test.traced", ledger=led)

    out = outer(jnp.ones(4, jnp.float32))
    np.testing.assert_array_equal(np.asarray(out), 2 * np.ones(4))
    assert "test.traced" not in led.snapshot()["per_op"]


def test_mesh_driver_credits_collective_bytes():
    """Acceptance: collective bytes for at least one mesh driver. On
    the 8-device CPU mesh (conftest forces host_platform_device_count)
    the compiled SUMMA program's all-reduce census must land in the
    process bytes ledger."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs a >=4-device mesh")
    from slate_tpu.core.grid import ProcessGrid
    from slate_tpu.parallel.summa import gemm_summa

    base = costs_mod.BYTES.snapshot()["per_op"].get(
        "parallel.summa[2x2]", {"calls": 0, "collective_bytes": 0.0})
    g = ProcessGrid.create(2, 2)
    n, nb = 64, 16
    A = st.from_dense(RNG.standard_normal((n, n)), nb=nb, grid=g)
    B = st.from_dense(RNG.standard_normal((n, n)), nb=nb, grid=g)
    C = gemm_summa(1.0, A, B, 0.0, st.zeros(n, n, nb, A.dtype, grid=g))
    resid = np.abs(C.to_numpy() - A.to_numpy() @ B.to_numpy()).max()
    assert resid < 1e-10 * n
    row = costs_mod.BYTES.snapshot()["per_op"]["parallel.summa[2x2]"]
    assert row["calls"] == base["calls"] + 1
    assert row["collective_bytes"] > base["collective_bytes"]


# -- roofline ---------------------------------------------------------------


def test_roofline_row_bounds_and_attainable():
    m = roofline_mod.MachineModel(peak_gflops=100.0, hbm_gbps=10.0)
    assert m.ridge == 10.0
    # below the ridge: memory bound, attainable = ai * bandwidth
    row = roofline_mod.roofline_row("x", flops=1e9, bytes_=1e9,
                                    seconds=1.0, machine=m)
    assert row["intensity"] == 1.0 and row["bound"] == "memory"
    assert row["attainable_gflops"] == 10.0
    assert row["gflops"] == pytest.approx(1.0)
    assert row["roof_fraction"] == pytest.approx(0.1)
    # above the ridge: compute bound, attainable = peak
    row = roofline_mod.roofline_row("y", flops=1e12, bytes_=1e9,
                                    machine=m)
    assert row["bound"] == "compute"
    assert row["attainable_gflops"] == 100.0
    assert row["roof_fraction"] is None  # no measurement
    # unknown bytes: intensity/bound stay None, never a crash
    row = roofline_mod.roofline_row("z", flops=1e9, bytes_=None,
                                    seconds=1.0, machine=m)
    assert row["intensity"] is None and row["bound"] is None


def test_machine_model_from_env(monkeypatch):
    monkeypatch.delenv("SLATE_TPU_PEAK_GFLOPS", raising=False)
    monkeypatch.delenv("SLATE_TPU_HBM_GBPS", raising=False)
    assert roofline_mod.MachineModel.from_env() is None  # never guessed
    monkeypatch.setenv("SLATE_TPU_PEAK_GFLOPS", "919000")
    monkeypatch.setenv("SLATE_TPU_HBM_GBPS", "1200")
    m = roofline_mod.MachineModel.from_env()
    assert m.peak_gflops == 919000.0 and m.hbm_gbps == 1200.0
    assert m.ici_gbps is None


def test_roofline_report_joins_both_ledgers():
    fled = flops_mod.FlopLedger()
    bled = costs_mod.BytesLedger()
    fled.record("joined", 4e9)
    bled.record("joined", bytes_accessed=2e9, collective_bytes=1e6)
    fled.record("floponly", 1e9)
    rep = roofline_mod.roofline_report(
        ledger=fled, bytes_ledger=bled, timers={"api.joined": 2.0},
        machine=roofline_mod.MachineModel(100.0, 10.0))
    rows = {r["op"]: r for r in rep["rows"]}
    j = rows["joined"]
    assert j["intensity"] == 2.0 and j["collective_bytes"] == 1e6
    assert j["gflops"] == pytest.approx(2.0)
    assert j["bound"] == "memory"
    # flop-only ops still report (bytes honestly None), never dropped
    assert rows["floponly"]["bytes"] is None
    assert rep["flops_total"] == 5e9 and rep["bytes_total"] == 2e9


def test_gflops_report_gains_intensity_column():
    op = "test.rfjoin"
    flops_mod.LEDGER.record(op, 3e9)
    costs_mod.BYTES.record(op, bytes_accessed=1e9)
    row = flops_mod.LEDGER.gflops_report(timers={})["per_op"][op]
    assert row["bytes"] >= 1e9
    assert row["intensity"] == pytest.approx(row["flops"] / row["bytes"])


# -- Session: cost log, HBM truth, eviction telemetry -----------------------


def test_warmup_populates_cost_log_and_hbm_gauges():
    sess, h, a = _lu_session()
    sess.warmup(h)
    whats = sorted(r["what"] for r in sess.cost_log)
    assert whats == ["factor", "solve"]
    for row in sess.cost_log:
        for k in ("op", "what", "shape", "model_flops", "bytes_accessed",
                  "temp_bytes", "peak_bytes", "collective_bytes",
                  "transient_bytes", "partial"):
            assert k in row
        assert row["model_flops"] > 0
        assert row["bytes_accessed"] and row["bytes_accessed"] > 0
    snap = sess.metrics.snapshot()
    resident = snap["gauges"]["resident_bytes"]
    assert resident == sum(r.nbytes for r in sess._cache.values()) > 0
    # peak = factors + the largest resident program's transient
    assert snap["gauges"]["peak_hbm_bytes"] >= resident
    assert sess.hbm_headroom() is None  # unbounded session


def test_aot_solves_credit_bytes_ledger_per_execution():
    sess, h, a = _lu_session()
    sess.warmup(h)
    base = costs_mod.BYTES.snapshot()["per_op"].get(
        "serve.solve", {"calls": 0})["calls"]
    n_solves = 3
    for _ in range(n_solves):
        x = sess.solve(h, RNG.standard_normal(N))
        assert np.abs(a @ x - np.zeros(N)).shape  # shape sanity only
    row = costs_mod.BYTES.snapshot()["per_op"]["serve.solve"]
    assert row["calls"] == base + n_solves
    assert sess.metrics.get("bytes_accessed_total") > 0


def test_eviction_telemetry_and_headroom_gauge():
    sess, h1, _ = _lu_session()
    a2 = RNG.standard_normal((N, N)) + N * np.eye(N)
    h2 = sess.register(st.from_dense(a2, nb=NB), op="lu")
    sess.solve(h1, RNG.standard_normal(N))
    resident = sess.metrics.get_gauge("resident_bytes")
    assert resident > 0
    # budget admits ~one factor: inserting h2's factor must evict h1's
    sess.hbm_budget = int(resident * 1.5)
    sess.solve(h2, RNG.standard_normal(N))
    assert h1 not in sess._cache and h2 in sess._cache
    snap = sess.metrics.snapshot()
    assert snap["counters"]["evictions"] == 1
    assert snap["counters"]["evicted_bytes"] == resident
    assert snap["gauges"]["resident_bytes"] > 0
    assert (snap["gauges"]["hbm_headroom"]
            == sess.hbm_budget - snap["gauges"]["peak_hbm_bytes"])
    assert sess.hbm_headroom() == snap["gauges"]["hbm_headroom"]
    # explicit evict / clear_cache keep the byte telemetry flowing
    assert sess.evict(h2) is True
    assert sess.metrics.get("evictions") == 2
    assert sess.metrics.get("evicted_bytes") > resident
    assert sess.metrics.get_gauge("resident_bytes") == 0


def test_oom_risk_warning_when_budget_cannot_hold_the_factor(caplog):
    import logging

    sess, h, _ = _lu_session(hbm_budget=64)  # absurdly small
    with caplog.at_level(logging.WARNING, logger="slate_tpu.obs"):
        sess.solve(h, RNG.standard_normal(N))
    assert sess.metrics.get("budget_overflows") == 1
    assert sess.metrics.get("oom_risk_warnings") == 1
    assert sess.hbm_headroom() < 0  # negative headroom, published
    assert sess.metrics.get_gauge("hbm_headroom") < 0
    assert any("OOM risk" in r.message for r in caplog.records)


def test_tree_nbytes_never_host_transfers():
    """Satellite pin: cache accounting is shape/dtype metadata only —
    materializing a leaf (np.asarray) device-transfers the factor."""

    class DeviceOnlyLeaf:
        shape = (64, 32)
        dtype = np.dtype(np.float32)

        def __array__(self, *a, **k):  # the old fallback called this
            raise AssertionError(
                "_tree_nbytes host-transferred a device leaf")

    class OpaqueLeaf:  # no shape/dtype, but an nbytes it can report
        nbytes = 12345

    payload = {"f": DeviceOnlyLeaf(), "o": OpaqueLeaf(), "s": 3.5}
    total = _tree_nbytes(payload)
    assert total == 64 * 32 * 4 + 12345 + np.dtype(float).itemsize
    # and the real thing: a jax factor payload matches its metadata sum
    import jax.numpy as jnp

    arr = jnp.zeros((N, N), jnp.float32)
    assert _tree_nbytes([arr, jnp.zeros(N, jnp.int32)]) == N * N * 4 + N * 4


# -- concurrent scrapes while serving ---------------------------------------


def test_concurrent_scrapes_while_serving():
    """Satellite: /metrics and /trace.json hammered from two threads
    while the Executor serves must return consistent, parseable
    payloads (extends the round-8 lock-guard work on utils/trace.py)."""
    tracer = Tracer().on()
    sess, h, _ = _lu_session(tracer=tracer)
    errors, scraped = [], {"metrics": 0, "trace": 0}

    def scrape(path, check, key, stop):
        while not stop.is_set():
            try:
                body = urllib.request.urlopen(
                    srv.url(path), timeout=10).read().decode()
                check(body)
                scraped[key] += 1
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(f"{path}: {e!r}")
                return

    def check_metrics(body):
        assert "slate_tpu_uptime_seconds" in body
        assert "slate_tpu_driver_bytes_total" in body

    def check_trace(body):
        tr = json.loads(body)
        assert obs.validate_chrome_trace(tr) == []

    srv = sess.serve_obs()
    stop = threading.Event()
    threads = [
        threading.Thread(target=scrape,
                         args=("/metrics", check_metrics, "metrics", stop)),
        threading.Thread(target=scrape,
                         args=("/trace.json", check_trace, "trace", stop)),
    ]
    try:
        with Executor(sess, max_batch=4, max_wait=1e-3) as ex:
            ex.warmup([h])
            for t in threads:
                t.start()
            futs = [ex.submit(h, RNG.standard_normal(N))
                    for _ in range(24)]
            for f in futs:
                f.result(timeout=60)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        sess.close_obs()
    assert not errors, errors
    assert scraped["metrics"] > 0 and scraped["trace"] > 0
