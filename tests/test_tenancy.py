"""Multi-tenant isolation (runtime/tenancy.py + the round-18 seams).

The acceptance pins: quota enforcement at BOTH seams — a tenant over
its in-flight cap or flops/s rate is turned away at ``Batcher.submit``
with a counted :class:`QuotaExceeded` (the conservation partition's
``quota_rejected`` outcome, tenant-labeled), and a tenant over its HBM
sub-budget evicts ITS OWN residents LRU-first at the Session's
factor-insert seam while another tenant's residents are untouchable
(the isolation pin); the deficit-weighted round-robin starvation bound
is hand-pinned (a victim bucket dispatches within a weight-derived
position bound regardless of the aggressor's backlog depth) and
dispatch-order fairness is BIT-PARITY safe (same programs, different
order); grouped small-op dispatch keeps the round-15 tenant-labeled
"1 miss + B−1 hits" tallies with policies attached; fleet migration
moves a resident BYTE-IDENTICALLY with routed requests following
(zero lost futures, zero refactors) and a ``migration_abort`` leaves
the source serving; the disabled path (``tenant_policies is None``)
allocates nothing (the round-8 discipline extended).
"""

import numpy as np
import pytest

import slate_tpu as st  # noqa: F401 — jax/platform init via conftest
from slate_tpu.runtime import (Batcher, Fleet, QuotaExceeded, Session,
                               ShedPolicy, TenantPolicy, TenantTable,
                               TokenBucket)
from slate_tpu.runtime.tenancy import DeficitScheduler, as_table

RNG = np.random.default_rng(53)
N = 8  # small-problem engine: tiny bucket programs, no dense compiles


def _small_op(seed=0):
    rng = np.random.default_rng(200 + seed)
    return np.asarray(rng.standard_normal((N, N)) + N * np.eye(N))


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -- policy table -----------------------------------------------------------


def test_policy_validation():
    with pytest.raises(ValueError):
        TenantPolicy(weight=0.0)
    with pytest.raises(ValueError):
        TenantPolicy(max_in_flight=0)
    with pytest.raises(ValueError):
        TenantPolicy(max_resident_bytes=-1)
    with pytest.raises(ValueError):
        TenantPolicy(flops_per_s=0.0)
    with pytest.raises(TypeError):
        TenantTable({"a": object()})
    with pytest.raises(TypeError):
        as_table(["not", "a", "table"])
    assert as_table(None) is None
    t = as_table({"a": TenantPolicy(weight=2.0)})
    assert t.weight("a") == 2.0
    assert t.weight("unlisted") == 1.0  # no default -> unconstrained
    assert t.policy("unlisted") is None
    t2 = TenantTable({"a": TenantPolicy()},
                     default=TenantPolicy(max_in_flight=3))
    assert t2.policy("anyone").max_in_flight == 3


# -- deficit-weighted round-robin (the starvation bound) --------------------


def test_drr_starvation_bound_hand_pinned():
    """THE fairness pin: a weight-4 victim's single ready bucket
    dispatches within the first ceil(c/(q·w)) + 1 foreign buckets —
    position ≤ 2 here — INDEPENDENT of the aggressor's backlog depth
    (FIFO would put it at position backlog+1). Exercised at three
    backlog depths so the bound's depth-independence is the assertion,
    not an example."""
    for backlog in (4, 16, 64):
        table = TenantTable({"noisy": TenantPolicy(weight=1.0),
                             "victim": TenantPolicy(weight=4.0)})
        sched = DeficitScheduler(table)
        buckets = [("noisy", 4, f"n{i}") for i in range(backlog)]
        buckets.append(("victim", 4, "v0"))
        order = sched.order(buckets)
        assert sorted(order) == sorted(x for _, _, x in buckets)
        assert order.index("v0") <= 2, (backlog, order[:4])


def test_drr_long_run_shares_follow_weights():
    """Equal-cost buckets, weights 2:1 — the emitted prefix carries
    ~2 of the heavy tenant per 1 of the light one."""
    table = TenantTable({"a": TenantPolicy(weight=2.0),
                         "b": TenantPolicy(weight=1.0)})
    sched = DeficitScheduler(table)
    buckets = ([("a", 1, f"a{i}") for i in range(30)]
               + [("b", 1, f"b{i}") for i in range(30)])
    order = sched.order(buckets)
    head = order[:27]
    na = sum(1 for x in head if x.startswith("a"))
    nb = sum(1 for x in head if x.startswith("b"))
    assert na == 2 * nb, (na, nb)


def test_drr_deficit_bounded_and_single_tenant_fifo():
    """Carried deficits stay bounded by one quantum call over call
    (no banked-credit bursting), and a single-tenant snapshot is plain
    FIFO."""
    table = TenantTable({"a": TenantPolicy(weight=8.0),
                         "b": TenantPolicy(weight=1.0)})
    sched = DeficitScheduler(table)
    for _ in range(20):
        sched.order([("a", 1, "x"), ("b", 4, "y")])
    assert all(d <= 4.0 for d in sched.deficits().values()), \
        sched.deficits()
    assert sched.order([("a", 2, i) for i in range(5)]) == list(range(5))


def test_token_bucket_refill_pinned_under_injected_clock():
    clk = _FakeClock()
    tb = TokenBucket(rate=100.0, burst=50.0, clock=clk)
    assert tb.admit(50.0)          # starts full
    assert not tb.admit(1.0)       # drained
    clk.t += 0.25                  # refills 25 tokens
    assert tb.admit(25.0)
    assert not tb.admit(1.0)
    clk.t += 10.0                  # refill caps at burst depth
    assert tb.admit(50.0)
    assert not tb.admit(1.0)


# -- quota enforcement at Batcher.submit ------------------------------------


def test_inflight_cap_rejects_counted_and_isolated():
    """The (B+1)-th submit of a capped tenant fails fast with
    QuotaExceeded — counted in quota_rejections_total AND the
    tenant-labeled quota_rejected outcome cell — while another
    tenant's submits are untouched; the cap re-opens once the
    in-flight drains (resolution decrements on every path)."""
    sess = Session(tenant_policies={"t1": TenantPolicy(max_in_flight=2)})
    sess.enable_attribution()
    h1 = sess.register(_small_op(0), op="lu_small", tenant="t1")
    h2 = sess.register(_small_op(1), op="lu_small", tenant="t2")
    bat = Batcher(sess, max_batch=8, max_wait=3600.0)
    futs = [bat.submit(h1, RNG.standard_normal(N)) for _ in range(4)]
    rejected = [f for f in futs if f.done()
                and isinstance(f.exception(), QuotaExceeded)]
    assert len(rejected) == 2
    assert sess.metrics.get("quota_rejections_total") == 2.0
    f2 = bat.submit(h2, RNG.standard_normal(N))
    assert not f2.done()  # the other tenant is unaffected
    bat.flush()
    for f in futs:
        if f not in rejected:
            f.result()
    f2.result()
    assert bat.tenant_inflight("t1") == 0  # drained on resolution
    # conservation: per-tenant outcome cells partition the submissions
    snap = sess.attribution.snapshot()["tenants"]
    assert snap["t1"]["totals"]["quota_rejected"] == 2.0
    assert snap["t1"]["totals"]["completed"] == 2.0
    assert snap["t2"]["totals"]["completed"] == 1.0
    assert "quota_rejected" not in snap["t2"]["totals"]
    # re-opened: the drained tenant submits again
    f3 = bat.submit(h1, RNG.standard_normal(N))
    assert not f3.done()
    bat.flush()
    f3.result()


def test_flops_rate_quota_under_injected_clock():
    """The optional flops/s rate: a burst admits, the next submit is
    quota-rejected, advancing the injected clock re-admits — the
    TokenBucket refill math at the real seam."""
    clk = _FakeClock()
    cost = None
    sess = Session(tenant_policies={
        "t": TenantPolicy(flops_per_s=1.0, burst_s=1.0)})
    h = sess.register(_small_op(2), op="lu_small", tenant="t")
    cost = sess.recompute_cost(h, 1)
    assert cost > 0
    # rate sized so exactly ONE request fits the burst
    sess.tenant_policies = as_table({
        "t": TenantPolicy(flops_per_s=cost, burst_s=1.0)})
    bat = Batcher(sess, max_batch=8, max_wait=3600.0, clock=clk)
    f1 = bat.submit(h, RNG.standard_normal(N))
    assert not f1.done()
    f2 = bat.submit(h, RNG.standard_normal(N))
    assert isinstance(f2.exception(), QuotaExceeded)
    assert sess.metrics.get("quota_rejections_total") == 1.0
    clk.t += 1.0  # one second refills one request's cost
    f3 = bat.submit(h, RNG.standard_normal(N))
    assert not f3.done()
    bat.flush()
    f1.result()
    f3.result()


# -- quota enforcement at the Session's factor-insert seam ------------------


def test_per_tenant_eviction_isolation():
    """Tenant A blowing through its sub-budget evicts A's OWN LRU
    residents — tenant B's resident is untouchable by A's pressure
    (THE isolation pin), and the eviction is counted in
    tenant_quota_evictions_total."""
    bytes_one = None
    probe = Session()
    hp = probe.register(_small_op(10), op="lu_small")
    probe.solve(hp, RNG.standard_normal(N))
    bytes_one = probe._cache[hp].nbytes
    sess = Session(tenant_policies={
        "a": TenantPolicy(max_resident_bytes=2 * bytes_one)})
    sess.enable_attribution()
    hb = sess.register(_small_op(11), op="lu_small", tenant="b")
    has = [sess.register(_small_op(12 + i), op="lu_small", tenant="a")
           for i in range(3)]
    sess.solve(hb, RNG.standard_normal(N))
    for h in has:
        sess.solve(h, RNG.standard_normal(N))
    cached = sess.cached_handles()
    assert hb in cached                      # B survived A's pressure
    assert has[0] not in cached              # A's own LRU evicted
    assert has[1] in cached and has[2] in cached
    assert sess.metrics.get("tenant_quota_evictions_total") == 1.0
    assert sess.tenant_resident_bytes("a") == 2 * bytes_one
    assert sess.metrics.get_gauge(
        "tenant_quota_resident_bytes:a") == 2 * bytes_one
    assert sess.metrics.get_gauge(
        "tenant_quota_hbm_headroom:a") == 0.0
    q = sess.quotas_payload()
    assert q["enabled"] and q["tenants"]["a"]["residents"] == 2
    assert q["tenants"]["b"]["max_resident_bytes"] is None


def test_kept_factor_over_sub_budget_counts_overflow():
    """A single factor larger than its tenant's whole sub-budget is
    KEPT (you cannot serve without it) and counted — the
    budget_overflows convention, tenant-scoped."""
    sess = Session(tenant_policies={
        "a": TenantPolicy(max_resident_bytes=1)})
    h = sess.register(_small_op(20), op="lu_small", tenant="a")
    x = sess.solve(h, RNG.standard_normal(N))
    assert np.isfinite(np.asarray(x)).all()
    assert h in sess.cached_handles()
    assert sess.metrics.get("tenant_quota_overflows") >= 1.0


# -- weighted-fair dispatch through the Batcher -----------------------------


def test_pop_ready_drr_order_and_bit_parity():
    """End-to-end fairness pin: with an aggressor's deep backlog and
    one victim bucket queued, pop_ready's dispatch order puts the
    victim's bucket within the DRR bound (FIFO dict order would put
    it LAST); and the solutions are BIT-IDENTICAL to a FIFO batcher's
    — same buckets, same programs, different order."""
    def build(policies):
        sess = Session(tenant_policies=policies)
        hn = sess.register(_small_op(30), op="lu_small",
                           tenant="noisy")
        hv = sess.register(_small_op(31), op="lu_small",
                           tenant="victim")
        return sess, hn, hv

    rhs = [RNG.standard_normal(N) for _ in range(13)]

    def run(policies):
        sess, hn, hv = build(policies)
        bat = Batcher(sess, max_batch=2, max_wait=3600.0)
        futs = [bat.submit(hn, b, tenant="noisy") for b in rhs[:12]]
        futs.append(bat.submit(hv, rhs[12], tenant="victim"))
        order = []
        for key, reqs in bat.pop_ready(force=True):
            order.append(sess.request_tenant(reqs[0].handle,
                                             reqs[0].tenant))
            bat.run(key, reqs)
        return [np.asarray(f.result()) for f in futs], order

    fair_pol = {"noisy": TenantPolicy(weight=1.0),
                "victim": TenantPolicy(weight=2.0)}
    xs_fair, order_fair = run(fair_pol)
    xs_fifo, order_fifo = run(None)
    # FIFO: the victim's bucket dispatches dead last
    assert order_fifo[-1] == "victim" and len(order_fifo) == 7
    # DRR: within the starvation bound, not behind the whole backlog
    assert order_fair.index("victim") <= 2, order_fair
    # fair-share deficit gauges published for both tenants
    # (cardinality = tenants, the rollup discipline)
    # bit-parity: same programs, different order
    for a, b in zip(xs_fair, xs_fifo):
        assert (a == b).all()


def test_fair_share_deficit_gauges_published():
    sess = Session(tenant_policies={"a": TenantPolicy(),
                                    "b": TenantPolicy()})
    ha = sess.register(_small_op(32), op="lu_small", tenant="a")
    hb = sess.register(_small_op(33), op="lu_small", tenant="b")
    bat = Batcher(sess, max_batch=4, max_wait=3600.0)
    fa = bat.submit(ha, RNG.standard_normal(N), tenant="a")
    fb = bat.submit(hb, RNG.standard_normal(N), tenant="b")
    bat.flush()
    fa.result()
    fb.result()
    gauges = sess.metrics.snapshot()["gauges"]
    assert "fair_share_deficit:a" in gauges
    assert "fair_share_deficit:b" in gauges


# -- tenant-scoped shedding + breakers --------------------------------------


def test_tenant_scoped_shed_victimizes_only_the_burning_tenant():
    """A tenant-scoped Objective burning past the threshold sheds
    ONLY that tenant's queued requests (cheapest-first), counted in
    tenant_sheds_total; the other tenant's queue is untouched."""
    from slate_tpu.obs.slo import Objective, SloTracker

    clk = _FakeClock()
    slo = SloTracker((Objective("noisy_errors", "error_rate", 0.9,
                                tenant="noisy", windows=(60.0,)),),
                     clock=clk)
    sess = Session(tenant_policies={"noisy": TenantPolicy(),
                                    "victim": TenantPolicy()})
    sess.slo = slo
    slo.metrics = sess.metrics
    hn = sess.register(_small_op(40), op="lu_small", tenant="noisy")
    hv = sess.register(_small_op(41), op="lu_small", tenant="victim")
    # the noisy tenant's scoped objective burns (all-bad events)
    for _ in range(10):
        slo.record_request("lu_small", N, 0.0, ok=False,
                           tenant="noisy", t=clk.t)
    assert slo.tenant_burn_rates(now=clk.t)["noisy"] > 1.0
    bat = Batcher(sess, max_batch=64, max_wait=3600.0,
                  shed_policy=ShedPolicy(burn_threshold=1.0,
                                         shed_fraction=1.0,
                                         min_queue_depth=1,
                                         check_interval_s=0.0))
    nf = [bat.submit(hn, RNG.standard_normal(N), tenant="noisy")
          for _ in range(4)]
    vf = [bat.submit(hv, RNG.standard_normal(N), tenant="victim")
          for _ in range(4)]
    # the injected clock drives the burn-rate windows, so the shed
    # check evaluates at the same instant the events were recorded
    shed = bat.maybe_shed(now=clk.t)
    assert shed >= 1
    assert sess.metrics.get("tenant_sheds_total") == 1.0
    assert all(not f.done() for f in vf)       # victim untouched
    assert any(f.done() for f in nf)           # noisy paid
    bat.flush()
    for f in vf:
        f.result()


def test_breaker_key_tenant_scoped_for_explicit_tenants():
    """An explicit-tenant bucket's circuit breaker is (op, n, tenant)
    — a noisy tenant's failing traffic cannot open every tenant's
    same-shape breaker; implicit buckets keep the round-14 (op, n)
    grain."""
    from slate_tpu.runtime.executor import Executor

    sess = Session()
    h = sess.register(_small_op(42), op="lu_small")
    ex = Executor(sess, max_batch=2, max_wait=3600.0)
    try:
        req, _ = ex.batcher.submit_deferred(h, RNG.standard_normal(N),
                                            tenant="noisy")
        (key, reqs), = ex.batcher.pop_ready(force=True)
        bk = ex._breaker_key(key, reqs)
        assert bk[-1] == "noisy" and len(bk) == 3
        ex.batcher.run(key, reqs)
        req.future.result()
        req2, _ = ex.batcher.submit_deferred(h, RNG.standard_normal(N))
        (key2, reqs2), = ex.batcher.pop_ready(force=True)
        assert ex._breaker_key(key2, reqs2) == ("lu_small", N)
        ex.batcher.run(key2, reqs2)
        req2.future.result()
    finally:
        ex.shutdown()


# -- grouped dispatch parity with policies attached -------------------------


def test_grouped_tenant_parity_with_policies():
    """The round-15 tenant-labeled "1 miss + B−1 hits" pin survives an
    attached tenant table: grouped small dispatch produces the SAME
    tenant-labeled hit/miss/outcome tallies as B per-request solves,
    and no quota counter moves (the bucket runs inside its limits)."""
    bs = [RNG.standard_normal(N) for _ in range(3)]

    def tallies(grouped):
        sess = Session(tenant_policies={
            "ta": TenantPolicy(weight=2.0, max_in_flight=16)})
        sess.enable_attribution()
        h = sess.register(_small_op(50), op="lu_small", tenant="ta")
        if grouped:
            bat = Batcher(sess, max_batch=8, max_wait=3600.0)
            futs = [bat.submit(h, b) for b in bs]
            bat.flush()
            xs = [f.result() for f in futs]
        else:
            xs = [sess.solve(h, b) for b in bs]
        snap = sess.attribution.snapshot()["tenants"]["ta"]["totals"]
        assert sess.metrics.get("quota_rejections_total") == 0.0
        return ({k: v for k, v in snap.items()
                 if k in ("cache_hits", "cache_misses", "completed",
                          "solve_flops", "factor_flops")},
                [np.asarray(x) for x in xs])

    g, xs_g = tallies(True)
    p, xs_p = tallies(False)
    assert g["cache_hits"] == p["cache_hits"] == 2.0
    assert g["cache_misses"] == p["cache_misses"] == 1.0
    assert g["solve_flops"] == p["solve_flops"]
    assert g["factor_flops"] == p["factor_flops"]
    for a, b in zip(xs_g, xs_p):
        assert (a == b).all()  # grouped ≡ per-request bits


# -- migration (fleet) ------------------------------------------------------


def test_migration_byte_identity_and_follow_the_handle():
    """Fleet migration moves a resident BYTE-IDENTICALLY via the
    checkpoint-transfer path; a request queued on the source at
    migration time still resolves (zero lost futures); post-migration
    requests route to the target and pay ZERO refactors — while plain
    eviction of a sibling handle pays one."""
    import jax

    sessions = {f"p{i}": Session() for i in range(2)}
    for s in sessions.values():
        s.enable_attribution()
    fleet = Fleet(sessions, max_batch=4, max_wait=3600.0)
    mats = {f"s{i}": _small_op(60 + i) for i in range(2)}
    for name, m in sorted(mats.items()):
        fleet.register(m, op="lu_small", handle=name, member="p0")
    b = RNG.standard_normal(N)
    for name in sorted(mats):
        f = fleet.submit(name, b)
        fleet.flush()
        f.result()
    pre = jax.tree_util.tree_leaves(
        fleet.member("p0")._cache["s0"].payload)
    pre_factors = sum(fleet.member(m).metrics.get("factors_total")
                      for m in fleet.alive())
    fq = fleet.submit("s0", b)  # queued across the migration
    assert fleet.migrate("s0") == "p1"
    assert fq.done() and fq.exception() is None
    assert "s0" not in fleet.member("p0")
    assert fleet.placement_of("s0") == ["p1"]
    post = jax.tree_util.tree_leaves(
        fleet.member("p1")._cache["s0"].payload)
    assert len(pre) == len(post)
    for x, y in zip(pre, post):
        assert (np.asarray(x) == np.asarray(y)).all()
    # follow-the-handle: the next solve routes to p1, zero refactors
    f2 = fleet.submit("s0", b)
    fleet.flush()
    x2 = np.asarray(f2.result())
    m = mats["s0"]
    assert float(np.abs(m @ x2.astype(np.float64) - b).max()) \
        / (N * max(float(np.abs(x2).max()), 1.0)) < 1e-6
    assert sum(fleet.member(mm).metrics.get("factors_total")
               for mm in fleet.alive()) == pre_factors
    # the control: eviction pays a refactor on the next touch
    fleet.member("p0").evict("s1")
    f3 = fleet.submit("s1", b)
    fleet.flush()
    f3.result()
    assert sum(fleet.member(mm).metrics.get("factors_total")
               for mm in fleet.alive()) == pre_factors + 1


def test_migration_abort_leaves_source_serving():
    """A fired migration_abort kills the transfer attempt mid-flight:
    the source keeps serving untouched, the retry is counted, and two
    consecutive aborts give up WITHOUT a half-resident anywhere."""
    from slate_tpu.runtime import FaultInjector, FaultPlan, FaultSpec

    sessions = {f"p{i}": Session() for i in range(2)}
    inj = FaultInjector(FaultPlan(seed=9, specs=(
        FaultSpec("migration_abort", rate=1.0, count=2),)))
    fleet = Fleet(sessions, max_batch=4, max_wait=3600.0, faults=inj)
    m = _small_op(70)
    fleet.register(m, op="lu_small", handle="s0", member="p0")
    b = RNG.standard_normal(N)
    f = fleet.submit("s0", b)
    fleet.flush()
    f.result()
    # both attempts abort -> give up; source untouched and serving
    assert fleet.migrate("s0") is None
    assert fleet.metrics.get("fleet_migration_aborts_total") == 2.0
    assert fleet.metrics.get("fleet_migration_retries_total") == 1.0
    assert "s0" in fleet.member("p0")
    assert "s0" not in fleet.member("p1")
    f2 = fleet.submit("s0", b)
    fleet.flush()
    f2.result()
    # fault budget exhausted -> the next migration lands
    assert fleet.migrate("s0") == "p1"


def test_empty_tenant_pool_falls_back_to_global_shed():
    """Review pin: a burning tenant with NOTHING queued must not
    suppress the round-14 global overload reflex for the interval —
    when its pool is empty and the global burn is also over
    threshold, the shed falls back to the global cheapest-first
    pool."""
    from slate_tpu.obs.slo import Objective, SloTracker

    clk = _FakeClock()
    slo = SloTracker((Objective("noisy_errors", "error_rate", 0.9,
                                tenant="noisy", windows=(60.0,)),),
                     clock=clk)
    sess = Session(tenant_policies={"noisy": TenantPolicy(),
                                    "victim": TenantPolicy()})
    sess.slo = slo
    slo.metrics = sess.metrics
    hv = sess.register(_small_op(45), op="lu_small", tenant="victim")
    for _ in range(10):
        slo.record_request("lu_small", N, 0.0, ok=False,
                           tenant="noisy", t=clk.t)
    bat = Batcher(sess, max_batch=64, max_wait=3600.0,
                  shed_policy=ShedPolicy(burn_threshold=1.0,
                                         shed_fraction=0.5,
                                         min_queue_depth=1,
                                         check_interval_s=0.0))
    # ONLY victim traffic queued: the noisy tenant's pool is empty
    vf = [bat.submit(hv, RNG.standard_normal(N), tenant="victim")
          for _ in range(6)]
    shed = bat.maybe_shed(now=clk.t)
    assert shed >= 1  # the global reflex still fired
    assert sess.metrics.get("load_sheds_total") == 1.0
    assert sess.metrics.get("tenant_sheds_total") == 0.0
    bat.flush()
    for f in vf:
        if not f.done() or f.exception() is None:
            f.result()


def test_implicit_tenant_small_groups_split_with_table():
    """Review pin: with a tenant table attached, two tenants'
    same-(op, n, dtype) small operators must NOT coalesce into one
    bucket on implicit (tenant=None) submits — the aggressor's
    backlog would ride the victim's weight through the DRR scheduler.
    Without a table the round-14 coalescing keys are untouched."""
    def buckets(policies):
        sess = Session(tenant_policies=policies)
        ha = sess.register(_small_op(46), op="lu_small", tenant="a")
        hb = sess.register(_small_op(47), op="lu_small", tenant="b")
        bat = Batcher(sess, max_batch=8, max_wait=3600.0)
        futs = [bat.submit(ha, RNG.standard_normal(N)),
                bat.submit(hb, RNG.standard_normal(N))]
        popped = bat.pop_ready(force=True)
        for key, reqs in popped:
            bat.run(key, reqs)
        for f in futs:
            f.result()
        return popped

    assert len(buckets({"a": TenantPolicy(weight=4.0)})) == 2
    assert len(buckets(None)) == 1  # round-14 keys byte-identical


# -- fleet quota rollups (obs) ----------------------------------------------


def test_quota_fold_and_fleet_prom_rollups():
    """The fleet quota fold sums per-tenant resident bytes and the
    quota counters across hosts (disabled/None hosts tolerated — the
    partial-host discipline) and renders tenant-LABELED rollup rows
    into the fleet Prometheus text."""
    from slate_tpu.obs import aggregate as agg

    pay = {"enabled": True,
           "tenants": {"a": {"resident_bytes": 100, "residents": 1,
                             "max_resident_bytes": 400}},
           "counters": {"quota_rejections_total": 3.0}}
    fold = agg.merge_quota_payloads([pay, pay, None,
                                     {"enabled": False, "tenants": {}}])
    assert fold["processes"] == 2
    assert fold["tenants"]["a"]["resident_bytes"] == 200.0
    assert fold["tenants"]["a"]["max_resident_bytes"] == 800
    assert fold["counters"]["quota_rejections_total"] == 6.0
    sess = Session()
    fleet_doc = agg.aggregate_processes(
        [sess.metrics.snapshot()], quota_payloads=[pay])
    text = agg.render_fleet_prometheus(fleet_doc)
    assert ('slate_tpu_fleet_tenant_quota_resident_bytes'
            '{tenant="a"} 100') in text
    assert ('slate_tpu_fleet_tenant_quota_max_resident_bytes'
            '{tenant="a"} 400') in text
    assert "slate_tpu_fleet_quota_rejections_total 3" in text


def test_metrics_route_renders_labeled_quota_rows():
    """/metrics on a policied session carries the tenant-labeled
    quota rows (render_quota_sections through the ObsServer's quotas
    provider) — rollups only, no handle cardinality."""
    import urllib.request

    sess = Session(tenant_policies={
        "qa": TenantPolicy(max_resident_bytes=1 << 20)})
    h = sess.register(_small_op(90), op="lu_small", tenant="qa")
    sess.solve(h, RNG.standard_normal(N))
    srv = sess.serve_obs()
    try:
        body = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=10).read().decode()
    finally:
        sess.close_obs()
    assert 'slate_tpu_tenant_quota_resident_bytes{tenant="qa"}' in body
    assert ('slate_tpu_tenant_quota_max_resident_bytes{tenant="qa"} '
            '1048576') in body


# -- disabled path (round-8 discipline) -------------------------------------


def test_disabled_path_allocates_nothing():
    """``tenant_policies is None`` (every existing caller): no
    scheduler, no per-tenant state, no quota/fairness gauges, no new
    counters — the hot path's only new cost is is-None checks."""
    sess = Session()
    assert sess.tenant_policies is None
    h = sess.register(_small_op(80), op="lu_small")
    bat = Batcher(sess, max_batch=4, max_wait=3600.0)
    futs = [bat.submit(h, RNG.standard_normal(N)) for _ in range(3)]
    bat.flush()
    for f in futs:
        f.result()
    assert bat._sched is None
    assert not hasattr(bat, "_tenant_inflight")
    snap = sess.metrics.snapshot()
    assert not any(k.startswith(("tenant_quota", "fair_share"))
                   for k in snap["gauges"])
    assert not any(k.startswith(("quota_", "tenant_"))
                   for k in snap["counters"])
    assert sess.quotas_payload() == {"enabled": False, "tenants": {}}
    payload = sess.tenants_payload()
    assert payload["quotas"]["enabled"] is False
