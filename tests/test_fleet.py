"""Fleet failover coordinator (round 17, ISSUE 14 tentpole b).

Pins consistent-hash placement determinism, checkpoint-transfer
replication (bit-identical replica), and the failover ladder walked by
a declared process death: replica serves immediately with NO refactor
→ checkpoint restores warm → cold re-register pays a counted
refactor-on-miss; orphaned in-flight requests re-route (zero lost
futures); a stale replica is refreshed, never served; the round-14
shed policy admission-controls the recovery surge; the partial-host
placement fold keeps the dead member's checkpointed rows visible.

Small-op operators throughout (the global linalg/batched bucket
program cache keeps compiles shared across tests — tier-1 budget).
"""

import os

import numpy as np
import pytest

from slate_tpu.runtime import (FaultInjector, FaultPlan, FaultSpec,
                               Fleet, RequestShed, Session, ShedPolicy)


def _diag_dom(rng, n=16):
    return (rng.standard_normal((n, n)) + n * np.eye(n)).astype(
        np.float32)


def _residual(a, x, b):
    x = np.asarray(x, dtype=np.float64)
    return float(np.abs(a.astype(np.float64) @ x
                        - np.asarray(b, np.float64)).max()) \
        / (a.shape[0] * max(float(np.abs(x).max()), 1.0))


def _fleet(tmp_path=None, n_members=3, shed=None, faults=None,
           with_ckpt=True, attribution=False):
    root = None if tmp_path is None else str(tmp_path / "ckpt")
    sessions = {}
    for i in range(n_members):
        cdir = (os.path.join(root, f"p{i}")
                if (root is not None and with_ckpt) else None)
        s = Session(checkpoint_dir=cdir)
        if attribution:
            s.enable_attribution()
        if faults is not None:
            s.faults = faults
        sessions[f"p{i}"] = s
    return Fleet(sessions, max_batch=4, max_wait=3600.0,
                 checkpoint_root=root if with_ckpt else None,
                 shed_policy=shed, faults=faults)


class TestPlacement:
    def test_ring_order_deterministic_across_instances(self):
        f1 = _fleet()
        f2 = _fleet()
        for h in ("a", "b", "c", 7, 42):
            assert f1.ring_order(h) == f2.ring_order(h)
            assert sorted(f1.ring_order(h)) == ["p0", "p1", "p2"]

    def test_register_routes_and_serves(self):
        rng = np.random.default_rng(0)
        fleet = _fleet()
        mats = {}
        for i in range(4):
            m = _diag_dom(rng)
            h = fleet.register(m, op="lu_small", handle=f"q{i}")
            mats[h] = m
            assert fleet.placement_of(h) == [fleet.ring_order(h)[0]]
        futs = []
        for h in mats:
            b = rng.standard_normal(16).astype(np.float32)
            futs.append((fleet.submit(h, b), h, b))
        fleet.flush()
        for f, h, b in futs:
            assert f.exception() is None
            assert _residual(mats[h], f.result(), b) < 1e-3

    def test_handles_must_be_checkpointable(self):
        from slate_tpu.core.exceptions import SlateError
        fleet = _fleet()
        with pytest.raises(SlateError):
            fleet.register(np.eye(4, dtype=np.float32),
                           op="lu_small", handle=("tuple", "handle"))


class TestReplication:
    def test_replica_bit_identical_to_primary(self, tmp_path):
        rng = np.random.default_rng(1)
        fleet = _fleet(tmp_path)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="r0", member="p0")
        fleet.member("p0").factor(h)
        replica = fleet.replicate(h)
        assert replica in ("p1", "p2")
        assert fleet.placement_of(h) == ["p0", replica]
        # checkpoint transfer: the replica's resident factor is the
        # SAME bytes, so its solve is bit-identical to the primary's
        b = rng.standard_normal(16).astype(np.float32)
        x_primary = fleet.member("p0").solve(h, b)
        x_replica = fleet.member(replica).solve(h, b)
        assert np.asarray(x_primary).tobytes() \
            == np.asarray(x_replica).tobytes()
        # and the replica did NOT refactor to get there
        assert fleet.member(replica).metrics.get("factors_total") == 0

    def test_replicate_hot_picks_hottest(self, tmp_path):
        rng = np.random.default_rng(2)
        fleet = _fleet(tmp_path, attribution=True)
        hs = [fleet.register(_diag_dom(rng), op="lu_small",
                             handle=f"w{i}", member=f"p{i % 3}")
              for i in range(3)]
        for h in hs:
            fleet.member(fleet.placement_of(h)[0]).solve(
                h, rng.standard_normal(16).astype(np.float32))
        hot = hs[1]
        for _ in range(4):  # drive w1 hottest
            fleet.member(fleet.placement_of(hot)[0]).solve(
                hot, rng.standard_normal(16).astype(np.float32))
        made = fleet.replicate_hot(1)
        assert made == [hot]
        assert len(fleet.placement_of(hot)) == 2


class TestFailover:
    def test_replica_serves_with_no_refactor(self, tmp_path):
        rng = np.random.default_rng(3)
        fleet = _fleet(tmp_path)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f0", member="p0")
        fleet.member("p0").factor(h)
        replica = fleet.replicate(h)
        pre = fleet.member(replica).metrics.get("factors_total")
        fleet.kill("p0")
        assert fleet.metrics.get("fleet_failover_replica_served") == 1
        assert fleet.placement_of(h) == [replica]
        b = rng.standard_normal(16).astype(np.float32)
        f = fleet.submit(h, b)
        fleet.flush()
        assert _residual(m, f.result(), b) < 1e-3
        # rung 1: served from the replica's resident — zero refactors
        assert fleet.member(replica).metrics.get(
            "factors_total") == pre

    def test_checkpoint_restores_warm(self, tmp_path):
        rng = np.random.default_rng(4)
        fleet = _fleet(tmp_path)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f1", member="p0")
        fleet.member("p0").factor(h)
        fleet.checkpoint_all()
        fleet.kill("p0")
        assert fleet.metrics.get("fleet_failover_restored") == 1
        target = fleet.placement_of(h)[0]
        b = rng.standard_normal(16).astype(np.float32)
        f = fleet.submit(h, b)
        fleet.flush()
        assert _residual(m, f.result(), b) < 1e-3
        # rung 2: warm restore — the survivor never refactored
        assert fleet.member(target).metrics.get("factors_total") == 0
        assert fleet.member(target).metrics.get(
            "restored_residents_total") == 1

    def test_replica_death_is_not_a_failover(self, tmp_path):
        """Killing the member that held only a handle's REPLICA must
        not walk the ladder: the primary never stopped serving, no
        replica_served/stale accounting fires (a stale injection must
        not evict the healthy primary), just a counted
        fleet_replicas_lost durability decrement."""
        rng = np.random.default_rng(10)
        stale_inj = FaultInjector(FaultPlan(seed=3, specs=(
            FaultSpec("replica_stale", rate=1.0),)))
        fleet = _fleet(tmp_path, faults=stale_inj)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f10", member="p1")
        fleet.member("p1").factor(h)
        replica = fleet.replicate(h)
        assert replica != "p1"
        fleet.kill(replica)
        assert fleet.metrics.get("fleet_replicas_lost") == 1
        assert fleet.metrics.get("fleet_failover_handles_total") == 0
        assert fleet.metrics.get("fleet_failover_replica_served") == 0
        assert fleet.metrics.get("fleet_replica_stale_refreshes") == 0
        assert fleet.placement_of(h) == ["p1"]
        # the primary's resident survived untouched: serving continues
        # with zero additional refactors
        b = rng.standard_normal(16).astype(np.float32)
        f = fleet.submit(h, b)
        fleet.flush()
        assert _residual(m, f.result(), b) < 1e-3
        assert fleet.member("p1").metrics.get("factors_total") == 1

    def test_close_flushed_checkpoint_found_by_failover(self, tmp_path):
        """A checkpoint flushed by Session.close() (or any prior
        coordinator incarnation) — never recorded by THIS
        coordinator's checkpoint_all — is still found at the derivable
        <base>/checkpoint path and restores warm."""
        rng = np.random.default_rng(9)
        fleet = _fleet(tmp_path)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f9", member="p0")
        fleet.member("p0").factor(h)
        # the member's own orderly-shutdown flush, not checkpoint_all
        fleet.member("p0").close()
        fleet.kill("p0")
        assert fleet.metrics.get("fleet_failover_restored") == 1
        target = fleet.placement_of(h)[0]
        b = rng.standard_normal(16).astype(np.float32)
        f = fleet.submit(h, b)
        fleet.flush()
        assert _residual(m, f.result(), b) < 1e-3
        assert fleet.member(target).metrics.get("factors_total") == 0

    def test_cold_reregister_refactors_counted(self, tmp_path):
        rng = np.random.default_rng(5)
        fleet = _fleet(tmp_path, with_ckpt=False)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f2", member="p0")
        fleet.member("p0").factor(h)
        fleet.kill("p0")  # no replica, no checkpoint
        assert fleet.metrics.get("fleet_failover_cold") == 1
        target = fleet.placement_of(h)[0]
        b = rng.standard_normal(16).astype(np.float32)
        f = fleet.submit(h, b)
        fleet.flush()
        assert _residual(m, f.result(), b) < 1e-3
        # rung 3 (the floor): one counted refactor-on-miss
        assert fleet.member(target).metrics.get("factors_total") == 1

    @pytest.mark.slow
    def test_orphaned_requests_reroute_zero_lost(self, tmp_path):
        """Slow (round-18 tier-1 budget): the replicate+kill+re-route
        sequence pays several restore/refactor program touches; tier-1
        siblings — test_stale_replica_refreshed_not_served and
        test_shed_policy_protects_recovery_surge keep the kill()
        failover path pinned, and the chaos recovery drill exit-gates
        zero-lost-futures end to end in examples/run_tests.py."""
        rng = np.random.default_rng(6)
        fleet = _fleet(tmp_path)
        m = _diag_dom(rng)
        # ring placement (no member= pin): the primary is the ring's
        # first preference, so submits genuinely queue on the victim
        h = fleet.register(m, op="lu_small", handle="f3")
        primary = fleet.placement_of(h)[0]
        fleet.member(primary).factor(h)
        fleet.replicate(h)
        # queue requests on the doomed member, then crash BEFORE any
        # dispatch: the fleet futures must still resolve (re-routed)
        futs = [(fleet.submit(h, b), b) for b in
                (rng.standard_normal(16).astype(np.float32)
                 for _ in range(3))]
        fleet.kill(primary)
        fleet.flush()
        assert fleet.metrics.get("fleet_failover_requests_total") == 3
        for f, b in futs:
            assert f.done() and f.exception() is None
            assert _residual(m, f.result(), b) < 1e-3

    def test_stale_replica_refreshed_not_served(self, tmp_path):
        rng = np.random.default_rng(7)
        inj = FaultInjector(FaultPlan(seed=1, specs=(
            FaultSpec("replica_stale", rate=1.0, count=1),)))
        fleet = _fleet(tmp_path, faults=inj)
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f4", member="p0")
        fleet.member("p0").factor(h)
        replica = fleet.replicate(h)
        fleet.kill("p0")
        assert fleet.metrics.get("fleet_replica_stale_refreshes") == 1
        assert fleet.metrics.get("fleet_failover_replica_served") == 0
        # the stale resident was evicted: the next touch refactors from
        # the registered operand and the answer is correct
        b = rng.standard_normal(16).astype(np.float32)
        f = fleet.submit(h, b)
        fleet.flush()
        assert _residual(m, f.result(), b) < 1e-3
        assert fleet.member(replica).metrics.get("cache_misses") >= 1

    def test_shed_policy_protects_recovery_surge(self, tmp_path):
        rng = np.random.default_rng(8)
        fleet = _fleet(tmp_path, shed=ShedPolicy(max_queue_depth=4,
                                                 min_queue_depth=1))
        m = _diag_dom(rng)
        h = fleet.register(m, op="lu_small", handle="f5", member="p0")
        fleet.member("p0").factor(h)
        fleet.checkpoint_all()
        fleet.kill("p0")
        surge = [fleet.submit(h, rng.standard_normal(16)
                              .astype(np.float32)) for _ in range(12)]
        fleet.flush()
        rejected = [f for f in surge if f.done()
                    and isinstance(f.exception(), RequestShed)]
        served = [f for f in surge if f.done()
                  and f.exception() is None]
        # admission control turned the excess away COUNTED; nothing
        # hung — zero lost futures either way
        assert len(rejected) == 8 and len(served) == 4
        assert all(f.done() for f in surge)

    def test_partial_placement_fold_after_crash(self, tmp_path):
        rng = np.random.default_rng(9)
        fleet = _fleet(tmp_path, attribution=True)
        h = fleet.register(_diag_dom(rng), op="lu_small",
                           handle="f6", member="p0")
        fleet.member("p0").solve(
            h, rng.standard_normal(16).astype(np.float32))
        fleet.checkpoint_all()
        fleet.kill("p0")
        doc = fleet.placement()
        # the dead member's checkpoint keeps it in the fold, marked
        assert doc["partial_hosts"] == ["p0"]
        dead_rows = [r for r in doc["rows"] if r["host"] == "p0"]
        assert dead_rows and dead_rows[0]["handle"] == repr("f6")
        assert dead_rows[0]["heat"] > 0
