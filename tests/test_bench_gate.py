"""tools/bench_gate.py: BENCH-trajectory schema normalization and the
regression gate (ISSUE 5 tentpole c). Pure-host — no jax import; the
gate must stay cheap enough to run in every CI invocation.

Acceptance pins: nonzero exit on an injected regression in a fixture
trajectory, zero on the real committed history, and --check-schema
validating every committed BENCH_*.json.
"""

import importlib.util
import json
import os
import pathlib
import sys

import pytest

_REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", str(_REPO / "tools" / "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


gate_mod = _load_gate()


def _bench_artifact(value, platform="tpu", n=8192, **extra):
    return {
        "metric": f"gemm_gflops_per_chip_fp32_n{n}",
        "value": value,
        "unit": "GFLOP/s",
        "vs_baseline": round(value / 700.0, 2),
        "platform": platform,
        **extra,
    }


def _write(dirpath, name, obj):
    with open(os.path.join(dirpath, name), "w") as f:
        json.dump(obj, f)


# -- normalization ----------------------------------------------------------


def test_normalize_all_three_schemas(tmp_path):
    # rounds 1-5 harness wrapper (metrics inside "parsed", platform
    # inferred from the tail's axon warning)
    _write(tmp_path, "BENCH_r01.json", {
        "n": 1, "cmd": "python bench.py", "rc": 0,
        "tail": "Platform 'axon' is experimental\n...",
        "parsed": _bench_artifact(140000.0, platform=None)})
    rec = gate_mod.normalize(str(tmp_path / "BENCH_r01.json"))
    assert rec["kind"] == "bench" and rec["round"] == 1
    assert rec["platform"] == "tpu" and rec["n"] == 8192
    assert rec["metrics"]["value"] == 140000.0

    # bare bench.py --out artifact (round 6+)
    _write(tmp_path, "BENCH_r06.json",
           _bench_artifact(100.0, platform="cpu-fallback", n=512,
                           potrf_gflops=1.5))
    rec = gate_mod.normalize(str(tmp_path / "BENCH_r06.json"))
    assert rec["round"] == 6 and rec["platform"] == "cpu-fallback"
    assert rec["n"] == 512 and rec["metrics"]["potrf_gflops"] == 1.5

    # bench_serve artifact (nested tracked metric via dotted path);
    # must carry EVERY current section (round 14: --check-schema fails
    # stale smoke fixtures)
    serve_art = {
        "bench": "serve", "backend": "cpu", "dtype": "float32",
        "n": 192, "nb": 64, "requests": 48, "max_batch": 16,
        "serve": {"solves_per_sec": 120.0},
        "per_request": {"solves_per_sec": 9.0}, "speedup": 13.3,
        "cost_log": [], "hbm": {}, "slo": {},
        "tenants": _tenants_section(),
        "numerics": _numerics_section(),
        "quotas": _quotas_section(),
        "spectral": _spectral_section(),
        "updates": _updates_section(),
        "tuning": _tuning_section(),
        "incidents": _incidents_section(),
        "forecast": _forecast_section()}
    assert set(gate_mod.SERVE_ARTIFACT_SECTIONS) <= set(serve_art)
    _write(tmp_path, "BENCH_SERVE_smoke.json", serve_art)
    rec = gate_mod.normalize(str(tmp_path / "BENCH_SERVE_smoke.json"))
    assert rec["kind"] == "serve" and rec["platform"] == "cpu"
    assert rec["metrics"]["serve.solves_per_sec"] == 120.0
    assert rec["metrics"]["speedup"] == 13.3

    # a STALE fixture — schema grew a section it lacks — fails
    # loudly (the rounds-12/13 trip class)
    stale = {k: v for k, v in serve_art.items() if k != "slo"}
    _write(tmp_path, "BENCH_SERVE_stale.json", stale)
    with pytest.raises(gate_mod.SchemaError, match="slo"):
        gate_mod.normalize(str(tmp_path / "BENCH_SERVE_stale.json"))


def test_normalize_rejects_unknown_schema(tmp_path):
    _write(tmp_path, "BENCH_r99.json", {"something": "else"})
    with pytest.raises(gate_mod.SchemaError):
        gate_mod.normalize(str(tmp_path / "BENCH_r99.json"))
    (tmp_path / "BENCH_r98.json").write_text("{not json")
    with pytest.raises(gate_mod.SchemaError):
        gate_mod.normalize(str(tmp_path / "BENCH_r98.json"))


def test_failed_round_is_excluded_not_an_error(tmp_path):
    # round 3's rc=1 wrapper (a crashed bench run) must normalize (the
    # history stays schema-clean) but contribute no gated points
    _write(tmp_path, "BENCH_r03.json", {
        "n": 3, "cmd": "python bench.py", "rc": 1,
        "tail": "Traceback ..."})
    rec = gate_mod.normalize(str(tmp_path / "BENCH_r03.json"))
    assert rec["ok"] is False and rec["metrics"] == {}


def test_normalize_legacy_multichip_blob(tmp_path):
    # rounds 1-5 dry-run wrapper: metrics buried in the tail text —
    # residuals come out as informational series (round 11 satellite)
    _write(tmp_path, "MULTICHIP_r05.json", {
        "n_devices": 8, "rc": 0, "ok": True, "skipped": False,
        "tail": "dryrun_multichip(8): mesh 2x4, posv+hemm OK (max "
                "residual 4.77e-07), getrf OK (2.38e-07), gbsv OK "
                "(2.38e-07)\n"})
    (rec,) = gate_mod.normalize_all(str(tmp_path / "MULTICHIP_r05.json"))
    assert rec["kind"] == "multichip_dryrun" and rec["round"] == 5
    assert rec["platform"] == "cpu" and rec["n"] == 8
    assert rec["metrics"]["residual_posv_hemm"] == pytest.approx(4.77e-7)
    assert rec["metrics"]["residual_getrf"] == pytest.approx(2.38e-7)
    # a failed round (the r01 blob) normalizes with no metrics
    _write(tmp_path, "MULTICHIP_r01.json", {
        "n_devices": 8, "rc": 1, "ok": False, "skipped": False,
        "tail": "Traceback ..."})
    (rec,) = gate_mod.normalize_all(str(tmp_path / "MULTICHIP_r01.json"))
    assert rec["ok"] is False and rec["metrics"] == {}


def _numerics_section(state="healthy"):
    """A minimal round-16 serve-artifact numerics section that passes
    gate_mod._check_numerics_section."""
    return {
        "enabled": True,
        "handles": {"1": {"op": "chol", "condest": 350.0,
                          "growth": 1.4, "resid_ewma": 2.1e-7,
                          "state": state}},
        "counts": {"healthy": 1, "degraded": 0, "suspect": 0},
        "counters": {"residual_probes_total": 12.0,
                     "condest_runs_total": 1.0},
        "sample_fraction": 0.25,
        "ok": True,
    }


def _quotas_section():
    """A minimal round-18 serve-artifact quotas section that passes
    gate_mod._check_quotas_section."""
    return {
        "enabled": True,
        "policies": {"policies": {"bench-a": {"weight": 2.0}},
                     "default": None},
        "tenants": {"bench-a": {"resident_bytes": 1024,
                                "residents": 1,
                                "max_resident_bytes": None,
                                "weight": 2.0}},
        "counters": {"quota_rejections_total": 0.0},
    }


def _spectral_section():
    """A minimal round-19 serve-artifact spectral section that passes
    gate_mod._check_spectral_section."""
    return {
        "enabled": True,
        "op": "eig",
        "n": 96,
        "functions": ["solve", "psd_project", "whiten", "truncate"],
        "new_compiles_after_warmup": 0,
        "apply_dot_ops": {"solve": 2, "psd_project": 2,
                          "whiten": 2, "truncate": 2},
        "stage_programs": ["spectral.he2hb", "spectral.hb2td",
                           "spectral.unmtr"],
        "solve_rel_err": 3.1e-6,
        "ok": True,
    }


def _updates_section():
    """A minimal round-20 serve-artifact updates section that passes
    gate_mod._check_updates_section."""
    return {
        "enabled": True,
        "op": "chol",
        "n": 96,
        "nb": 32,
        "k": 2,
        "updates_applied": 2,
        "new_compiles_after_warmup": 0,
        "update_refactors": 0,
        "refactors_during_updates": 0.0,
        "update_flops": 73728.0,
        "solve_rel_err": 4.1e-9,
        "ok": True,
    }


def _tuning_section():
    """A minimal round-21 serve-artifact tuning section that passes
    gate_mod._check_tuning_section."""
    return {
        "enabled": True,
        "op": "chol",
        "n": 32,
        "resolved": "TUNING_r01.json#0[nb=32,inner_blocking=16,"
                    "lookahead=0,wide_panel=32]",
        "table": {"schema": gate_mod.TUNING_SCHEMA,
                  "file": "TUNING_r01.json", "entries": 5,
                  "platform_match": True},
        "new_compiles_after_warmup": 0,
        "solve_rel_err": 9.1e-9,
        "ok": True,
    }


def _incidents_section():
    """A minimal round-22 serve-artifact incidents section that passes
    gate_mod._check_incidents_section (the sample is held to the
    slate_tpu.incident.v1 mirror validator)."""
    return {
        "enabled": True,
        "captured": 1,
        "journal_recorded": 3,
        "journal_digest": "sha256:deadbeef",
        "parity": {"eviction": {"counter": 1.0, "journal": 1.0,
                                "ok": True}},
        "sample": {
            "schema": gate_mod.INCIDENT_SCHEMA,
            "id": "inc-0000-bench_probe", "ts": 1700000000.0,
            "host": "bench", "reason": "bench_probe", "key": "smoke",
            "context": {},
            "journal": {"events": [{"kind": "eviction",
                                    "ts": 1700000000.0, "count": 1}],
                        "counts": {"eviction": 1},
                        "outcome_counts": {}},
            "flight": {"spans": [], "samples": []},
            "metrics": {"counters": {"evictions": 1.0}, "gauges": {}},
            "numerics": None, "quotas": None, "placement": None,
            "cost_log": None, "tuning": None,
        },
        "ok": True,
    }


def _forecast_section():
    """A minimal round-23 serve-artifact forecast section that passes
    gate_mod._check_forecast_section.  The history/forecast payloads
    are built by the REAL store + forecaster — the same stdlib-only
    modules bench_gate file-loads for its validators (sys.modules
    carries them under their fixed names once gate_mod is loaded), so
    the fixture can never drift from the schema it is held to."""
    tmod = sys.modules["slate_tpu_obs_timeseries"]
    fmod = sys.modules["slate_tpu_obs_forecast"]
    t = {"now": 0.0}
    store = tmod.TimeseriesStore(clock=lambda: t["now"])
    for i in range(12):
        t["now"] = float(i)
        store.record_gauge("queue_depth", float(i % 3))
        store.record_counter("solves_total", float(i + 1))
    hist = store.payload()
    fc = fmod.Forecaster(store).payload(horizon_s=10.0)
    cons = {name: {"store": total, "counter": total, "ok": True}
            for name, total in store.counter_totals().items()}
    return {
        "enabled": True,
        "series_count": len(hist["series"]),
        "dropped_series": 0,
        "dropped_samples": 0,
        "conservation": cons,
        "history": hist,
        "forecast": fc,
        "ok": True,
    }


def _tenants_section(conservation_ok=True, rows=None):
    """A minimal round-15 serve-artifact tenants section that passes
    gate_mod._check_tenants_section."""
    if rows is None:
        rows = [{
            "host": "bench", "tenant": "bench-a", "handle": "1",
            "op": "chol", "n": 192, "dtype": "float32",
            "bytes_per_chip": 147456, "heat": 2.5,
            "last_access": 1700000000.0,
            "health": "healthy", "condest": 350.0, "growth": 1.4}]
    return {
        "enabled": True, "halflife_s": 300.0,
        "per_tenant": {"bench-a": {"solve_flops": 1.0}},
        "conservation": {"solve_flops": {
            "per_tenant_sum": 1.0, "global": 1.0,
            "ok": conservation_ok}},
        "conservation_ok": conservation_ok,
        "placement": {"schema": gate_mod.PLACEMENT_SCHEMA,
                      "host": "bench", "rows": rows},
    }


def test_serve_tenants_section_schema(tmp_path):
    """Round 15: --check-schema holds the serve artifact's tenants
    section to the placement row schema — a row missing a key, or a
    placement block with the wrong schema id, fails loudly (the
    stale-fixture class)."""
    base = {
        "bench": "serve", "backend": "cpu", "dtype": "float32",
        "n": 192, "nb": 64, "requests": 48, "max_batch": 16,
        "serve": {"solves_per_sec": 120.0},
        "per_request": {"solves_per_sec": 9.0}, "speedup": 13.3,
        "cost_log": [], "hbm": {}, "slo": {},
        "numerics": _numerics_section(),
        "quotas": _quotas_section(),
        "spectral": _spectral_section(),
        "updates": _updates_section(),
        "tuning": _tuning_section(),
        "incidents": _incidents_section(),
        "forecast": _forecast_section()}
    # a placement row lacking "heat" fails
    bad_row = _tenants_section()
    del bad_row["placement"]["rows"][0]["heat"]
    _write(tmp_path, "BENCH_SERVE_badrow.json",
           dict(base, tenants=bad_row))
    with pytest.raises(gate_mod.SchemaError, match="heat"):
        gate_mod.normalize(str(tmp_path / "BENCH_SERVE_badrow.json"))
    # a wrong placement schema id fails
    bad_schema = _tenants_section()
    bad_schema["placement"]["schema"] = "nope.v0"
    _write(tmp_path, "BENCH_SERVE_badschema.json",
           dict(base, tenants=bad_schema))
    with pytest.raises(gate_mod.SchemaError, match="placement schema"):
        gate_mod.normalize(str(tmp_path / "BENCH_SERVE_badschema.json"))
    # a tenants section without the conservation verdict fails
    no_cons = _tenants_section()
    del no_cons["conservation"]
    _write(tmp_path, "BENCH_SERVE_nocons.json",
           dict(base, tenants=no_cons))
    with pytest.raises(gate_mod.SchemaError, match="conservation"):
        gate_mod.normalize(str(tmp_path / "BENCH_SERVE_nocons.json"))
    # the well-formed section parses
    _write(tmp_path, "BENCH_SERVE_ok.json",
           dict(base, tenants=_tenants_section()))
    rec = gate_mod.normalize(str(tmp_path / "BENCH_SERVE_ok.json"))
    assert rec["kind"] == "serve"


def test_placement_row_keys_mirror_pinned():
    """The jax-free mirror discipline (bench_gate stays standalone,
    the baseline-validator precedent): bench_gate's placement row
    keys and schema id must equal the obs.attribution originals —
    the tenants-section check is only as strong as this equality.
    (The SERVE_ARTIFACT_SECTIONS twin pin lives in test_faults.py.)"""
    from slate_tpu.obs import attribution as attr_mod
    assert tuple(gate_mod.PLACEMENT_ROW_KEYS) == \
        tuple(attr_mod.PLACEMENT_ROW_KEYS)
    assert gate_mod.PLACEMENT_SCHEMA == attr_mod.PLACEMENT_SCHEMA


def _multichip_artifact(solves=300.0, speedup=0.1):
    return {
        "bench": "multichip", "platform": "cpu",
        "forced_host_devices": True, "mesh_shape": [2, 4],
        "n_devices": 8, "ok": True,
        "rows": [{
            "op": "chol", "n": 128, "nb": 32, "dtype": "float32",
            "requests": 32, "ok": True,
            "serve": {"wall_s": 0.1, "solves_per_sec": solves},
            "single_device": {"wall_s": 0.01,
                              "solves_per_sec": solves / speedup},
            "speedup": speedup,
            "sharded_resident": True,
            "solve_collective_census": {"all-gather": 10},
        }],
    }


def _serve_mixed_artifact(solves=100.0, speedup=0.2):
    return {
        "bench": "serve_mixed", "platform": "cpu",
        "dtype": "float32", "factor_dtype": "bfloat16", "ok": True,
        "rows": [{
            "op": "chol", "n": 128, "nb": 32, "requests": 32,
            "dtype": "float32", "factor_dtype": "bfloat16", "ok": True,
            "mixed": {"wall_s": 0.3, "solves_per_sec": solves,
                      "iters_mean": 3.0, "factor_bytes": 32768,
                      "residents_within_budget": 6},
            "full": {"wall_s": 0.06, "solves_per_sec": solves / speedup,
                     "factor_bytes": 65536,
                     "residents_within_budget": 3},
            "speedup": speedup, "factor_bytes_ratio": 0.5,
            "residents_ratio": 2.0, "refine_fallbacks": 0,
        }],
    }


def test_normalize_serve_mixed_rows(tmp_path):
    """Round 13: the BENCH_MIXED_r*.json mixed-serving A/B — one
    serve_mixed record per row, series keyed (op, n, dtype); the
    structural residents_ratio rides as a tracked metric beside the
    solves/sec pair."""
    _write(tmp_path, "BENCH_MIXED_r01.json", _serve_mixed_artifact())
    (rec,) = gate_mod.normalize_all(
        str(tmp_path / "BENCH_MIXED_r01.json"))
    assert rec["kind"] == "serve_mixed" and rec["round"] == 1
    assert rec["op"] == "chol" and rec["n"] == 128
    assert rec["dtype"] == "float32"
    assert rec["metrics"]["mixed.solves_per_sec"] == 100.0
    assert rec["metrics"]["full.solves_per_sec"] == 500.0
    assert rec["metrics"]["residents_ratio"] == 2.0
    # single-object normalize() redirects to normalize_all
    with pytest.raises(gate_mod.SchemaError, match="normalize_all"):
        gate_mod.normalize(str(tmp_path / "BENCH_MIXED_r01.json"))
    # a row missing the structural ratio fails schema validation
    bad = _serve_mixed_artifact()
    del bad["rows"][0]["factor_bytes_ratio"]
    _write(tmp_path, "BENCH_MIXED_r02.json", bad)
    assert gate_mod.check_schema(
        [str(tmp_path / "BENCH_MIXED_r02.json")])
    # discovery picks the family up beside the other artifacts
    assert any(p.endswith("BENCH_MIXED_r01.json")
               for p in gate_mod.discover(str(tmp_path)))


def test_normalize_structured_multichip_rows(tmp_path):
    _write(tmp_path, "MULTICHIP_r06.json", _multichip_artifact())
    (rec,) = gate_mod.normalize_all(str(tmp_path / "MULTICHIP_r06.json"))
    assert rec["kind"] == "multichip_serve" and rec["round"] == 6
    assert rec["op"] == "chol" and rec["n"] == 128
    assert rec["mesh_shape"] == [2, 4]
    assert rec["metrics"]["serve.solves_per_sec"] == 300.0
    assert rec["metrics"]["speedup"] == 0.1
    # single-object normalize() redirects to normalize_all
    with pytest.raises(gate_mod.SchemaError, match="normalize_all"):
        gate_mod.normalize(str(tmp_path / "MULTICHIP_r06.json"))
    # missing row keys are schema errors, not silent drops
    bad = _multichip_artifact()
    del bad["rows"][0]["speedup"]
    _write(tmp_path, "MULTICHIP_r07.json", bad)
    with pytest.raises(gate_mod.SchemaError, match="speedup"):
        gate_mod.normalize_all(str(tmp_path / "MULTICHIP_r07.json"))


def test_multichip_dtype_rows_are_separate_series(tmp_path):
    # one artifact carries f32 AND f64 rows per (op, n); without the
    # dtype series key the much-slower f64 point would gate against
    # the f32 best-prior and fabricate a TPU regression
    def two_dtype(path):
        art = _multichip_artifact(3000.0)
        art["platform"] = "tpu"
        slow = dict(art["rows"][0], dtype="float64",
                    serve={"wall_s": 1.0, "solves_per_sec": 300.0})
        art["rows"].append(slow)
        _write(tmp_path, path, art)
    two_dtype("MULTICHIP_r06.json")
    two_dtype("MULTICHIP_r07.json")
    recs = gate_mod.normalize_all(str(tmp_path / "MULTICHIP_r06.json"))
    assert [r["dtype"] for r in recs] == ["float32", "float64"]
    assert gate_mod.main(["--dir", str(tmp_path)]) == 0


def test_multichip_series_gate_and_informational(tmp_path, capsys):
    # CPU multichip rows never gate (informational, like every CPU
    # smoke series); a TPU-platform regression in the same schema DOES
    _write(tmp_path, "MULTICHIP_r06.json", _multichip_artifact(300.0))
    _write(tmp_path, "MULTICHIP_r07.json", _multichip_artifact(30.0))
    assert gate_mod.main(["--dir", str(tmp_path)]) == 0
    capsys.readouterr()
    tpu6 = _multichip_artifact(300.0)
    tpu7 = _multichip_artifact(30.0)
    tpu6["platform"] = tpu7["platform"] = "tpu"
    _write(tmp_path, "MULTICHIP_r06.json", tpu6)
    _write(tmp_path, "MULTICHIP_r07.json", tpu7)
    rc = gate_mod.main(["--dir", str(tmp_path)])
    summary = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rc == 1 and any(
        r["metric"] == "serve.solves_per_sec"
        for r in summary["regressions"])


# -- the gate ---------------------------------------------------------------


def test_injected_tpu_regression_fails_gate(tmp_path, capsys):
    _write(tmp_path, "BENCH_r01.json",
           _bench_artifact(15000.0, potrf_gflops=5000.0))
    _write(tmp_path, "BENCH_r02.json",
           _bench_artifact(15100.0, potrf_gflops=3000.0))  # -40% potrf
    rc = gate_mod.main(["--dir", str(tmp_path)])
    assert rc == 1
    summary = json.loads(capsys.readouterr().out.splitlines()[0])
    assert summary["ok"] is False
    (reg,) = summary["regressions"]
    assert reg["metric"] == "potrf_gflops" and reg["platform"] == "tpu"
    assert reg["best_prior"] == 5000.0 and reg["last"] == 3000.0


def test_within_tolerance_passes(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _bench_artifact(15000.0))
    _write(tmp_path, "BENCH_r02.json", _bench_artifact(14000.0))  # -6.7%
    assert gate_mod.main(["--dir", str(tmp_path)]) == 0
    # ...and the same drop fails under a tighter tolerance
    assert gate_mod.main(["--dir", str(tmp_path),
                          "--tolerance", "0.05"]) == 1


def test_cpu_drop_is_informational_only(tmp_path, capsys):
    # the documented policy: CPU smoke numbers are dispatch-noise-
    # dominated (PERF.md rounds 6-7) — reported, never gated
    _write(tmp_path, "BENCH_r01.json",
           _bench_artifact(100.0, platform="cpu-fallback", n=512))
    _write(tmp_path, "BENCH_r02.json",
           _bench_artifact(10.0, platform="cpu-fallback", n=512))
    rc = gate_mod.main(["--dir", str(tmp_path)])
    summary = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rc == 0 and summary["ok"] is True
    assert summary["informational_drops"]


def test_series_keyed_by_platform_and_n(tmp_path):
    # a TPU round at n=16384 must NOT gate against an n=8192 round,
    # nor against a CPU round at any size
    _write(tmp_path, "BENCH_r01.json", _bench_artifact(15000.0, n=8192))
    _write(tmp_path, "BENCH_r02.json",
           _bench_artifact(100.0, platform="cpu-fallback", n=8192))
    _write(tmp_path, "BENCH_r03.json", _bench_artifact(900.0, n=16384))
    assert gate_mod.main(["--dir", str(tmp_path)]) == 0


# -- --check-schema ---------------------------------------------------------


def test_check_schema_flags_corrupt_artifact(tmp_path, capsys):
    _write(tmp_path, "BENCH_r01.json", _bench_artifact(1.0))
    assert gate_mod.main(["--dir", str(tmp_path), "--check-schema"]) == 0
    capsys.readouterr()
    (tmp_path / "BENCH_r02.json").write_text('{"bogus": true}')
    assert gate_mod.main(["--dir", str(tmp_path), "--check-schema"]) == 1
    summary = json.loads(capsys.readouterr().out.splitlines()[0])
    assert summary["schema_errors"]


# -- the real committed history (the acceptance pins) -----------------------


def test_real_history_schema_clean():
    paths = gate_mod.discover(str(_REPO))
    assert len(paths) >= 8  # seven BENCH rounds + the serve smoke
    # round 11: the MULTICHIP family is part of the checked trajectory
    assert any("MULTICHIP_r06" in p for p in paths)
    assert sum("MULTICHIP" in p for p in paths) >= 6
    assert gate_mod.check_schema(paths) == []


def test_real_history_passes_gate(capsys):
    rc = gate_mod.main(["--dir", str(_REPO)])
    summary = json.loads(capsys.readouterr().out.splitlines()[0])
    assert rc == 0 and summary["ok"] is True
    assert summary["rounds"], "trajectory read as empty"
    # the known CPU-smoke noise shows up as informational, proving the
    # platform split actually separated the series
    assert all(r["platform"] not in gate_mod.GATED_PLATFORMS
               for r in summary["informational_drops"])


# -- serve_failover artifact (round 17) -------------------------------------


def _failover_arm(recovery=0.01, refactors=0.0):
    return {"affected_handles": 2, "failover_s": 0.002,
            "recovery_s_max": recovery, "recovery_s_mean": recovery,
            "refactors_after_crash": refactors, "replica_served": 1.0,
            "restored": 1.0, "cold_registered": 0.0,
            "availability": 1.0, "completed": 16,
            "wrong_answers": 0}


def test_normalize_serve_failover_arms(tmp_path):
    art = {"bench": "serve_failover", "platform": "cpu", "n": 32,
           "nb": 16, "handles": 4, "members": 3,
           "arms": {"protected": _failover_arm(),
                    "cold": _failover_arm(0.05, 2.0)},
           "ok": True}
    _write(tmp_path, "BENCH_FAILOVER_r01.json", art)
    recs = gate_mod.normalize_all(
        str(tmp_path / "BENCH_FAILOVER_r01.json"))
    assert [r["op"] for r in recs] == ["cold", "protected"]
    assert all(r["kind"] == "serve_failover" for r in recs)
    cold = next(r for r in recs if r["op"] == "cold")
    assert cold["metrics"]["refactors_after_crash"] == 2.0
    assert cold["metrics"]["recovery_s_max"] == 0.05
    # single-object normalize refuses the multi-row artifact
    with pytest.raises(gate_mod.SchemaError):
        gate_mod.normalize(str(tmp_path / "BENCH_FAILOVER_r01.json"))


def test_serve_failover_missing_arm_rejected(tmp_path):
    art = {"bench": "serve_failover", "platform": "cpu", "n": 32,
           "arms": {"protected": _failover_arm()}, "ok": True}
    _write(tmp_path, "BENCH_FAILOVER_r02.json", art)
    with pytest.raises(gate_mod.SchemaError):
        gate_mod.normalize_all(
            str(tmp_path / "BENCH_FAILOVER_r02.json"))


def _fair_tenant_row(p99=0.02, rejected=10):
    return {"submitted": 40, "completed": 30,
            "quota_rejected": rejected, "reqs_per_sec": 25.0,
            "p50_latency_s": p99 / 2, "p99_latency_s": p99}


def test_normalize_serve_fair_arm_tenant_records(tmp_path):
    """Round 18: the tenant-isolation A/B artifact normalizes to one
    record per (arm, tenant) — arm.tenant in the op series slot, so a
    fair-arm victim series never gates against the fifo-arm one."""
    art = {"bench": "serve_fair", "platform": "cpu", "n": 32,
           "nb": 16, "service_ms": 10.0,
           "arms": {
               "fair": {"tenants": {
                   "victim": _fair_tenant_row(0.02, 0),
                   "aggressor": _fair_tenant_row(0.1, 80)}},
               "fifo": {"tenants": {
                   "victim": _fair_tenant_row(0.3, 0),
                   "aggressor": _fair_tenant_row(0.1, 0)}}},
           "ok": True}
    _write(tmp_path, "BENCH_FAIR_r01.json", art)
    recs = gate_mod.normalize_all(str(tmp_path / "BENCH_FAIR_r01.json"))
    assert sorted(r["op"] for r in recs) == [
        "fair.aggressor", "fair.victim", "fifo.aggressor",
        "fifo.victim"]
    assert all(r["kind"] == "serve_fair" for r in recs)
    fv = next(r for r in recs if r["op"] == "fair.victim")
    assert fv["metrics"]["p99_latency_s"] == 0.02
    assert fv["metrics"]["reqs_per_sec"] == 25.0
    # single-object normalize refuses the multi-row artifact
    with pytest.raises(gate_mod.SchemaError):
        gate_mod.normalize(str(tmp_path / "BENCH_FAIR_r01.json"))
    # missing arm / missing tenant column are rejected
    _write(tmp_path, "BENCH_FAIR_r02.json",
           dict(art, arms={"fair": art["arms"]["fair"]}))
    with pytest.raises(gate_mod.SchemaError):
        gate_mod.normalize_all(str(tmp_path / "BENCH_FAIR_r02.json"))
    bad = {"fair": {"tenants": {"victim": {"submitted": 1}}},
           "fifo": art["arms"]["fifo"]}
    _write(tmp_path, "BENCH_FAIR_r03.json", dict(art, arms=bad))
    with pytest.raises(gate_mod.SchemaError, match="p99|completed"):
        gate_mod.normalize_all(str(tmp_path / "BENCH_FAIR_r03.json"))


def test_fair_metrics_classify_lower_is_better():
    """The per-tenant latency series must enter the baseline
    lower-is-better (a starved victim read as an improvement would
    blind the watchdog); throughput stays higher-is-better."""
    assert gate_mod._direction("p99_latency_s") == "lower"
    assert gate_mod._direction("p50_latency_s") == "lower"
    assert gate_mod._direction("quota_rejected") == "lower"
    assert gate_mod._direction("reqs_per_sec") == "higher"
    assert gate_mod._direction("completed") == "higher"


def test_failover_metrics_classify_lower_is_better():
    """The recovery/failover/refactor columns must enter the baseline
    lower-is-better (a 10x recovery-time rise read as an improvement
    would blind the watchdog — the round-12 _direction discipline)."""
    for m in ("recovery_s_max", "failover_s", "refactors_after_crash"):
        assert gate_mod._direction(m) == "lower"
    assert gate_mod._direction("availability") == "higher"


def test_checkpoint_manifest_validator_paths(tmp_path):
    """The jax-free validator accepts a dict, a manifest path, or a
    checkpoint directory — and flags unreadable/invalid ones."""
    good = {"schema": gate_mod.CHECKPOINT_SCHEMA, "host": "x",
            "generated_at": 1.0, "records": []}
    assert gate_mod.validate_checkpoint_manifest(good) == []
    d = tmp_path / "ck"
    d.mkdir()
    with open(d / "manifest.json", "w") as f:
        json.dump(good, f)
    assert gate_mod.validate_checkpoint_manifest(str(d)) == []
    assert gate_mod.validate_checkpoint_manifest(
        str(d / "manifest.json")) == []
    assert gate_mod.validate_checkpoint_manifest(
        str(tmp_path / "missing")) != []
    assert gate_mod.validate_checkpoint_manifest(
        dict(good, schema="nope")) != []
